#!/usr/bin/env python
"""Training entry point: ``python multi_gpu_trainer.py <ExpName>``.

Preserves the reference launcher's observable behavior (multi_gpu_trainer.py
:167-219): reads ``<ExpName>.yaml`` (script dir, then cwd), creates
``Saved_Models/<ExpName><framework>/``, copies the yaml in, derives
batch (AMP×2) and lr (·batch·devices/512), then trains. The per-GPU
``mp.Process`` spawn is gone — one process drives the whole mesh (SPMD); on
multi-host TPU, launch this same script once per host.
"""

import os
import shutil
import sys


def main(argv, base_dir=None):
    """``base_dir`` overrides where ``Saved_Models/`` is rooted (default: the
    script dir, matching the reference's ``SavedDir``); used by tests."""
    if len(argv) < 2:
        print("usage: python multi_gpu_trainer.py <ExpName>")
        return 2
    exp_name = argv[1]
    here = os.path.dirname(os.path.abspath(__file__))
    sys.path.insert(0, here)
    base = base_dir or here

    from ddim_cold_tpu.config import load_config

    yaml_path = os.path.join(here, exp_name + ".yaml")
    if not os.path.isfile(yaml_path) or base_dir is not None:
        cand = os.path.abspath(exp_name + ".yaml")
        if os.path.isfile(cand):
            yaml_path = cand
    config = load_config(yaml_path, exp_name)

    from ddim_cold_tpu.train.trainer import run
    from ddim_cold_tpu.utils.platform import (
        honor_env_platform, require_accelerator_or_exit,
    )

    honor_env_platform()  # JAX_PLATFORMS env must beat any site-config pin
    # an accelerator-configured production run must fail fast on a wedged
    # tunnel (exit 3 re-arms recovery chains) — never hang in jax.devices()
    # and never silently train the config on one CPU core. BEFORE any
    # filesystem side effect: an exit-3 must not leave a yaml-only stub
    # run dir behind to fool evidence checks.
    require_accelerator_or_exit()

    saved_dir = os.path.join(base, "Saved_Models")
    run_dir = os.path.join(saved_dir, config.run_name)
    if os.path.isdir(run_dir):
        print("Warning!Current folder already exist!")
    os.makedirs(run_dir, exist_ok=True)
    shutil.copy(yaml_path, run_dir)

    result = run(config, base)
    print(f"\nbest val loss {result.best_loss:.5f} after {result.steps} steps "
          f"→ {result.run_dir}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main(sys.argv))
