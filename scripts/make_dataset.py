#!/usr/bin/env python
"""Deterministic surrogate-flowers dataset generator (the committed recipe).

The reference trained on 64px Oxford Flowers — 512 train / 85 val batches at
effective batch 32 (`/root/reference/Saved_Models/20220822vit_tiny_diffusion/
train.log:2-3`) — but the bench host has no network access, so the real
dataset cannot be fetched. This script is the committed RECIPE for a
procedural surrogate of the same scale and spirit: radially-symmetric
"flowers" (petal lobes with veins and a speckled center disc) over smooth
gradient backgrounds. The images carry genuine coarse→fine structure —
petal geometry and colors are recoverable from a downsampled view, while
veins/speckle/jpeg grain are not — which is exactly the signal the cold
downsample-restoration task (SURVEY.md C14) needs to have a learnable,
non-trivial optimum.

Every pixel is a pure function of (seed, split, index), so a regenerated
dataset is bit-identical and the training curve it produces is reproducible
from this file alone; nothing but this recipe needs committing.

Usage:
    python scripts/make_dataset.py --out OxfordFlowers          # full scale
    python scripts/make_dataset.py --out /tmp/d --train 64 --val 32  # smoke
"""

from __future__ import annotations

import argparse
import os
import zlib
from concurrent.futures import ThreadPoolExecutor

import numpy as np
from PIL import Image

#: reference dataset scale: 512 train / 85 val batches @ effective batch 32
TRAIN_N = 512 * 32
VAL_N = 85 * 32


def _unit_grid(size: int):
    ax = (np.arange(size) + 0.5) / size
    return np.meshgrid(ax, ax, indexing="xy")  # x (cols), y (rows) in [0,1]


def generate_image(rng: np.random.Generator, size: int = 64) -> np.ndarray:
    """One surrogate flower, uint8 (size, size, 3)."""
    x, y = _unit_grid(size)

    # background: diagonal blend of two muted colors + low-frequency waves
    c0 = rng.uniform(0.15, 0.75, 3)
    c1 = rng.uniform(0.15, 0.75, 3)
    ang = rng.uniform(0, 2 * np.pi)
    ramp = (np.cos(ang) * x + np.sin(ang) * y + 1.0) / 2.0
    img = ramp[..., None] * c0 + (1.0 - ramp[..., None]) * c1
    for _ in range(2):
        fx, fy = rng.uniform(1.5, 4.0, 2)
        ph = rng.uniform(0, 2 * np.pi, 2)
        wave = 0.5 + 0.5 * np.sin(2 * np.pi * fx * x + ph[0]) * np.sin(
            2 * np.pi * fy * y + ph[1])
        img += 0.08 * wave[..., None] * (rng.uniform(-1, 1, 3))

    # one or two green-ish leaf blobs behind the flower
    for _ in range(rng.integers(1, 3)):
        lx, ly = rng.uniform(0.15, 0.85, 2)
        lr = rng.uniform(0.12, 0.22)
        d2 = ((x - lx) ** 2 + (y - ly) ** 2) / lr**2
        mask = np.exp(-d2 * 1.8)
        leaf = np.array([rng.uniform(0.05, 0.2), rng.uniform(0.35, 0.6),
                         rng.uniform(0.08, 0.25)])
        img = img * (1 - mask[..., None]) + leaf * mask[..., None]

    # flower geometry: petal lobes r(θ) with a sharpness exponent
    cx, cy = rng.uniform(0.35, 0.65, 2)
    n_pet = int(rng.integers(5, 13))
    base_r = rng.uniform(0.22, 0.34)
    sharp = rng.uniform(0.8, 2.5)
    phase = rng.uniform(0, 2 * np.pi)
    dx, dy = x - cx, y - cy
    r = np.sqrt(dx * dx + dy * dy)
    th = np.arctan2(dy, dx)
    lobes = np.abs(np.cos(n_pet / 2.0 * th + phase)) ** sharp
    petal_r = base_r * (0.45 + 0.55 * lobes)
    petal = np.clip((petal_r - r) / (0.035 * base_r / 0.28), 0.0, 1.0)  # soft edge

    pc_in = rng.uniform(0.45, 1.0, 3)   # color near the center
    pc_out = rng.uniform(0.25, 1.0, 3)  # color at the petal tips
    radial = np.clip(r / np.maximum(petal_r, 1e-6), 0, 1)
    pc = pc_in + (pc_out - pc_in) * radial[..., None]
    # veins: fine angular stripes that fade toward the rim (high-freq detail
    # destroyed by downsampling — the restoration target)
    veins = 0.5 + 0.5 * np.sin((3 * n_pet) * th + 2 * phase)
    pc = pc * (1.0 - 0.18 * (veins * (1 - radial))[..., None])
    img = img * (1 - petal[..., None]) + pc * petal[..., None]

    # center disc with speckle
    disc_r = base_r * rng.uniform(0.22, 0.38)
    disc = np.clip((disc_r - r) / (0.3 * disc_r), 0, 1)
    dc = rng.uniform(0.0, 1.0) * np.array([1.0, 0.85, 0.2]) + rng.uniform(0, 0.15, 3)
    speck = rng.random((size, size))
    dc_px = dc[None, None, :] * (0.75 + 0.25 * speck[..., None])
    img = img * (1 - disc[..., None]) + dc_px * disc[..., None]

    # mild sensor-ish noise so val/train aren't noiseless manifolds
    img += rng.normal(0.0, 0.01, img.shape)
    return (np.clip(img, 0, 1) * 255).astype(np.uint8)


def write_split(out_dir: str, split: str, n: int, size: int, seed: int,
                quality: int = 92, threads: int = 16) -> None:
    d = os.path.join(out_dir, split)
    os.makedirs(d, exist_ok=True)

    def one(i: int):
        # seed sequence keyed by (seed, split, i): order/parallelism-invariant
        # (crc32, not hash() — str hashing is salted per process)
        rng = np.random.default_rng(
            np.random.SeedSequence([seed, zlib.crc32(split.encode()), i]))
        img = generate_image(rng, size)
        Image.fromarray(img).save(os.path.join(d, f"{split}_{i:06d}.jpg"),
                                  quality=quality)

    with ThreadPoolExecutor(threads) as pool:
        list(pool.map(one, range(n)))


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default="OxfordFlowers")
    ap.add_argument("--train", type=int, default=TRAIN_N)
    ap.add_argument("--val", type=int, default=VAL_N)
    ap.add_argument("--size", type=int, default=64)
    ap.add_argument("--seed", type=int, default=20220822)
    args = ap.parse_args(argv)
    write_split(args.out, "train", args.train, args.size, args.seed)
    write_split(args.out, "val", args.val, args.size, args.seed)
    print(f"wrote {args.train} train + {args.val} val {args.size}px jpgs to {args.out}/")


if __name__ == "__main__":
    main()
