#!/usr/bin/env python
"""Wedged-tunnel recovery watcher — the standing half of the failure-
detection story (SURVEY.md §5: failure detect/recovery).

``ensure_live_backend`` bounds a single CLI start against a wedged remote-TPU
tunnel; this watcher covers the other direction — a host whose tunnel is
*currently* wedged and which should resume hardware work the moment the
remote session lock clears. It probes backend liveness in bounded
SUBPROCESSES (never initializing a backend in-process, so the watcher itself
can never hang), refreshes the probe-success marker shared with
``ensure_live_backend`` (so every CLI starts instantly once the tunnel is
back), and optionally runs a one-shot recovery hook — e.g. a script that
gracefully stops a CPU-fallback trainer and relaunches the evidence chain on
the chip.

    python scripts/watch_tpu.py --interval 480 \
        --once-exec 'bash /tmp/recover_chain.sh'

Exits after the hook fires (or never, with no hook). A probe that times out
is killed safely: it was blocked *waiting* for the claim and never held the
grant (the wedge this guards against comes from killing a client that HELD
it).
"""

from __future__ import annotations

import argparse
import os
import subprocess
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from ddim_cold_tpu.utils.platform import _PROBE_CODE, probe_marker_path  # noqa: E402


def probe_once(platforms: str | None, timeout_s: float) -> tuple[bool, str]:
    """One bounded liveness probe in a subprocess. → (alive, detail)."""
    env = dict(os.environ)
    if platforms:
        env["DDIM_COLD_PROBE_PLATFORMS"] = platforms
    try:
        subprocess.run([sys.executable, "-c", _PROBE_CODE], check=True,
                       stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
                       timeout=timeout_s, env=env)
        return True, "probe ok"
    except subprocess.TimeoutExpired:
        return False, f"hung >{timeout_s:.0f}s"
    except subprocess.CalledProcessError as e:
        return False, f"rc={e.returncode}"


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--interval", type=float, default=480.0,
                    help="seconds between probes")
    ap.add_argument("--timeout", type=float, default=120.0,
                    help="per-probe bound")
    ap.add_argument("--platforms", default=None,
                    help="platform list for the probe (default: the site's "
                         "own pin, i.e. probe whatever a plain CLI would "
                         "use). Also keys the success marker — it must name "
                         "the CLIs' effective FIRST platform for them to "
                         "skip their own probes on recovery")
    ap.add_argument("--once-exec", default=None,
                    help="shell command run ONCE on the first success; the "
                         "watcher exits after it returns")
    ap.add_argument("--log", default=None, help="append probe results here")
    args = ap.parse_args(argv)

    def note(msg):
        line = f"{time.strftime('%F %T')} [watch-tpu] {msg}"
        print(line, flush=True)
        if args.log:
            with open(args.log, "a") as f:
                f.write(line + "\n")

    note(f"watching (interval={args.interval:.0f}s, timeout={args.timeout:.0f}s)")
    while True:
        alive, detail = probe_once(args.platforms, args.timeout)
        note(f"{'ALIVE' if alive else 'down'} ({detail})")
        if alive:
            # marker key must match what ensure_live_backend computes in the
            # CLIs: their effective first platform. Without --platforms the
            # best jax-free approximation is the env pin (the same value site
            # hooks apply); ensure_live_backend's own probe stays the
            # fallback when the two disagree.
            first = (args.platforms or os.environ.get("JAX_PLATFORMS", "")
                     or "axon").split(",")[0].strip()
            marker = probe_marker_path(first)
            try:
                with open(marker, "w"):
                    pass
            except OSError:
                pass
            if args.once_exec:
                note(f"recovery hook: {args.once_exec}")
                rc = subprocess.call(args.once_exec, shell=True)
                note(f"recovery hook exited rc={rc}")
                return rc
            # no hook: keep refreshing the marker so CLIs skip their probes
        time.sleep(args.interval)


if __name__ == "__main__":
    sys.exit(main())
