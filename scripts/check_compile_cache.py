#!/usr/bin/env python
"""Assert JAX's persistent compilation cache actually persists compiles.

The serving warmup (ddim_cold_tpu/serve/warmup.py) leans on the cache to
make a process restart compile-free — this check proves the wiring on the
running JAX, end to end:

1. ``enable_compile_cache`` points the cache at a temp (or given) directory;
2. a jitted function compiles → the directory must gain an entry;
3. the in-memory jit cache is cleared and the SAME function recompiles →
   the directory must NOT gain another entry (the disk hit served it).

Exit codes: 0 = verified (or SKIP where this JAX lacks the cache config —
capability-gated like parallel/_compat.py, never a false failure on old
versions), 1 = the cache directory was not created or not used.

Usage: ``python scripts/check_compile_cache.py [cache_dir]``
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _entries(path):
    names = []
    for root, _, files in os.walk(path):
        names += [os.path.join(root, f) for f in files]
    return sorted(names)


def main(argv=None):
    argv = sys.argv[1:] if argv is None else argv
    import tempfile

    import jax

    from ddim_cold_tpu.utils.platform import enable_compile_cache, honor_env_platform

    honor_env_platform()

    # capability gate: the persistent cache shipped gradually (the config
    # names below). A JAX without them can't run this check — skip cleanly,
    # matching the parallel/_compat.py stance on version spread.
    for opt in ("jax_compilation_cache_dir",
                "jax_persistent_cache_min_compile_time_secs"):
        if not hasattr(jax.config, opt):
            print(f"SKIP: this jax ({jax.__version__}) lacks {opt}; "
                  "persistent compilation cache unavailable")
            return 0

    tmp = None
    if argv:
        cache_dir = os.path.abspath(argv[0])
    else:
        tmp = tempfile.TemporaryDirectory(prefix="ddim_cold_cache_check_")
        cache_dir = tmp.name
    try:
        active = enable_compile_cache(cache_dir)
        if active is None:
            print("SKIP: enable_compile_cache declined (disabled via "
                  "DDIM_COLD_COMPILE_CACHE, or cache config rejected)")
            return 0
        # production keeps a 0.5 s floor so trivial compiles don't churn the
        # disk; the check's probe compile IS trivial, so the floor must drop
        # or the assertion below would test the floor, not the cache
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)

        import jax.numpy as jnp

        @jax.jit
        def probe(x):
            return jnp.sin(x) * jnp.arange(x.shape[0], dtype=x.dtype) + 3.0

        probe(jnp.ones((16,))).block_until_ready()
        after_first = _entries(active)
        if not after_first:
            print(f"FAIL: compile wrote no entry under {active} — the "
                  "persistent cache is configured but unused")
            return 1
        print(f"ok: first compile wrote {len(after_first)} cache "
              f"entr{'y' if len(after_first) == 1 else 'ies'} under {active}")

        probe.clear_cache()  # drop the in-memory executable, keep the disk
        probe(jnp.ones((16,))).block_until_ready()
        after_second = _entries(active)
        if after_second != after_first:
            print("FAIL: recompile after clear_cache changed the cache dir "
                  f"({len(after_first)} → {len(after_second)} entries) — "
                  "the disk entry was not reused")
            return 1
        print("ok: recompile after clear_cache reused the disk entry "
              "(no new files)")
        print(f"PASS: persistent compilation cache verified at {active}")
        return 0
    finally:
        if tmp is not None:
            tmp.cleanup()


if __name__ == "__main__":
    sys.exit(main())
