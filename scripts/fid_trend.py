#!/usr/bin/env python
"""Per-checkpoint FID trend under one fixed seeded extractor.

Without canonical InceptionV3 weights (this host is zero-egress; converter
torch-parity-tested in tests/test_inception_parity.py, so dropping in the
canonical ``.pth`` later is pure data movement), a single random-feature FID
at small n is high-variance and orders nothing. This script makes the metric
mean something the only way available offline: compute FID for SEVERAL
checkpoints of the same run — plus a random-init anchor — under ONE fixed
extractor (same seed, same n), so the number demonstrably orders models
(random ≫ early ≫ late). Real-set statistics are computed once and shared by
every point.

Checkpoint sources, newest schema first:
* ``<run>/snapshots/epoch_N/`` — periodic copies of ``lastepoch.ckpt``
  collected while the trainer runs;
* ``<run>/bestloss.ckpt`` — the run's best-val params (labelled "best").

Writes ``results/<run>/fid_trend.json`` and prints one JSON line.
"""

from __future__ import annotations

import argparse
import json
import math
import os
import re
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def collect_points(run_dir: str, max_points: int):
    """→ ordered [(label, epoch|None, ckpt_path|None)] trend points: the
    random-init anchor, evenly-thinned snapshot epochs (first and last always
    kept — obs.trend.thin, the one thinning rule for trend series), then the
    run's best checkpoint."""
    from ddim_cold_tpu.obs import trend

    points = [("random", -1, None)]  # anchor: params as-initialized
    snap_dir = os.path.join(run_dir, "snapshots")
    if os.path.isdir(snap_dir):
        snaps = []
        for name in os.listdir(snap_dir):
            m = re.fullmatch(r"epoch_(\d+)", name)
            if m:
                snaps.append((int(m.group(1)), os.path.join(snap_dir, name)))
        snaps.sort()
        snaps = trend.thin(snaps, max_points)
        points += [(f"epoch_{ep}", ep, path) for ep, path in snaps]
    best = os.path.join(run_dir, "bestloss.ckpt")
    if os.path.isdir(best):
        points.append(("best", None, best))
    return points


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("run_dir", nargs="?", default=os.path.join(
        REPO, "Saved_Models", "20220822vit_tiny_diffusion"))
    ap.add_argument("--val-dir", default=None,
                    help="real-image folder for the FID reference stream [default: the run config's own val dataStorage]")
    ap.add_argument("--n-samples", type=int, default=256,
                    help="samples per trend point (the headline fid.json uses "
                         "compute_fid.py's n=1024; trend points trade n for "
                         "breadth under the SAME extractor)")
    ap.add_argument("--n-real", type=int, default=2048)
    ap.add_argument("--batch", type=int, default=64)
    ap.add_argument("--inception-seed", type=int, default=0)
    ap.add_argument("--max-points", type=int, default=10,
                    help="evenly thin snapshot points beyond this count")
    ap.add_argument("--cpu", action="store_true")
    args = ap.parse_args(argv)

    from ddim_cold_tpu.utils.platform import (
        honor_env_platform, require_accelerator_or_exit,
    )
    from ddim_cold_tpu.utils.watchdog import StallWatchdog

    honor_env_platform()
    import jax

    if args.cpu:
        jax.config.update("jax_platforms", "cpu")
    else:
        # exit 3 on a wedged tunnel: a silent CPU fallback at 200px would
        # look exactly like the hang it was meant to avoid
        require_accelerator_or_exit()
    import numpy as np

    from ddim_cold_tpu.data import ColdDownSampleDataset, ShardedLoader
    from ddim_cold_tpu.eval import fid, inception
    from ddim_cold_tpu.ops import sampling
    from ddim_cold_tpu.utils import checkpoint as ckpt
    from ddim_cold_tpu.utils.run_io import load_run_template

    run_dir = args.run_dir
    config, model, template = load_run_template(run_dir)
    if args.val_dir is None:
        from ddim_cold_tpu.utils.run_io import default_val_dir

        args.val_dir = default_val_dir(config, REPO)

    points = collect_points(run_dir, args.max_points)

    # -- wedged-tunnel guard (r05: this script hung 45 min on its first
    # device interaction with nothing bounding it; tunnel_diag_r05.txt).
    # Partial trend points are still an artifact — they order checkpoints.
    run = os.path.basename(os.path.normpath(run_dir))
    results = []

    def _write_partial(label, silent_s):
        # a DISTINCT filename: a stall must never clobber a previously
        # complete fid_trend.json (same temp-then-promote discipline as the
        # chain's bench_v2 stage)
        out_dir = os.path.join(REPO, "results", run)
        os.makedirs(out_dir, exist_ok=True)
        with open(os.path.join(out_dir, "fid_trend.partial.json"), "w") as f:
            json.dump({"metric": "fid_trend_cold", "points": results,
                       "aborted": f"stalled {silent_s:.0f}s after {label!r} "
                                  "(wedged-tunnel watchdog)"}, f, indent=1)

    # shared arm-condition (utils/platform.watchdog_stall_s): env override,
    # else disarmed on an effective-cpu platform (comma-list aware), else 600s
    from ddim_cold_tpu.utils.platform import watchdog_stall_s

    stall_s = watchdog_stall_s("DDIM_COLD_FID_STALL_S", 600.0)
    wd = StallWatchdog(stall_s, on_abort=_write_partial,
                       name="fid-trend").start()

    # -- fixed extractor + shared real statistics ---------------------------
    wd.mark("inception init (first device compile)", budget_s=1800)
    inc_model, inc_vars = inception.init_variables(
        jax.random.PRNGKey(args.inception_seed))
    feature_fn, dim = fid.make_feature_fn(inc_model, inc_vars)
    ds = ColdDownSampleDataset(args.val_dir, imgSize=tuple(config.image_size),
                               target_mode="direct")
    n_real_seen = 0

    def real_batches():
        nonlocal n_real_seen
        loader = ShardedLoader(ds, args.batch, shuffle=False, drop_last=True)
        for _, clean, _ in loader:
            if n_real_seen >= args.n_real:
                break
            # the first yielded batch triggers the jitted Inception forward
            # compile (heavier than init_variables' compile) — it gets the
            # long-compile budget, not the default window
            wd.mark(f"real-batch {n_real_seen}/{args.n_real}",
                    budget_s=1800 if n_real_seen == 0 else None)
            yield (clean + 1.0) / 2.0
            n_real_seen += clean.shape[0]

    real = fid.stats_for_batches(real_batches(), feature_fn, dim)
    print(f"[fid-trend] real stats over {real.count} images", file=sys.stderr)

    levels = int(math.log2(config.image_size[0]))

    def load_point(path):
        if path is None:
            return template
        if os.path.basename(path).startswith("epoch_"):
            # two snapshot layouts exist: the trainer's snapshot_epochs option
            # writes bare params; out-of-band collectors copy lastepoch.ckpt,
            # which holds the full resume state with a "params" entry. Raw-
            # restore, unwrap if needed, cast onto the template's dtypes.
            raw = ckpt.restore_checkpoint(path)
            if isinstance(raw, dict) and "params" in raw and "opt_state" in raw:
                raw = raw["params"]
            return jax.tree.map(
                lambda t, v: np.asarray(v, np.asarray(t).dtype), template, raw)
        return ckpt.restore_checkpoint(path, template)  # bestloss: bare params

    first_sample = True
    for label, epoch, path in points:
        params = load_point(path)
        fake = fid.ActivationStats(dim)
        rng, remaining = jax.random.PRNGKey(1), args.n_samples  # same stream
        while remaining > 0:  # full batches: one sampler compile (static shape)
            keep = min(args.batch, remaining)
            rng, sub = jax.random.split(rng)
            wd.mark(f"sample-batch {label} {args.n_samples - remaining}"
                    f"/{args.n_samples}",
                    budget_s=1800 if first_sample else None)
            first_sample = False
            imgs = sampling.cold_sample(model, params, sub, n=args.batch,
                                        levels=levels)
            fake.update(np.asarray(feature_fn(imgs))[:keep])
            remaining -= keep
        value = fid.fid_from_stats(real, fake)
        results.append({"ckpt": label, "epoch": epoch,
                        "fid": round(float(value), 4)})
        print(f"[fid-trend] {label}: {value:.2f}", file=sys.stderr)

    wd.done()
    # the output speaks the regression gate's language: per-point deltas
    # under obs.trend's one noise-band policy (FID: lower is better), plus
    # the run_meta provenance stamp every bench artifact now carries
    from ddim_cold_tpu.obs import trend
    from ddim_cold_tpu.utils.record import run_metadata

    out = {
        "metric": "fid_trend_cold",
        "points": trend.annotate_deltas(results, "fid",
                                        lower_is_better=True),
        "run_meta": run_metadata(chip=str(jax.devices()[0].device_kind)),
        "n_samples": args.n_samples,
        "n_real": n_real_seen,
        "extractor": (f"seeded random init (PRNGKey({args.inception_seed})) — "
                      "no network for canonical weights; fixed across all "
                      "points, so values order models but are NOT comparable "
                      "to published FID numbers"),
        "run": run,
    }
    out_dir = os.path.join(REPO, "results", run)
    os.makedirs(out_dir, exist_ok=True)
    with open(os.path.join(out_dir, "fid_trend.json"), "w") as f:
        json.dump(out, f, indent=2)
    print(json.dumps(out))


if __name__ == "__main__":
    main()
