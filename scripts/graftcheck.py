#!/usr/bin/env python
"""Repo-checkout entry for graftcheck (no install needed).

Same CLI as ``python -m ddim_cold_tpu.analysis``::

    python scripts/graftcheck.py --baseline graftcheck.baseline
    python scripts/graftcheck.py --fix-baseline graftcheck.baseline
    python scripts/graftcheck.py --list-rules
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from ddim_cold_tpu.analysis.cli import main  # noqa: E402

if __name__ == "__main__":
    sys.exit(main())
