#!/usr/bin/env python
"""On-chip validation: numerics + honest timing on the real TPU.

Covers what the CPU suite can't: the Pallas flash-attention kernel compiled
for real TPU (vs interpret mode), bf16-on-MXU numerics, and wall-clock
throughput with forced host synchronization (block_until_ready can return
early through the remote-TPU tunnel — every timing below ends in a transfer).

Usage: python scripts/tpu_validate.py [--quick]
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true", help="skip the 200px timings")
    ap.add_argument("--no-bench", action="store_true",
                    help="numerics only — skip the bench delegation (for a "
                         "chain that runs bench.py separately)")
    ap.add_argument("--cpu", action="store_true",
                    help="force CPU (script self-test; site config outranks "
                         "the JAX_PLATFORMS env var)")
    args = ap.parse_args()

    import jax

    from ddim_cold_tpu.utils.platform import (
        honor_env_platform, require_accelerator_or_exit,
    )

    honor_env_platform()  # JAX_PLATFORMS env must beat any site-config pin
    if args.cpu:
        jax.config.update("jax_platforms", "cpu")
    else:
        require_accelerator_or_exit()  # wedged tunnel: exit 3, never hang
    import jax.numpy as jnp
    import numpy as np

    from ddim_cold_tpu.models import MODEL_CONFIGS, DiffusionViT
    from ddim_cold_tpu.ops import sampling
    from ddim_cold_tpu.train.step import create_train_state, make_train_step

    from ddim_cold_tpu.ops.flash_attention import KERNEL_REV

    print(f"backend={jax.default_backend()} devices={jax.devices()} "
          f"kernel_rev={KERNEL_REV}")
    if jax.default_backend() == "cpu":
        print("WARNING: running on CPU — numbers are not TPU numbers")

    # -- 1. fused-attention numerics on-chip (64px + 200px shapes): the
    # Pallas kernel AND the pure-XLA blockwise path, each vs dense. The
    # 200px flash leg runs the bench's tuned headline blocks so the parity
    # check covers the EXACT kernel configuration the record measures -----
    from bench import NS_FLASH_BLOCKS

    for name in ("vit_tiny",) + (() if args.quick else ("oxford_flower_200_p4",)):
        cfg = MODEL_CONFIGS[name]
        dense_m = DiffusionViT(dtype=jnp.bfloat16, **cfg)
        H, W = cfg["img_size"]
        x = jax.random.normal(jax.random.PRNGKey(0), (2, H, W, 3), jnp.float32)
        t = jnp.array([3, 1500], jnp.int32)
        params = dense_m.init(jax.random.PRNGKey(1), x, t)["params"]
        a = np.asarray(dense_m.apply({"params": params}, x, t))
        for impl, label in ((True, "flash"), ("xla", "xla")):
            blocks = (NS_FLASH_BLOCKS
                      if impl is True and name == "oxford_flower_200_p4"
                      else None)
            m = DiffusionViT(dtype=jnp.bfloat16, use_flash=impl,
                             flash_blocks=blocks, **cfg)
            b = np.asarray(m.apply({"params": params}, x, t))
            err = np.abs(a - b).max()
            ok = err < 0.05  # bf16 blockwise-vs-dense softmax tolerance
            print(f"[{label}-parity] {name}: max|dense-{label}|={err:.4f} "
                  f"{'OK' if ok else 'FAIL'}")
            if not ok:
                return 1

    # -- 2. train step + sampler numerics (finite, in-range) ---------------
    model = DiffusionViT(dtype=jnp.bfloat16, **MODEL_CONFIGS["vit_tiny"])
    rs = np.random.RandomState(0)
    B = 32
    batch = (jnp.asarray(rs.randn(B, 64, 64, 3), jnp.float32),
             jnp.asarray(rs.randn(B, 64, 64, 3), jnp.float32),
             jnp.asarray(rs.randint(1, 7, size=(B,)), jnp.int32))
    state = create_train_state(model, jax.random.PRNGKey(0), 2e-4, 51200, batch)
    step = make_train_step(model)
    state, _, ema = step(state, batch, jax.random.PRNGKey(1), jnp.float32(5.0))
    assert np.isfinite(float(ema)), "train step produced non-finite EMA"
    print("[train] one on-chip step: finite OK")
    h = np.asarray(sampling.ddim_sample(model, state.params, jax.random.PRNGKey(2),
                                        k=20, n=16))
    assert np.isfinite(h).all() and 0.0 <= h.min() and h.max() <= 1.0
    print("[sample] vit_tiny k=20 N=16: finite, in [0,1] OK")
    if not args.quick:
        # the 20-step bf16 sampler accumulation at 200px, both attention paths
        # (bench only times these — numerics are asserted here)
        for flash in (False, True, "xla"):
            # the flash leg samples under the bench's tuned headline blocks
            # so the accumulation is asserted at the measured configuration
            m2 = DiffusionViT(dtype=jnp.bfloat16, use_flash=flash,
                              flash_blocks=(NS_FLASH_BLOCKS
                                            if flash is True else None),
                              **MODEL_CONFIGS["oxford_flower_200_p4"])
            p2 = m2.init(jax.random.PRNGKey(0), jnp.zeros((1, 200, 200, 3)),
                         jnp.zeros((1,), jnp.int32))["params"]
            h = np.asarray(sampling.ddim_sample(m2, p2, jax.random.PRNGKey(2),
                                                k=100, n=4))
            assert np.isfinite(h).all() and 0.0 <= h.min() and h.max() <= 1.0
            print(f"[sample] 200px k=100 N=4 flash={flash}: finite, in [0,1] OK")

    # -- 3. timing: delegate to bench.py (single source of timing truth) ---
    if not args.no_bench:
        import bench

        bench_args = ["--smoke"] if args.quick else ["--ksweep"]
        if args.cpu:
            bench_args.append("--cpu")
        bench.main(bench_args)

    print("tpu_validate: ALL OK")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
