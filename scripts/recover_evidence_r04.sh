#!/usr/bin/env bash
# Round-4 evidence chain, fired on TPU-tunnel recovery (watch_tpu --once-exec).
#
# Ordering is VERDICT r3's: the flash 200px north-star FIRST (pending two
# rounds — run it before anything that could wedge the tunnel), then on-chip
# flash numerics, then the full bench (b64 re-measure + scaling to b1024 +
# remat row + e2e with steps-per-dispatch), then the 200px flash training
# run. Every stage commits its evidence the moment it lands (hosts re-image
# between sessions; uncommitted evidence dies) and is idempotent via
# scripts/r04_stage_done.py, so a re-fired chain never re-burns chip time.
#
# No `timeout` wrappers anywhere: SIGTERM/SIGKILL on a client that holds the
# chip grant is what wedges the tunnel in the first place (utils/platform.py).
# bench.py bounds itself with its stall watchdog (partial record + exit 3).
set -u
cd "$(dirname "$0")/.."
mkdir -p results
LOG=results/recovery_chain.log
note() { echo "$(date '+%F %T') [chain-r04] $*" | tee -a "$LOG"; }

ATTEMPTS_F=results/.r04_chain_attempts
A=$(cat "$ATTEMPTS_F" 2>/dev/null || echo 0); A=$((A+1)); echo "$A" > "$ATTEMPTS_F"
note "=== r04 chain start (pid $$, attempt $A) ==="

commit_evidence() { # $1 = message
  git add -A results/ >>"$LOG" 2>&1
  if ! git diff --cached --quiet; then
    # identity fallback: a re-imaged host may lose git config — evidence
    # must still commit, authored like the repo's existing history
    local -a idargs=()
    if ! git config user.email >/dev/null 2>&1; then
      idargs=(-c "user.name=$(git log -1 --format='%an')" \
              -c "user.email=$(git log -1 --format='%ae')")
    fi
    if git "${idargs[@]}" commit -q -m "$1" -m "No-Verification-Needed: evidence-only capture (results/ artifacts, no source change)" >>"$LOG" 2>&1; then
      note "committed: $1"
    else
      note "commit FAILED: $1"
    fi
  fi
}

run_stage() { # $1 = stage key, $2 = label, $3... = command
  local key=$1 label=$2; shift 2
  if python scripts/r04_stage_done.py "$key"; then
    note "$label: SKIPPED (evidence already present)"
    return 0
  fi
  note "$label: start"
  if "$@" >>"$LOG" 2>&1; then
    note "$label: OK"
  else
    note "$label: FAILED rc=$?"
  fi
  commit_evidence "Evidence: r04 $label"
}

# stage 0 — the north-star flash/dense 200px sampler record (+ b32 headline)
ns() {
  python bench.py --skip-e2e --skip-scaling --skip-sampler --no-ksweep \
    --flash-block-sweep --no-reuse \
    > results/bench_r04_northstar.json 2> results/bench_r04_northstar.log
}
run_stage northstar "north-star bench" ns

# stage 1 — on-chip flash fwd numerics (the fix 6d77056 is CPU-guarded only)
val() { python scripts/tpu_validate.py --no-bench > results/tpu_validate_r04.txt 2>&1; }
run_stage validate "tpu_validate numerics" val

# stage 2 — the full round-4 bench record (scaling→b1024, remat, e2e+spd)
fb() {
  python bench.py --no-reuse > results/bench_r04_tpu.json 2> results/bench_r04_tpu.log
}
run_stage fullbench "full bench" fb

# stage 3 — the 200px flash training run (flash BACKWARD on hardware — nothing
# has exercised it yet) + published run dir + snapshot FID trend
t200() {
  if [ ! -d OxfordFlowers200/train ] || [ ! -d OxfordFlowers200/val ]; then
    note "generating OxfordFlowers200 (4096 train / 512 val @ 200px)"
    python scripts/make_dataset.py --out OxfordFlowers200 --size 200 \
      --train 4096 --val 512 || return $?
  fi
  python multi_gpu_trainer.py 20220822_200px || return $?
  python scripts/publish_run.py Saved_Models/20220822_200pxflower200_diffusion || return $?
  python scripts/fid_trend.py Saved_Models/20220822_200pxflower200_diffusion \
    || note "fid_trend FAILED rc=$? (best-effort)"
  return 0
}
run_stage train200 "200px flash training" t200

# incomplete stages (tunnel died mid-chain)? re-arm the watcher, bounded.
# Re-arm target is the REPO-OWNED script itself (ADVICE r4 medium: a /tmp
# path is both wiped by re-imaging and pre-creatable by other local users
# on a shared host), and the chain refuses to arm a missing target.
SELF="$(pwd)/scripts/recover_evidence_r04.sh"
INCOMPLETE=0
for s in northstar validate fullbench train200; do
  python scripts/r04_stage_done.py "$s" || INCOMPLETE=1
done
if [ "$INCOMPLETE" = 1 ] && [ "$A" -lt 5 ]; then
  if [ ! -f "$SELF" ]; then
    note "re-arm ABORTED: exec target $SELF missing"
  else
    note "stages incomplete — re-arming watch_tpu (attempt $A/5)"
    nohup python scripts/watch_tpu.py --interval 180 --timeout 90 \
      --log results/watch_tpu_r04.log --once-exec "bash $SELF" \
      >/dev/null 2>&1 &
  fi
elif [ "$INCOMPLETE" = 1 ]; then
  note "stages incomplete but attempt budget exhausted ($A) — not re-arming"
else
  note "ALL STAGES DONE"
fi
note "=== r04 chain end (attempt $A) ==="
