#!/usr/bin/env python
"""Idempotence oracle for the round-4 recovery chain (recover_evidence_r04.sh).

Exit 0 when the named stage's evidence already exists — a re-fired chain
(the watcher re-arms after a mid-chain tunnel death) must never re-burn chip
time on work that is already committed. Stages:

* ``northstar`` — bench_r04_northstar.json is a TPU record whose submetrics
  carry the flash 200px number OR a recorded flash failure (VERDICT r3
  item 1: if Mosaic rejects, the stack trace IS the round's artifact);
* ``validate``  — tpu_validate_r04.txt reached its terminal "ALL OK" line;
* ``fullbench`` — bench_r04_tpu.json is a TPU record with a headline value
  and a batch-scaling table that reaches b512 (i.e. produced by the
  round-4 bench, not a stale partial);
* ``train200``  — the published 200px run shows >= 8 epochs.
"""

import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from ddim_cold_tpu.utils.record import is_tpu_record, last_json_record  # noqa: E402

RUN200 = "20220822_200pxflower200_diffusion"


def stage_done(stage: str) -> bool:
    res = lambda *p: os.path.join(REPO, "results", *p)  # noqa: E731
    if stage == "northstar":
        rec = last_json_record(res("bench_r04_northstar.json"))
        if not is_tpu_record(rec):
            return False
        sub = rec.get("submetrics", {})
        if "captured_earlier" in sub:
            return False  # a reused record is never stage evidence
        # a completed stage means the flash number AND the block sweep (a
        # watchdog abort between the two must re-run the stage) — or a
        # SECTION-level northstar_error, which only lands after the
        # section's retry also failed (bench re-raises flash-leg failures
        # precisely so transient ones get that retry); the per-leg
        # northstar_flash_error key alone is NOT terminal
        return ("northstar_error" in sub
                or ("sampler_throughput_200px_k20_flash" in sub
                    and "northstar_flash_block_sweep" in sub))
    if stage == "validate":
        try:
            with open(res("tpu_validate_r04.txt")) as f:
                return "tpu_validate: ALL OK" in f.read()
        except OSError:
            return False
    if stage == "fullbench":
        rec = last_json_record(res("bench_r04_tpu.json"))
        if not (is_tpu_record(rec) and rec.get("value")):
            return False
        if "captured_earlier" in rec.get("submetrics", {}):
            return False  # a reused record is never stage evidence
        rows = rec.get("submetrics", {}).get("batch_scaling", [])
        return any(row.get("batch") == 512 for row in rows)
    if stage == "train200":
        try:
            with open(res(RUN200, "summary.json")) as f:
                return json.load(f).get("epochs", 0) >= 8
        except Exception:
            return False
    raise SystemExit(f"unknown stage {stage!r}")


if __name__ == "__main__":
    sys.exit(0 if stage_done(sys.argv[1]) else 1)
