#!/usr/bin/env bash
# Round-5 evidence chain, fired on TPU-tunnel recovery (watch_tpu --once-exec).
#
# Ordering is VERDICT r4's: the flash 200px north-star FIRST (pending three
# rounds — run it before anything that could wedge the tunnel), then on-chip
# flash numerics, then the full bench (scaling to b1024 + remat row + e2e
# with steps-per-dispatch + compile cache), then the 200px flash training
# run (flash BACKWARD on hardware), then the 200px zero-shot apps from the
# fresh weights (VERDICT r4 item 8). Every stage commits its evidence the
# moment it lands (hosts re-image between sessions; uncommitted evidence
# dies) and is idempotent via scripts/r05_stage_done.py, so a re-fired chain
# never re-burns chip time.
#
# No `timeout` wrappers anywhere: SIGTERM/SIGKILL on a client that holds the
# chip grant is what wedges the tunnel in the first place (utils/platform.py).
# bench.py bounds itself with its stall watchdog (partial record + exit 3).
set -u
cd "$(dirname "$0")/.."
REPO=$(pwd)
mkdir -p results
LOG=results/recovery_chain.log
note() { echo "$(date '+%F %T') [chain-r05] $*" | tee -a "$LOG"; }

# bench round-provenance override: the chain KNOWS which round it serves, so
# bench never has to infer it from BENCH_r*.json mtimes (ADVICE r4 low #2)
export DDIM_COLD_ROUND=5

ATTEMPTS_F=results/.r05_chain_attempts
A=$(cat "$ATTEMPTS_F" 2>/dev/null || echo 0); A=$((A+1)); echo "$A" > "$ATTEMPTS_F"
note "=== r05 chain start (pid $$, attempt $A) ==="

commit_evidence() { # $1 = message
  git add -A results/ >>"$LOG" 2>&1
  if ! git diff --cached --quiet; then
    # identity fallback: a re-imaged host may lose git config — evidence
    # must still commit, authored like the repo's existing history
    local -a idargs=()
    if ! git config user.email >/dev/null 2>&1; then
      idargs=(-c "user.name=$(git log -1 --format='%an')" \
              -c "user.email=$(git log -1 --format='%ae')")
    fi
    if git "${idargs[@]}" commit -q -m "$1" -m "No-Verification-Needed: evidence-only capture (results/ artifacts, no source change)" >>"$LOG" 2>&1; then
      note "committed: $1"
    else
      note "commit FAILED: $1"
    fi
  fi
}

# settle window between chip clients: the r05 wedge hit the client that
# connected the same second the previous one disconnected (grant-handoff
# race, results/tunnel_diag_r05.txt) — give the relay a beat to release
SETTLE=${DDIM_COLD_STAGE_SETTLE:-10}

run_stage() { # $1 = stage key, $2 = label, $3... = command
  local key=$1 label=$2; shift 2
  if python scripts/r05_stage_done.py "$key"; then
    note "$label: SKIPPED (evidence already present)"
    return 0
  fi
  note "$label: start"
  sleep "$SETTLE"
  if "$@" >>"$LOG" 2>&1; then
    note "$label: OK"
  else
    note "$label: FAILED rc=$?"
  fi
  commit_evidence "Evidence: r05 $label"
}

# stage 0 — the north-star flash/dense/xla 200px sampler record (+ block
# sweep). Three rounds pending; it runs before anything that could wedge.
ns() {
  python bench.py --skip-e2e --skip-scaling --skip-sampler --no-ksweep \
    --flash-block-sweep --no-reuse \
    > results/bench_r05_northstar.json 2> results/bench_r05_northstar.log
}
run_stage northstar "north-star bench" ns

# stage 1 — on-chip flash fwd numerics (the Mosaic fix is CPU-guarded only)
val() { python scripts/tpu_validate.py --no-bench > results/tpu_validate_r05.txt 2>&1; }
run_stage validate "tpu_validate numerics" val

# stage 2 — the full round-5 bench record (scaling→b1024, remat, e2e+spd,
# b64 re-measure with the two-window timer)
fb() {
  python bench.py --no-reuse > results/bench_r05_tpu.json 2> results/bench_r05_tpu.log
}
run_stage fullbench "full bench" fb

# stage 3 — the 200px flash training run (flash BACKWARD on hardware) +
# published run dir + snapshot FID trend
t200() {
  if [ ! -d OxfordFlowers200/train ] || [ ! -d OxfordFlowers200/val ]; then
    note "generating OxfordFlowers200 (4096 train / 512 val @ 200px)"
    python scripts/make_dataset.py --out OxfordFlowers200 --size 200 \
      --train 4096 --val 512 || return $?
  fi
  python multi_gpu_trainer.py 20220822_200px || return $?
  sleep "$SETTLE"  # grant-handoff settle between chip clients (see above)
  python scripts/publish_run.py Saved_Models/20220822_200pxflower200_diffusion || return $?
  sleep "$SETTLE"
  python scripts/fid_trend.py Saved_Models/20220822_200pxflower200_diffusion \
    || note "fid_trend FAILED rc=$? (best-effort)"
  return 0
}
run_stage train200 "200px flash training" t200

# stage 4 — 200px zero-shot apps from the fresh stage-3 weights (VERDICT r4
# item 8): draft2drawing restart grid + slerp interpolation, published.
a200() {
  local run=Saved_Models/20220822_200pxflower200_diffusion
  local ck=""
  for c in "$run/bestloss.ckpt" "$run/bestloss.pkl" "$run/lastepoch.ckpt"; do
    [ -e "$c" ] && { ck=$c; break; }
  done
  if [ -z "$ck" ]; then
    note "apps200: no 200px checkpoint found (stage 3 incomplete?)"; return 1
  fi
  # draft + interpolation endpoints from the val split (any three images)
  local imgs
  imgs=$(ls OxfordFlowers200/val/*.jpg 2>/dev/null | head -3)
  set -- $imgs
  [ $# -ge 3 ] || { note "apps200: <3 val images available"; return 1; }
  python ViT_draft2drawing.py --config oxford_flower_200_p4 \
    --checkpoint "$ck" --draft "$1" --interpolate "$2" "$3" --cold-n 4 \
    >> "$LOG" 2>&1 || return $?
  mkdir -p results/20220822_200pxflower200_diffusion
  # get_next_path suffixes repeats; take the newest of each artifact family
  for base in draft2img interpolation cold_samples cold_sequence; do
    local latest
    latest=$(ls -t Saved_Models/${base}*.png 2>/dev/null | head -1)
    [ -n "$latest" ] && cp "$latest" \
      "results/20220822_200pxflower200_diffusion/${base}.png"
  done
  return 0
}
run_stage apps200 "200px zero-shot apps" a200

# stage 5a — re-validate on-chip numerics under the bf16-GEMM kernel
# revision (ops/flash_attention.py KERNEL_REV): interpret mode proved the
# math CPU-side; only hardware proves the Mosaic lowering computes the same
# numbers, and this is 7 min vs the 20-min bench it gates.
val2() { python scripts/tpu_validate.py --no-bench > results/tpu_validate_r05b.txt 2>&1; }
run_stage validate_v2 "tpu_validate (bf16-GEMM kernel)" val2

# stage 5a2 (after the numerics gate) — the 200px FID trend that died in the stage-3 wedge, now
# watchdog-bounded (utils/watchdog.py): a stall writes fid_trend.partial.json
# and exits 3 instead of hanging the chain
f200() { python scripts/fid_trend.py Saved_Models/20220822_200pxflower200_diffusion; }
run_stage fid200 "200px fid trend" f200

# stage 5b — re-measure the full record under the bf16-GEMM kernel revision
# (ops/flash_attention.py KERNEL_REV, landed mid-round after stages 0-3 had
# captured the f32-GEMM kernel). Writes to a temp file and promotes only on
# bench success so a watchdog abort can never clobber the committed stage-2
# record (which also backs the fullbench done-key); the pre-optimization
# record stays in git history either way.
bv2() {
  # HARD gate on the numerics re-validate: a kernel whose on-chip numerics
  # just failed (or never ran) must not produce a record that replaces the
  # committed numerics-valid stage-2 evidence
  if ! python scripts/r05_stage_done.py validate_v2; then
    note "bench_v2: blocked — validate_v2 has not passed for this kernel rev"
    return 1
  fi
  # tmp lives at the repo root, NOT under results/ — commit_evidence's
  # `git add -A results/` must never commit an un-promoted partial record
  local tmp=.bench_r05_v2_tmp.json
  if ! python bench.py --no-reuse --flash-block-sweep --skip-e2e \
      > "$tmp" 2> results/bench_r05_v2.log; then
    rm -f "$tmp"; return 1
  fi
  # Promote only a record that would satisfy stage_done('bench_v2') — same
  # bar, checked BEFORE the mv: bench.py exits 0 both on its deliberate
  # CPU-smoke fallback (wedged tunnel) and on a best-effort partial record
  # (e.g. batch_scaling failed both attempts, r03-style), and neither may
  # clobber the committed stage-2 TPU evidence. bv2 runs --skip-e2e (a
  # same-session re-run would measure warm caches and overstate "cold"), so
  # the stage-2 record's genuinely-cold e2e rows are carried into the
  # promoted record, labeled.
  if ! python - "$tmp" <<'PY'
import json, sys
from ddim_cold_tpu.ops.flash_attention import KERNEL_REV
from ddim_cold_tpu.utils.record import is_tpu_record, last_json_record
tmp = sys.argv[1]
rec = last_json_record(tmp)
sub = rec.get("submetrics", {}) if rec else {}
ok = (is_tpu_record(rec) and rec.get("value")
      and "captured_earlier" not in sub
      and sub.get("kernel_rev") == KERNEL_REV
      and any(r.get("batch") == 512 for r in sub.get("batch_scaling", [])))
if not ok:
    sys.exit(1)
old = last_json_record("results/bench_r05_tpu.json")
carried = {k: v for k, v in (old.get("submetrics", {}) if old else {}).items()
           if k.startswith("e2e_")}
if carried:
    sub.update(carried)
    sub["e2e_carried_from"] = (
        "stage-2 record (cold-cache session); bench_v2 skips e2e because a "
        "same-session re-run would measure warm caches — the kernel change "
        "does not touch the e2e path")
with open(tmp, "w") as f:
    f.write(json.dumps(rec) + "\n")
PY
  then
    note "bench_v2: record does not meet the stage bar — not promoting"
    rm -f "$tmp"; return 1
  fi
  mv "$tmp" results/bench_r05_tpu.json
}
run_stage bench_v2 "full bench (bf16-GEMM kernel)" bv2

# incomplete stages (tunnel died mid-chain)? re-arm the watcher, bounded.
# Re-arm target is the REPO-OWNED script path (ADVICE r4 medium: a /tmp
# path is both wiped by re-imaging and pre-creatable by other local users
# on a shared host), and the chain refuses to arm a missing target.
SELF="$REPO/scripts/recover_evidence_r05.sh"
INCOMPLETE=0
for s in northstar validate fullbench train200 apps200 validate_v2 fid200 bench_v2; do
  python scripts/r05_stage_done.py "$s" || INCOMPLETE=1
done
if [ "$INCOMPLETE" = 1 ] && [ "$A" -lt 5 ]; then
  if [ ! -f "$SELF" ]; then
    note "re-arm ABORTED: exec target $SELF missing"
  else
    note "stages incomplete — re-arming watch_tpu (attempt $A/5)"
    nohup python scripts/watch_tpu.py --interval 180 --timeout 90 \
      --log results/watch_tpu_r05.log --once-exec "bash $SELF" \
      >/dev/null 2>&1 &
  fi
elif [ "$INCOMPLETE" = 1 ]; then
  note "stages incomplete but attempt budget exhausted ($A) — not re-arming"
else
  note "ALL STAGES DONE"
fi
note "=== r05 chain end (attempt $A) ==="
