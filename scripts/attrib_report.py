#!/usr/bin/env python
"""Render a profiler capture as a slowest-scope-first attribution table.

Input: a ``jax.profiler`` output directory (``bench --attrib`` writes
``results/attrib_profile``; ``profiling.span_trace(..., perfetto=True)``
writes span-keyed ones), a ``.trace.json[.gz]`` file, or ``--demo`` for the
checked-in synthetic fixture — the same rendering path either way, so the
report format is testable without a chip (the ``obs_report --demo`` rule).

Usage:
  python scripts/attrib_report.py results/attrib_profile --device-kind "TPU v5 lite"
  python scripts/attrib_report.py --demo
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from ddim_cold_tpu.obs import attrib  # noqa: E402


def _fmt(v, spec="{}", none="-"):
    return none if v is None else spec.format(v)


def _render(report: dict) -> str:
    lines = [
        f"device: {report['device_kind'] or '?'} · "
        f"{report['device_lanes']} lane(s) · peak "
        f"{_fmt(report['peak_bf16_tflops'])} TFLOP/s · HBM "
        f"{_fmt(report['hbm_gb_s'])} GB/s · ridge "
        f"{_fmt(report['ridge_flops_per_byte'])} FLOP/byte",
        f"window {report['window_s']:.6f}s · busy "
        f"{report['device_busy_s']:.6f}s "
        f"({_fmt(report['busy_fraction'], '{:.1%}')}) · idle gaps "
        f"{report['idle_s']:.6f}s · coverage "
        f"{_fmt(report['coverage'], '{:.1%}')} of busy attributed "
        f"(floor {attrib.COVERAGE_FLOOR:.0%})",
        "",
        "| scope | self ms | total ms | share | TFLOP/s | MFU | bound |",
        "|---|---|---|---|---|---|---|",
    ]
    for name, node in attrib.ranked_scopes(report):
        lines.append(
            f"| {name} | {1000 * node['self_s']:.3f} | "
            f"{1000 * node['total_s']:.3f} | "
            f"{_fmt(node['share_of_busy'], '{:.1%}')} | "
            f"{_fmt(node['achieved_tflops'])} | {_fmt(node['mfu'])} | "
            f"{_fmt(node['roofline'])} |")
    if report["tree"]:
        lines += ["", "scope nesting: " + " · ".join(
            f"{p} → {{{', '.join(kids)}}}"
            for p, kids in sorted(report["tree"].items()))]
    if report["fusion_candidates"]:
        lines += ["", "fusion candidates (adjacent scoped ops, launch gap "
                  f"≤ {attrib.DEFAULT_GAP_US:.0f}µs):"]
        for c in report["fusion_candidates"][:5]:
            lines.append(
                f"  {c['pair'][0]} → {c['pair'][1]}: {c['count']}× · "
                f"{c['total_gap_us']}µs reclaimable (mean "
                f"{c['mean_gap_us']}µs) over {c['combined_busy_us']}µs busy")
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="slowest-scope-first attribution table from a "
                    "profiler trace")
    ap.add_argument("trace", nargs="?", default=None,
                    help="profiler output dir or .trace.json[.gz] file")
    ap.add_argument("--demo", action="store_true",
                    help="render the checked-in synthetic fixture (no "
                         "trace/chip needed)")
    ap.add_argument("--device-kind", default=None,
                    help="chip name for the flops/roofline join (e.g. "
                         "'TPU v5 lite'); omit for time-only attribution")
    ap.add_argument("--gap-us", type=float, default=attrib.DEFAULT_GAP_US,
                    help="fusion-candidate launch-gap ceiling")
    ap.add_argument("--json", default=None,
                    help="also write the full report to this path")
    args = ap.parse_args(argv)
    if args.demo:
        report = attrib.demo_report(gap_us=args.gap_us)
    elif args.trace:
        try:
            report = attrib.attribute(attrib.load_trace(args.trace),
                                      device_kind=args.device_kind,
                                      gap_us=args.gap_us)
        except attrib.AttribError as e:
            print(f"attrib_report: {e}", file=sys.stderr)
            return 1
        if not report["device_lanes"]:
            print("attrib_report: trace has no device lanes (a jax CPU "
                  "capture records host threads only) — nothing to "
                  "attribute; try --demo for the fixture", file=sys.stderr)
            return 1
    else:
        ap.error("pass a trace path or --demo")
    print(_render(report))
    if args.json:
        with open(args.json, "w") as f:
            json.dump(report, f, indent=1)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
