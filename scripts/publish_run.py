#!/usr/bin/env python
"""Publish a training run's evidence into the committed ``results/`` dir.

Round-1 verdict: the framework was unit-correct but shipped no proof that it
*trains* — no committed loss curve, no sample grids from trained weights.
This script turns a finished ``Saved_Models/<run>/`` into committable
artifacts:

* ``results/<run>/train.log`` + ``metrics.jsonl`` — the raw record (the
  reference's own train.log is the parity artifact, SURVEY.md C21);
* ``results/<run>/val_curve.png`` — our per-epoch val smooth-L1 overlaid
  against the reference's committed run
  (`/root/reference/Saved_Models/20220822vit_tiny_diffusion/train.log`:
  0.071 @ epoch 0 → best 0.0504). The datasets differ (procedural surrogate
  vs Oxford Flowers — the bench host has no network), so the overlay shows
  *convergence behavior*, not identical values;
* ``results/<run>/samples.png`` / ``cold_sequence.png`` — grids sampled from
  the run's ``bestloss.ckpt``;
* ``results/<run>/summary.json`` — machine-readable best/final losses.

Usage: python scripts/publish_run.py [run_dir] [--no-samples] [--cpu]
"""

from __future__ import annotations

import argparse
import json
import os
import re
import shutil
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
REF_LOG = ("/root/reference/Saved_Models/20220822vit_tiny_diffusion/train.log")
EPOCH_RE = re.compile(r"epoch:\s*(\d+)\s+loss:\s*([0-9.]+)")


def parse_epoch_losses(log_path: str) -> dict[int, float]:
    """epoch → val loss; later lines win (the reference log contains a
    restart whose epochs overlap, multi_gpu_trainer resume semantics)."""
    out: dict[int, float] = {}
    with open(log_path) as f:
        for line in f:
            m = EPOCH_RE.search(line)
            if m:
                out[int(m.group(1))] = float(m.group(2))
    return out


def render_curve(ours: dict[int, float], ref: dict[int, float], path: str):
    import matplotlib

    matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    fig, ax = plt.subplots(figsize=(7, 4.2), dpi=130)
    if ref:
        xs = sorted(ref)
        ax.plot(xs, [ref[x] for x in xs], color="#999999", lw=1.5,
                label="reference (torch/3090, Oxford Flowers)")
        ax.axhline(min(ref.values()), color="#999999", lw=0.8, ls="--",
                   label=f"reference best {min(ref.values()):.4f}")
    xs = sorted(ours)
    ax.plot(xs, [ours[x] for x in xs], color="#1666c0", lw=1.8,
            label="this framework (TPU, surrogate flowers)")
    ax.axhline(min(ours.values()), color="#1666c0", lw=0.8, ls="--",
               label=f"ours best {min(ours.values()):.4f}")
    ax.set_xlabel("epoch")
    ax.set_ylabel("val smooth-L1")
    ax.set_yscale("log")
    ax.set_title("Cold-diffusion vit_tiny 64px: val loss per epoch")
    ax.legend(fontsize=8)
    fig.tight_layout()
    fig.savefig(path)
    plt.close(fig)


def render_samples(run_dir: str, out_dir: str, *, n: int = 16, wd=None):
    """Grids from the run's best checkpoint: DDIM samples + the 6-step cold
    sequence (the reference's two acceptance figures, ViT.py:283-305,
    ViT_draft2drawing.py:364-376)."""
    import math

    import jax
    import numpy as np

    from ddim_cold_tpu.ops import sampling
    from ddim_cold_tpu.utils.image import save_grid
    from ddim_cold_tpu.utils.run_io import load_run

    config, model, params = load_run(run_dir)

    # cold-model grids in the run's trained regime: t ∈ [1, log2(H)] —
    # 6 levels for 64px, 7 for the 200px config (same rule as compute_fid)
    levels = int(math.log2(config.image_size[0]))
    side = int(np.sqrt(n))
    if wd is not None:  # first device op = the 200px sampler compile
        wd.mark("sample grid (first sampler compile)", budget_s=1800)
    cold = np.asarray(sampling.cold_sample(
        model, params, jax.random.PRNGKey(0), n=side * side, levels=levels))
    save_grid(cold, os.path.join(out_dir, "samples.png"),
              nrows=side, ncols=side)
    if wd is not None:  # n=4 differs from n=16: a second compile
        wd.mark("sequence grid (second sampler compile)", budget_s=1800)
    seq = np.asarray(sampling.cold_sample(
        model, params, jax.random.PRNGKey(1), n=4, levels=levels,
        return_sequence=True))
    # (levels, n, H, W, C) → rows = sample, cols = denoising level
    frames = seq.transpose(1, 0, 2, 3, 4).reshape(-1, *seq.shape[-3:])
    save_grid(frames, os.path.join(out_dir, "cold_sequence.png"),
              nrows=seq.shape[1], ncols=seq.shape[0])


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("run_dir", nargs="?", default=os.path.join(
        REPO, "Saved_Models", "20220822vit_tiny_diffusion"))
    ap.add_argument("--no-samples", action="store_true")
    ap.add_argument("--cpu", action="store_true")
    args = ap.parse_args(argv)

    from ddim_cold_tpu.utils.platform import honor_env_platform

    honor_env_platform()
    if args.cpu:
        import jax

        jax.config.update("jax_platforms", "cpu")
    else:
        from ddim_cold_tpu.utils.platform import require_accelerator_or_exit

        require_accelerator_or_exit()  # wedged tunnel: exit 3, never hang

    run = os.path.basename(os.path.normpath(args.run_dir))
    out_dir = os.path.join(REPO, "results", run)
    os.makedirs(out_dir, exist_ok=True)
    for name in ("train.log", "metrics.jsonl"):
        src = os.path.join(args.run_dir, name)
        if os.path.isfile(src):
            shutil.copy(src, out_dir)

    ours = parse_epoch_losses(os.path.join(args.run_dir, "train.log"))
    if not ours:
        raise SystemExit("no epoch lines in train.log — run unfinished?")
    ref = parse_epoch_losses(REF_LOG) if os.path.isfile(REF_LOG) else {}
    render_curve(ours, ref, os.path.join(out_dir, "val_curve.png"))

    if not args.no_samples:
        # wedged-tunnel guard for the mid-run RPCs require_accelerator's
        # one-shot probe can't cover (r05: fid_trend hung exactly there) —
        # the curves/logs above are already published; sampling is the only
        # unbounded device work, so a stall still leaves a partial artifact
        from ddim_cold_tpu.utils.platform import watchdog_stall_s
        from ddim_cold_tpu.utils.watchdog import StallWatchdog

        # shared arm-condition (comma-list aware; ADVICE r5 item 3)
        stall_s = watchdog_stall_s("DDIM_COLD_FID_STALL_S", 600.0)
        wd = StallWatchdog(stall_s, name="publish-run").start()
        render_samples(args.run_dir, out_dir, wd=wd)
        wd.done()

    summary = {
        "run": run,
        "epochs": len(ours),
        "val_loss_epoch0": ours.get(0),
        "val_loss_best": min(ours.values()),
        "val_loss_last": ours[max(ours)],
        "reference_best": min(ref.values()) if ref else None,
        "reference_epoch0": ref.get(0) if ref else None,
        "dataset": "procedural surrogate flowers (scripts/make_dataset.py; "
                   "bench host has no network for the real Oxford Flowers)",
    }
    with open(os.path.join(out_dir, "summary.json"), "w") as f:
        json.dump(summary, f, indent=2)
    print(json.dumps(summary))
    print(f"published → {out_dir}")


if __name__ == "__main__":
    main()
