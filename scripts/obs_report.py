#!/usr/bin/env python
"""Export the process span recorder, or re-render a dumped span file.

Two modes:

* ``--from-jsonl spans.jsonl --chrome trace.json`` — convert a JSONL span
  dump (``obs.spans.export_jsonl``) into Chrome trace-event JSON for
  chrome://tracing / Perfetto, plus a per-trace text summary on stdout.
* ``--demo`` — run a tiny traced serving drain on CPU (the test-model
  geometry) and write both exports; the quickest way to SEE a span tree.

In-process users call ``obs.spans.export_chrome()`` directly; this script
exists for the files they leave behind.
"""

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _chrome_from_rows(rows):
    events = []
    for s in rows:
        t1 = s["t1"] if s["t1"] is not None else s["t0"]
        args = {"span_id": s["span_id"], "parent_id": s["parent_id"]}
        args.update(s.get("attrs") or {})
        if s["t1"] is None:
            args["open"] = True
        events.append({
            "name": s["name"], "cat": "serve", "ph": "X",
            "ts": round(s["t0"] * 1e6, 3),
            "dur": round((t1 - s["t0"]) * 1e6, 3),
            "pid": 0, "tid": s["trace_id"], "args": args,
        })
    return {"traceEvents": events, "displayTimeUnit": "ms"}


def _root_dur(spans_in_trace):
    tree = sorted(spans_in_trace, key=lambda s: (s["t0"], s["span_id"]))
    root = next((s for s in tree if s["parent_id"] is None), tree[0])
    dur = float("inf") if root["t1"] is None else root["t1"] - root["t0"]
    return dur, root, tree


def _summarize(rows, out=sys.stdout):
    traces = {}
    for s in rows:
        traces.setdefault(s["trace_id"], []).append(s)
    print(f"{len(rows)} span(s) across {len(traces)} trace(s)", file=out)
    # slowest (or still-open) traces first: the p99 straggler is the one
    # being hunted, so it leads the report
    order = sorted(traces, key=lambda t: (-_root_dur(traces[t])[0], t))
    for tid in order:
        rdur, root, tree = _root_dur(traces[tid])
        dur = "open" if root["t1"] is None else f"{rdur:.4f}s"
        print(f"trace {tid}: {root['name']} ({dur}, {len(tree)} spans)",
              file=out)
        for s in tree:
            if s is root:
                continue
            sdur = "open" if s["t1"] is None else f"{s['t1'] - s['t0']:.4f}s"
            attrs = {k: v for k, v in (s.get("attrs") or {}).items()}
            print(f"  {s['name']:<12} {sdur:>10}  {attrs}", file=out)


def _demo(chrome_path, jsonl_path):
    import jax
    import jax.numpy as jnp

    from ddim_cold_tpu import serve
    from ddim_cold_tpu.models.vit import DiffusionViT
    from ddim_cold_tpu.obs import spans

    model = DiffusionViT(img_size=(16, 16), patch_size=8, embed_dim=32,
                         depth=2, num_heads=4, total_steps=2000)
    params = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 16, 16, 3)),
                        jnp.zeros((1,), jnp.int32))["params"]
    cfg = serve.SamplerConfig(k=500)
    engine = serve.Engine(model, params, buckets=(2,))
    serve.warmup(engine, [cfg])
    with spans.tracing():
        for seed in (0, 1):
            engine.submit(seed=seed, n=2, config=cfg)
        engine.run()
    rows = spans.export_jsonl(jsonl_path)
    spans.export_chrome(chrome_path)
    return rows


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--from-jsonl", metavar="PATH",
                    help="read spans from a JSONL dump instead of running")
    ap.add_argument("--chrome", metavar="PATH", default=None,
                    help="write Chrome trace-event JSON here")
    ap.add_argument("--jsonl", metavar="PATH", default=None,
                    help="write (or re-write) a JSONL span dump here")
    ap.add_argument("--demo", action="store_true",
                    help="run a tiny traced CPU serving drain first")
    args = ap.parse_args(argv)

    if args.demo:
        rows = _demo(args.chrome or "obs_trace.json",
                     args.jsonl or "obs_spans.jsonl")
    elif args.from_jsonl:
        with open(args.from_jsonl) as f:
            rows = [json.loads(line) for line in f if line.strip()]
        if args.chrome:
            with open(args.chrome, "w") as f:
                json.dump(_chrome_from_rows(rows), f)
        if args.jsonl:
            with open(args.jsonl, "w") as f:
                for row in rows:
                    f.write(json.dumps(row) + "\n")
    else:
        ap.error("pass --from-jsonl PATH or --demo")
        return 2
    _summarize(rows)
    return 0


if __name__ == "__main__":
    sys.exit(main())
