#!/usr/bin/env python
"""One-shot evidence pipeline: train → publish → FID → BASELINE update.

Round-1 verdict items 1-3 in one command (designed to run unattended as soon
as TPU access is available):

1. generate the surrogate dataset if absent (scripts/make_dataset.py recipe);
2. ``python multi_gpu_trainer.py 20220822`` — the reference's recorded
   experiment (100 epochs, 512 train / 85 val batches @ effective batch 32);
3. ``scripts/publish_run.py`` — committable results/: train.log,
   metrics.jsonl, val-curve overlay vs the reference record, sample grids;
4. ``scripts/compute_fid.py`` — FID between val images and cold samples from
   bestloss.ckpt (seeded extractor; see that script for weight provenance);
5. record the headline numbers into BASELINE.json's ``published`` map.

Usage: python scripts/run_evidence.py [--skip-train] [--epochs N]
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
RUN = os.path.join(REPO, "Saved_Models", "20220822vit_tiny_diffusion")


def sh(argv, **kw):
    print(f"[evidence] $ {' '.join(argv)}", flush=True)
    t0 = time.time()
    subprocess.run(argv, check=True, cwd=REPO, **kw)
    print(f"[evidence] done in {time.time() - t0:.0f}s", flush=True)


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--skip-train", action="store_true",
                    help="run publish/FID against an existing Saved_Models run")
    ap.add_argument("--epochs", type=int, default=None,
                    help="override epoch[1] (reduced-scale fallback runs)")
    ap.add_argument("--fid-samples", type=int, default=1024)
    ap.add_argument("--fid-real", type=int, default=2048,
                    help="real images for FID statistics (both scripts)")
    ap.add_argument("--trend-samples", type=int, default=256,
                    help="samples per fid_trend point")
    args = ap.parse_args(argv)

    if not os.path.isdir(os.path.join(REPO, "OxfordFlowers", "train")):
        sh([sys.executable, "scripts/make_dataset.py", "--out", "OxfordFlowers"])

    global RUN
    if args.epochs is not None:
        # reduced-scale runs live in their own exp dir; --skip-train reruns
        # of the SAME flags must target it too, not the canonical run
        name = f"20220822_e{args.epochs}"
        RUN = os.path.join(REPO, "Saved_Models", name + "vit_tiny_diffusion")
    else:
        name = "20220822"

    if not args.skip_train:
        if args.epochs is not None:
            import yaml

            with open(os.path.join(REPO, "20220822.yaml")) as f:
                cfg = yaml.safe_load(f)
            cfg["epoch"] = [0, args.epochs]
            with open(os.path.join(REPO, name + ".yaml"), "w") as f:
                yaml.safe_dump(cfg, f)
        sh([sys.executable, "multi_gpu_trainer.py", name])

    sh([sys.executable, "scripts/publish_run.py", RUN])
    sh([sys.executable, "scripts/compute_fid.py", RUN,
        "--n-samples", str(args.fid_samples), "--n-real", str(args.fid_real)])
    try:
        # per-checkpoint trend under the same seeded extractor (works even
        # without snapshots/: random-init anchor + best still give 2 points)
        sh([sys.executable, "scripts/fid_trend.py", RUN,
            "--n-samples", str(args.trend_samples),
            "--n-real", str(args.fid_real)])
    except subprocess.CalledProcessError as e:
        print(f"[evidence] fid_trend failed (non-fatal): {e}", flush=True)

    run_name = os.path.basename(RUN)
    out_dir = os.path.join(REPO, "results", run_name)
    with open(os.path.join(out_dir, "summary.json")) as f:
        summary = json.load(f)
    with open(os.path.join(out_dir, "fid.json")) as f:
        fid = json.load(f)

    baseline_path = os.path.join(REPO, "BASELINE.json")
    with open(baseline_path) as f:
        baseline = json.load(f)
    baseline.setdefault("published", {}).update({
        "val_smooth_l1_best": summary["val_loss_best"],
        "val_smooth_l1_epoch0": summary["val_loss_epoch0"],
        "reference_val_smooth_l1_best": summary["reference_best"],
        "epochs": summary["epochs"],
        fid["metric"]: fid["value"],
        "fid_extractor": fid["extractor"],
        "dataset": summary["dataset"],
    })
    with open(baseline_path, "w") as f:
        json.dump(baseline, f, indent=2)
    print(json.dumps(baseline["published"], indent=2))
    print(f"[evidence] BASELINE.json published map updated; artifacts in {out_dir}")


if __name__ == "__main__":
    main()
