#!/usr/bin/env bash
# Evidence chain fired on TPU-tunnel recovery (scripts/watch_tpu.py --once-exec).
#
# Round-3 ordering (VERDICT r2 "next round" items, most valuable first):
#   1. run_evidence.py — 100-epoch training on the chip, publish, FID
#      n=1024/2048 + per-snapshot trend (items 2+3);
#   2. bench.py full — the complete hardware record incl. the flash
#      north-star leg the pre-fix bench couldn't compile (item 1);
#   3. the 200px flash training run + publish (item 4).
#
# No `timeout` wrappers anywhere: SIGTERM/SIGKILL on a client that holds the
# chip grant is what wedges the tunnel in the first place (utils/platform.py).
# Stages continue on failure so one bad stage can't strand the rest.
set -u
cd "$(dirname "$0")/.."
mkdir -p results
LOG=results/recovery_chain.log
note() { echo "$(date '+%F %T') [chain] $*" | tee -a "$LOG"; }

note "=== chain start (pid $$) ==="

# stage 1 costs ~73 min of chip; skip it when its published artifacts
# already show a finished 100-epoch run (they are committed in results/, so
# they survive host re-images — an accidental full-chain fire must not
# re-train past them)
STAGE1_DONE=$(python - <<'PY'
import json
import os
d = "results/20220822vit_tiny_diffusion"
try:
    # BOTH the finished run AND its FID evidence must exist — summary.json
    # is written before the FID step in run_evidence.py, so a run whose FID
    # crashed must not be skipped past (the FID would never be produced)
    done = (json.load(open(os.path.join(d, "summary.json"))).get("epochs", 0)
            >= 100 and os.path.isfile(os.path.join(d, "fid.json")))
except Exception:
    done = False
print("yes" if done else "no")
PY
)
if [ "$STAGE1_DONE" = "yes" ]; then
  note "stage 1: SKIPPED (published summary already shows >=100 epochs)"
else
  note "stage 1: training evidence (scripts/run_evidence.py)"
  if python scripts/run_evidence.py >> "$LOG" 2>&1; then
    note "stage 1 OK"
  else
    note "stage 1 FAILED rc=$?"
  fi
fi

note "stage 2: full bench"
if python bench.py > results/bench_r03_tpu_full.json 2> results/bench_r03_tpu_full.log; then
  note "stage 2 OK: $(cat results/bench_r03_tpu_full.json | head -c 200)"
else
  note "stage 2 FAILED rc=$?"
fi

note "stage 2b: on-chip flash/dense numerics (tpu_validate --no-bench)"
if python scripts/tpu_validate.py --no-bench > results/tpu_validate_r03.txt 2>&1; then
  note "stage 2b OK"
else
  note "stage 2b FAILED rc=$?"
fi

note "stage 3: 200px flash training run"
# 20220822_200px.yaml points at OxfordFlowers200/ — build it if absent
# (smaller than the 64px set: the goal is a real run dir, not convergence)
if [ ! -d OxfordFlowers200/train ] || [ ! -d OxfordFlowers200/val ]; then
  note "stage 3: generating OxfordFlowers200 (4096 train / 512 val @ 200px)"
  python scripts/make_dataset.py --out OxfordFlowers200 --size 200 \
    --train 4096 --val 512 >> "$LOG" 2>&1 || note "stage 3 dataset gen FAILED rc=$?"
fi
if python multi_gpu_trainer.py 20220822_200px >> "$LOG" 2>&1; then
  if python scripts/publish_run.py Saved_Models/20220822_200pxflower200_diffusion >> "$LOG" 2>&1; then
    note "stage 3 OK"
  else
    note "stage 3 publish FAILED rc=$?"
  fi
else
  note "stage 3 train FAILED rc=$?"
fi

note "=== chain done ==="
