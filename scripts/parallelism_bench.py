#!/usr/bin/env python
"""Measure parallelism-layout overhead: dp vs dp×{pipe,seq,tp,expert}.

The round-1 suite proved these layouts *correct* (gradient equivalence); this
script measures what each one *costs*, so the README can say when to use
which (VERDICT round 1: "risk of a shipped feature that's always slower than
dp for in-repo model sizes").

On the 8-virtual-CPU-device mesh the devices timeshare one host core, so
wall-clock ≈ TOTAL WORK across the mesh: a layout that burns FLOPs on GPipe
bubble steps or re-materializes activations shows up directly as a ratio > 1
vs plain dp on the same global batch. (It cannot show ICI-bound speedups —
that needs a real slice; what it isolates is the schedule/collective overhead
each layout adds.)

Writes one JSON line per layout:
    {"layout": "dp4_pipe2", "ms_per_step": ..., "vs_dp": ..., "baseline": ...}
where ``baseline`` names the denominator row: dense layouts ratio against
plain dp, the MoE rows against the SAME MoE model on plain dp (comparing MoE
to the dense baseline would conflate model cost with layout cost — the two
families' ``vs_dp`` values are not cross-comparable).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--devices", type=int, default=8)
    ap.add_argument("--steps", type=int, default=8)
    ap.add_argument("--batch", type=int, default=16, help="global batch")
    ap.add_argument("--depth", type=int, default=8,
                    help="transformer depth (divisible by pipe stages)")
    ap.add_argument("--img", type=int, default=64)
    ap.add_argument("--patch", type=int, default=4,
                    help="4 → 257 tokens: long enough that seq sharding is real")
    ap.add_argument("--embed", type=int, default=128)
    ap.add_argument("--heads", type=int, default=4)
    ap.add_argument("--tpu", action="store_true",
                    help="run on the real TPU backend (default: virtual CPU "
                         "mesh — probing for a TPU can block when the chip "
                         "is leased elsewhere)")
    args = ap.parse_args(argv)

    flags = os.environ.get("XLA_FLAGS", "")
    if "--xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            f"{flags} --xla_force_host_platform_device_count={args.devices}"
        ).strip()
    import jax

    from ddim_cold_tpu.utils.platform import honor_env_platform

    if args.tpu:
        honor_env_platform()
    else:
        jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    import numpy as np

    from ddim_cold_tpu.config import ExperimentConfig
    from ddim_cold_tpu.parallel import make_mesh, shard_batch, shard_train_state
    from ddim_cold_tpu.parallel.layout import layout_for_mesh
    from ddim_cold_tpu.train.step import create_train_state, make_train_step
    from ddim_cold_tpu.train.trainer import build_model

    n = args.devices
    # (mesh, config overrides): the two MoE rows isolate the ep LAYOUT cost
    # by comparing the same MoE model on plain dp vs dp×ep — comparing MoE
    # to the dense dp baseline would conflate model cost with layout cost
    layouts = {
        f"dp{n}": ({"data": n}, {}),
        f"dp{n//2}_pipe2": ({"data": n // 2, "pipe": 2}, {}),
        f"dp{n//2}_seq2": ({"data": n // 2, "seq": 2}, {}),
        f"dp{n//2}_tp2": ({"data": n // 2, "model": 2}, {}),
        f"moe_dp{n}": ({"data": n}, {"num_experts": 4}),
        f"moe_dp{n//2}_ep2": ({"data": n // 2, "expert": 2},
                              {"num_experts": 4}),
        # index-dispatch rows: same MoE model, sort/gather routing — the
        # ratio against the einsum rows is the dispatch-implementation cost
        # at this (short-sequence) scale; index exists for the O(N²·cf)
        # regimes the einsum rows can't reach (models/moe.py)
        f"moe_idx_dp{n}": ({"data": n},
                           {"num_experts": 4, "moe_dispatch": "index"}),
        f"moe_idx_dp{n//2}_ep2": ({"data": n // 2, "expert": 2},
                                  {"num_experts": 4, "moe_dispatch": "index"}),
    }
    if n % 4 == 0 and n >= 8:
        # composed layouts (round 5): pipe×tp rides GSPMD-auto 'model'
        # inside each stage; seq×tp with both sp strategies (ring keeps
        # heads tp-sharded through the rotation, ulysses all-to-alls each
        # tp group's local heads). Gated like __graft_entry__'s composed
        # legs — an n//4 mesh cannot cover 2 or 6 devices.
        layouts.update({
            f"dp{n//4}_pipe2_tp2": ({"data": n // 4, "pipe": 2, "model": 2},
                                    {}),
            f"dp{n//4}_seq2_tp2_ring": ({"data": n // 4, "seq": 2,
                                         "model": 2}, {}),
            f"dp{n//4}_seq2_tp2_ul": ({"data": n // 4, "seq": 2, "model": 2},
                                      {"sp_mode": "ulysses"}),
            # pipe×ep (round 5): expert banks GSPMD-auto inside the manual
            # pipe region, aux re-sown through the schedule; ratios against
            # the same-model moe_dp row like every MoE layout
            f"moe_dp{n//4}_pipe2_ep2": ({"data": n // 4, "pipe": 2,
                                         "expert": 2}, {"num_experts": 4}),
        })

    rng = np.random.RandomState(0)
    batch = (
        rng.randn(args.batch, args.img, args.img, 3).astype(np.float32),
        rng.randn(args.batch, args.img, args.img, 3).astype(np.float32),
        rng.randint(1, 7, size=(args.batch,)).astype(np.int32),
    )

    # ONE precision for every row, per backend: bf16 on real TPU (the MXU
    # path users run), f32 on the virtual-CPU mesh. CPU has no native bf16 —
    # XLA emulates it, so amp=True there measures each layout's emulation
    # surface as much as its schedule/collective overhead (measured: it
    # inverts the dp-vs-model-parallel ordering), and the bf16 tp-psum
    # inside the partially-manual pipelined shard_map CHECK-fails in XLA's
    # CPU AllReducePromotion pass outright (pipeline.py docstring).
    amp = bool(args.tpu)
    results = {}
    for name, (mesh_shape, extra) in layouts.items():
        kw = dict(
            exp_name="pbench", amp=amp, batch_size=args.batch,
            image_size=(args.img, args.img), patch_size=args.patch,
            embed_dim=args.embed, depth=args.depth, head=args.heads,
            mesh=mesh_shape,
        )
        kw.update(extra)
        cfg = ExperimentConfig(**kw)
        mesh = make_mesh(mesh_shape)
        model = build_model(cfg, mesh=mesh)
        state = create_train_state(model, jax.random.PRNGKey(0), 1e-3, 1000,
                                   batch)
        specs, apply_fn = layout_for_mesh(
            model, mesh, state.params,
            n_microbatch=2 * int(mesh.shape.get("pipe", 1)))
        state = shard_train_state(state, mesh, specs)
        step = make_train_step(model, apply_fn)
        b = shard_batch(batch, mesh)
        ema = jnp.float32(5.0)

        with mesh:
            t0 = time.time()
            state, _, ema = step(state, b, jax.random.PRNGKey(1), ema)
            float(ema)
            compile_s = time.time() - t0
            t0 = time.time()
            for _ in range(args.steps):
                state, _, ema = step(state, b, jax.random.PRNGKey(1), ema)
            float(ema)
            dt = (time.time() - t0) / args.steps
        results[name] = dt
        print(f"[pbench] {name:12s} compile={compile_s:5.1f}s "
              f"{1000*dt:8.2f} ms/step", file=sys.stderr)

    # explicit per-row baselines; a missing baseline must fail loudly, never
    # silently ratio a row against the wrong model (moe vs dense) or the
    # wrong dispatch implementation (index vs einsum)
    baseline_of = {name: (f"moe_idx_dp{n}" if name.startswith("moe_idx_")
                          else f"moe_dp{n}" if name.startswith("moe_")
                          else f"dp{n}")
                   for name in results}
    # the index-dispatch dp row itself ratios against the einsum dp row:
    # that ratio IS the dispatch-implementation cost at this scale
    baseline_of[f"moe_idx_dp{n}"] = f"moe_dp{n}"
    for name, dt in results.items():
        ref_name = baseline_of[name]
        print(json.dumps({
            "layout": name, "ms_per_step": round(1000 * dt, 2),
            "vs_dp": round(dt / results[ref_name], 3),
            "baseline": ref_name,
            "note": "8 virtual CPU devices share one core: ratio ≈ total-work "
                    "overhead of the layout, not ICI speedup",
        }))


if __name__ == "__main__":
    main()
