#!/usr/bin/env python
"""Idempotence oracle for the round-5 recovery chain (recover_evidence_r05.sh).

Exit 0 when the named stage's evidence already exists — a re-fired chain
(the watcher re-arms after a mid-chain tunnel death) must never re-burn chip
time on work that is already committed. Stages:

* ``northstar`` — bench_r05_northstar.json is a TPU record whose submetrics
  carry the flash 200px number AND the block sweep, OR a recorded
  section-level flash failure (VERDICT r3 item 1: if Mosaic rejects, the
  stack trace IS the round's artifact);
* ``validate``  — tpu_validate_r05.txt reached its terminal "ALL OK" line;
* ``fullbench`` — bench_r05_tpu.json is a TPU record with a headline value
  and a batch-scaling table that reaches b512 (i.e. produced by this
  round's bench, not a stale partial);
* ``train200``  — the published 200px run shows >= 8 epochs;
* ``apps200``   — the 200px zero-shot artifacts (draft2img + interpolation,
  VERDICT r4 item 8) are published under results/<run200>/.
"""

import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from ddim_cold_tpu.utils.record import is_tpu_record, last_json_record  # noqa: E402

RUN200 = "20220822_200pxflower200_diffusion"


def stage_done(stage: str) -> bool:
    res = lambda *p: os.path.join(REPO, "results", *p)  # noqa: E731
    if stage == "northstar":
        rec = last_json_record(res("bench_r05_northstar.json"))
        if not is_tpu_record(rec):
            return False
        sub = rec.get("submetrics", {})
        if "captured_earlier" in sub:
            return False  # a reused record is never stage evidence
        # terminal = the flash number AND the block sweep (a watchdog abort
        # between the two must re-run the stage) — or a SECTION-level
        # northstar_error, which only lands after the section's retry also
        # failed; the per-leg northstar_flash_error key alone is NOT terminal
        return ("northstar_error" in sub
                or ("sampler_throughput_200px_k20_flash" in sub
                    and "northstar_flash_block_sweep" in sub))
    if stage == "validate":
        try:
            with open(res("tpu_validate_r05.txt")) as f:
                return "tpu_validate: ALL OK" in f.read()
        except OSError:
            return False
    if stage == "fullbench":
        rec = last_json_record(res("bench_r05_tpu.json"))
        if not (is_tpu_record(rec) and rec.get("value")):
            return False
        if "captured_earlier" in rec.get("submetrics", {}):
            return False  # a reused record is never stage evidence
        rows = rec.get("submetrics", {}).get("batch_scaling", [])
        return any(row.get("batch") == 512 for row in rows)
    if stage == "train200":
        try:
            with open(res(RUN200, "summary.json")) as f:
                return json.load(f).get("epochs", 0) >= 8
        except Exception:
            return False
    if stage == "apps200":
        return (os.path.exists(res(RUN200, "draft2img.png"))
                and os.path.exists(res(RUN200, "interpolation.png")))
    if stage == "fid200":
        # the 200px FID trend — stage 3's best-effort tail died in the r05
        # wedge (tunnel_diag_r05.txt) and train200's done-key (epochs >= 8)
        # rightly doesn't cover it, so it gets its own stage + watchdog
        try:
            with open(res(RUN200, "fid_trend.json")) as f:
                rec = json.load(f)
            return "aborted" not in rec and bool(rec.get("points"))
        except Exception:
            return False
    if stage == "validate_v2":
        # on-chip numerics re-validated under the bf16-GEMM kernel revision.
        # The morning r05 validate ran the pre-optimization kernel (its file
        # carries no kernel_rev stamp) — but a chain where stage 1 itself
        # runs post-revision writes a stamped tpu_validate_r05.txt, which is
        # byte-identical work this stage must not re-burn chip time on.
        from ddim_cold_tpu.ops.flash_attention import KERNEL_REV

        for fname in ("tpu_validate_r05b.txt", "tpu_validate_r05.txt"):
            try:
                with open(res(fname)) as f:
                    body = f.read()
            except OSError:
                continue
            if ("tpu_validate: ALL OK" in body
                    and f"kernel_rev={KERNEL_REV}" in body):
                return True
        return False
    if stage == "bench_v2":
        # fresh full record measured under the bf16-GEMM kernel revision
        # (ops/flash_attention.KERNEL_REV). The pre-optimization r05 record
        # carries no kernel_rev stamp, so reusing it can never satisfy this.
        from ddim_cold_tpu.ops.flash_attention import KERNEL_REV

        rec = last_json_record(res("bench_r05_tpu.json"))
        if not (is_tpu_record(rec) and rec.get("value")):
            return False
        sub = rec.get("submetrics", {})
        return ("captured_earlier" not in sub
                and sub.get("kernel_rev") == KERNEL_REV
                and any(row.get("batch") == 512
                        for row in sub.get("batch_scaling", [])))
    raise SystemExit(f"unknown stage {stage!r}")


if __name__ == "__main__":
    sys.exit(0 if stage_done(sys.argv[1]) else 1)
