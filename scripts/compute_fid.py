#!/usr/bin/env python
"""Compute FID for a trained run: model samples vs the validation images.

The north-star acceptance metric (BASELINE.json "FID within 0.5 of the CUDA
reference") — the reference itself never measures FID, and its pretrained
checkpoints are absent, so the number established here IS the baseline.

Weight provenance: this bench host has no network and no torchvision, so the
canonical pretrained InceptionV3 cannot be fetched. The extractor therefore
uses **seeded random weights** (`--inception-seed`, default 0): a fixed,
reproducible feature space. Random-feature FID is a valid distance for
comparing models/runs under the SAME extractor (and the converter itself is
validated layer-by-layer against a real torch forward in
tests/test_inception_parity.py, so dropping in the canonical ``.pth`` when
networked is pure data movement: ``--inception-pth``).

Writes ``results/<run>/fid.json`` and prints one JSON line.
"""

from __future__ import annotations

import argparse
import json
import math
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("run_dir", nargs="?", default=os.path.join(
        REPO, "Saved_Models", "20220822vit_tiny_diffusion"))
    ap.add_argument("--val-dir", default=None,
                    help="real-image folder for the FID reference stream [default: the run config's own val dataStorage]")
    ap.add_argument("--n-samples", type=int, default=1024)
    ap.add_argument("--n-real", type=int, default=2048)
    ap.add_argument("--batch", type=int, default=64)
    ap.add_argument("--sampler", choices=("cold", "ddim"), default="cold",
                    help="cold = the trained regime of the 20220822 run; "
                         "ddim uses stride --k")
    ap.add_argument("--k", type=int, default=20)
    ap.add_argument("--inception-seed", type=int, default=0)
    ap.add_argument("--inception-pth", default=None,
                    help="optional torchvision inception_v3 .pth for "
                         "published-comparable numbers")
    ap.add_argument("--cpu", action="store_true")
    args = ap.parse_args(argv)

    from ddim_cold_tpu.utils.platform import enable_compile_cache, honor_env_platform

    honor_env_platform()
    enable_compile_cache()  # repeat CLI runs reuse compiled XLA programs
    import jax

    if args.cpu:
        jax.config.update("jax_platforms", "cpu")
    else:
        from ddim_cold_tpu.utils.platform import require_accelerator_or_exit

        require_accelerator_or_exit()  # wedged tunnel: exit 3, never hang
    from ddim_cold_tpu.data import ColdDownSampleDataset, ShardedLoader
    from ddim_cold_tpu.eval import fid, inception
    from ddim_cold_tpu.ops import sampling
    from ddim_cold_tpu.utils.run_io import load_run

    # -- model from the run's own config + best checkpoint ------------------
    config, model, params = load_run(args.run_dir)
    if args.val_dir is None:
        from ddim_cold_tpu.utils.run_io import default_val_dir

        args.val_dir = default_val_dir(config, REPO)

    # -- extractor ----------------------------------------------------------
    if args.inception_pth:
        inc_model, inc_vars = inception.load_torch_inception(args.inception_pth)
        provenance = f"torchvision pth: {args.inception_pth}"
    else:
        inc_model, inc_vars = inception.init_variables(
            jax.random.PRNGKey(args.inception_seed))
        provenance = (f"seeded random init (PRNGKey({args.inception_seed})) — "
                      "no network for the canonical weights; converter "
                      "torch-parity-tested")

    # -- real stream: clean val images in [0,1] -----------------------------
    ds = ColdDownSampleDataset(args.val_dir, imgSize=tuple(config.image_size),
                               target_mode="direct")
    n_real_seen = 0

    def real_batches():
        nonlocal n_real_seen
        loader = ShardedLoader(ds, args.batch, shuffle=False, drop_last=True)
        for noisy, clean, t in loader:  # target of the direct mode is x0
            if n_real_seen >= args.n_real:
                break
            yield (clean + 1.0) / 2.0
            n_real_seen += clean.shape[0]

    # multi-chip hosts shard the sample batch over a data mesh (the samplers'
    # SPMD path); cold levels follow the run's image size — the trained
    # regime is t ∈ [1, log2(H)], not the 64px default
    mesh = None
    if jax.device_count() > 1 and args.batch % jax.device_count() == 0:
        from ddim_cold_tpu.parallel.mesh import make_mesh

        mesh = make_mesh({"data": jax.device_count()})
    levels = int(math.log2(config.image_size[0]))

    def sampler(rng, nb):
        if args.sampler == "cold":
            return sampling.cold_sample(model, params, rng, n=nb,
                                        levels=levels, mesh=mesh)
        return sampling.ddim_sample(model, params, rng, k=args.k, n=nb,
                                    mesh=mesh)

    value = fid.compute_fid(
        model, params, real_batches(), rng=jax.random.PRNGKey(1),
        n_samples=args.n_samples, sample_batch=args.batch,
        k=args.k, inception_model=inc_model, inception_variables=inc_vars,
        sampler=sampler,
    )

    run = os.path.basename(os.path.normpath(args.run_dir))
    out = {
        "metric": f"fid_{args.sampler}" + (f"_k{args.k}" if args.sampler == "ddim" else ""),
        "value": round(float(value), 4),
        "n_samples": args.n_samples,
        "n_real": n_real_seen,  # actually accumulated, not requested
        "extractor": provenance,
        "run": run,
    }
    out_dir = os.path.join(REPO, "results", run)
    os.makedirs(out_dir, exist_ok=True)
    with open(os.path.join(out_dir, "fid.json"), "w") as f:
        json.dump(out, f, indent=2)
    print(json.dumps(out))


if __name__ == "__main__":
    main()
