#!/usr/bin/env python
"""Render a bench record (results/bench_r*_tpu.json or BENCH_r*.json) into
the PERF.md-style markdown tables — so the write-up after an evidence drop
is a paste, not a transcription (and transcription errors can't creep into
the round's perf claims).

Usage: python scripts/perf_tables.py [record.json ...]
Defaults to the newest results/bench_r*_tpu.json.
"""

from __future__ import annotations

import glob
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from ddim_cold_tpu.utils.record import last_json_record  # noqa: E402

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _fmt_pct(v):
    return "-" if v is None else f"{100 * v:.1f}%"


def render(path: str) -> str:
    rec = last_json_record(path)
    if rec is None:
        return f"<!-- {path}: no parseable record -->"
    sub = rec.get("submetrics", {})
    lines = [f"### {os.path.relpath(path, REPO)}", ""]
    revs = " · ".join(f"{lbl} `{sub[key]}`" for lbl, key in
                      (("kernel", "kernel_rev"), ("quant", "quant_rev"))
                      if sub.get(key))
    lines += [f"chip: **{rec.get('chip')}** · headline "
              f"**{rec.get('value')} img/s** @ b32 "
              f"({rec.get('vs_baseline')}× the 702 img/s 3090 baseline) · "
              f"{rec.get('ms_per_step')} ms/step · MFU {rec.get('mfu')}"
              + (f" · {revs}" if revs else ""), ""]
    if rec.get("captured_earlier"):
        ce = sub.get("captured_earlier", {})
        lines += [f"> REUSED record ({ce.get('file')}"
                  + (f", stale round {ce['stale_round']}" if "stale_round" in ce
                     else "") + ") — not a fresh measurement", ""]
    rm = rec.get("run_meta")
    if rm:
        lines += [f"provenance: sha `{rm.get('git_sha')}` · jax "
                  f"{rm.get('jax')} / jaxlib {rm.get('jaxlib')} · ts "
                  f"{rm.get('timestamp')}"
                  + (" · replayed" if rm.get("replayed") else ""), ""]

    rows = sub.get("batch_scaling")
    if rows:
        lines += ["| batch | ms/step | img/s | MFU |", "|---|---|---|---|"]
        for r in rows:
            mfu = r.get("mfu")
            lines.append(f"| {r['batch']} | {r['ms_per_step']} | "
                         f"{r['img_per_sec']} | "
                         f"{'' if mfu is None else f'{100 * mfu:.1f}%'} |")
        lines.append("")

    for name in ("scan_blocks", "remat"):
        r = sub.get(name)
        if r:
            plain = r.get("plain_ms_per_step",
                          r.get("unrolled_ms_per_step"))  # pre-r04 key name
            lines.append(
                f"* **{name}** b{r['batch']}: {r['ms_per_step']} ms/step "
                f"(compile {r['compile_s']}s) vs plain {plain} ms/step"
                + (f", MFU {100 * r['mfu']:.1f}%" if r.get("mfu") else ""))

    ns = {s: sub.get("sampler_throughput_200px_k20" + s)
          for s in ("", "_dense", "_flash", "_xla", "_flash_n64",
                    "_cached", "_cached_delta", "_cached_adaptive",
                    "_cached_token", "_flash_w8a16")}
    if any(ns.values()):
        lines.append("")
        lines.append("**200px k=20 north-star (img/s/chip):** "
                     + " · ".join(f"{(s or '_best')[1:]}={v['value']}"
                                  for s, v in ns.items() if v))
    ad = ns.get("_cached_adaptive")
    if ad:
        lines.append(
            f"adaptive cache leg: τ={ad.get('cache_threshold')} @ "
            f"interval={ad.get('cache_interval')} · "
            f"{ad.get('speedup_vs_exact_flash')}× vs exact flash"
            + (f" · {ad['speedup_vs_fixed_delta']}× vs fixed delta i2"
               if ad.get("speedup_vs_fixed_delta") is not None else "")
            + f" · pixel drift {ad.get('max_abs_pixel_delta')}")
    tk = ns.get("_cached_token")
    if tk:
        lines.append(
            f"token cache leg: top-k={tk.get('cache_tokens')} @ "
            f"interval={tk.get('cache_interval')} · "
            f"{tk.get('speedup_vs_exact_flash')}× vs exact flash · "
            f"pixel drift {tk.get('max_abs_pixel_delta')}")
    w8 = ns.get("_flash_w8a16")
    if w8:
        lines.append(
            f"w8a16 flash leg: {w8.get('speedup_vs_bf16_flash')}× vs bf16 "
            f"flash · pixel drift {w8.get('max_abs_pixel_delta')} · param "
            f"bytes {w8.get('param_bytes')} → {w8.get('param_bytes_quant')}"
            + (f" · trunk GEMM fraction {w8['trunk_gemm_fraction']}"
               if w8.get("trunk_gemm_fraction") is not None else ""))
    sweep = sub.get("northstar_flash_block_sweep")
    if sweep:
        lines.append("flash block sweep: "
                     + " · ".join(f"{k}→{v}" for k, v in sweep.items()))
    for key in ("northstar_error", "northstar_flash_error",
                "northstar_dense_error", "northstar_xla_error",
                "northstar_n64_error"):
        if key in sub:
            lines.append(f"`{key}`: {sub[key]}")

    ks = sub.get("ksweep_64px_img_per_sec")
    if ks:
        lines.append("")
        lines.append("**k-sweep 64px (img/s):** "
                     + " · ".join(f"k={k}: {v}" for k, v in ks.items()))
    ksf = sub.get("ksweep_64px_fewstep_img_per_sec")
    if ksf:
        lines.append("few-step 64px (img/s, steps = total model "
                     "applications): "
                     + " · ".join(f"s={k}: {v}" for k, v in ksf.items()))

    q64 = sub.get("sampler_64px_w8a16")
    if q64:
        lines.append("")
        lines.append(
            f"**w8a16 64px (k={q64.get('k')}, n={q64.get('n')}):** "
            + " · ".join(
                f"{m}={leg['img_per_sec']} img/s "
                f"({leg['speedup_vs_float']}× float, "
                f"drift {leg['max_abs_pixel_delta']})"
                for m, leg in q64.get("modes", {}).items()
                if "img_per_sec" in leg)
            + f" · float={q64.get('float_img_per_sec')} img/s · param bytes "
              f"{q64.get('param_bytes')} → {q64.get('param_bytes_quant')}")

    srv = sub.get("serving")
    if srv:
        lines.append("")
        lines.append(
            f"**serving:** {srv.get('img_per_sec')} img/s "
            f"({srv.get('vs_oneshot')}× one-shot) · p50 "
            f"{srv.get('p50_latency_s')}s / p95 {srv.get('p95_latency_s')}s"
            + (f" / p99 {srv['p99_latency_s']}s"
               if srv.get("p99_latency_s") is not None else "")
            + (f" over {srv['requests']} requests"
               if srv.get("requests") else "")
            + f" · compiles after warmup {srv.get('compiles_after_warmup')}")
        sq = srv.get("quant")
        if sq:
            lines.append(
                f"serving w8a16: {sq.get('img_per_sec')} img/s "
                f"({sq.get('vs_float_serving')}× float serving) · param bytes "
                f"{sq.get('param_bytes')} → {sq.get('param_bytes_quant')} · "
                f"compiles after warmup {sq.get('compiles_after_warmup')}")

    fs = sub.get("fewstep")
    if fs:
        per = fs.get("per_k", {})
        base = fs.get("baseline", {})
        lines.append("")
        lines.append(
            "**few-step serving (img/s · n=1 latency):** "
            + " · ".join(f"k={k}: {leg.get('img_per_sec')} / "
                         f"{leg.get('latency_1_s')}s"
                         for k, leg in per.items())
            + f" · baseline k={base.get('k')} latency "
              f"{base.get('latency_1_s')}s (k=1 ratio "
              f"{fs.get('k1_latency_vs_baseline')}) · warmup "
              f"{fs.get('warmup_new_compiles')} compiles + "
              f"{fs.get('warmup_deduped')} deduped · compiles after warmup "
              f"{fs.get('compiles_after_warmup')}")

    ca = sub.get("cache_adaptive")
    if ca:
        lines.append("")
        lines.append(
            "**adaptive cache (one-shot img/s):** "
            + " · ".join(f"{name}={leg['img_per_sec']} "
                         f"({leg['vs_fixed_i2']}× fixed-i2)"
                         for name, leg in ca.items()
                         if isinstance(leg, dict) and "img_per_sec" in leg)
            + f" · τ→0 bitwise {ca.get('threshold0_bitwise_exact')}")
        sv = ca.get("served", {})
        if sv:
            lines.append(
                "adaptive cache served: "
                + " · ".join(f"{name}={leg['img_per_sec']} img/s"
                             for name, leg in sv.items()
                             if isinstance(leg, dict))
                + f" · warmup compiles {sv.get('warmup_new_compiles')} · "
                  "compiles after warmup "
                + "/".join(str(leg.get("compiles_after_warmup"))
                           for leg in sv.values() if isinstance(leg, dict)))

    fl = sub.get("faults")
    if fl:
        lines.append("")
        lines.append(
            f"**robustness:** disarmed {fl.get('clean_img_per_sec')} img/s"
            + (f" ({fl['disarmed_vs_serving']}× plain serving)"
               if fl.get("disarmed_vs_serving") is not None else "")
            + f" · chaos {fl.get('chaos_img_per_sec')} img/s "
              f"({fl.get('degraded_ratio')}× disarmed) under "
              f"{fl.get('injected')} injections {fl.get('by_site')} · "
              f"retries {fl.get('retries')} · quarantined "
              f"{fl.get('quarantined')} · compiles after warmup "
              f"{fl.get('compiles_after_warmup')}")

    ft = sub.get("fleet")
    if ft:
        lines.append("")
        lines.append(
            f"**fleet:** {ft.get('replicas')} replicas · clean "
            f"{ft.get('clean_img_per_sec')} img/s · chaos "
            f"{ft.get('chaos_img_per_sec')} img/s "
            f"({ft.get('degraded_ratio')}× clean) under "
            f"{ft.get('injected')} injections {ft.get('by_site')} · "
            f"hedges {ft.get('hedges')} · failovers {ft.get('failovers')} · "
            f"replicas retired {ft.get('replicas_retired')}/spawned "
            f"{ft.get('replicas_spawned')} · compiles after warmup "
            f"{ft.get('compiles_after_warmup')}")

    fp = sub.get("fleet_proc")
    if fp:
        asc = fp.get("autoscale") or {}
        lines.append("")
        lines.append(
            f"**fleet (multi-process):** {fp.get('replicas')} subprocess "
            f"replicas · {fp.get('img_per_sec')} img/s through a SIGKILL "
            f"mid-drain · survivors {fp.get('survivors')} "
            f"(bitwise={fp.get('bitwise_vs_direct')}) · kill→recovered "
            f"{fp.get('kill_to_recovered_s')}s · spawn+warm cold "
            f"{fp.get('spawn_warm_cold_s')}s / warm {fp.get('spawn_warm_s')}s "
            f"· failovers {fp.get('failovers')} · retired "
            f"{fp.get('replicas_retired')}/spawned "
            f"{fp.get('replicas_spawned')} · autoscale "
            f"{asc.get('scale_ups')}↑/{asc.get('scale_downs')}↓ → target "
            f"{asc.get('final_target')} · compiles after warmup "
            f"{fp.get('compiles_after_warmup')}")

    ed = sub.get("edit")
    if ed:
        per = ed.get("per_task", {})
        pv = ed.get("preview", {})
        lines.append("")
        lines.append(
            "**editing workloads (img/s):** "
            + " · ".join(f"{task}={leg.get('img_per_sec')}"
                         for task, leg in per.items())
            + f" · k={ed.get('k')} · compiles after warmup "
              f"{ed.get('compiles_after_warmup')}")
        if pv:
            lines.append(
                f"streamed previews (every={pv.get('every')}): first frame "
                f"{pv.get('latency_to_first_frame_s')}s of "
                f"{pv.get('total_s')}s drain "
                f"({pv.get('first_frame_fraction')}× wall) · "
                f"{pv.get('frames')} frames")

    ob = sub.get("obs")
    if ob:
        tel = ob.get("telemetry", {})
        lines.append("")
        lines.append(
            f"**observability:** tracing overhead "
            f"{ob.get('tracing_overhead_pct')}% "
            f"({ob.get('img_per_sec_tracing_off')} img/s off → "
            f"{ob.get('img_per_sec_tracing_on')} on) · traced bitwise "
            f"{ob.get('traced_bitwise_equal')} · {ob.get('spans_recorded')} "
            f"spans / {ob.get('chrome_events')} chrome events · step "
            f"telemetry {tel.get('refreshes')}r/{tel.get('reuses')}c "
            f"(ratio {tel.get('refresh_ratio')}) · compiles after warmup "
            f"{ob.get('compiles_after_warmup')}")

    at = sub.get("attrib")
    if at:
        top = at.get("top_scopes", [])
        lines.append("")
        lines.append(
            f"**attribution:** {_fmt_pct(at.get('coverage'))} of device-busy "
            f"attributed · busy {at.get('device_busy_s')}s / idle "
            f"{at.get('idle_s')}s ({_fmt_pct(at.get('busy_fraction'))} busy) · "
            f"{at.get('device_lanes')} lane(s) · ridge "
            f"{at.get('ridge_flops_per_byte')} FLOP/byte · "
            f"{len(at.get('fusion_candidates', []))} fusion candidates · "
            f"compiles after warmup {at.get('compiles_after_warmup')} · "
            f"source {at.get('trace_source')}")
        if top:
            lines += ["", "| scope | self ms | share | TFLOP/s | MFU | bound |",
                      "|---|---|---|---|---|---|"]
            for s in top:
                lines.append(
                    f"| {s.get('scope')} | {1000 * s.get('self_s', 0.0):.3f} | "
                    f"{_fmt_pct(s.get('share_of_busy'))} | "
                    f"{s.get('achieved_tflops')} | {s.get('mfu')} | "
                    f"{s.get('roofline')} |")
        tr = at.get("trend")
        if tr:
            st = tr.get("statuses", {})
            lines.append(
                f"trend gate: exit {tr.get('exit_code')} over "
                f"{tr.get('bench_points')} bench + "
                f"{tr.get('multichip_points')} multichip points · "
                + (" · ".join(f"{k}={v}" for k, v in sorted(st.items()))
                   or "no checks"))

    fu = sub.get("fusion")
    if fu:
        uf, fd = fu.get("unfused", {}), fu.get("fused", {})
        lines.append("")
        lines.append(
            f"**fused trunk (k={fu.get('k')}, buckets={fu.get('buckets')}):** "
            f"{uf.get('per_step_ms')} ms/step unfused → "
            f"{fd.get('per_step_ms')} ms fused ({fu.get('speedup')}×) · "
            f"{fd.get('img_per_sec')} img/s · MFU {uf.get('mfu')} → "
            f"{fd.get('mfu')} · oracle {fu.get('oracle')} (max |Δ| "
            f"{fu.get('max_abs_pixel_delta')}) · compiles after warmup "
            f"{fu.get('compiles_after_warmup')}")

    pl = sub.get("parallel")
    if pl and not pl.get("skipped"):
        degs = pl.get("degrees", {})
        lines.append("")
        lines.append(
            "**sequence-parallel serving (single request, "
            f"bucket={pl.get('bucket')}, {pl.get('devices')} devices):** "
            + " · ".join(
                f"sp{d}={leg.get('latency_s')}s"
                + (f" (p99 {leg['p99_latency_s']}s)"
                   if leg.get("p99_latency_s") is not None else "")
                + (f" ({leg.get('speedup_vs_sp1')}× sp1, "
                   f"{leg.get('sp_mode')})" if d != "1" else "")
                for d, leg in degs.items())
            + f" · sp1 bitwise {pl.get('sp1_bitwise_vs_direct')} · "
              f"compiles after warmup {pl.get('compiles_after_warmup')}")
        ns_sp = pl.get("northstar_200px_sp")
        if ns_sp:
            lines.append(
                f"200px k=20 all-local sp{ns_sp.get('sp_degree')}: "
                f"{ns_sp.get('latency_s')}s / {ns_sp.get('img_per_sec')} "
                f"img/s (bucket {ns_sp.get('bucket')}) · compiles after "
                f"warmup {ns_sp.get('compiles_after_warmup')}")

    for key, label in (("cached_quality_64px", "cached quality 64px"),
                       ("quant_quality_64px", "w8a16 quality 64px"),
                       ("quant_cached_quality_64px",
                        "w8a16 × cache quality 64px")):
        g = sub.get(key)
        if g:
            dist = g.get("fid_exact_vs_cached", g.get("fid_exact_vs_quant"))
            lines.append("")
            lines.append(
                f"**{label}:** paired Fréchet {dist} · pixel drift "
                f"{g.get('max_abs_pixel_delta')} (n={g.get('n_samples')}, "
                f"k={g.get('k')}, interval={g.get('cache_interval')})")
    e2e = [(lbl, sub.get(f"e2e_train_throughput_{lbl}"))
           for lbl in ("cold", "warm")]
    if any(v for _, v in e2e):
        bw = sub.get("h2d_bandwidth_mib_s")
        lines.append("")
        lines.append("**e2e disk→step (img/s):** " + " · ".join(
            f"{lbl}={v['value']} ({v['vs_baseline']}×"
            + (f", spd={v['steps_per_dispatch']}" if "steps_per_dispatch" in v
               else "") + ")"
            for lbl, v in e2e if v)
            + (f" · H2D link ≈ {bw} MiB/s" if bw else ""))
    return "\n".join(lines)


def main(argv=None):
    paths = (argv or sys.argv)[1:]
    if not paths:
        paths = sorted(glob.glob(os.path.join(REPO, "results", "bench_r*_tpu.json")))[-1:]
        if not paths:
            print("no bench records found", file=sys.stderr)
            return 1
    for p in paths:
        print(render(p))
        print()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
