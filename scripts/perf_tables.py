#!/usr/bin/env python
"""Render a bench record (results/bench_r*_tpu.json or BENCH_r*.json) into
the PERF.md-style markdown tables — so the write-up after an evidence drop
is a paste, not a transcription (and transcription errors can't creep into
the round's perf claims).

Usage: python scripts/perf_tables.py [record.json ...]
Defaults to the newest results/bench_r*_tpu.json.
"""

from __future__ import annotations

import glob
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from ddim_cold_tpu.utils.record import last_json_record  # noqa: E402

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def render(path: str) -> str:
    rec = last_json_record(path)
    if rec is None:
        return f"<!-- {path}: no parseable record -->"
    sub = rec.get("submetrics", {})
    lines = [f"### {os.path.relpath(path, REPO)}", ""]
    lines += [f"chip: **{rec.get('chip')}** · headline "
              f"**{rec.get('value')} img/s** @ b32 "
              f"({rec.get('vs_baseline')}× the 702 img/s 3090 baseline) · "
              f"{rec.get('ms_per_step')} ms/step · MFU {rec.get('mfu')}", ""]
    if rec.get("captured_earlier"):
        ce = sub.get("captured_earlier", {})
        lines += [f"> REUSED record ({ce.get('file')}"
                  + (f", stale round {ce['stale_round']}" if "stale_round" in ce
                     else "") + ") — not a fresh measurement", ""]

    rows = sub.get("batch_scaling")
    if rows:
        lines += ["| batch | ms/step | img/s | MFU |", "|---|---|---|---|"]
        for r in rows:
            mfu = r.get("mfu")
            lines.append(f"| {r['batch']} | {r['ms_per_step']} | "
                         f"{r['img_per_sec']} | "
                         f"{'' if mfu is None else f'{100 * mfu:.1f}%'} |")
        lines.append("")

    for name in ("scan_blocks", "remat"):
        r = sub.get(name)
        if r:
            plain = r.get("plain_ms_per_step",
                          r.get("unrolled_ms_per_step"))  # pre-r04 key name
            lines.append(
                f"* **{name}** b{r['batch']}: {r['ms_per_step']} ms/step "
                f"(compile {r['compile_s']}s) vs plain {plain} ms/step"
                + (f", MFU {100 * r['mfu']:.1f}%" if r.get("mfu") else ""))

    ns = {s: sub.get("sampler_throughput_200px_k20" + s)
          for s in ("", "_dense", "_flash", "_xla", "_flash_n64")}
    if any(ns.values()):
        lines.append("")
        lines.append("**200px k=20 north-star (img/s/chip):** "
                     + " · ".join(f"{(s or '_best')[1:]}={v['value']}"
                                  for s, v in ns.items() if v))
    sweep = sub.get("northstar_flash_block_sweep")
    if sweep:
        lines.append("flash block sweep: "
                     + " · ".join(f"{k}→{v}" for k, v in sweep.items()))
    for key in ("northstar_error", "northstar_flash_error",
                "northstar_dense_error", "northstar_xla_error",
                "northstar_n64_error"):
        if key in sub:
            lines.append(f"`{key}`: {sub[key]}")

    ks = sub.get("ksweep_64px_img_per_sec")
    if ks:
        lines.append("")
        lines.append("**k-sweep 64px (img/s):** "
                     + " · ".join(f"k={k}: {v}" for k, v in ks.items()))
    e2e = [(lbl, sub.get(f"e2e_train_throughput_{lbl}"))
           for lbl in ("cold", "warm")]
    if any(v for _, v in e2e):
        bw = sub.get("h2d_bandwidth_mib_s")
        lines.append("")
        lines.append("**e2e disk→step (img/s):** " + " · ".join(
            f"{lbl}={v['value']} ({v['vs_baseline']}×"
            + (f", spd={v['steps_per_dispatch']}" if "steps_per_dispatch" in v
               else "") + ")"
            for lbl, v in e2e if v)
            + (f" · H2D link ≈ {bw} MiB/s" if bw else ""))
    return "\n".join(lines)


def main(argv=None):
    paths = (argv or sys.argv)[1:]
    if not paths:
        paths = sorted(glob.glob(os.path.join(REPO, "results", "bench_r*_tpu.json")))[-1:]
        if not paths:
            print("no bench records found", file=sys.stderr)
            return 1
    for p in paths:
        print(render(p))
        print()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
