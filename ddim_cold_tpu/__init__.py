"""ddim_cold_tpu — a TPU-native (JAX/XLA/pjit/Pallas) diffusion framework.

Re-implements, TPU-first, the full capability surface of the DDIM-COLD
reference codebase (DDIM image generation with a ViT x0-predicting backbone,
Cold Diffusion via nearest-neighbor downsampling, distributed data-parallel
training, guided zero-shot sampling applications), plus the scale-out layers
(mesh/tensor/sequence parallelism, Pallas kernels) the reference reaches only
through the CUDA runtime.

Layering (bottom-up), mirroring SURVEY.md §1's target design:

  parallel/  mesh + sharding + collectives (replaces NCCL/DDP)
  data/      host-side image pipeline with per-host sharding
             (replaces DataLoader + DistributedSampler)
  models/    Flax DiffusionViT (replaces torch nn.Module model)
  ops/       schedules, samplers (lax.scan), degradation ops, attention
             (replaces Python sampler loops / cuDNN attention)
  train/     pjit SPMD train step + loop (replaces DDP/AMP/GradScaler)
  utils/     logging, checkpointing, image IO
  cli/       entry points preserving the reference's CLI surface
"""

__version__ = "0.1.0"
