"""The guided-editing tasks: each one a pure (init-state, schedule-suffix,
per-step constraint) triple over the existing samplers.

Every task in :data:`EDIT_TASKS` is two things at once:

* a **direct function** here (``inpaint``, ``super_resolve``,
  ``draft_to_drawing``, ``interpolate``) — the offline, single-call form,
  composing ops/sampling.py exactly the way the reference apps do
  (ViT_draft2drawing.py); and
* a **served product**: a :class:`~ddim_cold_tpu.serve.batching.SamplerConfig`
  with ``task=<name>`` submitted through ``Engine``/``Router``, which
  coalesces into the same buckets, warmup, step-cache, quant and fleet
  machinery as plain sampling — bitwise-equal to the direct call for the
  same rng (the engine contract, inherited because every init builder here
  is per-row and drawn at the request's own n).

The init builders (:func:`draft_init`, :func:`interp_init`,
:func:`superres_init`) are the SINGLE definition both paths use — the direct
functions and serve/engine.py's ``_request_init`` call the same code, so the
bitwise contract is structural, not coincidental.

| task       | sampler | init state                      | per-step constraint |
|------------|---------|---------------------------------|---------------------|
| inpaint    | ddim    | fresh noise from the request key| x̂0 mask re-projection
| superres   | cold    | nearest-upsampled low-res input | none (cold scan)    |
| draft      | ddim    | ``forward_noise(draft, t_start)``| none (suffix scan)  |
| interp     | ddim    | slerp of two encoded endpoints  | none (suffix scan)  |

This module imports ops/data layers only — never ``serve`` at module level
(serve/engine.py imports it; the one serve-touching helper,
:func:`default_edit_configs`, imports lazily).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ddim_cold_tpu.ops import degrade, sampling

#: the served editing tasks; "sample" (plain generation) completes the
#: SamplerConfig ``task`` domain (serve/batching.py keeps its own literal
#: copy — host-only module — pinned equal by tests/test_workloads.py)
EDIT_TASKS = ("inpaint", "superres", "draft", "interp")
TASKS = ("sample",) + EDIT_TASKS


# ---------------------------------------------------------------- inputs

def normalize_mask(mask, n: int, img_size) -> np.ndarray:
    """User mask → the engine/scan contract: float32 (n, H, W, 1) of {0, 1}
    (1 = KNOWN pixel, preserved exactly; 0 = to be synthesized).

    Accepts (H, W), (H, W, 1), (n, H, W) or (n, H, W, 1); a single mask
    broadcasts over the batch. Values must be exactly binary — the
    idempotence contract ("known pixels bit-preserved") is only meaningful
    for a hard projection, so soft masks are rejected rather than silently
    thresholded. Host-side numpy on purpose: the engine slices request rows
    out of this array on its assembly thread.
    """
    H, W = img_size
    m = np.asarray(mask, np.float32)
    if m.ndim == 2:
        m = m[None, :, :, None]
    elif m.ndim == 3:
        m = m[None] if m.shape == (H, W, 1) else m[..., None]
    if m.ndim != 4 or m.shape[1:] != (H, W, 1):
        raise ValueError(
            f"mask must be (H, W), (H, W, 1), (n, H, W) or (n, H, W, 1) "
            f"for image size {(H, W)}, got shape {np.shape(mask)}")
    if m.shape[0] == 1 and n > 1:
        m = np.broadcast_to(m, (n, H, W, 1))
    if m.shape[0] != n:
        raise ValueError(f"mask batch {m.shape[0]} != request n {n}")
    if not np.isin(m, (0.0, 1.0)).all():
        raise ValueError(
            "mask must be binary {0, 1} — known pixels are re-projected "
            "EXACTLY, which a soft mask cannot mean")
    return np.ascontiguousarray(m)


# ----------------------------------------------------------- init builders

def draft_init(rng: jax.Array, draft: jax.Array, t_start: int,
               total_steps: int = 2000) -> jax.Array:
    """Draft→drawing init: the sketch forward-noised to ``t_start``
    (reference ViT_draft2drawing.py:395) — then the task is just
    ``sample_from``. Per-row (the noise draw shape is the draft's own
    (n, H, W, C)), so the engine draws it at the request's n and slices."""
    return sampling.forward_noise(rng, draft, t_start, total_steps)


#: interp init: the slerp-mixed encodings of the endpoint pair — the exact
#: states ``slerp_interpolate`` decodes (one definition, ops/sampling.py)
interp_init = sampling.interp_states


def superres_init(low_res, size: int) -> np.ndarray:
    """Super-resolution init: the low-res input nearest-upsampled to the
    model's size — i.e. the cold-degraded full-size state D(x, level) for
    the unknown original (ops/degrade.upsample_nearest). Returned as host
    numpy: it is a guided-start payload for ``Engine.submit(x_init=...)``."""
    return np.asarray(degrade.upsample_nearest(low_res, size))


def superres_project(outputs, low_res) -> np.ndarray:
    """Data-consistency projection for super-resolution outputs: overwrite
    the nearest-downsample ANCHOR pixels of ``outputs`` (in [0, 1], the
    engine's delivery space) with the low-res input (in [−1, 1]), so that
    ``nearest-downsample(result) == (low_res + 1) / 2`` holds bit-exactly.

    The cold scan's naive Algorithm-1 update replaces x wholesale with the
    clamped prediction each step, so the anchors in the raw output are
    MODEL OUTPUTS that merely track the input — this projection is what
    turns "looks consistent" into a checkable contract
    (eval/fid.superres_consistency_guard), the same guarantee inpainting
    gets from its in-scan mask re-projection. It runs host-side as a
    finishing step because the anchor set is static (ops/degrade's
    floor-index convention) and per-row independent, so it composes with
    any serving batch shape without touching the shared cold programs."""
    out = np.array(outputs, np.float32, copy=True)
    low = np.asarray(low_res, np.float32)
    if out.ndim == 3:
        out = out[None]
    if low.ndim == 3:
        low = low[None]
    iy = degrade.nearest_indices(low.shape[1], out.shape[1])
    ix = degrade.nearest_indices(low.shape[2], out.shape[2])
    out[:, iy[:, None], ix[None, :], :] = (low + 1.0) / 2.0
    return out


# --------------------------------------------------------- direct functions

def inpaint(model, params, rng: jax.Array, known, mask, *, k: int = 10,
            t_start: Optional[int] = None, eta: float = 0.0,
            cache_interval: int = 1, cache_mode: str = "delta",
            cache_threshold: Optional[float] = None,
            cache_tokens: Optional[int] = None,
            return_sequence: bool = False) -> jax.Array:
    """Training-free inpainting: DDIM from fresh noise with per-step mask
    re-projection of the known pixels (ops/sampling._ddim_inpaint_impl).
    ``known`` is the reference image in [−1, 1]; ``mask`` selects the pixels
    to preserve (see :func:`normalize_mask`). Known pixels of the result are
    ``(known + 1) / 2`` bit-exactly — the projection runs after the cache
    branch in the cached variant too, so this holds at every
    ``cache_interval``/``cache_mode``. Served form:
    ``SamplerConfig(task="inpaint")`` + ``submit(seed=, x_init=known,
    mask=)``. ``cache_interval`` > 1 routes through the step-cached inpaint
    scan (all four cache modes; see ``ddim_sample`` for the
    adaptive/token statics)."""
    known = jnp.asarray(known, jnp.float32)
    if known.ndim == 3:
        known = known[None]
    n = known.shape[0]
    m = jnp.asarray(normalize_mask(mask, n, model.img_size))
    H, W = model.img_size
    x_init = jax.random.normal(rng, (n, H, W, model.in_chans), jnp.float32)
    # same fold as ddim_sample: the (eta>0-only) per-step noise key must not
    # correlate with the init draw; eta=0 (the served path) never reads it
    noise_rng = jax.random.fold_in(rng, 0xD1F)
    if cache_interval > 1:
        from ddim_cold_tpu.ops import step_cache

        cache0 = step_cache.init_cache(
            n, model.num_patches + 1, model.embed_dim, model.dtype,
            mode=cache_mode, img_shape=(H, W, model.in_chans))
        fn = (sampling._ddim_scan_inpaint_cached_seq if return_sequence
              else sampling._ddim_scan_inpaint_cached)
        out, _ = fn(model, params, x_init, known, m, noise_rng, cache0, k=k,
                    t_start=t_start, eta=eta, cache_interval=cache_interval,
                    cache_mode=cache_mode, cache_threshold=cache_threshold,
                    cache_tokens=cache_tokens, sequence=return_sequence)
        return out
    fn = (sampling._ddim_scan_inpaint_seq if return_sequence
          else sampling._ddim_scan_inpaint)
    return fn(model, params, x_init, known, m, noise_rng, k=k,
              t_start=t_start, eta=eta, sequence=return_sequence)


def super_resolve(model, params, low_res, *, level: int,
                  cache_interval: int = 1, cache_mode: str = "delta",
                  cache_threshold: Optional[float] = None,
                  cache_tokens: Optional[int] = None,
                  return_sequence: bool = False, mesh=None) -> jax.Array:
    """Training-free super-resolution: treat the low-res input as the cold
    degradation at ``level`` (it IS one — nearest-downsampling is the cold
    operator), upsample it into the sampler's state space, and run the cold
    scan from that level down. With a 1×1 constant-color input and the full
    level count this is exactly ``cold_sample`` (equivalence pinned in
    tests/test_workloads.py). Served form: ``SamplerConfig(sampler="cold",
    task="superres", levels=level)`` + ``submit(x_init=superres_init(...))``.
    """
    x_init = degrade.upsample_nearest(low_res, model.img_size[0])
    return sampling.cold_sample(model, params, x_init=x_init,
                                levels=int(level),
                                return_sequence=return_sequence, mesh=mesh,
                                cache_interval=cache_interval,
                                cache_mode=cache_mode,
                                cache_threshold=cache_threshold,
                                cache_tokens=cache_tokens)


def draft_to_drawing(model, params, rng: jax.Array, draft, *,
                     t_start: int = 1800, k: int = 10,
                     cache_interval: int = 1, cache_mode: str = "delta",
                     cache_threshold: Optional[float] = None,
                     cache_tokens: Optional[int] = None,
                     return_sequence: bool = False, mesh=None) -> jax.Array:
    """The reference's headline app (ViT_draft2drawing.py:394-408):
    forward-noise a rough draft to an intermediate ``t_start``, then DDIM
    back down — the sampler keeps the draft's layout and invents the detail.
    Served form: ``SamplerConfig(task="draft", t_start=)`` +
    ``submit(seed=, x_init=draft)``."""
    draft = jnp.asarray(draft, jnp.float32)
    if draft.ndim == 3:
        draft = draft[None]
    encoded = draft_init(rng, draft, t_start, model.total_steps)
    return sampling.sample_from(model, params, encoded, t_start, k=k,
                                return_sequence=return_sequence, mesh=mesh,
                                cache_interval=cache_interval,
                                cache_mode=cache_mode,
                                cache_threshold=cache_threshold,
                                cache_tokens=cache_tokens)


#: slerp interpolation promoted to a first-class task: the direct form is
#: ops/sampling.slerp_interpolate itself; the served form is
#: ``SamplerConfig(task="interp", t_start=)`` + ``submit(seed=,
#: x_init=np.stack([img_a, img_b]), n=n_interp)``.
interpolate = sampling.slerp_interpolate


# ------------------------------------------------------------ serve configs

def default_edit_configs(*, k: int = 10, t_start: int = 1800,
                         sr_level: int = 4, preview_every: int = 0) -> list:
    """One ready-to-warm SamplerConfig per editing task — the set a serving
    deployment passes to ``serve.warmup`` / ``Router(configs=...)`` to get
    every workload compile-free. Lazy serve import: this module stays below
    the serve layer."""
    from ddim_cold_tpu.serve.batching import SamplerConfig

    return [
        SamplerConfig(task="inpaint", k=k, preview_every=preview_every),
        SamplerConfig(task="superres", sampler="cold", levels=sr_level,
                      preview_every=preview_every),
        SamplerConfig(task="draft", k=k, t_start=t_start,
                      preview_every=preview_every),
        SamplerConfig(task="interp", k=k, t_start=t_start,
                      preview_every=preview_every),
    ]
