"""Streaming-preview frame selection — shared by the engine and its tests.

A preview-enabled config (``SamplerConfig(preview_every=m)``) makes the
engine dispatch the SEQUENCE variant of the config's scan, which returns the
(steps+1, N, H, W, C) trajectory: frame 0 is the init state, frame j the x̂0
prediction after step j, frame ``steps`` the final result. The engine
delivers every ``m``-th intermediate prediction through
``Ticket.previews()`` before the final rows land — this module pins WHICH
frames those are, so the engine, the bench's latency-to-first-frame metric,
and the bitwise-prefix test can never disagree about the schedule.

Host-only on purpose (plain ints — no jax): the selection runs on the
delivery path of every preview batch.
"""

from __future__ import annotations


def preview_indices(n_steps: int, every: int) -> list[int]:
    """Trajectory-frame indices streamed as previews: every ``every``-th x̂0
    prediction, EXCLUDING frame 0 (the init state is the caller's input, not
    a prediction) and frame ``n_steps`` (the final result, delivered through
    ``Ticket.result()``). ``every <= 0`` or ``every >= n_steps`` yields no
    previews (a 1-step scan has no intermediate frame to stream)."""
    if every <= 0:
        return []
    return list(range(every, n_steps, every))
