"""Guided-editing workloads: inpainting, super-resolution, draft→drawing and
slerp interpolation as first-class products over the serving stack.

Each task is a pure (init-state, schedule-suffix, per-step constraint)
triple over the samplers in ops/sampling.py — usable directly (one function
call) or served (a ``SamplerConfig(task=...)`` through ``Engine``/``Router``
with the bitwise-vs-direct and zero-compiles-after-warmup contracts intact).
``preview.py`` pins the streaming-preview frame schedule
(``SamplerConfig(preview_every=m)`` + ``Ticket.previews()``).

Quickstart (direct)::

    from ddim_cold_tpu import workloads
    out  = workloads.inpaint(model, params, rng, known, mask, k=10)
    hi   = workloads.super_resolve(model, params, low_res, level=4)
    img  = workloads.draft_to_drawing(model, params, rng, draft, t_start=1800)
    path = workloads.interpolate(model, params, rng, img_a, img_b, n_interp=8)

Quickstart (served, with streaming previews)::

    from ddim_cold_tpu import serve, workloads
    eng = serve.Engine(model, params, buckets=(8, 32))
    serve.warmup(eng, workloads.default_edit_configs(preview_every=2))
    cfg = serve.SamplerConfig(task="draft", t_start=1800, preview_every=2)
    t = eng.submit(seed=0, x_init=draft, config=cfg)
    eng.run()
    for step, frames in t.previews():   # intermediate x̂0 frames, in order
        show(step, frames)
    final = t.result()

This package never imports ``serve`` at module level — serve/engine.py
imports it for the shared init builders.
"""

from ddim_cold_tpu.workloads.preview import preview_indices
from ddim_cold_tpu.workloads.tasks import (EDIT_TASKS, TASKS,
                                           default_edit_configs, draft_init,
                                           draft_to_drawing, inpaint,
                                           interp_init, interpolate,
                                           normalize_mask, super_resolve,
                                           superres_init, superres_project)

__all__ = [
    "EDIT_TASKS", "TASKS", "default_edit_configs", "draft_init",
    "draft_to_drawing", "inpaint", "interp_init", "interpolate",
    "normalize_mask", "preview_indices", "super_resolve", "superres_init",
    "superres_project",
]
