"""Mesh-axis → parameter-layout/apply-fn selection, shared by the trainer
and the parallelism bench so they always measure the same wiring.

* a ``pipe`` axis: stacked-blocks params sharded stage-per-device +
  the GPipe pipelined apply_fn (parallel/pipeline.py);
* a ``model`` axis: Megatron column/row partition specs (parallel/sharding.py);
* otherwise: replicated params (gradient psum implicit in jit) — plain dp.
"""

from __future__ import annotations

from typing import Callable, Optional

from jax.sharding import Mesh


def layout_for_mesh(model, mesh: Mesh, params, *,
                    n_microbatch: int = 2) -> tuple[Optional[dict], Optional[Callable]]:
    """→ (partition_specs_or_None, apply_fn_or_None) for ``shard_train_state``
    and ``make_train_step``."""
    from ddim_cold_tpu.parallel.pipeline import make_pipelined_apply
    from ddim_cold_tpu.parallel.sharding import (
        param_partition_specs, pipeline_param_specs,
    )

    if int(mesh.shape.get("pipe", 1)) > 1:
        # 'expert' rides along like 'model': both stay GSPMD-auto inside the
        # pipeline's manual region, so MoE expert banks Megatron-shard the
        # same way tp kernels do (pipe×ep, the last composition gap)
        tensor_axes = tuple(a for a in ("model", "expert")
                            if int(mesh.shape.get(a, 1)) > 1)
        return (pipeline_param_specs(params, tensor_axes=tensor_axes),
                make_pipelined_apply(model, mesh, n_microbatch=n_microbatch))
    shard_axes = tuple(a for a in ("model", "expert")
                       if int(mesh.shape.get(a, 1)) > 1)
    if shard_axes:
        return param_partition_specs(params, axes=shard_axes), None
    return None, None
