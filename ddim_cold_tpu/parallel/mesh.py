"""Mesh/communication layer — replaces the reference's NCCL/DDP stack (C17).

The reference runs one OS process per GPU, rendezvouses over TCP
(multi_gpu_trainer.py:25-30), wraps the model in DDP for ring-allreduce of
gradients, and shards data with DistributedSampler. Under JAX SPMD all of that
collapses: one process per *host*, a ``jax.sharding.Mesh`` over the chips,
sharding annotations on params/batch, and XLA emits the collectives (psum for
gradients over ICI, all-gather where layouts require) fused into the step.

Mesh axes:
* ``data``  — batch (data parallelism; gradient psum is implicit in jit)
* ``model`` — attention heads / MLP hidden (Megatron-style tensor parallelism)

Multi-host: call ``initialize_distributed()`` once per host before device
queries; each host then feeds its data shard (data/loader.py shard_index =
``jax.process_index()``).
"""

from __future__ import annotations

from typing import Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def initialize_distributed(coordinator: Optional[str] = None, num_processes: Optional[int] = None,
                           process_id: Optional[int] = None) -> None:
    """Multi-host process coordination over DCN (replaces the TCP rendezvous at
    multi_gpu_trainer.py:25-30). No-op for single-host runs."""
    if num_processes is None or num_processes <= 1:
        return
    jax.distributed.initialize(
        coordinator_address=coordinator, num_processes=num_processes, process_id=process_id
    )


def make_mesh(shape: Optional[dict[str, int]] = None, devices=None) -> Mesh:
    """Build a Mesh. Default: every visible device on the 'data' axis with a
    trivial 'model' axis, so dp-only configs and tp-aware code share one layout.

    ``shape`` e.g. ``{"data": 4, "model": 2}`` must multiply to the device
    count (axis order = dict order, data-major outermost so model groups are
    ICI-adjacent).
    """
    devices = np.asarray(devices if devices is not None else jax.devices())
    if shape is None:
        shape = {"data": devices.size, "model": 1}
    sizes = tuple(shape.values())
    if int(np.prod(sizes)) != devices.size:
        raise ValueError(f"mesh shape {shape} does not match {devices.size} devices")
    return Mesh(devices.reshape(sizes), tuple(shape.keys()))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def data_axis_size(mesh: Optional[Mesh]) -> int:
    """Number of shards a batch's leading dim splits into on this mesh — 1
    for no mesh or a mesh without a 'data' axis (batch replicated). The serve
    engine validates its bucket sizes against this: a bucket that does not
    divide the data axis cannot be placed without a gather."""
    if mesh is None or "data" not in mesh.shape:
        return 1
    return int(mesh.shape["data"])


def batch_sharding(mesh: Mesh, grouped: bool = False) -> NamedSharding:
    """Batch arrays shard their leading dim over 'data' (DistributedSampler's
    role, now expressed as a sharding annotation). Meshes without a 'data'
    axis (e.g. pure sequence-parallel ``{seq: N}``) replicate the batch.

    ``grouped``: the batch carries a leading steps-per-dispatch axis (see
    train.step ``steps_per_dispatch``) — the scan axis stays unsharded and
    'data' moves to the per-step batch dim behind it."""
    if "data" not in mesh.shape:
        return NamedSharding(mesh, P())
    return NamedSharding(mesh, P(None, "data") if grouped else P("data"))


def shard_batch(batch, mesh: Mesh, grouped: bool = False):
    """Place a host-local batch as a global array sharded on 'data'.

    Multi-host: each process contributes its shard of the global batch
    (``make_array_from_process_local_data`` — the SPMD replacement for
    DistributedSampler rank interleaving)."""
    s = batch_sharding(mesh, grouped=grouped)
    if jax.process_count() > 1:
        return jax.tree.map(
            lambda x: jax.make_array_from_process_local_data(s, np.asarray(x)), batch
        )
    return jax.tree.map(lambda x: jax.device_put(x, s), batch)


def shard_params(params, mesh: Mesh, specs=None):
    """Place params on the mesh: replicated by default, or per-leaf
    PartitionSpecs (parallel/sharding.py) for tensor parallelism."""
    if specs is None:
        return jax.device_put(params, replicated(mesh))
    return jax.tree.map(
        lambda x, spec: jax.device_put(x, NamedSharding(mesh, spec)), params, specs
    )


def shard_train_state(state, mesh: Mesh, specs=None):
    """Place a TrainState on the mesh: params per ``specs`` (or replicated),
    optimizer moments co-sharded with their params.

    The optimizer-state layout is derived by re-running ``tx.init`` on the
    *already-sharded* params — optax moments are ``zeros_like(params)`` so they
    inherit the param shardings — and restored/initial values are then placed
    leaf-by-leaf onto that layout. Keeps Adam's mu/nu from silently living
    replicated next to tensor-sharded params (2× HBM + a reshard per step).
    """
    params = shard_params(state.params, mesh, specs)
    layout = state.tx.init(params)
    mesh_devices = set(mesh.devices.flat)

    def place(value, ref):
        sharding = ref.sharding
        if getattr(sharding, "device_set", None) != mesh_devices:
            sharding = replicated(mesh)  # scalars (e.g. adam count) from init
        return jax.device_put(np.asarray(value), sharding)

    opt_state = jax.tree.map(place, state.opt_state, layout)
    extra = {}
    if getattr(state, "ema_params", None) is not None:
        # the EMA shadow mirrors the params' tree and must mirror their
        # sharding too (elementwise update: no resharding in the step)
        extra["ema_params"] = shard_params(state.ema_params, mesh, specs)
    return state.replace(params=params, opt_state=opt_state, **extra)
