"""Pipeline parallelism — GPipe-style microbatch pipelining over a mesh axis.

The reference has no model parallelism of any kind (SURVEY.md C17:
"TP/PP/SP/EP/CP: ABSENT"); like tensor (sharding.py) and sequence
(ring_attention.py) parallelism, this is a TPU-native beyond-parity
capability: depth is sharded over the ``pipe`` mesh axis (each device owns
``depth / n_stages`` consecutive transformer blocks, stacked scan_blocks
layout), the batch is split into microbatches, and activations flow stage to
stage over ICI via ``ppermute`` while every stage computes a different
microbatch — the classic (M + S − 1)-step schedule with S−1 bubble steps.

Everything runs under one ``shard_map``: per step every device applies its
stage (a ``lax.scan`` over its local blocks) to its current microbatch and
rotates the result to its successor. The step loop is itself a ``lax.scan``,
so reverse-mode AD yields the reverse pipeline schedule for free (ppermute
transposes to the inverted permutation); stage parameters enter as sharded
operands, so their gradients come back sharded the same way — the optimizer
update stays local to each stage's device row.

Composes with data parallelism (batch dim stays sharded over ``data``) and —
since the ``shard_map`` is manual over only the pipe/data axes — with TENSOR
parallelism: a ``model`` mesh axis stays in GSPMD auto mode, so
``pipeline_param_specs(tensor_axes=("model",))`` Megatron-splits each
stage's kernels and the partitioner inserts the psums inside the stage body
(pipe×tp, VERDICT r4 weak #6). Sequence parallelism composes too: with a
``seq`` axis in the mesh the tokens shard over it as a second manual axis
and the stage body runs the inner sp kernel directly — ring rotation or the
ulysses all-to-all pair, per the model's ``sp_mode`` (pipe×sp).

Known backend quirk: a BF16 tp-psum inside this partially-manual shard_map
CHECK-fails in XLA's *CPU* AllReducePromotion pass (process abort) — f32
runs fine everywhere, and TPU handles bf16 all-reduce natively; the
virtual-CPU parallelism bench pins amp off for its pipe×tp row.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P
from ddim_cold_tpu.parallel import _compat
from ddim_cold_tpu.parallel._compat import shard_map


def pipeline_blocks(
    block,
    stacked_params,
    dpr: jax.Array,
    tokens: jax.Array,
    mesh: Mesh,
    *,
    axis: str = "pipe",
    batch_axis: Optional[str] = "data",
    seq_axis: Optional[str] = None,
    n_microbatch: int = 2,
    deterministic: bool = True,
    dropout_rng: Optional[jax.Array] = None,
    remat: bool = False,
    check_vma: bool = True,
    with_aux: bool = False,
) -> jax.Array:
    """Run the transformer trunk through the pipeline.

    ``block`` — unbound Block template (model.block_template());
    ``stacked_params`` — the scan_blocks ``params["blocks"]`` subtree, leaves
    leading dim = depth; ``dpr`` — (depth,) stochastic-depth rates;
    ``tokens`` — (B, N, C) trunk input. Requires depth % n_stages == 0 and
    B % n_microbatch == 0 (per data shard).

    ``seq_axis`` (pipe×sp): the token dim is additionally sharded over that
    manual axis and ``block`` must be the manual-ring template
    (``block_template(model, seq_manual_axis=seq_axis, …)``). Tokens are
    padded to a multiple of the axis size here and unpadded on return; the
    pad positions are masked inside the ring via the template's
    ``seq_valid_len``.

    ``with_aux`` (pipe×MoE): returns ``(tokens, aux)`` where ``aux`` is the
    mean of every sown 'losses' scalar across (layer, microbatch, seq shard)
    — the pipeline COUNTERPART of the plain path's layer-stacked ``moe_aux``,
    not a numerical reproduction of it. Each router here sees one microbatch
    (B/M tokens), so the load-balance term is a mean of per-microbatch
    statistics; the unpipelined path's router sees the full batch, and a
    load-balance penalty is nonlinear in the router's batch (fraction-routed
    × mean-gate products do not average across splits). Same standard GPipe
    + MoE semantics as e.g. GShard — equal in expectation, bit-different in
    value, and gradients steer routing per-microbatch, which is what a
    pipelined deployment actually load-balances. (train/step.py normalizes
    by element count, so the pre-normalized mean slots in unchanged.)
    Bubble-step applications are masked out: their tokens are garbage and
    their router stats would bias the load-balance term. Per data shard,
    shape (1,), P(batch_axis) — callers mean over it.
    """
    n_stages = int(mesh.shape[axis])
    depth = int(jax.tree.leaves(stacked_params)[0].shape[0])
    if depth % n_stages != 0:
        raise ValueError(f"depth {depth} not divisible by {n_stages} pipeline stages")
    bps = depth // n_stages
    B, N = tokens.shape[0], tokens.shape[1]
    M = int(n_microbatch)
    if B % M != 0:
        raise ValueError(f"batch {B} not divisible by {M} microbatches")
    if batch_axis is not None and batch_axis not in mesh.shape:
        batch_axis = None
    if seq_axis is not None:
        if not getattr(block, "seq_manual", False):
            # sharding tokens under a NON-manual block would run each local
            # einsum on its own shard — block-diagonal attention, silently
            # wrong output with no error
            raise ValueError(
                "seq_axis is set but `block` is not the manual-ring "
                "template — build it with block_template(model, "
                "seq_manual_axis=...)")
        if getattr(block, "num_experts", 1) > 1:
            # Inside the pipeline's manual region the WHOLE block — MLP
            # included — sees only its seq shard, so Switch capacity and
            # routing priority become shard-local: an expert can drop tokens
            # the unsharded model would keep (and ring-pad zeros would eat
            # capacity too). Every other layout reproduces the unsharded
            # step (the dryrun equivalence net's standard); a silently
            # different routing function fails that bar, so the pp×sp×MoE
            # TRIPLE is refused. All PAIRS compose: pp×ep (this module),
            # pp×sp (dense blocks), sp×ep (the global-collective wrapper,
            # where the MLP stays in GSPMD-land with the full token view).
            raise ValueError(
                "pipeline×sequence parallelism does not compose with "
                "num_experts > 1: the stage body would route each seq "
                "shard's tokens through shard-local Switch capacity, "
                "silently diverging from the unsharded model — drop the "
                f"'{seq_axis}' axis or use the {{data, seq, expert}} mesh")
        n_pad = (-N) % int(mesh.shape[seq_axis])
        if n_pad:
            tokens = jnp.pad(tokens, [(0, 0), (0, n_pad), (0, 0)])

    # (depth, ...) → (S, bps, ...): stage-major so P(axis) shards stages
    stage_params = jax.tree.map(
        lambda a: a.reshape((n_stages, bps) + a.shape[1:]), stacked_params)
    dpr_st = jnp.asarray(dpr, jnp.float32).reshape(n_stages, bps)
    mb = tokens.reshape((M, B // M) + tokens.shape[1:])

    use_rng = dropout_rng is not None
    # every manual axis the aux scalar ends up varying over (params vary per
    # pipe stage, tokens per data/seq shard) — scan carry inits must be
    # pcast to the same vma type as the loop output or shard_map's typing
    # rejects the scan (same rule as the schedule buffers below)
    aux_axes = tuple(a for a in (axis, batch_axis, seq_axis) if a is not None)

    # element count a single block call sows, captured at trace time — the
    # normalization must count sown ELEMENTS like train/step.py's plain path
    # (n_vals = Σ s.size), not block calls, or a block that one day sows a
    # second scalar (router z-loss) would silently double the pipelined aux
    # relative to the plain layout
    sown_per_call = [1]

    def apply_block(p, tok, rate, rngs):
        # mutable=["losses"] unconditionally: dense blocks sow nothing (aux
        # stays 0 and XLA drops the dead adds); MoE blocks sow their Switch
        # load-balance scalar, which the schedule below accumulates instead
        # of dropping (the pre-r05 guard refused MoE here for exactly that)
        tok, aux_vars = block.apply({"params": p}, tok, deterministic,
                                    dp_rate=rate, rngs=rngs,
                                    mutable=["losses"])
        sown = jax.tree.leaves(aux_vars.get("losses", {}))
        aux = (sum(jnp.sum(s) for s in sown).astype(jnp.float32)
               if sown else jnp.zeros((), jnp.float32))
        sown_per_call[0] = max(1, sum(int(s.size) for s in sown))
        return tok, aux

    if remat:
        apply_block = jax.checkpoint(apply_block)

    def per_device(params_s, dpr_s, mb_all, rng):
        params_s = jax.tree.map(lambda a: a[0], params_s)  # local (bps, ...)
        dpr_s = dpr_s[0]
        s = jax.lax.axis_index(axis)

        # rng coordinate: fold the DATA shard in (different samples need
        # different masks) but NOT the seq shard — seq shards hold pieces of
        # the SAME samples, and the per-sample stochastic-depth Bernoulli
        # must agree across them or a sample's residual gets half-dropped.
        # (Token-dropout masks therefore repeat across seq shards at equal
        # local offsets — correlated regularization, still unbiased.)
        d = (jax.lax.axis_index(batch_axis) if (use_rng and batch_axis is not None)
             else 0)
        n_data = int(mesh.shape.get(batch_axis, 1)) if batch_axis is not None else 1

        def stage_apply(tok, step_i):
            """One stage = scan over its bps local blocks; sown aux summed."""
            def body(carry, xs):
                tok, aux = carry
                p, rate, j = xs
                rngs = None
                if use_rng:
                    # distinct key per (data shard, schedule step, global
                    # layer): step_i identifies the microbatch flowing
                    # through, s*bps+j the layer, d the data row — without d
                    # every dp shard would draw identical dropout masks.
                    key = jax.random.fold_in(
                        rng[0], (step_i * depth + s * bps + j) * n_data + d)
                    rngs = {"dropout": key}
                tok, a = apply_block(p, tok, rate, rngs)
                return (tok, aux + a), None

            aux0 = _compat.pcast(jnp.zeros((), jnp.float32), aux_axes,
                                 to="varying")
            (tok, aux), _ = jax.lax.scan(
                body, (tok, aux0), (params_s, dpr_s, jnp.arange(bps)))
            return tok, aux

        T = M + n_stages - 1
        # accumulators must be typed varying over the pipe axis too (values
        # differ per stage via params/ppermute) for shard_map's vma loop
        # typing; zeros_like already inherits the data-varying from mb_all
        vary = lambda z: _compat.pcast(z, (axis,), to="varying")
        out_buf = vary(jnp.zeros_like(mb_all))
        buf = vary(jnp.zeros_like(mb_all[0]))
        aux_acc = _compat.pcast(jnp.zeros((), jnp.float32), aux_axes,
                                to="varying")

        def step(carry, i):
            buf, out_buf, aux_acc = carry
            # stage 0 injects microbatch i; later stages consume the ring buffer
            inject = mb_all[jnp.clip(i, 0, M - 1)]
            cur = jnp.where(s == 0, inject, buf)
            y, aux_step = stage_apply(cur, i)
            # bubble steps (this stage has no live microbatch) pass input
            # through unchanged — keeps values bounded, result is discarded
            # (and the bubble's sown aux with it: garbage-token router stats
            # would bias the load-balance mean)
            active = (i - s >= 0) & (i - s < M)
            y = jnp.where(active, y, cur)
            aux_acc = aux_acc + jnp.where(active, aux_step, 0.0)
            # last stage banks its finished microbatch
            out_idx = i - (n_stages - 1)
            collect = (s == n_stages - 1) & (out_idx >= 0) & (out_idx < M)
            banked = jax.lax.dynamic_update_index_in_dim(
                out_buf, y, jnp.clip(out_idx, 0, M - 1), 0)
            out_buf = jnp.where(collect, banked, out_buf)
            perm = [(d, (d + 1) % n_stages) for d in range(n_stages)]
            buf = jax.lax.ppermute(y, axis, perm)
            return (buf, out_buf, aux_acc), None

        (buf, out_buf, aux_acc), _ = jax.lax.scan(
            step, (buf, out_buf, aux_acc), jnp.arange(T))
        # replicate the last stage's outputs to every stage (zeros elsewhere)
        out = jnp.where(s == n_stages - 1, out_buf, jnp.zeros_like(out_buf))
        out = jax.lax.psum(out, axis)
        if not with_aux:
            return out
        # mean over every sown scalar: psum folds the per-stage (and per-seq-
        # shard) sums, each active (stage, step) contributed bps block sows
        aux = jax.lax.psum(aux_acc, axis)
        n_sown = depth * M * sown_per_call[0]
        if seq_axis is not None:
            aux = jax.lax.psum(aux, seq_axis)
            n_sown *= int(mesh.shape[seq_axis])
        return out, aux[None] / n_sown

    tok_spec = P(None, batch_axis, seq_axis, None)
    rng_arg = (dropout_rng if use_rng else jax.random.PRNGKey(0))[None]
    # manual ONLY over the pipeline (and dp/sp) axes: any other mesh axis —
    # 'model' in particular — stays in GSPMD auto mode, so tensor-parallel
    # param shardings (pipeline_param_specs tensor_axes) partition the
    # stage body's einsums without the block code knowing (pipe×tp
    # composition, VERDICT r4 weak #6; specs may not name auto axes — the
    # tp sharding rides on the param arrays themselves)
    manual = {axis} | {a for a in (batch_axis, seq_axis) if a is not None}
    fn = shard_map(
        per_device,
        mesh=mesh,
        in_specs=(P(axis), P(axis), tok_spec, P()),
        out_specs=(tok_spec, P(batch_axis)) if with_aux else tok_spec,
        axis_names=frozenset(manual),
        check_vma=check_vma,
    )
    if with_aux:
        out, aux = fn(stage_params, dpr_st, mb, rng_arg)
    else:
        out = fn(stage_params, dpr_st, mb, rng_arg)
    out = out.reshape(tokens.shape)
    out = out[:, :N]  # drop ring padding (no-op when seq_axis is None)
    return (out, aux) if with_aux else out


def make_pipelined_apply(model, mesh: Mesh, *, axis: str = "pipe",
                         batch_axis: Optional[str] = "data",
                         seq_axis: Optional[str] = "seq",
                         n_microbatch: int = 2):
    """An ``apply_fn`` drop-in for ``model.apply`` that routes the block trunk
    through the pipeline: embed (replicated, cheap) → pipelined blocks →
    head. ``model`` must be built with ``scan_blocks=True``.

    Composition is MESH-driven, the model stays plain: a ``model`` axis adds
    GSPMD tensor parallelism via ``pipeline_param_specs(tensor_axes=…)``; a
    ``seq_axis`` present in the mesh adds RING sequence parallelism inside
    each stage (the block template runs the inner ring kernel over the
    already-manual axis — pipe×sp; requires ``attn_drop_rate == 0``, same
    rule as every sequence-parallel path)."""
    if not model.scan_blocks:
        raise ValueError("pipelined apply requires scan_blocks=True")
    if model.seq_axis is not None or model.head_axis is not None:
        # composition is mesh-driven HERE, not via model fields: a model
        # built with the global-collective sp/tp attention would nest a
        # shard_map inside the pipeline's manual region.
        raise ValueError(
            "pipelined apply composes via MESH axes, not model fields — "
            "build the model plain (no seq_axis/head_axis) and put "
            "'seq'/'model' in the mesh")
    from ddim_cold_tpu.models.vit import block_template

    sp = (int(mesh.shape.get(seq_axis, 1))
          if seq_axis is not None and seq_axis in mesh.shape else 1)
    check_vma = True
    if sp > 1:
        # attn_drop_rate > 0 is fine in EVAL (dropout inactive); a TRAINING
        # apply raises at trace time inside the manual attention branch —
        # same rule as every sequence-parallel path (trainer zeroes it).
        # sp_mode picks the manual kernel: ring (ppermute rotation) or
        # ulysses (all-to-all head split on the stage's local heads).
        n_tokens = model.num_patches + 1  # + cls/time token (vit.py)
        manual = tuple(a for a in (seq_axis, batch_axis, axis)
                       if a is not None and a in mesh.shape)
        block = block_template(model, seq_manual_axis=seq_axis,
                               seq_valid_len=n_tokens,
                               seq_varying_axes=manual)
        if model.sp_mode == "ulysses" and model.use_flash:
            # same exemption the global ulysses wrapper applies, for BOTH
            # fused paths: the Pallas kernel's internal jaxpr trips the vma
            # matcher in interpret mode, and the xla blockwise scan's
            # unvarying o/l/m carry inits mix with the varying q/k/v
            check_vma = False
    else:
        seq_axis = None
        block = block_template(model)
    dpr = np.linspace(0.0, model.drop_path_rate, model.depth)

    def apply_fn(variables, x, t, deterministic: bool = True, rngs=None,
                 mutable=None):
        """``mutable=["losses"]`` mirrors ``model.apply``'s MoE contract
        (pipe×MoE): returns ``(out, {"losses": {"moe_aux": aux}})`` where
        ``aux`` is the per-data-shard mean of the sown Switch scalars —
        train/step.py's sum/size normalization then reproduces the plain
        path's aux term. The stage body re-sows what the shard_map would
        otherwise drop (pipeline_blocks ``with_aux``)."""
        # normalize flax's accepted mutable forms (str | bool | iterable);
        # collections this apply can't thread fail LOUD — silently dropping
        # a requested collection would corrupt the caller's unpack
        if mutable is None or mutable is False:
            cols = None
        elif mutable is True:
            cols = ("losses",)  # the only collection the trunk sows
        elif isinstance(mutable, str):
            cols = (mutable,)
        else:
            cols = tuple(mutable)
        if cols:
            unsupported = [c for c in cols if c != "losses"]
            if unsupported:
                raise ValueError(
                    f"pipelined apply threads only the 'losses' collection, "
                    f"got mutable={list(cols)!r}")
        want_losses = bool(cols) and "losses" in cols
        params = variables["params"]
        dropout_rng = (rngs or {}).get("dropout")
        tokens = model.apply({"params": params}, x, t, stage="embed",
                             deterministic=deterministic, rngs=rngs)
        tokens = pipeline_blocks(
            block, params["blocks"], dpr, tokens, mesh,
            axis=axis, batch_axis=batch_axis, seq_axis=seq_axis,
            n_microbatch=n_microbatch,
            deterministic=deterministic, dropout_rng=dropout_rng,
            remat=model.remat, check_vma=check_vma, with_aux=want_losses,
        )
        if want_losses:
            tokens, aux = tokens
        out = model.apply({"params": params}, x, t, stage="head",
                          tokens=tokens, deterministic=deterministic, rngs=rngs)
        if want_losses:
            return out, {"losses": {"moe_aux": aux}}
        if cols is not None:  # mutable=[] is valid flax: keep the 2-tuple arity
            return out, {}
        return out

    # the train step keys its mutable=["losses"] MoE path off this flag —
    # a plain custom apply_fn without it still gets the fail-loud refusal
    apply_fn.supports_losses = True
    return apply_fn
