"""Tensor-parallel partition specs for DiffusionViT parameters.

Megatron-style column→row sharding per transformer block over the 'model'
mesh axis:

* qkv kernel   (E, 3E): split the fused output dim  → P(None, 'model')
  (heads are the true unit — 3E reshapes to (3, H, hd), so 'model' must
  divide num_heads);
* attn proj    (E, E):  split the input dim          → P('model', None);
  XLA closes the pair with one reduce-scatter/all-reduce over ICI;
* mlp fc1      (E, hE): split the hidden dim         → P(None, 'model');
* mlp fc2      (hE, E): split the input dim          → P('model', None);
* sharded-dim biases follow their kernel; everything else (embeddings,
  layernorms, head, cls/pos/time tables) is replicated.

The reference has NO tensor parallelism (SURVEY.md C17: DP is the only
parallelism present); this layer is the TPU-native scale-out beyond parity.
"""

from __future__ import annotations

import jax
from jax.sharding import PartitionSpec as P

_COL_KERNELS = ("qkv", "fc1")  # output-dim sharded
_ROW_KERNELS = ("proj", "fc2")  # input-dim sharded


def _spec_for(path: tuple[str, ...], value, axes) -> P:
    names = [getattr(k, "key", str(k)) for k in path]
    if "patch_embed" in names:
        return P()  # keep the token projection replicated (small, bandwidth-bound)
    leaf = names[-1]
    module = names[-2] if len(names) >= 2 else ""
    if module == "moe":
        # Switch-MoE expert banks (models/moe.py): stacked expert params
        # carry a leading E axis → shard it over 'expert'; the router stays
        # replicated (tiny, every token needs it). Under the scan_blocks
        # stacked layout the LAYER axis leads instead and the expert axis
        # sits at dim 1 — sharding dim 0 there would split layers over
        # 'expert' (wrong layout, and a crash whenever depth % E != 0).
        if leaf == "router" or "expert" not in axes:
            return P()
        ndim = getattr(value, "ndim", 1)
        lead = 1 if names[0] == "blocks" else 0
        return P(*([None] * lead), "expert", *([None] * (ndim - 1 - lead)))
    if "model" not in axes:
        return P()
    # w8a16 trees (ops/quant.py quantize_params) keep the module paths and
    # swap kernel → {w_int8, scale}: the int8 matrix shards exactly like the
    # kernel it encodes; the per-output-channel scale vector follows the
    # bias rule (sharded with the output dim on column kernels, replicated
    # on row kernels, whose output dim is unsharded).
    if module in _COL_KERNELS:
        spec = P(None, "model") if leaf in ("kernel", "w_int8") else P("model")
    elif module in _ROW_KERNELS:
        spec = P("model", None) if leaf in ("kernel", "w_int8") else P()
    else:
        return P()
    if names[0] == "blocks":
        # stacked scan_blocks layout: an extra leading layer axis shifts
        # every dim right by one
        return P(None, *spec)
    return spec


def param_partition_specs(params, axes=("model", "expert")):
    """PyTree of PartitionSpecs matching ``params``' structure (both the
    unrolled ``blocks_{i}`` and stacked ``blocks`` layouts). ``axes`` MUST
    name only mesh axes the target mesh actually has — a spec referencing a
    missing axis fails at shard time (layout_for_mesh derives the right set
    from the mesh; direct callers owe the same care). The default covers
    meshes that carry both sharding axes."""
    return jax.tree_util.tree_map_with_path(
        lambda p, v: _spec_for(p, v, tuple(axes)), params)


def pipeline_param_specs(params, axis: str = "pipe", tensor_axes=()):
    """Specs for pipeline parallelism: the stacked ``blocks`` subtree shards
    its leading layer axis over ``axis`` (each stage's device row owns its
    own blocks — grads and optimizer state stay stage-local); everything
    outside the trunk (embeddings, norm, head) is replicated.

    ``tensor_axes`` composes tensor parallelism INSIDE each stage (e.g.
    ``("model",)`` on a {data, pipe, model} mesh): block kernels get their
    Megatron column/row split on the trailing dims on top of the leading
    ``axis`` shard. The pipeline executor runs the stage body with the
    tensor axes left in GSPMD auto mode (pipeline.py ``axis_names``), so
    these specs are the only tp wiring needed — the block code is unchanged.
    """
    tensor_axes = tuple(tensor_axes)

    def spec(path, value):
        names = [getattr(k, "key", str(k)) for k in path]
        if not (names and names[0] == "blocks"):
            return P()
        tail = ()
        if tensor_axes:
            # _spec_for's stacked-layout spec: leading layer axis + tensor
            # split on the trailing dims — swap its leading None for `axis`
            tail = tuple(_spec_for(path, value, tensor_axes))[1:]
        return P(axis, *tail)

    return jax.tree_util.tree_map_with_path(spec, params)
