"""Ulysses sequence parallelism — all-to-all head↔sequence resharding.

The second sequence-parallel strategy next to the ring (ring_attention.py),
after DeepSpeed-Ulysses (arXiv:2309.14509). Instead of rotating K/V around
the ring for ``S−1`` steps, the sequence-sharded activations are reshaped
with ONE all-to-all so each device holds the FULL sequence for ``H/S`` of
the heads, runs an ordinary local attention (dense einsum or the Pallas
flash kernel — softmax is per-head, so no cross-device softmax state at
all), and a second all-to-all restores sequence sharding.

Trade-off vs the ring: 2 all-to-alls of activation-sized payload vs S−1
ppermutes of K/V-sized payload with blockwise-softmax arithmetic — Ulysses
wins when heads are plentiful and the interconnect handles all-to-all well
(TPU ICI does); the ring wins when ``H < S`` or per-step overlap with
compute matters. Select per-run with ``sp_mode: ulysses`` in the YAML.

Requires the LOCAL head count divisible by the seq axis —
``(num_heads / tp) % S == 0``, where tp is any tensor-parallel head-sharding
axis in play (``head_axis``; VERDICT r4 weak #6 composition) — the ring has
no such constraint.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P
from ddim_cold_tpu.parallel._compat import shard_map
from ddim_cold_tpu.utils import profiling


class SeqParallelConfigError(ValueError):
    """A sequence-parallel geometry that cannot run: head count vs seq-axis
    divisibility (Ulysses' structural requirement). Subclasses ValueError so
    existing callers' error handling keeps working; raised with an actionable
    message naming the serving config knobs (``SamplerConfig.sp_mode`` /
    ``sp_degree``) — the engine's ring fallback catches exactly this class
    when resolving a config's attention strategy."""


def ulysses_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    axis_name: str,
    n_valid: Optional[int] = None,
    scale: float,
    use_flash: "bool | str" = False,
    flash_blocks: Optional[tuple] = None,
) -> jax.Array:
    """Manual (inside-shard_map) Ulysses attention on LOCAL shards — the
    body both :func:`ulysses_self_attention` (its own shard_map) and the
    pipeline executor's pipe×sp stage attention (an enclosing manual region,
    parallel/pipeline.py) run.

    q/k/v: per-device ``(B', n_loc, H_loc, D)`` with the sequence dim
    sharded over ``axis_name`` (padded so ``n_loc * S`` covers the
    sequence); ``n_valid`` is the unpadded global length — pad positions
    are sliced off between the two all-to-alls so the local attention never
    sees them. Requires ``H_loc % S == 0``.
    """
    S = jax.lax.psum(1, axis_name)  # static inside shard_map
    B, n_loc, H_loc, D = q.shape
    if H_loc % S != 0:
        raise SeqParallelConfigError(
            f"ulysses needs local heads ({H_loc}) divisible by the "
            f"'{axis_name}' axis ({S}); use sp_mode='ring' otherwise "
            "(serving: SamplerConfig(sp_mode='ring', sp_degree=...), or "
            "pick an sp_degree that divides the local head count)")
    Np = n_loc * S
    n_valid = Np if n_valid is None else n_valid
    n_pad = Np - n_valid

    # seq-sharded → head-sharded: every device gets the whole sequence for
    # its H_loc/S heads
    gather = partial(jax.lax.all_to_all, axis_name=axis_name,
                     split_axis=2, concat_axis=1, tiled=True)
    with profiling.scope("sp/all_to_all_gather"):
        qf, kf, vf = gather(q), gather(k), gather(v)  # (B', Np, H_loc/S, D)
    qf, kf, vf = (x[:, :n_valid] for x in (qf, kf, vf))

    if use_flash == "xla":
        from ddim_cold_tpu.ops.flash_attention import blockwise_attention_xla

        out = blockwise_attention_xla(
            qf, kf, vf, scale,
            *((flash_blocks[1],) if flash_blocks else ())).astype(q.dtype)
    elif use_flash:
        from ddim_cold_tpu.ops.flash_attention import flash_attention

        out = flash_attention(
            qf, kf, vf, scale, *(flash_blocks or ())).astype(q.dtype)
    else:
        logits = jnp.einsum(
            "bnhd,bmhd->bhnm", qf.astype(jnp.float32),
            kf.astype(jnp.float32)) * scale
        p = jax.nn.softmax(logits, axis=-1)
        out = jnp.einsum(
            "bhnm,bmhd->bnhd", p, vf.astype(jnp.float32)).astype(q.dtype)

    if n_pad:
        out = jnp.pad(out, [(0, 0), (0, n_pad), (0, 0), (0, 0)])
    # head-sharded → seq-sharded
    with profiling.scope("sp/all_to_all_scatter"):
        return jax.lax.all_to_all(out, axis_name=axis_name,
                                  split_axis=1, concat_axis=2, tiled=True)


def ulysses_self_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mesh: Mesh,
    *,
    axis: str = "seq",
    batch_axis: Optional[str] = None,
    head_axis: Optional[str] = None,
    scale: Optional[float] = None,
    use_flash: "bool | str" = False,  # False | True (Pallas) | "xla" (blockwise)
    flash_blocks: Optional[tuple] = None,
) -> jax.Array:
    """Global-array front end, mirror of ``ring_self_attention``.

    q/k/v are ``(B, N, H, D)`` global arrays with the sequence dim sharded
    over ``axis``; returns the dense-softmax result with the same sharding.
    ``batch_axis`` keeps dp composition (each (data, seq) device row holds a
    (B/dp, N/sp) tile). Padding tokens (N rarely divides S) are sliced off
    *after* the gather-side all-to-all, so neither the local attention nor
    the flash kernel ever sees them.

    ``head_axis`` composes with tensor parallelism (VERDICT r4 weak #6 —
    previously refused): the qkv projection already shards heads over the tp
    axis, and the all-to-all here further splits each device's LOCAL H/tp
    heads over ``axis`` — every (tp, sp) device pair ends up with the full
    sequence for H/(tp·sp) heads, attention stays exactly per-head, and the
    two all-to-alls ride only the 'seq' groups (no cross-tp traffic).
    Requires ``(H / tp) % sp == 0``.
    """
    B, N, H, D = q.shape
    if scale is None:
        scale = D**-0.5
    parts = int(mesh.shape[axis])
    if head_axis is not None and head_axis not in mesh.shape:
        raise ValueError(
            f"head_axis {head_axis!r} is not an axis of the mesh "
            f"{dict(mesh.shape)} — drop it, or add the tp axis to the mesh")
    tp = int(mesh.shape[head_axis]) if head_axis else 1
    if H % tp != 0:
        raise SeqParallelConfigError(
            f"num_heads ({H}) must divide over the '{head_axis}' axis ({tp})")
    if (H // tp) % parts != 0:
        raise SeqParallelConfigError(
            f"ulysses needs local heads ({H}//{tp}={H // tp}) divisible by "
            f"the '{axis}' axis ({parts}); use sp_mode='ring' otherwise "
            "(serving: SamplerConfig(sp_mode='ring', sp_degree=...), or "
            "pick an sp_degree that divides the local head count)")
    n_pad = (-N) % parts
    if n_pad:
        pad = [(0, 0), (0, n_pad), (0, 0), (0, 0)]
        q, k, v = (jnp.pad(x, pad) for x in (q, k, v))
    Np = N + n_pad

    def per_device(q, k, v):  # (B', Np/S, H_loc, D)
        return ulysses_attention(q, k, v, axis_name=axis, n_valid=N,
                                 scale=scale, use_flash=use_flash,
                                 flash_blocks=flash_blocks)

    seq_spec = P(batch_axis, axis, head_axis, None)
    # check_vma off: the body is stateless (two all-to-alls around a local
    # attention), and the Pallas kernel's internal jaxpr trips the vma
    # matcher in interpret mode (mixed varying/constant dynamic_slice)
    fn = shard_map(per_device, mesh=mesh,
                   in_specs=(seq_spec, seq_spec, seq_spec),
                   out_specs=seq_spec, check_vma=False)
    out = fn(q, k, v)
    return out[:, :N]
