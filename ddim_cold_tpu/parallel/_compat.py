"""shard_map across jax versions.

The parallel subsystem is written against the current top-level API
(``jax.shard_map`` with ``axis_names`` naming the MANUAL axes and
``check_vma`` for the varying-axes checker). Older jax (< 0.6) ships
shard_map under ``jax.experimental`` with the inverse/older spellings:
``auto`` names the axes that STAY automatic, and the checker flag is
``check_rep``. This shim presents the new surface on both.

Translation rules on the legacy path:
* ``axis_names`` given → ``auto = mesh axes − axis_names``; omitted →
  fully manual (``auto = ∅``), matching the new default.
* ``check_vma`` maps to ``check_rep`` — except that legacy partial-auto
  shard_map rejects ``check_rep=True``, so a nonempty ``auto`` forces it
  off (the caller's checker request is best-effort there, not a semantics
  change: the checker only verifies replication annotations).
"""

from __future__ import annotations

import jax

try:  # jax ≥ 0.6: the public top-level export — use it verbatim
    from jax import shard_map  # noqa: F401
except ImportError:  # jax 0.4/0.5
    from jax.experimental.shard_map import shard_map as _legacy_shard_map

    def shard_map(f, *, mesh, in_specs, out_specs, axis_names=None,
                  check_vma=True):
        if axis_names is not None:
            auto = frozenset(mesh.axis_names) - frozenset(axis_names)
        else:
            auto = frozenset()
        return _legacy_shard_map(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            auto=auto, check_rep=bool(check_vma) and not auto)


def pcast(x, axes, *, to):
    """``jax.lax.pcast`` when the running jax has it; identity otherwise.

    New-jax shard_map types every array with the manual axes it varies over
    and ``pcast(..., to="varying")`` is how a replicated literal (e.g. a zeros
    accumulator) is promoted to match a varying loop carry. Legacy shard_map
    has no varying-axes type system — every array is just an array — so there
    is nothing to promote and identity is the faithful translation.
    """
    fn = getattr(jax.lax, "pcast", None)
    if fn is None:
        return x
    return fn(x, axes, to=to)
