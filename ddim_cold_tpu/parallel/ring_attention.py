"""Ring attention — sequence/context parallelism over a mesh axis.

The reference's attention is dense O(N²) on one device (ViT.py:110-114; max
in-repo sequence 257 tokens, worst plausible 2501 for the 200px/p4 config) —
sequence parallelism is NOT a reference capability, but it is first-class
here: this is the TPU-native long-context primitive (blockwise softmax with
running max/denominator, K/V blocks rotating around the ring via ``ppermute``
over ICI), the shard_map analogue of Ring Attention (arXiv:2310.01889).

Memory per device drops from O(N²) to O(N·N/P) logits; compute overlaps with
the neighbor exchange. Padding tokens (sequences rarely divide the ring) are
handled with a key-validity mask carried alongside K/V.

Usage: either call ``ring_attention`` inside your own ``shard_map`` with the
sequence dim sharded over ``axis_name``, or use ``ring_self_attention`` which
wraps padding + shard_map over an existing mesh axis.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P
from ddim_cold_tpu.parallel import _compat
from ddim_cold_tpu.parallel._compat import shard_map
from ddim_cold_tpu.utils import profiling

_NEG_INF = -1e30


def ring_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    kv_valid: Optional[jax.Array],
    *,
    axis_name: str,
    scale: float,
    varying_axes: Optional[tuple[str, ...]] = None,
) -> jax.Array:
    """Blockwise-softmax attention with K/V ring rotation.

    Shapes (per-device shards): q/k/v ``(B, n_local, H, D)``, kv_valid
    ``(B, n_local)`` bool (True = real token) or None. Returns ``(B, n_local,
    H, D)``. Non-causal (ViT) — every query attends to every valid key.
    """
    axis_size = jax.lax.psum(1, axis_name)
    B, n_loc, H, D = q.shape
    if kv_valid is None:
        kv_valid = jnp.ones((B, n_loc), dtype=bool)

    # running (output·denominator, denominator, max) accumulators, f32 —
    # marked varying over every axis the inputs vary on (the ring axis, plus
    # the batch axis on a composed dp×sp mesh) for shard_map's vma loop typing
    vary = lambda x: _compat.pcast(x, varying_axes or (axis_name,), to="varying")
    o = vary(jnp.zeros((B, H, n_loc, D), jnp.float32))
    l = vary(jnp.zeros((B, H, n_loc), jnp.float32))
    m = vary(jnp.full((B, H, n_loc), _NEG_INF, jnp.float32))
    qf = q.astype(jnp.float32).transpose(0, 2, 1, 3)  # (B,H,nq,D)

    def accumulate(o, l, m, k_blk, v_blk, valid_blk):
        from ddim_cold_tpu.ops.flash_attention import online_softmax_update

        logits = jnp.einsum("bhqd,bkhd->bhqk", qf, k_blk.astype(jnp.float32)) * scale
        logits = jnp.where(valid_blk[:, None, None, :], logits, _NEG_INF)
        # v arrives (B, k, H, D); the shared update wants (B, H, k, D)
        return online_softmax_update(
            o, l, m, logits, v_blk.astype(jnp.float32).transpose(0, 2, 1, 3))

    def body(_, carry):
        o, l, m, k_blk, v_blk, valid_blk = carry
        o, l, m = accumulate(o, l, m, k_blk, v_blk, valid_blk)
        perm = [(i, (i + 1) % axis_size) for i in range(axis_size)]
        with profiling.scope("sp/ring_exchange"):
            k_blk = jax.lax.ppermute(k_blk, axis_name, perm)
            v_blk = jax.lax.ppermute(v_blk, axis_name, perm)
            valid_blk = jax.lax.ppermute(valid_blk, axis_name, perm)
        return o, l, m, k_blk, v_blk, valid_blk

    # axis_size − 1 rotations; the final block is consumed outside the loop so
    # no dead last exchange rides the ICI.
    o, l, m, k_blk, v_blk, valid_blk = jax.lax.fori_loop(
        0, axis_size - 1, body, (o, l, m, k, v, kv_valid))
    o, l, _ = accumulate(o, l, m, k_blk, v_blk, valid_blk)
    out = o / l[..., None]
    return out.transpose(0, 2, 1, 3).astype(q.dtype)


def ring_self_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mesh: Mesh,
    *,
    axis: str = "data",
    batch_axis: Optional[str] = None,
    head_axis: Optional[str] = None,
    scale: Optional[float] = None,
) -> jax.Array:
    """Global-array front end: pads the sequence to the ring size, shards it
    over ``axis``, runs ``ring_attention`` under shard_map, unpads.

    q/k/v are ``(B, N, H, D)`` global arrays; the result matches dense softmax
    attention. On a composed mesh (e.g. ``{'data': 2, 'seq': 4}``) pass
    ``batch_axis`` so the batch dim stays sharded over data parallelism while
    the ring rotates over ``axis`` — each (data, seq) device row then holds a
    (B/dp, N/sp) tile and the ppermute rides only the seq axis. With tensor
    parallelism too (dp×tp×sp), pass ``head_axis`` so the Megatron-column-
    split qkv activations keep their heads sharded over tp — softmax is
    per-head, so each tp group rings only its own heads; without it the specs
    would force an all-gather and redundant full-head compute.
    """
    B, N, H, D = q.shape
    if scale is None:
        scale = D**-0.5
    parts = int(mesh.shape[axis])
    n_pad = (-N) % parts
    valid = jnp.arange(N + n_pad) < N
    valid = jnp.broadcast_to(valid[None], (B, N + n_pad))
    if n_pad:
        pad = [(0, 0), (0, n_pad), (0, 0), (0, 0)]
        q, k, v = (jnp.pad(x, pad) for x in (q, k, v))

    seq_spec = P(batch_axis, axis, head_axis, None)
    varying = (axis,) + tuple(a for a in (batch_axis, head_axis) if a)
    fn = shard_map(
        partial(ring_attention, axis_name=axis, scale=scale, varying_axes=varying),
        mesh=mesh,
        in_specs=(seq_spec, seq_spec, seq_spec, P(batch_axis, axis)),
        out_specs=seq_spec,
    )
    out = fn(q, k, v, valid)
    return out[:, :N]
