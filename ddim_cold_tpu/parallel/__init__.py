from ddim_cold_tpu.parallel.mesh import (
    batch_sharding,
    make_mesh,
    replicated,
    shard_batch,
    shard_params,
    shard_train_state,
)
from ddim_cold_tpu.parallel.sharding import param_partition_specs

__all__ = [
    "make_mesh",
    "batch_sharding",
    "replicated",
    "shard_batch",
    "shard_params",
    "shard_train_state",
    "param_partition_specs",
]
