from ddim_cold_tpu.parallel.mesh import (
    batch_sharding,
    make_mesh,
    replicated,
    shard_batch,
    shard_params,
    shard_train_state,
)
from ddim_cold_tpu.parallel.pipeline import make_pipelined_apply, pipeline_blocks
from ddim_cold_tpu.parallel.sharding import param_partition_specs, pipeline_param_specs
from ddim_cold_tpu.parallel.ulysses import SeqParallelConfigError

__all__ = [
    "SeqParallelConfigError",
    "make_mesh",
    "batch_sharding",
    "replicated",
    "shard_batch",
    "shard_params",
    "shard_train_state",
    "param_partition_specs",
    "pipeline_param_specs",
    "make_pipelined_apply",
    "pipeline_blocks",
]
