"""Per-host sharded batching — replaces DataLoader + DistributedSampler.

Under SPMD there is one process per host (not per chip), so the reference's
two process boundaries (mp.spawn rank procs + 8 DataLoader workers each,
multi_gpu_trainer.py:63,212-219) collapse into this loader: each host decodes
only its shard of the global index order and feeds a host-local numpy batch;
pjit/shard_map then treats the per-host batches as one global batch sharded on
the 'data' mesh axis.

Sharding semantics mirror torch DistributedSampler exactly
(multi_gpu_trainer.py:61-64):

* train: per-epoch permutation from seed 42 (+epoch), drop_last — the global
  sample count is ⌊len/world⌋·world and shard r takes indices [r::world];
* eval: no shuffle, wrap-around (tiled) padding so every shard sees the same
  batch count even when the dataset is smaller than the shard count (torch
  tiles its index list the same way; upstream eval divides by the padded
  count, we keep that). ``pad_final_batch`` additionally rounds the LAST
  batch up to full size by wrapping — required because batches are placed
  with their leading dim sharded over the 'data' mesh axis, which needs even
  divisibility (a GPU ragged tail has no SPMD equivalent); the duplicate
  samples bias the epoch-mean val loss negligibly and deterministically.

Decode is overlapped with device compute by a thread pool that parallelizes
*within* a batch plus a bounded prefetch queue, so at most ``prefetch + 1``
decoded batches exist at any time regardless of dataset size (PIL decode
releases the GIL; this replaces the reference's 8 worker processes).
"""

from __future__ import annotations

import queue
import threading
from concurrent.futures import ThreadPoolExecutor
from typing import Iterator, Optional

import numpy as np

from ddim_cold_tpu.utils import faults


class ShardedLoader:
    """Iterable over host-local batches of ``(noisy, target, t)`` numpy arrays."""

    def __init__(
        self,
        dataset,
        batch_size: int,
        *,
        shuffle: bool,
        seed: int = 42,
        drop_last: bool = True,
        shard_index: int = 0,
        shard_count: int = 1,
        num_threads: int = 8,
        prefetch: int = 2,
        pad_final_batch: bool = False,
        raw: bool = False,
    ):
        if raw and not hasattr(dataset, "get_raw_batch"):
            raise ValueError(
                f"raw=True needs dataset.get_raw_batch; {type(dataset).__name__} "
                "does not implement the device-side corruption contract")
        self.dataset = dataset
        self.batch_size = batch_size
        self.shuffle = shuffle
        self.seed = seed
        self.drop_last = drop_last
        self.shard_index = shard_index
        self.shard_count = shard_count
        self.num_threads = num_threads
        self.prefetch = prefetch
        self.pad_final_batch = pad_final_batch
        self.raw = raw
        self.epoch = 0

    def set_epoch(self, epoch: int) -> None:
        """Reseed the epoch shuffle (mirrors DistributedSampler.set_epoch)."""
        self.epoch = epoch
        if hasattr(self.dataset, "set_epoch"):
            self.dataset.set_epoch(epoch)

    def _shard_indices(self) -> np.ndarray:
        n = len(self.dataset)
        if self.shuffle:
            indices = np.random.RandomState(self.seed + self.epoch).permutation(n)
        else:
            indices = np.arange(n)
        world = self.shard_count
        if self.drop_last:
            total = (n // world) * world
            indices = indices[:total]
        else:
            total = -(-n // world) * world  # ceil to a multiple of world
            if total > n:
                indices = np.resize(indices, total)  # tiled wrap-around pad
        return indices[self.shard_index :: world]

    def __len__(self) -> int:
        per_shard = len(self._shard_indices())
        if self.drop_last:
            return per_shard // self.batch_size
        return -(-per_shard // self.batch_size)

    def _batches(self) -> list[np.ndarray]:
        indices = self._shard_indices()
        nb = len(self)
        if self.pad_final_batch and nb * self.batch_size > len(indices):
            indices = np.resize(indices, nb * self.batch_size)
        return [indices[i * self.batch_size : (i + 1) * self.batch_size]
                for i in range(nb)]

    def _collate(self, items):
        noisy = np.stack([it[0] for it in items])
        target = np.stack([it[1] for it in items])
        t = np.asarray([it[2] for it in items], dtype=np.int32)
        return noisy, target, t

    def _make_batch(self, idxs: np.ndarray, pool: Optional[ThreadPoolExecutor] = None):
        # chaos hook: covers the threaded and unthreaded iteration paths
        # alike (an injected raise here surfaces at the consumer's next(),
        # exactly like a real decode failure would)
        faults.fire("data.next", tag=f"epoch:{self.epoch}|")
        if self.raw:  # (base, t) only — corruption happens on device (in-jit)
            return self.dataset.get_raw_batch(
                idxs, num_threads=max(1, self.num_threads), pool=pool)
        # native fast path: the dataset assembles the whole batch in C++
        # threads (decode/resize/degrade/collate outside the GIL); None means
        # "not available for this batch" → per-item python path.
        get_batch = getattr(self.dataset, "get_batch", None)
        if get_batch is not None:
            batch = get_batch(idxs, num_threads=max(1, self.num_threads), pool=pool)
            if batch is not None:
                return batch
        if pool is None:
            items = [self.dataset[int(i)] for i in idxs]
        else:
            items = list(pool.map(self.dataset.__getitem__, [int(i) for i in idxs]))
        return self._collate(items)

    def __iter__(self) -> Iterator:
        batches = self._batches()
        if self.num_threads <= 1:
            for b in batches:
                yield self._make_batch(b)
            return

        # one producer thread decodes batch-by-batch (items fan out over the
        # pool); the bounded queue caps live memory at prefetch+1 batches and
        # an abandoned iterator stops decoding within one batch.
        with ThreadPoolExecutor(self.num_threads) as pool:
            yield from _background_map(
                batches, lambda b: self._make_batch(b, pool), self.prefetch)


def _background_map(items, fn, depth: int):
    """Yield ``fn(item)`` with the mapping running ``depth`` items ahead in a
    producer thread (bounded queue). Exceptions from ``fn`` or the iterator
    surface at the consuming ``next()``; abandoning the generator (break/
    close) stops the producer within one item. Shared machinery for the
    decode pipeline (ShardedLoader) and the H2D overlap (device_prefetch).
    """
    q: queue.Queue = queue.Queue(maxsize=max(1, depth))
    stop = threading.Event()

    def put(item) -> bool:
        while not stop.is_set():
            try:
                q.put(item, timeout=0.1)
                return True
            except queue.Full:
                continue
        return False

    def producer():
        try:
            for it in items:
                if stop.is_set() or not put(fn(it)):
                    return
            put(None)
        except BaseException as e:  # noqa: BLE001 — worker thread: ANY error (incl. KeyboardInterrupt) must surface to the consumer
            put(e)

    thread = threading.Thread(target=producer, daemon=True)
    thread.start()
    try:
        while True:
            item = q.get()
            if item is None:
                break
            if isinstance(item, BaseException):
                raise item
            yield item
    finally:
        stop.set()
        # unblock a producer waiting on a full queue, then reap it
        while thread.is_alive():
            try:
                q.get_nowait()
            except queue.Empty:
                pass
            thread.join(timeout=0.2)


def group_batches(batches, n: int):
    """Stack every ``n`` successive batches along a new leading axis — the
    host-side half of ``make_train_step(steps_per_dispatch=n)``: one grouped
    batch becomes one dispatch running n optimizer steps on device. A
    trailing partial group (< n batches at epoch end) is dropped, mirroring
    ``drop_last`` semantics — callers that must see every sample should size
    epochs divisible by n or flush the tail with a 1-step fn."""
    if n <= 1:
        yield from batches
        return
    import jax

    buf = []
    for b in batches:
        buf.append(b)
        if len(buf) == n:
            yield jax.tree.map(lambda *xs: np.stack(xs), *buf)
            buf = []


def device_prefetch(batches, place, depth: int = 2):
    """Yield ``place(batch)`` for each host batch, with the placement (the
    host→device copy) running ``depth`` batches ahead in a background thread.

    On network-attached TPU hosts ``jax.device_put`` blocks on the upload RPC,
    so an unprefetched loop serializes transfer and compute; this overlaps
    them (the JAX client is thread-safe for placement).
    """
    return _background_map(batches, place, depth)
