"""Dataset classes — host-side image pipeline (replaces diffusion_loader.py).

All three reference datasets are provided with their exact tensor contracts
``__getitem__(index, t=None) → (noisy, target, t)`` where images are float32
HWC in [−1, 1] (NHWC is the TPU-native layout; the torch reference is CHW).

Reference quirks fixed per SURVEY.md's quirks register (do-not-copy list):
 #1 ``ColdDownSampleDataset`` defines ``__len__`` (upstream omits it and would
    crash DistributedSampler, diffusion_loader.py:60-97 vs :137-138);
 #2 the index is honored — upstream ``DiffusionDataset`` overrides it with
    ``random.randint(0,9)`` (diffusion_loader.py:44), a debug leftover.
File listings are sorted for cross-host determinism (upstream relies on raw
``os.listdir`` order, which is filesystem-dependent — under SPMD every host
must agree on the index→file mapping).

Per-item randomness (the step t, the Gaussian noise) is drawn from a
``seed/epoch/index``-keyed generator so any sample is reproducible — upstream
leaves this to worker-process global RNG state.

Decoded-image caching: the reference re-decodes every jpg every epoch
(diffusion_loader.py:47 via DataLoader workers); at TPU step rates the decode
dominates the epoch. Both datasets therefore cache the decoded+resized base
image (the deterministic part — corruption stays per-epoch random) in RAM,
auto-enabled when the whole dataset fits ``CACHE_BUDGET_BYTES`` and
overridable via ``cache_images``/the YAML ``cache_images`` key.
"""

from __future__ import annotations

import math
import os
import threading
from concurrent.futures import ThreadPoolExecutor
from typing import Optional, Sequence

import numpy as np
from PIL import Image

from ddim_cold_tpu.data import native, resize

_IMG_EXTS = {".jpg", ".jpeg", ".png", ".bmp", ".webp"}

#: auto-enable the decoded-image cache while all caching datasets in the
#: process together fit in this budget (train + val both auto-enable)
CACHE_BUDGET_BYTES = 2 << 30
#: skip the uint8 header probe (→ float32 mode) above this many files — the
#: per-file header open would dominate startup on huge/network datasets
U8_PROBE_MAX_FILES = 100_000
_cache_reserved = 0
_cache_lock = threading.Lock()


class _BaseCache:
    """Decoded-base-image cache shared by both dataset classes.

    Entries are keyed by index and stored RAW-preferred: uint8 RGB when the
    file decodes at exactly ``img_size`` (no resize — 4× less RAM, and the
    uint8 transfer path ships these bytes straight to the device), float32
    HWC [−1,1] otherwise. ``_normalize`` converts on read with the exact
    host-pipeline op order, so both storage forms are interchangeable.
    Concurrent ``__getitem__`` calls may race on a miss — both decode, one
    write wins; contents are identical either way (native and PIL paths are
    bit-exact, tests/test_native).
    """

    def _probe_uniform_u8(self) -> bool:
        """Header-only size scan (no pixel decode): True when EVERY file's
        native size equals img_size, i.e. raw uint8 storage/transfer applies.

        The decision is per-dataset, never per-batch — batch dtype must be
        stable across batches and across SPMD hosts (every host lists the
        same sorted files AND checks the same native capability, so every
        host with an identical build decides identically). u8 entries only
        ever come from the native decode tier, so the mode requires the
        ``ddim_decode_batch`` entry point — a stale .so forces float32
        everywhere rather than diverging from the budget estimate.

        Cost control: the first header short-circuits resize-needed datasets
        instantly; homogeneous datasets scan the rest over a thread pool;
        above U8_PROBE_MAX_FILES the probe is skipped (float32 mode) so a
        million-file dataset never serializes header reads into startup."""
        if not (self.use_native and native.has_decode_batch()):
            return False
        if len(self.imgList) > U8_PROBE_MAX_FILES:
            return False
        want = (int(self.img_size[1]), int(self.img_size[0]))  # PIL is (w, h)

        def ok(name: str) -> bool:
            try:
                with Image.open(os.path.join(self.root, name)) as im:
                    return im.size == want
            except Exception:  # noqa: BLE001 — PIL decode errors are legion; any failure just means "probe says no"
                return False

        if not ok(self.imgList[0]):
            return False
        # chunked scan: a mismatch bails after its chunk — an eager full
        # pool.map would submit (and then wait out) every remaining open
        with ThreadPoolExecutor(8) as pool:
            for lo in range(1, len(self.imgList), 1024):
                if not all(pool.map(ok, self.imgList[lo:lo + 1024])):
                    return False
        return True

    def _init_cache(self, cache_images: Optional[bool], n_items: int,
                    img_size: Sequence[int]) -> None:
        global _cache_reserved
        self._uniform_u8 = self._probe_uniform_u8()
        # uint8 entries are 4× smaller — let the auto budget see that
        est = n_items * int(img_size[0]) * int(img_size[1]) * 3 * (
            1 if self._uniform_u8 else 4)
        if cache_images is None:
            # budget is process-wide: train + val datasets both auto-enabling
            # must together stay under CACHE_BUDGET_BYTES
            with _cache_lock:
                cache_images = _cache_reserved + est <= CACHE_BUDGET_BYTES
                if cache_images:
                    _cache_reserved += est
        elif cache_images:
            with _cache_lock:
                _cache_reserved += est
        self.cache_images = bool(cache_images)
        self._cache_reservation = est if self.cache_images else 0
        self._cache: dict[int, np.ndarray] = {}

    def __del__(self):
        res = getattr(self, "_cache_reservation", 0)
        if res:
            try:
                global _cache_reserved
                with _cache_lock:
                    _cache_reserved -= res
            except Exception:  # noqa: BLE001 — interpreter teardown: globals may be gone
                pass

    @staticmethod
    def _normalize(entry: np.ndarray) -> np.ndarray:
        """uint8 entry → float32 [−1,1] with the exact ``_load_base`` op order
        (÷255 then ·2−1); float entries pass through."""
        if entry.dtype == np.uint8:
            return (entry.astype(np.float32) / 255.0) * 2.0 - 1.0
        return entry

    def _load_raw(self, path: str) -> np.ndarray:
        """One file, raw-preferred: uint8 when it decodes at exactly img_size,
        else the float [−1,1] resize pipeline."""
        img = pil_loader(path)
        if (img.height, img.width) == tuple(self.img_size):
            return np.asarray(img, dtype=np.uint8)
        arr = np.asarray(img, dtype=np.float32) / 255.0
        return resize.resize_bilinear(arr, tuple(self.img_size)) * 2.0 - 1.0

    def _base(self, index: int) -> np.ndarray:
        """Decoded+resized float32 base image for one item, through the cache."""
        hit = self._cache.get(index) if self.cache_images else None
        if hit is not None:
            return self._normalize(hit)
        if self.use_native:
            raw = self._raw_entries([index], num_threads=1)
            return self._normalize(raw[0])
        img = _load_base(os.path.join(self.root, self.imgList[index]),
                         self.img_size, use_native=False)
        if self.cache_images:
            self._cache[index] = img
        return img

    def _raw_entries(self, indices: Sequence[int], num_threads: int,
                     pool=None) -> list[np.ndarray]:
        """Cache entries (u8 or f32, see class docstring) for a batch.

        Misses fill in three tiers: raw C++ u8 decode (exact-size files) →
        fused C++ f32 decode+resize (size-mismatched files) → PIL per item
        (formats native rejects), fanned over ``pool`` when provided.
        """
        missing = ([i for i in indices if int(i) not in self._cache]
                   if self.cache_images else list(indices))
        got: dict[int, np.ndarray] = {}
        if missing:
            paths = [os.path.join(self.root, self.imgList[int(i)]) for i in missing]
            if self._uniform_u8:  # gated by the header probe — a dataset that
                # needs resizing must not pay a doomed full decode here
                res = native.decode_batch(paths, self.img_size,
                                          num_threads=num_threads)
                if res is not None:
                    u8, failed = res
                    for j, i in enumerate(missing):
                        if not failed[j]:
                            got[int(i)] = u8[j]
            left = [(j, int(i)) for j, i in enumerate(missing) if int(i) not in got]
            if left and not self._uniform_u8:
                # f32 fused decode+resize — NEVER under u8 mode: a runtime
                # decode failure must not flip the pinned batch dtype (PIL
                # below returns u8 for exact-size files, keeping the invariant)
                res = native.base_batch([paths[j] for j, _ in left],
                                        self.img_size, num_threads=num_threads)
                if res is not None:
                    f32, failed = res
                    for k, (_, i) in enumerate(left):
                        if not failed[k]:
                            got[i] = f32[k]
                left = [(j, i) for j, i in left if i not in got]
            if left:  # formats native rejects (progressive jpg/webp/…) → PIL
                mapper = pool.map if pool is not None else map
                for (j, i), entry in zip(
                    left, mapper(self._load_raw, [paths[j] for j, _ in left])
                ):
                    got[i] = entry
            if self.cache_images:
                # .copy(): u8[j]/f32[k] are views into the batch buffers —
                # caching views would pin the whole buffer per entry
                self._cache.update({k: v.copy() for k, v in got.items()})
        if self.cache_images:
            return [self._cache[int(i)] for i in indices]
        return [got[int(i)] for i in indices]  # no cache → all were missing

    def _raw_bases(self, indices: Sequence[int], num_threads: int,
                   pool=None) -> np.ndarray:
        """Stacked bases for the device-corruption path, dtype pinned
        per-DATASET (_uniform_u8): uint8 raw bytes for uniform datasets,
        float32 [−1,1] otherwise. The single place the pinning is enforced —
        both datasets' get_raw_batch delegate here."""
        if self.use_native:
            entries = self._raw_entries(indices, num_threads, pool=pool)
        else:  # per-item through the cache, fanned over the loader's pool
            mapper = pool.map if pool is not None else map
            entries = list(mapper(self._base, map(int, indices)))
        if self._uniform_u8:
            bad = [int(i) for i, e in zip(indices, entries)
                   if e.dtype != np.uint8]
            if bad:
                # never silently flip the batch dtype mid-run: it forces a jit
                # retrace, and under multi-host SPMD a single host shipping
                # float32 while the rest ship uint8 diverges the global array
                # dtype (hang/crash). Only cause: a file changed on disk after
                # the header probe pinned this dataset uint8.
                raise RuntimeError(
                    f"dataset pinned uint8 but indices {bad[:8]} decoded to a "
                    "different dtype — files mutated after the header probe; "
                    "rebuild the dataset or reopen it to re-probe")
            return np.stack(entries)
        return np.stack([self._normalize(e) for e in entries])

    def _bases_for(self, indices: Sequence[int], num_threads: int,
                   pool=None) -> np.ndarray:
        """Batch of float32 [−1,1] bases (the host-degrade contract)."""
        return np.stack([
            self._normalize(e)
            for e in self._raw_entries(indices, num_threads, pool=pool)
        ])


def pil_loader(path: str) -> Image.Image:
    """Open an image file and force RGB (reference diffusion_loader.py:17-21).

    PIL is the LAST decode tier (native rejects route here), so its failures
    are terminal: re-raise with the offending path attached — a
    DecompressionBombError or truncated-file error naming only an internal
    buffer is undebuggable mid-epoch over a million-file dataset."""
    with open(path, "rb") as f:
        try:
            img = Image.open(f)
            return img.convert("RGB")
        except Exception as e:  # noqa: BLE001 — re-raised below with the path attached
            # prepend the path in-place: constructing type(e) from a bare
            # string is not a safe contract across exception classes
            e.args = (f"{path}: " + (str(e.args[0]) if e.args else repr(e)),
                      *e.args[1:])
            raise


def _list_images(root: str, hint_size: int = 64) -> list[str]:
    if not os.path.isdir(root):
        out = os.path.dirname(root) or root  # <set>/train → <set>
        raise FileNotFoundError(
            f"dataset folder {root!r} does not exist — point the yaml's "
            "dataStorage at a folder of images, or generate the committed "
            f"surrogate set: python scripts/make_dataset.py --out {out} "
            f"--size {hint_size}")
    names = sorted(
        n for n in os.listdir(root) if os.path.splitext(n)[1].lower() in _IMG_EXTS
    )
    if not names:
        raise FileNotFoundError(f"no image files in {root!r}")
    return names


def _load_base(path: str, img_size: Sequence[int], use_native: bool = True) -> np.ndarray:
    """jpg → float32 HWC in [−1, 1]: to_tensor (÷255) → bilinear resize →
    ·2−1 (reference diffusion_loader.py:47-49 order).

    Dispatches to the native C++ decoder (data/native.py) when available —
    same math, same output, no GIL; falls back to PIL/numpy per-file.
    """
    hw = (int(img_size[0]), int(img_size[1]))
    if use_native:
        out = native.load_base(path, hw)
        if out is not None:
            return out
    img = np.asarray(pil_loader(path), dtype=np.float32) / 255.0
    img = resize.resize_bilinear(img, hw)
    return img * 2.0 - 1.0


class DiffusionDataset(_BaseCache):
    """Gaussian forward-noising dataset (reference diffusion_loader.py:24-58).

    ``__getitem__ → (x_t, x_0, t)`` with t ~ U[0, max_step) and
    x_t = √ᾱ·x0 + √(1−ᾱ)·ε under ᾱ = 1 − √((t+1)/T).
    """

    def __init__(self, root: str, imgSize: Sequence[int] = (32, 32), max_step: int = 2000,
                 seed: int = 0, use_native: bool = True,
                 cache_images: Optional[bool] = None):
        self.root = root
        self.img_size = tuple(int(s) for s in imgSize)
        self.max_step = max_step
        self.seed = seed
        self.use_native = use_native
        self.epoch = 0
        self.imgList = _list_images(root, hint_size=int(self.img_size[0]))
        self._init_cache(cache_images, len(self.imgList), self.img_size)

    def set_epoch(self, epoch: int) -> None:
        self.epoch = epoch

    def _rng(self, index: int) -> np.random.Generator:
        return np.random.Generator(
            np.random.Philox(np.random.SeedSequence([self.seed, self.epoch, index, 0xD1FF]))
        )

    def _noise_for(self, index: int, img: np.ndarray, t: Optional[int]):
        """(t, x_t) from the per-(seed, epoch, index) Philox stream — t is
        drawn BEFORE the noise, so native/PIL decode paths see identical
        randomness."""
        rng = self._rng(index)
        drawn = int(rng.integers(self.max_step))
        if t is None:
            t = drawn
        alpha = 1.0 - math.sqrt((t + 1) / self.max_step)
        noise = rng.standard_normal(img.shape).astype(np.float32)
        noisy = math.sqrt(alpha) * img + math.sqrt(1.0 - alpha) * noise
        return t, noisy.astype(np.float32)

    def __getitem__(self, index: int, t: Optional[int] = None):
        img = self._base(index)
        t, noisy = self._noise_for(index, img, t)
        return noisy, img.astype(np.float32), t

    def get_raw_batch(self, indices: Sequence[int], num_threads: int = 8,
                      pool=None):
        """Device-side-corruption path: ``(x₀, t)`` — clean bases (uint8 when
        the dataset is uniform at img_size, see _BaseCache) plus per-sample
        steps from the SAME Philox stream as the host path (t is drawn before
        the noise there, so schedules agree). The forward noising happens
        in-jit (ops/degrade.make_gaussian_prepare) with device-drawn ε."""
        ts = np.empty(len(indices), np.int32)
        for j, i in enumerate(indices):
            ts[j] = int(self._rng(int(i)).integers(self.max_step))
        return self._raw_bases(indices, num_threads, pool=pool), ts

    def get_batch(self, indices: Sequence[int], num_threads: int = 8,
                  pool=None):
        """Batch fast path: decode+resize in C++ threads (through the cache),
        noise in numpy. Returns collated ``(noisy, target, t)`` arrays, or
        None to make the loader fall back to per-item assembly.
        ``pool`` fans the PIL tier (formats native rejects) over the loader's
        shared executor."""
        if not self.use_native:
            return None
        base = self._bases_for(indices, num_threads, pool=pool)
        noisy = np.empty_like(base)
        ts = np.empty(len(base), np.int32)
        for j, i in enumerate(indices):
            ts[j], noisy[j] = self._noise_for(int(i), base[j], None)
        return noisy, base, ts

    def __len__(self) -> int:
        return len(self.imgList)


class ColdDownSampleDataset(_BaseCache):
    """Cold (downsampling) degradation dataset (reference diffusion_loader.py:60-138).

    ``target_mode``:
      * ``"chain"`` (default — what the trainer uses, multi_gpu_trainer.py:5,59):
        returns ``(D(x,t), D(x,t−1), t)`` — one-level restoration targets.
      * ``"direct"`` (the ``_au`` paper variant, diffusion_loader.py:99-138):
        returns ``(D(x,t), x_0, t)`` — direct clean-image targets.

    max_step = log2(size) (6 for 64px); t ∈ [1, max_step]; the degradation is
    nearest-resize down to ⌊size/2^t⌋ then nearest back up, torch interpolate
    index convention (data/resize.py).
    """

    def __init__(self, root: str, imgSize: Sequence[int] = (32, 32),
                 target_mode: str = "chain", seed: int = 0, use_native: bool = True,
                 cache_images: Optional[bool] = None):
        if imgSize[0] != imgSize[1]:
            raise ValueError("downsample dataset requires square images")
        if target_mode not in ("chain", "direct"):
            raise ValueError(f"unknown target_mode {target_mode!r}")
        self.root = root
        self.img_size = tuple(int(s) for s in imgSize)
        self.size = int(imgSize[0])
        self.max_step = int(np.log2(self.size))
        self.target_mode = target_mode
        self.seed = seed
        self.use_native = use_native
        self.epoch = 0
        self.imgList = _list_images(root, hint_size=int(self.img_size[0]))
        self._init_cache(cache_images, len(self.imgList), self.img_size)

    def set_epoch(self, epoch: int) -> None:
        self.epoch = epoch

    def get_t(self, img: np.ndarray, level_scale: int) -> np.ndarray:
        """D(x, s) for s = 2^t (reference diffusion_loader.py:79-83)."""
        return resize.cold_degrade(img, level_scale, self.size)

    def _draw_t(self, index: int) -> int:
        rng = np.random.Generator(
            np.random.Philox(np.random.SeedSequence([self.seed, self.epoch, index, 0xC01D]))
        )
        return int(rng.integers(self.max_step)) + 1  # t ∈ [1, max_step]

    def _degrade_pair(self, img: np.ndarray, t: int):
        """(D(x,t), target) from a decoded base image (numpy nearest-resize)."""
        noisy_t = self.get_t(img, 2**t)
        target = self.get_t(img, 2 ** (t - 1)) if self.target_mode == "chain" else img
        return noisy_t.astype(np.float32), target.astype(np.float32)

    def __getitem__(self, index: int, t: Optional[int] = None):
        path = os.path.join(self.root, self.imgList[index])
        if t is None:
            t = self._draw_t(index)
        if self.cache_images:
            # cached base + numpy degrade (degrade is cheap; decode was the cost)
            noisy, target = self._degrade_pair(self._base(index), t)
            return noisy, target, t
        if self.use_native:
            # full item (decode → resize → degrade) in one C++ call
            res = native.cold_item(path, self.size, t, self.target_mode == "chain")
            if res is not None:
                return res[0], res[1], t
        return self._pil_item(index, t)

    def get_batch(self, indices: Sequence[int], num_threads: int = 8,
                  pool=None):
        """Batch fast path: the whole (decode, resize, degrade, collate)
        pipeline in C++ threads (decode through the cache when enabled);
        failed slots redone via PIL with the same t. Returns
        ``(noisy, target, t)`` or None (→ loader per-item path).
        ``pool`` fans the PIL tier over the loader's shared executor."""
        if not self.use_native:
            return None
        ts = [self._draw_t(int(i)) for i in indices]
        if self.cache_images:
            base = self._bases_for(indices, num_threads, pool=pool)
            pair = native.cold_pair_batch(base, ts, self.target_mode == "chain",
                                          num_threads=num_threads)
            if pair is not None:
                return pair[0], pair[1], np.asarray(ts, np.int32)
            pairs = [self._degrade_pair(base[j], ts[j]) for j in range(len(ts))]
            return (np.stack([p[0] for p in pairs]),
                    np.stack([p[1] for p in pairs]),
                    np.asarray(ts, np.int32))
        paths = [os.path.join(self.root, self.imgList[int(i)]) for i in indices]
        res = native.cold_batch(paths, ts, self.size, self.target_mode == "chain",
                                num_threads=num_threads)
        if res is None:
            return None
        noisy, target, failed = res
        if failed.all():
            # fully non-native batch → loader's parallel per-item path
            return None
        for j, i in enumerate(indices):
            if failed[j]:
                noisy[j], target[j], _ = self._pil_item(int(i), ts[j])
        return noisy, target, np.asarray(ts, np.int32)

    def get_raw_batch(self, indices: Sequence[int], num_threads: int = 8,
                      pool=None):
        """Device-side-corruption path: ``(base, t)`` — the clean decoded
        bases plus the per-sample steps, with NO host degradation. The jitted
        step rebuilds ``(D(x,t), target, t)`` on device via
        ops/degrade.make_cold_prepare (bit-identical gathers), so the host
        ships one image per sample instead of two degraded copies — the
        transfer, not the decode, dominates on network-attached TPU hosts.

        ``t`` comes from the same per-(seed, epoch, index) stream as the host
        path, so both paths train on identical corruption schedules.
        ``pool`` is the loader's shared ThreadPoolExecutor for the PIL
        fallback (avoids per-batch executor churn on the hot path).

        When every base decodes at exactly img_size the batch ships as raw
        **uint8** (4× less host→device traffic than float32; the in-jit
        ``normalize_base`` conversion is bit-exact), else float32."""
        ts = np.asarray([self._draw_t(int(i)) for i in indices], np.int32)
        return self._raw_bases(indices, num_threads, pool=pool), ts

    def _pil_item(self, index: int, t: int):
        img = _load_base(os.path.join(self.root, self.imgList[index]),
                         self.img_size, use_native=False)
        noisy_t = self.get_t(img, 2**t)
        target = self.get_t(img, 2 ** (t - 1)) if self.target_mode == "chain" else img
        return noisy_t.astype(np.float32), target.astype(np.float32), t

    def __len__(self) -> int:
        return len(self.imgList)
