from ddim_cold_tpu.data.datasets import (
    ColdDownSampleDataset,
    DiffusionDataset,
    pil_loader,
)
from ddim_cold_tpu.data.loader import ShardedLoader

__all__ = ["DiffusionDataset", "ColdDownSampleDataset", "ShardedLoader", "pil_loader"]
