"""NumPy image resizing with torch ``F.interpolate`` conventions.

The reference resizes *tensors* through torchvision ``F.resize``
(diffusion_loader.py:48,81-82,89), which dispatches to ``torch.nn.functional
.interpolate``:

* **nearest**: source index = ``floor(dst * in/out)`` (asymmetric convention —
  NOT PIL's pixel-center rounding, and NOT jax.image.resize's half-pixel
  round). The cold degradation operator is built from this, so the convention
  is observable in training targets and must match bit-for-bit.
* **bilinear, antialias=False, align_corners=False**: half-pixel centers,
  ``src = (dst + 0.5)·scale − 0.5`` clamped at 0, 2-tap separable.

Pure NumPy (host data path); the device-side twin lives in
ops/degrade.py and is gather-based with identical index math.
"""

from __future__ import annotations

import numpy as np


def nearest_indices(out_size: int, in_size: int) -> np.ndarray:
    """torch interpolate-nearest source indices: floor(i · in/out)."""
    scale = in_size / out_size
    idx = np.floor(np.arange(out_size) * scale).astype(np.int64)
    return np.minimum(idx, in_size - 1)


def resize_nearest(img: np.ndarray, out_hw: tuple[int, int]) -> np.ndarray:
    """Nearest-neighbor resize of an (H, W, C) or (H, W) array, torch convention."""
    h, w = out_hw
    iy = nearest_indices(h, img.shape[0])
    ix = nearest_indices(w, img.shape[1])
    return img[iy][:, ix]


def _bilinear_weights(out_size: int, in_size: int):
    scale = in_size / out_size
    src = (np.arange(out_size) + 0.5) * scale - 0.5
    src = np.clip(src, 0.0, None)
    i0 = np.floor(src).astype(np.int64)
    i0 = np.minimum(i0, in_size - 1)
    i1 = np.minimum(i0 + 1, in_size - 1)
    frac = (src - i0).astype(np.float32)
    return i0, i1, frac


def resize_bilinear(img: np.ndarray, out_hw: tuple[int, int]) -> np.ndarray:
    """Bilinear resize (align_corners=False, no antialias) of (H, W, C) float array."""
    h, w = out_hw
    y0, y1, fy = _bilinear_weights(h, img.shape[0])
    x0, x1, fx = _bilinear_weights(w, img.shape[1])
    img = img.astype(np.float32, copy=False)
    top = img[y0]  # (h, W, C)
    bot = img[y1]
    fy = fy[:, None, None] if img.ndim == 3 else fy[:, None]
    rows = top * (1 - fy) + bot * fy
    left = rows[:, x0]
    right = rows[:, x1]
    fx = fx[None, :, None] if img.ndim == 3 else fx[None, :]
    return left * (1 - fx) + right * fx


def cold_degrade(img: np.ndarray, level_scale: int, size: int) -> np.ndarray:
    """The cold-diffusion degradation D(x, s): nearest-downsample to
    ⌊size/s⌋ then nearest-upsample back (reference diffusion_loader.py:79-83).

    ``level_scale`` is 2^t; s=1 is the identity.
    """
    target = int(np.floor(size / level_scale))
    target = max(target, 1)
    small = resize_nearest(img, (target, target))
    return resize_nearest(small, (size, size))
