"""ctypes binding to the native C++ data pipeline (native/ddim_data.cc).

The reference parallelizes decode with 8 DataLoader worker *processes* per
rank (multi_gpu_trainer.py:63); the TPU-native runtime keeps one process per
host and moves the per-image work (libjpeg/libpng decode, torch-convention
resize, cold degradation, batch assembly) into a C++ thread pool that fills
numpy-owned float32 buffers — no Python, no GIL in the hot path.

The library is built lazily on first use (``g++`` one-liner, cached as
``native/libddim_data.so``); every entry point degrades gracefully to the
PIL/numpy path (datasets.py / resize.py), so the native layer is a pure
accelerator, never a dependency. Set ``DDIM_COLD_NO_NATIVE=1`` to disable.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional, Sequence

import numpy as np

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
_NATIVE_DIR = os.path.join(_REPO_ROOT, "native")
_SO_PATH = os.path.join(_NATIVE_DIR, "libddim_data.so")

#: formats the native decoder handles; everything else goes through PIL.
NATIVE_EXTS = {".jpg", ".jpeg", ".png"}

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_lib_failed = False


def _build() -> bool:
    src = os.path.join(_NATIVE_DIR, "ddim_data.cc")
    if not os.path.isfile(src):
        return False
    # compile to a per-process temp name, then atomically rename: concurrent
    # processes (multi-host on a shared fs, pytest-xdist) must never dlopen a
    # half-written .so.
    tmp = f"{_SO_PATH}.{os.getpid()}.tmp"
    try:
        subprocess.run(
            ["g++", "-O3", "-fPIC", "-std=c++17", "-ffp-contract=off", "-shared",
             src, "-o", tmp, "-ljpeg", "-lpng", "-lpthread"],
            check=True, capture_output=True, timeout=120,
        )
        os.replace(tmp, _SO_PATH)
        return True
    except (subprocess.SubprocessError, OSError):  # compile failed / no g++
        try:
            os.unlink(tmp)
        except OSError:
            pass
        return False


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _lib_failed
    if _lib is not None or _lib_failed:
        return _lib
    with _lock:
        if _lib is not None or _lib_failed:
            return _lib
        if os.environ.get("DDIM_COLD_NO_NATIVE"):
            _lib_failed = True
            return None
        src = os.path.join(_NATIVE_DIR, "ddim_data.cc")
        stale = (os.path.isfile(_SO_PATH) and os.path.isfile(src)
                 and os.path.getmtime(src) > os.path.getmtime(_SO_PATH))
        if (not os.path.isfile(_SO_PATH) or stale) and not _build():
            # a stale-but-present .so still loads (new entry points are
            # hasattr-guarded); only a missing library is fatal here
            if not os.path.isfile(_SO_PATH):
                _lib_failed = True
                return None
        try:
            lib = ctypes.CDLL(_SO_PATH)
        except OSError:
            _lib_failed = True
            return None
        f32p = ctypes.POINTER(ctypes.c_float)
        i32p = ctypes.POINTER(ctypes.c_int32)
        charpp = ctypes.POINTER(ctypes.c_char_p)
        lib.ddim_load_base.argtypes = [ctypes.c_char_p, ctypes.c_int, ctypes.c_int, f32p]
        lib.ddim_load_base.restype = ctypes.c_int
        lib.ddim_cold_degrade.argtypes = [f32p, ctypes.c_int, ctypes.c_int,
                                          ctypes.c_int, f32p]
        lib.ddim_cold_degrade.restype = None
        lib.ddim_cold_item.argtypes = [ctypes.c_char_p, ctypes.c_int, ctypes.c_int,
                                       ctypes.c_int, f32p, f32p]
        lib.ddim_cold_item.restype = ctypes.c_int
        lib.ddim_cold_batch.argtypes = [charpp, i32p, ctypes.c_int, ctypes.c_int,
                                        ctypes.c_int, ctypes.c_int, f32p, f32p, i32p]
        lib.ddim_cold_batch.restype = ctypes.c_int
        lib.ddim_base_batch.argtypes = [charpp, ctypes.c_int, ctypes.c_int,
                                        ctypes.c_int, ctypes.c_int, f32p, i32p]
        lib.ddim_base_batch.restype = ctypes.c_int
        try:
            lib.ddim_cold_pair_batch.argtypes = [f32p, i32p, ctypes.c_int,
                                                 ctypes.c_int, ctypes.c_int,
                                                 ctypes.c_int, f32p, f32p]
            lib.ddim_cold_pair_batch.restype = None
        except AttributeError:  # stale .so from before this entry point
            pass
        try:
            u8p = ctypes.POINTER(ctypes.c_uint8)
            lib.ddim_decode_batch.argtypes = [charpp, ctypes.c_int, ctypes.c_int,
                                              ctypes.c_int, ctypes.c_int, u8p,
                                              i32p]
            lib.ddim_decode_batch.restype = ctypes.c_int
        except AttributeError:  # stale .so from before this entry point
            pass
        _lib = lib
        return _lib


def available() -> bool:
    """True when the native library is loaded (building it if needed)."""
    return _load() is not None


def has_decode_batch() -> bool:
    """True when the raw-u8 decode entry point exists (a stale .so built
    before it would silently force the float path — callers gate the uint8
    transfer mode on this so dtype never depends on which tier happened to
    fill a batch)."""
    lib = _load()
    return lib is not None and hasattr(lib, "ddim_decode_batch")


def supports(path: str) -> bool:
    return os.path.splitext(path)[1].lower() in NATIVE_EXTS


def _f32(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_float))


def _paths_array(paths: Sequence[str]):
    arr = (ctypes.c_char_p * len(paths))()
    arr[:] = [p.encode() for p in paths]
    return arr


def load_base(path: str, out_hw: tuple[int, int]) -> Optional[np.ndarray]:
    """decode → [0,1] → bilinear resize → [−1,1]; None on decode failure."""
    lib = _load()
    if lib is None or not supports(path):
        return None
    h, w = out_hw
    out = np.empty((h, w, 3), np.float32)
    if lib.ddim_load_base(path.encode(), h, w, _f32(out)):
        return None
    return out


def cold_degrade(img: np.ndarray, level_scale: int) -> Optional[np.ndarray]:
    """Native D(x, s) for a square (S, S, C) float32 array; None if unavailable."""
    lib = _load()
    if lib is None:
        return None
    img = np.ascontiguousarray(img, np.float32)
    size, _, c = img.shape
    out = np.empty_like(img)
    lib.ddim_cold_degrade(_f32(img), size, c, int(level_scale), _f32(out))
    return out


def cold_item(path: str, size: int, t: int, chain: bool):
    """(D(x,t), target) for one file; None on failure → caller uses PIL."""
    lib = _load()
    if lib is None or not supports(path):
        return None
    noisy = np.empty((size, size, 3), np.float32)
    target = np.empty((size, size, 3), np.float32)
    if lib.ddim_cold_item(path.encode(), size, int(t), int(chain), _f32(noisy),
                          _f32(target)):
        return None
    return noisy, target


def cold_batch(paths: Sequence[str], ts: Sequence[int], size: int, chain: bool,
               num_threads: int = 8):
    """Assemble a whole (noisy, target) batch in C++ threads, straight into
    the final buffers — the C layer sniffs magic bytes itself, so unsupported
    or corrupt files just set their slot in ``failed_mask`` for the caller's
    PIL redo. Returns ``(noisy, target, failed_mask)`` or None when the
    library is unavailable.
    """
    lib = _load()
    if lib is None:
        return None
    n = len(paths)
    noisy = np.empty((n, size, size, 3), np.float32)
    target = np.empty((n, size, size, 3), np.float32)
    failed = np.zeros(n, np.int32)
    ts_arr = np.asarray(ts, np.int32)
    lib.ddim_cold_batch(
        _paths_array(paths), ts_arr.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        n, size, int(chain), int(num_threads), _f32(noisy), _f32(target),
        failed.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
    )
    return noisy, target, failed.astype(bool)


def cold_pair_batch(bases: np.ndarray, ts: Sequence[int], chain: bool,
                    num_threads: int = 8):
    """(D(x,t), target) pairs from already-decoded (n, S, S, 3) base images —
    the cache's warm-epoch path (no file IO, degrade in C++ threads). Returns
    ``(noisy, target)`` or None when the library (or entry point) is missing."""
    lib = _load()
    if lib is None or not hasattr(lib, "ddim_cold_pair_batch"):
        return None
    bases = np.ascontiguousarray(bases, np.float32)
    n, size = bases.shape[0], bases.shape[1]
    noisy = np.empty_like(bases)
    target = np.empty_like(bases)
    ts_arr = np.asarray(ts, np.int32)
    lib.ddim_cold_pair_batch(
        _f32(bases), ts_arr.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        n, size, int(chain), int(num_threads), _f32(noisy), _f32(target),
    )
    return noisy, target


def decode_batch(paths: Sequence[str], out_hw: tuple[int, int], num_threads: int = 8):
    """Raw RGB8 batch for the uint8 transfer path: a slot succeeds only when
    the file decodes at exactly ``out_hw`` (no resize — the bytes are the
    pre-normalization pixels). Returns ``(u8_batch, failed_mask)`` or None
    when the library (or entry point) is unavailable; failed slots go through
    the float path."""
    lib = _load()
    if lib is None or not hasattr(lib, "ddim_decode_batch"):
        return None
    n = len(paths)
    h, w = out_hw
    out = np.empty((n, h, w, 3), np.uint8)
    failed = np.zeros(n, np.int32)
    lib.ddim_decode_batch(
        _paths_array(paths), n, h, w, int(num_threads),
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        failed.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
    )
    return out, failed.astype(bool)


def base_batch(paths: Sequence[str], out_hw: tuple[int, int], num_threads: int = 8):
    """Batch of [−1,1] base images (Gaussian dataset front half); returns
    ``(base, failed_mask)`` or None when unavailable."""
    lib = _load()
    if lib is None:
        return None
    n = len(paths)
    h, w = out_hw
    out = np.empty((n, h, w, 3), np.float32)
    failed = np.zeros(n, np.int32)
    lib.ddim_base_batch(
        _paths_array(paths), n, h, w, int(num_threads), _f32(out),
        failed.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
    )
    return out, failed.astype(bool)
