"""Progressive distillation: teacher takes two steps, student learns one.

The served few-step samplers (``ops/sampling.ddim_sample_fewstep``,
``SamplerConfig(steps=k)``) are only as good as the weights behind them — a
k=20-trained x̂₀ predictor run at k=1 jumps straight from full noise to the
clean image through coefficients it never saw. Progressive distillation
(Salimans & Ho; the Efficient Diffusion Models survey's few-step axis)
closes that gap with a halving loop: at each round the TEACHER runs two
consecutive steps of its 2s-evaluation schedule and the STUDENT — same
architecture, initialized from the teacher — learns to land on the
teacher's two-step output in one update of its s-evaluation schedule. The
round's student becomes the next round's teacher, so one k=20 model yields
the whole k∈{…,4,2,1} family.

Schedule consistency is what makes the pairing exact: every other entry of
``fewstep_time_sequence(T, 2s)`` IS ``fewstep_time_sequence(T, s)``
(ops/schedule.py), so student position j sits at teacher position 2j and
the teacher's sub-steps (2j, 2j+1) end exactly where the student's single
update j must land. The update math is the sampler's own affine form
(``fewstep_coefficients``) — the student trains against the exact program
serving dispatches, including the pinned jump-to-clean final row.

Both degradation families are covered:

* ``variant="ddim"`` — Gaussian forward noising at the drawn schedule level
  (the dataset's ᾱ(t) = 1 − √((t+1)/T) convention), teacher sub-steps via
  the affine DDIM update.
* ``variant="cold"`` — the deterministic cold degradation
  (ops/degrade.cold_degrade) at the drawn level; the naive cold update is
  ``x ← clamp(f(x, t))``, so the teacher's two steps are two model
  applications at consecutive schedule levels and the student matches the
  second output directly.

Training reuses the in-tree machinery end to end: ``EmaTrainState`` +
``make_optimizer`` from train/step.py (clip → AdamW-cosine, optional EMA
shadow), buffer donation on the jitted step, and orbax checkpoint/resume
via utils/checkpoint (per-round student files plus a mid-round ``live``
checkpoint, the trainer's template-restore idiom). The default
:class:`DistillConfig` is CPU-smoke sized; scale ``iters``/``batch_size``
up for a real run.
"""

from __future__ import annotations

import dataclasses
import os
from functools import partial
from typing import Any, Callable, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ddim_cold_tpu.ops import degrade, schedule
from ddim_cold_tpu.train.step import EmaTrainState, make_optimizer
from ddim_cold_tpu.utils import checkpoint as ckpt
from ddim_cold_tpu.utils.logging import print_log


def _log(msg: str, log: Optional[str]) -> None:
    print(msg, flush=True)
    if log:
        print_log(msg, log)


@dataclasses.dataclass(frozen=True)
class DistillConfig:
    """Knobs for one halving run. Defaults are CPU-smoke sized (seconds,
    not hours) — a real run raises ``iters``/``batch_size``/``lr`` and
    points ``checkpoint_dir`` somewhere durable."""

    start_steps: int = 4      # first student's evaluation count
    target_steps: int = 1     # halve until this count is reached
    iters: int = 60           # optimizer updates per round
    batch_size: int = 8
    lr: float = 1e-4
    variant: str = "ddim"     # "ddim" | "cold"
    cold_levels: int = 6      # cold: the start degradation level L
    ema_decay: float = 0.0    # > 0 keeps an EMA shadow of the student
    log_every: int = 20
    save_every: int = 0       # mid-round live-checkpoint cadence (0 = off)
    checkpoint_dir: Optional[str] = None
    seed: int = 0

    def __post_init__(self):
        if self.target_steps < 1:
            raise ValueError(
                f"target_steps must be >= 1, got {self.target_steps}")
        s = self.start_steps
        if s < self.target_steps:
            raise ValueError(
                f"start_steps ({s}) must be >= target_steps "
                f"({self.target_steps})")
        while s > self.target_steps:
            if s % 2:
                raise ValueError(
                    f"start_steps ({self.start_steps}) must reach "
                    f"target_steps ({self.target_steps}) by halving")
            s //= 2
        if s != self.target_steps:
            raise ValueError(
                f"start_steps ({self.start_steps}) must reach target_steps "
                f"({self.target_steps}) by halving")
        if self.variant not in ("ddim", "cold"):
            raise ValueError(
                f"variant must be 'ddim' or 'cold', got {self.variant!r}")
        if self.variant == "cold":
            for s in self.round_steps():
                if self.cold_levels % (2 * s):
                    raise ValueError(
                        f"cold_levels ({self.cold_levels}) must divide into "
                        f"2x the round's step count (round steps={s}) so "
                        "teacher/student level strides stay integral")
        if self.iters < 1 or self.batch_size < 1:
            raise ValueError("iters and batch_size must be >= 1")

    def round_steps(self) -> list:
        """Student evaluation counts, one per round: start, start/2, …,
        target."""
        out, s = [], self.start_steps
        while s >= self.target_steps:
            out.append(s)
            if s == self.target_steps:
                break
            s //= 2
        return out


def synthetic_batch(rng: jax.Array, n: int, img_size, chans: int):
    """Placeholder clean images for CPU smoke: piecewise-constant [−1, 1]
    tiles (a 4×4 draw nearest-upsampled), so the distill loss has real
    structure to fit without any dataset on disk."""
    H, _ = img_size
    tiles = jax.random.uniform(rng, (n, min(4, H), min(4, H), chans),
                               jnp.float32, minval=-1.0, maxval=1.0)
    return degrade.upsample_nearest(tiles, H)


def make_distill_step(model, *, steps: int, variant: str = "ddim",
                      cold_levels: int = 6,
                      ema_decay: float = 0.0) -> Callable:
    """``(state, teacher_params, x0, rng, loss_rec) →
    (state, loss, loss_rec)``, jitted with the student state and the loss
    EMA donated (train/step.py's calling convention).

    The teacher forward runs under ``stop_gradient`` on separately passed
    params — one program holds both; nothing about the teacher enters the
    optimizer. Per example, a schedule position j is drawn uniformly, the
    clean image is corrupted to the student's level t_j, the teacher takes
    its two sub-steps (2j, 2j+1) and the student's single update j is
    regressed onto the teacher's landing point (MSE in update space, so the
    final jump-to-clean position degenerates to plain x̂₀ matching)."""
    T = model.total_steps
    if variant == "ddim":
        c_s = schedule.fewstep_coefficients(T, steps)
        c_t = schedule.fewstep_coefficients(T, 2 * steps)
        t_s, t_t = c_s.t_seq, c_t.t_seq
    else:
        stride = cold_levels // steps
        t_s = np.arange(cold_levels, 0, -stride, dtype=np.int32)
        t_t = np.arange(cold_levels, 0, -stride // 2, dtype=np.int32)
        c_s = c_t = None

    def forward(params, x, t):
        out = model.apply({"params": params}, x, t)
        return jnp.clip(out, -1.0, 1.0)

    def teacher_target(teacher_params, x, j):
        """Two teacher sub-steps from student position j — the landing
        point the student must reach in one update."""
        tp = jax.lax.stop_gradient(teacher_params)
        if variant == "ddim":
            tt = jnp.asarray(t_t)
            cx, cx0 = jnp.asarray(c_t.cx), jnp.asarray(c_t.cx0)
            y = x
            for sub in (2 * j, 2 * j + 1):
                x0 = forward(tp, y, tt[sub])
                y = (cx[sub][:, None, None, None] * y
                     + cx0[sub][:, None, None, None] * x0)
            return y
        tt = jnp.asarray(t_t)
        y = forward(tp, x, tt[2 * j])
        return forward(tp, y, tt[2 * j + 1])

    def loss_fn(params, teacher_params, x_t, j):
        target = jax.lax.stop_gradient(
            teacher_target(teacher_params, x_t, j))
        x0_s = forward(params, x_t, jnp.asarray(t_s)[j])
        if variant == "ddim":
            cs = jnp.asarray(c_s.cx)[j][:, None, None, None]
            cs0 = jnp.asarray(c_s.cx0)[j][:, None, None, None]
            pred = cs * x_t + cs0 * x0_s
        else:
            pred = x0_s
        return jnp.mean(jnp.square(pred - target))

    @partial(jax.jit, donate_argnums=(0, 4))
    def step(state, teacher_params, x0, rng, loss_rec):
        rj, re = jax.random.split(rng)
        n = x0.shape[0]
        j = jax.random.randint(rj, (n,), 0, steps)
        if variant == "ddim":
            t = jnp.asarray(t_s)[j].astype(jnp.float32)
            alpha = (1.0 - jnp.sqrt((t + 1.0) / T))[:, None, None, None]
            eps = jax.random.normal(re, x0.shape, jnp.float32)
            x_t = jnp.sqrt(alpha) * x0 + jnp.sqrt(1.0 - alpha) * eps
        else:
            x_t = degrade.cold_degrade(x0, jnp.asarray(t_s)[j],
                                       size=x0.shape[1],
                                       max_step=cold_levels)
        loss, grads = jax.value_and_grad(loss_fn)(
            state.params, teacher_params, x_t, j)
        state = state.apply_gradients(grads=grads)
        if ema_decay:
            state = state.replace(ema_params=jax.tree.map(
                lambda e, p: ema_decay * e + (1.0 - ema_decay) * p,
                state.ema_params, state.params))
        loss_rec = 0.99 * loss_rec + 0.01 * loss
        return state, loss, loss_rec

    return step


def make_student_state(model, teacher_params, lr: float, total_iters: int,
                       ema_decay: float = 0.0) -> EmaTrainState:
    """A fresh optimizer wrapped around a COPY of the teacher's params —
    the standard progressive-distillation init (the student starts as the
    teacher and only has to learn the schedule compression)."""
    params = jax.tree.map(jnp.copy, teacher_params)
    state = EmaTrainState.create(
        apply_fn=model.apply, params=params,
        tx=make_optimizer(lr, total_iters),
        ema_params=jax.tree.map(jnp.copy, params) if ema_decay else None)
    return state.replace(step=jnp.asarray(0, jnp.int32))


def _round_template(state: EmaTrainState) -> dict:
    """Checkpoint template (structure + dtypes) for the live mid-round
    file — the trainer's template-restore idiom (utils/checkpoint)."""
    return {"steps": 0, "iter": 0, "loss": 0.0,
            "params": state.params, "opt_state": state.opt_state}


def distill(model, teacher_params, config: DistillConfig = DistillConfig(),
            *, batches: Optional[Callable] = None,
            log=None) -> Dict[str, Any]:
    """Run the halving loop; returns ``{"students": {steps: params},
    "history": {steps: [logged losses]}, "final_steps": k}``.

    ``batches`` is ``(rng) → (batch_size, H, W, C)`` clean images in
    [−1, 1]; the default draws :func:`synthetic_batch` (CPU smoke). With
    ``config.checkpoint_dir`` set, each finished round lands in
    ``student_k<steps>/`` and a ``live/`` checkpoint makes mid-round
    interrupts resumable — rerunning the same config skips completed
    rounds entirely (their students restore from disk)."""
    cfg = config
    if batches is None:
        H, W = model.img_size

        def batches(rng):
            return synthetic_batch(rng, cfg.batch_size, (H, W),
                                   model.in_chans)

    rng = jax.random.PRNGKey(cfg.seed)
    students: Dict[int, Any] = {}
    history: Dict[int, list] = {}
    teacher = teacher_params
    live_dir = (os.path.join(cfg.checkpoint_dir, "live")
                if cfg.checkpoint_dir else None)
    for round_idx, steps in enumerate(cfg.round_steps()):
        round_dir = (os.path.join(cfg.checkpoint_dir, f"student_k{steps}")
                     if cfg.checkpoint_dir else None)
        if round_dir and os.path.isdir(round_dir):
            restored = ckpt.restore_checkpoint(
                round_dir, target={"params": teacher})
            students[steps] = teacher = restored["params"]
            history[steps] = []
            _log(f"distill round {round_idx} (k={steps}): restored "
                 f"finished student from {round_dir}", log)
            continue
        state = make_student_state(model, teacher, cfg.lr, cfg.iters,
                                   cfg.ema_decay)
        start_iter = 0
        if live_dir and os.path.isdir(live_dir):
            live = ckpt.restore_checkpoint(live_dir,
                                           target=_round_template(state))
            if int(live["steps"]) == steps:
                state = state.replace(params=live["params"],
                                      opt_state=live["opt_state"])
                start_iter = int(live["iter"])
                _log(f"distill round {round_idx} (k={steps}): resumed "
                     f"at iter {start_iter}", log)
        step_fn = make_distill_step(model, steps=steps, variant=cfg.variant,
                                    cold_levels=cfg.cold_levels,
                                    ema_decay=cfg.ema_decay)
        loss_rec = jnp.asarray(0.0, jnp.float32)
        losses = []
        for it in range(start_iter, cfg.iters):
            rng, rb, rs = jax.random.split(rng, 3)
            x0 = batches(rb)
            state, loss, loss_rec = step_fn(state, teacher, x0, rs, loss_rec)
            if cfg.log_every and (it + 1) % cfg.log_every == 0:
                val = float(loss)
                losses.append(val)
                _log(f"distill k={steps} iter {it + 1:5d}/{cfg.iters} "
                     f"loss {val:.6f}", log)
            if live_dir and cfg.save_every and (it + 1) % cfg.save_every == 0:
                ckpt.save_checkpoint(live_dir, {
                    "steps": steps, "iter": it + 1, "loss": float(loss),
                    "params": state.params, "opt_state": state.opt_state})
        student = (state.ema_params if cfg.ema_decay else state.params)
        if round_dir:
            ckpt.save_checkpoint(round_dir, {"params": student})
        students[steps] = teacher = student
        history[steps] = losses
    return {"students": students, "history": history,
            "final_steps": cfg.target_steps}
