from ddim_cold_tpu.train.step import create_train_state, make_eval_step, make_train_step

__all__ = ["create_train_state", "make_train_step", "make_eval_step"]
