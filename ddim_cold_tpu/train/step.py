"""The SPMD training step — replaces DDP + AMP + GradScaler + per-step
scheduler (SURVEY.md C16).

One jitted ``train_step(state, batch, rng) → (state, loss)`` carries the whole
reference inner loop (multi_gpu_trainer.py:109-134): forward in the model's
compute dtype (bf16 under "AMP" — no GradScaler; bf16 keeps fp32 range so loss
scaling is unnecessary on TPU), smooth-L1 loss in f32, global-norm clip 1.0,
AdamW(wd=0.05) with a per-step cosine schedule to 0 — the optax chain mirrors
torch's clip→AdamW→CosineAnnealingLR order of operations.

Parallelism is carried by the *data*, not the code: params live replicated (or
tensor-sharded) on the mesh, the batch is sharded on 'data', and XLA inserts
the gradient psum over ICI where DDP used an NCCL allreduce. The same step
function serves 1 chip or a full slice.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
import optax
from flax.training import train_state

from ddim_cold_tpu.ops.losses import smooth_l1


class EmaTrainState(train_state.TrainState):
    """TrainState plus an optional EMA (exponential moving average) shadow of
    the params — the standard diffusion-training practice of sampling from
    smoothed weights (the reference has no EMA weights; this is a
    beyond-parity, opt-in feature: ``ema_decay: 0`` keeps it off and the
    field ``None``, so default runs are byte-identical to before)."""

    ema_params: Any = None


def make_optimizer(lr: float, total_steps: int) -> optax.GradientTransformation:
    """clip_by_global_norm(1.0) → AdamW(cosine→0, wd=0.05)
    (multi_gpu_trainer.py:89-92,130)."""
    schedule = optax.cosine_decay_schedule(init_value=lr, decay_steps=total_steps, alpha=0.0)
    return optax.chain(
        optax.clip_by_global_norm(1.0),
        optax.adamw(schedule, b1=0.9, b2=0.999, eps=1e-8, weight_decay=0.05),
    )


def create_train_state(model, rng: jax.Array, lr: float, total_steps: int,
                       sample_batch, ema_decay: float = 0.0) -> EmaTrainState:
    """Initialize params (same rng on every host ⇒ identical init, making the
    reference's save-to-file-and-sleep broadcast (multi_gpu_trainer.py:71-80)
    unnecessary) and wrap them with the optimizer. ``ema_decay`` > 0 also
    seeds an EMA shadow of the params (see :class:`EmaTrainState`)."""
    noisy, _, t = sample_batch
    params = model.init(rng, jnp.asarray(noisy), jnp.asarray(t))["params"]
    state = EmaTrainState.create(
        apply_fn=model.apply, params=params, tx=make_optimizer(lr, total_steps),
        ema_params=jax.tree.map(jnp.copy, params) if ema_decay else None,
    )
    # flax seeds step=0 as a python int → weak-typed int32 through the jitted
    # step, while a checkpoint-restored step is strong-typed — two avals, two
    # compiles across a resume. Anchor it once here (GRAFT-J002).
    return state.replace(step=jnp.asarray(0, jnp.int32))


def make_train_step(model, apply_fn: Optional[Callable] = None,
                    prepare: Optional[Callable] = None,
                    ema_decay: float = 0.0,
                    grad_accum: int = 1,
                    moe_aux_weight: float = 0.0,
                    steps_per_dispatch: int = 1) -> Callable:
    """``(state, batch, rng, loss_rec) → (state, loss, loss_rec)``.

    The EMA train loss (0.99/0.01, multi_gpu_trainer.py:126) is carried as a
    device scalar so the host only syncs at log points — the reference's
    per-step ``loss.item()`` would serialize the TPU pipeline. State buffers
    are donated (in-place update, no double-buffered params in HBM).

    ``apply_fn`` overrides ``model.apply`` with the same signature — the hook
    pipeline parallelism uses (parallel.pipeline.make_pipelined_apply).

    ``prepare`` is the device-side corruption hook: ``(raw_batch, rng) →
    (noisy, target, t)`` traced into the step (ops/degrade.make_cold_prepare),
    letting the host ship clean bases instead of degraded pairs.

    ``ema_decay`` > 0 updates the state's EMA param shadow each step
    (``ema ← d·ema + (1−d)·p``, plain decay, no bias correction — the warmup
    bias is irrelevant over a full training run and the seed is the init
    params, not zeros). Elementwise, so it fuses into the optimizer tail and
    inherits whatever sharding the params carry.

    ``grad_accum`` > 1 splits each step's batch into that many equal
    micro-slices and runs them through one ``lax.scan``, averaging the
    per-slice gradients before the single optimizer update — the standard
    big-batch-on-small-HBM tool (absent upstream). Peak activation memory
    drops ~grad_accum×; with dropout off the result is numerically
    equivalent to the unaccumulated step (smooth-L1 is a mean, and the mean
    of equal-sized slice means is the full-batch mean — only the float
    summation order differs, ~1e-7); with dropout on each slice folds its
    own mask key, which is the correct regularization, not a divergence.
    Slices are INTERLEAVED (slice j = rows j, j+ga, …): under a
    batch-dim-sharded mesh each slice stays evenly distributed over the
    'data' axis, where a contiguous split would park whole slices on one
    device and idle the rest.

    ``moe_aux_weight`` > 0 (Switch-MoE models only, models/moe.py): the
    forward runs with the ``losses`` collection mutable and the Switch
    load-balance loss — the mean of the per-block ``sow``n values — is
    added to the smooth-L1 with this coefficient.

    ``steps_per_dispatch`` > 1 changes the batch contract: every leaf gains
    a leading axis of that length (n stacked per-step batches) and ONE
    dispatch runs n full optimizer steps through a ``lax.scan``, returning
    the mean loss over them. Each inner step is the identical single-step
    math (the per-step rng/prepare folds key off ``state.step``, which
    advances inside the scan), so the result matches n sequential calls that
    pass the same ``rng``. This is the host-link lever: n× fewer
    host↔device round trips and n× larger transfers — decisive when the
    device is network-attached (remote-TPU tunnel, DCN-fed host), a regime
    where per-dispatch RPC latency and small-payload bandwidth dominate the
    step time (measured r03: e2e cold 613 img/s vs 4,089 synthetic at the
    same batch — the gap is entirely the tunnel link, not compute).
    """
    moe_on = moe_aux_weight > 0 and getattr(model, "num_experts", 1) > 1
    if (moe_on and apply_fn is not None
            and not getattr(apply_fn, "supports_losses", False)):
        raise ValueError(
            "moe_aux_weight requires an apply path that threads the "
            "'losses' collection — model.apply, or a custom apply_fn that "
            "sets .supports_losses (e.g. make_pipelined_apply)")
    apply_fn = apply_fn or model.apply
    if grad_accum < 1:
        raise ValueError(f"grad_accum must be >= 1, got {grad_accum}")
    if not 0.0 <= ema_decay < 1.0:  # same bound config.py enforces — direct
        raise ValueError(  # API callers must not bypass it (1.0 freezes the
            f"ema_decay must be in [0, 1), got {ema_decay!r}")  # shadow)
    if steps_per_dispatch < 1:
        raise ValueError(
            f"steps_per_dispatch must be >= 1, got {steps_per_dispatch}")

    def step_body(state: EmaTrainState, batch, rng: jax.Array,
                  loss_rec: jax.Array):
        if prepare is not None:
            # distinct fold constant: fold_in(rng, step+1) would be bit-equal
            # to the NEXT step's dropout key, correlating a stochastic
            # prepare's noise with the following step's dropout mask
            batch = prepare(
                batch, jax.random.fold_in(jax.random.fold_in(rng, 0x5EED), state.step))
        noisy, target, t = batch
        dropout_rng = jax.random.fold_in(rng, state.step)

        def loss_fn(params, noisy, target, t, drop_rng):
            if moe_on:
                pred, aux_vars = apply_fn(
                    {"params": params}, noisy, t, deterministic=False,
                    rngs={"dropout": drop_rng}, mutable=["losses"],
                )
                sown = jax.tree.leaves(aux_vars.get("losses", {}))
                # mean over LAYERS, layout-independent: the unrolled model
                # sows depth scalar leaves, the scan_blocks layout ONE
                # (depth,)-stacked leaf — normalize by total element count,
                # not leaf count, so both layouts weight the aux identically
                n_vals = sum(s.size for s in sown)
                aux = (sum(jnp.sum(s) for s in sown) / n_vals
                       if sown else 0.0)
                return smooth_l1(pred, target) + moe_aux_weight * aux
            pred = apply_fn(
                {"params": params}, noisy, t, deterministic=False,
                rngs={"dropout": drop_rng},
            )
            return smooth_l1(pred, target)

        if grad_accum == 1:
            loss, grads = jax.value_and_grad(loss_fn)(
                state.params, noisy, target, t, dropout_rng)
        else:
            b = noisy.shape[0]
            if b % grad_accum:
                raise ValueError(
                    f"batch {b} not divisible by grad_accum {grad_accum}")
            split = lambda x: x.reshape(  # noqa: E731 — interleaved: see doc
                (b // grad_accum, grad_accum) + x.shape[1:]).swapaxes(0, 1)

            def slice_grad(carry, sl):
                mb_noisy, mb_target, mb_t, i = sl
                loss_i, g_i = jax.value_and_grad(loss_fn)(
                    state.params, mb_noisy, mb_target, mb_t,
                    jax.random.fold_in(dropout_rng, i))
                return (jax.tree.map(jnp.add, carry[0], g_i),
                        carry[1] + loss_i), None

            zero = (jax.tree.map(jnp.zeros_like, state.params),
                    jnp.float32(0.0))
            (gsum, lsum), _ = jax.lax.scan(
                slice_grad, zero,
                (split(noisy), split(target), split(t),
                 jnp.arange(grad_accum)))
            grads = jax.tree.map(lambda g: g / grad_accum, gsum)
            loss = lsum / grad_accum
        new_state = state.apply_gradients(grads=grads)
        if ema_decay:
            if state.ema_params is None:  # trace-time: silently training
                raise ValueError(  # with no shadow would surface only when
                    # bestloss_ema is missing at the end of the run
                    "ema_decay > 0 but the state carries no ema_params — "
                    "create it with create_train_state(..., ema_decay=...) "
                    "or seed state.replace(ema_params=...)")
            new_state = new_state.replace(ema_params=optax.incremental_update(
                new_state.params, state.ema_params,
                step_size=1.0 - ema_decay))
        return new_state, loss, loss_rec * 0.99 + loss * 0.01

    if steps_per_dispatch == 1:
        return partial(jax.jit, donate_argnums=(0, 3))(step_body)

    @partial(jax.jit, donate_argnums=(0, 3))
    def multi_step(state: EmaTrainState, stacked_batch, rng: jax.Array,
                   loss_rec: jax.Array):
        def scan_body(carry, bt):
            st, rec = carry
            st, loss, rec = step_body(st, bt, rng, rec)
            return (st, rec), loss

        (state, loss_rec), losses = jax.lax.scan(
            scan_body, (state, loss_rec), stacked_batch,
            length=steps_per_dispatch)
        return state, losses.mean(), loss_rec

    return multi_step


def make_eval_step(model, apply_fn: Optional[Callable] = None,
                   prepare: Optional[Callable] = None) -> Callable:
    apply_fn = apply_fn or model.apply

    @jax.jit
    def eval_step(params, batch):
        if prepare is not None:
            batch = prepare(batch, jax.random.PRNGKey(0))
        noisy, target, t = batch
        pred = apply_fn({"params": params}, noisy, t, deterministic=True)
        return smooth_l1(pred, target)

    return eval_step
