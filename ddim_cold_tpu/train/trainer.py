"""The training loop — replaces ``multi_gpu_trainer.main`` (SURVEY.md §3.1).

The reference spawns one process per GPU, rendezvouses over NCCL, and runs a
per-rank loop with DDP allreduce inside backward. Here one process per host
drives a pjit'd step over the mesh; the call stack collapses to:

    run(config)
    ├─ make_mesh / shard params+batch          (parallel/mesh.py — was NCCL init)
    ├─ ShardedLoader per host                  (data/loader.py — was DataLoader×8 workers)
    ├─ create_train_state                      (train/step.py — was model+DDP+AdamW+scaler)
    ├─ optional warm-start / resume            (utils/checkpoint.py)
    └─ epoch loop: train_step scan → evaluate → log → checkpoint

Behavioral parity preserved: EMA(0.99) train loss starting at 5.0, every-100-
step log line, per-epoch val line, best/last dual checkpoints, epoch-granular
resume restoring scheduler position (the step count), best metric and EMA
loss (multi_gpu_trainer.py:53-55,94-106,126,135-163).
"""

from __future__ import annotations

import dataclasses
import os
import threading
import time
from dataclasses import dataclass
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ddim_cold_tpu.config import ExperimentConfig
from ddim_cold_tpu.data import ColdDownSampleDataset, DiffusionDataset, ShardedLoader
from ddim_cold_tpu.data.loader import device_prefetch, group_batches
from ddim_cold_tpu.ops import degrade
from ddim_cold_tpu.models import DiffusionViT
from ddim_cold_tpu.parallel import make_mesh, shard_batch, shard_train_state
from ddim_cold_tpu.parallel.layout import layout_for_mesh
from ddim_cold_tpu.train.step import create_train_state, make_eval_step, make_train_step
from ddim_cold_tpu.utils import checkpoint as ckpt
from ddim_cold_tpu.utils import profiling
from ddim_cold_tpu.utils.logging import ScalarWriter, asctime, print_log


@dataclass
class TrainResult:
    best_loss: float
    last_val_loss: float
    steps: int
    run_dir: str


class _GracefulStop:
    """SIGTERM/SIGINT → set a flag; the epoch loop finishes the current step,
    evaluates, checkpoints, and returns normally.

    A hard-killed training process is not just lost work: on network-attached
    TPU hosts the dead client's session claim can wedge the chip for every
    later process (see utils/platform.ensure_live_backend). Exiting through
    the normal path releases the claim and leaves a resumable lastepoch.ckpt.
    A SECOND signal restores the previous dispositions and re-delivers
    itself — truly urgent kill, not a second graceful pass. Handlers are only
    installable from the main thread — elsewhere this is a no-op
    (``requested`` stays False).

    Multi-host: the local flag must NOT gate collective control flow directly
    (only the signaled host would leave the loop — mismatched collectives
    deadlock the slice); callers consult :meth:`agreed` at loop points every
    host reaches at the same step.
    """

    def __init__(self):
        self.requested = False
        self._prev: dict = {}

    def agreed(self) -> bool:
        """Cross-host consensus on the stop flag: True when ANY process was
        signaled. Every process must call this at the same loop point."""
        if jax.process_count() == 1:
            return self.requested
        from jax.experimental import multihost_utils

        return bool(
            multihost_utils.process_allgather(np.asarray([self.requested])).any())

    def __enter__(self):
        import signal

        def handler(signum, frame):
            if self.requested:  # second signal: restore + re-deliver → die now
                for s, h in self._prev.items():
                    signal.signal(s, h)
                os.kill(os.getpid(), signum)
                return
            self.requested = True

        try:
            for s in (signal.SIGTERM, signal.SIGINT):
                self._prev[s] = signal.signal(s, handler)
        except ValueError:  # not the main thread
            self._prev = {}
        return self

    def __exit__(self, *exc):
        import signal

        for s, h in self._prev.items():
            signal.signal(s, h)
        return False


class _AsyncSaver:
    """Runs each epoch's checkpoint writes in a background thread so the
    device→host pull + serialization overlap the next epoch's compute (the
    writes were ~half the epoch wall time on a tunneled TPU host). At most one
    epoch's saves are in flight (``wait`` before the next ``submit``); save
    errors re-raise at the next wait point. Multi-host runs stay synchronous —
    orbax saves are collective and host-side thread scheduling must not
    reorder them against other collectives.
    """

    def __init__(self, sync: bool):
        self.sync = sync
        self._thread: Optional[threading.Thread] = None
        self._error: Optional[BaseException] = None

    def submit(self, fn) -> None:
        if self.sync:
            fn()
            return

        def run():
            try:
                fn()
            except BaseException as e:  # noqa: BLE001 — re-raised on the main thread at wait()
                self._error = e

        self._thread = threading.Thread(target=run, daemon=True)
        self._thread.start()

    def wait(self) -> None:
        if self._thread is not None:
            self._thread.join()
            self._thread = None
        if self._error is not None:
            e, self._error = self._error, None
            raise e


def _fully_addressable(tree) -> bool:
    """True when every array shard lives on this host (single-host runs) —
    the precondition for materializing params into a torch-style pickle."""
    return all(
        getattr(x, "is_fully_addressable", True) for x in jax.tree.leaves(tree)
    )


def _check_loaded_params(loaded, expected, src_path: str) -> None:
    """Fail LOUDLY on a config-mismatched warm-start/resume source (e.g. a
    stale pkl/ckpt from a different-sized run under the same name): orbax
    returns the ON-DISK shapes when they differ from a numpy template
    (measured), and silently replacing the tree would surface only as an
    opaque jit shape error — fatal for unattended evidence runs."""
    if jax.tree.structure(loaded) != jax.tree.structure(expected):
        raise ValueError(
            f"initializing file {src_path} does not match this model config "
            "(different param tree — wrong depth, positional-embedding mode, "
            "or bias layout)")
    paths = jax.tree_util.tree_flatten_with_path(expected)[0]
    mism = [
        f"{jax.tree_util.keystr(p)}: file {np.shape(a)} vs model {np.shape(b)}"
        for (p, b), a in zip(paths, jax.tree.leaves(loaded))
        if np.shape(a) != np.shape(b)]
    if mism:
        raise ValueError(
            f"initializing file {src_path} does not match this model config "
            f"— {'; '.join(mism[:4])}"
            + (f"; +{len(mism) - 4} more" if len(mism) > 4 else ""))


def _build_dataset(config: ExperimentConfig, root: str):
    cache = config.cache_images
    if config.dataset == "cold":
        return ColdDownSampleDataset(root, imgSize=config.image_size,
                                     target_mode="chain", cache_images=cache)
    if config.dataset == "cold_direct":
        return ColdDownSampleDataset(root, imgSize=config.image_size,
                                     target_mode="direct", cache_images=cache)
    if config.dataset == "gaussian":
        return DiffusionDataset(root, imgSize=config.image_size,
                                max_step=config.total_steps, cache_images=cache)
    raise ValueError(f"unknown dataset kind {config.dataset!r}")


def build_model(config: ExperimentConfig, mesh=None) -> DiffusionViT:
    """Model from config. With a mesh carrying a ``seq`` axis, attention runs
    as ring attention sharded over it (sequence parallelism); attention-
    dropout is zeroed then — the ring path never materializes the weights, and
    silently training dense while configured for sp would be worse. A ``pipe``
    axis forces the stacked scan_blocks layout (the pipeline's substrate)."""
    kwargs = dict(config.model_kwargs())
    mesh_shape = getattr(mesh, "shape", {}) if mesh is not None else {}
    if "pipe" in mesh_shape:
        # composition is mesh-driven inside the pipeline executor
        # (make_pipelined_apply): the model stays plain — seq/model fields
        # would nest a shard_map inside the pipeline's manual region.
        # sp_mode is the one field that travels: it picks the manual kernel
        # (ring rotation or ulysses all-to-all) the stage attention runs.
        kwargs["scan_blocks"] = True
        if "seq" in mesh_shape:
            kwargs["attn_drop_rate"] = 0.0  # manual sp: same dropout rule
            kwargs["sp_mode"] = config.sp_mode
    if "seq" in mesh_shape and "pipe" not in mesh_shape:
        # pure-sp meshes ({seq: N}, no data axis) replicate the batch; with a
        # tp axis the ring keeps heads sharded over it (no qkv all-gather)
        batch_axis = "data" if "data" in mesh_shape else None
        head_axis = "model" if int(mesh_shape.get("model", 1)) > 1 else None
        kwargs.update(seq_mesh=mesh, seq_axis="seq", batch_axis=batch_axis,
                      head_axis=head_axis, attn_drop_rate=0.0,
                      sp_mode=config.sp_mode)
    return DiffusionViT(
        dtype=jnp.bfloat16 if config.amp else jnp.float32, **kwargs
    )


def run(config: ExperimentConfig, base_dir: str, *, max_steps: Optional[int] = None,
        log_every: int = 100) -> TrainResult:
    """Train per the config; returns the best/final metrics. ``max_steps``
    bounds total optimizer steps (test/bench hook, not in the reference)."""
    from ddim_cold_tpu.utils.platform import enable_compile_cache

    enable_compile_cache()  # repeat compiles (resume, re-run, bench) become
    # disk reads — the ~35-40s cold-start otherwise erases the steady-state
    # win on short runs (VERDICT r3 weak #2). Proven in tests/conftest.py.
    saved_dir = os.path.join(base_dir, "Saved_Models")
    run_dir = os.path.join(saved_dir, config.run_name)
    os.makedirs(run_dir, exist_ok=True)
    log = os.path.join(run_dir, "train.log")

    # -- mesh over the requested device count ------------------------------
    avail = jax.devices()
    if config.mesh:
        # explicit mesh: the global batch and lr both derive from mesh['data']
        # (config.data_parallel_size), so clamping num_devices would change
        # nothing but the lr — a too-small host is a hard error instead.
        mesh_shape = dict(config.mesh)
        need = int(np.prod(list(mesh_shape.values())))
        if need > len(avail):
            raise ValueError(
                f"config.mesh {mesh_shape} needs {need} devices, "
                f"only {len(avail)} visible")
    else:
        ndev = config.num_devices
        if ndev > len(avail):
            print_log(f"requested {ndev} devices, only {len(avail)} visible — clamping", log)
            ndev = len(avail)
            # keep the lr↔global-batch linear-scaling rule consistent with the
            # batch actually trained (config.lr derives from num_devices here)
            config = dataclasses.replace(config, num_devices=ndev)
        mesh_shape = {"data": ndev}
    mesh = make_mesh(mesh_shape, devices=avail[: int(np.prod(list(mesh_shape.values())))])
    exp_size = int(mesh.shape.get("expert", 1))
    if exp_size > 1 and (config.num_experts <= 1
                         or config.num_experts % exp_size):
        raise ValueError(
            f"mesh 'expert' axis of {exp_size} needs num_experts (got "
            f"{config.num_experts}) set and divisible by it")

    # -- data --------------------------------------------------------------
    # per-device batch × devices = the global batch fed each step; sharding on
    # the 'data' axis routes each device its slice (replaces DistributedSampler
    # rank interleaving + per-rank DataLoader).
    # build the model first: it validates mesh-axis composition (pipe vs
    # model/seq) before any batch-arithmetic error can mask that message
    model = build_model(config, mesh=mesh)
    data_mesh_size = int(mesh.shape.get("data", 1))
    global_batch = config.effective_batch * data_mesh_size
    pipe_stages = int(mesh.shape.get("pipe", 1))
    n_micro = (config.microbatches or 2 * pipe_stages) if pipe_stages > 1 else 1
    if pipe_stages > 1 and (
        global_batch % n_micro or (global_batch // n_micro) % data_mesh_size
    ):
        raise ValueError(
            f"pipeline needs global batch {global_batch} divisible by "
            f"microbatches {n_micro} and each microbatch by data={data_mesh_size}")
    if config.grad_accum > 1:
        if pipe_stages > 1:
            raise ValueError(
                "grad_accum composes with dp/tp/sp only — the pipe axis has "
                "its own microbatching (config.microbatches)")
        if (global_batch % config.grad_accum
                or (global_batch // config.grad_accum) % data_mesh_size):
            raise ValueError(
                f"grad_accum needs global batch {global_batch} divisible by "
                f"{config.grad_accum} and each slice by data={data_mesh_size}")
    shard_index, shard_count = jax.process_index(), jax.process_count()
    train_set = _build_dataset(config, config.data_storage[0])
    test_set = _build_dataset(config, config.data_storage[1])
    # device-side corruption: datasets ship clean bases and the jitted step
    # rebuilds the corrupted batch on device — for cold, bit-identical gathers
    # (both loaders); for gaussian, device-drawn ε (train loader only: the val
    # loss stays on the deterministic host path). 2-8× less host→device
    # traffic, the dominant per-step cost on tunneled TPU hosts.
    is_cold = config.dataset in ("cold", "cold_direct")
    raw_train = config.device_degrade and config.dataset in (
        "cold", "cold_direct", "gaussian")
    raw_eval = config.device_degrade and is_cold
    prepare = eval_prepare = None
    if raw_train:
        if is_cold:
            prepare = degrade.make_cold_prepare(
                size=int(config.image_size[0]), max_step=train_set.max_step,
                chain=(config.dataset == "cold"), mesh=mesh)
            eval_prepare = prepare
        else:
            prepare = degrade.make_gaussian_prepare(config.total_steps,
                                                    mesh=mesh)
    train_loader = ShardedLoader(
        train_set, global_batch // shard_count, shuffle=True, seed=config.seed,
        drop_last=True, shard_index=shard_index, shard_count=shard_count,
        raw=raw_train,
    )
    test_loader = ShardedLoader(
        test_set, global_batch // shard_count, shuffle=False, drop_last=False,
        shard_index=shard_index, shard_count=shard_count,
        pad_final_batch=True,  # sharded leading dim needs even divisibility
        raw=raw_eval,
    )
    train_batches, test_batches = len(train_loader), len(test_loader)
    if train_batches == 0:
        raise ValueError("dataset smaller than one global batch (drop_last)")

    # -- model state -------------------------------------------------------
    rng = jax.random.PRNGKey(config.seed)
    # init traces the real step (incl. any ring-attention shard_map), so the
    # sample's leading dim must divide over the data axis like a real batch
    sample_n = 2 * data_mesh_size
    sample = next(iter(ShardedLoader(train_set, sample_n, shuffle=False,
                                     drop_last=False, pad_final_batch=True,
                                     num_threads=1)))
    sample = shard_batch(sample, mesh)
    # no ema_decay here: the EMA shadow is seeded AFTER warm-start/resume
    # resolve the actual starting params (below) — a create-time seed would
    # be a dead full-tree copy on every warm-started run.
    # Cosine-schedule length = the steps that will actually run: grouped
    # dispatch drops epoch tails shorter than steps_per_dispatch, and a
    # schedule sized for the ungrouped count would end the run mid-cosine
    # (LR never reaching its configured floor).
    steps_per_epoch = (train_batches // config.steps_per_dispatch
                       ) * config.steps_per_dispatch
    if steps_per_epoch == 0:
        raise ValueError(
            f"steps_per_dispatch {config.steps_per_dispatch} exceeds the "
            f"{train_batches} batches in an epoch — every epoch would drop")
    state = create_train_state(
        model, rng, config.lr, steps_per_epoch * config.epoch[1], sample
    )

    # warm start (the reference's `initializing` key, C18): load if present,
    # else persist this init for future runs. No broadcast needed under SPMD.
    epoch_start = config.epoch[0]
    steps, loss_rec, best_loss = 0, 5.0, 5.0
    if config.initializing not in ("", "none"):
        init_path = os.path.join(saved_dir, config.initializing)
        ckpt.recover_swap(init_path)  # owner-side heal of a crashed save swap
        loaded = None
        if os.path.isfile(init_path):
            loaded = ckpt.load_torch_pkl(init_path, config.patch_size)
        elif os.path.isdir(init_path):
            # orbax restore with a template returns the ON-DISK shapes when
            # they differ (measured) — validated below like the pkl branch
            loaded = ckpt.restore_checkpoint(init_path, state.params)
        elif jax.process_index() == 0:
            # best-effort convenience cache (same seed reproduces the init
            # regardless): torch-less hosts still write the pkl via the
            # native writer; anything the pkl bridge refuses (e.g. MoE
            # params have no reference torch layout) falls back to orbax —
            # the isdir branch above loads that form on the next run
            try:
                ckpt.save_torch_pkl(state.params, init_path, config.patch_size)
            except Exception as e:  # noqa: BLE001
                print_log(f"init pkl export unavailable ({e}); "
                          "persisting orbax instead", log)
                if os.path.isfile(init_path):  # partial file from the failed
                    os.remove(init_path)  # write would poison later runs AND
                    # break save_checkpoint's dir rename onto it
                ckpt.save_checkpoint(init_path, state.params)
        if loaded is not None:
            _check_loaded_params(loaded, state.params, init_path)
            state = state.replace(params=loaded)

    if config.resume != "none":
        ckpt.recover_swap(config.resume)  # owner-side heal (crashed save swap)
        base_tpl = {"epoch": 0, "steps": 0, "loss_rec": 0.0, "metric": 0.0,
                    "params": state.params, "opt_state": state.opt_state}
        want_ema = bool(config.ema_decay)
        template = dict(base_tpl,
                        **({"ema_params": state.params} if want_ema else {}))
        try:
            restored = ckpt.restore_checkpoint(config.resume, template)
        except ValueError as first_err:
            # orbax is strict BOTH ways about the optional ema_params key
            # (measured: template-extra and template-missing each raise
            # ValueError) — so ema_decay can be toggled across a resume:
            # retry with the key flipped; if that fails too the mismatch was
            # something else, so surface the ORIGINAL error, not the
            # doubly-mutated retry's
            alt = (dict(base_tpl) if want_ema
                   else dict(base_tpl, ema_params=state.params))
            try:
                restored = ckpt.restore_checkpoint(config.resume, alt)
            except Exception:  # noqa: BLE001 — retry failed for any reason: surface the ORIGINAL error
                raise first_err
            if want_ema:
                print_log("resume checkpoint has no ema_params — re-seeding "
                          "the EMA shadow from the restored params", log)
            else:
                print_log("resume checkpoint carries ema_params but "
                          "ema_decay is off — dropping the shadow", log)
        _check_loaded_params(restored["params"], state.params, config.resume)
        epoch_start = int(restored["epoch"]) + 1
        steps = int(restored["steps"])
        loss_rec = float(restored["loss_rec"])
        best_loss = float(restored["metric"])
        state = state.replace(
            params=restored["params"], opt_state=restored["opt_state"], step=steps,
            **({"ema_params": restored["ema_params"]}
               if want_ema and "ema_params" in restored else {}),
        )
        print_log(f"resuming from epoch {epoch_start:8d} of " + config.resume, log)
        print_log(f"recovering best_loss {best_loss:4f}", log)
    else:
        print_log(f"Date: {asctime()}", log)
        print_log("TrainSet batchs:" + str(train_batches), log)
        print_log("TestSet batchs:" + str(test_batches), log)

    if config.ema_decay and (config.resume == "none"
                             or "ema_params" not in restored):
        # seed the EMA shadow from whatever params the run actually starts
        # with (fresh init, warm-start, or an ema-less resume). jnp.copy, not
        # aliasing: params and ema_params are both donated into the first
        # step, and aliased donated buffers are rejected.
        state = state.replace(
            ema_params=jax.tree.map(jnp.copy, state.params))

    # parallelism-dependent param layout: pipeline shards the stacked blocks
    # over 'pipe'; tensor parallelism shards Megatron column/row kernels over
    # 'model'; pure-dp stays replicated (gradient psum implicit in jit).
    specs, apply_fn = layout_for_mesh(model, mesh, state.params,
                                      n_microbatch=n_micro)
    state = shard_train_state(state, mesh, specs)
    spd = config.steps_per_dispatch
    if (max_steps is not None and spd > 1 and max_steps > steps
            and (max_steps - steps) % spd):
        # the loop advances `steps` in whole dispatches of spd optimizer
        # steps (one compiled lax.scan), so a bound not reachable in whole
        # dispatches FROM THE (possibly resumed) START STEP would silently
        # run up to spd-1 steps past max_steps — and the cosine schedule/
        # checkpoint counters would include them (ADVICE r4). A bench/test
        # comparing against a step-bounded baseline must get the exact step
        # count it asked for, so fail loud instead of rounding.
        raise ValueError(
            f"max_steps={max_steps} is not reachable in whole dispatches of "
            f"steps_per_dispatch={spd} from start step {steps}; the dispatch "
            "granularity makes the bound inexact — use a compatible bound, "
            "or steps_per_dispatch=1")
    train_step = make_train_step(
        model, apply_fn, prepare=prepare,
        ema_decay=config.ema_decay, grad_accum=config.grad_accum,
        moe_aux_weight=(config.moe_aux_weight
                        if config.num_experts > 1 else 0.0),
        steps_per_dispatch=spd)
    eval_step = make_eval_step(model, apply_fn, prepare=eval_prepare)
    writer = ScalarWriter(run_dir)
    step_rng = jax.random.PRNGKey(config.seed + 1)

    if config.nan_checks:
        profiling.enable_nan_checks()
    # step-bounded device trace (SURVEY.md §5: the reference only had
    # wall-clock prints); host 0 traces its own devices
    profiling_until = steps + config.profile_steps if config.profile_steps else 0
    if profiling_until and jax.process_index() == 0:
        profiling.start_trace(os.path.join(run_dir, "trace"))

    vloss = float("nan")
    loss_rec_dev = jnp.float32(loss_rec)
    time_start = time.time()
    done = False
    # the host→device copy of batch n+1 overlaps the compute of batch n —
    # device_put blocks on the upload RPC on network-attached TPU hosts, so
    # an unprefetched loop would serialize transfer and compute
    place = lambda b: shard_batch(b, mesh)  # noqa: E731
    # grouped batches carry a leading scan axis — 'data' shards dim 1 there
    place_train = (lambda b: shard_batch(b, mesh, grouped=True)) if spd > 1 else place
    saver = _AsyncSaver(
        sync=jax.process_count() > 1 or not config.async_checkpoint)
    stopper = _GracefulStop()
    stopper.__enter__()  # released AFTER the finally block below — a signal
    # during the last in-flight checkpoint write must stay graceful too
    try:
        for epoch in range(epoch_start, config.epoch[1]):
            train_loader.set_epoch(epoch)
            # steps_per_dispatch > 1: n batches stack into one dispatch that
            # scans n optimizer steps on device (n× fewer host round trips —
            # the lever on network-attached hosts). Log/stop checks fire on
            # log-window BOUNDARY CROSSINGS, which for spd=1 reduces to the
            # old `steps % log_every == 0`.
            for batch in device_prefetch(
                    group_batches(train_loader, spd) if spd > 1 else train_loader,
                    place_train):
                state, _, loss_rec_dev = train_step(
                    state, batch, step_rng, loss_rec_dev
                )
                prev_steps = steps
                steps += spd
                crossed = steps // log_every > prev_steps // log_every
                if profiling_until and steps >= profiling_until and jax.process_index() == 0:
                    float(loss_rec_dev)  # real D2H drain — block_until_ready can
                    # return early through a remote-TPU tunnel (see bench.py)
                    profiling.stop_trace()
                    profiling_until = 0
                if crossed and jax.process_index() == 0:
                    loss_rec = float(loss_rec_dev)  # the only per-step host sync
                    time_end = time.time()
                    print_log(
                        f"steps: {steps:8d} loss: {loss_rec:.4f} "
                        f"time_cost: {time_end - time_start:.2f}", log)
                    time_start = time.time()
                # consensus check at an aligned loop point (every log window)
                # — gating collectives on the host-local flag would leave
                # only the signaled host's loop, deadlocking the slice
                if crossed and stopper.agreed():
                    done = True
                    if jax.process_index() == 0:
                        print_log(f"stop signal at step {steps:8d} — "
                                  "evaluating, checkpointing, exiting", log)
                    break
                if max_steps is not None and steps >= max_steps:
                    done = True
                    break
            # epoch end is also an aligned loop point every host reaches —
            # without this check a run whose epoch is shorter than log_every
            # ignores a stop signal for ⌈log_every/steps_per_epoch⌉ epochs
            if not done and stopper.agreed():
                done = True
                if jax.process_index() == 0:
                    print_log(f"stop signal at epoch {epoch:4d} end — "
                              "evaluating, checkpointing, exiting", log)
            loss_rec = float(loss_rec_dev)

            # -- evaluate: global-mean loss per batch, mean over batches --------
            # losses stay on device so dispatch pipelines across the val set; the
            # single float() below is the only host sync (the reference's
            # loss.item()-per-batch pattern would idle the TPU between batches)
            test_loader.set_epoch(epoch)
            batch_losses = [
                eval_step(state.params, b) for b in device_prefetch(test_loader, place)
            ]
            vloss = float(jnp.mean(jnp.stack(batch_losses)))

            if jax.process_index() == 0:
                print_log(f"epoch: {epoch:4d}    loss: {vloss:.5f}    time:{asctime()}", log)
                writer.add_scalar("loss", vloss, epoch)
            # orbax writes of sharded global arrays are collective — EVERY process
            # calls save_checkpoint (vloss is a global mean, identical on all
            # hosts, so the branch agrees); only logging and the host-local torch
            # pkl export stay process-0-gated.
            saver.wait()  # at most one epoch's saves in flight
            if saver.sync:
                # synchronous saves finish before the next (donating) step
                params_snap, opt_snap = state.params, state.opt_state
                ema_snap = state.ema_params
            else:
                # snapshot on device: the live buffers are donated to the next
                # train_step, so the async saver must read from its own copy
                params_snap = jax.tree.map(jnp.copy, state.params)
                opt_snap = jax.tree.map(jnp.copy, state.opt_state)
                ema_snap = (jax.tree.map(jnp.copy, state.ema_params)
                            if state.ema_params is not None else None)

            # NaN-safe: a diverged epoch (vloss NaN) compares False and leaves
            # best_loss finite — min() would store NaN and poison resume
            improved = vloss < best_loss
            if improved:
                best_loss = vloss

            def save_epoch(epoch=epoch, steps=steps, loss_rec=loss_rec,
                           improved=improved, best=best_loss,
                           params=params_snap, opt_state=opt_snap,
                           ema=ema_snap):
                if improved:
                    ckpt.save_checkpoint(os.path.join(run_dir, "bestloss.ckpt"), params)
                    if ema is not None:
                        # the smoothed weights diffusion users actually sample
                        # from; saved beside (never instead of) the live best
                        ckpt.save_checkpoint(
                            os.path.join(run_dir, "bestloss_ema.ckpt"), ema)
                    if (jax.process_index() == 0 and _fully_addressable(params)
                            and config.num_experts == 1):
                        # (MoE params have no reference torch layout — the
                        # bridge refuses them, so don't retry every epoch)
                        # best-effort bridge export (torch-less hosts fall
                        # back to the native writer internally): a refused
                        # export must never kill the run at its best-loss
                        # moment — the orbax ckpt above is already safe
                        try:
                            ckpt.save_torch_pkl(params,
                                                os.path.join(run_dir, "bestloss.pkl"),
                                                config.patch_size)
                            if ema is not None:  # reference-bridge export of
                                ckpt.save_torch_pkl(  # the smoothed weights
                                    ema,
                                    os.path.join(run_dir, "bestloss_ema.pkl"),
                                    config.patch_size)
                        except Exception as e:  # noqa: BLE001
                            print_log(f"bestloss pkl export skipped: {e}", log)
                if config.snapshot_epochs and epoch % config.snapshot_epochs == 0:
                    # bare-params snapshot for the FID trend
                    # (scripts/fid_trend.py); keyed by epoch, never rewritten.
                    # With EMA on, the smoothed weights land beside as
                    # epoch_<E>_ema (the trend's strict epoch_(\d+) match
                    # keeps its raw-params series uncontaminated).
                    snap_dir = os.path.join(run_dir, "snapshots")
                    os.makedirs(snap_dir, exist_ok=True)
                    ckpt.save_checkpoint(
                        os.path.join(snap_dir, f"epoch_{epoch}"), params)
                    if ema is not None:
                        ckpt.save_checkpoint(
                            os.path.join(snap_dir, f"epoch_{epoch}_ema"), ema)
                ckpt.save_checkpoint(
                    os.path.join(run_dir, "lastepoch.ckpt"),
                    {"epoch": epoch, "steps": steps, "loss_rec": loss_rec,
                     "metric": best, "params": params,
                     "opt_state": opt_state,
                     **({"ema_params": ema} if ema is not None else {})},
                )

            saver.submit(save_epoch)
            if done:
                break
    finally:
        # every cleanup step must run even when an earlier one raises: an
        # abandoned in-flight checkpoint write (saver.wait skipped) loses the
        # final epoch, and a leaked signal handler outlives run()
        try:
            try:
                if profiling_until and jax.process_index() == 0:
                    profiling.stop_trace()  # run ended inside the trace window
            finally:
                writer.close()
        finally:
            try:
                saver.wait()
            finally:
                # hand signals back LAST — a SIGTERM during the waits above
                # stayed graceful (second signal escalates to immediate kill)
                stopper.__exit__()
    return TrainResult(best_loss=best_loss, last_val_loss=vloss, steps=steps,
                       run_dir=run_dir)
