"""Experiment configuration — the reference's flat-YAML schema, preserved.

Schema (20220822.yaml:1-15): ``initializing, resume, AMP, framework,
num_gpus, batch_size, epoch: [start, end], base_lr, dataStorage: [train, val],
image_size, diff_step, patch_size, embed_dim, depth, head``.

Derived-value rules are part of the observable behavior (SURVEY.md quirk #7)
and replicated exactly (multi_gpu_trainer.py:191-196):

* AMP doubles the per-device batch (AMP ⇒ bf16 compute on TPU — no GradScaler
  needed, loss scaling is a float16 artifact);
* lr = base_lr · batch · num_devices / 512.

``num_gpus`` is retained as the device-count key (it now counts TPU chips in
the 'data' mesh axis); ``num_devices`` is accepted as an alias. ``diff_step``
is honored — passed to the model as total_steps when ``honor_diff_step`` is
set; by default it is recorded but the time-embedding table stays at 2000 rows
for checkpoint compatibility (SURVEY.md quirk #4: the reference reads the key
but never forwards it, multi_gpu_trainer.py:206 vs ViT.py:162).

New optional keys (defaulted so reference YAMLs run unchanged):
``dataset`` (cold | cold_direct | gaussian — the trainer hardwires cold,
multi_gpu_trainer.py:5,59), ``seed``, ``honor_diff_step``, ``mesh`` (axis
sizes for multi-chip layouts, e.g. ``{data: 4, model: 2}``), ``use_flash``
(Pallas fused attention, recommended for the 200px configs),
``use_sincos_pos`` (fixed sinusoidal positional table, C7), ``remat``
(gradient checkpointing per block — HBM for FLOPs on big configs),
``profile_steps`` (device-trace the first N steps into ``<run_dir>/trace``)
and ``nan_checks`` (``jax_debug_nans`` for the run). A ``seq`` axis in
``mesh`` (e.g. ``{data: 4, seq: 2}``) turns on sequence
parallelism — ``sp_mode`` selects the strategy: ``ring`` (K/V rotation,
default, parallel/ring_attention.py) or ``ulysses`` (all-to-all head
resharding, parallel/ulysses.py; local heads — num_heads over any tp
axis — must divide the seq axis)
parallelism (parallel/ring_attention.py); a ``pipe`` axis (with optional
``microbatches``) turns on GPipe pipeline parallelism over the stacked
``scan_blocks`` layout (parallel/pipeline.py).
"""

from __future__ import annotations

import dataclasses
import os
from typing import Any, Optional

import yaml


@dataclasses.dataclass
class ExperimentConfig:
    exp_name: str
    initializing: str = "none"
    resume: str = "none"
    amp: bool = False
    framework: str = "experiment"
    num_devices: int = 1
    batch_size: int = 16
    epoch: tuple[int, int] = (0, 100)
    base_lr: float = 0.005
    data_storage: tuple[str, str] = ("", "")
    image_size: tuple[int, int] = (64, 64)
    diff_step: int = 2000
    patch_size: int = 8
    embed_dim: int = 384
    depth: int = 7
    head: int = 12
    dataset: str = "cold"
    seed: int = 42
    honor_diff_step: bool = False
    mesh: Optional[dict[str, int]] = None
    use_flash: "bool | str" = False  # False | True (Pallas) | "xla" (blockwise)
    # Pallas kernel (block_q, block_kv) override; None = kernel defaults.
    # The bench's --flash-block-sweep measures candidates — pin its winner
    # here (e.g. ``flash_blocks: [512, 1024]`` in the 200px yaml).
    flash_blocks: Optional[tuple] = None
    use_sincos_pos: bool = False
    sp_mode: str = "ring"  # seq-parallel strategy: ring | ulysses
    remat: bool = False
    profile_steps: int = 0  # trace this many early steps into <run_dir>/trace
    nan_checks: bool = False  # jax_debug_nans for the whole run
    cache_images: object = None  # None=auto (fits 2GB), True/False=force
    # device-side corruption: ship clean bases, corrupt in-jit. Cold datasets:
    # bit-identical gathers (tests/test_device_path.py), both loaders.
    # Gaussian: device-drawn ε, train loader only (val stays host-exact).
    # 2-8× less host→device traffic; False forces the host/C++ pipeline.
    device_degrade: bool = True
    # overlap epoch-end checkpoint writes with the next epoch's compute (costs
    # one transient on-device params+opt_state copy); multi-host runs are
    # always synchronous (collective orbax writes must not be reordered)
    async_checkpoint: bool = True
    scan_blocks: bool = False  # nn.scan over depth (stacked params)
    microbatches: Optional[int] = None  # pipeline microbatches (default 2·pipe)
    # every N epochs, additionally save params to <run>/snapshots/epoch_<E>/ —
    # feeds the per-checkpoint FID trend (scripts/fid_trend.py); 0 = off
    snapshot_epochs: int = 0
    # split each optimizer step's batch into N sequential micro-slices with
    # averaged gradients (one lax.scan in the jitted step) — the standard
    # big-batch-on-small-HBM tool, absent upstream. 1 = off. Same math as
    # the unaccumulated step (dropout gets per-slice keys); peak activation
    # memory drops ~N×. Not composable with a pipe mesh axis (the pipeline
    # has its own microbatching).
    grad_accum: int = 1
    # stack N successive batches into ONE dispatch that lax.scans N full
    # optimizer steps on device — N× fewer host↔device round trips and N×
    # larger transfers, the lever when the device is network-attached
    # (remote-TPU tunnel, DCN-fed host). 1 = off (parity default). Identical
    # per-step math (rng folds key off state.step, which advances inside the
    # scan). Epoch tails shorter than N are dropped (drop_last semantics),
    # and train.log `steps:` lines land on log-window boundary crossings.
    steps_per_dispatch: int = 1
    # EMA shadow of the params (standard diffusion practice, absent upstream):
    # 0 = off (default, byte-identical to the reference behavior); e.g. 0.999
    # maintains ema ← d·ema + (1−d)·p each step, checkpointed alongside the
    # live params (bestloss_ema.ckpt + ema_params in lastepoch.ckpt)
    ema_decay: float = 0.0
    # Switch-MoE (models/moe.py): >1 swaps each block's MLP for a top-1
    # routed expert bank whose stacked params shard over an 'expert' mesh
    # axis — the ep counterpart to mesh's data/model/seq/pipe. 1 = off.
    num_experts: int = 1
    moe_capacity_factor: float = 1.25  # per-expert queue: ceil(N·cf/E)
    moe_aux_weight: float = 0.01  # Switch load-balance loss coefficient
    # routing implementation (models/moe.py): "einsum" = one-hot GEMM
    # dispatch (XLA-friendliest, O(N²·cf) activations); "index" =
    # sort/gather dispatch (O(N·cf·D)) for long-sequence configs
    moe_dispatch: str = "einsum"

    @property
    def effective_batch(self) -> int:
        """AMP doubles the batch (multi_gpu_trainer.py:191-194)."""
        return self.batch_size * 2 if self.amp else self.batch_size

    @property
    def data_parallel_size(self) -> int:
        """Devices the batch is split over: mesh['data'] when an explicit mesh
        is configured, else num_devices (the pure-dp default)."""
        if self.mesh:
            return int(self.mesh.get("data", 1))
        return self.num_devices

    @property
    def lr(self) -> float:
        """base_lr · batch · dp-world / 512 (multi_gpu_trainer.py:196).

        The reference's ``num_gpus`` IS its dp world size; with an explicit
        mesh the dp world is mesh['data'], keeping lr tied to the global batch
        actually trained."""
        return self.base_lr * self.effective_batch * self.data_parallel_size / 512.0

    @property
    def total_steps(self) -> int:
        """Model time-embedding rows: 2000 unless diff_step is honored."""
        return self.diff_step if self.honor_diff_step else 2000

    @property
    def run_name(self) -> str:
        """Run dir name = <ExpName><framework> (multi_gpu_trainer.py:198)."""
        return f"{self.exp_name}{self.framework}"

    def model_kwargs(self) -> dict[str, Any]:
        return dict(
            img_size=tuple(self.image_size),
            patch_size=self.patch_size,
            embed_dim=self.embed_dim,
            depth=self.depth,
            num_heads=self.head,
            total_steps=self.total_steps,
            use_flash=self.use_flash,
            flash_blocks=self.flash_blocks,
            use_sincos_pos=self.use_sincos_pos,
            remat=self.remat,
            scan_blocks=self.scan_blocks,
            num_experts=self.num_experts,
            moe_capacity_factor=self.moe_capacity_factor,
            moe_dispatch=self.moe_dispatch,
        )


def _check_flash_blocks(value, use_flash):
    if value is None:
        return None
    if use_flash is False:
        # the same silent-misconfiguration class the unknown-key check
        # kills: a tuned pair pinned in the yaml with use_flash unset would
        # validate, thread through model_kwargs, and then attend DENSE
        raise ValueError(
            "flash_blocks is set but use_flash is false — the blocks would "
            "be silently ignored; set use_flash: true (or 'xla', which "
            "uses only the block_kv half)")
    try:
        bq, bkv = (int(v) for v in value)
    except (TypeError, ValueError):
        raise ValueError(
            f"flash_blocks must be a [block_q, block_kv] pair, got {value!r}")
    if bq < 1 or bkv < 1:
        raise ValueError(f"flash_blocks must be positive, got {value!r}")
    return (bq, bkv)


def _check_use_flash(value):
    # YAML surface: false | true (Pallas kernel) | "xla" (pure-XLA blockwise)
    if isinstance(value, str):
        if value.lower() == "xla":
            return "xla"
        if value.lower() in ("pallas", "true"):
            return True
        if value.lower() in ("false", "none", ""):
            return False
        raise ValueError(
            f"use_flash must be true/false/'xla'/'pallas', got {value!r}")
    return bool(value)


def _check_sp_mode(value: str) -> str:
    if value not in ("ring", "ulysses"):
        raise ValueError(f"sp_mode must be 'ring' or 'ulysses', got {value!r}")
    return value


def _check_grad_accum(value: int) -> int:
    if value < 1:
        raise ValueError(f"grad_accum must be >= 1, got {value!r}")
    return value


def _check_num_experts(value: int) -> int:
    if value < 1:
        raise ValueError(f"num_experts must be >= 1, got {value!r}")
    return value


def _check_moe_capacity(value: float) -> float:
    # cf ≤ 0 clamps every expert queue to one token: nearly all tokens
    # overflow onto the residual and the MoE silently contributes nothing
    if value <= 0.0:
        raise ValueError(f"moe_capacity_factor must be > 0, got {value!r}")
    return value


def _check_moe_aux(value: float) -> float:
    if value < 0.0:  # negative would actively REWARD routing imbalance
        raise ValueError(f"moe_aux_weight must be >= 0, got {value!r}")
    return value


def _check_moe_dispatch(value: str) -> str:
    if value not in ("einsum", "index"):
        raise ValueError(
            f"moe_dispatch must be 'einsum' or 'index', got {value!r}")
    return value


def _check_steps_per_dispatch(value: int) -> int:
    if value < 1:
        raise ValueError(f"steps_per_dispatch must be >= 1, got {value!r}")
    return value


def _check_ema_decay(value: float) -> float:
    # d=1.0 freezes the shadow at init forever; d>1 diverges to NaN within
    # steps and the damage only surfaces at sampling time — fail loudly here
    if not 0.0 <= value < 1.0:
        raise ValueError(f"ema_decay must be in [0, 1), got {value!r}")
    return value


#: every key load_config reads, including the reference-schema aliases —
#: anything else in the YAML is a typo and must fail loud: this loader is
#: .get()-based, so an unknown key (`use_flahs: true`, `scan_block: true`)
#: would otherwise be silently ignored and the run silently misconfigured
_KNOWN_KEYS = frozenset({
    "initializing", "resume", "AMP", "amp", "framework", "num_devices",
    "num_gpus", "batch_size", "epoch", "base_lr", "dataStorage",
    "image_size", "diff_step", "patch_size", "embed_dim", "depth", "head",
    "dataset", "seed", "honor_diff_step", "mesh", "use_flash", "flash_blocks",
    "use_sincos_pos", "sp_mode", "remat", "profile_steps", "nan_checks",
    "cache_images", "device_degrade", "async_checkpoint", "scan_blocks",
    "microbatches", "snapshot_epochs", "ema_decay", "num_experts",
    "moe_capacity_factor", "moe_aux_weight", "moe_dispatch", "grad_accum",
    "steps_per_dispatch",
})


def load_config(yaml_path: str, exp_name: Optional[str] = None) -> ExperimentConfig:
    """Parse a reference-schema YAML into an ExperimentConfig."""
    with open(yaml_path) as f:
        raw = yaml.safe_load(f)
    unknown = sorted(set(raw) - _KNOWN_KEYS)
    if unknown:
        import difflib

        hints = []
        for k in unknown:
            close = difflib.get_close_matches(k, _KNOWN_KEYS, n=1)
            hints.append(f"{k!r}" + (f" (did you mean {close[0]!r}?)"
                                     if close else ""))
        raise ValueError(
            f"{yaml_path}: unknown config key(s) {', '.join(hints)} — "
            "a misspelled key would be silently ignored and the run "
            "silently misconfigured; remove or fix it")
    name = exp_name or os.path.splitext(os.path.basename(yaml_path))[0]
    epoch = raw.get("epoch", [0, 100])
    return ExperimentConfig(
        exp_name=name,
        initializing=raw.get("initializing", "none"),
        resume=raw.get("resume", "none"),
        amp=bool(raw.get("AMP", raw.get("amp", False))),
        framework=raw.get("framework", "experiment"),
        num_devices=int(raw.get("num_devices", raw.get("num_gpus", 1))),
        batch_size=int(raw.get("batch_size", 16)),
        epoch=(int(epoch[0]), int(epoch[1])),
        base_lr=float(raw.get("base_lr", 0.005)),
        data_storage=tuple(raw.get("dataStorage", ["", ""])),
        image_size=tuple(raw.get("image_size", [64, 64])),
        diff_step=int(raw.get("diff_step", 2000)),
        patch_size=int(raw.get("patch_size", 8)),
        embed_dim=int(raw.get("embed_dim", 384)),
        depth=int(raw.get("depth", 7)),
        head=int(raw.get("head", 12)),
        dataset=raw.get("dataset", "cold"),
        seed=int(raw.get("seed", 42)),
        honor_diff_step=bool(raw.get("honor_diff_step", False)),
        mesh=raw.get("mesh"),
        use_flash=_check_use_flash(raw.get("use_flash", False)),
        flash_blocks=_check_flash_blocks(
            raw.get("flash_blocks"),
            _check_use_flash(raw.get("use_flash", False))),
        use_sincos_pos=bool(raw.get("use_sincos_pos", False)),
        sp_mode=_check_sp_mode(raw.get("sp_mode", "ring")),
        remat=bool(raw.get("remat", False)),
        profile_steps=int(raw.get("profile_steps", 0)),
        nan_checks=bool(raw.get("nan_checks", False)),
        cache_images=raw.get("cache_images"),
        device_degrade=bool(raw.get("device_degrade", True)),
        async_checkpoint=bool(raw.get("async_checkpoint", True)),
        scan_blocks=bool(raw.get("scan_blocks", False)),
        microbatches=(int(raw["microbatches"]) if "microbatches" in raw else None),
        snapshot_epochs=int(raw.get("snapshot_epochs", 0)),
        ema_decay=_check_ema_decay(float(raw.get("ema_decay", 0.0))),
        num_experts=_check_num_experts(int(raw.get("num_experts", 1))),
        moe_capacity_factor=_check_moe_capacity(
            float(raw.get("moe_capacity_factor", 1.25))),
        moe_aux_weight=_check_moe_aux(float(raw.get("moe_aux_weight", 0.01))),
        moe_dispatch=_check_moe_dispatch(raw.get("moe_dispatch", "einsum")),
        grad_accum=_check_grad_accum(int(raw.get("grad_accum", 1))),
        steps_per_dispatch=_check_steps_per_dispatch(
            int(raw.get("steps_per_dispatch", 1))),
    )
