"""Deterministic, seeded fault injection — the chaos half of the serving
robustness layer.

The serving engine (serve/engine.py), the checkpoint writer
(utils/checkpoint.py) and the data loader (data/loader.py) each call
:func:`fire` at their named fault sites. With nothing armed, ``fire`` is a
flag check and a dict read — the fast path executes byte-identical device
code and the bench's faults-disarmed leg pins zero throughput overhead.
Armed (a scoped :func:`inject` context or the ``DDIM_COLD_FAULTS`` env var),
each matching spec draws from its OWN seeded RNG on a per-site call counter,
so a chaos run's injection sequence is a pure function of (specs, call
order) — and since every site is fired from a deterministic thread (the
engine's single assembly thread, the single dispatch thread), the whole run
replays.

Every realized injection is recorded in the active :class:`FaultPlan`;
``plan.replay()`` converts the record into ``at=`` specs that re-fire at
exactly the same (site, call-index) points, so any chaos failure is
reproducible without re-rolling the dice (corrupt element choice is re-drawn
from the spec seed on replay; the schedule — which calls fire which kinds —
is exact).

Spec grammar (env var / :func:`parse_specs`), specs joined by ``;``::

    site:kind[:key=value[,key=value...]]
    DDIM_COLD_FAULTS="serve.dispatch:transient:rate=0.2,seed=7;serve.fetch:latency:latency_s=0.05"

Kinds: ``transient`` raises :class:`TransientFault` (the retryable
transfer/RPC class — the engine backs off and retries), ``permanent``
raises :class:`PermanentFault` (deterministic — the engine bisects the
batch and quarantines the poisoned request), ``latency`` sleeps
``latency_s``, ``corrupt`` flips one element of the call's payload buffer
(NaN for float dtypes) chosen by the spec's RNG.

Process-level kinds (the out-of-process fleet's chaos surface —
serve/remote.py + serve/replica_main.py): ``kill`` SIGKILLs the CALLING
process (fired inside a replica server it is the no-warning crash the
RPC handle's crash detection must catch), ``hang`` sleeps ``hang_s``
(default effectively forever — the wedged-replica case a heartbeat miss
budget retires). The matching sites are ``replica.kill`` /
``replica.hang`` (fired by the replica server per request) and
``rpc.drop`` / ``rpc.latency`` (fired by the client around every frame
send, so a chaos schedule can break the wire itself).
"""

from __future__ import annotations

import os
import signal
import threading
import time
from contextlib import contextmanager
from dataclasses import dataclass, replace
from typing import Optional, Sequence

import numpy as np

from ddim_cold_tpu.obs import metrics as _obs_metrics

ENV_VAR = "DDIM_COLD_FAULTS"

#: realized injections land in the obs registry keyed by site, so a chaos
#: run's fault pressure shows up next to the serving counters it perturbs
_METRICS = _obs_metrics.scope("faults")

#: the named fault sites (typo guard for specs; ``fire`` itself accepts any
#: string so a site can be added where it is fired before it is listed here)
SITES = ("serve.assemble", "serve.dispatch", "serve.fetch", "serve.compile",
         "serve.preview",
         "ckpt.save", "data.next",
         "router.place", "router.failover", "replica.spawn",
         # the process boundary (serve/remote.py + serve/replica_main.py):
         # fired server-side per work request (kill/hang) and client-side
         # around every RPC frame (drop/latency)
         "replica.kill", "replica.hang", "rpc.drop", "rpc.latency")
KINDS = ("transient", "permanent", "latency", "corrupt", "kill", "hang")


class FaultError(Exception):
    """Base class of every injected fault."""


class TransientFault(FaultError):
    """Injected retryable fault (the transfer/RPC failure class)."""


class PermanentFault(FaultError):
    """Injected deterministic fault (fails every retry the same way)."""


#: What each raising kind throws (``latency``/``corrupt`` never raise).
#: serve/errors.py derives RETRYABLE_EXCEPTIONS from TRANSIENT_EXCEPTIONS so
#: a new retryable kind added here cannot silently become non-retryable —
#: tests/test_faults.py pins the two tables against each other.
KIND_EXCEPTIONS: dict = {"transient": TransientFault,
                         "permanent": PermanentFault}

#: The transient (retry-recoverable) fault classes this module can raise.
TRANSIENT_EXCEPTIONS: tuple = (TransientFault,)


@dataclass(frozen=True)
class FaultSpec:
    """One armed fault: where, what, and on which seeded schedule.

    ``rate`` is the per-eligible-call injection probability drawn from a
    ``RandomState(seed)`` private to this spec; ``at`` overrides the dice
    with explicit site call indices (the replay path). ``match`` restricts
    eligibility to calls whose tag contains the substring (tags use
    ``|``-separated ``key:value`` fields — e.g. ``req:3|`` targets one
    request). ``max_fires`` caps total injections.
    """

    site: str
    kind: str = "transient"
    rate: float = 1.0
    seed: int = 0
    latency_s: float = 0.05
    #: ``hang`` kind only: how long the hung call sleeps. The default is
    #: "longer than any heartbeat budget" — a hang is a wedge, not a blip.
    hang_s: float = 3600.0
    max_fires: Optional[int] = None
    match: Optional[str] = None
    at: Optional[tuple] = None

    def __post_init__(self):
        if self.site not in SITES:
            raise ValueError(f"unknown fault site {self.site!r} "
                             f"(known: {SITES})")
        if self.kind not in KINDS:
            raise ValueError(f"kind must be one of {KINDS}, got {self.kind!r}")
        if not 0.0 <= self.rate <= 1.0:
            raise ValueError(f"rate must be in [0, 1], got {self.rate}")
        if self.at is not None:
            object.__setattr__(self, "at", tuple(int(i) for i in self.at))


class FaultPlan:
    """The realized injections of one armed scope.

    ``realized`` is a list of JSON-able dicts ``{site, call, tag, kind,
    spec}`` in injection order (``spec`` indexes the plan's spec table);
    :meth:`replay` turns it back into specs that re-fire identically.
    """

    def __init__(self):
        self._specs: list[FaultSpec] = []
        self.realized: list[dict] = []

    def _record(self, site, call, tag, spec, detail=None):
        try:
            idx = next(i for i, s in enumerate(self._specs) if s is spec)
        except StopIteration:
            self._specs.append(spec)
            idx = len(self._specs) - 1
        entry = {"site": site, "call": call, "tag": tag,
                 "kind": spec.kind, "spec": idx}
        if detail:
            entry["detail"] = detail
        self.realized.append(entry)

    def by_site(self) -> dict:
        out: dict[str, int] = {}
        for r in self.realized:
            out[r["site"]] = out.get(r["site"], 0) + 1
        return out

    def replay(self) -> tuple:
        """Specs that reproduce this plan's schedule exactly: every fired
        (site, call) becomes an ``at=`` entry; the dice are retired."""
        calls: dict[int, list] = {}
        for r in self.realized:
            calls.setdefault(r["spec"], []).append(r["call"])
        return tuple(
            replace(self._specs[i], at=tuple(sorted(set(cs))),
                    rate=1.0, match=None, max_fires=None)
            for i, cs in sorted(calls.items()))


class _Armed:
    """Per-spec live state: the private RNG and the fire count."""

    __slots__ = ("spec", "rng", "fires")

    def __init__(self, spec: FaultSpec):
        self.spec = spec
        self.rng = np.random.RandomState(spec.seed)
        self.fires = 0


_lock = threading.RLock()
_armed: list = []                                       # guarded-by: _lock
_calls: dict = {}                                       # guarded-by: _lock
_plan: Optional[FaultPlan] = None                       # guarded-by: _lock
_env_checked = False                                    # guarded-by: _lock


def active() -> bool:
    return bool(_armed)


def current_plan() -> Optional[FaultPlan]:
    return _plan


def snapshot() -> dict:
    """Health-report view: armed spec count and realized injections by site
    (what engine.health() surfaces as ``faults_by_site``)."""
    with _lock:
        plan = _plan
        return {
            "armed": len(_armed),
            "injected": len(plan.realized) if plan else 0,
            "by_site": plan.by_site() if plan else {},
        }


def _arm(specs: Sequence[FaultSpec]):
    global _plan
    with _lock:
        if _plan is None:
            _plan = FaultPlan()
            _calls.clear()
        handles = [_Armed(s) for s in specs]
        _armed.extend(handles)
        return handles, _plan


def _disarm(handles) -> None:
    global _plan
    with _lock:
        for h in handles:
            _armed.remove(h)
        if not _armed:
            _plan = None
            _calls.clear()


@contextmanager
def inject(*specs: FaultSpec):
    """Arm ``specs`` for the scope; yields the live :class:`FaultPlan`.
    Scopes stack (an inner scope adds specs); call counters and the plan
    reset only when the LAST scope exits, so nested determinism holds."""
    handles, plan = _arm(specs)
    try:
        yield plan
    finally:
        _disarm(handles)


def arm_from_env() -> Optional[FaultPlan]:
    """Arm the ``DDIM_COLD_FAULTS`` specs for the process lifetime (no
    scope). Called lazily by the first :func:`fire`; safe to call directly.
    Returns the plan, or None when the env var is unset/empty."""
    global _env_checked
    with _lock:
        if _env_checked:
            return _plan
        _env_checked = True
    text = os.environ.get(ENV_VAR, "").strip()
    if not text:
        return None
    _, plan = _arm(parse_specs(text))
    return plan


def fire(site: str, tag: str = "", payload=None):
    """The fault point. Returns ``payload`` (possibly corrupted); may sleep
    or raise per the armed specs. Near-free when disarmed."""
    if not _env_checked:
        arm_from_env()
    if not _armed:
        return payload
    return _fire(site, tag, payload)


def _fire(site: str, tag: str, payload):
    fired = []
    with _lock:
        call = _calls.get(site, 0)
        _calls[site] = call + 1
        plan = _plan
        for armed in _armed:
            spec = armed.spec
            if spec.site != site:
                continue
            if spec.match is not None and spec.match not in tag:
                continue
            if spec.at is not None:
                hit = call in spec.at
            else:
                hit = bool(armed.rng.random_sample() < spec.rate)
            if not hit:
                continue
            if spec.max_fires is not None and armed.fires >= spec.max_fires:
                continue
            armed.fires += 1
            detail = None
            if spec.kind == "corrupt" and isinstance(payload, np.ndarray) \
                    and payload.size:
                idx = int(armed.rng.randint(payload.size))
                payload = np.array(payload)  # never corrupt the caller's copy
                flat = payload.reshape(-1)
                if np.issubdtype(payload.dtype, np.floating):
                    flat[idx] = np.nan
                elif payload.dtype != np.bool_:
                    flat[idx] = np.iinfo(payload.dtype).max
                else:
                    flat[idx] = not flat[idx]
                detail = {"index": idx}
            plan._record(site, call, tag, spec, detail)
            fired.append((spec, call))
    if fired:
        _METRICS.inc("faults.injected", len(fired), key=site)
    for spec, _ in fired:
        if spec.kind == "latency":
            time.sleep(spec.latency_s)
    for spec, _ in fired:
        if spec.kind == "hang":
            time.sleep(spec.hang_s)
    for spec, _ in fired:
        if spec.kind == "kill":
            # the no-warning crash: the process dies HERE, mid-request —
            # nothing after this line runs, no socket close, no drain
            os.kill(os.getpid(), signal.SIGKILL)
    for spec, at_call in fired:
        if spec.kind == "transient":
            raise TransientFault(
                f"injected transient fault at {site}[{at_call}] "
                f"(seed={spec.seed}, tag={tag!r})")
    for spec, at_call in fired:
        if spec.kind == "permanent":
            raise PermanentFault(
                f"injected permanent fault at {site}[{at_call}] "
                f"(seed={spec.seed}, tag={tag!r})")
    return payload


def parse_specs(text: str) -> tuple:
    """Parse the ``site:kind[:k=v,...]`` grammar (``;``-joined specs) —
    the env-var form of :class:`FaultSpec`."""
    specs = []
    for part in text.split(";"):
        part = part.strip()
        if not part:
            continue
        bits = part.split(":", 2)
        if len(bits) < 2:
            raise ValueError(f"fault spec needs site:kind, got {part!r}")
        kw: dict = {"site": bits[0].strip(), "kind": bits[1].strip()}
        if len(bits) == 3 and bits[2].strip():
            for item in bits[2].split(","):
                k, _, v = item.partition("=")
                k, v = k.strip(), v.strip()
                if k in ("rate", "latency_s", "hang_s"):
                    kw[k] = float(v)
                elif k in ("seed", "max_fires"):
                    kw[k] = int(v)
                elif k == "match":
                    kw[k] = v
                elif k == "at":
                    kw[k] = tuple(int(x) for x in v.split("+"))
                else:
                    raise ValueError(f"unknown fault spec key {k!r} in {part!r}")
        specs.append(FaultSpec(**kw))
    return tuple(specs)
