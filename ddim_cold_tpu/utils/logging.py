"""Logging/metrics — train.log is the parity artifact (SURVEY.md C21).

``print_log`` reproduces the reference's append-only logger
(multi_gpu_trainer.py:18-23) and the trainer emits the same line formats:

    Date: <asctime>
    TrainSet batchs:<n> / TestSet batchs:<n>
    steps: {steps:8d} loss: {ema:.4f} time_cost: {secs:.2f}
    epoch: {epoch:4d}    loss: {vloss:.5f}    time:<asctime>

``ScalarWriter`` replaces the rank-0 TensorBoard writer
(multi_gpu_trainer.py:15,108,151): it uses tensorboard when importable and
always appends machine-readable ``metrics.jsonl`` next to the log (so headless
TPU runs keep observability without the TB dependency).
"""

from __future__ import annotations

import json
import os
import time


def print_log(string: str, file_name: str) -> int:
    """Append one line (reference printLog, multi_gpu_trainer.py:18-23)."""
    with open(file_name, "a") as f:
        f.write(string + "\n")
    return 0


def asctime() -> str:
    return time.asctime(time.localtime(time.time()))


class ScalarWriter:
    """add_scalar → metrics.jsonl (always) + TensorBoard (when available)."""

    def __init__(self, log_dir: str):
        self.log_dir = log_dir
        os.makedirs(log_dir, exist_ok=True)
        self.jsonl_path = os.path.join(log_dir, "metrics.jsonl")
        self._tb = None
        try:  # torch's SummaryWriter needs the tensorboard package
            from torch.utils.tensorboard import SummaryWriter

            self._tb = SummaryWriter(log_dir=log_dir)
        except Exception:  # noqa: BLE001 — optional dep: import OR construction may fail many ways; jsonl logging carries on
            self._tb = None

    def add_scalar(self, tag: str, value: float, step: int) -> None:
        with open(self.jsonl_path, "a") as f:
            f.write(json.dumps({"tag": tag, "value": float(value), "step": int(step),
                                "time": time.time()}) + "\n")
        if self._tb is not None:
            self._tb.add_scalar(tag, value, step)

    def close(self) -> None:
        if self._tb is not None:
            self._tb.close()
