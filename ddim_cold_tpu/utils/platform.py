"""Make the ``JAX_PLATFORMS`` env var authoritative for our entry points.

Some deployments (e.g. the axon TPU-tunnel image this framework is benched
on) inject a site hook that pins ``jax_platforms`` programmatically, which
silently overrides the env var — a user running ``JAX_PLATFORMS=cpu python
multi_gpu_trainer.py …`` would still dial the TPU. Every CLI in this repo
calls :func:`honor_env_platform` before its first device query so the env var
behaves the way the JAX docs say it does.
"""

from __future__ import annotations

import os


def honor_env_platform() -> None:
    """Re-apply ``JAX_PLATFORMS`` over any site-config pin.

    No-op when the env var is unset or the configured first-choice platform
    already matches (so the site's own ``axon,cpu`` fallback list survives a
    redundant ``JAX_PLATFORMS=axon``)."""
    want = os.environ.get("JAX_PLATFORMS", "").strip()
    if not want:
        return
    import jax

    current = jax.config.jax_platforms or ""
    if current.split(",")[0].strip() == want.split(",")[0].strip():
        return
    jax.config.update("jax_platforms", want)


def require_accelerator_or_exit(attempts: int = 1) -> None:
    """CLI guard for accelerator-intended runs: bound the first backend init
    (a wedged remote-TPU tunnel blocks ``jax.devices()`` FOREVER — an
    unguarded CLI strands any unattended chain that invoked it), and if an
    accelerator was configured but is unreachable, exit 3 with an actionable
    message instead of silently degrading a production run to one CPU core.
    CPU-pinned invocations (``JAX_PLATFORMS=cpu`` / ``--cpu``) skip the
    probe entirely and are unaffected — deliberate CPU use stays first-class
    (the whole test suite runs that way).

    ``attempts=1`` deliberately (vs bench's 3-with-backoff budget): exit-3
    callers lose nothing by failing after one bounded probe — a recovery
    watcher re-arms them — where the bench's CPU fallback would lose the
    round's hardware record.
    """
    # coordinated multi-host launch: backend init requires ALL hosts to
    # rendezvous, so a lone probe subprocess would time out on perfectly
    # healthy hardware — the guard targets the single-host wedged-tunnel
    # case and must stand down here. TPU_WORKER_HOSTNAMES counts only when
    # it actually lists multiple workers: single-host sites (the axon
    # tunnel image among them) set it to 'localhost'.
    if any(os.environ.get(v) for v in
           ("JAX_COORDINATOR_ADDRESS", "COORDINATOR_ADDRESS",
            "MEGASCALE_COORDINATOR_ADDRESS")):
        return
    if "," in os.environ.get("TPU_WORKER_HOSTNAMES", ""):
        return
    plat, reason = ensure_live_backend(attempts=attempts)
    if plat == "cpu":
        import sys

        print(
            f"ERROR: configured accelerator backend unreachable ({reason}); "
            "set JAX_PLATFORMS=cpu (or pass --cpu where available) to run "
            "on CPU deliberately", file=sys.stderr)
        raise SystemExit(3)


def enable_compile_cache(path: str | None = None) -> str | None:
    """Point JAX's persistent compilation cache at a repo-local directory.

    The test suite has used this for two rounds (tests/conftest.py) and it
    turns every repeat compile into a disk read; the trainer and bench now
    wire it by default so a real run's first step doesn't re-pay XLA
    compilation the suite already proved cacheable (VERDICT r3 weak #2: the
    ~35–40 s cold-start compile erased the steady-state win on short runs).

    ``DDIM_COLD_COMPILE_CACHE`` overrides the location; ``0``/``off``/``none``
    disables. Returns the active cache dir, or None when disabled/failed
    (cache failure must never take down a run — it is purely an accelerant).
    """
    env = os.environ.get("DDIM_COLD_COMPILE_CACHE", "").strip()
    if env.lower() in ("0", "off", "none"):
        return None
    if path is None:
        path = env or os.path.join(
            os.path.dirname(os.path.dirname(os.path.dirname(
                os.path.abspath(__file__)))), ".jax_cache")
    try:
        os.makedirs(path, exist_ok=True)
        import jax

        jax.config.update("jax_compilation_cache_dir", path)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
    except Exception:  # noqa: BLE001 — best-effort accelerant only
        return None
    return path


#: default probe body: apply the parent's effective platform choice (passed
#: via env — the probe's own site hooks would otherwise re-pin it), then
#: force real backend init.
_PROBE_CODE = (
    "import os, jax\n"
    "p = os.environ.get('DDIM_COLD_PROBE_PLATFORMS')\n"
    "if p: jax.config.update('jax_platforms', p)\n"
    "jax.devices()\n"
)
#: a successful probe is valid this long (marker file mtime) — bursts of CLI
#: invocations must not each pay a duplicate remote backend init + claim
_PROBE_TTL_S = 600.0


def effective_platforms() -> str:
    """The platform list JAX will actually use, without touching the
    backend: jax.config (where site hooks and :func:`honor_env_platform`
    write) wins over the pre-import env var. Empty string when nothing is
    configured (JAX will then auto-detect). The ONE owner of this resolution
    rule — :func:`ensure_live_backend` and bench.py's stall watchdog both
    derive from it so the probe decision and the watchdog arming can never
    drift apart."""
    import jax

    return (jax.config.jax_platforms or "").strip() or os.environ.get(
        "JAX_PLATFORMS", "").strip()


def effective_first_platform() -> str:
    """First entry of :func:`effective_platforms` (the backend JAX tries
    first); empty string when nothing is configured."""
    return effective_platforms().split(",")[0].strip()


def watchdog_stall_s(env_var: str, accel_default_s: float) -> float:
    """The shared watchdog arm-condition: how long a device-touching script
    may go silent before its StallWatchdog aborts it.

    An explicit env value always wins (``0`` disarms; empty string counts as
    unset — the yaml/CI "unset" idiom). Otherwise the default is ``0`` (never
    armed) when the effective FIRST platform is cpu — a local backend has no
    tunnel to wedge, and healthy CPU runs of heavy sections legitimately blow
    any sane deadline — else ``accel_default_s``. Resolution goes through
    :func:`effective_first_platform`, so a comma-separated platform list like
    ``"cpu,host"`` is read the same way everywhere (previously fid_trend /
    publish_run compared ``jax.config.jax_platforms == "cpu"`` exactly and
    would arm a 600 s watchdog on such a CPU run — ADVICE r5 item 3).
    """
    env = os.environ.get(env_var) or None
    if env is not None:
        return float(env)
    return 0.0 if effective_first_platform() == "cpu" else accel_default_s


def probe_marker_path(first: str) -> str:
    """Per-user probe-success marker for platform ``first`` — shared by
    :func:`ensure_live_backend` and the recovery watcher
    (scripts/watch_tpu.py) so a watcher-observed recovery immediately
    unblocks CLI probes."""
    import tempfile

    uid = os.getuid() if hasattr(os, "getuid") else "nt"
    return os.path.join(tempfile.gettempdir(),
                        f"ddim_cold_backend_ok_{uid}_{first or 'site'}")


def ensure_live_backend(timeout_s: float = 120.0, *, attempts: int = 1,
                        backoff_s: float = 45.0,
                        _probe_code: str = _PROBE_CODE) -> tuple[str, str]:
    """Bound backend initialization against a wedged remote-TPU tunnel.

    A network-attached TPU whose session lock is stuck (e.g. a previous
    client was hard-killed mid-claim) makes ``jax.devices()`` block FOREVER
    in a claim-retry loop — and an in-process watchdog thread cannot rescue
    it, because the hung init holds jax's backend-init lock so a later CPU
    ``devices()`` deadlocks on the same lock (verified on the axon tunnel).
    So the probe runs in a SUBPROCESS with the parent's effective platform
    list: it either initializes that backend and exits cleanly (releasing
    its claim), or we time it out / read its error and pin
    ``jax_platforms=cpu`` in THIS process before any backend touch.

    Returns ``(platform, reason)`` where platform is ``"default"`` (ambient
    backend live, or probe skipped: already CPU-pinned / recent success
    cached) or ``"cpu"`` (fallback applied; reason says whether the probe
    hung or crashed, with a stderr tail). Call before the first device query.

    ``attempts`` > 1 re-probes after linear backoff (``backoff_s``,
    ``2*backoff_s``, …) before giving up — a flaky tunnel often recovers
    within minutes, and a bench that downscoped to CPU on one bad probe
    loses the whole hardware record for the round (VERDICT r2 weak #1).
    """
    import jax

    # the parent's FIRST device query resolves from jax.config (site hooks
    # and honor_env_platform write there); env is only the pre-import intent
    effective = effective_platforms()
    first = effective_first_platform()
    if first == "cpu":
        return "default", "already cpu-pinned"

    import subprocess
    import sys
    import tempfile
    import time

    # per-user marker: on a shared host a world-shared path could be owned or
    # pre-created by another user — at best the cache never writes, at worst a
    # stale foreign marker skips the probe against a wedged tunnel
    marker = probe_marker_path(first)
    try:
        if time.time() - os.path.getmtime(marker) < _PROBE_TTL_S:
            return "default", "recent probe success cached"
    except OSError:
        pass

    env = dict(os.environ)
    if effective:
        env["DDIM_COLD_PROBE_PLATFORMS"] = effective
    reason = "no probe attempted"
    for attempt in range(max(1, attempts)):
        if attempt:
            time.sleep(backoff_s * attempt)  # linear backoff between probes
        # killing a TIMED-OUT probe is safe: it is blocked *waiting* for the
        # claim and never held the grant — the wedge this module defends
        # against comes from killing a client that already HELD it
        # stderr to a FILE, stdout devnull: pipe capture can block past the
        # timeout if the probe forked a helper that inherits the pipe ends
        with tempfile.TemporaryFile() as errf:
            try:
                subprocess.run([sys.executable, "-c", _probe_code], check=True,
                               stdout=subprocess.DEVNULL, stderr=errf,
                               timeout=timeout_s, env=env)
                try:
                    with open(marker, "w"):
                        pass
                except OSError:
                    pass
                return "default", "probe ok" + (
                    f" (attempt {attempt + 1})" if attempt else "")
            except subprocess.TimeoutExpired:
                reason = (f"backend init probe hung >{timeout_s:.0f}s "
                          "(wedged tunnel?)")
            except subprocess.CalledProcessError as e:
                errf.seek(0)
                tail = errf.read()[-400:].decode("utf-8", "replace").strip()
                reason = f"backend init probe failed (rc={e.returncode}): {tail}"

    if attempts > 1:
        reason += f" — after {attempts} attempts with backoff"
    jax.config.update("jax_platforms", "cpu")
    return "cpu", reason
