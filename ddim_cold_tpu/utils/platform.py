"""Make the ``JAX_PLATFORMS`` env var authoritative for our entry points.

Some deployments (e.g. the axon TPU-tunnel image this framework is benched
on) inject a site hook that pins ``jax_platforms`` programmatically, which
silently overrides the env var — a user running ``JAX_PLATFORMS=cpu python
multi_gpu_trainer.py …`` would still dial the TPU. Every CLI in this repo
calls :func:`honor_env_platform` before its first device query so the env var
behaves the way the JAX docs say it does.
"""

from __future__ import annotations

import os


def honor_env_platform() -> None:
    """Re-apply ``JAX_PLATFORMS`` over any site-config pin.

    No-op when the env var is unset or the configured first-choice platform
    already matches (so the site's own ``axon,cpu`` fallback list survives a
    redundant ``JAX_PLATFORMS=axon``)."""
    want = os.environ.get("JAX_PLATFORMS", "").strip()
    if not want:
        return
    import jax

    current = jax.config.jax_platforms or ""
    if current.split(",")[0].strip() == want.split(",")[0].strip():
        return
    jax.config.update("jax_platforms", want)
