"""Load a finished training run (config + model + best params) from its
``Saved_Models/<run>/`` directory — shared by the publishing/eval scripts
(scripts/publish_run.py, scripts/compute_fid.py).

The run dir is self-describing: the launcher copies the experiment yaml into
it (multi_gpu_trainer.py, mirroring reference :201), and ``bestloss.ckpt``
holds the best-val params. Restoring goes through a freshly-initialized
template tree so a checkpoint written on one topology (the TPU) loads on
another (a CPU publish host) — see utils/checkpoint.py restore_args.
"""

from __future__ import annotations

import os


def load_run_template(run_dir: str):
    """→ (config, model, template_params) — the run's model rebuilt from its
    own yaml plus a freshly-initialized param tree to restore checkpoints
    against. The single source of the template recipe (dtype, init rng, yaml
    selection); every checkpoint-loading script goes through here so the
    recipe can never drift between them."""
    import jax
    import jax.numpy as jnp

    from ddim_cold_tpu.config import load_config
    from ddim_cold_tpu.models import DiffusionViT

    yamls = [f for f in os.listdir(run_dir) if f.endswith(".yaml")]
    if not yamls:
        raise FileNotFoundError(f"no experiment yaml in {run_dir}")
    config = load_config(os.path.join(run_dir, yamls[0]),
                         os.path.splitext(yamls[0])[0])
    model = DiffusionViT(dtype=jnp.bfloat16, **config.model_kwargs())
    template = model.init(
        jax.random.PRNGKey(0),
        jnp.zeros((1, *config.image_size, 3)), jnp.zeros((1,), jnp.int32),
    )["params"]
    return config, model, template


def load_run(run_dir: str):
    """→ (config, model, params) for the run's best checkpoint."""
    from ddim_cold_tpu.utils import checkpoint as ckpt

    config, model, template = load_run_template(run_dir)
    params = ckpt.restore_checkpoint(
        os.path.join(run_dir, "bestloss.ckpt"), template)
    return config, model, params


def default_val_dir(config, repo_root: str) -> str:
    """The run's own validation split, resolved for the FID scripts'
    ``--val-dir`` default — ONE policy shared by compute_fid.py and
    fid_trend.py (a 200px run must not silently compare against the 64px
    OxfordFlowers default; preflight-caught). Relative dataStorage paths
    (the committed yamls' form) resolve against the repo root the trainer
    runs from."""
    val = config.data_storage[1]
    if not val:
        raise ValueError(
            f"run yaml for {config.run_name!r} has no dataStorage val entry "
            "— pass --val-dir explicitly")
    return val if os.path.isabs(val) else os.path.join(repo_root, val)
