"""Analytic FLOP accounting for the DiffusionViT — the MFU denominator.

The reference never measures utilization (its only perf record is wall-clock
``time_cost`` lines, multi_gpu_trainer.py:135-138); to say how far a step is
from the chip's ceiling we count the model's matmul FLOPs analytically and
divide by (peak · step_time). Elementwise/softmax/LN work is ignored — on TPU
those ride the VPU and are fused into the GEMM pipeline; standard MFU practice
counts MXU FLOPs only.

Peak numbers are per-chip bf16 dense (not sparse) from published TPU specs,
keyed by ``jax.devices()[0].device_kind`` so the bench JSON can name the
hardware it ran on (BENCH vs_baseline is otherwise cross-hardware
apples-to-oranges — VERDICT round 1).
"""

from __future__ import annotations

#: bf16 dense peak TFLOP/s per chip by jax device_kind (prefix-matched).
PEAK_BF16_TFLOPS = {
    "TPU v6": 918.0,  # Trillium
    "TPU v5p": 459.0,
    "TPU v5 lite": 197.0,  # v5e
    "TPU v5": 459.0,
    "TPU v4 lite": 138.0,  # v4i
    "TPU v4": 275.0,
    "TPU v3": 123.0,
    "TPU v2": 46.0,
}

#: int8 dense peak TOP/s per chip — the MXU rate w8a16 trunk GEMMs are
#: entitled to (ops/quant.py). v5e/v6e double their bf16 rate at int8;
#: v4 and earlier have no faster int8 path, so their entry equals bf16 and
#: mixed-peak MFU degenerates to the plain number there.
PEAK_INT8_TOPS = {
    "TPU v6": 1836.0,  # Trillium
    "TPU v5p": 918.0,
    "TPU v5 lite": 394.0,  # v5e
    "TPU v5": 918.0,
    "TPU v4 lite": 138.0,
    "TPU v4": 275.0,
    "TPU v3": 123.0,
    "TPU v2": 46.0,
}


#: HBM bandwidth GB/s per chip (published specs, same prefix-match keys as
#: the peak tables) — the roofline's other axis: a scope whose arithmetic
#: intensity sits below peak/bandwidth is bandwidth-bound no matter how the
#: kernel schedules its MXU passes.
HBM_GB_S = {
    "TPU v6": 1638.0,  # Trillium
    "TPU v5p": 2765.0,
    "TPU v5 lite": 819.0,  # v5e
    "TPU v5": 2765.0,
    "TPU v4 lite": 614.0,  # v4i
    "TPU v4": 1228.0,
    "TPU v3": 900.0,
    "TPU v2": 700.0,
}


#: per-core VMEM capacity in bytes (published specs / pallas guide; same
#: prefix-match keys). This is the budget every Pallas kernel's per-program
#: footprint — in/out blocks double-buffered by the pipeline, plus VMEM
#: scratch — must fit inside (graftcheck P002, analysis/kernel_checks.py).
VMEM_BYTES = {
    "TPU v6": 32 << 20,  # Trillium: 32 MiB
    "TPU v5p": 16 << 20,
    "TPU v5 lite": 16 << 20,  # v5e — the bench chip
    "TPU v5": 16 << 20,
    "TPU v4": 16 << 20,
    "TPU v3": 16 << 20,
    "TPU v2": 16 << 20,
}

#: per-chip HBM capacity in bytes (published specs) — the budget a served
#: program's statically estimated peak live bytes must fit inside
#: (graftcheck M001, analysis/memory_checks.py).
HBM_BYTES = {
    "TPU v6": 32 << 30,  # Trillium
    "TPU v5p": 95 << 30,
    "TPU v5 lite": 16 << 30,  # v5e — the bench chip
    "TPU v5": 95 << 30,
    "TPU v4 lite": 8 << 30,  # v4i
    "TPU v4": 32 << 30,
    "TPU v3": 32 << 30,
    "TPU v2": 16 << 30,
}


def _prefix_lookup(table: dict, device_kind: str) -> float | None:
    best = None
    for kind, peak in table.items():
        if device_kind.startswith(kind) and (best is None or len(kind) > best[0]):
            best = (len(kind), peak)
    return best[1] if best else None


def peak_tflops(device_kind: str) -> float | None:
    """Longest-prefix match of the device kind; None when unknown (CPU etc.)."""
    return _prefix_lookup(PEAK_BF16_TFLOPS, device_kind)


def peak_int8_tops(device_kind: str) -> float | None:
    """int8 dense peak TOP/s; None when unknown."""
    return _prefix_lookup(PEAK_INT8_TOPS, device_kind)


def mixed_peak_tflops(device_kind: str, int8_fraction: float = 0.0) -> float | None:
    """Effective peak when ``int8_fraction`` of a step's matmul FLOPs run at
    the int8 rate and the rest at bf16 — the time-weighted harmonic mix
    (each fraction contributes its FLOPs/rate to the ideal step time).
    With no int8 table entry the whole step is charged at bf16 — MFU stays
    conservative rather than flattering."""
    bf16 = peak_tflops(device_kind)
    if bf16 is None:
        return None
    f = min(max(float(int8_fraction), 0.0), 1.0)
    if f == 0.0:
        return bf16
    int8 = peak_int8_tops(device_kind) or bf16
    return 1.0 / (f / int8 + (1.0 - f) / bf16)


def vmem_bytes(device_kind: str) -> int | None:
    """Per-core VMEM capacity in bytes; None when unknown (CPU etc.)."""
    v = _prefix_lookup(VMEM_BYTES, device_kind)
    return None if v is None else int(v)


def hbm_bytes(device_kind: str) -> int | None:
    """Per-chip HBM capacity in bytes; None when unknown (CPU etc.)."""
    v = _prefix_lookup(HBM_BYTES, device_kind)
    return None if v is None else int(v)


def hbm_gb_s(device_kind: str) -> float | None:
    """HBM bandwidth GB/s for the chip; None when unknown (CPU etc.)."""
    return _prefix_lookup(HBM_GB_S, device_kind)


def ridge_flops_per_byte(device_kind: str,
                         int8_fraction: float = 0.0) -> float | None:
    """The roofline ridge point: arithmetic intensity (FLOPs/byte) at which
    peak compute and peak HBM bandwidth take equal time. Scopes below it are
    HBM-bound, above it compute-bound. None when either peak is unknown."""
    peak = mixed_peak_tflops(device_kind, int8_fraction)
    bw = hbm_gb_s(device_kind)
    if peak is None or bw is None:
        return None
    return peak * 1e12 / (bw * 1e9)


def vit_forward_flops(*, img_size=(64, 64), patch_size=8, embed_dim=384,
                      depth=7, num_heads=12, mlp_ratio=1.0, in_chans=3) -> float:
    """Matmul FLOPs (2·MACs) for one image's forward pass.

    Per block (dim D, tokens N): qkv 3·N·D², attn scores+values 2·N²·D,
    proj N·D², MLP 2·N·D²·mlp_ratio. Plus patch-embed N·P²·C·D in and the
    head's N·D·P²·C out (ViT.py:158-218 structure).
    """
    H, W = img_size
    n = (H // patch_size) * (W // patch_size) + 1  # +1 cls token
    d = embed_dim
    per_block = 3 * n * d * d + 2 * n * n * d + n * d * d + 2 * n * d * d * mlp_ratio
    patch = n * (patch_size * patch_size * in_chans) * d  # embed + head are
    return 2.0 * (depth * per_block + 2 * patch)          # the same GEMM shape


def vit_trunk_gemm_fraction(*, img_size=(64, 64), patch_size=8, embed_dim=384,
                            depth=7, num_heads=12, mlp_ratio=1.0,
                            in_chans=3) -> float:
    """Fraction of the forward's matmul FLOPs in the quantized trunk denses
    (qkv + proj + MLP; attention score/value GEMMs and patch/head stay
    bf16) — the ``int8_fraction`` a w8a16 forward feeds ``mfu``, and the
    analytic-ceiling input for PERF.md's quantization section."""
    H, W = img_size
    n = (H // patch_size) * (W // patch_size) + 1
    d = embed_dim
    dense = depth * (3 * n * d * d + n * d * d + 2 * n * d * d * mlp_ratio)
    attn = depth * 2 * n * n * d
    patch = 2 * n * (patch_size * patch_size * in_chans) * d
    return dense / (dense + attn + patch)


def train_step_flops(batch: int, **model_kwargs) -> float:
    """fwd + bwd ≈ 3× forward (grads w.r.t. inputs and weights each cost one
    forward's worth of matmuls)."""
    return 3.0 * batch * vit_forward_flops(**model_kwargs)


def mfu(flops_per_step: float, step_seconds: float, device_kind: str,
        n_devices: int = 1, int8_fraction: float = 0.0) -> float | None:
    """``int8_fraction`` > 0 charges that share of the FLOPs at the chip's
    int8 peak (w8a16 trunks, ops/quant.py) — the denominator grows, so a
    quantized run's MFU stays honest instead of flattering."""
    peak = mixed_peak_tflops(device_kind, int8_fraction)
    if peak is None or step_seconds <= 0:
        return None
    return flops_per_step / (step_seconds * peak * 1e12 * n_devices)


def vit_scope_costs(*, img_size=(64, 64), patch_size=8, embed_dim=384,
                    depth=7, num_heads=12, mlp_ratio=1.0, in_chans=3,
                    flash=False, quant=False, fused=False) -> dict:
    """FLOP + HBM-byte estimates for ONE image's forward pass, split by the
    named scopes profiling.scope plants (obs/attrib.py joins these against
    per-scope device time → achieved TFLOP/s, MFU, roofline class).

    Each entry is the scope's INCLUSIVE cost — ``sampler/model`` carries the
    whole forward, matching attribution's rollup time (an event inside
    ``flash_attention/fwd`` counts toward both). Byte estimates are the
    minimal HBM traffic: weights once per call, activations read+written at
    layer boundaries, and — for the flash path — q/k/v/out streamed without
    materializing the N² score matrix. Elementwise traffic rides along with
    the GEMMs it fuses into, same convention as the FLOP side.

    ``fused=True`` models the fused sampler-trunk programs (models/vit.py
    ``fused``): the attention scope becomes ``flash_attention/fused_qkv``
    (the one kernel carrying qkv dequant-GEMM + online softmax + proj GEMM;
    the qkv/context activations never touch HBM, so its byte estimate is
    x-in twice + out once + weights) with the epilogue cast under
    ``flash_attention/fused_proj``, and the Mlp scope becomes ``mlp/pallas``
    (hidden activation VMEM-resident). ``flash_attention/fwd`` and
    ``dequant_matmul/pallas`` never fire in a fused-quant program and are
    omitted; fused without quant keeps the plain flash scope.
    """
    H, W = img_size
    n = (H // patch_size) * (W // patch_size) + 1
    d = embed_dim
    act_b = 2  # bf16 activations
    w_b = 1 if quant else 2  # int8 trunk weights under w8a16
    attn_flops = 2.0 * depth * 2 * n * n * d
    qkv_proj_flops = 2.0 * depth * (3 * n * d * d + n * d * d)
    mlp_flops = 2.0 * depth * 2 * n * d * d * mlp_ratio
    dense_flops = qkv_proj_flops + mlp_flops
    patch_flops = 2.0 * 2 * n * (patch_size * patch_size * in_chans) * d
    # bytes: flash attention streams q, k, v in and the context out once per
    # layer; trunk denses read their weights plus in/out activations for the
    # qkv, proj and two MLP GEMMs; patch/head move the pixel-space tensors
    # and their (shared-shape) weight once each.
    attn_bytes = float(depth * 4 * n * d * act_b)
    dense_bytes = float(depth * ((4 + 2 * mlp_ratio) * d * d * w_b
                                 + 8 * n * d * act_b))
    patch_bytes = float(2 * n * (patch_size * patch_size * in_chans) * act_b
                        + 2 * (patch_size * patch_size * in_chans) * d * 2)
    costs = {"sampler/model": {
        "flops": attn_flops + dense_flops + patch_flops,
        "bytes": attn_bytes + dense_bytes + patch_bytes}}
    if fused:
        costs["mlp/pallas"] = {
            "flops": mlp_flops,
            "bytes": float(depth * (2 * mlp_ratio * d * d * w_b
                                    + 2 * n * d * act_b))}
        if quant:
            costs["flash_attention/fused_qkv"] = {
                "flops": attn_flops + qkv_proj_flops,
                "bytes": float(depth * (4 * d * d * w_b
                                        + 3 * n * d * act_b))}
            costs["flash_attention/fused_proj"] = {
                "flops": 0.0,  # the f32→compute-dtype epilogue cast only
                "bytes": float(depth * 2 * n * d * act_b)}
        elif flash:
            costs["flash_attention/fwd"] = {"flops": attn_flops,
                                            "bytes": attn_bytes}
        return costs
    if flash:
        costs["flash_attention/fwd"] = {"flops": attn_flops,
                                        "bytes": attn_bytes}
    if quant:
        costs["dequant_matmul/pallas"] = {"flops": dense_flops,
                                          "bytes": dense_bytes}
    return costs
