"""Checkpointing — orbax for native state, plus a torch-pickle bridge.

Reproduces the reference's dual-checkpoint behavior (SURVEY.md C20,
multi_gpu_trainer.py:94-106,152-163):

* ``bestloss`` — bare model weights whenever val improves;
* ``lastepoch`` — full training state (epoch, steps, EMA loss, best metric,
  params, optimizer state) every epoch, the resume target.

Native format is orbax (one directory per checkpoint). The legacy ``*.pkl``
bridge converts between torch state_dicts (``blocks.N.attn.qkv.weight``…) and
the Flax param tree so reference checkpoints load here and vice versa; torch
(cpu) is an optional conversion-time dependency only.
"""

from __future__ import annotations

import os
import re
from typing import Any

import jax
import numpy as np


# ---------------------------------------------------------------------------
# torch state_dict ↔ flax params
# ---------------------------------------------------------------------------

def _strip_ddp_prefix(state_dict: dict) -> dict:
    """lastepoch state_dicts carry DDP's 'module.' prefix (multi_gpu_trainer.py:160)."""
    return {re.sub(r"^module\.", "", k): v for k, v in state_dict.items()}


def flax_from_torch_state_dict(state_dict: dict, patch_size: int) -> dict:
    """Map a reference torch state_dict to the DiffusionViT param tree.

    Layout transforms: Linear ``W (out,in)`` → kernel ``(in,out)``; the patch
    Conv2d ``W (E,C,p,p)`` → Dense kernel ``(p²C, E)`` with (row, col, chan)
    patch-feature order (models/vit.py PatchEmbed docstring); LayerNorm
    weight → scale.
    """
    sd = {k: np.asarray(v.detach().cpu().numpy() if hasattr(v, "detach") else v,
                        dtype=np.float32)
          for k, v in _strip_ddp_prefix(state_dict).items()}
    p = patch_size
    params: dict[str, Any] = {
        "cls_token": sd["cls_token"],
        # pos_embed is absent for use_sincos_pos models (fixed table, not a
        # param) — tolerated in both directions.
        **({"pos_embed": sd["pos_embed"]} if "pos_embed" in sd else {}),
        "time_embed": {"embedding": sd["time_embed.weight"]},
        "norm": {"scale": sd["norm.weight"], "bias": sd["norm.bias"]},
        "head": {"kernel": sd["head.weight"].T, "bias": sd["head.bias"]},
    }
    w = sd["patch_embed.proj.weight"]  # (E, C, p, p)
    e = w.shape[0]
    params["patch_embed"] = {
        "proj": {
            "kernel": w.transpose(2, 3, 1, 0).reshape(p * p * w.shape[1], e),
            "bias": sd["patch_embed.proj.bias"],
        }
    }
    depth = 1 + max(
        int(m.group(1)) for k in sd if (m := re.match(r"blocks\.(\d+)\.", k))
    )
    for i in range(depth):
        b = f"blocks.{i}."
        params[f"blocks_{i}"] = {
            "norm1": {"scale": sd[b + "norm1.weight"], "bias": sd[b + "norm1.bias"]},
            "norm2": {"scale": sd[b + "norm2.weight"], "bias": sd[b + "norm2.bias"]},
            "attn": {
                "qkv": {"kernel": sd[b + "attn.qkv.weight"].T,
                        **({"bias": sd[b + "attn.qkv.bias"]}
                           if b + "attn.qkv.bias" in sd else {})},
                "proj": {"kernel": sd[b + "attn.proj.weight"].T,
                         "bias": sd[b + "attn.proj.bias"]},
            },
            "mlp": {
                "fc1": {"kernel": sd[b + "mlp.fc1.weight"].T, "bias": sd[b + "mlp.fc1.bias"]},
                "fc2": {"kernel": sd[b + "mlp.fc2.weight"].T, "bias": sd[b + "mlp.fc2.bias"]},
            },
        }
    return params


def stack_block_params(params: dict) -> dict:
    """Unrolled ``blocks_0..blocks_{d-1}`` subtrees → one ``blocks`` subtree
    with a leading layer axis (the ``scan_blocks=True`` model's layout)."""
    depth = 0
    while f"blocks_{depth}" in params:
        depth += 1
    if depth == 0:
        return dict(params)
    out = {k: v for k, v in params.items() if not re.match(r"^blocks_\d+$", k)}
    out["blocks"] = jax.tree.map(
        lambda *leaves: np.stack([np.asarray(l) for l in leaves]),
        *(params[f"blocks_{i}"] for i in range(depth)),
    )
    return out


def unstack_block_params(params: dict) -> dict:
    """Inverse of ``stack_block_params``: split the stacked ``blocks`` subtree
    back into per-layer ``blocks_{i}`` trees."""
    if "blocks" not in params:
        return dict(params)
    out = {k: v for k, v in params.items() if k != "blocks"}
    stacked = params["blocks"]
    depth = jax.tree.leaves(stacked)[0].shape[0]
    for i in range(depth):
        out[f"blocks_{i}"] = jax.tree.map(lambda a, _i=i: np.asarray(a[_i]), stacked)
    return out


def torch_state_dict_from_flax(params, patch_size: int) -> dict:
    """Inverse of ``flax_from_torch_state_dict`` (numpy arrays, torch-key
    names). Accepts both block layouts — a stacked ``blocks`` subtree
    (scan_blocks models) is unstacked first."""
    params = unstack_block_params(params)
    if any("moe" in blk for blk in params.values() if isinstance(blk, dict)):
        raise ValueError(
            "MoE params (num_experts > 1) have no reference torch layout — "
            "the torch-pkl bridge covers the reference's dense architecture "
            "only; use the orbax checkpoints for MoE runs")
    g = lambda *ks: np.asarray(_dig(params, ks))
    p = patch_size
    pk = g("patch_embed", "proj", "kernel")  # (p²C, E)
    e = pk.shape[1]
    c = pk.shape[0] // (p * p)
    sd = {
        "cls_token": g("cls_token"),
        **({"pos_embed": g("pos_embed")} if "pos_embed" in params else {}),
        "time_embed.weight": g("time_embed", "embedding"),
        "patch_embed.proj.weight": pk.reshape(p, p, c, e).transpose(3, 2, 0, 1),
        "patch_embed.proj.bias": g("patch_embed", "proj", "bias"),
        "norm.weight": g("norm", "scale"),
        "norm.bias": g("norm", "bias"),
        "head.weight": g("head", "kernel").T,
        "head.bias": g("head", "bias"),
    }
    i = 0
    while f"blocks_{i}" in params:
        b = f"blocks_{i}"
        sd[f"blocks.{i}.norm1.weight"] = g(b, "norm1", "scale")
        sd[f"blocks.{i}.norm1.bias"] = g(b, "norm1", "bias")
        sd[f"blocks.{i}.norm2.weight"] = g(b, "norm2", "scale")
        sd[f"blocks.{i}.norm2.bias"] = g(b, "norm2", "bias")
        sd[f"blocks.{i}.attn.qkv.weight"] = g(b, "attn", "qkv", "kernel").T
        if "bias" in params[b]["attn"]["qkv"]:
            sd[f"blocks.{i}.attn.qkv.bias"] = g(b, "attn", "qkv", "bias")
        sd[f"blocks.{i}.attn.proj.weight"] = g(b, "attn", "proj", "kernel").T
        sd[f"blocks.{i}.attn.proj.bias"] = g(b, "attn", "proj", "bias")
        sd[f"blocks.{i}.mlp.fc1.weight"] = g(b, "mlp", "fc1", "kernel").T
        sd[f"blocks.{i}.mlp.fc1.bias"] = g(b, "mlp", "fc1", "bias")
        sd[f"blocks.{i}.mlp.fc2.weight"] = g(b, "mlp", "fc2", "kernel").T
        sd[f"blocks.{i}.mlp.fc2.bias"] = g(b, "mlp", "fc2", "bias")
        i += 1
    return sd


def _dig(tree, keys):
    for k in keys:
        tree = tree[k]
    return tree


def load_torch_pkl(path: str, patch_size: int) -> dict:
    """Load a reference ``*.pkl`` (bare state_dict or the lastepoch dict) into
    a Flax param tree. Uses torch when importable; otherwise falls back to the
    torch-free zip-format reader (:mod:`.torch_pickle`) — a TPU host needs no
    torch install to ingest reference checkpoints (parity pinned by
    tests/test_torch_pickle.py::test_load_torch_pkl_falls_back_without_torch).
    """
    try:
        # only the IMPORT selects the fallback: an ImportError raised inside
        # torch.load itself (e.g. a module named by the pickle stream missing
        # on this host) is a real error that must surface, not trigger a
        # silent re-parse that fails elsewhere
        import torch
    except ImportError:
        from ddim_cold_tpu.utils import torch_pickle

        obj = torch_pickle.load(path)
    else:
        obj = torch.load(path, map_location="cpu", weights_only=False)
    if isinstance(obj, dict) and "state_dict" in obj:
        obj = obj["state_dict"]
    return flax_from_torch_state_dict(obj, patch_size)


def save_torch_pkl(params, path: str, patch_size: int) -> None:
    """Write params as a torch state_dict pickle a reference user can load.
    Torch-less hosts fall back to the native zip-format writer
    (:func:`.torch_pickle.save`) — real ``torch.load`` reads its output
    (parity pinned by tests/test_torch_pickle.py)."""
    sd_np = {k: np.array(v, order="C")
             for k, v in torch_state_dict_from_flax(params, patch_size).items()}
    try:
        import torch
    except ImportError:
        from ddim_cold_tpu.utils import torch_pickle

        torch_pickle.save(sd_np, path)  # write-then-rename internally
        return
    # same atomicity as the native writer: torch.save writes the destination
    # directly, and a crash mid-write would leave a truncated file that
    # poisons every later warm start
    from ddim_cold_tpu.utils.torch_pickle import atomic_replace

    with atomic_replace(path) as tmp:
        torch.save({k: torch.from_numpy(v) for k, v in sd_np.items()}, tmp)


# ---------------------------------------------------------------------------
# orbax train-state checkpoints
# ---------------------------------------------------------------------------

def _to_host(tree):
    def conv(x):
        # multi-host shards aren't host-materializable; orbax writes global
        # jax.Arrays distributedly, so pass them through untouched.
        if hasattr(x, "is_fully_addressable") and not x.is_fully_addressable:
            return x
        return np.asarray(x)

    return jax.tree.map(conv, tree)


def save_checkpoint(path: str, tree) -> None:
    """Save a pytree checkpoint directory (orbax).

    Single-host saves write beside the destination and swap in with two
    rename metadata ops — ``force=True`` straight onto ``path`` would delete
    the PREVIOUS checkpoint before the (multi-second, on tunneled hosts)
    write, so a crash mid-write would lose the only resume point. Multi-host
    saves go directly through orbax's own collective commit protocol (a
    per-process directory swap on a shared fs would race).

    The ``ckpt.save`` fault site fires at every crash window of the
    single-host sequence (pre-write / post-write / mid-swap / post-swap) —
    the crash-window tests kill the save at each and assert a loadable
    checkpoint always survives (``recover_swap`` + restore).
    """
    import shutil

    import orbax.checkpoint as ocp

    from ddim_cold_tpu.utils import faults

    path = os.path.abspath(path)
    ckptr = ocp.PyTreeCheckpointer()
    if jax.process_count() > 1:
        ckptr.save(path, _to_host(tree), force=True)
        return
    recover_swap(path)
    tmp, old = path + ".writing", path + ".old"
    for d in (tmp, old):  # true leftovers (post-recovery) from a crashed save
        if os.path.isdir(d):
            shutil.rmtree(d)
    faults.fire("ckpt.save", tag="window:pre-write|")
    ckptr.save(tmp, _to_host(tree), force=True)
    faults.fire("ckpt.save", tag="window:post-write|")
    if os.path.isdir(path):
        os.rename(path, old)
    faults.fire("ckpt.save", tag="window:mid-swap|")
    os.rename(tmp, path)
    faults.fire("ckpt.save", tag="window:post-swap|")
    if os.path.isdir(old):
        shutil.rmtree(old)


def recover_swap(path: str) -> None:
    """Heal a crash between the two swap renames in :func:`save_checkpoint`:
    a lone ``<path>.old`` with no ``<path>`` IS the last good checkpoint —
    move it back rather than ever treating it as deletable garbage.

    Only the DIRECTORY OWNER (the trainer, on resume/warm-start and before
    each save) may call this — a read-only consumer healing concurrently
    with a writer's in-progress swap would race its second rename.
    Multi-host: process 0 renames, everyone barriers."""
    path = os.path.abspath(path)
    old = path + ".old"
    if jax.process_count() > 1:
        if jax.process_index() == 0 and not os.path.isdir(path) and os.path.isdir(old):
            os.rename(old, path)
        from jax.experimental import multihost_utils

        multihost_utils.sync_global_devices("ddim_cold_ckpt_recover")
        return
    if not os.path.isdir(path) and os.path.isdir(old):
        os.rename(old, path)


def restore_checkpoint(path: str, target=None):
    """Restore a pytree checkpoint; ``target`` fixes structure/dtypes.

    numpy targets restore as host arrays regardless of the topology that
    saved them (a checkpoint written by an N-process run names devices a
    different world doesn't have — the restore args below override those
    saved shardings); jax.Array targets restore sharded per their sharding.
    """
    import orbax.checkpoint as ocp

    ckptr = ocp.PyTreeCheckpointer()
    if target is None:
        return ckptr.restore(os.path.abspath(path))
    item = _to_host(target)

    def restore_arg(x):
        if isinstance(x, jax.Array):  # non-addressable multi-host leaf
            return ocp.ArrayRestoreArgs(sharding=x.sharding,
                                        global_shape=x.shape, dtype=x.dtype)
        if isinstance(x, np.ndarray):
            return ocp.RestoreArgs(restore_type=np.ndarray, dtype=x.dtype)
        return ocp.RestoreArgs()

    return ckptr.restore(
        os.path.abspath(path),
        args=ocp.args.PyTreeRestore(
            item=item, restore_args=jax.tree.map(restore_arg, item)),
    )
