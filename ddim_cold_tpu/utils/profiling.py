"""Tracing/profiling + numeric-debug hooks (SURVEY.md §5 aux subsystems).

The reference has no profiler — only wall-clock prints (per-100-step
``time_cost`` and per-sampler-step elapsed, multi_gpu_trainer.py:135-138,
ViT.py:222-235). Here the equivalents are structural:

* ``trace(dir)`` — a ``jax.profiler`` trace context; view in TensorBoard or
  Perfetto. Wrap any train/sample region.
* ``annotate(name)`` — named TraceAnnotation so steps show up labeled.
* ``enable_nan_checks()`` — ``jax_debug_nans`` (the SPMD replacement for the
  reference's commented TORCH_DISTRIBUTED_DEBUG, with actually-useful
  semantics: fail at the op that produced the NaN).
"""

from __future__ import annotations

import jax
import numpy as np


def trace(log_dir: str, perfetto: bool = False):
    """Capture a device trace into ``log_dir`` — ``jax.profiler.trace`` is
    already a context manager with stop-in-finally semantics; pass through so
    upstream improvements (perfetto links, etc.) come for free.

    ``perfetto=True`` additionally writes the trace-event JSON dump
    (``plugins/profile/<run>/perfetto_trace.json.gz``) that
    ``obs/attrib.py`` parses — without it the capture is xplane-only and
    attribution has nothing to read. Guarded for older jax signatures."""
    if perfetto:
        try:
            return jax.profiler.trace(log_dir, create_perfetto_trace=True)
        except TypeError:  # jax predating create_perfetto_trace
            pass
    return jax.profiler.trace(log_dir)


def start_trace(log_dir: str) -> None:
    """Step-bounded tracing (the trainer's ``profile_steps``): start here,
    ``stop_trace()`` when the window closes."""
    jax.profiler.start_trace(log_dir)


def stop_trace() -> None:
    jax.profiler.stop_trace()


def annotate(name: str):
    """Named region inside a trace (shows up on the TPU timeline)."""
    return jax.profiler.TraceAnnotation(name)


def scope(name: str):
    """Named scope INSIDE traced code (``jax.named_scope``) — the compiled
    sibling of :func:`annotate`: the name lands on the ops themselves, so
    profiler timelines attribute kernel time to sampler stages
    (``ddim/model``, ``flash_attention/fwd``, ``sp/all_to_all``, …).
    Metadata-only: the printed jaxpr and its J006 signature hash are
    untouched, and numerics are bit-identical with or without it."""
    return jax.named_scope(name)


def span_trace(log_dir: str, span=None, perfetto: bool = False):
    """A ``jax.profiler`` trace session keyed to an obs span: the capture
    lands in ``log_dir/trace_<trace_id>_<span_id>`` (or ``log_dir`` when no
    span / tracing disabled), so a slow request's profiler timeline is
    findable from its span ids — the span→profiler workflow for the MFU
    push (PERF.md "Observability"). ``perfetto=True`` adds the trace-event
    JSON dump ``obs/attrib.py`` attributes (see :func:`trace`)."""
    import os

    ctx = getattr(span, "ctx", None)
    if ctx is not None:
        log_dir = os.path.join(log_dir, f"trace_{ctx.trace_id}_{ctx.span_id}")
    return trace(log_dir, perfetto=perfetto)


def enable_nan_checks(enable: bool = True) -> None:
    """Re-run suspect computations de-optimized and raise at NaN origin."""
    jax.config.update("jax_debug_nans", enable)


def latency_summary(samples_s) -> dict:
    """Order statistics over a list of latencies in seconds — the serving
    engine's per-request report (bench --serving, serve.Engine.stats)."""
    arr = np.asarray(list(samples_s), dtype=np.float64)
    if arr.size == 0:
        return {"n": 0, "count": 0, "p50_s": 0.0, "p95_s": 0.0, "p99_s": 0.0,
                "mean_s": 0.0, "max_s": 0.0}
    return {
        "n": int(arr.size),
        "count": int(arr.size),  # explicit alias: dashboards key on "count"
        "p50_s": float(np.percentile(arr, 50)),
        "p95_s": float(np.percentile(arr, 95)),
        "p99_s": float(np.percentile(arr, 99)),
        "mean_s": float(arr.mean()),
        "max_s": float(arr.max()),
    }
