"""Torch-free reader for torch ``*.pkl`` checkpoints (SURVEY.md §7 hard
part: "torch-pickle checkpoint conversion without torch installed").

A TPU host has no reason to carry a torch install just to ingest the
reference's ``bestloss.pkl``/``lastepoch.pkl`` (reference
multi_gpu_trainer.py:152-163 writes bare/nested ``state_dict`` pickles via
``torch.save``). This module parses torch's zip serialization format
directly — stdlib ``zipfile`` + ``pickle`` with a ``persistent_load`` hook,
tensors materialized as numpy arrays — so ``utils.checkpoint`` can fall back
to it whenever torch is absent. Parity with ``torch.load`` is pinned by
tests (torch is available in CI).

Format notes (validated against real ``torch.save`` output):

* the file is a zip archive: ``<name>/data.pkl`` holds the pickled object
  graph; each storage's raw bytes live at ``<name>/data/<key>``;
* tensors pickle as ``torch._utils._rebuild_tensor_v2(storage, offset,
  size, stride, requires_grad, hooks[, metadata])`` where ``storage``
  arrives through a persistent ID ``('storage', <StorageType>, key,
  location, numel)``;
* the legacy (pre-1.6, non-zip) format is NOT handled — every reference-era
  (2022) checkpoint uses the zip format; a clear error names torch as the
  escape hatch.
"""

from __future__ import annotations

import io
import pickle
import zipfile
from typing import Any

import numpy as np

#: torch storage-class name → numpy dtype (the classes themselves are
#: pickled BY NAME, so no torch import is needed to resolve them)
_STORAGE_DTYPES = {
    "FloatStorage": np.float32,
    "DoubleStorage": np.float64,
    "HalfStorage": np.float16,
    "LongStorage": np.int64,
    "IntStorage": np.int32,
    "ShortStorage": np.int16,
    "CharStorage": np.int8,
    "ByteStorage": np.uint8,
    "BoolStorage": np.bool_,
    # UntypedStorage carries no dtype; _rebuild_tensor_v2's metadata names it
    "UntypedStorage": None,
    "BFloat16Storage": "bfloat16",  # resolved lazily via ml_dtypes
}


class _NamedStub:
    """Placeholder for any torch class referenced only by name (storage
    classes, dtype singletons); records the name, compares by it."""

    def __init__(self, module: str, name: str):
        self.module, self.name = module, name

    def __call__(self, *args, **kwargs):  # tolerate constructed singletons
        return self  # (e.g. a dtype/device reduce) inside non-tensor state

    def __repr__(self):  # pragma: no cover - debug aid
        return f"<torch-stub {self.module}.{self.name}>"


def _np_dtype(storage_name: str):
    if storage_name not in _STORAGE_DTYPES:
        raise ValueError(f"unsupported torch storage type {storage_name!r}")
    dt = _STORAGE_DTYPES[storage_name]
    if dt == "bfloat16":
        import ml_dtypes  # jax dependency, present wherever this repo runs

        return np.dtype(ml_dtypes.bfloat16)
    return np.dtype(dt)


def _rebuild_tensor_v2(storage, offset, size, stride, *unused) -> np.ndarray:
    """numpy re-implementation of ``torch._utils._rebuild_tensor_v2``:
    a strided view into the storage buffer (torch strides are in ELEMENTS)."""
    buf, dtype = storage
    itemsize = dtype.itemsize
    if not size:  # 0-dim tensor
        return np.frombuffer(buf, dtype=dtype, count=1, offset=offset * itemsize
                             ).reshape(()).copy()
    flat = np.frombuffer(buf, dtype=dtype, offset=offset * itemsize)
    arr = np.lib.stride_tricks.as_strided(
        flat, shape=tuple(size), strides=tuple(s * itemsize for s in stride))
    return np.ascontiguousarray(arr)  # own the memory; drop the view


class _TorchUnpickler(pickle.Unpickler):
    """Resolves ``torch.*`` globals to stubs/shims and storages to
    ``(bytes, np.dtype)`` pairs read straight from the zip archive."""

    def __init__(self, data_pkl: bytes, read_record):
        super().__init__(io.BytesIO(data_pkl))
        self._read_record = read_record

    def find_class(self, module: str, name: str) -> Any:
        if module == "torch._utils" and name in (
            "_rebuild_tensor_v2", "_rebuild_tensor"
        ):
            return _rebuild_tensor_v2
        if module == "collections" and name == "OrderedDict":
            import collections

            return collections.OrderedDict
        if module.startswith("torch"):
            return _NamedStub(module, name)
        # a checkpoint is a state_dict: tensors, containers, scalars. Any
        # other global is either corruption or a malicious reduce (pickle's
        # DEFAULT find_class would import and hand back arbitrary callables
        # — e.g. os.system — for pickle to invoke). Refuse it.
        raise pickle.UnpicklingError(
            f"refusing non-checkpoint global {module}.{name} — this reader "
            "only loads torch state_dict-style checkpoints")

    def persistent_load(self, pid):
        kind, storage_type, key, _location, numel = pid
        if kind != "storage":
            raise pickle.UnpicklingError(f"unknown persistent id {kind!r}")
        name = (storage_type.name if isinstance(storage_type, _NamedStub)
                else getattr(storage_type, "__name__", str(storage_type)))
        dtype = _np_dtype(name)
        if dtype is None:
            raise ValueError(
                "untyped torch storage needs the dtype from tensor metadata "
                "— not produced by reference-era torch.save; load with torch")
        raw = self._read_record(key)
        expect = numel * dtype.itemsize
        if len(raw) != expect:
            raise ValueError(
                f"storage {key}: {len(raw)} bytes on disk, expected {expect}")
        return (raw, dtype)


def load(path: str) -> Any:
    """``torch.load(path, map_location='cpu')`` without torch: the object
    graph with every tensor as a numpy array. Dicts come back as plain
    dict/OrderedDict; unknown torch objects as named stubs."""
    with zipfile.ZipFile(path) as zf:
        names = zf.namelist()
        pkl = [n for n in names if n.endswith("/data.pkl") or n == "data.pkl"]
        if not pkl:
            raise ValueError(
                f"{path}: not a torch zip checkpoint (legacy pre-1.6 format?)"
                " — load it with torch, or re-save it with a current torch")
        root = pkl[0][: -len("data.pkl")]
        byteorder = "little"
        bo_name = root + "byteorder"
        if bo_name in names:
            byteorder = zf.read(bo_name).decode().strip() or "little"
        if byteorder != "little":
            raise ValueError(f"{path}: {byteorder}-endian checkpoint on a "
                             "little-endian host — load with torch")
        data_pkl = zf.read(pkl[0])

        def read_record(key):
            return zf.read(f"{root}data/{key}")

        return _TorchUnpickler(data_pkl, read_record).load()
