"""Torch-free reader for torch ``*.pkl`` checkpoints (SURVEY.md §7 hard
part: "torch-pickle checkpoint conversion without torch installed").

A TPU host has no reason to carry a torch install just to ingest the
reference's ``bestloss.pkl``/``lastepoch.pkl`` (reference
multi_gpu_trainer.py:152-163 writes bare/nested ``state_dict`` pickles via
``torch.save``). This module parses torch's zip serialization format
directly — stdlib ``zipfile`` + ``pickle`` with a ``persistent_load`` hook,
tensors materialized as numpy arrays — so ``utils.checkpoint`` can fall back
to it whenever torch is absent. Parity with ``torch.load`` is pinned by
tests (torch is available in CI).

Format notes (validated against real ``torch.save`` output):

* the file is a zip archive: ``<name>/data.pkl`` holds the pickled object
  graph; each storage's raw bytes live at ``<name>/data/<key>``;
* tensors pickle as ``torch._utils._rebuild_tensor_v2(storage, offset,
  size, stride, requires_grad, hooks[, metadata])`` where ``storage``
  arrives through a persistent ID ``('storage', <StorageType>, key,
  location, numel)``;
* the legacy (pre-1.6, non-zip) format is NOT handled — every reference-era
  (2022) checkpoint uses the zip format; a clear error names torch as the
  escape hatch.
"""

from __future__ import annotations

import io
import pickle
import zipfile
from typing import Any

import numpy as np

import contextlib
import os as _os


@contextlib.contextmanager
def atomic_replace(path: str):
    """Write-then-rename: yields a tmp path; on clean exit the tmp replaces
    ``path`` atomically, on error the tmp is removed — a kill mid-write can
    never leave a truncated file at the destination (which would poison
    every later load until hand-deleted). Clears stale tmp leftovers of
    either kind (a dir from a crashed orbax save shares the suffix). The
    ONE owner of this protocol for single-FILE checkpoint artifacts
    (orbax's directory swap in checkpoint.save_checkpoint is its own,
    two-rename protocol)."""
    tmp = path + ".writing"
    if _os.path.isdir(tmp):
        import shutil

        shutil.rmtree(tmp)
    elif _os.path.exists(tmp):
        _os.remove(tmp)
    try:
        yield tmp
        _os.replace(tmp, path)
    finally:
        if _os.path.exists(tmp):
            _os.remove(tmp)


#: largest tensor this reader will materialize (it copies, unlike
#: torch.load's cheap views) — far above any in-scope checkpoint, far below
#: a crafted 0-stride/huge-size allocation bomb
_MAX_TENSOR_BYTES = 2 << 30

def _check_materialization_cap(shape, itemsize: int, exc=None) -> tuple:
    """Normalize ``shape`` to a dims tuple and enforce the byte cap — the
    ONE owner of the policy shared by all three enforcement points
    (:func:`_rebuild_tensor_v2`, :class:`_BoundedNdarray`,
    ``_checked_reconstruct``), so they cannot drift."""
    import math

    dims = ((int(shape),) if isinstance(shape, (int, np.integer))
            else tuple(int(d) for d in shape))
    if math.prod(dims or (1,)) * max(1, int(itemsize)) > _MAX_TENSOR_BYTES:
        raise (exc or pickle.UnpicklingError)(
            f"array of shape {dims} (itemsize {itemsize}) exceeds the "
            f"{_MAX_TENSOR_BYTES}-byte materialization cap — load with torch")
    return dims


#: torch storage-class name → numpy dtype (the classes themselves are
#: pickled BY NAME, so no torch import is needed to resolve them)
_STORAGE_DTYPES = {
    "FloatStorage": np.float32,
    "DoubleStorage": np.float64,
    "HalfStorage": np.float16,
    "LongStorage": np.int64,
    "IntStorage": np.int32,
    "ShortStorage": np.int16,
    "CharStorage": np.int8,
    "ByteStorage": np.uint8,
    "BoolStorage": np.bool_,
    # UntypedStorage carries no dtype; _rebuild_tensor_v2's metadata names it
    "UntypedStorage": None,
    "BFloat16Storage": "bfloat16",  # resolved lazily via ml_dtypes
}


class _NamedStub:
    """Placeholder for any torch class referenced only by name (storage
    classes, dtype singletons); records the name, compares by it."""

    def __init__(self, module: str, name: str):
        self.module, self.name = module, name

    def __call__(self, *args, **kwargs):  # tolerate constructed singletons
        return self  # (e.g. a dtype/device reduce) inside non-tensor state

    def __repr__(self):  # pragma: no cover - debug aid
        return f"<torch-stub {self.module}.{self.name}>"


def _np_dtype(storage_name: str) -> np.dtype:
    if storage_name not in _STORAGE_DTYPES:
        raise ValueError(f"unsupported torch storage type {storage_name!r}")
    dt = _STORAGE_DTYPES[storage_name]
    if dt is None:  # UntypedStorage: numel is in BYTES and the dtype lives
        raise ValueError(  # in tensor metadata this reader doesn't consume
            "untyped torch storage needs the dtype from tensor metadata "
            "— not produced by reference-era torch.save; load with torch")
    if dt == "bfloat16":
        import ml_dtypes  # jax dependency, present wherever this repo runs

        return np.dtype(ml_dtypes.bfloat16)
    return np.dtype(dt)


def _rebuild_tensor_v2(storage, offset, size, stride, *unused) -> np.ndarray:
    """numpy re-implementation of ``torch._utils._rebuild_tensor_v2``:
    a strided view into the storage buffer (torch strides are in ELEMENTS).

    size/stride/offset come from the pickle stream INDEPENDENTLY of the
    storage length, so they are validated against it before ``as_strided``
    — unchecked they would address arbitrary process memory (the tensor-path
    analogue of the find_class hardening below)."""
    buf, dtype = storage
    itemsize = dtype.itemsize
    size, stride = tuple(size), tuple(stride)
    if offset < 0 or any(d < 0 for d in size) or any(s < 0 for s in stride):
        raise ValueError(
            f"corrupt tensor metadata: offset={offset} size={size} "
            f"stride={stride}")
    if not size:  # 0-dim tensor
        if (offset + 1) * itemsize > len(buf):
            raise ValueError("corrupt tensor metadata: offset past storage")
        return np.frombuffer(buf, dtype=dtype, count=1, offset=offset * itemsize
                             ).reshape(()).copy()
    if 0 in size:
        return np.zeros(size, dtype=dtype)
    # this reader MATERIALIZES tensors, so 0-stride expand() metadata (a
    # cheap view under torch.load) or a crafted size could demand an
    # unbounded allocation from a tiny storage
    _check_materialization_cap(size, itemsize, exc=ValueError)
    flat = np.frombuffer(buf, dtype=dtype, offset=offset * itemsize)
    span = sum((d - 1) * s for d, s in zip(size, stride)) + 1
    if span > flat.size:
        raise ValueError(
            f"corrupt tensor metadata: size={size} stride={stride} span "
            f"{span} elements exceeds storage of {flat.size}")
    arr = np.lib.stride_tricks.as_strided(
        flat, shape=size, strides=tuple(s * itemsize for s in stride))
    # UNCONDITIONAL copy (ascontiguousarray would no-op on an already-
    # contiguous view): the view over frombuffer(bytes) is read-only and
    # pins the whole storage buffer alive
    return np.array(arr)


#: the numpy reconstruction globals a checkpoint's METADATA may legitimately
#: reference (numpy-typed scalars/arrays in e.g. a lastepoch dict) — mirrors
#: torch's own weights_only allowlist; anything else stays refused
_NUMPY_ALLOWLIST = frozenset(
    (mod, name)
    for mod in ("numpy._core.multiarray", "numpy.core.multiarray")
    for name in ("scalar", "_reconstruct")
) | frozenset((("numpy", "dtype"), ("numpy", "ndarray"),
               ("_codecs", "encode"),  # numpy scalar payloads pickle via it
               # protocol 2 pickles EMPTY bytes as the bytes global itself
               # (non-empty go via _codecs.encode); the constructor of a
               # primitive is safe to resolve
               ("__builtin__", "bytes"), ("builtins", "bytes")))


class _BoundedNdarray(np.ndarray):
    """ndarray whose construction is capped at ``_MAX_TENSOR_BYTES`` —
    handed out in place of the raw ``numpy.ndarray`` global so a crafted
    pickle cannot request an unbounded uninitialized allocation. Legit
    metadata arrays (built via numpy's ``_reconstruct`` + setstate, whose
    payload is bounded by the file itself) work unchanged."""

    def __new__(cls, shape=0, *args, **kwargs):
        dtype = kwargs.get("dtype", args[0] if args else np.float64)
        _check_materialization_cap(shape, np.dtype(dtype).itemsize)
        return super().__new__(cls, shape, *args, **kwargs)

    def __setstate__(self, state):
        # pickle's BUILD re-allocates the array at the C level to the
        # STATE's shape before any payload-length check (list payloads are
        # not size-validated by numpy) — the same cap must gate it, and
        # object dtypes (arbitrary embedded pickles) are refused outright
        if isinstance(state, tuple) and len(state) >= 3:
            shape, dtype = state[1], state[2]
            try:
                dt = np.dtype(dtype)
            except TypeError:
                dt = np.dtype("O")
            if dt.hasobject:
                raise pickle.UnpicklingError(
                    "object-dtype arrays are not loadable by the torch-free "
                    "reader — load with torch")
            _check_materialization_cap(shape, dt.itemsize)
        super().__setstate__(state)


class _TorchUnpickler(pickle.Unpickler):
    """Resolves ``torch.*`` globals to stubs/shims and storages to
    ``(bytes, np.dtype)`` pairs read straight from the zip archive."""

    def __init__(self, data_pkl: bytes, read_record):
        super().__init__(io.BytesIO(data_pkl))
        self._read_record = read_record
        self._storages: dict = {}  # key → (raw, dtype): tied weights share
        # one storage; torch.load dedups by key, so must we (else N aliases
        # cost N reads + N transient buffers)

    def find_class(self, module: str, name: str) -> Any:
        if module == "torch._utils" and name in (
            "_rebuild_tensor_v2", "_rebuild_tensor"
        ):
            return _rebuild_tensor_v2
        if module == "torch._utils" and name == "_rebuild_parameter":
            # Parameter(tensor, requires_grad, hooks) → just the tensor; a
            # stub here would silently discard the already-rebuilt data
            return lambda t, *a: t
        if module == "torch._tensor" and name == "_rebuild_from_type_v2":
            # tensor SUBCLASSES (nn.Buffer, plain Tensor wrappers) pickle as
            # _rebuild_from_type_v2(func, type, args, state): rebuild the
            # underlying tensor, drop the subclass identity
            return lambda func, typ, args, state=None: func(*args)
        if ((module == "torch" or module.startswith("torch."))
                and name.startswith("_rebuild")):
            # any OTHER rebuild flavor (quantized, wrapper subclass, …):
            # a stub would swallow the tensor silently — surface the escape
            # hatch instead
            raise pickle.UnpicklingError(
                f"unsupported tensor rebuild {module}.{name} — load with "
                "torch")
        if module == "collections" and name == "OrderedDict":
            import collections

            return collections.OrderedDict
        if (module, name) in _NUMPY_ALLOWLIST:
            # numpy scalars/arrays in checkpoint metadata (e.g. a
            # numpy-averaged loss_rec in a lastepoch dict) — resolve the
            # small reconstruction set torch's own weights_only unpickler
            # allows, nothing else
            if name == "bytes":
                return bytes  # '__builtin__' (py2 spelling) isn't importable
            if name == "ndarray":
                # a bounded stand-in: numpy's _reconstruct bootstrap passes
                # it as the subtype, but a crafted REDUCE(ndarray, (2**40,))
                # would otherwise allocate terabytes from a tiny file,
                # sidestepping the tensor-path materialization cap
                return _BoundedNdarray
            import importlib

            resolved = getattr(importlib.import_module(module), name)
            if name == "_reconstruct":
                # the real C _reconstruct allocates via ndarray.__new__ at
                # the C level, skipping _BoundedNdarray's Python __new__ —
                # cap its shape argument here (itemsize ≥ 1, so an element
                # count over the byte cap is always over the byte cap)
                def _checked_reconstruct(subtype, shape, *args, **kwargs):
                    try:  # the dtype rides the same untrusted stream: a
                        # crafted 'V100000000' itemsize would otherwise
                        # stretch an in-cap element count into a 100 GB
                        # allocation
                        itemsize = np.dtype(args[0]).itemsize if args else 1
                    except TypeError:
                        itemsize = 1
                    _check_materialization_cap(shape, itemsize)
                    return resolved(subtype, shape, *args, **kwargs)

                return _checked_reconstruct
            return resolved
        if module == "torch" or module.startswith("torch."):
            # torch proper only: torchvision/torch_* and every other foreign
            # module stays refused below (a stub there would be silent data
            # loss, not a passive singleton)
            return _NamedStub(module, name)
        # a checkpoint is a state_dict: tensors, containers, scalars. Any
        # other global is either corruption or a malicious reduce (pickle's
        # DEFAULT find_class would import and hand back arbitrary callables
        # — e.g. os.system — for pickle to invoke). Refuse it.
        raise pickle.UnpicklingError(
            f"refusing non-checkpoint global {module}.{name} — this reader "
            "only loads torch state_dict-style checkpoints")

    def persistent_load(self, pid):
        kind, storage_type, key, _location, numel = pid
        if kind != "storage":
            raise pickle.UnpicklingError(f"unknown persistent id {kind!r}")
        name = (storage_type.name if isinstance(storage_type, _NamedStub)
                else getattr(storage_type, "__name__", str(storage_type)))
        dtype = _np_dtype(name)  # raises on UntypedStorage (byte-counted)
        if key in self._storages:
            raw, cached_dtype, cached_numel = self._storages[key]
            # EVERY pid is validated, cached or not: a crafted second pid
            # reusing the key with a different dtype/numel must not ride the
            # first pid's validation
            if cached_dtype != dtype or cached_numel != numel:
                raise ValueError(
                    f"storage {key}: conflicting persistent ids "
                    f"({cached_dtype}/{cached_numel} vs {dtype}/{numel})")
            return (raw, dtype)
        raw = self._read_record(key)
        expect = numel * dtype.itemsize
        if len(raw) != expect:
            raise ValueError(
                f"storage {key}: {len(raw)} bytes on disk, expected {expect}")
        self._storages[key] = (raw, dtype, numel)
        return (raw, dtype)


#: numpy dtype name → torch storage-class name (inverse of _STORAGE_DTYPES)
_DTYPE_STORAGES = {
    "float32": "FloatStorage",
    "float64": "DoubleStorage",
    "float16": "HalfStorage",
    "int64": "LongStorage",
    "int32": "IntStorage",
    "int16": "ShortStorage",
    "int8": "CharStorage",
    "uint8": "ByteStorage",
    "bool": "BoolStorage",
    "bfloat16": "BFloat16Storage",
}


class _FakeGlobal:
    """Stands in for a torch global we must NAME in the pickle stream
    (``torch.FloatStorage``, ``torch._utils._rebuild_tensor_v2``) without
    importing torch: the writer below emits it as a plain GLOBAL opcode, and
    the real torch.load resolves the name to the real object."""

    def __init__(self, module: str, name: str):
        self.module, self.name = module, name

    def __call__(self, *a, **k):  # never invoked; pickle's save_reduce
        raise TypeError("stand-in global")  # merely requires a callable


class _TensorProxy:
    """A numpy array destined to become a torch tensor in the stream."""

    def __init__(self, arr: np.ndarray, key: int):
        self.arr, self.key = arr, key


class _TorchPickler(pickle._Pickler):  # Python impl: save_global overridable
    """Emits torch's object graph: tensors as REDUCE of
    ``torch._utils._rebuild_tensor_v2`` over a persistent storage id —
    byte-compatible with what ``torch.save`` writes (protocol 2, the torch
    default)."""

    def save_global(self, obj, name=None):
        if isinstance(obj, _FakeGlobal):
            # GLOBAL by name, skipping pickle's import-and-verify (torch is
            # exactly what this host doesn't have)
            self.write(b"c" + obj.module.encode("utf-8") + b"\n"
                       + obj.name.encode("utf-8") + b"\n")
            self.memoize(obj)
            return
        return super().save_global(obj, name)

    def persistent_id(self, obj):
        if isinstance(obj, _PersistentStorage):
            return obj.pid
        return None

    def reducer_override(self, obj):  # py3.8+: checked before dispatch
        if isinstance(obj, _FakeGlobal):
            # a string reduce means "save as a global of this name" — pickle
            # routes it to save_global, where the override above emits the
            # torch name without importing torch
            return obj.name
        if isinstance(obj, _TensorProxy):
            a = obj.arr
            storage = _FakeGlobal(
                "torch", _DTYPE_STORAGES[a.dtype.name])
            pid = _PersistentStorage(
                ("storage", storage, str(obj.key), "cpu", int(a.size)))
            stride = tuple(s // a.itemsize for s in a.strides)
            return (_FakeGlobal("torch._utils", "_rebuild_tensor_v2"),
                    (pid, 0, a.shape, stride, False,
                     __import__("collections").OrderedDict()))
        return NotImplemented


class _PersistentStorage:
    """Wrapper whose presence routes through the pickler's persistent-id
    machinery (torch.load's unpickler calls persistent_load with the pid)."""

    def __init__(self, pid):
        self.pid = pid


def save(obj: Any, path: str) -> None:
    """``torch.save(obj, path)`` without torch: numpy arrays become torch
    tensors on the reading side (real ``torch.load`` resolves the named
    globals; :func:`load` resolves them to numpy). Arrays are written
    C-contiguous."""
    tensors: list[np.ndarray] = []
    seen: dict[int, _TensorProxy] = {}  # same ndarray object → one storage
    # record (torch.save preserves ties; views over a shared base still
    # write separate records — this dedups identity, not aliasing)

    def proxy(x):
        if isinstance(x, np.ndarray):
            if id(x) in seen:
                return seen[id(x)]
            if x.dtype.name not in _DTYPE_STORAGES:
                raise ValueError(
                    f"unsupported numpy dtype {x.dtype} for torch export — "
                    f"supported: {sorted(_DTYPE_STORAGES)}")
            # native byte order: dtype.name drops the order, so a '>f4'
            # array would otherwise be written byte-swapped under the
            # 'little' stamp — silently corrupt for torch.load
            native = x.astype(x.dtype.newbyteorder("="), copy=False)
            # reshape restores 0-dim: ascontiguousarray is at-least-1-d,
            # which would round-trip a scalar tensor as shape [1]
            arr = np.ascontiguousarray(native).reshape(x.shape)
            tensors.append(arr)
            seen[id(x)] = _TensorProxy(arr, len(tensors) - 1)
            return seen[id(x)]
        if isinstance(x, dict):
            # keys go through the same conversion/refusal as values (a
            # frozenset key would write a checkpoint only torch could
            # reopen; a numpy-scalar key would trip weights_only loads)
            return {proxy(k): proxy(v) for k, v in x.items()}
        if isinstance(x, tuple) and hasattr(x, "_fields"):
            # a namedtuple pickles as a GLOBAL of its defining module, which
            # load()'s strict find_class refuses — writing one would produce
            # a checkpoint only a torch host could reopen (asymmetry)
            raise ValueError(
                f"namedtuple {type(x).__name__} is not round-trippable "
                "through the torch-free reader — convert to a plain "
                "tuple/dict before export")
        if isinstance(x, (list, tuple)):
            return type(x)(proxy(v) for v in x)
        if isinstance(x, np.generic):
            # plain Python scalar: a numpy scalar would pickle via numpy
            # reconstruction globals that torch>=2.6's default
            # weights_only=True load refuses (measured) — .item() is
            # lossless and loads everywhere
            return x.item()
        if x is None or isinstance(x, (bool, int, float, str, bytes)):
            return x  # scalars this module's own load() can read back
        # anything else (a set, a custom object, …) would pickle via a
        # global that load()'s strict find_class refuses — a checkpoint only
        # a torch host could reopen. Refuse symmetrically at write time.
        raise ValueError(
            f"unsupported value of type {type(x).__name__} for torch "
            "export — checkpoints hold arrays, containers, and scalars")

    import sys as _sys

    if _sys.byteorder != "little":
        # arr.tobytes() would be big-endian under the 'little' stamp below —
        # a checkpoint real torch.load silently misreads
        raise ValueError("torch-free writer supports little-endian hosts "
                         "only — save with torch on this machine")
    graph = proxy(obj)
    buf = io.BytesIO()
    _TorchPickler(buf, protocol=2).dump(graph)
    with atomic_replace(path) as tmp:
        with zipfile.ZipFile(tmp, "w", zipfile.ZIP_STORED) as zf:
            zf.writestr("archive/data.pkl", buf.getvalue())
            zf.writestr("archive/version", "3")
            zf.writestr("archive/byteorder", "little")
            for i, arr in enumerate(tensors):
                # arr is C-contiguous: a flat memoryview writes without the
                # extra full copy tobytes() would make. Fallback for buffers
                # memoryview/zipfile can't take (0-dim, exotic dtypes).
                try:
                    # cast('B'): len() must be the BYTE count — zipfile
                    # sizes its zip64 decision from len(), and a typed view
                    # reports elements
                    payload = arr.reshape(-1).data.cast("B")
                except (TypeError, ValueError):
                    payload = arr.tobytes()
                zf.writestr(f"archive/data/{i}", payload)


def load(path: str) -> Any:
    """``torch.load(path, map_location='cpu')`` without torch: the object
    graph with every tensor as a numpy array. Dicts come back as plain
    dict/OrderedDict; unknown torch objects as named stubs."""
    try:
        zf_ctx = zipfile.ZipFile(path)
    except zipfile.BadZipFile:
        raise ValueError(
            f"{path}: not a torch zip checkpoint (legacy pre-1.6 format?)"
            " — load it with torch, or re-save it with a current torch")
    with zf_ctx as zf:
        names = zf.namelist()
        pkl = [n for n in names if n.endswith("/data.pkl") or n == "data.pkl"]
        if not pkl:
            raise ValueError(
                f"{path}: not a torch zip checkpoint (legacy pre-1.6 format?)"
                " — load it with torch, or re-save it with a current torch")
        root = pkl[0][: -len("data.pkl")]
        byteorder = "little"
        bo_name = root + "byteorder"
        if bo_name in names:
            byteorder = zf.read(bo_name).decode().strip() or "little"
        import sys as _sys

        if byteorder != _sys.byteorder:
            # np.frombuffer would silently misread cross-endian bytes
            raise ValueError(f"{path}: {byteorder}-endian checkpoint on a "
                             f"{_sys.byteorder}-endian host — load with torch")
        data_pkl = zf.read(pkl[0])

        def read_record(key):
            return zf.read(f"{root}data/{key}")

        return _TorchUnpickler(data_pkl, read_record).load()
