"""Image grid rendering + output-path helper (reference CLI surface, C23).

``save_grid`` replaces the matplotlib ImageGrid figures (ViT.py:283-305) with
a direct PIL tiling — no matplotlib dependency on TPU hosts, same artifact.
``get_next_path`` fixes the reference's infinite loop (ViT.py:307-313 never
increments ``i``; SURVEY.md quirk #3).
"""

from __future__ import annotations

import os

import numpy as np
from PIL import Image


def to_uint8(img: np.ndarray) -> np.ndarray:
    """[0,1] float HWC → uint8."""
    return (np.clip(np.asarray(img), 0.0, 1.0) * 255.0 + 0.5).astype(np.uint8)


def save_grid(images: np.ndarray, path: str, *, nrows: int, ncols: int, pad: int = 2) -> str:
    """Tile (N, H, W, C) images in [0,1] into an nrows×ncols grid PNG."""
    images = np.asarray(images)
    n, h, w, c = images.shape
    canvas = np.full(
        (nrows * h + (nrows - 1) * pad, ncols * w + (ncols - 1) * pad, c), 255, np.uint8
    )
    for idx in range(min(n, nrows * ncols)):
        r, col = divmod(idx, ncols)
        y, x = r * (h + pad), col * (w + pad)
        canvas[y : y + h, x : x + w] = to_uint8(images[idx])
    Image.fromarray(canvas.squeeze()).save(path)
    return path


def grid_shape(n: int) -> tuple[int, int]:
    """(nrows, ncols) for tiling n images: ⌊√n⌋ columns, rows ceil-divided so
    every sample is shown (the reference's 16×16 grid generalized)."""
    ncols = max(int(n**0.5), 1)
    return -(-n // ncols), ncols


def get_next_path(pth: str) -> str:
    """First non-existing ``<stem>_<i><ext>`` (reference intent, loop fixed)."""
    prefix, ext = os.path.splitext(pth)
    i = 1
    file_path = pth
    while os.path.isfile(file_path):
        file_path = f"{prefix}_{i}{ext}"
        i += 1
    return file_path
