"""Bounded-liveness guard for one-shot evidence scripts on the remote TPU.

A dropped tunnel leaves the next XLA RPC blocked forever with no exception
to catch — observed r03 (bench, fixed with bench.py's inline watchdog) and
again r05 (`scripts/fid_trend.py`: 45 min flat I/O, SIGINT-immune, stage 4
blocked behind it; results/tunnel_diag_r05.txt). A script that hangs until
an outer kill records nothing, and killing a client that holds the chip
grant is itself what wedges the tunnel (utils/platform.py) — so every
chip-touching evidence script bounds its own silent windows and exits with
a partial artifact instead.

This is bench.py's beacon/watchdog pattern extracted for the smaller
scripts (fid_trend, publish_run): call :meth:`mark` before every
potentially-silent device interaction; a watchdog thread aborts the process
(``on_abort`` then ``os._exit(exit_code)``) if no mark lands within the
stall budget. ``os._exit`` is deliberate — the main thread is parked in a
native call that will never re-enter the interpreter (r05: two SIGINTs
delivered, neither KeyboardInterrupt ever fired), so cooperative shutdown
cannot work.
"""

from __future__ import annotations

import os
import sys
import threading
import time
from typing import Callable, Optional


class StallWatchdog:
    """Abort the process when no :meth:`mark` lands within ``stall_s``.

    ``stall_s`` ≤ 0 disables the guard (CPU runs have no tunnel to wedge).
    ``budget_s`` on a mark stretches the deadline for the single window
    AFTER it — known-long silent operations (a first Mosaic compile at
    N=2501 exceeds any sane default) must not be killed as wedged.

    ``exit_code=None`` selects SOFT mode for long-running in-process hosts
    (the serving engine): on stall the watchdog calls ``on_abort`` once and
    stops, WITHOUT ``os._exit`` — the abort hook unblocks waiters (fails
    their tickets) while the wedged native call stays parked on its own
    thread. One-shot evidence scripts keep the hard default: their main
    thread IS the wedged one, so only process death frees anything.
    """

    def __init__(self, stall_s: float, *, exit_code: Optional[int] = 3,
                 on_abort: Optional[Callable[[str, float], None]] = None,
                 name: str = "watchdog"):
        self.stall_s = float(stall_s)
        self.exit_code = exit_code
        self.on_abort = on_abort
        self.name = name
        self._state = {"t": time.time(), "label": "start",  # guarded-by: _lock
                       "budget": None, "done": False}
        self._lock = threading.Lock()

    def mark(self, label: str, budget_s: Optional[float] = None) -> None:
        with self._lock:
            self._state.update(t=time.time(), label=label, budget=budget_s)

    def done(self) -> None:
        """Disarm — call when the script's artifact is fully written."""
        with self._lock:
            self._state["done"] = True

    def start(self) -> "StallWatchdog":
        if self.stall_s > 0:
            threading.Thread(target=self._run, daemon=True).start()
        return self

    def _run(self) -> None:
        while True:
            time.sleep(min(15.0, max(0.05, self.stall_s / 4)))
            with self._lock:
                if self._state["done"]:
                    return
                limit = max(self.stall_s, self._state["budget"] or 0.0)
                silent = time.time() - self._state["t"]
                label = self._state["label"]
            if silent > limit:
                print(f"[{self.name}] STALL: no progress for {silent:.0f}s "
                      f"(> {limit:.0f}s) after {label!r} — aborting with "
                      f"partial artifact (wedged-tunnel guard)",
                      file=sys.stderr, flush=True)
                if self.on_abort is not None:
                    try:
                        self.on_abort(label, silent)
                    except Exception as e:  # noqa: BLE001 — abort must abort
                        print(f"[{self.name}] on_abort failed: {e!r}",
                              file=sys.stderr, flush=True)
                if self.exit_code is None:  # soft mode: one-shot, no exit
                    self.done()
                    return
                os._exit(self.exit_code)
