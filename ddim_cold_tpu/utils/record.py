"""Bench-record parsing shared by bench.py's captured-earlier fallback and
the recovery chain's idempotence oracle (scripts/r04_stage_done.py) — ONE
policy for "what is the record in this file" and "was it captured on a real
accelerator", so the chain and the bench can never disagree about whether a
committed results file is a reusable TPU record."""

from __future__ import annotations

import json
from typing import Optional


def last_json_record(path: str) -> Optional[dict]:
    """Last parseable JSON line of ``path`` — a fatal/watchdog emit can
    print the record twice, and the last one is the most complete. None when
    the file is missing/empty/garbage."""
    try:
        with open(path) as f:
            lines = [ln for ln in f.read().splitlines() if ln.strip()]
    except OSError:
        return None
    for ln in reversed(lines):
        try:
            rec = json.loads(ln)
        except ValueError:
            continue
        return rec if isinstance(rec, dict) else None
    return None


def is_tpu_record(rec) -> bool:
    """True when ``rec`` is a bench record captured on a real accelerator —
    chip recorded and not a CPU fallback."""
    return bool(isinstance(rec, dict) and rec.get("chip")
                and "cpu" not in str(rec["chip"]).lower())
