"""Bench-record parsing shared by bench.py's captured-earlier fallback and
the recovery chain's idempotence oracle (scripts/r04_stage_done.py) — ONE
policy for "what is the record in this file" and "was it captured on a real
accelerator", so the chain and the bench can never disagree about whether a
committed results file is a reusable TPU record."""

from __future__ import annotations

import json
from typing import Optional


def last_json_record(path: str) -> Optional[dict]:
    """Last parseable JSON line of ``path`` — a fatal/watchdog emit can
    print the record twice, and the last one is the most complete. None when
    the file is missing/empty/garbage."""
    try:
        with open(path) as f:
            lines = [ln for ln in f.read().splitlines() if ln.strip()]
    except OSError:
        return None
    for ln in reversed(lines):
        try:
            rec = json.loads(ln)
        except ValueError:
            continue
        return rec if isinstance(rec, dict) else None
    return None


def is_tpu_record(rec) -> bool:
    """True when ``rec`` is a bench record captured on a real accelerator —
    chip recorded and not a CPU fallback."""
    return bool(isinstance(rec, dict) and rec.get("chip")
                and "cpu" not in str(rec["chip"]).lower())


def run_metadata(chip=None, repo=None) -> dict:
    """The provenance stamp every bench JSON carries (``run_meta``): git
    sha, device kind, jax/jaxlib versions, round, and an EXTERNALLY-supplied
    timestamp — ``obs/trend.py`` orders and annotates series points off it
    instead of inferring from filenames.

    The timestamp comes from ``DDIM_COLD_RUN_TS`` (seconds since epoch; the
    driver/chain exports it) or ``SOURCE_DATE_EPOCH``, never from the wall
    clock here — an unstamped environment yields ``None`` rather than a
    value that would make re-runs nondeterministic. Versions come from
    package metadata, not ``import jax`` — this helper must stay importable
    from the host-only trend/attrib layer (graftcheck A004)."""
    import os
    import subprocess

    here = repo or os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    sha = None
    try:
        out = subprocess.run(["git", "rev-parse", "--short", "HEAD"],
                             cwd=here, capture_output=True, text=True,
                             timeout=10)
        sha = out.stdout.strip() or None
    except Exception:  # noqa: BLE001 — no git / not a checkout: stamp None
        sha = None

    def _version(dist):
        try:
            from importlib.metadata import version
            return version(dist)
        except Exception:  # noqa: BLE001 — uninstalled dist: stamp None
            return None

    ts = None
    raw_ts = (os.environ.get("DDIM_COLD_RUN_TS")
              or os.environ.get("SOURCE_DATE_EPOCH") or "").strip()
    if raw_ts:
        try:
            ts = float(raw_ts)
        except ValueError:
            ts = raw_ts  # ISO strings still order lexicographically
    rnd = os.environ.get("DDIM_COLD_ROUND", "").strip()
    return {
        "git_sha": sha,
        "device_kind": chip,
        "jax": _version("jax"),
        "jaxlib": _version("jaxlib"),
        "timestamp": ts,
        "round": int(rnd) if rnd.isdigit() else None,
    }
