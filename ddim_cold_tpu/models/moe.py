"""Switch-style mixture-of-experts MLP — the ``ep`` (expert-parallel) axis.

The reference is data-parallel only (SURVEY.md C17) and its ViT uses a dense
MLP (reference ViT.py:74-90); this module is TPU-native scale-out beyond
parity: ``num_experts`` in the YAML swaps each block's MLP for a top-1
routed expert bank (Switch Transformer, arXiv:2101.03961) whose stacked
expert parameters shard over an ``expert`` mesh axis
(parallel/sharding.py). The routing math is pure one-hot einsum
dispatch/combine — static shapes, no gather/scatter, no host control flow —
so XLA lays the token exchange onto ICI collectives by itself.

Design notes (TPU-first):

* routing is per batch row over its N tokens with per-expert capacity
  ``C = ceil(N / E · capacity_factor)`` — everything stays (B, …)-leading,
  so the ``data`` batch sharding composes untouched;
* overflow tokens are DROPPED by the expert (their MLP delta is zero) and
  ride the block's residual connection unchanged — the Switch paper's
  behavior, and what keeps shapes static;
* the router runs in float32 (softmax stability under bf16 compute);
* the Switch load-balance auxiliary loss is ``sow``n into the ``losses``
  collection; the train step adds ``moe_aux_weight ×`` its mean (it is a
  no-op for consumers that do not mark the collection mutable, so the
  sampler/eval paths need no changes).

Two dispatch implementations, selectable per config (``moe_dispatch``):

* ``"einsum"`` (default) — one-hot dispatch/combine tensors (B, N, E, C)
  with E·C ≈ N·capacity_factor, i.e. **O(B·N²·cf) activation memory per
  MoE block**: all-GEMM, no gather/scatter, the friendliest form for the
  XLA partitioner — and fine at the 64px scales (N ≤ 257);
* ``"index"`` — stable-sort tokens by expert id, gather each expert's
  capacity slice, scatter-free token-side combine via a per-token slot
  gather: **O(B·N·cf·D)** activations, no quadratic tensor anywhere. The
  stable sort preserves token order within an expert, so exactly the same
  tokens overflow as under the einsum path's cumsum priority — the two
  modes are numerically interchangeable (tested) — making MoE composable
  with long-sequence configs (the 200px/p4 N=2501 case that motivated it,
  ADVICE r3).
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
from flax import linen as nn

from ddim_cold_tpu.models.init import trunc_normal

Dtype = Any


class SwitchMlp(nn.Module):
    """Top-1 routed expert bank, drop-in for the block's dense ``Mlp``."""

    num_experts: int
    hidden_features: int
    out_features: int
    capacity_factor: float = 1.25
    drop: float = 0.0
    dtype: Dtype = jnp.float32
    dispatch: str = "einsum"  # "einsum" (one-hot GEMMs) | "index" (sort/gather)

    @nn.compact
    def __call__(self, x: jax.Array, deterministic: bool = True) -> jax.Array:
        import math

        B, N, D = x.shape
        E, H = self.num_experts, self.hidden_features
        # per-expert queue length: static at trace time (N, E, cf all static)
        C = max(1, math.ceil(N * self.capacity_factor / E))

        # ---- router (f32: softmax stability under bf16 compute) ----------
        wr = self.param("router", trunc_normal(std=0.02), (D, E), jnp.float32)
        logits = jnp.einsum("bnd,de->bne", x.astype(jnp.float32), wr)
        probs = jax.nn.softmax(logits, axis=-1)  # (B, N, E)
        expert = jnp.argmax(probs, axis=-1)  # (B, N)
        gate = jnp.max(probs, axis=-1)  # (B, N)

        onehot = jax.nn.one_hot(expert, E, dtype=jnp.float32)  # (B, N, E)
        if self.dispatch == "index":
            # sort/gather routing, O(B·N·cf·D): stable sort by expert id
            # groups tokens per expert WITHOUT changing their order inside a
            # group, so slot priority (and therefore the overflow set) is
            # identical to the einsum path's cumsum priority.
            perm = jnp.argsort(expert, axis=1, stable=True)          # (B, N)
            exp_sorted = jnp.take_along_axis(expert, perm, axis=1)   # (B, N)
            x_sorted = jnp.take_along_axis(
                x.astype(self.dtype), perm[..., None], axis=1)       # (B, N, D)
            counts = jnp.sum(onehot, axis=1).astype(jnp.int32)       # (B, E)
            starts = jnp.cumsum(counts, axis=1) - counts             # (B, E)
            # expert e's queue slot c holds sorted token starts[e] + c
            c_ar = jnp.arange(C, dtype=jnp.int32)
            idx = starts[:, :, None] + c_ar[None, None, :]           # (B, E, C)
            q_valid = c_ar[None, None, :] < counts[:, :, None]       # (B, E, C)
            idx = jnp.clip(idx, 0, N - 1).reshape(B, E * C)
            xe = jnp.take_along_axis(x_sorted, idx[..., None], axis=1)
            xe = (xe.reshape(B, E, C, D)
                  * q_valid[..., None].astype(self.dtype))
        elif self.dispatch == "einsum":
            # position of each token in its expert's queue (per batch row)
            pos = jnp.cumsum(onehot, axis=1) - onehot  # (B, N, E)
            within = pos < C
            keep = onehot * within  # (B, N, E) — dropped tokens zero out here
            slot = jax.nn.one_hot(
                (pos * onehot).sum(-1).astype(jnp.int32), C, dtype=jnp.float32)
            # dispatch/combine one-hots (B, N, E, C): static-shape einsum routing
            dispatch = keep[..., None] * slot[:, :, None, :]
            combine = dispatch * gate[..., None, None]
            xe = jnp.einsum("bnd,bnec->becd", x.astype(self.dtype),
                            dispatch.astype(self.dtype))
        else:
            raise ValueError(
                f"dispatch must be 'einsum' or 'index', got {self.dispatch!r}")

        # ---- experts: stacked params, leading E shards over 'expert' -----
        O = self.out_features
        w1 = self.param("w1", trunc_normal(std=0.02), (E, D, H), jnp.float32)
        b1 = self.param("b1", nn.initializers.zeros_init(), (E, H), jnp.float32)
        w2 = self.param("w2", trunc_normal(std=0.02), (E, H, O), jnp.float32)
        b2 = self.param("b2", nn.initializers.zeros_init(), (E, O), jnp.float32)

        h = jnp.einsum("becd,edh->bech", xe, w1.astype(self.dtype))
        h = h + b1.astype(self.dtype)[None, :, None, :]
        h = nn.gelu(h, approximate=False)
        h = nn.Dropout(self.drop, deterministic=deterministic)(h)
        ye = jnp.einsum("bech,ehd->becd", h, w2.astype(self.dtype))
        ye = ye + b2.astype(self.dtype)[None, :, None, :]
        if self.dispatch == "index":
            # token-side combine: each token reads its own queue slot (a
            # gather, no (B, N, E, C) combine tensor). pos = this token's
            # rank within its expert group, recovered by inverting the sort.
            rank = (jnp.arange(N, dtype=jnp.int32)[None, :]
                    - jnp.take_along_axis(starts, exp_sorted, axis=1))
            # invert the sort by scattering rank back to token order — O(N),
            # where a second argsort would be another full TPU sort
            tok_pos = jnp.put_along_axis(jnp.zeros_like(rank), perm, rank,
                                         axis=1, inplace=False)      # (B, N)
            keep_tok = tok_pos < C
            slot_tok = jnp.clip(expert.astype(jnp.int32) * C + tok_pos,
                                0, E * C - 1)
            y = jnp.take_along_axis(ye.reshape(B, E * C, O),
                                    slot_tok[..., None], axis=1)
            w_tok = (gate * keep_tok).astype(self.dtype)
            y = y * w_tok[..., None]
        else:
            y = jnp.einsum("becd,bnec->bnd", ye, combine.astype(self.dtype))
        y = nn.Dropout(self.drop, deterministic=deterministic)(y)

        # ---- Switch load-balance loss: E · Σ_e f_e · P_e -----------------
        # f_e = fraction of tokens routed to e, P_e = mean router prob of e
        frac = onehot.mean(axis=(0, 1))  # (E,)
        mean_prob = probs.mean(axis=(0, 1))  # (E,)
        self.sow("losses", "moe_aux", E * jnp.sum(frac * mean_prob))
        return y
