"""Switch-style mixture-of-experts MLP — the ``ep`` (expert-parallel) axis.

The reference is data-parallel only (SURVEY.md C17) and its ViT uses a dense
MLP (reference ViT.py:74-90); this module is TPU-native scale-out beyond
parity: ``num_experts`` in the YAML swaps each block's MLP for a top-1
routed expert bank (Switch Transformer, arXiv:2101.03961) whose stacked
expert parameters shard over an ``expert`` mesh axis
(parallel/sharding.py). The routing math is pure one-hot einsum
dispatch/combine — static shapes, no gather/scatter, no host control flow —
so XLA lays the token exchange onto ICI collectives by itself.

Design notes (TPU-first):

* routing is per batch row over its N tokens with per-expert capacity
  ``C = ceil(N / E · capacity_factor)`` — everything stays (B, …)-leading,
  so the ``data`` batch sharding composes untouched;
* overflow tokens are DROPPED by the expert (their MLP delta is zero) and
  ride the block's residual connection unchanged — the Switch paper's
  behavior, and what keeps shapes static;
* the router runs in float32 (softmax stability under bf16 compute);
* the Switch load-balance auxiliary loss is ``sow``n into the ``losses``
  collection; the train step adds ``moe_aux_weight ×`` its mean (it is a
  no-op for consumers that do not mark the collection mutable, so the
  sampler/eval paths need no changes).

When to use: the one-hot dispatch/combine tensors are (B, N, E, C) floats
with E·C ≈ N·capacity_factor, i.e. **O(B·N²·cf) activation memory per MoE
block** — negligible at the 64px scales this ships tested at (N ≤ 257), but
at the 200px/p4 config (N = 2501) the dispatch tensors alone would be
~25 MB·B·cf per block in bf16 and dominate HBM long before the expert
banks do (ADVICE r3). Pairing MoE with long-sequence configs needs an
index-based (argsort/segment-sum) dispatch first — prefer dense MLP + the
``seq`` axis there until then.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
from flax import linen as nn

from ddim_cold_tpu.models.init import trunc_normal

Dtype = Any


class SwitchMlp(nn.Module):
    """Top-1 routed expert bank, drop-in for the block's dense ``Mlp``."""

    num_experts: int
    hidden_features: int
    out_features: int
    capacity_factor: float = 1.25
    drop: float = 0.0
    dtype: Dtype = jnp.float32

    @nn.compact
    def __call__(self, x: jax.Array, deterministic: bool = True) -> jax.Array:
        import math

        B, N, D = x.shape
        E, H = self.num_experts, self.hidden_features
        # per-expert queue length: static at trace time (N, E, cf all static)
        C = max(1, math.ceil(N * self.capacity_factor / E))

        # ---- router (f32: softmax stability under bf16 compute) ----------
        wr = self.param("router", trunc_normal(std=0.02), (D, E), jnp.float32)
        logits = jnp.einsum("bnd,de->bne", x.astype(jnp.float32), wr)
        probs = jax.nn.softmax(logits, axis=-1)  # (B, N, E)
        expert = jnp.argmax(probs, axis=-1)  # (B, N)
        gate = jnp.max(probs, axis=-1)  # (B, N)

        onehot = jax.nn.one_hot(expert, E, dtype=jnp.float32)  # (B, N, E)
        # position of each token in its expert's queue (per batch row)
        pos = jnp.cumsum(onehot, axis=1) - onehot  # (B, N, E)
        within = pos < C
        keep = onehot * within  # (B, N, E) — dropped tokens zero out here
        slot = jax.nn.one_hot(
            (pos * onehot).sum(-1).astype(jnp.int32), C, dtype=jnp.float32)
        # dispatch/combine one-hots (B, N, E, C): static-shape einsum routing
        dispatch = keep[..., None] * slot[:, :, None, :]
        combine = dispatch * gate[..., None, None]

        # ---- experts: stacked params, leading E shards over 'expert' -----
        O = self.out_features
        w1 = self.param("w1", trunc_normal(std=0.02), (E, D, H), jnp.float32)
        b1 = self.param("b1", nn.initializers.zeros_init(), (E, H), jnp.float32)
        w2 = self.param("w2", trunc_normal(std=0.02), (E, H, O), jnp.float32)
        b2 = self.param("b2", nn.initializers.zeros_init(), (E, O), jnp.float32)

        xe = jnp.einsum("bnd,bnec->becd", x.astype(self.dtype),
                        dispatch.astype(self.dtype))
        h = jnp.einsum("becd,edh->bech", xe, w1.astype(self.dtype))
        h = h + b1.astype(self.dtype)[None, :, None, :]
        h = nn.gelu(h, approximate=False)
        h = nn.Dropout(self.drop, deterministic=deterministic)(h)
        ye = jnp.einsum("bech,ehd->becd", h, w2.astype(self.dtype))
        ye = ye + b2.astype(self.dtype)[None, :, None, :]
        y = jnp.einsum("becd,bnec->bnd", ye, combine.astype(self.dtype))
        y = nn.Dropout(self.drop, deterministic=deterministic)(y)

        # ---- Switch load-balance loss: E · Σ_e f_e · P_e -----------------
        # f_e = fraction of tokens routed to e, P_e = mean router prob of e
        frac = onehot.mean(axis=(0, 1))  # (E,)
        mean_prob = probs.mean(axis=(0, 1))  # (E,)
        self.sow("losses", "moe_aux", E * jnp.sum(frac * mean_prob))
        return y
