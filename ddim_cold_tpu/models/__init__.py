from ddim_cold_tpu.models.vit import (
    DiffusionViT,
    MODEL_CONFIGS,
    positionalencoding1d,
    sp_clone,
)
from ddim_cold_tpu.models import init

__all__ = ["DiffusionViT", "MODEL_CONFIGS", "positionalencoding1d",
           "sp_clone", "init"]
