"""DiffusionViT — the x0-predicting Vision Transformer backbone, TPU-first.

Re-implements the reference's ``DiffusionVisionTransformer`` (ViT.py:158-218;
the trainer imports the identical copy in ViT_draft2drawing.py:175-238 — the
build keeps ONE module, SURVEY.md quirk #6) as a Flax linen module:

* NHWC image layout (TPU-native; the torch reference is NCHW — the checkpoint
  converter in utils/checkpoint.py handles the transpose).
* Patch embedding as reshape + Dense instead of Conv2d: for kernel=stride=p
  the two are identical linear maps, and the reshape+matmul form feeds the MXU
  one large GEMM with no im2col.
* Attention as einsum with float32 softmax; mlp_ratio defaults to 1.0 and
  qkv_bias to True per the reference ctor defaults (ViT.py:160-162).
* Time conditioning: a learned ``Embed(total_steps, dim)`` row added to every
  token together with the learned positional embedding (ViT.py:204-205).
* Output head predicts the clean image x̂0 directly: Linear(dim → C·p²) then
  un-patchify with the exact pixel mapping of the reference's
  ``view/permute(0,5,1,3,2,4)/view`` (ViT.py:214-217).
* Stochastic depth linearly scaled 0 → drop_path_rate across blocks
  (ViT.py:176), active only in training; dropout 0.1 on pos/attn/proj/mlp.

Compute dtype is configurable (bfloat16 replaces the reference's CUDA AMP);
parameters always live in float32.
"""

from __future__ import annotations

import math
from typing import Any, Optional, Sequence

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh

from ddim_cold_tpu.models.init import torch_default_uniform, trunc_normal

Dtype = Any

#: Model configurations appearing in the reference (SURVEY.md §2 table).
MODEL_CONFIGS = {
    # reference ViT.py:277
    "oxford_flower_64": dict(
        img_size=(64, 64), patch_size=4, embed_dim=256, depth=6, num_heads=4
    ),
    # reference ViT.py:274 / 20220822.yaml:12-15 / ViT_draft2drawing.py:342
    "vit_tiny": dict(
        img_size=(64, 64), patch_size=8, embed_dim=384, depth=7, num_heads=12
    ),
    # checkpoint name only (README.md:28-29); config absent upstream — both
    # plausible patch sizes are provided, selectable by state-dict shapes.
    "oxford_flower_200_p4": dict(
        img_size=(200, 200), patch_size=4, embed_dim=256, depth=6, num_heads=4
    ),
    "oxford_flower_200_p8": dict(
        img_size=(200, 200), patch_size=8, embed_dim=384, depth=7, num_heads=12
    ),
}


def positionalencoding1d(d_model: int, length: int) -> np.ndarray:
    """Sinusoidal 1-D positional encoding (reference ViT_draft2drawing.py:140-156).

    Kept as an option for large-image configs (>64px), where the reference
    sketches swapping the learned pos_embed for this fixed table
    (ViT_draft2drawing.py:191-193).
    """
    if d_model % 2 != 0:
        raise ValueError(f"Cannot use sin/cos positional encoding with odd dim {d_model}")
    pe = np.zeros((length, d_model), dtype=np.float32)
    position = np.arange(0, length, dtype=np.float32)[:, None]
    div_term = np.exp(np.arange(0, d_model, 2, dtype=np.float32) * -(math.log(10000.0) / d_model))
    pe[:, 0::2] = np.sin(position * div_term)
    pe[:, 1::2] = np.cos(position * div_term)
    return pe


class _DenseParams(nn.Module):
    """Declares an ``nn.Dense``'s ``{kernel, bias}`` leaves — same names,
    shapes, dtypes and initializers as Mlp's denses, at the same module path
    when given the same ``name`` — WITHOUT computing the matmul. The float
    fused-Mlp path consumes the raw leaves (ops/quant.mlp_pallas), and the
    identical param structure keeps a fused and an unfused model
    interchangeable on one param tree."""

    features: int

    @nn.compact
    def __call__(self, in_features: int):
        kernel = self.param("kernel", trunc_normal(std=0.02),
                            (in_features, self.features))
        bias = self.param("bias", nn.initializers.zeros_init(),
                          (self.features,))
        return kernel, bias


class Mlp(nn.Module):
    """2-layer GELU MLP with dropout after both linears (reference ViT.py:74-90)."""

    hidden_features: int
    out_features: int
    drop: float = 0.0
    dtype: Dtype = jnp.float32
    quant: Optional[str] = None  # None | "xla" | "pallas" | "w8a8" (ops/quant.py)
    fused: bool = False  # whole fc1 → GELU → fc2 chain as ONE Pallas kernel
    # (ops/quant.mlp_pallas) — the (M, hidden) activation never exists in HBM

    @nn.compact
    def __call__(self, x: jax.Array, deterministic: bool = True) -> jax.Array:
        # fused trunk Mlp: inference path — the inter-linear dropouts must be
        # inactive (training with drop > 0 falls through to the unfused
        # composition, which applies them), and quant="xla" explicitly opts
        # out of Pallas kernels. The param holders declare the exact leaves
        # the unfused denses would, so both paths share one tree.
        if (self.fused and self.quant != "xla"
                and (deterministic or self.drop == 0.0)):
            from ddim_cold_tpu.ops import tuning
            from ddim_cold_tpu.ops.quant import QuantParams, mlp_pallas

            x = x.astype(self.dtype)
            in_features = x.shape[-1]
            if self.quant:
                w1, s1, b1 = QuantParams(
                    self.hidden_features, name="fc1")(in_features)
                w2, s2, b2 = QuantParams(
                    self.out_features, name="fc2")(self.hidden_features)
                mode = self.quant  # "pallas" (w8a16) | "w8a8"
                act_dt = jnp.int8 if self.quant == "w8a8" else x.dtype
            else:
                w1, b1 = _DenseParams(
                    self.hidden_features, name="fc1")(in_features)
                w2, b2 = _DenseParams(
                    self.out_features, name="fc2")(self.hidden_features)
                s1 = s2 = None
                mode = None
                act_dt = x.dtype
            bm = tuning.mlp_block_m(in_features, self.hidden_features, act_dt,
                                    quant=self.quant is not None)
            return mlp_pallas(x, w1, b1, w2, b2, scale1=s1, scale2=s2,
                              mode=mode, block_m=bm)

        if self.quant:
            from ddim_cold_tpu.ops.quant import QuantDense

            dense = lambda feat, name: QuantDense(
                feat, dtype=self.dtype, mode=self.quant, name=name)
        else:
            dense = lambda feat, name: nn.Dense(
                feat,
                dtype=self.dtype,
                kernel_init=trunc_normal(std=0.02),
                bias_init=nn.initializers.zeros_init(),
                name=name,
            )
        x = dense(self.hidden_features, "fc1")(x)
        x = nn.gelu(x, approximate=False)
        x = nn.Dropout(self.drop, deterministic=deterministic)(x)
        x = dense(self.out_features, "fc2")(x)
        x = nn.Dropout(self.drop, deterministic=deterministic)(x)
        return x


class Attention(nn.Module):
    """Multi-head self-attention, fused-QKV (reference ViT.py:93-117).

    Returns ``(x, attn)`` like the reference so the attention-probe path
    (Block.return_attention) stays expressible — EXCEPT when the Pallas
    fused kernel runs (``use_flash`` on, ``need_weights=False``, attention
    dropout inactive), which never materializes the weights and returns
    ``(x, None)``. Callers that need the weights must pass
    ``need_weights=True`` (Block does this for its probe path). Softmax runs
    in float32 regardless of compute dtype; the einsum layout keeps the two
    contractions as plain batched GEMMs for the MXU.
    """

    dim: int
    num_heads: int = 8
    qkv_bias: bool = False
    qk_scale: Optional[float] = None
    attn_drop: float = 0.0
    proj_drop: float = 0.0
    dtype: Dtype = jnp.float32
    # False = dense einsum; True = Pallas fused kernel; "xla" = pure-XLA
    # blockwise online-softmax (no kernel to reject, bounded memory)
    use_flash: "bool | str" = False
    # Pallas kernel block sizes (block_q, block_kv); None = the kernel's
    # defaults. A tuning knob for long-sequence configs — e.g. block_kv >= N
    # makes K/V fully VMEM-resident (single-chunk, no online-softmax loop).
    # Applies to the plain flash path and ulysses' local flash attention;
    # ring sp has its own per-device chunking and ignores it.
    flash_blocks: Optional[tuple] = None
    # sequence parallelism: rotate K/V blocks around `seq_axis` of `seq_mesh`
    # (parallel/ring_attention.py); `batch_axis` keeps dp sharding composed,
    # `head_axis` keeps tensor-parallel head sharding effective inside the ring.
    # `sp_mode` picks the strategy: "ring" (ppermute K/V rotation) or
    # "ulysses" (all-to-all head↔seq reshard, parallel/ulysses.py).
    seq_mesh: Optional[Mesh] = None
    seq_axis: Optional[str] = None
    batch_axis: Optional[str] = None
    head_axis: Optional[str] = None
    sp_mode: str = "ring"
    # manual-collective mode (pipe×sp composition): the module is ALREADY
    # inside a shard_map whose manual axes include ``seq_axis`` (the
    # pipeline executor, parallel/pipeline.py) — call the inner sp kernel
    # (``sp_mode``: ring rotation or the ulysses all-to-all pair) directly
    # on the local shard instead of wrapping a new shard_map.
    # ``seq_valid_len`` is the unpadded global sequence length (ring masks
    # the padding via kv_valid; ulysses slices it off between its two
    # all-to-alls); ``seq_varying_axes`` names every manual axis the
    # activations vary over, for the ring accumulators' vma typing
    # (ulysses needs none — its body is stateless).
    seq_manual: bool = False
    seq_valid_len: Optional[int] = None
    seq_varying_axes: Optional[tuple] = None
    quant: Optional[str] = None  # w8a16 qkv/proj kernels (ops/quant.py)
    fused: bool = False  # qkv dequant-GEMM → flash → proj dequant-GEMM as
    # ONE Pallas kernel (ops/flash_attention.fused_trunk_attention); needs
    # quant in ("pallas", "w8a8") — the dequant producer IS the fusion

    @nn.compact
    def __call__(self, x: jax.Array, deterministic: bool = True,
                 need_weights: bool = True):
        B, N, C = x.shape
        head_dim = C // self.num_heads
        scale = self.qk_scale or head_dim**-0.5

        # Flash/ring/fused paths never materialize the O(N²) weights, so they
        # require inactive attention-dropout (else fall back to einsum) and
        # no weight probing.
        weightless_ok = not need_weights and (deterministic or self.attn_drop == 0.0)
        seq_parallel = self.seq_mesh is not None and self.seq_axis is not None

        # fused sampler trunk: the qkv dequant-matmul runs INSIDE the flash
        # kernel as producer and the proj dequant-matmul consumes the
        # attention output in place — the (B, N, 3C) qkv and (B, N, C)
        # context activations never round-trip through HBM. Inference only
        # (no VJP); the probe path (need_weights=True) and sp fall through
        # to the unfused composition below, whose QuantDense declares the
        # identical param leaves — one tree serves both.
        if (self.fused and self.quant in ("pallas", "w8a8")
                and not seq_parallel and not self.seq_manual
                and weightless_ok):
            from ddim_cold_tpu.ops import tuning
            from ddim_cold_tpu.ops.flash_attention import fused_trunk_attention
            from ddim_cold_tpu.ops.quant import QuantParams

            w_qkv, s_qkv, b_qkv = QuantParams(
                3 * self.dim, use_bias=self.qkv_bias, name="qkv")(C)
            w_proj, s_proj, b_proj = QuantParams(
                self.dim, use_bias=True, name="proj")(C)
            # explicit flash_blocks win (they also pin the unfused path's kv
            # chunking — SAME block_kv is what makes fused≡unfused bitwise);
            # otherwise the committed autotune table for this geometry
            act_dt = jnp.int8 if self.quant == "w8a8" else self.dtype
            blocks = self.flash_blocks or tuning.attn_blocks(
                N, C, self.num_heads, act_dt)
            out = fused_trunk_attention(
                x.astype(self.dtype), w_qkv, s_qkv, b_qkv,
                w_proj, s_proj, b_proj,
                num_heads=self.num_heads, scale=scale,
                block_q=blocks[0], block_kv=blocks[1],
                mode="w8a8" if self.quant == "w8a8" else "pallas")
            out = nn.Dropout(self.proj_drop, deterministic=deterministic)(out)
            return out, None

        if self.quant:
            from ddim_cold_tpu.ops.quant import QuantDense

            dense = lambda feat, use_bias, name: QuantDense(
                feat, use_bias=use_bias, dtype=self.dtype, mode=self.quant,
                name=name)
        else:
            dense = lambda feat, use_bias, name: nn.Dense(
                feat,
                use_bias=use_bias,
                dtype=self.dtype,
                kernel_init=trunc_normal(std=0.02),
                bias_init=nn.initializers.zeros_init(),
                name=name,
            )
        qkv = dense(3 * self.dim, self.qkv_bias, "qkv")(x)
        # unpack order (3, heads, head_dim) matches the torch reshape
        # (B,N,3,H,hd) so converted checkpoints line up slice-for-slice.
        qkv = qkv.reshape(B, N, 3, self.num_heads, head_dim)
        q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]  # (B, N, H, hd)

        if seq_parallel and not need_weights and not weightless_ok:
            # falling back to dense here would silently materialize the full
            # O(N²) global attention matrix — the exact thing sp exists to
            # avoid. Configs must zero attn_drop (trainer.build_model does).
            # (need_weights=True — the probe path — deliberately still falls
            # through to the dense global einsum.)
            raise ValueError(
                "sequence-parallel attention cannot apply attention-dropout "
                f"(attn_drop={self.attn_drop} active in training); set "
                "attn_drop_rate=0.0 on the model")
        if self.seq_manual and not weightless_ok:
            # no dense fallback exists inside the manual region — a local
            # einsum would silently attend block-diagonally
            raise ValueError(
                "manual sequence-parallel attention cannot apply "
                "attention-dropout or return weights — set "
                "attn_drop_rate=0.0 and need_weights=False")
        if self.seq_manual:
            # inside an enclosing manual shard_map (pipeline executor,
            # pipe×sp): x is the LOCAL (B', N/sp, C) shard; run the inner
            # sp kernel over the already-manual seq axis. A tp 'model'
            # axis, if any, stays GSPMD-auto via the param specs. Padding
            # tokens (dim padded up to the axis size) are masked (ring) or
            # sliced between the all-to-alls (ulysses) via seq_valid_len.
            if self.sp_mode == "ulysses":
                from ddim_cold_tpu.parallel.ulysses import ulysses_attention

                out = ulysses_attention(
                    q, k, v, axis_name=self.seq_axis,
                    n_valid=self.seq_valid_len, scale=scale,
                    use_flash=self.use_flash, flash_blocks=self.flash_blocks,
                ).astype(self.dtype)
            else:
                from ddim_cold_tpu.parallel.ring_attention import ring_attention

                valid = None
                if self.seq_valid_len is not None:
                    pos = (jax.lax.axis_index(self.seq_axis) * N
                           + jnp.arange(N))
                    valid = jnp.broadcast_to(
                        (pos < self.seq_valid_len)[None, :], (B, N))
                out = ring_attention(
                    q, k, v, valid, axis_name=self.seq_axis, scale=scale,
                    varying_axes=self.seq_varying_axes,
                ).astype(self.dtype)
            attn = None
        elif seq_parallel and weightless_ok:
            if self.sp_mode == "ulysses":
                from ddim_cold_tpu.parallel.ulysses import ulysses_self_attention

                # tp composition: the all-to-all splits each tp group's
                # LOCAL heads over the seq axis (ulysses.py head_axis)
                out = ulysses_self_attention(
                    q, k, v, self.seq_mesh,
                    axis=self.seq_axis, batch_axis=self.batch_axis,
                    head_axis=self.head_axis,
                    scale=scale, use_flash=self.use_flash,
                    flash_blocks=self.flash_blocks,
                ).astype(self.dtype)
            else:
                from ddim_cold_tpu.parallel.ring_attention import ring_self_attention

                out = ring_self_attention(
                    q, k, v, self.seq_mesh,
                    axis=self.seq_axis, batch_axis=self.batch_axis,
                    head_axis=self.head_axis, scale=scale,
                ).astype(self.dtype)
            attn = None
        elif self.use_flash and weightless_ok:
            if self.use_flash == "xla":
                # pure-XLA blockwise path: no Pallas to reject, bounded
                # memory — the safety net / inference middle path (its scan
                # backward saves per-block carries, so prefer the kernel for
                # training where it lowers)
                from ddim_cold_tpu.ops.flash_attention import (
                    blockwise_attention_xla,
                )

                out = blockwise_attention_xla(
                    q, k, v, scale,
                    *((self.flash_blocks[1],) if self.flash_blocks else ())
                ).astype(self.dtype)
            else:
                from ddim_cold_tpu.ops.flash_attention import flash_attention

                # None defers to the kernel's own defaults — one source of truth
                out = flash_attention(
                    q, k, v, scale, *(self.flash_blocks or ())).astype(self.dtype)
            attn = None
        else:
            logits = jnp.einsum("bnhd,bmhd->bhnm", q, k) * scale
            attn = jax.nn.softmax(logits.astype(jnp.float32), axis=-1).astype(self.dtype)
            attn = nn.Dropout(self.attn_drop, deterministic=deterministic)(attn)
            out = jnp.einsum("bhnm,bmhd->bnhd", attn, v)

        out = out.reshape(B, N, C)
        out = dense(self.dim, True, "proj")(out)
        out = nn.Dropout(self.proj_drop, deterministic=deterministic)(out)
        return out, attn


class Block(nn.Module):
    """Pre-LN transformer block with stochastic-depth residuals (reference ViT.py:120-138)."""

    dim: int
    num_heads: int
    mlp_ratio: float = 4.0
    qkv_bias: bool = False
    qk_scale: Optional[float] = None
    drop: float = 0.0
    attn_drop: float = 0.0
    drop_path: float = 0.0
    dtype: Dtype = jnp.float32
    use_flash: "bool | str" = False  # False | True (Pallas) | "xla" (blockwise)
    flash_blocks: Optional[tuple] = None
    seq_mesh: Optional[Mesh] = None
    seq_axis: Optional[str] = None
    batch_axis: Optional[str] = None
    head_axis: Optional[str] = None
    sp_mode: str = "ring"
    # manual-collective sp (pipe×sp; see Attention.seq_manual)
    seq_manual: bool = False
    seq_valid_len: Optional[int] = None
    seq_varying_axes: Optional[tuple] = None
    num_experts: int = 1  # >1: Switch-MoE MLP (models/moe.py, 'expert' axis)
    moe_capacity_factor: float = 1.25
    moe_dispatch: str = "einsum"  # routing impl: "einsum" | "index" (moe.py)
    quant: Optional[str] = None  # w8a16 trunk denses (ops/quant.py)
    fused: bool = False  # fused trunk kernels (Attention + Mlp megakernels)

    @nn.compact
    def __call__(self, x: jax.Array, deterministic: bool = True,
                 return_attention: bool = False,
                 dp_rate: Optional[jax.Array] = None):
        if self.quant and self.num_experts > 1:
            raise ValueError(
                "quant covers the dense trunk only — the Switch-MoE expert "
                "banks have no quantized path (set num_experts=1)")
        ln = lambda name: nn.LayerNorm(epsilon=1e-5, dtype=self.dtype, name=name)
        y, attn = Attention(
            dim=self.dim,
            num_heads=self.num_heads,
            qkv_bias=self.qkv_bias,
            qk_scale=self.qk_scale,
            attn_drop=self.attn_drop,
            proj_drop=self.drop,
            dtype=self.dtype,
            use_flash=self.use_flash,
            flash_blocks=self.flash_blocks,
            seq_mesh=self.seq_mesh,
            seq_axis=self.seq_axis,
            batch_axis=self.batch_axis,
            head_axis=self.head_axis,
            sp_mode=self.sp_mode,
            seq_manual=self.seq_manual,
            seq_valid_len=self.seq_valid_len,
            seq_varying_axes=self.seq_varying_axes,
            quant=self.quant,
            fused=self.fused,
            name="attn",
        )(ln("norm1")(x), deterministic=deterministic,
          need_weights=return_attention)
        if return_attention:
            return attn

        # per-sample stochastic depth (reference ViT.py:52-71): Bernoulli(keep)
        # mask broadcast over all but the batch dim, survivors scaled 1/keep —
        # exactly nn.Dropout with broadcast_dims. Under nn.scan the rate
        # arrives as a traced per-block scalar (``dp_rate``) — no Python
        # branching on it allowed, so the mask is drawn explicitly.
        if dp_rate is None:
            residual = nn.Dropout(self.drop_path, broadcast_dims=(1, 2),
                                  deterministic=deterministic)
        elif deterministic:
            residual = lambda y: y
        else:
            def residual(y, _rate=dp_rate):
                keep = 1.0 - _rate
                mask = jax.random.bernoulli(
                    self.make_rng("dropout"), keep, (y.shape[0], 1, 1))
                return jnp.where(mask, y / keep, jnp.zeros_like(y)).astype(y.dtype)

        x = x + residual(y)
        if self.num_experts > 1:
            from ddim_cold_tpu.models.moe import SwitchMlp

            mlp = SwitchMlp(
                num_experts=self.num_experts,
                hidden_features=int(self.dim * self.mlp_ratio),
                out_features=self.dim,
                capacity_factor=self.moe_capacity_factor,
                drop=self.drop,
                dtype=self.dtype,
                dispatch=self.moe_dispatch,
                name="moe",
            )
        else:
            mlp = Mlp(
                hidden_features=int(self.dim * self.mlp_ratio),
                out_features=self.dim,
                drop=self.drop,
                dtype=self.dtype,
                quant=self.quant,
                fused=self.fused,
                name="mlp",
            )
        y = mlp(ln("norm2")(x), deterministic=deterministic)
        x = x + residual(y)
        return x


def block_template(model: "DiffusionViT", *, seq_manual_axis=None,
                   seq_valid_len=None, seq_varying_axes=None) -> "Block":
    """Unbound single-layer Block matching ``model``'s scan_blocks layout —
    the pipeline executor (parallel/pipeline.py) applies it functionally per
    stage layer with slices of the stacked ``blocks`` params (drop-path rate
    arrives traced). Module-level fn: constructing a child inside an unbound
    module method trips flax's parent tracking.

    ``seq_manual_axis`` builds the pipe×sp variant: attention runs the inner
    ring kernel over that (already-manual) axis on the local shard."""
    return Block(
        dim=model.embed_dim, num_heads=model.num_heads, mlp_ratio=model.mlp_ratio,
        qkv_bias=model.qkv_bias, qk_scale=model.qk_scale, drop=model.drop_rate,
        attn_drop=model.attn_drop_rate, drop_path=0.0, dtype=model.dtype,
        use_flash=model.use_flash, flash_blocks=model.flash_blocks,
        sp_mode=model.sp_mode,
        seq_manual=seq_manual_axis is not None, seq_axis=seq_manual_axis,
        seq_valid_len=seq_valid_len, seq_varying_axes=seq_varying_axes,
        num_experts=model.num_experts,
        moe_capacity_factor=model.moe_capacity_factor,
        moe_dispatch=model.moe_dispatch,
    )


class _ScanShell(nn.Module):
    """Scan-compatible adapter around Block: ``(carry, (det, dp_rate)) →
    (carry, None)``. ``nn.scan`` over this stacks every block's params on a
    leading depth axis — one compiled block regardless of depth, and the
    substrate pipeline parallelism shards stages from."""

    blk: "Block"

    @nn.compact
    def __call__(self, x, deterministic, dp_rate):
        return self.blk(x, deterministic, dp_rate=dp_rate), None


class PatchEmbed(nn.Module):
    """Image → patch tokens as one GEMM (reference ViT.py:141-155 uses Conv2d).

    For kernel=stride=p a convolution is exactly a linear map on flattened
    patches; the reshape+Dense form is the MXU-friendly expression. The patch
    feature order (row, col, channel — channel fastest) matches the torch conv
    weight layout after ``W.transpose(2,3,1,0).reshape(p²C, E)`` so converted
    checkpoints are bit-identical.

    Init: torch Conv2d default (kaiming_uniform a=√5) — the reference's
    ``_init_weights`` skips Conv2d (models/init.py docstring).
    """

    patch_size: int
    embed_dim: int
    in_chans: int = 3
    dtype: Dtype = jnp.float32

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        B, H, W, C = x.shape
        p = self.patch_size
        hp, wp = H // p, W // p
        fan_in = C * p * p
        x = x.reshape(B, hp, p, wp, p, C)
        x = x.transpose(0, 1, 3, 2, 4, 5)  # (B, hp, wp, p, p, C)
        x = x.reshape(B, hp * wp, p * p * C)
        x = nn.Dense(
            self.embed_dim,
            dtype=self.dtype,
            kernel_init=torch_default_uniform(fan_in),
            bias_init=torch_default_uniform(fan_in),
            name="proj",
        )(x)
        return x


class DiffusionViT(nn.Module):
    """The diffusion backbone: ``(x_t, t) → x̂0`` (reference ViT.py:158-218).

    Inputs are NHWC in [−1, 1]; ``t`` is an int32 vector of per-sample steps in
    [0, total_steps). Out-of-range steps produce NaN outputs (JAX fills
    out-of-bounds gathers) — the traced-code analogue of torch's IndexError.
    Constructor defaults mirror the reference ctor (ViT.py:160-162):
    mlp_ratio=1.0, qkv_bias=True, all drop rates 0.1, total_steps=2000.
    ``diff_step``-style cold configs keep the full 2000-row time-embedding
    table (SURVEY.md quirk #4) unless ``total_steps`` is overridden.
    """

    img_size: Sequence[int] = (64, 64)
    patch_size: int = 8
    in_chans: int = 3
    embed_dim: int = 256
    depth: int = 3
    num_heads: int = 4
    mlp_ratio: float = 1.0
    qkv_bias: bool = True
    qk_scale: Optional[float] = None
    drop_rate: float = 0.1
    attn_drop_rate: float = 0.1
    drop_path_rate: float = 0.1
    total_steps: int = 2000
    dtype: Dtype = jnp.float32
    use_sincos_pos: bool = False  # fixed sinusoidal pos table for >64px configs (C7)
    use_flash: "bool | str" = False  # False=dense | True=Pallas fused | "xla"=
    # pure-XLA blockwise online-softmax (long-seq configs; "xla" is the
    # Mosaic-free safety net)
    flash_blocks: Optional[tuple] = None  # (block_q, block_kv) kernel tuning
    remat: bool = False  # jax.checkpoint each block: recompute activations in
    # backward instead of holding depth× residuals in HBM (big-config training)
    # sequence parallelism (ring attention over `seq_axis` of `seq_mesh`;
    # `batch_axis` composes with dp sharding) — sequences beyond one chip
    seq_mesh: Optional[Mesh] = None
    seq_axis: Optional[str] = None
    batch_axis: Optional[str] = None
    head_axis: Optional[str] = None  # tp axis for head-sharded ring attention
    sp_mode: str = "ring"  # "ring" | "ulysses" (all-to-all head resharding)
    scan_blocks: bool = False  # nn.scan over depth: params stacked on a
    # leading layer axis (O(1) compile in depth; pipeline-parallel substrate)
    num_experts: int = 1  # >1: Switch-MoE MLP per block (models/moe.py);
    # expert params shard over an 'expert' mesh axis. Composes with
    # scan_blocks (the scan stacks the sown aux losses on the layer axis)
    # AND with pipe (the pipeline stage body re-sows: each block call's aux
    # is accumulated across the schedule, bubble steps masked, and returned
    # through the pipelined apply's mutable=["losses"] path — pipeline.py).
    moe_capacity_factor: float = 1.25
    moe_dispatch: str = "einsum"  # see models/moe.py: "index" removes the
    # O(N^2*cf) one-hot dispatch tensors (long-sequence configs)
    quant: Optional[str] = None  # w8a16 trunk inference (ops/quant.py):
    # None = float kernels (the training path, bit-identical to before);
    # "xla" | "pallas" = per-output-channel int8 qkv/proj/fc1/fc2 consumed
    # from a quantize_params tree; embeddings/norms/patch/head stay float.
    # Part of the module hash, so jit/AOT program caches key on it.
    fused: bool = False  # fused sampler-trunk megakernels (inference): with
    # quant="pallas"/"w8a8" the attention runs qkv-dequant → flash → proj as
    # ONE kernel and the Mlp as another (ops/flash_attention.py, ops/quant.py);
    # with quant=None only the float fused Mlp applies. Declares the SAME
    # param leaves as the unfused composition — one tree serves both — and
    # the training/probe/sp paths silently fall back to it.

    @property
    def num_patches(self) -> int:
        return (self.img_size[0] // self.patch_size) * (self.img_size[1] // self.patch_size)


    @nn.compact
    def __call__(
        self,
        x: jax.Array,
        t: jax.Array,
        deterministic: bool = True,
        return_attention_layer: Optional[int] = None,
        stage: str = "full",
        tokens: Optional[jax.Array] = None,
        skip_blocks: Optional[tuple] = None,
        block_delta: Optional[jax.Array] = None,
        capture_split: Optional[int] = None,
        capture_tokens: bool = False,
        token_cache: Optional[tuple] = None,
        token_k: Optional[int] = None,
    ) -> jax.Array:
        """``stage`` partitions the forward for pipeline parallelism
        (parallel/pipeline.py): ``"embed"`` returns the token sequence after
        patch/pos/time embedding; ``"head"`` takes ``tokens`` (the trunk
        output, supplied by the pipeline) and runs final-LN → head →
        un-patchify; ``"full"`` is the normal forward.

        Step-cache hooks (ops/step_cache.py, Δ-DiT-style training-free
        sampler acceleration):

        * ``capture_split=s`` (static, 1 ≤ s < depth) — a *refresh* forward:
          run every block and additionally return the cumulative residual
          deltas of the front (blocks [0, s)) and rear (blocks [s, depth))
          trunk halves, ``(x̂0, (delta_front, delta_rear))``. Each delta is
          the (B, N+1, E) token-stream displacement the half contributes;
          because blocks are residual, the sum over a contiguous range is
          exactly ``tokens_out − tokens_in`` of that range.
        * ``skip_blocks=(lo, hi)`` + ``block_delta`` (static range, traced
          delta) — a *reuse* forward: blocks [lo, hi) are never executed;
          their cached cumulative delta is added to the token stream where
          block ``lo`` would have run. The skipped blocks' parameters are
          untouched (flax ``apply`` tolerates unused params), so reuse steps
          pay only the remaining blocks' FLOPs.

        Both are static trace-time decisions — no device branching — and are
        mutually exclusive with each other, with ``scan_blocks`` (one scanned
        body cannot statically drop layers), with the attention probe, and
        with partial ``stage`` forwards.

        Token-cache hooks (JiT-style spatial caching, arXiv:2603.10744 —
        ``cache_mode="token"`` in ops/step_cache.py):

        * ``capture_tokens=True`` — a *refresh* forward: run every block on
          every token and return ``(x̂0, (ref_in, trunk_delta))`` where
          ``ref_in`` is the post-embed token stream (the reference each
          later step measures per-token change against) and ``trunk_delta``
          is the (B, N+1, E) trunk displacement ``trunk_out − ref_in``.
        * ``token_cache=(ref_in, trunk_delta)`` + ``token_k=k`` (static k)
          — a *reuse* forward: score each token by its squared change vs
          ``ref_in``, force the CLS token live, gather the top-k changed
          tokens (indices SORTED into position order so k = N+1 degenerates
          to the identity permutation and the step is bitwise the plain
          forward), run the full trunk on only those k tokens, and scatter
          the results into the cached stream ``tokens + trunk_delta``.
          Returns ``(x̂0, (new_ref, new_delta))`` with the recomputed rows
          refreshed in both cache leaves. Reuse steps pay the trunk at
          sequence length k instead of N+1.

        The token hooks carry the same static restrictions as the block-
        delta hooks and are mutually exclusive with them (one cache family
        per forward)."""
        if self.quant is not None:
            from ddim_cold_tpu.ops.quant import QUANT_MODES

            if self.quant not in QUANT_MODES:
                raise ValueError(f"quant must be None or one of {QUANT_MODES}, "
                                 f"got {self.quant!r}")
            if self.scan_blocks:
                # the stacked (depth, in, out) kernel layout would need a
                # per-layer scale axis the codec doesn't model; quant serves
                # the unrolled inference path (which the samplers use)
                raise ValueError("quant requires scan_blocks=False")
        if self.fused and self.quant == "xla":
            raise ValueError(
                "fused=True requests the Pallas fused trunk kernels but "
                "quant='xla' explicitly opts out of Pallas — use "
                "quant='pallas' or 'w8a8' (or quant=None for the float "
                "fused Mlp alone)")
        if skip_blocks is not None or capture_split is not None:
            if self.scan_blocks:
                raise ValueError(
                    "step caching (skip_blocks/capture_split) requires "
                    "scan_blocks=False — one scanned block body cannot "
                    "statically drop layers")
            if stage != "full":
                raise ValueError("step caching composes with stage='full' only")
            if return_attention_layer is not None:
                raise ValueError("step caching excludes the attention probe")
        if skip_blocks is not None and capture_split is not None:
            raise ValueError(
                "skip_blocks (reuse step) and capture_split (refresh step) "
                "are distinct cache branches — pass one or the other")
        if skip_blocks is not None:
            lo, hi = skip_blocks
            if not (0 <= lo < hi <= self.depth):
                raise ValueError(f"skip_blocks {skip_blocks} outside "
                                 f"[0, {self.depth})")
            if block_delta is None:
                raise ValueError("skip_blocks requires the cached block_delta")
        if capture_split is not None and not (1 <= capture_split < self.depth):
            raise ValueError(f"capture_split {capture_split} must split "
                             f"depth {self.depth} into two non-empty halves")
        if capture_tokens or token_cache is not None:
            if self.scan_blocks:
                raise ValueError(
                    "token caching (capture_tokens/token_cache) requires "
                    "scan_blocks=False — the gathered subset changes the "
                    "scanned body's shape")
            if stage != "full":
                raise ValueError("token caching composes with stage='full' only")
            if return_attention_layer is not None:
                raise ValueError("token caching excludes the attention probe")
            if skip_blocks is not None or capture_split is not None:
                raise ValueError(
                    "token caching (capture_tokens/token_cache) and block-"
                    "delta caching (skip_blocks/capture_split) are distinct "
                    "cache families — pass one or the other")
        if capture_tokens and token_cache is not None:
            raise ValueError(
                "capture_tokens (refresh step) and token_cache (reuse step) "
                "are distinct cache branches — pass one or the other")
        if token_cache is not None:
            if token_k is None or not (1 <= token_k <= self.num_patches + 1):
                raise ValueError(
                    f"token_cache requires static token_k in "
                    f"[1, {self.num_patches + 1}], got {token_k!r}")
        elif token_k is not None:
            raise ValueError("token_k only applies with token_cache")
        B = x.shape[0]
        E = self.embed_dim
        N = self.num_patches

        if stage == "head":
            if tokens is None:
                raise ValueError('stage="head" requires tokens')
            tokens = nn.LayerNorm(epsilon=1e-5, dtype=self.dtype, name="norm")(tokens)
            tokens = nn.Dense(
                self.in_chans * self.patch_size**2,
                dtype=self.dtype,
                kernel_init=trunc_normal(std=0.02),
                bias_init=nn.initializers.zeros_init(),
                name="head",
            )(tokens)
            return self.unpatchify(tokens[:, 1:, :]).astype(jnp.float32)

        x = x.astype(self.dtype)
        tokens = PatchEmbed(
            patch_size=self.patch_size,
            embed_dim=E,
            in_chans=self.in_chans,
            dtype=self.dtype,
            name="patch_embed",
        )(x)

        cls_token = self.param("cls_token", trunc_normal(std=0.02), (1, 1, E))
        tokens = jnp.concatenate(
            [jnp.broadcast_to(cls_token.astype(self.dtype), (B, 1, E)), tokens], axis=1
        )

        # time conditioning: one learned row per step, added to EVERY token
        # (cls included) together with the positional embedding (ViT.py:204-205).
        time_embed = nn.Embed(
            self.total_steps,
            E,
            embedding_init=trunc_normal(std=0.02),
            dtype=self.dtype,
            name="time_embed",
        )(t.astype(jnp.int32))[:, None, :]

        if self.use_sincos_pos:
            pos_embed = jnp.asarray(positionalencoding1d(E, N + 1))[None]
        else:
            pos_embed = self.param("pos_embed", trunc_normal(std=0.02), (1, N + 1, E))
        tokens = tokens + pos_embed.astype(self.dtype) + time_embed
        tokens = nn.Dropout(self.drop_rate, deterministic=deterministic, name="pos_drop")(tokens)
        if stage == "embed":
            return tokens

        stream_in = tokens  # post-embed stream — the token-cache reference
        live = None
        if token_cache is not None:
            ref_in, trunk_delta = token_cache
            sub_in = tokens
            # static degenerate k = N+1: every token is live, so the gather/
            # scatter would be the identity — elide it at trace time, making
            # this branch op-for-op the plain trunk (the BITWISE contract:
            # fusion around a gather rounds differently inside a scan body)
            if token_k < N + 1:
                # per-token squared change vs the stream each token was last
                # recomputed at; reductions in f32 so bf16 streams rank stably
                scores = jnp.sum(
                    jnp.square((tokens - ref_in).astype(jnp.float32)), axis=-1)
                # CLS attends globally and feeds nothing to unpatchify's
                # pixels directly, but every live token attends TO it — keep
                # it fresh
                scores = scores.at[:, 0].set(jnp.finfo(jnp.float32).max)
                _, live = jax.lax.top_k(scores, token_k)  # (B, k) per-row
                # sorted into position order so the gathered subsequence
                # keeps the stream's relative layout
                live = jnp.sort(live, axis=-1)
                sub_in = jnp.take_along_axis(tokens, live[:, :, None], axis=1)
            tokens = sub_in  # the trunk below runs at sequence length k

        # stochastic depth decay rule: linspace(0, rate, depth) (ViT.py:176)
        dpr = np.linspace(0.0, self.drop_path_rate, self.depth)
        if self.scan_blocks:
            if return_attention_layer is not None:
                raise ValueError("attention probe requires scan_blocks=False")
            blk = Block(
                dim=E, num_heads=self.num_heads, mlp_ratio=self.mlp_ratio,
                qkv_bias=self.qkv_bias, qk_scale=self.qk_scale,
                drop=self.drop_rate, attn_drop=self.attn_drop_rate,
                drop_path=0.0,  # rate arrives traced per layer (dp_rate)
                dtype=self.dtype, use_flash=self.use_flash,
                flash_blocks=self.flash_blocks,
                seq_mesh=self.seq_mesh, seq_axis=self.seq_axis,
                batch_axis=self.batch_axis, head_axis=self.head_axis,
                sp_mode=self.sp_mode,
                num_experts=self.num_experts,
                moe_capacity_factor=self.moe_capacity_factor,
                moe_dispatch=self.moe_dispatch,
                fused=self.fused,  # quant is refused above — float fused Mlp
                # the shell's field module binds to THIS scope, not the
                # shell's — name it so params land under "blocks"
                name="blocks",
            )
            shell = _ScanShell if not self.remat else nn.remat(
                _ScanShell, static_argnums=(2,))
            scan = nn.scan(
                shell,
                # 'losses' scanned on the layer axis keeps the Switch-MoE
                # aux loss (sown per block, models/moe.py) — previously the
                # MoE×scan_blocks combination was refused because the sown
                # values were dropped (VERDICT r4 weak #6)
                variable_axes={"params": 0, "losses": 0},
                split_rngs={"params": True, "dropout": True},
                in_axes=(nn.broadcast, 0),
                length=self.depth,
                metadata_params={nn.meta.PARTITION_NAME: "layers"},
            )(blk)
            tokens, _ = scan(tokens, deterministic,
                             jnp.asarray(dpr, jnp.float32))
        else:
            # deterministic (argnum 2; 0 is the module) is a Python bool
            # steering trace-time structure — static under jax.checkpoint.
            block_cls = nn.remat(Block, static_argnums=(2,)) if self.remat else Block
            lo, hi = skip_blocks if skip_blocks is not None else (0, 0)
            tokens_in = tokens if capture_split is not None else None
            tokens_mid = None
            for i in range(self.depth):
                if skip_blocks is not None and lo <= i < hi:
                    if i == lo:
                        tokens = tokens + block_delta.astype(self.dtype)
                    continue
                blk_kwargs = dict(
                    dim=E,
                    num_heads=self.num_heads,
                    mlp_ratio=self.mlp_ratio,
                    qkv_bias=self.qkv_bias,
                    qk_scale=self.qk_scale,
                    drop=self.drop_rate,
                    attn_drop=self.attn_drop_rate,
                    drop_path=float(dpr[i]),
                    dtype=self.dtype,
                    use_flash=self.use_flash,
                    flash_blocks=self.flash_blocks,
                    seq_mesh=self.seq_mesh,
                    seq_axis=self.seq_axis,
                    batch_axis=self.batch_axis,
                    head_axis=self.head_axis,
                    sp_mode=self.sp_mode,
                    num_experts=self.num_experts,
                    moe_capacity_factor=self.moe_capacity_factor,
                    moe_dispatch=self.moe_dispatch,
                    quant=self.quant,
                    fused=self.fused,
                )
                probe = (return_attention_layer is not None
                         and i == return_attention_layer % self.depth)
                if probe:
                    # attention probe (reference Block.return_attention,
                    # ViT.py:132-135) — forward-only, so remat would be pure
                    # overhead: probe a plain Block (same name ⇒ same params).
                    return Block(**blk_kwargs, name=f"blocks_{i}")(
                        tokens, deterministic=deterministic, return_attention=True)
                # positional deterministic: jax.checkpoint static_argnums
                # covers positionals only; Dropout branches on it in Python.
                tokens = block_cls(**blk_kwargs, name=f"blocks_{i}")(tokens, deterministic)
                if capture_split is not None and i == capture_split - 1:
                    tokens_mid = tokens

        if token_cache is not None:
            sub_out = tokens  # (B, k, E) — trunk output of the live subset
            if live is None:  # degenerate k = N+1 — full overwrite, no scatter
                new_ref = sub_in
                new_delta = (sub_out - sub_in).astype(trunk_delta.dtype)
            else:
                brow = jnp.arange(B)[:, None]
                # stale tokens: last trunk output ≈ current embed + cached
                # trunk displacement; live rows get this step's true output
                tokens = stream_in + trunk_delta.astype(self.dtype)
                tokens = tokens.at[brow, live].set(sub_out)
                new_ref = ref_in.at[brow, live].set(sub_in)
                new_delta = trunk_delta.at[brow, live].set(
                    (sub_out - sub_in).astype(trunk_delta.dtype))

        trunk_out = tokens  # pre-norm trunk output — the delta reference point
        tokens = nn.LayerNorm(epsilon=1e-5, dtype=self.dtype, name="norm")(tokens)
        tokens = nn.Dense(
            self.in_chans * self.patch_size**2,
            dtype=self.dtype,
            kernel_init=trunc_normal(std=0.02),
            bias_init=nn.initializers.zeros_init(),
            name="head",
        )(tokens)
        out = self.unpatchify(tokens[:, 1:, :]).astype(jnp.float32)
        if capture_split is not None:
            return out, (tokens_mid - tokens_in, trunk_out - tokens_mid)
        if capture_tokens:
            return out, (stream_in, trunk_out - stream_in)
        if token_cache is not None:
            return out, (new_ref, new_delta)
        return out

    def unpatchify(self, x: jax.Array) -> jax.Array:
        """(B, N, p²C) → (B, H, W, C), exact reference pixel mapping.

        The torch path (ViT.py:214-217) views the feature dim as (p, p, C)
        with C fastest, then permute(0,5,1,3,2,4): pixel (i·p+a, j·p+b, c) ←
        feature a·pC + b·C + c of patch (i, j). NHWC equivalent below.
        """
        p = self.patch_size
        C = self.in_chans
        H, W = self.img_size
        B = x.shape[0]
        x = x.reshape(B, H // p, W // p, p, p, C)
        x = x.transpose(0, 1, 3, 2, 4, 5)  # (B, H/p, p, W/p, p, C)
        return x.reshape(B, H, W, C)


def sp_clone(model: DiffusionViT, mesh, *, sp_mode: str = "ulysses",
             seq_axis: str = "seq", batch_axis: str = "data",
             head_axis=None) -> DiffusionViT:
    """The sequence-parallel variant of ``model`` for sampling over ``mesh``
    — the SAME clone the serve engine builds per sp config (engine, direct
    callers, and the graftcheck sweep all route through here so the
    strategy resolution can never diverge between them).

    Resolution: ``sp_mode='ulysses'`` needs the tp-local head count
    divisible by the seq axis (parallel/ulysses.py raises
    SeqParallelConfigError otherwise), so it falls back to the ring — which
    has no head constraint — instead of failing at trace time. Patch tokens
    end up sequence-sharded inside the attention shard_map; the CLS/time
    conditioning stays replicated like every other non-sequence activation.
    """
    parts = int(mesh.shape[seq_axis])
    tp = int(mesh.shape[head_axis]) if head_axis else 1
    if sp_mode == "ulysses" and (model.num_heads // tp) % parts:
        sp_mode = "ring"
    return model.clone(seq_mesh=mesh, seq_axis=seq_axis,
                       batch_axis=batch_axis, head_axis=head_axis,
                       sp_mode=sp_mode)
