"""Parameter initializers matching the reference's torch semantics.

The reference initializes every Linear/embedding/pos-embed with
``trunc_normal_(std=.02)`` whose truncation bounds are the *absolute* values
[a, b] = [−2, 2] (reference ViT.py:12-50) — NOT ±2 standard deviations as in
``jax.nn.initializers.truncated_normal``. With std=0.02 the bounds sit at
±100σ, so the distribution is effectively an untruncated N(0, 0.02²), but we
reproduce the inverse-CDF construction exactly so the semantics hold for any
(std, a, b).

The patch-embedding projection is a ``nn.Conv2d`` which the reference's
``_init_weights`` does NOT touch (it matches only Linear/LayerNorm,
ViT.py:189-196), so it keeps torch's default ``kaiming_uniform_(a=√5)``:
U(−1/√fan_in, 1/√fan_in) for both kernel and bias. ``torch_default_uniform``
reproduces that.
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp


def _norm_cdf(x: float) -> float:
    return (1.0 + math.erf(x / math.sqrt(2.0))) / 2.0


def trunc_normal(std: float = 0.02, mean: float = 0.0, a: float = -2.0, b: float = 2.0):
    """Truncated normal with ABSOLUTE bounds [a, b] (torch ``trunc_normal_`` semantics).

    Inverse-CDF construction identical to reference ViT.py:24-45: sample
    U(2l−1, 2u−1) where l,u are the CDF values of the bounds, apply erfinv,
    scale by std·√2, shift by mean, clamp to [a, b].
    """
    lo = _norm_cdf((a - mean) / std)
    hi = _norm_cdf((b - mean) / std)

    def init(key, shape, dtype=jnp.float32):
        u = jax.random.uniform(
            key, shape, dtype=jnp.float32, minval=2 * lo - 1, maxval=2 * hi - 1
        )
        x = jax.scipy.special.erfinv(u) * (std * math.sqrt(2.0)) + mean
        return jnp.clip(x, a, b).astype(dtype)

    return init


def torch_default_uniform(fan_in: int):
    """torch's default Linear/Conv init: kaiming_uniform_(a=√5) ⇒ U(±1/√fan_in).

    gain = √(2/(1+5)) = √(1/3); bound = gain·√(3/fan_in) = 1/√fan_in. Used for
    the patch-embed projection (and its bias), which the reference leaves at
    torch defaults.
    """
    bound = 1.0 / math.sqrt(fan_in)

    def init(key, shape, dtype=jnp.float32):
        return jax.random.uniform(key, shape, dtype=dtype, minval=-bound, maxval=bound)

    return init
