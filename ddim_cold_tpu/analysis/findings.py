"""Finding records, the rule table, and the reviewed-baseline grammar.

A :class:`Finding` is one rule violation at one place. The ``(rule, path,
subject)`` triple is the finding's identity: line numbers drift with every
edit, so the baseline (the reviewed allowlist ``--baseline`` consumes and
``--fix-baseline`` regenerates) keys on the stable triple and carries the
line only for display. ``subject`` is chosen per rule to survive unrelated
edits — an entry-point name, an enclosing-function + callee pair, a fault
site, a '/'-joined param-leaf path.

Baseline grammar (one finding per line, ``#`` comments and blanks ignored)::

    <RULE-ID> <path> :: <subject>
    GRAFT-A002 ddim_cold_tpu/data/datasets.py :: _probe_uniform_u8:Exception

``--fix-baseline`` writes the file sorted and de-duplicated so regenerated
baselines diff cleanly under review.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field

#: rule id → one-line description. Stable ids: tests, baselines and CI grep
#: these — never renumber, only append.
RULES = {
    "GRAFT-J001": "low-precision (bf16/f16) accumulation in a matmul/conv — "
                  "violates the bf16-trunk/f32-accumulate dtype policy",
    "GRAFT-J002": "weak-typed float output from a traced entry point — "
                  "promotion hazard and a jit-cache-miss (recompile) hazard",
    "GRAFT-J003": "donated buffer XLA would drop: no output matches the "
                  "donated aval's (shape, dtype), so donation frees nothing",
    "GRAFT-J004": "oversized constant baked into a traced program — HBM "
                  "bloat and a compile-cache poison (const bytes are keyed)",
    "GRAFT-J005": "host callback primitive inside a scanned sampler body — "
                  "forces host sync every step of the scan",
    "GRAFT-J006": "unstable or colliding abstract trace signature across the "
                  "serve sweep — breaks the zero-compiles-after-warmup "
                  "guarantee",
    "GRAFT-J007": "`while` primitive in a served sampler program — a "
                  "data-dependent trip count; the adaptive drift gate must "
                  "select branches INSIDE one static-trip scan, never "
                  "vary the loop itself",
    "GRAFT-A001": "wall-clock/stdlib-random call inside a jitted or scanned "
                  "function — nondeterminism the fault-replay contract "
                  "(utils/faults.py) forbids",
    "GRAFT-A002": "broad `except Exception`/bare `except` without a "
                  "`# noqa: BLE001` justification on the same line",
    "GRAFT-A003": "faults.fire() site violation: unregistered site name, "
                  "non-literal site, or duplicate (site, tag) pair",
    "GRAFT-A004": "device-array (jnp/jax) call in a host-only serve module — "
                  "would force a device sync inside row planning",
    "GRAFT-A005": "obs.metrics emit violation: unregistered metric name, "
                  "non-literal name, or duplicate (name, key) emit site",
    "GRAFT-S001": "trunk GEMM param leaf (qkv/proj/fc1/fc2 kernel|w_int8) "
                  "fell through to a replicated spec on a model-axis mesh",
    "GRAFT-S002": "param leaf without a usable PartitionSpec (structure "
                  "mismatch, rank overflow, or unknown mesh axis)",
    "GRAFT-T001": "shared attribute with a declared `# guarded-by:` lock "
                  "written (outside __init__) without holding the guard — "
                  "a data race on the worker-thread/submit path",
    "GRAFT-T002": "lock acquired while holding a lock of equal or higher "
                  "rank in the declared hierarchy (router < engine/fleet < "
                  "batching < obs) — an ordering inversion that can deadlock",
    "GRAFT-T003": "ticket resolution or user-visible callback invoked while "
                  "holding a lock — the callback can re-enter the serving "
                  "layer and deadlock (callbacks must fire outside locks)",
    "GRAFT-T004": "Event.wait()/Condition.wait() on one synchronizer while "
                  "holding a different lock — the notifier may need that "
                  "lock, wedging both threads",
    "GRAFT-T005": "unguarded lazy-init: check-then-set on a guarded shared "
                  "attribute without the lock (and without a re-check under "
                  "it) — double allocation under concurrent first use",
    "GRAFT-C001": "collective sequence diverges across program shards of "
                  "one mesh (collective under per-shard control flow "
                  "inside the manual shard_map region) — an SPMD deadlock; "
                  "every shard must issue the same collectives in the "
                  "same order per mesh axis",
    "GRAFT-C002": "collective over a mesh axis the program's mesh does not "
                  "define (or outside any mesh) — unlowerable or silently "
                  "wrong sp program",
    "GRAFT-P001": "Pallas block geometry violates the Mosaic tile rules "
                  "(min sublane×lane tile per dtype, whole-dim span, "
                  "block-divides-array) or the grid is not fully static — "
                  "the r04 on-chip rejection class, invisible to CPU "
                  "interpret mode",
    "GRAFT-P002": "Pallas kernel's per-program VMEM footprint (double-"
                  "buffered in/out blocks + VMEM scratch) exceeds the "
                  "device kind's VMEM capacity",
    "GRAFT-P003": "Pallas grid/block padding inflates kernel compute "
                  "beyond the waste threshold at a registered geometry",
    "GRAFT-M001": "traced program's donation-aware peak live HBM bound "
                  "exceeds the device kind's HBM budget",
    "GRAFT-M002": "bucket/sequence padding inflates a traced program's "
                  "resident token axis beyond the threshold over the "
                  "logical payload",
    "GRAFT-R001": "RPC frame-kind parity violation: a wire method/event "
                  "without a table entry, a table entry without a site, a "
                  "client/server table mismatch, or a health field missing "
                  "from a backend the fleet control plane reads",
    "GRAFT-R002": "exception-serialization hole: a serve/errors.py type "
                  "outside the wire codec (or failing round-trip), or a "
                  "protocol-module raise of an unregistered type that "
                  "would degrade to RequestFailedError on the wire",
    "GRAFT-R003": "rid lifecycle inversion: the client ticket registration "
                  "does not dominate the submit send — a done event racing "
                  "the response finds no ticket (the PR-19 race)",
    "GRAFT-R004": "unbounded read/send on the RPC wire: a length-prefixed "
                  "read or frame send without a MAX_FRAME_BYTES check, an "
                  "uncapped recv chunk, or a socket going deadline-free "
                  "before its validated handshake read",
    "GRAFT-R005": "wire chaos-site gap: the frame-send/dispatch choke "
                  "points don't fire their registered rpc.*/replica.* "
                  "fault sites (or the sites aren't registered at all)",
    "GRAFT-X001": "legal SamplerConfig program class with no serve-sweep "
                  "witness — it would reach production untraced and "
                  "unwarmed (the J006 completeness converse)",
    "GRAFT-X002": "config validation inconsistency: construction-time and "
                  "program-build gates disagree, a distill-producible "
                  "student count is unservable, or a frozen config is "
                  "mutated past the gate via object.__setattr__",
    "GRAFT-X003": "warm-set/bench config outside the legal lattice (or "
                  "warmed without a sweep witness) — serving would warm or "
                  "benchmark a program the lattice proofs never saw",
}

#: rule-family letter (GRAFT-<X>NNN) → the CLI layer that emits it. The
#: partial --fix-baseline (--only) uses this to know which baseline lines a
#: layer run is authoritative for.
RULE_LAYERS = {"A": "ast", "J": "jaxpr", "S": "sharding",
               "T": "threads", "C": "collective",
               "P": "kernels", "M": "memory",
               "R": "protocol", "X": "config"}


def rule_layer(rule: str) -> str:
    """The CLI layer a rule id belongs to (``GRAFT-T001`` → ``threads``)."""
    return RULE_LAYERS[rule.split("-", 1)[1][0]]


@dataclass(frozen=True, order=True)
class Finding:
    """One rule violation. Identity (baseline key) is (rule, path, subject);
    ``line``/``message`` are display-only."""

    rule: str
    path: str          # repo-relative, '/'-separated
    subject: str       # stable short identifier within the file/check
    line: int = field(default=0, compare=True)
    message: str = field(default="", compare=False)

    @property
    def key(self) -> str:
        return f"{self.rule} {self.path} :: {self.subject}"

    def render(self) -> str:
        loc = f"{self.path}:{self.line}" if self.line else self.path
        return f"{self.rule} {loc} [{self.subject}] {self.message}"


def load_baseline(path: str | None) -> set[str]:
    """Parse a baseline file into the set of suppressed finding keys. A
    missing file is an empty baseline (strict), never an error — CI can pass
    the flag unconditionally."""
    keys: set[str] = set()
    if not path or not os.path.isfile(path):
        return keys
    with open(path) as f:
        for raw in f:
            line = raw.strip()
            if not line or line.startswith("#"):
                continue
            if " :: " not in line or not line.split(" ", 1)[0] in RULES:
                raise ValueError(
                    f"{path}: malformed baseline line {line!r} "
                    "(expected '<RULE-ID> <path> :: <subject>')")
            keys.add(line)
    return keys


def write_baseline(path: str, findings: list[Finding],
                   extra_keys: set[str] | frozenset = frozenset()) -> int:
    """Regenerate the allowlist deterministically: header, then the sorted,
    de-duplicated keys of ``findings`` — reviewed diffs stay minimal.
    ``extra_keys`` are preserved verbatim alongside the regenerated keys —
    the partial refresh (``--fix-baseline --only``) passes the lines of
    layers it did NOT run, so adopting one rule family never churns the
    others' reviewed entries."""
    keys = sorted({f.key for f in findings} | set(extra_keys))
    with open(path, "w") as f:
        f.write("# graftcheck baseline — reviewed allowlist of known "
                "findings.\n")
        f.write("# One per line: <RULE-ID> <path> :: <subject>   "
                "(regenerate: graftcheck --fix-baseline)\n")
        for k in keys:
            f.write(k + "\n")
    return len(keys)
