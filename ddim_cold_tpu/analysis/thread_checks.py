"""GRAFT-T001–T005 — lockset/lock-order analysis of the threaded host layer.

The serving stack's host side (engine/router/fleet/batching/obs/watchdog/
faults) is lock-based: worker threads, a control loop, done-callbacks and a
watchdog all touch shared state. This pass proves the locking discipline
statically, from two in-code annotation grammars plus a declared hierarchy:

``# guarded-by: <lock>`` — written on the attribute's ``__init__`` (or
module-level) assignment, declares which lock protects the attribute. Every
write to the attribute outside ``__init__`` must then hold that lock
(**T001**), and lazy check-then-set must re-check under it (**T005**).
Un-annotated attributes are not checked: thread-confined state (the engine
run loop's program registry, the router control loop's bookkeeping) stays
annotation-free with a comment saying whose thread owns it.

``# requires: <lock>`` — written on a ``def`` line, declares a helper that
asserts nothing itself because its callers hold the lock. The analyzer
seeds the helper's lockset with it AND verifies every same-class call site
actually holds it.

The declared lock hierarchy (**T002**) is rank-based — a lock may only be
taken while holding strictly lower-ranked locks::

    router._lock(0) < engine/fleet._lock(10) < batching Ticket(20)
                    < obs/watchdog/faults locks(30)

**T003** bans resolving tickets or firing user callbacks while holding any
lock (the callback re-enters the serving layer: router's done-callback
takes the router lock), and **T004** bans waiting on one synchronizer while
holding a different lock the notifier may need.

Pure-AST: no imports of the analyzed modules, no jax, sub-second over the
whole host layer.
"""

from __future__ import annotations

import ast
import os
import re

from ddim_cold_tpu.analysis.findings import Finding

#: the threaded host modules this pass covers (repo-relative)
HOST_THREADED_MODULES = (
    "ddim_cold_tpu/serve/batching.py",
    "ddim_cold_tpu/serve/engine.py",
    "ddim_cold_tpu/serve/fleet.py",
    "ddim_cold_tpu/serve/router.py",
    "ddim_cold_tpu/serve/remote.py",
    "ddim_cold_tpu/serve/autoscale.py",
    "ddim_cold_tpu/obs/metrics.py",
    "ddim_cold_tpu/obs/spans.py",
    "ddim_cold_tpu/utils/watchdog.py",
    "ddim_cold_tpu/utils/faults.py",
)

#: declared lock hierarchy: ``<module>::<lock attr>`` → rank. Acquiring a
#: lock is legal only while every held lock has a strictly LOWER rank
#: (same-lock re-entry is legal for RLocks only). Locks not listed rank as
#: None and are exempt from T002 (but still count for T001/T003/T004).
LOCK_RANKS = {
    "ddim_cold_tpu/serve/router.py::_lock": 0,
    "ddim_cold_tpu/serve/engine.py::_lock": 10,
    "ddim_cold_tpu/serve/fleet.py::_lock": 10,
    # remote handle: registry lock, then the send lock (framed writes
    # serialize under it while the registry stays free for the reader)
    "ddim_cold_tpu/serve/remote.py::_lock": 10,
    "ddim_cold_tpu/serve/remote.py::_send_lock": 11,
    # the autoscaler only guards its own thread handle; router calls
    # (rank 0) always happen lock-free from the tick path
    "ddim_cold_tpu/serve/autoscale.py::_lock": 10,
    "ddim_cold_tpu/serve/batching.py::_lock": 20,
    "ddim_cold_tpu/serve/batching.py::_pcond": 21,
    "ddim_cold_tpu/obs/metrics.py::_lock": 30,
    "ddim_cold_tpu/obs/spans.py::_lock": 30,
    "ddim_cold_tpu/utils/watchdog.py::_lock": 30,
    "ddim_cold_tpu/utils/faults.py::_lock": 30,
}

#: cross-object callee summaries: a method name every module recognizes →
#: the minimum lock rank that callee acquires internally. Interprocedural
#: edges the AST cannot type-resolve (``req.ticket._fail`` from the engine,
#: ``self.metrics.inc`` from anywhere) are ranked by name — the names are
#: unique enough across the host layer that this is exact in practice.
XCALL_RANKS = {
    # batching.Ticket surface (rank 20)
    "_deliver": 20, "_fail": 20, "_preview": 20, "add_done_callback": 20,
    "add_preview_callback": 20,
    # obs/metrics + obs/spans + watchdog + faults surfaces (rank 30)
    "inc": 30, "gauge": 30, "observe": 30, "mark": 30, "fire": 30,
}

#: calls that BLOCK on another thread's progress — banned under any lock
#: (T004) unless passed a literal 0 timeout: ``exception(0)`` polls.
BLOCKING_CALLS = ("wait", "join", "result", "exception", "previews")

#: ticket-resolution / user-callback surfaces — banned under any lock
#: (T003): the callee runs arbitrary observer code (the router's
#: done-callback takes the router lock on the calling thread).
RESOLUTION_CALLS = ("_fail", "_deliver", "_resolve", "_run_callback",
                    "add_done_callback", "add_preview_callback")
CALLBACK_NAMES = ("fn", "cb", "callback", "on_abort", "hook")

_GUARD_RE = re.compile(r"#\s*guarded-by:\s*([A-Za-z_]\w*)")
_REQUIRES_RE = re.compile(r"#\s*requires:\s*([A-Za-z_]\w*)")
_LOCK_CTORS = {"Lock": "lock", "RLock": "rlock",
               "Condition": "condition", "Event": "event"}
_MUTATORS = frozenset({
    "append", "extend", "add", "remove", "discard", "pop", "popitem",
    "popleft", "appendleft", "clear", "update", "setdefault", "insert",
    "sort",
})


def _ctor_kind(node) -> str | None:
    """``threading.Lock()`` / ``Condition()`` → its lock kind, else None."""
    if not isinstance(node, ast.Call):
        return None
    fn = node.func
    name = fn.attr if isinstance(fn, ast.Attribute) else (
        fn.id if isinstance(fn, ast.Name) else None)
    return _LOCK_CTORS.get(name)


def _own_target(node, selfname) -> str | None:
    """``self.X`` (class scope, selfname='self') or bare ``X`` (module
    scope, selfname=None) → the owned attribute/global name, else None."""
    if selfname is None:
        return node.id if isinstance(node, ast.Name) else None
    if (isinstance(node, ast.Attribute) and isinstance(node.value, ast.Name)
            and node.value.id == selfname):
        return node.attr
    return None


def _comment_tag(lines, node, rx) -> str | None:
    ln = getattr(node, "lineno", 0)
    if 0 < ln <= len(lines):
        m = rx.search(lines[ln - 1])
        if m:
            return m.group(1)
    return None


class _Scope:
    """One analyzed lock domain: a class body, or the module top level
    (faults.py keeps its registry in module globals)."""

    def __init__(self, name: str, selfname: str | None):
        self.name = name            # "Ticket" / "<module>"
        self.selfname = selfname    # "self" / None
        self.locks: dict = {}       # lock attr -> kind
        self.guards: dict = {}      # data attr -> guarding lock attr
        self.funcs: dict = {}       # fn name -> ast.FunctionDef
        self.requires: dict = {}    # fn name -> lock the caller must hold


def _collect_scopes(tree, lines) -> list[_Scope]:
    scopes = []
    mod = _Scope("<module>", None)
    for stmt in tree.body:
        if isinstance(stmt, ast.ClassDef):
            cls = _Scope(stmt.name, "self")
            for item in stmt.body:
                if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    cls.funcs[item.name] = item
                    req = _comment_tag(lines, item, _REQUIRES_RE)
                    if req:
                        cls.requires[item.name] = req
                    for sub in ast.walk(item):
                        if isinstance(sub, (ast.Assign, ast.AnnAssign)):
                            _note_decl(cls, sub, lines)
            scopes.append(cls)
        elif isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            mod.funcs[stmt.name] = stmt
            req = _comment_tag(lines, stmt, _REQUIRES_RE)
            if req:
                mod.requires[stmt.name] = req
        elif isinstance(stmt, (ast.Assign, ast.AnnAssign)):
            _note_decl(mod, stmt, lines)
    scopes.append(mod)
    return scopes


def _note_decl(scope: _Scope, stmt, lines) -> None:
    """Record lock constructions and ``# guarded-by:`` declarations from one
    assignment (class scopes read them out of method bodies — __init__)."""
    targets = stmt.targets if isinstance(stmt, ast.Assign) else [stmt.target]
    value = stmt.value
    for tgt in targets:
        attr = _own_target(tgt, scope.selfname)
        if attr is None:
            continue
        kind = _ctor_kind(value)
        if kind is not None:
            scope.locks.setdefault(attr, kind)
            continue
        guard = _comment_tag(lines, stmt, _GUARD_RE)
        if guard:
            scope.guards.setdefault(attr, guard)


# ---------------------------------------------------------------------------
# per-function lockset walk
# ---------------------------------------------------------------------------

class _FnAnalysis:
    """Shared state for one function walk (class method or module fn)."""

    def __init__(self, checker: "_Checker", fname: str, entry_locked: tuple):
        self.c = checker
        self.fname = fname
        self.subject_fn = (f"{checker.scope.name}.{fname}"
                          if checker.scope.selfname else fname)
        self.entry_locked = entry_locked


class _Checker:
    def __init__(self, scope: _Scope, rel: str, lines, ranks: dict,
                 findings: list):
        self.scope = scope
        self.rel = rel
        self.lines = lines
        self.ranks = ranks          # lock attr -> rank (may miss entries)
        self.findings = findings
        self._summaries: dict = {}  # fn name -> frozenset of acquired locks

    # -- summaries: which own locks does fn (transitively) acquire? --------
    def summary(self, fname: str, _stack=()) -> frozenset:
        if fname in self._summaries:
            return self._summaries[fname]
        if fname in _stack or fname not in self.scope.funcs:
            return frozenset()
        acquired = set()
        for node in ast.walk(self.scope.funcs[fname]):
            if isinstance(node, ast.withitem):
                lk = self._lock_of(node.context_expr)
                if lk:
                    acquired.add(lk)
            elif isinstance(node, ast.Call):
                callee = self._self_callee(node)
                if callee:
                    acquired |= self.summary(callee, _stack + (fname,))
        out = frozenset(acquired)
        self._summaries[fname] = out
        return out

    def _lock_of(self, expr) -> str | None:
        attr = _own_target(expr, self.scope.selfname)
        if attr is not None and attr in self.scope.locks:
            if self.scope.locks[attr] != "event":  # events aren't lockable
                return attr
        return None

    def _self_callee(self, call) -> str | None:
        attr = _own_target(call.func, self.scope.selfname)
        return attr if attr in self.scope.funcs else None

    def emit(self, rule, node, subject, msg) -> None:
        self.findings.append(Finding(
            rule, self.rel, subject, getattr(node, "lineno", 0), msg))

    # -- driver ------------------------------------------------------------
    def check_all(self) -> None:
        for fname, fn in self.scope.funcs.items():
            if fname == "__init__":
                continue
            held = frozenset({self.scope.requires[fname]}
                             if fname in self.scope.requires else ())
            self._walk_body(fn.body, held, fname)

    # -- statement walk, threading the lockset -----------------------------
    def _walk_body(self, stmts, held: frozenset, fname: str) -> None:
        for stmt in stmts:
            self._walk_stmt(stmt, held, fname)

    def _walk_stmt(self, stmt, held, fname) -> None:
        if isinstance(stmt, ast.With):
            inner = set(held)
            for item in stmt.items:
                self._scan_exprs([item.context_expr], held, fname)
                lk = self._lock_of(item.context_expr)
                if lk:
                    self._check_acquire(lk, held, stmt, fname)
                    inner.add(lk)
            self._walk_body(stmt.body, frozenset(inner), fname)
        elif isinstance(stmt, ast.If):
            self._scan_exprs([stmt.test], held, fname)
            self._check_lazy_init(stmt, held, fname)
            self._walk_body(stmt.body, held, fname)
            self._walk_body(stmt.orelse, held, fname)
        elif isinstance(stmt, (ast.For, ast.While)):
            head = [stmt.iter] if isinstance(stmt, ast.For) else [stmt.test]
            self._scan_exprs(head, held, fname)
            self._walk_body(stmt.body, held, fname)
            self._walk_body(stmt.orelse, held, fname)
        elif isinstance(stmt, ast.Try):
            self._walk_body(stmt.body, held, fname)
            for h in stmt.handlers:
                self._walk_body(h.body, held, fname)
            self._walk_body(stmt.orelse, held, fname)
            self._walk_body(stmt.finalbody, held, fname)
        elif isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # a nested def runs LATER on whatever thread calls it: analyze
            # its body as a lock-free callback context, not under `held`
            self._walk_body(stmt.body, frozenset(), f"{fname}.{stmt.name}")
        else:
            self._check_writes(stmt, held, fname)
            self._scan_exprs([stmt], held, fname)

    # -- rule bodies -------------------------------------------------------
    def _check_acquire(self, lock: str, held, node, fname) -> None:
        if lock in held and self.scope.locks.get(lock) not in (
                "rlock", "condition"):
            self.emit("GRAFT-T002", node,
                      f"{self._subj(fname)}:{lock}>{lock}",
                      f"non-reentrant lock {lock!r} re-acquired while "
                      "already held — self-deadlock")
            return
        rank = self.ranks.get(lock)
        if rank is None:
            return
        for h in held:
            if h == lock:
                continue
            hrank = self.ranks.get(h)
            if hrank is not None and hrank >= rank:
                self.emit("GRAFT-T002", node,
                          f"{self._subj(fname)}:{h}>{lock}",
                          f"acquires {lock!r} (rank {rank}) while holding "
                          f"{h!r} (rank {hrank}) — inverts the declared "
                          "lock hierarchy")

    def _check_writes(self, stmt, held, fname) -> None:
        for attr, node in self._stored_attrs(stmt):
            guard = self.scope.guards.get(attr)
            if guard and guard not in held:
                self.emit("GRAFT-T001", node,
                          f"{self._subj(fname)}:{attr}",
                          f"writes {attr!r} (guarded-by: {guard}) without "
                          f"holding {guard!r}")

    def _stored_attrs(self, stmt):
        """(attr, node) pairs this simple statement writes: assignment /
        augassign / del / subscript store / mutator-method calls."""
        out = []
        if isinstance(stmt, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
            targets = (stmt.targets if isinstance(stmt, ast.Assign)
                       else [stmt.target])
            for tgt in targets:
                out += self._store_targets(tgt)
        elif isinstance(stmt, ast.Delete):
            for tgt in stmt.targets:
                out += self._store_targets(tgt)
        for call in self._calls_in(stmt):
            if isinstance(call.func, ast.Attribute) \
                    and call.func.attr in _MUTATORS:
                attr = _own_target(call.func.value, self.scope.selfname)
                if attr is not None:
                    out.append((attr, call))
        return out

    def _store_targets(self, tgt):
        if isinstance(tgt, (ast.Tuple, ast.List)):
            out = []
            for el in tgt.elts:
                out += self._store_targets(el)
            return out
        if isinstance(tgt, ast.Subscript):
            tgt = tgt.value
        attr = _own_target(tgt, self.scope.selfname)
        return [(attr, tgt)] if attr is not None else []

    def _check_lazy_init(self, stmt: ast.If, held, fname) -> None:
        attr = self._lazy_tested_attr(stmt.test)
        if attr is None:
            return
        guard = self.scope.guards.get(attr)
        if guard is None or guard in held:
            return
        writes = any(a == attr
                     for sub in ast.walk(stmt)
                     for a, _ in self._stored_attrs(sub))
        if not writes:
            return
        # double-checked init is fine: a `with <guard>:` inside the body
        # that re-tests the same attribute before the write
        for sub in ast.walk(stmt):
            if isinstance(sub, ast.With) and any(
                    self._lock_of(i.context_expr) == guard
                    for i in sub.items):
                if any(isinstance(s2, ast.If)
                       and self._lazy_tested_attr(s2.test) == attr
                       for s2 in ast.walk(sub)):
                    return
        self.emit("GRAFT-T005", stmt,
                  f"{self._subj(fname)}:{attr}",
                  f"lazy check-then-set of {attr!r} (guarded-by: {guard}) "
                  f"outside the lock and without a re-check under it")

    def _lazy_tested_attr(self, test) -> str | None:
        """``self.X is None`` / ``not self.X`` / ``k not in self.X``."""
        if (isinstance(test, ast.Compare) and len(test.ops) == 1):
            if isinstance(test.ops[0], ast.Is) and isinstance(
                    test.comparators[0], ast.Constant) \
                    and test.comparators[0].value is None:
                return _own_target(test.left, self.scope.selfname)
            if isinstance(test.ops[0], ast.NotIn):
                return _own_target(test.comparators[0], self.scope.selfname)
        if isinstance(test, ast.UnaryOp) and isinstance(test.op, ast.Not):
            return _own_target(test.operand, self.scope.selfname)
        return None

    # -- expression-level checks (calls) -----------------------------------
    def _calls_in(self, node):
        """Call nodes reachable without entering deferred code (lambdas)."""
        stack = [node]
        while stack:
            n = stack.pop()
            if isinstance(n, ast.Lambda):
                continue
            if isinstance(n, ast.Call):
                yield n
            stack.extend(ast.iter_child_nodes(n))

    def _scan_exprs(self, nodes, held, fname) -> None:
        for node in nodes:
            for call in self._calls_in(node):
                self._check_call(call, held, fname)

    def _check_call(self, call, held, fname) -> None:
        fn = call.func
        name = fn.attr if isinstance(fn, ast.Attribute) else (
            fn.id if isinstance(fn, ast.Name) else None)
        if name is None:
            return
        subj = self._subj(fname)
        # T003: resolution/callback surfaces under any lock
        if held and (name in RESOLUTION_CALLS or (
                isinstance(fn, ast.Name) and name in CALLBACK_NAMES) or (
                isinstance(fn, ast.Attribute) and name in CALLBACK_NAMES)):
            self.emit("GRAFT-T003", call, f"{subj}:{name}",
                      f"invokes {name!r} while holding "
                      f"{sorted(held)} — callbacks must fire outside locks")
            return
        # T004: blocking waits under a lock the notifier may need.
        # Condition.wait while holding ONLY that condition is the one legal
        # form (wait atomically releases it).
        if held and name in BLOCKING_CALLS and not self._poll_timeout(call):
            owner = (_own_target(fn.value, self.scope.selfname)
                     if isinstance(fn, ast.Attribute) else None)
            cond_self_wait = (
                name == "wait" and owner is not None
                and self.scope.locks.get(owner) == "condition"
                and held == frozenset({owner}))
            if not cond_self_wait:
                self.emit("GRAFT-T004", call, f"{subj}:{name}",
                          f"blocking {name!r} while holding "
                          f"{sorted(held)} — the notifier may need the "
                          "lock (wedge)")
            return
        # T002 interprocedural: same-class callees via summaries
        callee = self._self_callee(call)
        if callee:
            need = self.scope.requires.get(callee)
            if need and need not in held:
                self.emit("GRAFT-T001", call, f"{subj}:{callee}",
                          f"calls {callee!r} (# requires: {need}) without "
                          f"holding {need!r}")
            if held:
                for lk in self.summary(callee):
                    if lk not in held:  # re-entry checked at its own site
                        self._check_acquire(lk, held, call, fname)
            return
        # T002 cross-object: name-ranked callee summaries
        if held and name in XCALL_RANKS:
            rank = XCALL_RANKS[name]
            for h in held:
                hrank = self.ranks.get(h)
                if hrank is not None and hrank >= rank:
                    self.emit("GRAFT-T002", call, f"{subj}:{h}>{name}()",
                              f"calls {name!r} (acquires rank {rank}) while "
                              f"holding {h!r} (rank {hrank}) — inverts the "
                              "declared lock hierarchy")

    @staticmethod
    def _poll_timeout(call) -> bool:
        """True for a literal-0 timeout — a poll, not a blocking wait."""
        cands = list(call.args[:1]) + [kw.value for kw in call.keywords
                                       if kw.arg == "timeout"]
        return any(isinstance(a, ast.Constant) and a.value == 0
                   for a in cands)

    def _subj(self, fname: str) -> str:
        return (f"{self.scope.name}.{fname}" if self.scope.selfname
                else fname)


# ---------------------------------------------------------------------------
# entry points
# ---------------------------------------------------------------------------

def _ranks_for(rel: str) -> dict:
    pref = f"{rel}::"
    return {k[len(pref):]: v for k, v in LOCK_RANKS.items()
            if k.startswith(pref)}


def lint_source(source: str, rel: str,
                lock_ranks: dict | None = None) -> list[Finding]:
    """All T-rule findings for one module's source. ``lock_ranks`` maps the
    module's lock attributes to hierarchy ranks; by default the declared
    :data:`LOCK_RANKS` slice for ``rel`` (tests pass their own)."""
    tree = ast.parse(source)
    lines = source.splitlines()
    ranks = _ranks_for(rel) if lock_ranks is None else dict(lock_ranks)
    findings: list[Finding] = []
    for scope in _collect_scopes(tree, lines):
        _Checker(scope, rel, lines, ranks, findings).check_all()
    return findings


def lint_tree(root: str) -> list[Finding]:
    """T001–T005 over every module in :data:`HOST_THREADED_MODULES`."""
    findings: list[Finding] = []
    for rel in HOST_THREADED_MODULES:
        path = os.path.join(root, rel)
        if not os.path.isfile(path):
            continue
        with open(path) as f:
            findings += lint_source(f.read(), rel)
    return findings


def run_thread_checks(root: str) -> list[Finding]:
    return lint_tree(root)
