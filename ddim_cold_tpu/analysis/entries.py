"""The traced-entry registry: which real hot paths graftcheck proves.

Each :class:`Entry` names one jitted entry point and the abstract arguments
(``ShapeDtypeStruct``) to trace it with — the SAME functions the samplers,
the trainer and the serving engine dispatch, at the tiny model geometry
``tests/test_serve.py`` uses (so the serve-sweep signature check covers
exactly the warmed ``(SamplerConfig, bucket)`` pairs that suite proves
empirically). Tracing is abstract end to end: params come from
``jax.eval_shape(model.init, ...)``, quantized params from
``eval_shape(quantize_params, ...)`` — no parameter is ever materialized.

Geometry is small but structurally faithful — every check here is about
graph *structure* (dtypes, aliasing, constants, callbacks, trace identity),
which does not change with width/depth, only with code.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable

import jax
import jax.numpy as jnp

from ddim_cold_tpu.analysis import jaxpr_checks
from ddim_cold_tpu.analysis.findings import Finding

#: tests/test_serve.py's model geometry — keep in sync (test_analysis.py
#: asserts equality so the serve sweep and the empirical guard can't drift)
TINY = dict(img_size=(16, 16), patch_size=8, embed_dim=32, depth=2,
            num_heads=4, total_steps=2000)
K = 500    # the 4-reverse-step stride test_serve.py warms
N = 4      # batch rows for the non-serve entries

#: the warmed (SamplerConfig, buckets) sweep tests/test_serve.py +
#: tests/test_quant.py + tests/test_workloads.py cover — built lazily
#: (SamplerConfig import). Entries must differ STRUCTURALLY (trip count,
#: function identity, quant, sequence flag, avals) — signature_hash does
#: not see constant values, so e.g. two t_starts with the same step count
#: would collide by design, not by bug.
def serve_sweep():
    from ddim_cold_tpu.serve.batching import SamplerConfig

    # Bucket policy: the bucket axis enters every program the same way (a
    # batch-dim substitution), so two-bucket stability/distinctness is
    # proven by ONE (4, 8) witness per scan family — ddim, cold, inpaint,
    # the sequence variant, fewstep (plus the warmed pairs tests pin).
    # Every other entry traces at (4,) only: each extra bucket is a full
    # extra trace in BOTH J006 worlds, and the single-bucket entries'
    # program structure is already bucket-proven by their family witness.
    sweep = [
        ("ddim_k500", SamplerConfig(k=K), (4, 8)),
        ("ddim_k500_ci2", SamplerConfig(k=K, cache_interval=2), (4,)),
        # cache_mode="full" (whole-trunk reuse steps) had NO sweep entry
        # until the X001 sweep-completeness rule flagged it: a legal,
        # serveable mode with zero J006 coverage
        ("ddim_k500_ci2_full",
         SamplerConfig(k=K, cache_interval=2, cache_mode="full"), (4,)),
        # adaptive/token caching (ISSUE 8). ONE adaptive threshold value in
        # the whole sweep: signature_hash is constant-blind, so a second
        # threshold would collide by design. Distinct token_k values ARE
        # structurally distinct (the gathered (B, k, E) aval differs).
        ("ddim_k500_adapt",
         SamplerConfig(k=K, cache_interval=2, cache_mode="adaptive",
                       cache_threshold=0.05), (4,)),
        ("ddim_k500_adapt_qxla",
         SamplerConfig(k=K, cache_interval=2, cache_mode="adaptive",
                       cache_threshold=0.05, quant="xla"), (4,)),
        # device-telemetry variants (ISSUE 11): same cached samplers with a
        # per-step (branch, drift) aux — the extra scan outputs make them
        # structurally distinct from their plain counterparts
        ("ddim_k500_ci2_tel",
         SamplerConfig(k=K, cache_interval=2, telemetry=True), (4,)),
        ("ddim_k500_adapt_tel",
         SamplerConfig(k=K, cache_interval=2, cache_mode="adaptive",
                       cache_threshold=0.05, telemetry=True), (4,)),
        ("ddim_k500_tok3",
         SamplerConfig(k=K, cache_interval=2, cache_mode="token",
                       cache_tokens=3), (4,)),
        ("ddim_k500_tok2",
         SamplerConfig(k=K, cache_interval=2, cache_mode="token",
                       cache_tokens=2), (4,)),
        ("cold_l4_adapt",
         SamplerConfig(sampler="cold", levels=4, cache_interval=2,
                       cache_mode="adaptive", cache_threshold=0.05), (4,)),
        ("inpaint_k500_ci2",
         SamplerConfig(task="inpaint", k=K, cache_interval=2), (4,)),
        ("inpaint_k500_tok3",
         SamplerConfig(task="inpaint", k=K, cache_interval=2,
                       cache_mode="token", cache_tokens=3), (4,)),
        ("cold_l4", SamplerConfig(sampler="cold", levels=4), (4, 8)),
        ("ddim_k500_t999", SamplerConfig(k=K, t_start=999), (4,)),
        ("ddim_k500_qxla", SamplerConfig(k=K, quant="xla"), (4,)),
        # editing workloads (ddim_cold_tpu/workloads) + preview variants:
        # trip counts at K=500/T=2000 — t=None→4, t1200→3, t999→2, t400→1
        ("ddim_k500_pv2", SamplerConfig(k=K, preview_every=2), (4, 8)),
        ("ddim_k500_ci2_pv2",
         SamplerConfig(k=K, cache_interval=2, preview_every=2), (4,)),
        ("inpaint_k500", SamplerConfig(task="inpaint", k=K), (4, 8)),
        ("inpaint_k500_qxla",
         SamplerConfig(task="inpaint", k=K, quant="xla"), (4,)),
        ("inpaint_k500_pv2",
         SamplerConfig(task="inpaint", k=K, preview_every=2), (4,)),
        ("inpaint_k500_ci2_pv2",
         SamplerConfig(task="inpaint", k=K, cache_interval=2,
                       preview_every=2), (4,)),
        ("superres_l3",
         SamplerConfig(task="superres", sampler="cold", levels=3), (4,)),
        ("superres_l3_ci2",
         SamplerConfig(task="superres", sampler="cold", levels=3,
                       cache_interval=2), (4,)),
        # cached+preview crossings (X001): each scan family's cached
        # SEQUENCE variant is a distinct program (_*_cached_seq) the sweep
        # previously never traced — cold here, inpaint and fewstep below
        ("superres_l3_ci2_pv1",
         SamplerConfig(task="superres", sampler="cold", levels=3,
                       cache_interval=2, preview_every=1), (4,)),
        ("superres_l3_pv1",
         SamplerConfig(task="superres", sampler="cold", levels=3,
                       preview_every=1), (4,)),
        ("draft_k500_t1200",
         SamplerConfig(task="draft", k=K, t_start=1200), (4,)),
        ("draft_k500_t1200_ci2",
         SamplerConfig(task="draft", k=K, t_start=1200, cache_interval=2),
         (4,)),
        ("interp_k500_t400",
         SamplerConfig(task="interp", k=K, t_start=400), (4,)),
        # few-step distilled family (ISSUE 17): scan over steps-1 schedule
        # updates + the final jump-to-clean forward OUTSIDE the scan, so
        # steps=1 lowers scan-free and every k is structurally distinct
        # from the stride family's equal-trip-count scans. NO student
        # variants here: a student config runs the teacher's program on
        # different params (warmup dedup relies on exactly that), so a
        # student entry would be a deliberate J006 collision.
        ("ddim_fs1", SamplerConfig(steps=1), (4, 8)),
        ("ddim_fs2", SamplerConfig(steps=2), (4,)),
        ("ddim_fs4", SamplerConfig(steps=4), (4,)),
        ("ddim_fs4_ci2", SamplerConfig(steps=4, cache_interval=2), (4,)),
        ("ddim_fs4_ci2_pv1",
         SamplerConfig(steps=4, cache_interval=2, preview_every=1), (4,)),
        ("ddim_fs2_pv1", SamplerConfig(steps=2, preview_every=1), (4,)),
        ("ddim_fs1_qxla", SamplerConfig(steps=1, quant="xla"), (4,)),
    ]
    # sequence-parallel program family (sp_mode/sp_degree — the engine's
    # (data, seq)-mesh executables). Gated on the PROCESS's device count:
    # the graftcheck CLI world runs at 1 CPU device (no sp geometry exists
    # there), the pytest world at 8 via conftest's
    # --xla_force_host_platform_device_count. The gate is deterministic
    # within a process, so both J006 worlds see the same sweep and hash
    # stability is preserved — each world is internally consistent.
    n_dev = jax.device_count()
    if n_dev >= 2 and n_dev % 2 == 0:
        sweep += [
            # ulysses vs ring at the same geometry must hash distinctly
            # (all_to_all pair vs ppermute scan inside the shard_map jaxpr)
            ("ddim_k500_sp2u",
             SamplerConfig(k=K, sp_mode="ulysses", sp_degree=2), (4, 8)),
            ("ddim_k500_sp2r",
             SamplerConfig(k=K, sp_mode="ring", sp_degree=2), (4,)),
            # static (non-adaptive) caching composes with sp — the carry
            # rides the same (data, seq) mesh
            ("ddim_k500_ci2_sp2u",
             SamplerConfig(k=K, cache_interval=2, sp_mode="ulysses",
                           sp_degree=2), (4,)),
        ]
    if n_dev >= 8 and n_dev % 8 == 0:
        # TINY's 4 heads do not divide a seq axis of 8: this entry proves
        # the ulysses→ring fallback traces (and hashes) at the all-local
        # geometry — distinct from sp2r because the mesh differs
        sweep.append(
            ("ddim_k500_sp8u_fallback",
             SamplerConfig(k=K, sp_mode="ulysses", sp_degree=8), (8,)))
    return sweep


@dataclass
class Entry:
    """One traced entry point. ``jitted(*static_args, *dyn_args, **kwargs)``
    is the exact dispatch; ``path`` is where findings point."""

    name: str
    path: str
    jitted: Any
    dyn_args: tuple
    static_args: tuple = ()
    kwargs: dict = field(default_factory=dict)
    donates: bool = False
    #: layer hints: ``tokens`` = the logical token count (the P003/M002
    #: padding checks charge padded extents against it), ``rows`` = batch
    #: rows, ``memory`` = run the M-rules' liveness walk over this entry
    meta: dict = field(default_factory=dict)

    def _call(self, *dyn):
        return self.jitted(*self.static_args, *dyn, **self.kwargs)

    def trace(self):
        return jax.make_jaxpr(self._call)(*self.dyn_args)

    def out_shapes(self):
        return jax.eval_shape(self._call, *self.dyn_args)

    def args_info(self):
        return self.jitted.lower(*self.static_args, *self.dyn_args,
                                 **self.kwargs).args_info


class Context:
    """One independently constructed (model, abstract params) world. The
    signature check builds two and demands identical trace hashes — flax
    modules hash by field values, so a fresh instance MUST retrace to the
    same program or serving would recompile on every engine restart."""

    def __init__(self):
        from ddim_cold_tpu.models import DiffusionViT
        from ddim_cold_tpu.ops import quant

        self.model = DiffusionViT(**TINY)
        H, W = self.model.img_size
        self.key = jax.random.PRNGKey(0)
        x2 = jax.ShapeDtypeStruct((2, H, W, self.model.in_chans), jnp.float32)
        t2 = jax.ShapeDtypeStruct((2,), jnp.int32)
        self.params = jax.eval_shape(self.model.init, self.key, x2,
                                     t2)["params"]
        self.qmodel = self.model.clone(quant="xla")
        self.qparams = jax.eval_shape(quant.quantize_params, self.params)
        self._sp_meshes: dict = {}
        self._sp_models: dict = {}

    def sp_mesh(self, degree: int):
        """The (data, seq) mesh for one sp_degree — the same geometry
        Engine._sp_mesh builds (data-major over every visible device)."""
        from ddim_cold_tpu.parallel.mesh import make_mesh

        mesh = self._sp_meshes.get(degree)
        if mesh is None:
            n = jax.device_count()
            mesh = make_mesh({"data": n // degree, "seq": degree})
            self._sp_meshes[degree] = mesh
        return mesh

    def sp_model(self, config):
        """The sp model clone a config's programs trace — routed through
        models.sp_clone, the SAME resolver the engine uses, so the sweep's
        ulysses→ring fallback can never diverge from serving's."""
        from ddim_cold_tpu.models.vit import sp_clone

        key = (config.sp_mode, config.sp_degree, config.quant)
        model = self._sp_models.get(key)
        if model is None:
            base = self.qmodel if config.quant else self.model
            model = self._sp_models[key] = sp_clone(
                base, self.sp_mesh(config.sp_degree),
                sp_mode=config.sp_mode)
        return model

    def x(self, n: int):
        H, W = self.model.img_size
        return jax.ShapeDtypeStruct((n, H, W, self.model.in_chans),
                                    jnp.float32)

    def cache(self, n: int, mode: str = "delta"):
        from ddim_cold_tpu.ops import step_cache

        H, W = self.model.img_size
        return jax.eval_shape(
            lambda: step_cache.init_cache(n, self.model.num_patches + 1,
                                          self.model.embed_dim,
                                          self.model.dtype, mode=mode,
                                          img_shape=(H, W,
                                                     self.model.in_chans)))

    def mask(self, n: int):
        H, W = self.model.img_size
        return jax.ShapeDtypeStruct((n, H, W, 1), jnp.float32)


def build_entries(ctx: Context) -> list[Entry]:
    from ddim_cold_tpu.ops import quant, sampling
    from ddim_cold_tpu.train.step import create_train_state, make_train_step

    SAMP = "ddim_cold_tpu/ops/sampling.py"
    m, p, key = ctx.model, ctx.params, ctx.key
    x = ctx.x(N)
    ddim_kw = dict(k=K, t_start=None, eta=0.0)
    entries = [
        Entry("ddim_scan_last", SAMP, sampling._ddim_scan_last,
              (p, x, key), (m,), dict(ddim_kw), donates=True),
        Entry("ddim_scan_guided", SAMP, sampling._ddim_scan_last,
              (p, x, key), (m,), dict(ddim_kw, t_start=999), donates=True),
        Entry("ddim_scan_sequence", SAMP, sampling._ddim_scan_sequence,
              (p, x, key), (m,), dict(ddim_kw)),
        Entry("ddim_scan_cached", SAMP, sampling._ddim_scan_cached,
              (p, x, key, ctx.cache(N)), (m,),
              dict(ddim_kw, cache_interval=2, cache_mode="delta",
                   sequence=False), donates=True),
        Entry("ddim_scan_cached_adaptive", SAMP, sampling._ddim_scan_cached,
              (p, x, key, ctx.cache(N, "adaptive")), (m,),
              dict(ddim_kw, cache_interval=2, cache_mode="adaptive",
                   cache_threshold=0.05, sequence=False), donates=True),
        Entry("ddim_scan_cached_tel", SAMP, sampling._ddim_scan_cached_tel,
              (p, x, key, ctx.cache(N, "adaptive")), (m,),
              dict(ddim_kw, cache_interval=2, cache_mode="adaptive",
                   cache_threshold=0.05), donates=True),
        Entry("ddim_scan_cached_token", SAMP, sampling._ddim_scan_cached,
              (p, x, key, ctx.cache(N, "token")), (m,),
              dict(ddim_kw, cache_interval=2, cache_mode="token",
                   cache_tokens=3, sequence=False), donates=True),
        Entry("ddim_scan_inpaint_cached", SAMP,
              sampling._ddim_scan_inpaint_cached,
              (p, x, x, ctx.mask(N), key, ctx.cache(N)), (m,),
              dict(ddim_kw, cache_interval=2, cache_mode="delta",
                   sequence=False), donates=True),
        Entry("cold_scan", SAMP, sampling._cold_scan, (p, x), (m,),
              dict(levels=4, return_sequence=False), donates=True),
        Entry("cold_scan_seq", SAMP, sampling._cold_scan_seq, (p, x), (m,),
              dict(levels=4, return_sequence=True)),
        Entry("cold_scan_cached", SAMP, sampling._cold_scan_cached,
              (p, x, ctx.cache(N)), (m,),
              dict(levels=4, return_sequence=False, cache_interval=2,
                   cache_mode="delta"), donates=True),
        Entry("ddim_scan_inpaint", SAMP, sampling._ddim_scan_inpaint,
              (p, x, x, ctx.mask(N), key), (m,),
              dict(ddim_kw, sequence=False), donates=True),
        Entry("ddim_scan_inpaint_seq", SAMP, sampling._ddim_scan_inpaint_seq,
              (p, x, x, ctx.mask(N), key), (m,),
              dict(ddim_kw, sequence=True)),
        Entry("ddim_scan_last_w8a16", "ddim_cold_tpu/ops/quant.py",
              sampling._ddim_scan_last, (ctx.qparams, ctx.x(N), key),
              (ctx.qmodel,), dict(ddim_kw), donates=True),
        Entry("dequant_matmul_xla", "ddim_cold_tpu/ops/quant.py",
              jax.jit(quant.dequant_matmul, static_argnames=("mode",)),
              (jax.ShapeDtypeStruct((8, 32), jnp.bfloat16),
               jax.ShapeDtypeStruct((32, 64), jnp.int8),
               jax.ShapeDtypeStruct((64,), jnp.float32)),
              (), dict(mode="xla")),
    ]

    TRAIN = "ddim_cold_tpu/train/step.py"
    H, W = m.img_size
    noisy = jax.ShapeDtypeStruct((N, H, W, m.in_chans), jnp.float32)
    t = jax.ShapeDtypeStruct((N,), jnp.int32)
    state = jax.eval_shape(
        lambda k, nz, tt: create_train_state(m, k, 1e-3, 100, (nz, None, tt)),
        key, noisy, t)
    loss_rec = jax.ShapeDtypeStruct((), jnp.float32)
    entries.append(Entry(
        "train_step", TRAIN, make_train_step(m),
        (state, (noisy, noisy, t), key, loss_rec), donates=True))
    return entries


def run_entry_checks(max_const_bytes: int = 1 << 20,
                     traces: dict | None = None) -> list[Finding]:
    """J001–J005 over every registered entry. When ``traces`` is passed
    (a dict), each entry's ``(entry, closed_jaxpr)`` is stashed into it —
    the kernels layer (P-rules) walks these instead of re-tracing."""
    ctx = Context()
    findings: list[Finding] = []
    for e in build_entries(ctx):
        closed = e.trace()
        if traces is not None:
            traces[e.name] = (e, closed)
        out_shapes = e.out_shapes()
        findings += jaxpr_checks.check_accumulation(closed, e.name, e.path)
        findings += jaxpr_checks.check_weak_types(out_shapes, e.name, e.path)
        findings += jaxpr_checks.check_donation(
            e.args_info(), out_shapes, e.name, e.path,
            expect_donation=e.donates)
        findings += jaxpr_checks.check_constants(closed, e.name, e.path,
                                                 max_bytes=max_const_bytes)
        findings += jaxpr_checks.check_host_callbacks(closed, e.name, e.path)
    return findings


# ---------------------------------------------------------------------------
# J006 — the serve-sweep signature check
# ---------------------------------------------------------------------------

def _serve_entry(ctx: Context, config, bucket: int) -> Entry:
    """The exact dispatch serve/engine.py's ``_build_program`` AOT-compiles
    for (config, bucket) — same functions, same statics, same aval shapes —
    mirrored here so its trace identity is checked statically. The task and
    preview branches mirror too: inpaint has its own constrained scan (with
    known/mask avals), ``preview_every > 0`` selects the sequence variant."""
    from ddim_cold_tpu.ops import sampling

    model = ctx.qmodel if config.quant else ctx.model
    if config.sp_degree > 1:
        # the engine traces sp configs against the sp clone over the
        # per-degree (data, seq) mesh; the mesh appears in the shard_map
        # jaxpr params, so sp programs hash distinctly from non-sp (and
        # per-geometry) even though the arg avals are identical
        model = ctx.sp_model(config)
    params = ctx.qparams if config.quant else ctx.params
    x = ctx.x(bucket)
    seq = config.preview_every > 0
    cache_kw = dict(cache_interval=config.cache_interval,
                    cache_mode=config.cache_mode,
                    cache_threshold=config.cache_threshold,
                    cache_tokens=config.cache_tokens or None)
    if config.task == "inpaint":
        H, W = ctx.model.img_size
        mask = jax.ShapeDtypeStruct((bucket, H, W, 1), jnp.float32)
        if config.cached:
            fn = (sampling._ddim_scan_inpaint_cached_seq if seq
                  else sampling._ddim_scan_inpaint_cached)
            return Entry("serve", "", fn,
                         (params, x, ctx.x(bucket), mask, ctx.key,
                          ctx.cache(bucket, config.cache_mode)), (model,),
                         dict(k=config.k, t_start=config.t_start, eta=0.0,
                              sequence=seq, **cache_kw))
        fn = (sampling._ddim_scan_inpaint_seq if seq
              else sampling._ddim_scan_inpaint)
        return Entry("serve", "", fn,
                     (params, x, ctx.x(bucket), mask, ctx.key), (model,),
                     dict(k=config.k, t_start=config.t_start, eta=0.0,
                          sequence=seq))
    if config.sampler == "cold":
        if config.cached:
            fn = (sampling._cold_scan_cached_seq if seq
                  else sampling._cold_scan_cached)
            return Entry("serve", "", fn,
                         (params, x, ctx.cache(bucket, config.cache_mode)),
                         (model,),
                         dict(levels=config.levels, return_sequence=seq,
                              **cache_kw))
        fn = sampling._cold_scan_seq if seq else sampling._cold_scan
        return Entry("serve", "", fn, (params, x), (model,),
                     dict(levels=config.levels, return_sequence=seq))
    if config.steps > 0:
        if config.cached:
            fn = (sampling._ddim_scan_fewstep_cached_seq if seq
                  else sampling._ddim_scan_fewstep_cached)
            return Entry("serve", "", fn,
                         (params, x, ctx.key,
                          ctx.cache(bucket, config.cache_mode)), (model,),
                         dict(steps=config.steps, t_start=config.t_start,
                              eta=0.0, sequence=seq, **cache_kw))
        fn = (sampling._ddim_scan_fewstep_seq if seq
              else sampling._ddim_scan_fewstep)
        return Entry("serve", "", fn, (params, x, ctx.key), (model,),
                     dict(steps=config.steps, t_start=config.t_start,
                          eta=0.0, sequence=seq))
    if config.cached:
        if config.telemetry:
            # mirrors Engine._ddim_cached_tel_spec: the telemetry scan has
            # no `sequence` static (last-only by contract)
            return Entry("serve", "", sampling._ddim_scan_cached_tel,
                         (params, x, ctx.key,
                          ctx.cache(bucket, config.cache_mode)), (model,),
                         dict(k=config.k, t_start=config.t_start, eta=0.0,
                              **cache_kw))
        fn = (sampling._ddim_scan_cached_seq if seq
              else sampling._ddim_scan_cached)
        return Entry("serve", "", fn,
                     (params, x, ctx.key,
                      ctx.cache(bucket, config.cache_mode)), (model,),
                     dict(k=config.k, t_start=config.t_start, eta=0.0,
                          sequence=seq, **cache_kw))
    fn = (sampling._ddim_scan_sequence if seq
          else sampling._ddim_scan_last)
    return Entry("serve", "", fn,
                 (params, x, ctx.key), (model,),
                 dict(k=config.k, t_start=config.t_start, eta=0.0))


def serve_signatures(ctx: Context, findings: list | None = None,
                     traces: dict | None = None) -> dict[str, str]:
    """``"<label>:b<bucket>" → trace hash`` for the whole warmed sweep.
    When ``findings`` is passed, each trace is also run through the J007
    static-trip-count check (no extra tracing — the J006 trace is reused).
    When ``traces`` is passed (a dict), each subject's ``(config,
    closed_jaxpr)`` is stashed into it — the collective-order pass (C001/
    C002) consumes this cache instead of re-tracing the sweep, which is
    what keeps the full graftcheck run inside the CPU budget."""
    out = {}
    for label, config, buckets in serve_sweep():
        for bucket in buckets:
            e = _serve_entry(ctx, config, bucket)
            closed = e.trace()
            subject = f"{label}:b{bucket}"
            out[subject] = jaxpr_checks.signature_hash(closed, e.dyn_args)
            if findings is not None:
                findings += jaxpr_checks.check_static_trip_count(
                    closed, subject, "ddim_cold_tpu/serve/engine.py")
            if traces is not None:
                traces[subject] = (config, closed)
    return out


# ---------------------------------------------------------------------------
# 200px kernel/memory entries — the geometry that crashed r04
# ---------------------------------------------------------------------------

#: the north-star model the kernels/memory layers prove statically
NS_MODEL = "oxford_flower_200_p4"
NS_TOKENS = 2501   # (200/4)² patches + cls — the N Mosaic rejected on r04
NS_ROWS = 16       # the bench's north-star batch
NS_K = 20          # the north-star DDIM step count

_FLASH_PATH = "ddim_cold_tpu/ops/flash_attention.py"
_QUANT_PATH = "ddim_cold_tpu/ops/quant.py"


def kernel_entries() -> list[Entry]:
    """First-class 200px entries (N=2501; f32, bf16, w8a16): the full
    sampler scans the bench's north-star legs dispatch — every in-tree
    pallas_call at the EXACT geometry that crashed r04 — plus standalone
    flash forward/grad traces per (dtype, block config) covering the
    backward dq/dkv kernels and every ``--flash-block-sweep`` row, and the
    dequant-pallas kernel at the 200px trunk GEMM shapes. The TINY serve
    sweep contains zero pallas_calls (it serves quant="xla" only), so
    these entries ARE the kernels layer's real coverage.

    Tracing stays abstract end to end (eval_shape params); the whole
    registry traces in a few seconds on CPU."""
    from ddim_cold_tpu.models.vit import MODEL_CONFIGS, DiffusionViT
    from ddim_cold_tpu.ops import quant, sampling
    from ddim_cold_tpu.ops.flash_attention import (
        FLASH_BLOCK_SWEEP, NS_FLASH_BLOCKS, flash_attention,
    )

    cfg = MODEL_CONFIGS[NS_MODEL]
    key = jax.random.PRNGKey(0)
    entries: list[Entry] = []

    # full sampler programs, flash trunk at the tuned north-star blocks —
    # these feed BOTH layers (P over their pallas_calls, M over the scan).
    # The fused variants dispatch the trunk megakernels (fused attention +
    # fused Mlp, ops/flash_attention.py + ops/quant.py) so P001–P003/
    # M001–M002 certify the exact programs bench --fusion runs.
    base = DiffusionViT(dtype=jnp.bfloat16, use_flash=True,
                        flash_blocks=NS_FLASH_BLOCKS, **cfg)
    H, W = base.img_size
    x2 = jax.ShapeDtypeStruct((2, H, W, base.in_chans), jnp.float32)
    t2 = jax.ShapeDtypeStruct((2,), jnp.int32)
    xr = jax.ShapeDtypeStruct((NS_ROWS, H, W, base.in_chans), jnp.float32)
    mem = dict(tokens=NS_TOKENS, rows=NS_ROWS, memory=True)
    fparams = jax.eval_shape(base.init, key, x2, t2)["params"]
    qparams = jax.eval_shape(quant.quantize_params, fparams)
    for label, model in (("f32", base.clone(dtype=jnp.float32)),
                         ("bf16", base),
                         ("w8a16", base.clone(quant="pallas")),
                         ("w8a16_fused", base.clone(quant="pallas",
                                                    fused=True)),
                         ("w8a8_fused", base.clone(quant="w8a8",
                                                   fused=True))):
        params = qparams if model.quant else fparams
        entries.append(Entry(
            f"ns200_{label}", _FLASH_PATH, sampling._ddim_scan_last,
            (params, xr, key), (model,),
            dict(k=NS_K, t_start=None, eta=0.0), donates=True,
            meta=dict(mem)))

    # few-step distilled serving at the north star (ISSUE 17): the k=4
    # student program the --fewstep bench leg dispatches — 3-trip schedule
    # scan + the final jump-to-clean forward — so the P-rules certify its
    # pallas calls and the M-rules its peak-HBM at the 200px geometry
    entries.append(Entry(
        "ns200_fewstep4_bf16", _FLASH_PATH, sampling._ddim_scan_fewstep,
        (fparams, xr, key), (base,),
        dict(steps=4, t_start=None, eta=0.0, sequence=False), donates=True,
        meta=dict(mem)))

    # standalone flash kernels per (dtype, blocks): forward for every
    # sweep row, grad (the backward dq/dkv kernels) at the default and
    # tuned configs. scale matches the model's head_dim=64.
    qkv = jax.ShapeDtypeStruct((2, NS_TOKENS, cfg["num_heads"],
                                cfg["embed_dim"] // cfg["num_heads"]),
                               jnp.float32)
    scale = (cfg["embed_dim"] // cfg["num_heads"]) ** -0.5
    configs = []
    for bq, bkv in ((256, 512), NS_FLASH_BLOCKS, *FLASH_BLOCK_SWEEP):
        if (bq, bkv) not in configs:
            configs.append((bq, bkv))
    for dt_label, dtype in (("f32", jnp.float32), ("bf16", jnp.bfloat16)):
        q = jax.ShapeDtypeStruct(qkv.shape, dtype)
        for bq, bkv in configs:
            def fwd(qq, kk, vv, _bq=bq, _bkv=bkv):
                return flash_attention(qq, kk, vv, scale, _bq, _bkv)

            entries.append(Entry(
                f"flash200_{dt_label}_{bq}x{bkv}", _FLASH_PATH, fwd,
                (q, q, q), meta=dict(tokens=NS_TOKENS)))
            if (bq, bkv) in ((256, 512), NS_FLASH_BLOCKS):
                def loss(qq, kk, vv, _f=fwd):
                    return jnp.sum(_f(qq, kk, vv).astype(jnp.float32))

                entries.append(Entry(
                    f"flash200_grad_{dt_label}_{bq}x{bkv}", _FLASH_PATH,
                    jax.grad(loss, argnums=(0, 1, 2)), (q, q, q),
                    meta=dict(tokens=NS_TOKENS)))

    # the dequant-pallas kernel at the 200px trunk GEMM shapes: qkv
    # (E → 3E) and proj/mlp (E → E) over M = rows·N activation rows
    E = cfg["embed_dim"]
    M = NS_ROWS * NS_TOKENS
    for label, n_out in (("qkv", 3 * E), ("proj", E)):
        entries.append(Entry(
            f"dequant200_{label}", _QUANT_PATH, quant._dequant_matmul_pallas,
            (jax.ShapeDtypeStruct((M, E), jnp.bfloat16),
             jax.ShapeDtypeStruct((E, n_out), jnp.int8),
             jax.ShapeDtypeStruct((n_out,), jnp.float32))))

    # standalone fused trunk kernels at the 200px geometry, blocks from the
    # committed autotune table (ops/tuning.py) — every (kernel, dtype, mode)
    # variant the fused sampler can dispatch gets its own P-rule subject
    from ddim_cold_tpu.ops import tuning
    from ddim_cold_tpu.ops.flash_attention import fused_trunk_attention

    heads = cfg["num_heads"]
    wq = jax.ShapeDtypeStruct((E, 3 * E), jnp.int8)
    sq = jax.ShapeDtypeStruct((3 * E,), jnp.float32)
    bq_ = jax.ShapeDtypeStruct((3 * E,), jnp.float32)
    wp = jax.ShapeDtypeStruct((E, E), jnp.int8)
    sp_ = jax.ShapeDtypeStruct((E,), jnp.float32)
    bp_ = jax.ShapeDtypeStruct((E,), jnp.float32)
    for dt_label, dtype, mode in (("f32", jnp.float32, "pallas"),
                                  ("bf16", jnp.bfloat16, "pallas"),
                                  ("w8a8", jnp.float32, "w8a8")):
        kernel_dt = jnp.int8 if mode == "w8a8" else dtype
        fbq, fbkv = tuning.attn_blocks(NS_TOKENS, E, heads, kernel_dt,
                                       device_kind=tuning.DEVICE_KIND)

        def fattn(xx, a, b, c, d, e, f, _bq=fbq, _bkv=fbkv, _mode=mode):
            return fused_trunk_attention(
                xx, a, b, c, d, e, f, num_heads=heads, scale=scale,
                block_q=_bq, block_kv=_bkv, mode=_mode)

        entries.append(Entry(
            f"fused200_attn_{dt_label}", _FLASH_PATH, fattn,
            (jax.ShapeDtypeStruct((2, NS_TOKENS, E), dtype),
             wq, sq, bq_, wp, sp_, bp_), meta=dict(tokens=NS_TOKENS)))

    # fused Mlp at the 200px trunk shapes (mlp_ratio=1.0 → hidden = E):
    # float, w8a16 and w8a8 variants over the full M = rows·N row count
    b1 = jax.ShapeDtypeStruct((E,), jnp.float32)
    b2 = jax.ShapeDtypeStruct((E,), jnp.float32)
    for dt_label, x_dt, w_dt, mode in (
            ("float_bf16", jnp.bfloat16, jnp.bfloat16, None),
            ("w8a16_bf16", jnp.bfloat16, jnp.int8, "pallas"),
            ("w8a8", jnp.float32, jnp.int8, "w8a8")):
        kernel_dt = jnp.int8 if mode == "w8a8" else x_dt
        bm = tuning.mlp_block_m(E, E, kernel_dt, quant=mode is not None,
                                device_kind=tuning.DEVICE_KIND)
        def fmlp(xx, w1_, b1_, w2_, b2_, *scales, _bm=bm, _mode=mode):
            kw = (dict(scale1=scales[0], scale2=scales[1]) if scales
                  else {})
            return quant.mlp_pallas(xx, w1_, b1_, w2_, b2_, mode=_mode,
                                    block_m=_bm, **kw)

        entries.append(Entry(
            f"mlp200_{dt_label}", _QUANT_PATH, fmlp,
            (jax.ShapeDtypeStruct((M, E), x_dt),
             jax.ShapeDtypeStruct((E, E), w_dt), b1,
             jax.ShapeDtypeStruct((E, E), w_dt), b2,
             *( (sp_, sp_) if mode else () ))))
    return entries


def kernel_traces() -> dict:
    """``name → (entry, closed_jaxpr)`` for the 200px registry — the
    shared input of the kernels/memory layers and bench's static
    memory-budget leg."""
    return {e.name: (e, e.trace()) for e in kernel_entries()}


def run_serve_signature_check(traces: dict | None = None) -> list[Finding]:
    """Trace the warmed sweep twice with independently built model/param
    worlds. Hash instability across worlds = a retrace would MISS the AOT
    executable (a serve-time compile); a hash shared by two distinct
    (config, bucket) pairs = the programs are indistinguishable at the
    abstract level, so the check itself lost resolution — both are J006.

    This cross-world stability is also the fleet replacement proof
    (serve/router.py): a replacement replica warms from the same
    (config, bucket) set in a freshly built world, which is exactly the
    world-B trace here — hash-equal programs mean the replacement serves
    from its own warmup without a single in-service compile.

    The world-A traces are also run through J007 (static trip count): no
    served program — in particular no adaptive-gated cached sampler — may
    contain a ``while`` primitive, so the drift gate provably cannot vary
    the loop structure at run time."""
    PATH = "ddim_cold_tpu/serve/engine.py"
    findings: list[Finding] = []
    sigs_a = serve_signatures(Context(), findings, traces)
    sigs_b = serve_signatures(Context())
    by_hash: dict[str, str] = {}
    for subject, h in sigs_a.items():
        if sigs_b[subject] != h:
            findings.append(Finding(
                "GRAFT-J006", PATH, f"unstable:{subject}", 0,
                f"serve pair {subject} traces to a different program hash "
                "from an independently built model — warmup's AOT "
                "executable would not be reused (serve-time recompile)"))
            continue
        if h in by_hash:
            findings.append(Finding(
                "GRAFT-J006", PATH, f"collision:{subject}", 0,
                f"serve pairs {by_hash[h]} and {subject} hash to the same "
                "abstract program — distinct configs must compile distinct "
                "programs or the signature check has lost resolution"))
        else:
            by_hash[h] = subject
    return findings
