"""GRAFT-M001/M002 — static peak-HBM budget analysis over traced programs.

xDiT-style multi-axis serving and the fused-kernel work both make
per-program memory budgets the scaling constraint, and the engine's AOT
model means every served program's residency is decided at trace time —
so prove it there. For each traced ``(SamplerConfig, bucket)`` program
(and the first-class 200px entries) the pass runs a donation-aware
liveness walk over the jaxpr and produces an upper bound on peak live HBM
bytes: resident params and the step cache are program inputs and are
counted from entry; a donated input (the engine donates every carry —
``pjit``'s ``donated_invars`` rides the eqn params, no lowering needed)
dies at its last use, a non-donated one stays live to the end; each eqn's
outputs join the live set as they materialize and operands leave it after
their last use; a nested scan/cond/pjit body contributes its own interior
peak above its boundary (one iteration's peak stands in for all — XLA
reuses the body's buffers across trips).

The walk ignores XLA fusion (two eqns XLA would fuse never materialize
the intermediate), so the bound is conservative: a program that passes
here fits on chip with room to spare; a program that fails is flagged
before it burns a hardware window.

**M001** — peak over the device HBM budget (``utils/flops.HBM_BYTES``,
default the bench v5e) at a registered geometry.

**M002** — bucket/sequence padding inflating residency: any traced aval
whose dim sits in ``[tokens, 2·tokens)`` is the padded token axis; its
extent over the logical token count beyond the threshold means the
program carries padding as if it were payload (the tile-padding worst
case stays well under; a pad-to-power-of-two class bug trips it). The
window only identifies a token axis when the token count is large enough
to be distinctive (``MIN_PAD_TOKENS``) — at the TINY sweep's 5 tokens,
batch and pixel dims land inside it, so the check abstains there and
bites at the registered 200px geometry (N=2501), where no other axis
comes near.
"""

from __future__ import annotations

import numpy as np
from jax import core as jax_core

from ddim_cold_tpu.analysis.findings import Finding

#: the device kind the HBM budget defaults to — the bench chip (v5e, the
#: smallest-HBM kind we run; fitting there keeps every bigger chip safe)
DEVICE_KIND = "TPU v5 lite"

#: M002 threshold: padded token extent over the logical token count. The
#: in-tree worst case — the streamed-kv flash padding at 200px
#: (3072/2501 = 1.228) — passes; a pad-to-4096 class bug at N=2501
#: (1.64) fails.
PAD_THRESHOLD = 1.30

#: below this token count the [tokens, 2·tokens) window is ambiguous —
#: batch sizes and image pixel dims land inside it — so M002 abstains
#: rather than guess which dim is the token axis
MIN_PAD_TOKENS = 128

_SUB_JAXPR_KEYS = ("jaxpr", "call_jaxpr", "fun_jaxpr", "branches",
                   "cond_jaxpr", "body_jaxpr")


def aval_bytes(aval) -> int:
    shape = getattr(aval, "shape", None)
    dtype = getattr(aval, "dtype", None)
    if shape is None or dtype is None:
        return 0
    return int(np.prod(shape or (1,))) * np.dtype(dtype).itemsize


def _sub_jaxprs(eqn):
    for key in _SUB_JAXPR_KEYS:
        val = eqn.params.get(key)
        if val is None:
            continue
        vals = val if isinstance(val, (list, tuple)) else [val]
        for v in vals:
            v = getattr(v, "jaxpr", v)  # ClosedJaxpr → Jaxpr
            if hasattr(v, "eqns"):
                yield v


def _inner_extra(eqn) -> int:
    """The interior peak a nested body adds ABOVE its boundary (the body's
    invars/consts are the eqn's operands, already counted by the caller's
    live set). Max over sub-jaxprs; cond/switch branches don't run
    together, so max is exact for them too."""
    extra = 0
    for sub in _sub_jaxprs(eqn):
        boundary = sum(aval_bytes(v.aval) for v in sub.invars)
        boundary += sum(aval_bytes(v.aval) for v in sub.constvars)
        extra = max(extra, _jaxpr_peak(sub) - boundary)
    return max(extra, 0)


def _jaxpr_peak(jaxpr, donated=()) -> int:
    """Peak live bytes over one jaxpr's straight-line schedule. ``donated``
    flags align with ``jaxpr.invars``; a donated invar dies at its last
    use, everything else the caller retains lives throughout."""
    jaxpr = getattr(jaxpr, "jaxpr", jaxpr)
    n_eqns = len(jaxpr.eqns)
    last_use: dict = {}
    for i, eqn in enumerate(jaxpr.eqns):
        for v in eqn.invars:
            if not isinstance(v, jax_core.Literal):
                last_use[v] = i
    for v in jaxpr.outvars:
        if not isinstance(v, jax_core.Literal):
            last_use[v] = n_eqns  # program outputs live to the end
    donated = tuple(donated) + (False,) * (len(jaxpr.invars) - len(donated))
    running = 0
    for v in jaxpr.constvars:
        running += aval_bytes(v.aval)
        last_use[v] = n_eqns  # consts are executable-resident
    for v, don in zip(jaxpr.invars, donated):
        running += aval_bytes(v.aval)
        if not don:
            last_use[v] = n_eqns
    peak = running
    for i, eqn in enumerate(jaxpr.eqns):
        # while the eqn runs: operands still live + the body's interior
        peak = max(peak, running + _inner_extra(eqn))
        for v in eqn.outvars:
            if v in last_use:  # unused outputs (DropVar) never materialize
                running += aval_bytes(v.aval)
        peak = max(peak, running)
        for v in {v for v in eqn.invars
                  if not isinstance(v, jax_core.Literal)}:
            if last_use.get(v) == i:
                running -= aval_bytes(v.aval)
    return peak


def peak_live_bytes(closed) -> int:
    """Upper bound on peak live HBM bytes for one traced program. A
    top-level single-``pjit`` trace (every jitted entry) is unwrapped so
    the body's ``donated_invars`` drive the walk — the outer wrapper would
    double-count each donated carry against its aliased output."""
    consts = sum(aval_bytes(getattr(c, "aval", c))
                 for c in getattr(closed, "consts", ()))
    jaxpr = closed.jaxpr
    if len(jaxpr.eqns) == 1 and jaxpr.eqns[0].primitive.name == "pjit":
        eqn = jaxpr.eqns[0]
        body = eqn.params["jaxpr"]
        don = eqn.params.get("donated_invars") or ()
        return consts + _jaxpr_peak(body, don)
    return consts + _jaxpr_peak(jaxpr)


def _iter_avals(closed):
    """Every traced aval: program inputs plus each eqn output, nested
    bodies included (their boundary vars are the enclosing operands)."""
    from ddim_cold_tpu.analysis import jaxpr_checks

    jaxpr = getattr(closed, "jaxpr", closed)
    for v in jaxpr.invars:
        yield v.aval
    for eqn, _ in jaxpr_checks.iter_eqns(jaxpr):
        for v in eqn.outvars:
            yield v.aval


# ---------------------------------------------------------------------------
# M001 — peak over the device HBM budget
# ---------------------------------------------------------------------------

def check_peak_hbm(closed, subject: str, path: str, *,
                   device_kind: str = DEVICE_KIND,
                   budget_bytes: int | None = None) -> list[Finding]:
    from ddim_cold_tpu.utils import flops

    if budget_bytes is None:
        budget_bytes = flops.hbm_bytes(device_kind)
    if budget_bytes is None:
        return []
    peak = peak_live_bytes(closed)
    if peak <= budget_bytes:
        return []
    return [Finding(
        "GRAFT-M001", path, f"{subject}:peak", 0,
        f"program `{subject}` peaks at {peak / 2**30:.2f} GiB live HBM "
        f"(donation-aware liveness bound) — over the {device_kind} budget "
        f"of {budget_bytes / 2**30:.0f} GiB; shrink the bucket, shard the "
        "program, or drop residuals")]


# ---------------------------------------------------------------------------
# M002 — padding inflating residency over the logical payload
# ---------------------------------------------------------------------------

def check_padding(closed, subject: str, path: str, *, tokens: int,
                  threshold: float = PAD_THRESHOLD) -> list[Finding]:
    if tokens < MIN_PAD_TOKENS:
        return []  # window too ambiguous to name a token axis — abstain
    worst, worst_shape = 1.0, None
    for aval in _iter_avals(closed):
        for dim in getattr(aval, "shape", ()):
            if tokens <= dim < 2 * tokens:
                ratio = dim / tokens
                if ratio > worst:
                    worst, worst_shape = ratio, tuple(aval.shape)
    if worst <= threshold:
        return []
    return [Finding(
        "GRAFT-M002", path, f"{subject}:pad", 0,
        f"program `{subject}` carries a token axis padded to "
        f"{100 * (worst - 1):.0f}% over the logical {tokens} tokens "
        f"(aval {worst_shape}; threshold {100 * (threshold - 1):.0f}%) — "
        "bucket/sp/tile padding is being paid as resident payload")]


# ---------------------------------------------------------------------------
# drivers
# ---------------------------------------------------------------------------

#: serve-sweep findings anchor where J006's do
ENGINE_PATH = "ddim_cold_tpu/serve/engine.py"


def check_program(closed, subject: str, path: str, *, tokens: int,
                  device_kind: str = DEVICE_KIND,
                  budget_bytes: int | None = None,
                  threshold: float = PAD_THRESHOLD) -> list[Finding]:
    findings = check_peak_hbm(closed, subject, path,
                              device_kind=device_kind,
                              budget_bytes=budget_bytes)
    findings += check_padding(closed, subject, path, tokens=tokens,
                              threshold=threshold)
    return findings


def run_memory_checks(serve_traces: dict | None = None,
                      kernel_traces: dict | None = None,
                      device_kind: str = DEVICE_KIND) -> list[Finding]:
    """The memory layer: peak-HBM + padding budget per (SamplerConfig,
    bucket) sweep program and per 200px sampler entry. Reuses the CLI's
    shared traces; standalone (``--only M``) it traces its own world."""
    from ddim_cold_tpu.analysis import entries

    if serve_traces is None:
        serve_traces = {}
        entries.serve_signatures(entries.Context(), traces=serve_traces)
    if kernel_traces is None:
        kernel_traces = entries.kernel_traces()
    tiny_tokens = (entries.TINY["img_size"][0]
                   // entries.TINY["patch_size"]) ** 2 + 1
    findings: list[Finding] = []
    for subject in sorted(serve_traces):
        _config, closed = serve_traces[subject]
        findings += check_program(closed, subject, ENGINE_PATH,
                                  tokens=tiny_tokens,
                                  device_kind=device_kind)
    for name in sorted(kernel_traces):
        e, closed = kernel_traces[name]
        meta = e.meta or {}
        if not meta.get("memory"):
            continue  # pure kernel-geometry entries — P-rules cover them
        findings += check_program(closed, name, e.path,
                                  tokens=meta["tokens"],
                                  device_kind=device_kind)
    return findings


def budget_report(kernel_traces: dict | None = None,
                  device_kind: str = DEVICE_KIND) -> dict:
    """JSON-ready static budget summary for bench's ``submetrics.memory``:
    per-200px-program peak HBM GiB and per-kernel VMEM MiB, worst-case
    rollups first so obs/trend.py can band them."""
    from ddim_cold_tpu.analysis import entries, kernel_checks
    from ddim_cold_tpu.utils import flops

    if kernel_traces is None:
        kernel_traces = entries.kernel_traces()
    programs: dict = {}
    kernels: dict = {}
    findings: list[Finding] = []
    for name in sorted(kernel_traces):
        e, closed = kernel_traces[name]
        meta = e.meta or {}
        if meta.get("memory"):
            programs[name] = round(peak_live_bytes(closed) / 2**30, 3)
            findings += check_program(closed, name, e.path,
                                      tokens=meta["tokens"],
                                      device_kind=device_kind)
        seen = 0
        for call in kernel_checks.iter_kernel_calls(closed, e.path):
            seen += 1
            key = f"{name}:{call.name}#{seen}"
            kernels[key] = round(call.vmem_bytes() / 2**20, 3)
        findings += kernel_checks.check_program(
            closed, name, e.path, logical=meta.get("tokens"),
            device_kind=device_kind)
    return {
        "device_kind": device_kind,
        "hbm_budget_gib": round((flops.hbm_bytes(device_kind) or 0) / 2**30),
        "vmem_budget_mib": round(
            (flops.vmem_bytes(device_kind) or 0) / 2**20),
        "peak_hbm_gb": max(programs.values()) if programs else None,
        "max_kernel_vmem_mb": max(kernels.values()) if kernels else None,
        "programs": programs,
        "kernels": kernels,
        "findings": [f.render() for f in findings],
    }
