"""graftcheck — static analysis proving the repo's TPU invariants.

PRs 1–4 established invariants the test suite can only sample at a few
shapes: zero serve-time recompiles, live buffer donation, the
bf16-trunk/f32-accumulate dtype policy, full sharding-spec coverage. This
package checks them *statically* on every commit by abstractly tracing the
real entry points (no device, no params materialized) and linting the host
code for the repo-specific hazards:

* :mod:`.jaxpr_checks` + :mod:`.entries` — GRAFT-J001..J007 over traced
  jaxprs, AOT donation metadata, and the serve-sweep signature hash.
* :mod:`.ast_checks` — GRAFT-A001..A005 source lint.
* :mod:`.sharding_checks` — GRAFT-S001/S002 param-tree spec coverage.
* :mod:`.thread_checks` — GRAFT-T001..T005 lockset/lock-order analysis of
  the threaded host serving layer (``# guarded-by:`` annotation grammar).
* :mod:`.collective_checks` — GRAFT-C001/C002 collective-order deadlock
  proofs over the serve sweep's cached traces (multi-axis mesh programs).
* :mod:`.kernel_checks` — GRAFT-P001..P003 Mosaic tile legality, VMEM fit,
  and padding waste for every ``pallas_call`` in the traces (including the
  first-class 200px kernel entries at the north-star geometry).
* :mod:`.memory_checks` — GRAFT-M001/M002 donation-aware peak-HBM liveness
  bound and padded-residency check per traced program.
* :mod:`.cli` — ``python -m ddim_cold_tpu.analysis`` / ``graftcheck``;
  nonzero exit on non-baselined findings; ``--fix-baseline`` regenerates
  the reviewed allowlist (``--only`` limits it to selected rule families).

This module stays import-light (no jax) so the CLI can pin the platform
before tracing.
"""

from ddim_cold_tpu.analysis.findings import RULES, Finding  # noqa: F401
