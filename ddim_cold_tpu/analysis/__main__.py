import sys

from ddim_cold_tpu.analysis.cli import main

sys.exit(main())
