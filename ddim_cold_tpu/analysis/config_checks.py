"""X-rules: the SamplerConfig lattice, abstractly enumerated (layer X).

The J-layer proves every *swept* config traces, hashes stably, and (J006)
hashes *distinctly*. What nothing proved until now is the converse
direction: that the sweep actually COVERS the legal config space — a new
legal combination (say ``cache_mode="full"``, which shipped with zero
sweep entries) silently gets no trace/hash/compile coverage, and its first
trace happens in production. The X-layer closes that hole by enumerating
the lattice from the validation code itself and demanding sweep witnesses.

``SamplerConfig.__post_init__`` is the single construction-time gate, so
the legal space is *decidable by construction*: build every candidate in a
product grid over the declared axes and keep the ones that don't raise.
The grid is quotiented the same way PR 17's ``program_fingerprint`` is
constant-blind: axes whose values are scan-trip constants or pure
param-routing (``k``, ``t_start``, thresholds, token counts, ``student``)
collapse to one representative each, because two values on such an axis
are *by design* the same compiled program class.

Rules:

* **X001 sweep completeness** — every legal program CLASS (the
  ``config_class`` quotient) is witnessed by the J-layer sweep:
  (D1) every legal (family, cached, telemetry, seq) projection at the
  base modifiers has a sweep entry; (D2) every legal cache mode has a
  cached witness; (D3) every CPU-traceable quant mode has a cached and an
  uncached witness (the Pallas-backed modes — ``pallas``/``w8a8``/
  ``fused`` — are documented exclusions certified by the P/M kernel
  layers instead, and the exclusion list is pinned against
  ``_QUANT_MODES`` so a new quant mode can't ship unclassified);
  (D4) the sequence-parallel family is witnessed at exactly the
  geometries the sweep's device gate admits in this world.
* **X002 validation consistency** — the lattice has ONE boundary:
  (a) the cache subspace accepted at SamplerConfig construction agrees
  with ``ops/step_cache.cache_spec`` (the program-build gate) combo by
  combo; (b) every step count the distillation trainer can produce a
  student at is servable (``steps=s, student=True`` constructs), and the
  ``steps=0`` student hole stays closed; (c) no code path bypasses the
  gate by ``object.__setattr__`` onto a frozen config (the dataclass is
  frozen precisely so construction is the only door).
* **X003 warmup-set soundness** — the configs serving actually warms are
  inside the lattice: every ``workloads.default_edit_configs`` member (at
  preview 0 and 2) constructs AND its D1 projection is sweep-witnessed;
  every literal ``SamplerConfig(...)`` call site in ``bench.py``
  constructs once non-literal kwargs are substituted from per-axis
  representatives.
"""

from __future__ import annotations

import ast
import dataclasses
import itertools
import os

from ddim_cold_tpu.analysis.findings import Finding

_ENTRIES_PATH = "ddim_cold_tpu/analysis/entries.py"
_BATCHING_PATH = "ddim_cold_tpu/serve/batching.py"
_TASKS_PATH = "ddim_cold_tpu/workloads/tasks.py"

#: quant modes the CPU lattice sweep covers vs the documented exclusions
#: (Pallas-backed programs don't lower on the CPU J-layer worlds — their
#: trace/latency coverage is the P/M kernel layers' 200px entries). X001
#: pins COVERED ∪ EXCLUDED == _QUANT_MODES so a new mode must be filed.
COVERED_QUANT = (None, "xla")
EXCLUDED_QUANT = ("pallas", "w8a8")

#: one cache-axis representative per mode: (interval, mode, threshold,
#: tokens). Values on the threshold/token axes are constant-blind
#: (fingerprint-equivalent) — one representative each is the quotient.
_CACHE_POINTS = (
    (1, "delta", None, 0),        # uncached
    (2, "delta", None, 0),
    (2, "full", None, 0),
    (2, "adaptive", 0.05, 0),
    (2, "token", None, 3),
)

#: (steps, student) representatives: stride family, two fewstep counts
#: (steps=1 lowers scan-free — structurally its own class), one student
#: (param-routing only: same program, so it adds no D1 class)
_STEP_POINTS = ((0, False), (1, False), (4, False), (2, True))

#: modules X002c scans for frozen-config bypasses
_BYPASS_SCAN = (
    "ddim_cold_tpu/serve",
    "ddim_cold_tpu/workloads",
    "ddim_cold_tpu/train",
    "bench.py",
)

#: substitutes for non-literal kwargs at bench.py SamplerConfig sites —
#: one in-lattice representative per axis (X003's constant-blind quotient:
#: WHICH value a sweep variable takes never changes legality)
_BENCH_REPRESENTATIVES = {
    "k": 10, "t_start": 999, "levels": 4, "cache_interval": 2,
    "cache_threshold": 0.05, "cache_tokens": 3, "steps": 2,
    "sp_degree": 2, "preview_every": 2,
}


def _sampler_config():
    from ddim_cold_tpu.serve.batching import SamplerConfig

    return SamplerConfig


def _sp_error():
    from ddim_cold_tpu.parallel.ulysses import SeqParallelConfigError

    return SeqParallelConfigError


def try_config(**kwargs):
    """Construct a SamplerConfig; the legality oracle. Returns the config
    or None when the validation gate rejects the combination."""
    SamplerConfig = _sampler_config()
    try:
        return SamplerConfig(**kwargs)
    except (ValueError, _sp_error()):  # noqa: BLE001 — the two documented
        # rejection types (sp errors are lazily imported, hence computed)
        return None


def config_class(cfg) -> tuple:
    """The program-class quotient of one config: the axes that select a
    DIFFERENT compiled program under PR 17's constant-blind fingerprint.
    Constants (k, t_start, levels, thresholds, token/step counts) and pure
    param routing (student) are deliberately absent."""
    if cfg.task == "inpaint":
        family = "inpaint"
    elif cfg.sampler == "cold":
        family = "cold"
    elif cfg.steps > 0:
        family = "fewstep"
    else:
        family = "ddim"
    return (family, cfg.cached, cfg.telemetry, cfg.preview_every > 0,
            cfg.cache_mode if cfg.cached else None, cfg.quant, cfg.fused,
            cfg.sp_mode, cfg.sp_degree)


def projection(cls: tuple) -> tuple:
    """D1's coarse view of a class: (family, cached, telemetry, seq)."""
    return cls[:4]


def _sp_points():
    """The sp geometries the sweep's device gate admits in THIS world —
    X001's demands must mirror the gate exactly or the 1-device CLI world
    would demand witnesses that cannot exist there."""
    import jax

    pts = [("none", 1)]
    n_dev = jax.device_count()
    if n_dev >= 2 and n_dev % 2 == 0:
        pts += [("ulysses", 2), ("ring", 2)]
    if n_dev >= 8 and n_dev % 8 == 0:
        pts.append(("ulysses", 8))
    return pts


def enumerate_lattice() -> list:
    """Every legal config class, as (class, config) pairs — the product
    grid over the quotiented axes, filtered by the construction gate."""
    from ddim_cold_tpu.serve.batching import (_QUANT_MODES, _SAMPLERS,
                                              _TASKS)

    seen = {}
    for task, sampler, cache, quant, fused, preview, tel, steps_pt, sp in \
            itertools.product(_TASKS, _SAMPLERS, _CACHE_POINTS,
                              _QUANT_MODES, (False, True), (0, 2),
                              (False, True), _STEP_POINTS, _sp_points()):
        interval, mode, threshold, tokens = cache
        steps, student = steps_pt
        cfg = try_config(
            task=task, sampler=sampler, cache_interval=interval,
            cache_mode=mode, cache_threshold=threshold,
            cache_tokens=tokens, quant=quant, fused=fused,
            preview_every=preview, telemetry=tel, steps=steps,
            student=student, sp_mode=sp[0], sp_degree=sp[1],
            t_start=999 if task in ("draft", "interp") else None)
        if cfg is not None:
            seen.setdefault(config_class(cfg), cfg)
    return sorted(seen.items(), key=lambda kv: repr(kv[0]))


def _class_name(cls: tuple) -> str:
    family, cached, tel, seq, mode, quant, fused, sp_mode, sp_degree = cls
    bits = [family]
    if cached:
        bits.append(f"cached:{mode}")
    if tel:
        bits.append("tel")
    if seq:
        bits.append("seq")
    if quant:
        bits.append(f"quant:{quant}")
    if fused:
        bits.append("fused")
    if sp_mode != "none":
        bits.append(f"sp:{sp_mode}{sp_degree}")
    return "/".join(bits)


def check_sweep_completeness(sweep=None) -> list:
    """X001: the J-layer sweep witnesses the legal lattice (D1–D4)."""
    if sweep is None:
        from ddim_cold_tpu.analysis import entries

        sweep = entries.serve_sweep()
    findings = []
    witnesses = [config_class(cfg) for _, cfg, _ in sweep]
    lattice = enumerate_lattice()

    def base(cls):
        # quant=None, unfused, sp-off — the D1 plane
        return cls[5] is None and not cls[6] and cls[7] == "none"

    # D1 — every legal (family, cached, tel, seq) projection on the base
    # plane has a witness on the base plane
    legal_projs = sorted({projection(cls) for cls, _ in lattice
                          if base(cls)})
    witnessed_projs = {projection(c) for c in witnesses if base(c)}
    for proj in legal_projs:
        if proj not in witnessed_projs:
            family, cached, tel, seq = proj
            findings.append(Finding(
                "GRAFT-X001", _ENTRIES_PATH,
                f"class:{_class_name((*proj, None, None, False, 'none', 1))}",
                0,
                f"legal program class (family={family}, cached={cached}, "
                f"telemetry={tel}, seq={seq}) has no serve_sweep entry — "
                "it would reach production untraced, unhashed, and "
                "unwarmed (J006 proves nothing about it)"))

    # D2 — every legal cache mode has a cached witness
    legal_modes = sorted({cls[4] for cls, _ in lattice
                          if base(cls) and cls[1]})
    witnessed_modes = {c[4] for c in witnesses if c[1]}
    for mode in legal_modes:
        if mode not in witnessed_modes:
            findings.append(Finding(
                "GRAFT-X001", _ENTRIES_PATH, f"cache-mode:{mode}", 0,
                f"legal cache_mode={mode!r} has no cached sweep entry — "
                "a whole reuse-step program family with zero J-layer "
                "coverage"))

    # D3 — CPU-coverable quant modes need cached + uncached witnesses;
    # the exclusion list is pinned against the declared axis
    from ddim_cold_tpu.serve.batching import _QUANT_MODES

    unclassified = set(_QUANT_MODES) - set(COVERED_QUANT) \
        - set(EXCLUDED_QUANT)
    for quant in sorted(unclassified, key=repr):
        findings.append(Finding(
            "GRAFT-X001", _BATCHING_PATH, f"unclassified-quant:{quant}", 0,
            f"quant mode {quant!r} is neither sweep-covered nor a "
            "documented kernel-layer exclusion — classify it in "
            "analysis/config_checks.py (COVERED_QUANT / EXCLUDED_QUANT)"))
    for quant in COVERED_QUANT:
        for cached in (False, True):
            hit = any(c[5] == quant and c[1] == cached for c in witnesses)
            if not hit:
                findings.append(Finding(
                    "GRAFT-X001", _ENTRIES_PATH,
                    f"quant:{quant}:{'cached' if cached else 'uncached'}",
                    0,
                    f"quant={quant!r} has no "
                    f"{'cached' if cached else 'uncached'} sweep witness"))

    # D4 — sp geometries the device gate admits must each be witnessed
    # (ulysses, ring, and — above the base pair — cached-sp composition)
    for sp_mode, sp_degree in _sp_points():
        if sp_mode == "none":
            continue
        if not any(c[7] == sp_mode and c[8] == sp_degree
                   for c in witnesses):
            findings.append(Finding(
                "GRAFT-X001", _ENTRIES_PATH,
                f"sp:{sp_mode}{sp_degree}", 0,
                f"sp_mode={sp_mode!r} sp_degree={sp_degree} is legal at "
                "this world's device count but unswept"))
    if any(p != ("none", 1) for p in _sp_points()):
        if not any(c[1] and c[7] != "none" for c in witnesses):
            findings.append(Finding(
                "GRAFT-X001", _ENTRIES_PATH, "sp:cached", 0,
                "static caching composes with sp but no cached sp entry "
                "exists in the sweep"))
    return findings


# ---------------------------------------------------------------------------
# X002 — validation consistency
# ---------------------------------------------------------------------------

def _default_spec_fn(interval, mode, threshold, tokens):
    """The program-build gate, probed at the sweep model's geometry
    (depth=4 blocks, 17 tokens, 4 reuse steps). Returns True when
    cache_spec accepts the combination."""
    from ddim_cold_tpu.ops import step_cache

    kwargs = dict(depth=4, n_steps=4, cache_interval=interval,
                  cache_mode=mode, threshold=threshold,
                  token_k=tokens or None,
                  n_tokens=17 if mode == "token" else None)
    try:
        step_cache.cache_spec(**kwargs)
        return True
    except ValueError:
        return False


def check_validation_consistency(spec_fn=None) -> list:
    """X002 (a)+(b): one legality boundary, not two."""
    if spec_fn is None:
        spec_fn = _default_spec_fn
    findings = []

    # (a) cache subspace: construction gate vs program-build gate, combo
    # by combo over the representatives grid. cache_tokens' model-
    # dependent UPPER bound (≤ n_tokens) is the one documented exemption:
    # the host-only config never sees the model, so it defers that edge
    # to build — the grid stays under the probe geometry's bound.
    from ddim_cold_tpu.serve.batching import _CACHE_MODES

    for interval, mode, threshold, tokens in itertools.product(
            (2,), _CACHE_MODES, (None, 0.05), (0, 3)):
        cfg_ok = try_config(cache_interval=interval, cache_mode=mode,
                            cache_threshold=threshold,
                            cache_tokens=tokens) is not None
        spec_ok = spec_fn(interval, mode, threshold, tokens)
        if cfg_ok != spec_ok:
            combo = (f"ci{interval}/{mode}/th={threshold}/tok={tokens}")
            gate = "construction accepts what build rejects" if cfg_ok \
                else "build accepts what construction rejects"
            findings.append(Finding(
                "GRAFT-X002", _BATCHING_PATH, f"cache:{combo}", 0,
                f"SamplerConfig and ops/step_cache.cache_spec disagree on "
                f"{combo}: {gate} — a config admitted at submit would "
                "fail (or silently differ) at program build"))

    # (b) distill ↔ serve: every halving-chain step count the trainer can
    # emit a student at must construct as a servable student config
    from ddim_cold_tpu.train.distill import DistillConfig

    producible = []
    for start in (1, 2, 4, 8):
        try:
            DistillConfig(start_steps=start, target_steps=1)
        except ValueError:
            continue
        s = start
        while s >= 1:
            producible.append(s)
            if s == 1:
                break
            s //= 2
    for s in sorted(set(producible)):
        if try_config(steps=s, student=True) is None:
            findings.append(Finding(
                "GRAFT-X002", _BATCHING_PATH, f"student-steps:{s}", 0,
                f"distillation can produce a student at steps={s} but "
                "SamplerConfig(steps={s}, student=True) is rejected — "
                "the trained artifact would be unservable"))
    if try_config(steps=0, student=True) is not None:
        findings.append(Finding(
            "GRAFT-X002", _BATCHING_PATH, "student-steps:0", 0,
            "SamplerConfig(steps=0, student=True) constructs — the "
            "stride-family student hole (silently mis-serving a teacher "
            "schedule on student params) has reopened"))
    return findings


def lint_config_source(source: str, rel: str) -> list:
    """X002 (c): flag ``object.__setattr__(cfg, "<SamplerConfig field>",
    ...)`` — a post-construction mutation that skips the validation gate
    the frozen dataclass exists to enforce."""
    field_names = {f.name for f in dataclasses.fields(_sampler_config())}
    findings = []
    for node in ast.walk(ast.parse(source)):
        if not isinstance(node, ast.Call):
            continue
        fn = node.func
        if not (isinstance(fn, ast.Attribute) and fn.attr == "__setattr__"
                and isinstance(fn.value, ast.Name)
                and fn.value.id == "object"):
            continue
        if len(node.args) < 2:
            continue
        target, key = node.args[0], node.args[1]
        name = ""
        while isinstance(target, ast.Attribute):
            target = target.value
        if isinstance(target, ast.Name):
            name = target.id.lower()
        if not ("config" in name or "cfg" in name):
            continue
        if isinstance(key, ast.Constant) and key.value in field_names:
            findings.append(Finding(
                "GRAFT-X002", rel, f"bypass:{key.value}", node.lineno,
                f"object.__setattr__ writes SamplerConfig.{key.value} "
                "after construction — the frozen validation gate is "
                "bypassed; build a new config instead"))
    return findings


def _scan_bypasses(root: str) -> list:
    findings = []
    for target in _BYPASS_SCAN:
        path = os.path.join(root, target)
        if os.path.isfile(path):
            files = [(path, target)]
        elif os.path.isdir(path):
            files = []
            for dirpath, _, names in os.walk(path):
                for n in sorted(names):
                    if n.endswith(".py"):
                        full = os.path.join(dirpath, n)
                        files.append(
                            (full, os.path.relpath(full, root)
                             .replace(os.sep, "/")))
        else:
            continue
        for full, rel in files:
            with open(full) as f:
                findings += lint_config_source(f.read(), rel)
    return findings


# ---------------------------------------------------------------------------
# X003 — warmup-set soundness
# ---------------------------------------------------------------------------

def _literal(node):
    """Evaluate a (possibly negated) literal constant; None on anything
    dynamic. Returns (ok, value)."""
    if isinstance(node, ast.Constant):
        return True, node.value
    if isinstance(node, ast.UnaryOp) and isinstance(node.op, ast.USub) \
            and isinstance(node.operand, ast.Constant):
        return True, -node.operand.value
    return False, None


def _bench_config_sites(source: str) -> list:
    """(lineno, kwargs) for each evaluable ``SamplerConfig(...)`` call:
    literal kwargs kept, known sweep variables substituted from
    representatives, sites with splats/positional args skipped."""
    sites = []
    for node in ast.walk(ast.parse(source)):
        if not isinstance(node, ast.Call):
            continue
        fn = node.func
        name = fn.attr if isinstance(fn, ast.Attribute) else \
            fn.id if isinstance(fn, ast.Name) else ""
        if name != "SamplerConfig":
            continue
        if node.args or any(kw.arg is None for kw in node.keywords):
            continue  # positional/splat call — not statically evaluable
        kwargs = {}
        ok = True
        for kw in node.keywords:
            lit, value = _literal(kw.value)
            if lit:
                kwargs[kw.arg] = value
            elif kw.arg in _BENCH_REPRESENTATIVES:
                kwargs[kw.arg] = _BENCH_REPRESENTATIVES[kw.arg]
            else:
                ok = False
                break
        if ok:
            sites.append((node.lineno, kwargs))
    return sites


def check_warmup_soundness(root=None, sweep=None) -> list:
    """X003: everything serving warms or bench constructs is in-lattice
    (and, for the edit set, sweep-witnessed on the D1 plane)."""
    if root is None:
        from ddim_cold_tpu.analysis.cli import repo_root

        root = repo_root()
    if sweep is None:
        from ddim_cold_tpu.analysis import entries

        sweep = entries.serve_sweep()
    findings = []
    witnessed_projs = {projection(config_class(cfg))
                       for _, cfg, _ in sweep}

    # (a) the default edit warm set, at both preview settings it serves
    from ddim_cold_tpu.workloads.tasks import default_edit_configs

    for preview in (0, 2):
        try:
            configs = default_edit_configs(preview_every=preview)
        except (ValueError, _sp_error()) as exc:  # noqa: BLE001 — the
            # gate's two rejection types; the catch IS the finding
            findings.append(Finding(
                "GRAFT-X003", _TASKS_PATH, f"edit-set:pv{preview}", 0,
                f"default_edit_configs(preview_every={preview}) raised "
                f"{type(exc).__name__}: {exc} — the standard warm set "
                "is outside the legal lattice"))
            continue
        for cfg in configs:
            proj = projection(config_class(cfg))
            if proj not in witnessed_projs:
                findings.append(Finding(
                    "GRAFT-X003", _TASKS_PATH,
                    f"edit-unswept:{cfg.task}:pv{preview}", 0,
                    f"default_edit_configs warms task={cfg.task!r} at "
                    f"preview_every={preview} but its program class "
                    f"{proj} has no sweep witness"))

    # (b) bench.py literal construction sites all build in-lattice
    # configs (excluded-quant/fused/sp sites still CONSTRUCT — only
    # their trace coverage lives elsewhere, so no coverage demand here)
    bench = os.path.join(root, "bench.py")
    if os.path.isfile(bench):
        with open(bench) as f:
            sites = _bench_config_sites(f.read())
        for lineno, kwargs in sites:
            if try_config(**kwargs) is None:
                findings.append(Finding(
                    "GRAFT-X003", "bench.py", f"bench.py:{lineno}",
                    lineno,
                    f"bench.py SamplerConfig site at line {lineno} "
                    f"(kwargs {kwargs}) is rejected by the validation "
                    "gate — the benchmark constructs an illegal config"))
    return findings


def run_config_checks(root=None) -> list:
    """The full X-layer."""
    if root is None:
        from ddim_cold_tpu.analysis.cli import repo_root

        root = repo_root()
    findings = []
    findings += check_sweep_completeness()
    findings += check_validation_consistency()
    findings += _scan_bypasses(root)
    findings += check_warmup_soundness(root)
    return findings
