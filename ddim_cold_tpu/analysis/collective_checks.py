"""GRAFT-C001/C002 — collective-order deadlock proofs for mesh programs.

A multi-axis (sequence-parallel, and eventually pipeline-parallel) sampler
program is SPMD: one jaxpr, executed by every shard of the mesh. Shards
deadlock when they disagree about which collective comes next on an axis —
one shard enters an ``all_to_all`` while its peer entered a ``ppermute``,
and both wait forever. Because the program is single-source, the ONLY way
shards can disagree is data-dependent control flow: a collective under a
``cond``/``switch`` whose predicate can differ per shard, or under a
``while`` whose trip count can (J007 already bans the latter from served
programs; this pass re-proves it for the collective case).

**C001** therefore proves per program: along every control-flow path
*inside the manual (shard_map) region*, the ordered sequence of collective
primitives per mesh axis is identical — every ``cond``/``switch`` branch
set has ONE common collective sequence, and no ``while`` body
communicates. Control flow OUTSIDE the manual region is exempt by
construction: a ``lax.cond`` predicate is a scalar, scalars are replicated
under the partitioner, and every device computes it from the same
replicated values — so all shards take the same branch *together* even
when the branches' collective counts differ (the adaptive drift gate's
refresh-vs-reuse ``cond`` wraps the sp attention exactly this way).
Per-shard values, the only source of divergence, exist only inside
shard_map. Path-invariance there + single-program SPMD ⇒ every shard
issues the same collectives in the same order ⇒ the program cannot
self-deadlock on its mesh. This is the static precondition the ROADMAP's
pipeline-parallel serving item needs before an sp×pipe program may land
(see PERF.md).

**C002** proves every collective names an axis its enclosing mesh actually
defines (and sits inside a mesh at all): an ``all_to_all`` over a
misspelled or out-of-mesh axis is at best unlowerable and at worst a
silently wrong program when the axis exists on some OTHER mesh.

The pass walks the J006 serve-sweep traces the signature check already
built — it re-traces nothing (``graftcheck``'s jaxpr layer hands its
world-A traces over), keeping the whole run inside the existing CPU
budget.
"""

from __future__ import annotations

from ddim_cold_tpu.analysis.findings import Finding

#: the engine owns the serve sweep — C findings anchor where J006's do
ENGINE_PATH = "ddim_cold_tpu/serve/engine.py"

#: communicating collectives: a rendezvous across shards of the named axis.
#: (``axis_index`` is deliberately absent — it reads the coordinate without
#: communicating, so it cannot deadlock.)
COLLECTIVES = frozenset({
    "psum", "pmax", "pmin", "ppermute", "pbroadcast", "all_to_all",
    "all_gather", "all_gather_invariant", "psum_scatter", "reduce_scatter",
})

_SUB_JAXPR_KEYS = ("jaxpr", "call_jaxpr", "fun_jaxpr",
                   "cond_jaxpr", "body_jaxpr")


def _axes_of(eqn) -> tuple:
    """The mesh axis names a collective eqn communicates over, from its
    params (``axis_name`` for the permute/gather family, ``axes`` for the
    psum family; ints are positional axes, not mesh axes — dropped)."""
    raw = eqn.params.get("axis_name", eqn.params.get("axes", ()))
    if isinstance(raw, (tuple, list, frozenset, set)):
        axes = tuple(a for a in raw if isinstance(a, str))
    else:
        axes = (raw,) if isinstance(raw, str) else ()
    return axes


def _inner(obj):
    """ClosedJaxpr/Jaxpr → the Jaxpr with eqns."""
    return obj.jaxpr if hasattr(obj, "jaxpr") else obj


class _Walk:
    """One program's walk state: per-axis event sequences + findings."""

    def __init__(self, subject: str, path: str):
        self.subject = subject
        self.path = path
        self.findings: list[Finding] = []

    def emit(self, rule, tag, msg) -> None:
        self.findings.append(Finding(
            rule, self.path, f"{self.subject}:{tag}", 0, msg))

    def events(self, jaxpr, mesh_axes) -> tuple:
        """The ordered ``(primitive, axis)`` collective sequence of one
        (sub)jaxpr, emitting C001/C002 along the way. ``mesh_axes`` is the
        manual axis-name set of the enclosing shard_map, or None outside
        any mesh."""
        out: list = []
        for eqn in _inner(jaxpr).eqns:
            prim = eqn.primitive.name
            if prim == "shard_map":
                mesh = eqn.params.get("mesh")
                names = tuple(getattr(mesh, "axis_names", ()) or ())
                manual = frozenset(names) - frozenset(
                    eqn.params.get("auto", ()) or ())
                out += self.events(eqn.params["jaxpr"], manual)
            elif prim in ("cond", "switch"):
                out += self._branch_events(eqn, mesh_axes)
            elif prim == "while":
                for key in ("cond_jaxpr", "body_jaxpr"):
                    body = self.events(eqn.params[key], mesh_axes)
                    # inside the manual region the trip count can be
                    # per-shard — shards would disagree on how many
                    # rendezvous to issue; outside, it is replicated and
                    # uniform (same argument as branches, see _branch_events)
                    if body and mesh_axes is not None:
                        self.emit(
                            "GRAFT-C001", f"while:{body[0][0]}",
                            f"collective {body[0][0]!r} inside a `while` "
                            f"{key.split('_')[0]} within the manual mesh "
                            "region — a per-shard trip count lets shards "
                            "disagree on how many rendezvous to issue "
                            "(deadlock)")
                    out += body
            elif prim in COLLECTIVES:
                axes = _axes_of(eqn)
                if not axes:
                    continue  # axis-free psum (positional reduce) — local
                for ax in axes:
                    if mesh_axes is None:
                        self.emit(
                            "GRAFT-C002", f"{prim}:{ax}:no-mesh",
                            f"collective {prim!r} over axis {ax!r} outside "
                            "any shard_map mesh")
                    elif ax not in mesh_axes:
                        self.emit(
                            "GRAFT-C002", f"{prim}:{ax}",
                            f"collective {prim!r} names axis {ax!r}, absent "
                            f"from the program mesh axes "
                            f"{sorted(mesh_axes)}")
                    out.append((prim, ax))
            else:
                for key in _SUB_JAXPR_KEYS:
                    sub = eqn.params.get(key)
                    if sub is None:
                        continue
                    subs = sub if isinstance(sub, (tuple, list)) else (sub,)
                    for s in subs:
                        # a scan body's sequence repeats a STATIC number of
                        # times — same order on every shard, so one pass of
                        # its events stands in for all iterations
                        out += self.events(s, mesh_axes)
        return tuple(out)

    def _branch_events(self, eqn, mesh_axes) -> tuple:
        """cond/switch INSIDE the manual mesh region: every branch must
        issue the identical collective sequence, else shards whose
        (per-shard) predicates diverge deadlock — C001. OUTSIDE the manual
        region the predicate is a replicated scalar: every device computes
        it from the same replicated values and takes the same branch
        together, so differing branch sequences are safe (the drift gate's
        refresh-vs-reuse cond over the sp attention is the in-tree case).
        The branch set's contribution is the first branch's sequence —
        exact under the in-region identity proof, and representative under
        the out-of-region uniform-choice argument."""
        seqs = [self.events(b, mesh_axes)
                for b in eqn.params.get("branches", ())]
        if not seqs:
            return ()
        if mesh_axes is not None and any(s != seqs[0] for s in seqs[1:]):
            shapes = [" ".join(f"{p}@{a}" for p, a in s) or "<none>"
                      for s in seqs]
            self.emit(
                "GRAFT-C001", "cond-divergent",
                "collective sequence differs across cond/switch branches "
                f"inside the manual mesh region ({' | '.join(shapes)}) — "
                "shards whose per-shard predicates diverge rendezvous out "
                "of order (deadlock)")
        return seqs[0]


def collective_signature(closed, subject: str = "",
                         path: str = ENGINE_PATH) -> dict:
    """``{axis: (primitive, ...)}`` — the per-axis collective order of one
    traced program (tests assert the sp sweep entries' signatures are
    non-empty, proving the pass actually sees the collectives)."""
    walk = _Walk(subject, path)
    sig: dict = {}
    for prim, ax in walk.events(closed, None):
        sig.setdefault(ax, []).append(prim)
    return {ax: tuple(seq) for ax, seq in sig.items()}


def check_jaxpr(closed, subject: str,
                path: str = ENGINE_PATH) -> list[Finding]:
    """C001 + C002 over one traced program."""
    walk = _Walk(subject, path)
    walk.events(closed, None)
    return walk.findings


def check_serve_collectives(traces: dict) -> list[Finding]:
    """C001/C002 over the J006 sweep's cached traces: ``traces`` maps the
    J006 subject (``"<label>:b<bucket>"``) to ``(config, closed_jaxpr)`` as
    built by ``entries.serve_signatures(..., traces=...)`` — the proof
    reuses those traces instead of re-tracing the sweep."""
    findings: list[Finding] = []
    for subject in sorted(traces):
        _config, closed = traces[subject]
        findings += check_jaxpr(closed, subject)
    return findings


def run_collective_checks() -> list[Finding]:
    """Standalone entry (``--only collective`` without the jaxpr layer):
    builds one world and traces the sweep itself."""
    from ddim_cold_tpu.analysis import entries

    traces: dict = {}
    entries.serve_signatures(entries.Context(), traces=traces)
    return check_serve_collectives(traces)
