"""Sharding-coverage check (GRAFT-S001/S002): every param leaf must carry a
usable PartitionSpec from ``parallel/sharding.py``.

``param_partition_specs`` derives specs by module-path pattern matching, so
a renamed module or a new leaf kind (exactly what ``quantize_params`` did
when it introduced ``w_int8``/``scale``) silently falls through to the
replicated default — correct-but-slow for small leaves, a scale-out
regression when the fallen leaf is a trunk GEMM weight. This check walks
the REAL param trees (float, quantized, stacked-scan, MoE — all abstract
via ``eval_shape``) and flags:

* S002 — structurally unusable specs: tree-structure mismatch between
  params and specs, a spec longer than the leaf's rank, or a spec naming a
  mesh axis outside the declared set.
* S001 — a trunk GEMM leaf (``attn/{qkv,proj}``, ``mlp/{fc1,fc2}`` —
  ``kernel`` or its ``w_int8`` encoding) whose spec does not mention the
  'model' axis even though the axis set offers it: the Megatron split
  silently degraded to replication.
* S003 — sequence-parallel ACTIVATION specs (the ``P(batch_axis, seq_axis,
  head_axis, None)`` family the ulysses/ring attention fronts shard_map
  over the model's ``seq_mesh``): an sp axis missing from the mesh, an
  axis double-used across spec dims, or a resolved 'ulysses' mode whose
  head count does not divide the seq axis (the sp_clone fallback bypassed).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ddim_cold_tpu.analysis.findings import Finding

PATH = "ddim_cold_tpu/parallel/sharding.py"

#: the tiny geometry (analysis/entries.py TINY) with the layout variants
#: whose param trees must all be covered
TREE_VARIANTS = ("float", "quant", "scan_blocks", "moe")


def _leaf_paths(tree, is_leaf=None):
    flat, _ = jax.tree_util.tree_flatten_with_path(tree, is_leaf=is_leaf)
    return {
        "/".join(getattr(k, "key", str(k)) for k in path): leaf
        for path, leaf in flat
    }


def _is_trunk_gemm(names: list[str]) -> bool:
    from ddim_cold_tpu.ops import quant

    return (names[-1] in ("kernel", "w_int8") and len(names) >= 2
            and quant._is_trunk_dense(tuple(names[:-1])))


def check_param_tree(params, specs, tag: str,
                     axes=("model", "expert")) -> list[Finding]:
    """Validate ``specs`` (a PartitionSpec tree) against ``params``."""
    findings = []
    p_leaves = _leaf_paths(params)
    # P() must stay a leaf even on jax builds where PartitionSpec iterates
    # like a tuple — an empty spec flattening to nothing would vanish
    s_leaves = _leaf_paths(specs, is_leaf=lambda x: isinstance(
        x, jax.sharding.PartitionSpec))
    for missing in sorted(set(p_leaves) - set(s_leaves)):
        findings.append(Finding(
            "GRAFT-S002", PATH, f"{tag}:{missing}", 0,
            f"param leaf {missing} ({tag} tree) has no PartitionSpec — "
            "spec tree structure diverged from the param tree"))
    for extra in sorted(set(s_leaves) - set(p_leaves)):
        findings.append(Finding(
            "GRAFT-S002", PATH, f"{tag}:{extra}", 0,
            f"spec leaf {extra} ({tag} tree) matches no param leaf"))
    for path in sorted(set(p_leaves) & set(s_leaves)):
        leaf, spec = p_leaves[path], s_leaves[path]
        names = path.split("/")
        if not isinstance(spec, jax.sharding.PartitionSpec):
            findings.append(Finding(
                "GRAFT-S002", PATH, f"{tag}:{path}", 0,
                f"spec for {path} ({tag} tree) is {type(spec).__name__}, "
                "not a PartitionSpec"))
            continue
        ndim = getattr(leaf, "ndim", len(getattr(leaf, "shape", ())))
        if len(spec) > ndim:
            findings.append(Finding(
                "GRAFT-S002", PATH, f"{tag}:{path}", 0,
                f"spec {spec} for {path} ({tag} tree) has {len(spec)} "
                f"entries but the leaf is rank {ndim} — sharding would "
                "raise at placement"))
            continue
        flat_axes = [a for entry in spec if entry is not None
                     for a in (entry if isinstance(entry, tuple)
                               else (entry,))]
        unknown = [a for a in flat_axes if a not in axes]
        if unknown:
            findings.append(Finding(
                "GRAFT-S002", PATH, f"{tag}:{path}", 0,
                f"spec {spec} for {path} ({tag} tree) names mesh axes "
                f"{unknown} outside the declared set {tuple(axes)}"))
            continue
        if ("model" in axes and _is_trunk_gemm(names)
                and "model" not in flat_axes):
            findings.append(Finding(
                "GRAFT-S001", PATH, f"{tag}:{path}", 0,
                f"trunk GEMM leaf {path} ({tag} tree) fell through to "
                f"replicated spec {spec} on a model-axis mesh — the "
                "Megatron column/row split silently degraded"))
    return findings


SP_PATH = "ddim_cold_tpu/parallel/ulysses.py"


def check_sp_activation_specs() -> list[Finding]:
    """GRAFT-S003: every sequence-parallel model the serve sweep traces has
    a PLACEABLE activation sharding.

    The sp attention fronts (``ulysses_self_attention`` /
    ``ring_self_attention``) shard the (B, N, H, D) activations with
    ``P(batch_axis, seq_axis, head_axis, None)`` inside a shard_map over
    the model's ``seq_mesh`` — patch tokens sequence-sharded, everything
    else (CLS/time conditioning included) replicated outside the manual
    region. Walks the sp clones of analysis/entries.py's serve sweep (the
    same device-count gate, so the CLI world at 1 device simply has no sp
    geometry to check) and flags: an sp axis name that is not an axis of
    the mesh (shard_map would raise at warmup), an axis reused across two
    spec dims (double-sharding), and a RESOLVED 'ulysses' mode whose
    tp-local head count does not divide the seq axis — the structural
    requirement the models.sp_clone fallback exists to uphold, so a finding
    here means the fallback was bypassed."""
    from ddim_cold_tpu.analysis.entries import Context, serve_sweep

    ctx = Context()
    findings: list[Finding] = []
    seen = set()
    for label, config, _ in serve_sweep():
        geom = (config.sp_mode, config.sp_degree)
        if config.sp_degree == 1 or geom in seen:
            continue
        seen.add(geom)
        model = ctx.sp_model(config)
        mesh_axes = dict(model.seq_mesh.shape)
        used: list[str] = []
        for field_name, ax in (("batch_axis", model.batch_axis),
                               ("seq_axis", model.seq_axis),
                               ("head_axis", model.head_axis)):
            if ax is None:
                continue
            if ax not in mesh_axes:
                findings.append(Finding(
                    "GRAFT-S003", SP_PATH, f"{label}:{field_name}", 0,
                    f"sp model for {label} names {field_name}={ax!r} but "
                    f"the seq_mesh axes are {tuple(mesh_axes)} — shard_map "
                    "would raise at warmup"))
                continue
            if ax in used:
                findings.append(Finding(
                    "GRAFT-S003", SP_PATH, f"{label}:{field_name}", 0,
                    f"sp model for {label} reuses mesh axis {ax!r} for "
                    f"{field_name} and another spec dim — the activation "
                    "would double-shard over the same devices"))
                continue
            used.append(ax)
        if model.sp_mode == "ulysses":
            tp = mesh_axes.get(model.head_axis, 1) if model.head_axis else 1
            s = mesh_axes.get(model.seq_axis, 1)
            if (model.num_heads // tp) % s:
                findings.append(Finding(
                    "GRAFT-S003", SP_PATH, f"{label}:heads", 0,
                    f"sp model for {label} resolved to 'ulysses' with "
                    f"{model.num_heads}//{tp} local heads over a seq axis "
                    f"of {s} — not divisible; the sp_clone ring fallback "
                    "was bypassed and warmup would raise "
                    "SeqParallelConfigError"))
    return findings


def _tiny_params(**overrides):
    from ddim_cold_tpu.analysis.entries import TINY
    from ddim_cold_tpu.models import DiffusionViT

    model = DiffusionViT(**{**TINY, **overrides})
    H, W = model.img_size
    x = jax.ShapeDtypeStruct((2, H, W, model.in_chans), jnp.float32)
    t = jax.ShapeDtypeStruct((2,), jnp.int32)
    return jax.eval_shape(model.init, jax.random.PRNGKey(0), x, t)["params"]


def run_sharding_checks() -> list[Finding]:
    """S001/S002 over every layout variant's abstract param tree."""
    from ddim_cold_tpu.ops import quant
    from ddim_cold_tpu.parallel.sharding import param_partition_specs

    findings = []
    float_params = _tiny_params()
    trees = {
        "float": float_params,
        "quant": jax.eval_shape(quant.quantize_params, float_params),
        "scan_blocks": _tiny_params(scan_blocks=True),
        "moe": _tiny_params(num_experts=2),
    }
    assert set(trees) == set(TREE_VARIANTS)
    for tag, params in trees.items():
        specs = param_partition_specs(params)
        findings += check_param_tree(params, specs, tag)
    findings += check_sp_activation_specs()
    return findings
