"""graftcheck CLI — ``python -m ddim_cold_tpu.analysis`` / ``graftcheck``.

Runs the nine layers (AST lint, thread-safety lockset analysis, jaxpr
entry checks + serve-signature sweep, Pallas kernel-geometry verification,
peak-HBM budget analysis, collective-order proofs over the sweep's traces,
sharding coverage, RPC protocol proofs, SamplerConfig lattice coverage),
subtracts the reviewed ``--baseline`` allowlist,
prints the rest and exits nonzero if any remain.
``--fix-baseline`` regenerates the allowlist deterministically instead
(sorted, deduped) so its diffs review cleanly; combined with ``--only`` it
refreshes ONLY the selected layers' rule families, preserving the other
layers' reviewed lines verbatim.

``--only`` takes layer names or rule-family letters, comma-separable:
``--only T,C`` ≡ ``--only threads --only collective`` — the fast host-side
path CI runs without paying for a trace sweep; ``--only P,M`` is the
kernel-geometry + memory-budget pre-flight for block-shape work.

The trace-consuming layers (jaxpr, kernels, memory, collective) pin jax to
CPU before any trace (the checks are backend-independent — they never
execute a program) unless ``--platform`` says otherwise, and SHARE traces:
the jaxpr layer's world-A sweep feeds collective/kernels/memory, its
build/train entry traces feed kernels — each program is traced once no
matter how many layers walk it. The 200px kernel entries
(``entries.kernel_entries``) are traced once and shared by kernels+memory.

Layers run CONCURRENTLY where they can: the pure host-side layers (ast,
threads, protocol, config) fan out onto worker threads while the
jax-touching chain — jaxpr/collective/kernels/memory serialized through
the one shared trace stash, plus sharding — runs on the calling thread.
The config layer qualifies as host-side because its lattice enumeration
never traces: its X001/X003 sweep witnesses come from
``entries.serve_sweep()``, which only CONSTRUCTS configs. Every layer
returns its own findings list, so the fan-out needs no locking; the final
``sorted()`` merge keeps output order identical to a serial run.
"""

from __future__ import annotations

import argparse
import os
import sys
from concurrent.futures import ThreadPoolExecutor

from ddim_cold_tpu.analysis import findings as F

LAYERS = ("ast", "jaxpr", "kernels", "memory", "sharding", "threads",
          "collective", "protocol", "config")

#: rule-family letters accepted by --only as layer aliases (--only T,C)
_ONLY_ALIASES = {"a": "ast", "j": "jaxpr", "s": "sharding",
                 "t": "threads", "c": "collective",
                 "p": "kernels", "m": "memory",
                 "r": "protocol", "x": "config"}


def parse_only(values) -> tuple:
    """Normalize repeatable/comma-separated ``--only`` tokens (layer names
    or family letters, any case) into an ordered layer tuple."""
    out = []
    for value in values:
        for tok in value.split(","):
            tok = tok.strip().lower()
            if not tok:
                continue
            layer = _ONLY_ALIASES.get(tok, tok)
            if layer not in LAYERS:
                raise argparse.ArgumentTypeError(
                    f"unknown layer {tok!r} (choose from "
                    f"{', '.join(LAYERS)} or letters "
                    f"{', '.join(sorted(_ONLY_ALIASES))})")
            if layer not in out:
                out.append(layer)
    return tuple(out)


def repo_root() -> str:
    """The directory holding the ``ddim_cold_tpu`` package."""
    pkg = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    return os.path.dirname(pkg)


def _host_layer(layer: str, root: str):
    """One pure host-side layer as a thunk result — no jax tracing, no
    shared state, safe on a worker thread."""
    if layer == "ast":
        from ddim_cold_tpu.analysis import ast_checks

        return ast_checks.lint_tree(root)
    if layer == "threads":
        from ddim_cold_tpu.analysis import thread_checks

        return thread_checks.lint_tree(root)
    if layer == "protocol":
        from ddim_cold_tpu.analysis import protocol_checks

        return protocol_checks.run_protocol_checks(root)
    from ddim_cold_tpu.analysis import config_checks

    return config_checks.run_config_checks(root)


#: layers _host_layer serves — fanned out on worker threads by collect()
_HOST_LAYERS = ("ast", "threads", "protocol", "config")


def collect(root: str, only=LAYERS, max_const_bytes: int = 1 << 20
            ) -> list[F.Finding]:
    """All findings from the requested layers, sorted for stable output.

    The host-side layers run on a thread pool overlapping the jax chain
    below; futures are collected at the end so a worker exception
    propagates exactly like a serial failure would.
    """
    out: list[F.Finding] = []
    host = [layer for layer in _HOST_LAYERS if layer in only]
    pool = ThreadPoolExecutor(max_workers=len(host)) if host else None
    futures = [pool.submit(_host_layer, layer, root) for layer in host]
    # the collective/kernels/memory layers consume the jaxpr layer's sweep
    # traces when they run together (one sweep trace no matter how many
    # layers walk it); the kernels layer additionally rides the jaxpr
    # layer's build/train entry traces. Without the jaxpr layer, one world
    # is traced here and shared the same way.
    need_sweep = any(layer in only
                     for layer in ("collective", "kernels", "memory"))
    traces = {} if need_sweep else None
    entry_traces = {} if "kernels" in only else None
    if "jaxpr" in only:
        from ddim_cold_tpu.analysis import entries

        out += entries.run_entry_checks(max_const_bytes=max_const_bytes,
                                        traces=entry_traces)
        out += entries.run_serve_signature_check(traces=traces)
    elif traces is not None:
        from ddim_cold_tpu.analysis import entries

        ctx = entries.Context()
        entries.serve_signatures(ctx, traces=traces)
        if entry_traces is not None:
            entry_traces.update((e.name, (e, e.trace()))
                                for e in entries.build_entries(ctx))
    if "collective" in only:
        from ddim_cold_tpu.analysis import collective_checks

        out += collective_checks.check_serve_collectives(traces)
    # the 200px kernel entries are traced once, shared by kernels+memory
    ktraces = None
    if "kernels" in only or "memory" in only:
        from ddim_cold_tpu.analysis import entries

        ktraces = entries.kernel_traces()
    if "kernels" in only:
        from ddim_cold_tpu.analysis import kernel_checks

        out += kernel_checks.run_kernel_checks(serve_traces=traces,
                                               entry_traces=entry_traces,
                                               kernel_traces=ktraces)
    if "memory" in only:
        from ddim_cold_tpu.analysis import memory_checks

        out += memory_checks.run_memory_checks(serve_traces=traces,
                                               kernel_traces=ktraces)
    if "sharding" in only:
        from ddim_cold_tpu.analysis import sharding_checks

        out += sharding_checks.run_sharding_checks()
    if pool is not None:
        try:
            for future in futures:
                out += future.result()
        finally:
            pool.shutdown()
    return sorted(out)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="graftcheck",
        description="static analysis of the ddim_cold_tpu TPU invariants")
    ap.add_argument("--root", default=repo_root(),
                    help="repo root holding the ddim_cold_tpu package")
    ap.add_argument("--baseline", default=None, metavar="FILE",
                    help="reviewed allowlist; listed findings don't fail "
                         "the run (missing file = empty baseline)")
    ap.add_argument("--fix-baseline", default=None, metavar="FILE",
                    help="write the current findings as the new baseline "
                         "and exit 0; with --only, refresh ONLY the "
                         "selected layers' rule families and keep the "
                         "file's other lines verbatim")
    ap.add_argument("--only", action="append", default=None,
                    metavar="LAYER[,LAYER...]",
                    help="run a subset of layers (repeatable or "
                         "comma-separated; layer names or rule-family "
                         "letters: --only T,C)")
    ap.add_argument("--max-const-bytes", type=int, default=1 << 20,
                    help="GRAFT-J004 threshold (default 1 MiB)")
    ap.add_argument("--platform", default="cpu",
                    help="jax platform for abstract tracing (default cpu)")
    ap.add_argument("--list-rules", action="store_true",
                    help="print the rule table and exit")
    args = ap.parse_args(argv)

    if args.list_rules:
        for rule, desc in sorted(F.RULES.items()):
            print(f"{rule}  {desc}")
        return 0

    # the environment may pre-select an accelerator platform; tracing is
    # abstract, so pin the cheap backend before the first jax import runs
    # device discovery (post-import config update — same as tests/conftest)
    import jax

    jax.config.update("jax_platforms", args.platform)

    try:
        only = parse_only(args.only) if args.only else LAYERS
    except argparse.ArgumentTypeError as e:
        ap.error(str(e))
    all_findings = collect(args.root, only=only,
                           max_const_bytes=args.max_const_bytes)

    if args.fix_baseline:
        extra: set[str] = set()
        if args.only:
            # partial refresh: the layers we did NOT run stay authoritative
            # in the existing file — carry their lines over verbatim so
            # adopting one rule family never churns the others' entries
            extra = {k for k in F.load_baseline(args.fix_baseline)
                     if F.rule_layer(k.split(" ", 1)[0]) not in only}
        n = F.write_baseline(args.fix_baseline, all_findings,
                             extra_keys=extra)
        kept = f" ({len(extra)} kept from other layers)" if extra else ""
        print(f"graftcheck: wrote {n} baseline entr"
              f"{'y' if n == 1 else 'ies'} to {args.fix_baseline}{kept}")
        return 0

    baseline = F.load_baseline(args.baseline)
    fresh = [f for f in all_findings if f.key not in baseline]
    suppressed = len(all_findings) - len(fresh)
    for f in fresh:
        print(f.render())
    tail = f" ({suppressed} baselined)" if suppressed else ""
    print(f"graftcheck: {len(fresh)} finding"
          f"{'' if len(fresh) == 1 else 's'}{tail} "
          f"[layers: {', '.join(only)}]")
    return 1 if fresh else 0


if __name__ == "__main__":
    sys.exit(main())
