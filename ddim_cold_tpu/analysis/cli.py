"""graftcheck CLI — ``python -m ddim_cold_tpu.analysis`` / ``graftcheck``.

Runs the three layers (AST lint, jaxpr entry checks + serve-signature
sweep, sharding coverage), subtracts the reviewed ``--baseline`` allowlist,
prints the rest and exits nonzero if any remain. ``--fix-baseline``
regenerates the allowlist deterministically instead (sorted, deduped) so
its diffs review cleanly.

The jaxpr layer traces real model code, so the CLI pins jax to CPU before
any trace (the check is backend-independent — it never executes a program)
unless ``--platform`` says otherwise.
"""

from __future__ import annotations

import argparse
import os
import sys

from ddim_cold_tpu.analysis import findings as F

LAYERS = ("ast", "jaxpr", "sharding")


def repo_root() -> str:
    """The directory holding the ``ddim_cold_tpu`` package."""
    pkg = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    return os.path.dirname(pkg)


def collect(root: str, only=LAYERS, max_const_bytes: int = 1 << 20
            ) -> list[F.Finding]:
    """All findings from the requested layers, sorted for stable output."""
    out: list[F.Finding] = []
    if "ast" in only:
        from ddim_cold_tpu.analysis import ast_checks

        out += ast_checks.lint_tree(root)
    if "jaxpr" in only:
        from ddim_cold_tpu.analysis import entries

        out += entries.run_entry_checks(max_const_bytes=max_const_bytes)
        out += entries.run_serve_signature_check()
    if "sharding" in only:
        from ddim_cold_tpu.analysis import sharding_checks

        out += sharding_checks.run_sharding_checks()
    return sorted(out)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="graftcheck",
        description="static analysis of the ddim_cold_tpu TPU invariants")
    ap.add_argument("--root", default=repo_root(),
                    help="repo root holding the ddim_cold_tpu package")
    ap.add_argument("--baseline", default=None, metavar="FILE",
                    help="reviewed allowlist; listed findings don't fail "
                         "the run (missing file = empty baseline)")
    ap.add_argument("--fix-baseline", default=None, metavar="FILE",
                    help="write the current findings as the new baseline "
                         "and exit 0")
    ap.add_argument("--only", action="append", choices=LAYERS, default=None,
                    help="run a subset of layers (repeatable)")
    ap.add_argument("--max-const-bytes", type=int, default=1 << 20,
                    help="GRAFT-J004 threshold (default 1 MiB)")
    ap.add_argument("--platform", default="cpu",
                    help="jax platform for abstract tracing (default cpu)")
    ap.add_argument("--list-rules", action="store_true",
                    help="print the rule table and exit")
    args = ap.parse_args(argv)

    if args.list_rules:
        for rule, desc in sorted(F.RULES.items()):
            print(f"{rule}  {desc}")
        return 0

    # the environment may pre-select an accelerator platform; tracing is
    # abstract, so pin the cheap backend before the first jax import runs
    # device discovery (post-import config update — same as tests/conftest)
    import jax

    jax.config.update("jax_platforms", args.platform)

    only = tuple(args.only) if args.only else LAYERS
    all_findings = collect(args.root, only=only,
                           max_const_bytes=args.max_const_bytes)

    if args.fix_baseline:
        n = F.write_baseline(args.fix_baseline, all_findings)
        print(f"graftcheck: wrote {n} baseline entr"
              f"{'y' if n == 1 else 'ies'} to {args.fix_baseline}")
        return 0

    baseline = F.load_baseline(args.baseline)
    fresh = [f for f in all_findings if f.key not in baseline]
    suppressed = len(all_findings) - len(fresh)
    for f in fresh:
        print(f.render())
    tail = f" ({suppressed} baselined)" if suppressed else ""
    print(f"graftcheck: {len(fresh)} finding"
          f"{'' if len(fresh) == 1 else 's'}{tail} "
          f"[layers: {', '.join(only)}]")
    return 1 if fresh else 0


if __name__ == "__main__":
    sys.exit(main())
