"""R-rules: the fleet RPC protocol, statically proven (graftcheck layer R).

PR 19's review found the wire-protocol failure classes these rules now make
*provable* instead of reviewed-for: a ticket registered after its submit
frame left (a done event racing the response had no ticket to resolve), an
unbounded hello read (a wedged child blocking fleet supervision forever),
and frame limits enforced on one side only. Each is a RULE here, with a
violating fixture in tests/test_protocol_checks.py replaying the old code
shape.

Two halves, both cheap (no tracing, no sockets):

* **pure AST** over the protocol modules (``lint_source`` /
  ``lint_protocol_sources``) — frame-table/site parity, rid-lifecycle
  statement ordering, bounded-read discipline, raise-type wire coverage,
  chaos-site presence;
* **import-time introspection** (``run_protocol_checks``) — the literal
  frame tables on both sides of the wire are set-equal, every
  ``serve/errors.py`` type round-trips through the wire codec, the wire
  chaos sites are registered, and the ``health()`` field contract holds
  for every backend the fleet control plane reads.

Rules:

* **R001 frame-kind parity** — every client-sent method literal is in
  ``remote.CLIENT_METHODS`` and that table is set-equal to the server's
  ``replica_main.SERVER_METHODS`` (each pinned to its actual dispatch
  arms); every server-pushed event literal is in
  ``replica_main.SERVER_EVENTS`` and has a client dispatch arm
  (``remote.CLIENT_EVENT_ARMS``). Also the health-field half of the frame
  contract: every key in ``REQUIRED_HEALTH_KEYS`` (the set the router +
  autoscaler read) is provided by every health backend (Engine, StubEngine,
  the LocalReplica/RemoteReplica augmentations).
* **R002 exception-serialization totality** — every exception class
  ``serve/errors.py`` defines round-trips through
  ``encode_exception``/``decode_exception`` as its own type, and every
  ``raise SomeError(...)`` in the protocol modules names a registered wire
  type (anything else degrades to ``RequestFailedError`` — legal only for
  types the server cannot anticipate, never for its own raises).
* **R003 rid-lifecycle ordering** — in any function that both registers a
  ticket into a ``*tickets*`` table and sends a ``"submit"`` frame, the
  registration statement must dominate the send (the exact PR-19 HIGH
  race: a fast done event must always find its ticket).
* **R004 bounded-read discipline** — length-prefixed reads check
  ``MAX_FRAME_BYTES`` before allocation, raw ``recv`` chunks are
  ``min()``-capped, sends re-check the limit before ``sendall``, and a
  socket may only go deadline-free (``settimeout(None)``) AFTER its
  validated handshake read.
* **R005 fault-site coverage** — the client's frame-send choke point fires
  ``rpc.drop``/``rpc.latency``, the server fires
  ``replica.kill``/``replica.hang`` on its work methods, all four sites
  are registered in ``faults.SITES``, and ``WORK_METHODS`` is a subset of
  the served method table.
"""

from __future__ import annotations

import ast
import os

from ddim_cold_tpu.analysis.findings import Finding

#: the modules the R-layer walks (repo-relative)
PROTOCOL_MODULES = (
    "ddim_cold_tpu/serve/remote.py",
    "ddim_cold_tpu/serve/replica_main.py",
    "ddim_cold_tpu/serve/backend.py",
    "ddim_cold_tpu/serve/errors.py",
    "ddim_cold_tpu/utils/faults.py",
)

#: health-dict keys the fleet control plane (serve/router.py +
#: serve/autoscale.py) reads off replica snapshots. R001 proves every
#: backend provides each of them, and that each is actually still read
#: (a stale pin would rot silently).
REQUIRED_HEALTH_KEYS = (
    "state", "queue_depth", "open_tickets", "latency_p95_s",
    "last_progress_s", "stalled", "closed", "quarantined",
    "compiles_after_warmup",
)

#: the providers of those keys: (path, class, method) triples whose dict
#: literals / ``h["key"] = ...`` augmentations together must cover
#: REQUIRED_HEALTH_KEYS. Engine and StubEngine each pair with the
#: LocalReplica augmentation (the handle every backend is served behind).
_HEALTH_PROVIDERS = (
    ("ddim_cold_tpu/serve/engine.py", "Engine"),
    ("ddim_cold_tpu/serve/replica_main.py", "StubEngine"),
)
_HEALTH_AUGMENTORS = (
    ("ddim_cold_tpu/serve/fleet.py", "LocalReplica"),
    ("ddim_cold_tpu/serve/remote.py", "RemoteReplica"),
)
_HEALTH_CONSUMERS = (
    "ddim_cold_tpu/serve/router.py",
    "ddim_cold_tpu/serve/autoscale.py",
)

#: the wire-level chaos sites R005 pins (client send path + server work
#: dispatch), the way A003 pins fire() sites generally
WIRE_FAULT_SITES = ("rpc.drop", "rpc.latency", "replica.kill",
                    "replica.hang")


# ---------------------------------------------------------------------------
# small AST helpers (same idiom as ast_checks)
# ---------------------------------------------------------------------------

def _dotted(node) -> str:
    """Best-effort dotted name of an expression (``a.b.c``)."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
    return ".".join(reversed(parts))


def _str_const(node) -> str | None:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def _call_name(call: ast.Call) -> str:
    """Trailing name of the called function (``self._call`` → ``_call``)."""
    fn = call.func
    if isinstance(fn, ast.Attribute):
        return fn.attr
    if isinstance(fn, ast.Name):
        return fn.id
    return ""


def _functions(tree: ast.AST):
    """(qualname, FunctionDef) for every function, class-qualified."""
    out = []

    def walk(node, prefix):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                out.append((f"{prefix}{child.name}", child))
                walk(child, f"{prefix}{child.name}.")
            elif isinstance(child, ast.ClassDef):
                walk(child, f"{prefix}{child.name}.")
            else:
                walk(child, prefix)

    walk(tree, "")
    return out


def _module_tables(tree: ast.AST) -> dict:
    """Module-level ``NAME = ("lit", ...)`` tuple assignments."""
    tables = {}
    for node in tree.body:
        if not isinstance(node, ast.Assign) or len(node.targets) != 1:
            continue
        target = node.targets[0]
        if not isinstance(target, ast.Name):
            continue
        if isinstance(node.value, (ast.Tuple, ast.List)):
            vals = [_str_const(e) for e in node.value.elts]
            if all(v is not None for v in vals):
                tables[target.id] = (tuple(vals), node.lineno)
    return tables


def _fired_sites(tree: ast.AST) -> set:
    """String literals passed as the first arg of a ``*.fire(...)`` call."""
    out = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Call) and _call_name(node) == "fire" \
                and node.args:
            lit = _str_const(node.args[0])
            if lit:
                out.add(lit)
    return out


# ---------------------------------------------------------------------------
# R001 — frame-kind parity (AST half: table ↔ site consistency per module)
# ---------------------------------------------------------------------------

def _client_method_literals(tree: ast.AST) -> set:
    """Method literals the client puts on the wire: first arg of
    ``self._call("m", ...)`` plus ``"method": "m"`` keys in dicts handed
    to ``_send``."""
    out = set()
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        name = _call_name(node)
        if name == "_call" and node.args:
            lit = _str_const(node.args[0])
            if lit:
                out.add(lit)
        elif name == "_send":
            for arg in node.args:
                if isinstance(arg, ast.Dict):
                    for k, v in zip(arg.keys, arg.values):
                        if _str_const(k) == "method" and _str_const(v):
                            out.add(_str_const(v))
    return out


def _event_compare_arms(tree: ast.AST) -> set:
    """Event kinds the client-side code compares against: ``event ==
    "kind"`` / ``x.get("event") != "kind"`` anywhere in the module."""
    out = set()
    for node in ast.walk(tree):
        if not isinstance(node, ast.Compare) or len(node.comparators) != 1:
            continue
        sides = (node.left, node.comparators[0])
        lits = [_str_const(s) for s in sides]
        names = []
        for s in sides:
            if isinstance(s, ast.Name):
                names.append(s.id)
            elif isinstance(s, ast.Call) and _call_name(s) == "get" \
                    and s.args and _str_const(s.args[0]) == "event":
                names.append("event")
        if "event" in names:
            out.update(v for v in lits if v)
    return out


def _server_handler_methods(tree: ast.AST) -> set:
    """Method kinds a server ``handle`` function dispatches: ``method ==
    "m"`` comparisons and ``method in ("a", "b")`` memberships."""
    out = set()
    for qual, fn in _functions(tree):
        if not qual.endswith("handle"):
            continue
        for node in ast.walk(fn):
            if not isinstance(node, ast.Compare) \
                    or len(node.comparators) != 1:
                continue
            left_is_method = (isinstance(node.left, ast.Name)
                              and node.left.id == "method") or \
                (isinstance(node.left, ast.Call)
                 and _call_name(node.left) == "get"
                 and node.left.args
                 and _str_const(node.left.args[0]) == "method")
            if not left_is_method:
                continue
            comp = node.comparators[0]
            if isinstance(node.ops[0], (ast.Eq, ast.NotEq)):
                lit = _str_const(comp)
                if lit:
                    out.add(lit)
            elif isinstance(node.ops[0], (ast.In, ast.NotIn)):
                if isinstance(comp, (ast.Tuple, ast.List)):
                    out.update(v for v in
                               (_str_const(e) for e in comp.elts) if v)
    return out


def _pushed_events(tree: ast.AST) -> set:
    """Event kinds a server pushes: ``send({"event": "kind", ...})``."""
    out = set()
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call) or _call_name(node) != "send":
            continue
        for arg in node.args:
            if isinstance(arg, ast.Dict):
                for k, v in zip(arg.keys, arg.values):
                    if _str_const(k) == "event" and _str_const(v):
                        out.add(_str_const(v))
    return out


def _check_frame_tables(tree: ast.AST, rel: str) -> list:
    """R001 per-module half: every wire literal is in its declared table
    and every table entry has a site. Modules without wire sites (backend,
    errors, faults) pass through untouched."""
    findings = []
    tables = _module_tables(tree)

    def pin(table_name: str, sites: set, kind: str):
        if table_name not in tables:
            if sites:
                findings.append(Finding(
                    "GRAFT-R001", rel, f"missing-table:{table_name}", 1,
                    f"{kind} literals {sorted(sites)} on the wire but no "
                    f"{table_name} table pins them"))
            return
        declared, lineno = tables[table_name]
        for name in sorted(sites - set(declared)):
            findings.append(Finding(
                "GRAFT-R001", rel, f"{table_name}:{name}", lineno,
                f"{kind} {name!r} used on the wire but missing from "
                f"{table_name}"))
        for name in sorted(set(declared) - sites):
            findings.append(Finding(
                "GRAFT-R001", rel, f"{table_name}:{name}", lineno,
                f"{table_name} declares {name!r} but no {kind} site "
                "uses it"))

    client_methods = _client_method_literals(tree)
    if client_methods or "CLIENT_METHODS" in tables:
        pin("CLIENT_METHODS", client_methods, "client-sent method")
        pin("CLIENT_EVENT_ARMS", _event_compare_arms(tree),
            "client event dispatch arm")
    server_methods = _server_handler_methods(tree)
    if server_methods or "SERVER_METHODS" in tables:
        pin("SERVER_METHODS", server_methods, "server handler method")
        pin("SERVER_EVENTS", _pushed_events(tree), "server-pushed event")
    return findings


def _health_dict_keys(tree: ast.AST, cls: str) -> set:
    """Keys a class's ``health`` method provides: string keys of every
    dict literal it returns plus ``h["key"] = ...`` augmentations."""
    out = set()
    for qual, fn in _functions(tree):
        if qual != f"{cls}.health":
            continue
        for node in ast.walk(fn):
            if isinstance(node, ast.Dict):
                out.update(v for v in (_str_const(k) for k in node.keys
                                       if k is not None) if v)
            elif isinstance(node, ast.Assign):
                for target in node.targets:
                    if isinstance(target, ast.Subscript):
                        lit = _str_const(target.slice)
                        if lit:
                            out.add(lit)
    return out


def _read_health_keys(tree: ast.AST) -> set:
    """Keys a consumer module reads off health snapshots: ``x.get("k")``
    and ``x["k"]`` literals (broad on purpose — freshness pin only)."""
    out = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Call) and _call_name(node) == "get" \
                and node.args:
            lit = _str_const(node.args[0])
            if lit:
                out.add(lit)
        elif isinstance(node, ast.Subscript):
            lit = _str_const(node.slice)
            if lit:
                out.add(lit)
    return out


def _check_health_parity(root: str) -> list:
    """R001 health half: every REQUIRED_HEALTH_KEYS key is provided by
    every backend (engine-level dict ∪ handle-level augmentation) and is
    still actually read by a consumer."""
    findings = []
    trees = {}
    for rel in {p for p, _ in _HEALTH_PROVIDERS} \
            | {p for p, _ in _HEALTH_AUGMENTORS} | set(_HEALTH_CONSUMERS):
        path = os.path.join(root, rel)
        if not os.path.isfile(path):
            return []  # partial checkout (fixture runs) — nothing to pin
        with open(path) as f:
            trees[rel] = ast.parse(f.read())
    augmented = set()
    for rel, cls in _HEALTH_AUGMENTORS:
        augmented |= _health_dict_keys(trees[rel], cls)
    for rel, cls in _HEALTH_PROVIDERS:
        provided = _health_dict_keys(trees[rel], cls) | augmented
        for key in REQUIRED_HEALTH_KEYS:
            if key not in provided:
                findings.append(Finding(
                    "GRAFT-R001", rel, f"health-key:{cls}:{key}", 0,
                    f"{cls}.health() (plus the replica-handle "
                    f"augmentations) never provides {key!r}, which the "
                    "fleet control plane reads — backends must share one "
                    "health field contract"))
    read = set()
    for rel in _HEALTH_CONSUMERS:
        read |= _read_health_keys(trees[rel])
    for key in REQUIRED_HEALTH_KEYS:
        if key not in read:
            findings.append(Finding(
                "GRAFT-R001", _HEALTH_CONSUMERS[0], f"health-key:{key}", 0,
                f"REQUIRED_HEALTH_KEYS pins {key!r} but no control-plane "
                "consumer reads it any more — drop it from the pin"))
    return findings


# ---------------------------------------------------------------------------
# R002 — exception-serialization totality (AST half: raise discipline)
# ---------------------------------------------------------------------------

def _check_raise_types(tree: ast.AST, rel: str, wire_names: frozenset
                       ) -> list:
    """Every ``raise SomeError(...)`` in a protocol module must name a
    registered wire type: the server encodes ITS OWN raises, and a type
    outside the table silently degrades to RequestFailedError — losing the
    retryable/terminal distinction the router keys on."""
    findings = []
    for qual, fn in _functions(tree):
        for node in ast.walk(fn):
            if not isinstance(node, ast.Raise) or node.exc is None:
                continue
            exc = node.exc
            name = None
            if isinstance(exc, ast.Call) and isinstance(exc.func, ast.Name):
                name = exc.func.id
            elif isinstance(exc, ast.Name) and exc.id[:1].isupper():
                name = exc.id
            if name is None or not name[:1].isupper():
                continue  # re-raise of a bound variable — typed upstream
            if name not in wire_names:
                findings.append(Finding(
                    "GRAFT-R002", rel, f"{qual}:{name}", node.lineno,
                    f"raise {name} in a protocol module but {name!r} is "
                    "not a registered wire type — it would cross the RPC "
                    "boundary degraded to RequestFailedError"))
    return findings


def _wire_type_names() -> frozenset:
    from ddim_cold_tpu.serve import errors

    return frozenset(errors._wire_types())


def _check_wire_roundtrip() -> list:
    """R002 import half: every serve/errors.py exception class is in the
    wire table and decode(encode(exc)) restores the exact type."""
    import inspect

    from ddim_cold_tpu.serve import errors

    findings = []
    rel = "ddim_cold_tpu/serve/errors.py"
    table = errors._wire_types()
    for name, obj in vars(errors).items():
        if not (inspect.isclass(obj) and issubclass(obj, BaseException)):
            continue
        if obj.__module__ != errors.__name__:
            continue
        if name not in table:
            findings.append(Finding(
                "GRAFT-R002", rel, f"unregistered:{name}", 0,
                f"exception class {name} is defined in serve/errors.py "
                "but missing from _wire_types() — it cannot round-trip "
                "the RPC boundary as itself"))
    for name, cls in table.items():
        try:
            decoded = errors.decode_exception(
                errors.encode_exception(cls("probe")))
        except Exception as exc:  # noqa: BLE001 — the codec itself failing
            # IS the finding; anything it raises is the evidence
            findings.append(Finding(
                "GRAFT-R002", rel, f"codec:{name}", 0,
                f"encode/decode of {name} raised {type(exc).__name__}: "
                f"{exc}"))
            continue
        if type(decoded) is not cls:
            findings.append(Finding(
                "GRAFT-R002", rel, f"roundtrip:{name}", 0,
                f"{name} decodes as {type(decoded).__name__} — the wire "
                "codec loses the type"))
    return findings


# ---------------------------------------------------------------------------
# R003 — rid-lifecycle ordering
# ---------------------------------------------------------------------------

def _check_rid_ordering(tree: ast.AST, rel: str) -> list:
    """The PR-19 HIGH race as a rule: in any function that sends a
    ``"submit"`` frame, the ticket-table registration (``...tickets[rid] =
    ticket``) must appear — and appear BEFORE the send. A done event from
    a fast replica races the submit response; registration-after-send
    loses that race and blocks ``result()`` forever."""
    findings = []
    for qual, fn in _functions(tree):
        submit_line = None
        register_line = None
        for node in ast.walk(fn):
            if isinstance(node, ast.Call) \
                    and _call_name(node) in ("_call", "_send", "call",
                                             "send_frame") \
                    and node.args and _str_const(node.args[0]) == "submit":
                if submit_line is None or node.lineno < submit_line:
                    submit_line = node.lineno
            if isinstance(node, ast.Assign):
                for target in node.targets:
                    if isinstance(target, ast.Subscript) \
                            and "tickets" in _dotted(target.value):
                        if register_line is None \
                                or node.lineno < register_line:
                            register_line = node.lineno
        if submit_line is None:
            continue
        if register_line is None:
            findings.append(Finding(
                "GRAFT-R003", rel, qual, submit_line,
                f"{qual} sends a 'submit' frame but never registers a "
                "ticket — a pushed done event has nothing to resolve"))
        elif register_line > submit_line:
            findings.append(Finding(
                "GRAFT-R003", rel, qual, register_line,
                f"{qual} registers its ticket at line {register_line}, "
                f"AFTER the submit frame leaves at line {submit_line} — "
                "a done event racing the response finds no ticket (the "
                "PR-19 rid-after-send race)"))
    return findings


# ---------------------------------------------------------------------------
# R004 — bounded-read discipline
# ---------------------------------------------------------------------------

def _mentions_max_frame(fn: ast.AST) -> bool:
    for node in ast.walk(fn):
        if isinstance(node, ast.Compare):
            for side in (node.left, *node.comparators):
                if "MAX_FRAME_BYTES" in _dotted(side):
                    return True
    return False


def _check_bounded_reads(tree: ast.AST, rel: str) -> list:
    findings = []
    for qual, fn in _functions(tree):
        unpacks_len = False
        recv_lines = []          # calls whose name mentions recv
        raw_recv = []            # socket-level .recv(...) calls
        sendall_line = None
        timeout_none = []        # settimeout(None) statements
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            name = _call_name(node)
            if name == "unpack" and node.args \
                    and _str_const(node.args[0]) == ">I":
                unpacks_len = True
            if "recv" in name:
                recv_lines.append(node.lineno)
                if name == "recv":
                    raw_recv.append(node)
            if name == "sendall":
                sendall_line = node.lineno
            if name == "settimeout" and node.args \
                    and isinstance(node.args[0], ast.Constant) \
                    and node.args[0].value is None:
                timeout_none.append(node.lineno)
        guarded = _mentions_max_frame(fn)
        # (a) a length prefix feeding a read must be limit-checked first
        if unpacks_len and recv_lines and not guarded:
            findings.append(Finding(
                "GRAFT-R004", rel, f"{qual}:unchecked-length",
                min(recv_lines),
                f"{qual} unpacks a frame length and reads from it without "
                "checking MAX_FRAME_BYTES — a corrupt prefix becomes an "
                "arbitrary allocation"))
        # (b) frame sends re-check the limit on their own side
        if sendall_line is not None and not guarded:
            findings.append(Finding(
                "GRAFT-R004", rel, f"{qual}:unchecked-send", sendall_line,
                f"{qual} sends a frame without checking MAX_FRAME_BYTES — "
                "the peer's recv_frame would kill the connection instead "
                "of this side failing typed"))
        # (c) raw recv chunks are min()-capped
        for node in raw_recv:
            arg = node.args[0] if node.args else None
            capped = isinstance(arg, ast.Call) \
                and isinstance(arg.func, ast.Name) and arg.func.id == "min"
            if not capped:
                findings.append(Finding(
                    "GRAFT-R004", rel, f"{qual}:uncapped-recv",
                    node.lineno,
                    f"{qual} calls recv() without a min()-capped chunk "
                    "size — one call may allocate the whole (attacker-"
                    "chosen) length"))
        # (d) deadline-free sockets only after the validated read — the
        # PR-19 unbounded-hello shape
        for lineno in timeout_none:
            if recv_lines and lineno < min(recv_lines):
                findings.append(Finding(
                    "GRAFT-R004", rel, f"{qual}:unbounded-read", lineno,
                    f"{qual} drops the socket deadline (settimeout(None)) "
                    "BEFORE its first read — a wedged peer blocks this "
                    "thread forever (the PR-19 unbounded-hello shape)"))
    return findings


# ---------------------------------------------------------------------------
# R005 — fault-site coverage
# ---------------------------------------------------------------------------

def _check_fault_sites(tree: ast.AST, rel: str) -> list:
    """The wire chaos sites must actually fire on the paths they claim:
    a module with a frame-send choke point (``_send``) fires the rpc.*
    pair; a module with a server ``handle`` fires the replica.* pair."""
    findings = []
    fired = _fired_sites(tree)
    has_send = any(q.endswith("._send") or q == "_send"
                   for q, _ in _functions(tree))
    has_handle = any(q.endswith(".handle") for q, _ in _functions(tree))
    if has_send:
        for site in ("rpc.drop", "rpc.latency"):
            if site not in fired:
                findings.append(Finding(
                    "GRAFT-R005", rel, site, 1,
                    f"client frame-send path never fires {site!r} — the "
                    "chaos schedule cannot break this wire"))
    if has_handle:
        for site in ("replica.kill", "replica.hang"):
            if site not in fired:
                findings.append(Finding(
                    "GRAFT-R005", rel, site, 1,
                    f"server dispatch path never fires {site!r} — kill/"
                    "hang chaos cannot target this replica's work"))
    return findings


def _check_site_registration() -> list:
    from ddim_cold_tpu.serve import remote, replica_main
    from ddim_cold_tpu.utils import faults

    findings = []
    for site in WIRE_FAULT_SITES:
        if site not in faults.SITES:
            findings.append(Finding(
                "GRAFT-R005", "ddim_cold_tpu/utils/faults.py", site, 0,
                f"wire chaos site {site!r} is not registered in "
                "faults.SITES — specs naming it would silently no-op"))
    for method in replica_main.ReplicaServer.WORK_METHODS:
        if method not in replica_main.SERVER_METHODS:
            findings.append(Finding(
                "GRAFT-R005", "ddim_cold_tpu/serve/replica_main.py",
                f"work-method:{method}", 0,
                f"WORK_METHODS entry {method!r} is not a served RPC "
                "method — its kill/hang coverage is dead"))
    del remote  # imported for symmetry with R001's table checks
    return findings


# ---------------------------------------------------------------------------
# entry points
# ---------------------------------------------------------------------------

def lint_source(source: str, rel: str, wire_names: frozenset | None = None
                ) -> list:
    """All AST-half R-rules over one module source (fixtures use this)."""
    tree = ast.parse(source)
    if wire_names is None:
        wire_names = _wire_type_names()
    findings = []
    findings += _check_frame_tables(tree, rel)
    findings += _check_raise_types(tree, rel, wire_names)
    findings += _check_rid_ordering(tree, rel)
    findings += _check_bounded_reads(tree, rel)
    findings += _check_fault_sites(tree, rel)
    return findings


def _table_parity() -> list:
    """R001 import half: the two sides' literal frame tables agree."""
    from ddim_cold_tpu.serve import remote, replica_main

    findings = []
    client = set(remote.CLIENT_METHODS)
    server = set(replica_main.SERVER_METHODS)
    for method in sorted(client - server):
        findings.append(Finding(
            "GRAFT-R001", "ddim_cold_tpu/serve/replica_main.py",
            f"unhandled-method:{method}", 0,
            f"client sends {method!r} but the server has no handler"))
    for method in sorted(server - client):
        findings.append(Finding(
            "GRAFT-R001", "ddim_cold_tpu/serve/remote.py",
            f"unreachable-method:{method}", 0,
            f"server handles {method!r} but no client path sends it"))
    arms = set(remote.CLIENT_EVENT_ARMS)
    for event in sorted(set(replica_main.SERVER_EVENTS) - arms):
        findings.append(Finding(
            "GRAFT-R001", "ddim_cold_tpu/serve/remote.py",
            f"undispatched-event:{event}", 0,
            f"server pushes {event!r} but the client reader has no "
            "dispatch arm — the event would be dropped on the floor"))
    return findings


def run_protocol_checks(root: str | None = None) -> list:
    """The full R-layer: AST over the protocol modules + the import-time
    parity/round-trip/registration checks."""
    if root is None:
        from ddim_cold_tpu.analysis.cli import repo_root

        root = repo_root()
    wire_names = _wire_type_names()
    findings = []
    for rel in PROTOCOL_MODULES:
        path = os.path.join(root, rel)
        if not os.path.isfile(path):
            continue
        with open(path) as f:
            findings += lint_source(f.read(), rel, wire_names)
    findings += _table_parity()
    findings += _check_health_parity(root)
    findings += _check_wire_roundtrip()
    findings += _check_site_registration()
    return findings
