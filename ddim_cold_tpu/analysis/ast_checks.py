"""AST lint — the repo-specific host-code rules (GRAFT-A001..A005).

Pure ``ast`` walking, no imports of the checked modules, so the lint runs on
any tree state (including one that currently fails to import). The one
dynamic input is the registered fault-site tuple, read from
``ddim_cold_tpu.utils.faults.SITES`` by the caller and passed in.

Traced-function detection (rule A001) is necessarily an approximation of
"code JAX will stage out": a function counts as traced when it is

* decorated with / wrapped by ``jax.jit`` (including the
  ``partial(jax.jit, ...)`` and ``name = jax.jit(fn, ...)`` forms),
* passed as a body/branch to ``lax.scan`` / ``while_loop`` / ``fori_loop``
  / ``cond`` / ``switch`` / ``pallas_call`` / ``vmap`` / ``grad`` /
  ``value_and_grad`` / ``checkpoint`` / ``remat`` (``functools.partial``
  wrappers unwrapped), or
* defined inside, or called by name from, a traced function (transitive
  closure over same-file calls).

That covers every staged function in this repo; a helper smuggled through a
container would evade it, which is the usual static-lint bargain.
"""

from __future__ import annotations

import ast
import os
from typing import Iterable, Optional, Sequence

from ddim_cold_tpu.analysis.findings import Finding

#: wrapper callables whose function-typed arguments get traced.
#: name → indices of the function args (None = "all positional args").
_TRACE_ARGS = {
    "jit": (0,), "vmap": (0,), "pmap": (0,), "grad": (0,),
    "value_and_grad": (0,), "checkpoint": (0,), "remat": (0,),
    "custom_jvp": (0,), "custom_vjp": (0,), "named_call": (0,),
    "scan": (0,), "while_loop": (0, 1), "fori_loop": (2,),
    "cond": (1, 2), "switch": None, "pallas_call": (0,),
    "map": (0,), "associative_scan": (0,),
}

#: modules whose use inside traced code is nondeterministic (rule A001).
#: maps canonical module name → reason fragment.
_NONDET_MODULES = {
    "time": "wall clock",
    "random": "stdlib RNG (unseeded per-trace)",
    "numpy.random": "host RNG outside the jax PRNG contract",
}

#: modules that imply device interaction in host-only files (rule A004)
_DEVICE_MODULES = ("jax.numpy", "jax")

#: serve modules that must never touch a device array (repo-relative paths
#: with '/' separators): row planning (batching) and fleet routing —
#: placement decisions reading health dicts must stay host-typed, or every
#: routing tick forces a device sync
HOST_ONLY_MODULES = ("ddim_cold_tpu/serve/batching.py",
                     "ddim_cold_tpu/serve/fleet.py",
                     "ddim_cold_tpu/serve/router.py",
                     # the obs layer rides the router's host threads (and its
                     # registry/span emits sit on serving hot paths) — a jax
                     # attribute here is a hidden device sync per emit
                     "ddim_cold_tpu/obs/metrics.py",
                     "ddim_cold_tpu/obs/spans.py",
                     "ddim_cold_tpu/obs/device.py",
                     # trace attribution + the trend gate parse artifacts
                     # after the fact — often in CI or on a laptop that
                     # never saw the device; importing jax there would drag
                     # a backend init into every report render
                     "ddim_cold_tpu/obs/attrib.py",
                     "ddim_cold_tpu/obs/trend.py",
                     # the process boundary: the parent-side RPC handle and
                     # autoscaler never touch a device, and the replica
                     # server must boot to its hello without one — engine
                     # construction hides behind serve/backend.py (the one
                     # jax-touching import, deferred inside the child)
                     "ddim_cold_tpu/serve/remote.py",
                     "ddim_cold_tpu/serve/autoscale.py",
                     "ddim_cold_tpu/serve/replica_main.py")

#: obs.metrics emit methods (rule A005) → the registry kind they imply
_METRIC_EMITS = ("inc", "gauge", "observe")


def _dotted(node: ast.AST) -> Optional[str]:
    """``a.b.c`` attribute/name chain → 'a.b.c' (None for anything else)."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _import_aliases(tree: ast.AST) -> dict[str, str]:
    """Local name → canonical dotted module/object it binds."""
    aliases: dict[str, str] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                aliases[a.asname or a.name.split(".")[0]] = (
                    a.name if a.asname else a.name.split(".")[0])
        elif isinstance(node, ast.ImportFrom) and node.module:
            for a in node.names:
                aliases[a.asname or a.name] = f"{node.module}.{a.name}"
    return aliases


def _canonical(dotted: str, aliases: dict[str, str]) -> str:
    head, _, rest = dotted.partition(".")
    head = aliases.get(head, head)
    return f"{head}.{rest}" if rest else head


def _unwrap_partial(node: ast.AST) -> ast.AST:
    """``partial(f, ...)`` / ``functools.partial(f, ...)`` → ``f``."""
    if (isinstance(node, ast.Call) and node.args
            and (_dotted(node.func) or "").split(".")[-1] == "partial"):
        return _unwrap_partial(node.args[0])
    return node


class _FnIndex(ast.NodeVisitor):
    """Collect every function def (with parent chain) and call site."""

    def __init__(self):
        self.defs: list[ast.AST] = []
        self.parents: dict[ast.AST, Optional[ast.AST]] = {}
        self._stack: list[ast.AST] = []

    def _visit_fn(self, node):
        self.defs.append(node)
        self.parents[node] = self._stack[-1] if self._stack else None
        self._stack.append(node)
        self.generic_visit(node)
        self._stack.pop()

    visit_FunctionDef = _visit_fn
    visit_AsyncFunctionDef = _visit_fn
    visit_Lambda = _visit_fn


def _traced_functions(tree: ast.AST) -> set[ast.AST]:
    """The traced-function set per the module docstring's detection rules."""
    idx = _FnIndex()
    idx.visit(tree)
    by_name: dict[str, list[ast.AST]] = {}
    for d in idx.defs:
        if not isinstance(d, ast.Lambda):
            by_name.setdefault(d.name, []).append(d)

    traced: set[ast.AST] = set()

    def mark_name(name: Optional[str]):
        for d in by_name.get(name or "", []):
            traced.add(d)

    def fn_arg_names(call: ast.Call, which) -> Iterable[Optional[str]]:
        args = call.args if which is None else [
            call.args[i] for i in which if i < len(call.args)]
        for a in args:
            a = _unwrap_partial(a)
            if isinstance(a, ast.Name):
                yield a.id
            elif isinstance(a, (ast.List, ast.Tuple)):
                for el in a.elts:
                    el = _unwrap_partial(el)
                    if isinstance(el, ast.Name):
                        yield el.id

    for node in ast.walk(tree):
        # decorators: @jax.jit / @partial(jax.jit, ...) / @jax.checkpoint ...
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                target = _unwrap_partial(dec) if isinstance(dec, ast.Call) \
                    else dec
                if isinstance(target, ast.Call):  # @partial(jax.jit, ...)
                    target = target.func if not target.args else target
                name = _dotted(target if not isinstance(target, ast.Call)
                               else target.func)
                if name and name.split(".")[-1] in _TRACE_ARGS:
                    traced.add(node)
                # @partial(jax.jit, kw=...) leaves partial's first arg as
                # jax.jit with no function — the decorated def is the fn
                if (isinstance(dec, ast.Call)
                        and (_dotted(dec.func) or "").split(".")[-1]
                        == "partial" and dec.args):
                    inner = _dotted(dec.args[0])
                    if inner and inner.split(".")[-1] in _TRACE_ARGS:
                        traced.add(node)
        if not isinstance(node, ast.Call):
            continue
        func = _unwrap_partial(node.func) if isinstance(node.func, ast.Call) \
            else node.func
        name = _dotted(func)
        if not name:
            continue
        leaf = name.split(".")[-1]
        if leaf in _TRACE_ARGS:
            for fn_name in fn_arg_names(node, _TRACE_ARGS[leaf]):
                mark_name(fn_name)
        # `x = jax.jit(fn, ...)` handled by the branch above (leaf == 'jit');
        # `partial(jax.jit, ...)(step_body)` — func is a partial Call:
        if isinstance(node.func, ast.Call):
            inner = node.func
            if ((_dotted(inner.func) or "").split(".")[-1] == "partial"
                    and inner.args):
                wrapped = _dotted(inner.args[0])
                if wrapped and wrapped.split(".")[-1] in _TRACE_ARGS:
                    for a in node.args:
                        a = _unwrap_partial(a)
                        if isinstance(a, ast.Name):
                            mark_name(a.id)

    # transitive closure: defs nested in traced fns, and same-file functions
    # called by name from a traced body
    changed = True
    while changed:
        changed = False
        for d in idx.defs:
            if d in traced:
                continue
            p = idx.parents.get(d)
            while p is not None:
                if p in traced:
                    traced.add(d)
                    changed = True
                    break
                p = idx.parents.get(p)
        for d in list(traced):
            for node in ast.walk(d):
                if isinstance(node, ast.Call) and isinstance(node.func,
                                                             ast.Name):
                    for target in by_name.get(node.func.id, []):
                        if target not in traced:
                            traced.add(target)
                            changed = True
    return traced


def _enclosing_name(tree: ast.AST, lineno: int) -> str:
    """Name of the innermost def containing ``lineno`` (module scope → the
    file stem placeholder '<module>'). Used as the stable finding subject."""
    best, best_span = "<module>", None
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            end = getattr(node, "end_lineno", node.lineno)
            if node.lineno <= lineno <= end:
                span = end - node.lineno
                if best_span is None or span < best_span:
                    best, best_span = node.name, span
    return best


# ---------------------------------------------------------------------------
# per-rule checks (each takes a parsed file, returns findings)
# ---------------------------------------------------------------------------

def _check_determinism(tree, rel: str, aliases) -> list[Finding]:
    out = []
    seen = set()
    for fn in _traced_functions(tree):
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            name = _dotted(node.func)
            if not name:
                continue
            canon = _canonical(name, aliases)
            mod = canon.rsplit(".", 1)[0] if "." in canon else canon
            hit = None
            for bad, why in _NONDET_MODULES.items():
                if mod == bad or mod.startswith(bad + "."):
                    hit = (canon, why)
            if hit and node.lineno not in seen:
                seen.add(node.lineno)
                fname = getattr(fn, "name", "<lambda>")
                out.append(Finding(
                    "GRAFT-A001", rel, f"{fname}:{hit[0]}", node.lineno,
                    f"`{name}()` inside traced function `{fname}` — "
                    f"{hit[1]}; traced code must draw from the jax PRNG / "
                    "scanned inputs only"))
    return out


def _check_broad_except(tree, rel: str, lines: list[str]) -> list[Finding]:
    out = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.ExceptHandler):
            continue
        names = []
        t = node.type
        for el in (t.elts if isinstance(t, ast.Tuple) else [t]):
            names.append(_dotted(el) if el is not None else None)
        broad = any(n is None or (n or "").split(".")[-1]
                    in ("Exception", "BaseException") for n in names)
        if not broad:
            continue
        src = lines[node.lineno - 1] if node.lineno <= len(lines) else ""
        if "noqa: BLE001" in src:
            continue
        fn = _enclosing_name(tree, node.lineno)
        caught = "bare except" if node.type is None else \
            f"except {'/'.join(n or '?' for n in names)}"
        out.append(Finding(
            "GRAFT-A002", rel, f"{fn}:{caught}", node.lineno,
            f"{caught} without `# noqa: BLE001 — <why>` on the handler "
            "line; narrow the exception or justify the breadth"))
    return out


def _fire_calls(tree) -> list[tuple[ast.Call, object, object]]:
    """Every ``faults.fire(...)`` call → (node, site_arg, tag_arg)."""
    calls = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        name = _dotted(node.func) or ""
        if name.split(".")[-1] != "fire" or "." not in name:
            continue
        site = node.args[0] if node.args else None
        tag = node.args[1] if len(node.args) > 1 else None
        for kw in node.keywords:
            if kw.arg == "site":
                site = kw.value
            elif kw.arg == "tag":
                tag = kw.value
        calls.append((node, site, tag))
    return calls


def _check_fault_sites(tree, rel: str, sites: Sequence[str],
                       seen_pairs: dict) -> list[Finding]:
    out = []
    for node, site, tag in _fire_calls(tree):
        if not isinstance(site, ast.Constant) or not isinstance(site.value,
                                                                str):
            out.append(Finding(
                "GRAFT-A003", rel, "fire:<dynamic>", node.lineno,
                "faults.fire() site must be a string literal so the "
                "registry and the replay grammar can see it statically"))
            continue
        name = site.value
        if name not in sites:
            out.append(Finding(
                "GRAFT-A003", rel, f"fire:{name}", node.lineno,
                f"fault site {name!r} is not registered in "
                "utils/faults.SITES — specs targeting it would be rejected "
                "as typos"))
        tag_lit = (tag.value if isinstance(tag, ast.Constant)
                   and isinstance(tag.value, str) else None)
        if tag_lit is not None:
            pair = (name, tag_lit)
            if pair in seen_pairs:
                first = seen_pairs[pair]
                out.append(Finding(
                    "GRAFT-A003", rel, f"fire:{name}:{tag_lit}", node.lineno,
                    f"duplicate fire site ({name!r}, tag {tag_lit!r}) — "
                    f"first fired at {first}; replay cannot distinguish "
                    "the two call points"))
            else:
                seen_pairs[pair] = f"{rel}:{node.lineno}"
    return out


def _metric_calls(tree) -> list[tuple[ast.Call, object, object]]:
    """Every ``<scope>.inc/.gauge/.observe(...)`` emit → (node, name_arg,
    key_arg). Attribute calls only — a bare ``inc(...)`` is some other
    function, exactly as ``fire`` detection works in :func:`_fire_calls`."""
    calls = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        name = _dotted(node.func) or ""
        if name.split(".")[-1] not in _METRIC_EMITS or "." not in name:
            continue
        metric = node.args[0] if node.args else None
        key = None
        for kw in node.keywords:
            if kw.arg == "name":
                metric = kw.value
            elif kw.arg == "key":
                key = kw.value
        calls.append((node, metric, key))
    return calls


def _check_metric_sites(tree, rel: str, metric_names: Sequence[str],
                        seen_pairs: dict) -> list[Finding]:
    out = []
    for node, metric, key in _metric_calls(tree):
        if not isinstance(metric, ast.Constant) or not isinstance(
                metric.value, str):
            out.append(Finding(
                "GRAFT-A005", rel, "metric:<dynamic>", node.lineno,
                "obs.metrics emit (.inc/.gauge/.observe) must pass a "
                "string-literal metric name so the registry stays "
                "statically auditable"))
            continue
        name = metric.value
        if name not in metric_names:
            out.append(Finding(
                "GRAFT-A005", rel, f"metric:{name}", node.lineno,
                f"metric {name!r} is not registered in obs.metrics.METRICS "
                "— the registry would reject the emit at runtime"))
        key_lit = (key.value if isinstance(key, ast.Constant)
                   and isinstance(key.value, str) else None)
        if key is not None and key_lit is None:
            continue  # dynamic key= subdivides one site — uniqueness holds
        pair = (name, key_lit)
        if pair in seen_pairs:
            first = seen_pairs[pair]
            subj = f"metric:{name}" + (f":{key_lit}" if key_lit else "")
            out.append(Finding(
                "GRAFT-A005", rel, subj, node.lineno,
                f"duplicate emit site for metric ({name!r}, key "
                f"{key_lit!r}) — first emitted at {first}; give the second "
                "site a distinct literal key= (the A003 tag rule)"))
        else:
            seen_pairs[pair] = f"{rel}:{node.lineno}"
    return out


def _check_host_only(tree, rel: str, aliases) -> list[Finding]:
    out = []
    seen = set()
    for node in ast.walk(tree):
        name = _dotted(node) if isinstance(node, ast.Attribute) else None
        if not name or "." not in name:
            continue
        canon = _canonical(name, aliases)
        root = canon.split(".")[0]
        if root not in ("jax",) and not canon.startswith("jax.numpy"):
            continue
        if node.lineno in seen:
            continue
        seen.add(node.lineno)
        fn = _enclosing_name(tree, node.lineno)
        out.append(Finding(
            "GRAFT-A004", rel, f"{fn}:{name}", node.lineno,
            f"`{name}` in host-only module {rel} — row planning must stay "
            "on numpy/host types or every plan forces a device sync"))
    return out


# ---------------------------------------------------------------------------
# entry points
# ---------------------------------------------------------------------------

def lint_source(source: str, rel: str, *, sites: Sequence[str] = (),
                metric_names: Sequence[str] = (),
                host_only: bool = False,
                seen_fire_pairs: Optional[dict] = None,
                seen_metric_pairs: Optional[dict] = None) -> list[Finding]:
    """Lint one file's source (the unit tests feed violating snippets here).
    ``rel`` is the repo-relative path used in findings."""
    tree = ast.parse(source)
    aliases = _import_aliases(tree)
    lines = source.splitlines()
    findings = []
    findings += _check_determinism(tree, rel, aliases)
    findings += _check_broad_except(tree, rel, lines)
    findings += _check_fault_sites(tree, rel, sites,
                                   {} if seen_fire_pairs is None
                                   else seen_fire_pairs)
    findings += _check_metric_sites(tree, rel, metric_names,
                                    {} if seen_metric_pairs is None
                                    else seen_metric_pairs)
    if host_only:
        findings += _check_host_only(tree, rel, aliases)
    return findings


def lint_tree(root: str, package: str = "ddim_cold_tpu",
              sites: Optional[Sequence[str]] = None,
              metric_names: Optional[Sequence[str]] = None) -> list[Finding]:
    """Lint every ``.py`` file under ``root/package``. ``sites`` defaults to
    the live ``utils.faults.SITES`` registry, ``metric_names`` to the live
    ``obs.metrics.METRICS`` registry."""
    if sites is None:
        from ddim_cold_tpu.utils import faults

        sites = faults.SITES
        dupes = {s for s in sites if list(sites).count(s) > 1}
        if dupes:
            return [Finding("GRAFT-A003", f"{package}/utils/faults.py",
                            f"SITES:{s}", 0,
                            f"site {s!r} registered more than once in SITES")
                    for s in sorted(dupes)]
    if metric_names is None:
        from ddim_cold_tpu.obs import metrics as obs_metrics

        metric_names = tuple(n for n, _, _ in obs_metrics.METRICS)
        dupes = {n for n in metric_names
                 if list(metric_names).count(n) > 1}
        if dupes:
            return [Finding("GRAFT-A005", f"{package}/obs/metrics.py",
                            f"METRICS:{n}", 0,
                            f"metric {n!r} registered more than once in "
                            "METRICS")
                    for n in sorted(dupes)]
    findings: list[Finding] = []
    seen_fire: dict = {}
    seen_metric: dict = {}
    base = os.path.join(root, package)
    for dirpath, _, files in sorted(os.walk(base)):
        for fname in sorted(files):
            if not fname.endswith(".py"):
                continue
            path = os.path.join(dirpath, fname)
            rel = os.path.relpath(path, root).replace(os.sep, "/")
            with open(path) as f:
                src = f.read()
            findings += lint_source(
                src, rel, sites=sites, metric_names=metric_names,
                host_only=rel in HOST_ONLY_MODULES,
                seen_fire_pairs=seen_fire,
                seen_metric_pairs=seen_metric)
    return findings
