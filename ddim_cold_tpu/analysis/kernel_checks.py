"""GRAFT-P001..P003 — static Pallas kernel-geometry verification.

The one class of failure that has actually burned a chip window is
statically decidable: the r04 north-star died on a 200px Mosaic
block-divisibility error that CPU interpret mode (what CI runs) does not
enforce. This layer walks every ``pallas_call`` eqn in the abstract traces
graftcheck already builds — the J006 serve sweep, the build/train entries,
and the first-class 200px kernel entries (``entries.kernel_entries``) — and
re-derives kernel legality from the raw eqn geometry, deliberately NOT by
calling ``ops/tiling.legal_block``: the pass must catch a call site that
bypassed (or a regression inside) the legalizer, so it keeps its own copy
of the Mosaic tile table and applies the rule to what the trace actually
contains.

**P001 — tile legality.** Per block mapping, each of the block's last two
dims must be a multiple of the dtype's minimum tile (sublane × lane: f32
(8, 128), bf16/f16 (16, 128), int8 (32, 128)) or span the whole array dim;
the array dim must additionally be a multiple of the block (the in-tree
pad-to-block-multiple policy — the exact invariant whose violation killed
r04). The dequant matmul's dual-dtype K constraint (activation lane dim AND
int8 weight sublane dim at once) needs no special case: the shared K block
size appears in two block mappings, each checked against its own dtype.
P001 also demands a fully STATIC grid: a ``np.int64`` grid entry silently
becomes a dynamic grid dim, making the geometry unprovable (and forfeiting
static scheduling) — the in-tree bug the first run of this pass found in
``tiling.legal_block``'s lcm arithmetic.

**P002 — VMEM fit.** Per program instance the pipeline holds each in/out
block double-buffered plus every ``pltpu.VMEM`` scratch operand; the sum
must fit the per-device-kind VMEM capacity (``utils/flops.VMEM_BYTES``).

**P003 — padding waste.** ``round_up(dim, block) / dim`` over the block
geometry — and, when the entry registers a logical token count (N=2501 at
200px; arrays reach the kernel pre-padded, so the eqn alone can't see the
logical extent), the padded extent over the LOGICAL one. A block choice
that inflates compute past the threshold is flagged before it burns chip
time.
"""

from __future__ import annotations

from collections import Counter
from dataclasses import dataclass, field

import numpy as np

from ddim_cold_tpu.analysis import jaxpr_checks
from ddim_cold_tpu.analysis.findings import Finding

#: the device kind the static budgets default to — the bench chip (v5e).
#: Proving fit on the smallest-VMEM/HBM kind we actually run keeps every
#: bigger chip safe for free.
DEVICE_KIND = "TPU v5 lite"

#: independent copy of the Mosaic minimum tile table, keyed by itemsize —
#: (sublane, lane). Deliberately NOT imported from ops/tiling: the pass
#: must re-derive legality so a legalizer regression is caught, not
#: trusted (tests cross-check the two tables agree).
MIN_TILE = {4: (8, 128), 2: (16, 128), 1: (32, 128)}

#: the Pallas pipeline keeps each in/out block double-buffered (copy-in of
#: block i+1 overlaps compute on block i)
PIPELINE_BUFFERS = 2

#: P003 threshold: padded compute over logical compute. The 200px flash
#: q-axis padding (2560/2501 at bq=512) is 1.024, the streamed-kv sweep
#: worst case (3072/2501 at bkv=1024) 1.228 — real geometry sits well
#: under; a careless 2048-block at N=2501 (4096/2501 = 1.64) trips it.
WASTE_THRESHOLD = 1.25


def _round_up(n: int, m: int) -> int:
    return -(-n // m) * m


@dataclass
class BlockInfo:
    """One pallas_call operand's geometry: VMEM block vs backing array."""

    kind: str              # "in" / "out"
    index: int             # operand position within its kind
    block: tuple           # block shape (ints; squeezed dims already ints)
    array: tuple           # backing array shape
    dtype: np.dtype


@dataclass
class KernelCall:
    """One ``pallas_call`` eqn, flattened to checkable geometry."""

    name: str              # kernel function name (name_and_src_info)
    path: str              # repo-relative source file of the kernel
    line: int              # source line (display only)
    grid: tuple            # raw grid entries (ints, or dynamic-dim objects)
    blocks: list = field(default_factory=list)    # [BlockInfo]
    scratch: list = field(default_factory=list)   # [(shape, dtype)] VMEM

    @property
    def grid_static(self) -> bool:
        return all(isinstance(g, (int, np.integer)) for g in self.grid)

    def vmem_bytes(self) -> int:
        """Per-program-instance VMEM footprint: every in/out block held
        ``PIPELINE_BUFFERS``× by the pipeline, plus the scratch operands."""
        total = 0
        for b in self.blocks:
            total += PIPELINE_BUFFERS * int(
                np.prod(b.block or (1,))) * b.dtype.itemsize
        for shape, dtype in self.scratch:
            total += int(np.prod(shape or (1,))) * np.dtype(dtype).itemsize
        return total


def _rel_path(src: str, fallback: str) -> tuple[str, int]:
    """``"... a/b/ddim_cold_tpu/ops/quant.py:295"`` → repo-relative path +
    line; the enclosing entry's path when the src info is unparseable."""
    tail = src.rsplit(" ", 1)[-1] if src else ""
    path, line = tail, 0
    if ":" in tail:
        path, _, ln = tail.rpartition(":")
        line = int(ln) if ln.isdigit() else 0
    marker = "ddim_cold_tpu/"
    if marker in path:
        return marker + path.split(marker, 1)[1], line
    return fallback, 0


def iter_kernel_calls(closed, fallback_path: str):
    """Yield a :class:`KernelCall` for every ``pallas_call`` eqn in the
    trace (nested scan/pjit/cond bodies included)."""
    for eqn, _ in jaxpr_checks.iter_eqns(closed):
        if eqn.primitive.name != "pallas_call":
            continue
        nsi = eqn.params.get("name_and_src_info")
        name = getattr(nsi, "name", None) or "pallas_call"
        path, line = _rel_path(str(getattr(nsi, "src_info", "") or ""),
                               fallback_path)
        gm = eqn.params["grid_mapping"]
        call = KernelCall(name=name, path=path, line=line,
                          grid=tuple(gm.grid))
        n_in, n_out = gm.num_inputs, gm.num_outputs
        for i, bm in enumerate(gm.block_mappings):
            sd = bm.array_shape_dtype
            block = tuple(int(d) for d in bm.block_shape
                          if isinstance(d, (int, np.integer)))
            call.blocks.append(BlockInfo(
                kind="in" if i < n_in else "out",
                index=i if i < n_in else i - n_in,
                block=block, array=tuple(sd.shape), dtype=np.dtype(sd.dtype)))
        kjaxpr = eqn.params.get("jaxpr")
        n_scratch = getattr(gm, "num_scratch_operands", 0)
        if kjaxpr is not None and n_scratch:
            for v in kjaxpr.invars[-n_scratch:]:
                aval = v.aval
                space = str(getattr(aval, "memory_space", "vmem")).lower()
                if "vmem" in space or space in ("none", "any"):
                    call.scratch.append(
                        (tuple(aval.shape), np.dtype(aval.dtype)))
        yield call


# ---------------------------------------------------------------------------
# P001 — Mosaic tile legality + static grid
# ---------------------------------------------------------------------------

def check_tile_legality(call: KernelCall, entry: str,
                        subject: str) -> list[Finding]:
    out: list[Finding] = []
    if not call.grid_static:
        dyn = [str(type(g).__name__) for g in call.grid
               if not isinstance(g, (int, np.integer))]
        out.append(Finding(
            "GRAFT-P001", call.path, f"{subject}:grid", call.line,
            f"kernel `{call.name}` in `{entry}` traced with a non-static "
            f"grid {call.grid} ({'/'.join(dyn)}) — a non-Python-int grid "
            "entry (np.int64 from block arithmetic) becomes a dynamic grid "
            "dim; cast every grid entry to int (tile legality is unprovable "
            "and static scheduling is forfeited)"))
    for b in call.blocks:
        if len(b.block) < 1 or b.dtype.itemsize not in MIN_TILE:
            continue
        sub_u, lane_u = MIN_TILE[b.dtype.itemsize]
        problems = []
        # (axis name, block dim, array dim, min unit) for the last two dims
        axes = [("lane", b.block[-1], b.array[-1], lane_u)]
        if len(b.block) >= 2 and len(b.array) >= 2:
            axes.append(("sublane", b.block[-2], b.array[-2], sub_u))
        for axis, blk, arr, unit in axes:
            if blk != arr and blk % unit:
                problems.append(
                    f"{axis} block {blk} is neither a multiple of the "
                    f"{b.dtype} min-tile unit {unit} nor the whole array "
                    f"dim {arr}")
            if blk and arr % blk:
                problems.append(
                    f"{axis} array dim {arr} is not a multiple of block "
                    f"{blk} — a partial final block (the caller must pad "
                    "the array to a block multiple; the r04 Mosaic "
                    "rejection class)")
        if problems:
            out.append(Finding(
                "GRAFT-P001", call.path,
                f"{subject}:{b.kind}{b.index}", call.line,
                f"kernel `{call.name}` in `{entry}`, {b.kind}[{b.index}] "
                f"block {b.block} over {b.dtype}{b.array}: "
                + "; ".join(problems)))
    return out


# ---------------------------------------------------------------------------
# P002 — per-program VMEM fit
# ---------------------------------------------------------------------------

def check_vmem_fit(call: KernelCall, entry: str, subject: str, *,
                   device_kind: str = DEVICE_KIND,
                   budget_bytes: int | None = None) -> list[Finding]:
    from ddim_cold_tpu.utils import flops

    if budget_bytes is None:
        budget_bytes = flops.vmem_bytes(device_kind)
    if budget_bytes is None:
        return []
    used = call.vmem_bytes()
    if used <= budget_bytes:
        return []
    blocks = " + ".join(
        f"{b.kind}[{b.index}]{b.block}x{PIPELINE_BUFFERS}@{b.dtype}"
        for b in call.blocks)
    scratch = " + ".join(f"scratch{s}@{d}" for s, d in call.scratch) or "none"
    return [Finding(
        "GRAFT-P002", call.path, f"{subject}:vmem", call.line,
        f"kernel `{call.name}` in `{entry}` needs "
        f"{used / 2**20:.1f} MiB VMEM per program instance "
        f"({blocks}; {scratch}) — over the {device_kind} capacity of "
        f"{budget_bytes / 2**20:.0f} MiB; shrink the blocks or split the "
        "scratch")]


# ---------------------------------------------------------------------------
# P003 — grid/block padding waste at a registered geometry
# ---------------------------------------------------------------------------

def check_padding_waste(call: KernelCall, entry: str, subject: str, *,
                        logical: int | None = None,
                        threshold: float = WASTE_THRESHOLD) -> list[Finding]:
    """Worst padded-over-payload compute ratio across the call's block
    geometry. ``logical`` is the entry's registered logical extent (the
    true token count, e.g. N=2501 at 200px): arrays reach the kernel
    already padded, so any array dim in ``[logical, 2·logical)`` is read
    as that logical axis and charged against the UNPADDED extent."""
    worst, worst_why = 1.0, ""
    for b in call.blocks:
        n = min(len(b.block), len(b.array), 2)
        for k in range(1, n + 1):
            blk, arr = b.block[-k], b.array[-k]
            if not blk or not arr:
                continue
            padded = _round_up(arr, blk)
            base = arr
            if logical and logical <= arr < 2 * logical:
                base = logical
            ratio = padded / base
            if ratio > worst:
                worst = ratio
                worst_why = (f"{b.kind}[{b.index}] dim -{k}: block {blk} "
                             f"pads {base} → {padded}")
    if worst <= threshold:
        return []
    return [Finding(
        "GRAFT-P003", call.path, f"{subject}:pad", call.line,
        f"kernel `{call.name}` in `{entry}` wastes {100 * (worst - 1):.0f}% "
        f"of its compute on block padding ({worst_why}; threshold "
        f"{100 * (threshold - 1):.0f}%) — pick a block that divides the "
        "geometry more tightly")]


# ---------------------------------------------------------------------------
# drivers
# ---------------------------------------------------------------------------

def check_program(closed, entry: str, fallback_path: str, *,
                  logical: int | None = None,
                  device_kind: str = DEVICE_KIND,
                  vmem_budget: int | None = None,
                  waste_threshold: float = WASTE_THRESHOLD) -> list[Finding]:
    """P001 + P002 + P003 over every pallas_call in one traced program.
    Subjects are ``<entry>:<kernel>#<n>[:...]`` with ``n`` the per-(entry,
    kernel) occurrence counter — stable across unrelated edits."""
    findings: list[Finding] = []
    counts: Counter = Counter()
    for call in iter_kernel_calls(closed, fallback_path):
        counts[call.name] += 1
        subject = f"{entry}:{call.name}#{counts[call.name]}"
        findings += check_tile_legality(call, entry, subject)
        findings += check_vmem_fit(call, entry, subject,
                                   device_kind=device_kind,
                                   budget_bytes=vmem_budget)
        findings += check_padding_waste(call, entry, subject,
                                        logical=logical,
                                        threshold=waste_threshold)
    return findings


#: serve-sweep findings anchor where J006's do
ENGINE_PATH = "ddim_cold_tpu/serve/engine.py"


def run_kernel_checks(serve_traces: dict | None = None,
                      entry_traces: dict | None = None,
                      kernel_traces: dict | None = None,
                      device_kind: str = DEVICE_KIND) -> list[Finding]:
    """The kernels layer: every pallas_call in the serve sweep, the
    build/train entries, and the 200px kernel entries. The CLI hands over
    the traces the jaxpr layer already built (one trace either way);
    standalone (``--only P``) this traces its own world."""
    from ddim_cold_tpu.analysis import entries

    if serve_traces is None or entry_traces is None:
        ctx = entries.Context()
        if serve_traces is None:
            serve_traces = {}
            entries.serve_signatures(ctx, traces=serve_traces)
        if entry_traces is None:
            entry_traces = {e.name: (e, e.trace())
                            for e in entries.build_entries(ctx)}
    if kernel_traces is None:
        kernel_traces = entries.kernel_traces()
    findings: list[Finding] = []
    for subject in sorted(serve_traces):
        _config, closed = serve_traces[subject]
        findings += check_program(closed, subject, ENGINE_PATH,
                                  device_kind=device_kind)
    for group in (entry_traces, kernel_traces):
        for name in sorted(group):
            e, closed = group[name]
            findings += check_program(
                closed, name, e.path, device_kind=device_kind,
                logical=(e.meta or {}).get("tokens"))
    return findings
