"""Jaxpr-level checks (GRAFT-J001..J005) over abstractly traced entry points.

Everything here works on ``jax.make_jaxpr`` output plus the AOT metadata of
the jitted entry (``.lower(...).args_info`` for donation flags,
``jax.eval_shape`` for output avals) — no device arrays are ever allocated,
so the whole pass runs on any backend in milliseconds.

Jaxprs nest: a jitted call is one ``pjit`` eqn whose body lives in
``eqn.params["jaxpr"]``; ``lax.scan`` bodies, ``cond``/``switch`` branches,
``while`` cond/body and ``pallas_call`` kernels likewise hang off eqn
params. :func:`iter_eqns` walks the whole tree and tracks whether the
current eqn sits inside a scan/while body — the "per step of the sampler
loop" context rules J005 cares about.
"""

from __future__ import annotations

import hashlib
from collections import Counter
from typing import Any, Iterator

import jax
import numpy as np

from ddim_cold_tpu.analysis.findings import Finding

#: dtypes on the wrong side of the f32-accumulate policy
_LOW_PRECISION = ("bfloat16", "float16")

#: eqn params that hold nested jaxprs. Values are either a (Closed)Jaxpr,
#: a list/tuple of them (cond/switch 'branches'), or something else entirely
#: (ignored).
_SUB_JAXPR_KEYS = ("jaxpr", "call_jaxpr", "fun_jaxpr", "branches",
                   "cond_jaxpr", "body_jaxpr")

#: primitives that re-enter the host every execution of their body/site
_HOST_CALLBACK_PRIMS = ("pure_callback", "io_callback", "debug_callback",
                        "outside_call", "host_callback_call", "callback")

#: primitives whose body executes once per carried step
_LOOP_PRIMS = ("scan", "while")


def _as_jaxprs(val) -> list:
    """Normalize an eqn-param value to a list of open Jaxprs."""
    vals = val if isinstance(val, (list, tuple)) else [val]
    out = []
    for v in vals:
        v = getattr(v, "jaxpr", v)  # ClosedJaxpr → Jaxpr
        if hasattr(v, "eqns"):
            out.append(v)
    return out


def iter_eqns(jaxpr, in_loop: bool = False) -> Iterator[tuple[Any, bool]]:
    """Yield ``(eqn, inside_loop_body)`` over ``jaxpr`` and every sub-jaxpr."""
    jaxpr = getattr(jaxpr, "jaxpr", jaxpr)
    for eqn in jaxpr.eqns:
        yield eqn, in_loop
        enters_loop = in_loop or eqn.primitive.name in _LOOP_PRIMS
        for key in _SUB_JAXPR_KEYS:
            if key in eqn.params:
                for sub in _as_jaxprs(eqn.params[key]):
                    yield from iter_eqns(sub, enters_loop)


def iter_consts(closed_jaxpr) -> Iterator[Any]:
    """Yield every constant captured by ``closed_jaxpr`` or a nested one."""
    yield from getattr(closed_jaxpr, "consts", ())
    for eqn, _ in iter_eqns(closed_jaxpr):
        for key in _SUB_JAXPR_KEYS:
            val = eqn.params.get(key)
            vals = val if isinstance(val, (list, tuple)) else [val]
            for v in vals:
                yield from getattr(v, "consts", ())


def _dtype_name(aval) -> str:
    return str(np.dtype(aval.dtype)) if hasattr(aval, "dtype") else "?"


# ---------------------------------------------------------------------------
# J001 — low-precision accumulation
# ---------------------------------------------------------------------------

def check_accumulation(closed_jaxpr, entry: str, path: str) -> list[Finding]:
    """Flag matmul/conv eqns that BOTH consume and produce low precision —
    i.e. traced without ``preferred_element_type=f32``, so the MXU
    accumulates in bf16. A low-precision *input* with an f32 *output* is the
    designed bf16-trunk/f32-accumulate pattern (ops/quant.py, flash kernel)
    and passes; so does a post-accumulation ``convert_element_type`` emit
    cast."""
    out, idx = [], Counter()
    for eqn, _ in iter_eqns(closed_jaxpr):
        prim = eqn.primitive.name
        if prim not in ("dot_general", "conv_general_dilated"):
            continue
        in_dts = [_dtype_name(v.aval) for v in eqn.invars[:2]]
        out_dt = _dtype_name(eqn.outvars[0].aval)
        idx[prim] += 1
        if any(d in _LOW_PRECISION for d in in_dts) and out_dt in _LOW_PRECISION:
            out.append(Finding(
                "GRAFT-J001", path, f"{entry}:{prim}#{idx[prim]}", 0,
                f"{prim} #{idx[prim]} in `{entry}` accumulates in {out_dt} "
                f"(inputs {'/'.join(in_dts)}) — trace it with "
                "preferred_element_type=float32 and cast at emit"))
    return out


# ---------------------------------------------------------------------------
# J002 — weak-typed outputs
# ---------------------------------------------------------------------------

def check_weak_types(out_shapes, entry: str, path: str) -> list[Finding]:
    """Weak-typed float outputs promote silently downstream and, fed back
    into a jitted callee, miss the cache a strong-typed aval populated —
    the recompile hazard."""
    out = []
    leaves = jax.tree_util.tree_leaves(out_shapes)
    for i, leaf in enumerate(leaves):
        if getattr(leaf, "weak_type", False):
            out.append(Finding(
                "GRAFT-J002", path, f"{entry}:out{i}", 0,
                f"output {i} of `{entry}` is weak-typed "
                f"{_dtype_name(leaf)}{tuple(leaf.shape)} — anchor it with an "
                "explicit jnp.asarray(..., dtype) before returning"))
    return out


# ---------------------------------------------------------------------------
# J003 — droppable donations
# ---------------------------------------------------------------------------

def check_donation(args_info, out_shapes, entry: str, path: str,
                   expect_donation: bool = True) -> list[Finding]:
    """XLA aliases a donated input only to an output with the identical
    (shape, dtype); anything else is silently dropped (the buffer is freed
    late and the donation buys nothing). Match the donated avals against the
    output avals as multisets — each output slot can absorb one donation."""
    donated = []
    for key_path, info in jax.tree_util.tree_flatten_with_path(args_info)[0]:
        if getattr(info, "donated", False):
            label = jax.tree_util.keystr(key_path)
            donated.append((label, tuple(info.shape), _dtype_name(info)))
    if expect_donation and not donated:
        return [Finding(
            "GRAFT-J003", path, f"{entry}:<none-donated>", 0,
            f"`{entry}` is expected to donate its carry buffers but lowered "
            "with zero donated inputs")]
    budget = Counter(
        (tuple(leaf.shape), _dtype_name(leaf))
        for leaf in jax.tree_util.tree_leaves(out_shapes))
    out = []
    for label, shape, dtype in donated:
        if budget[(shape, dtype)] > 0:
            budget[(shape, dtype)] -= 1
        else:
            out.append(Finding(
                "GRAFT-J003", path, f"{entry}:{label}", 0,
                f"donated arg {label} of `{entry}` ({dtype}{shape}) matches "
                "no remaining output aval — XLA drops the donation "
                "(jax warns at runtime; the buffer is never reused)"))
    return out


# ---------------------------------------------------------------------------
# J004 — oversized baked-in constants
# ---------------------------------------------------------------------------

def check_constants(closed_jaxpr, entry: str, path: str,
                    max_bytes: int = 1 << 20) -> list[Finding]:
    """Closure-captured arrays are baked into the compiled program: they
    occupy HBM per-executable and key the compile cache by VALUE, so a big
    one both bloats memory and poisons cache reuse. Coefficient tables are
    tiny; anything over ``max_bytes`` should be an argument instead."""
    out = []
    for i, const in enumerate(iter_consts(closed_jaxpr)):
        nbytes = getattr(const, "nbytes", None)
        if nbytes is None:
            size = int(np.prod(getattr(const, "shape", ()) or (1,)))
            itemsize = np.dtype(getattr(const, "dtype", np.float32)).itemsize
            nbytes = size * itemsize
        if nbytes > max_bytes:
            shape = tuple(getattr(const, "shape", ()))
            out.append(Finding(
                "GRAFT-J004", path, f"{entry}:const#{i}", 0,
                f"`{entry}` bakes in a {nbytes}-byte constant "
                f"(shape {shape}, threshold {max_bytes}) — pass it as an "
                "argument so the executable and the compile cache stay lean"))
    return out


# ---------------------------------------------------------------------------
# J005 — host callbacks in loop bodies
# ---------------------------------------------------------------------------

def check_host_callbacks(closed_jaxpr, entry: str, path: str) -> list[Finding]:
    """A callback primitive inside a scan/while body syncs the device to the
    host EVERY step — the exact serialization the scan samplers exist to
    avoid."""
    out, seen = [], set()
    for eqn, in_loop in iter_eqns(closed_jaxpr):
        prim = eqn.primitive.name
        if prim in _HOST_CALLBACK_PRIMS and in_loop and prim not in seen:
            seen.add(prim)
            out.append(Finding(
                "GRAFT-J005", path, f"{entry}:{prim}", 0,
                f"host callback `{prim}` inside the scanned body of "
                f"`{entry}` — every loop step round-trips to the host"))
    return out


# ---------------------------------------------------------------------------
# J007 — data-dependent trip counts in served programs
# ---------------------------------------------------------------------------

def check_static_trip_count(closed_jaxpr, entry: str,
                            path: str) -> list[Finding]:
    """A ``while`` primitive's trip count is decided by device data at run
    time — the one loop form that can differ between two executions of the
    same compiled program. Served sampler programs must be pure static-trip
    ``scan``: the adaptive drift gate picks a *branch index* inside the scan
    body (``lax.switch`` over a static branch set), so a gate-induced
    ``while`` here means the caching rewrite broke the
    one-program-per-(config, bucket) contract."""
    out, count = [], 0
    for eqn, _ in iter_eqns(closed_jaxpr):
        if eqn.primitive.name == "while":
            count += 1
    if count:
        out.append(Finding(
            "GRAFT-J007", path, f"{entry}:while", 0,
            f"`{entry}` lowers with {count} `while` eqn(s) — a "
            "data-dependent trip count in a served sampler; the drift gate "
            "must stay a branch select inside the static scan"))
    return out


# ---------------------------------------------------------------------------
# abstract trace signature (J006 building block — used by entries.py)
# ---------------------------------------------------------------------------

def signature_hash(closed_jaxpr, in_tree) -> str:
    """Hash of everything jit keys a compiled program on that we can see
    statically: the printed jaxpr (structure + primitive params) and the
    input avals. Two traces with equal hashes hit one executable; a hash
    that moves between two traces of the same entry predicts a serve-time
    recompile."""
    avals = ",".join(
        f"{_dtype_name(l)}{tuple(l.shape)}"
        for l in jax.tree_util.tree_leaves(in_tree)
        if hasattr(l, "shape"))
    blob = f"{closed_jaxpr}\n#avals={avals}".encode()
    return hashlib.sha256(blob).hexdigest()
