"""Block-shape autotuning for the fused sampler-trunk kernels at the
first-class 200px geometries.

The fused kernels (ops/flash_attention.fused_trunk_attention,
ops/quant.mlp_pallas) take block shapes the same way the unfused flash
kernel does — but the 200px geometries (f32/bf16 N=2501 for the p4 model,
bf16 N=626 for p8, the dual-dtype dequant K blocks) each have a different
P001-legal block space and a different VMEM frontier. This module:

* enumerates the LEGAL candidate space for each kernel family under exactly
  the rules graftcheck's kernels layer proves (ops/tiling.legal_block units,
  the double-buffered VMEM budget, the P003 padding-waste ceiling) — so a
  candidate that enumerates here cannot be rejected by Mosaic or flagged by
  ``graftcheck --only P`` later;
* scores candidates with a static cost model (prefer fewer grid steps —
  large kv blocks amortize the in-kernel k/v reprojection across a bigger
  MXU pass, large q/m blocks amortize weight staging — subject to the VMEM
  and waste ceilings);
* pins the winners into the committed :data:`TUNED_BLOCKS` table, keyed by
  ``(device kind, dtype name, geometry tag)``. Lookups for absent keys fall
  back to ``NS_FLASH_BLOCKS`` (attention) / the kernel defaults (mlp), so
  un-tuned devices and geometries keep working unchanged;
* offers :func:`autotune_attn` / :func:`autotune_mlp` — on-device timing
  sweeps over the legal space — for regenerating the table in a hardware
  window (``python -m ddim_cold_tpu.ops.tuning`` prints the static sweep).

Provenance: the committed entries are STATIC-model picks (this module run on
CPU — see PERF.md "Fused kernels"); a chip-armed bench window re-ranks them
with ``autotune_*`` and any change lands as a table diff with the timing
evidence attached.

Constants ``WASTE_THRESHOLD``/``PIPELINE_BUFFERS``/``DEVICE_KIND`` mirror
analysis/kernel_checks.py (the P-rules); tests/test_fusion.py pins them
equal so the enumerator and the verifier cannot drift apart.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from ddim_cold_tpu.ops import tiling
from ddim_cold_tpu.utils import flops as flops_util

#: default device the committed table is tuned for (the bench chip) —
#: mirrors analysis/kernel_checks.DEVICE_KIND (pinned by tests/test_fusion)
DEVICE_KIND = "TPU v5 lite"
#: padding-waste ceiling, mirrors kernel_checks.WASTE_THRESHOLD (P003)
WASTE_THRESHOLD = 1.25
#: pipeline double-buffering factor, mirrors kernel_checks.PIPELINE_BUFFERS
PIPELINE_BUFFERS = 2

_F32 = 4


def _itemsize(dtype) -> int:
    return int(np.dtype(dtype).itemsize)


def attn_geometry(n: int, c: int, heads: int) -> str:
    """Geometry tag for a fused-attention problem (tokens, embed, heads)."""
    return f"attn_n{n}_c{c}_h{heads}"


def mlp_geometry(c: int, hidden: int, *, quant: bool = True) -> str:
    """Geometry tag for a fused-Mlp problem (embed, hidden width). The
    weight layout is part of the geometry: int8 weights (``mlp_``) stage
    4× (f32) / 2× (bf16) smaller blocks than float weights (``mlpf_``), so
    the two layouts have different VMEM frontiers and tuned block_m."""
    return f"{'mlp' if quant else 'mlpf'}_c{c}_h{hidden}"


def dequant_geometry(m: int, k: int, n: int) -> str:
    """Geometry tag for a standalone dequant-matmul problem."""
    return f"dequant_m{m}_k{k}_n{n}"


# ---------------------------------------------------------------------------
# static VMEM models — mirror the kernels' scratch/block arithmetic exactly
# ---------------------------------------------------------------------------

def attn_vmem_bytes(bq: int, bkv: int, c: int, heads: int, act_dtype,
                    *, qkv_bias: bool = True,
                    compute_dtype=None) -> int:
    """Per-program VMEM footprint of ``fused_trunk_attention`` at blocks
    (bq, bkv): in/out blocks × PIPELINE_BUFFERS plus the scratch arrays —
    the same accounting graftcheck P002 applies to the kernel entry."""
    act = _itemsize(act_dtype)
    cdt = _itemsize(compute_dtype if compute_dtype is not None else act_dtype)
    blocks = (bq * c * act            # x_q
              + bkv * c * act        # x_kv
              + c * 3 * c            # w_qkv int8
              + 3 * c * _F32         # s_qkv
              + (3 * c * _F32 if qkv_bias else 0)
              + c * c                # w_proj int8
              + c * _F32             # s_proj
              + bq * c * _F32)       # out (f32)
    scratch = (bq * c * cdt          # projected q
               + bq * c * _F32      # output accumulator
               + 2 * heads * bq * tiling.LANE * _F32)  # running max / denom
    return PIPELINE_BUFFERS * blocks + scratch


def mlp_vmem_bytes(bm: int, k: int, hidden: int, nout: int, act_dtype,
                   *, quant: bool = True) -> int:
    """Per-program VMEM footprint of ``mlp_pallas`` at M-block ``bm``."""
    act = _itemsize(act_dtype)
    w = 1 if quant else act  # float weights are staged at the act dtype
    blocks = (bm * k * act
              + k * hidden * w + hidden * _F32       # w1 (+ b1)
              + (hidden * _F32 if quant else 0)      # s1
              + hidden * nout * w
              + (nout * _F32 if quant else 0)        # s2
              + bm * nout * _F32)                    # out (f32)
    scratch = bm * hidden * _F32
    return PIPELINE_BUFFERS * blocks + scratch


def dequant_vmem_bytes(bm: int, bn: int, bk: int, act_dtype) -> int:
    """Per-program VMEM footprint of ``_dequant_matmul_pallas``."""
    act = _itemsize(act_dtype)
    blocks = bm * bk * act + bk * bn + bn * _F32 + bm * bn * _F32
    return PIPELINE_BUFFERS * blocks + bm * bn * _F32


# ---------------------------------------------------------------------------
# legal candidate enumeration (the P001/P002/P003 space)
# ---------------------------------------------------------------------------

def _waste_ok(n: int, block: int) -> bool:
    return tiling.round_up(n, block) / n <= WASTE_THRESHOLD


def _seq_block_candidates(n: int, dtype) -> list[int]:
    """Legal sequence-axis block sizes for an array dim of ``n``: every
    unit-multiple up to the unit-padded dim (the single-block case last)."""
    unit = tiling.sublane_unit(dtype)
    full = tiling.round_up(n, unit)
    out = []
    b = unit
    while b < full:
        if _waste_ok(n, b):
            out.append(b)
        b += unit
    out.append(full)  # single block spans the (unit-padded) dim
    return out


def attn_candidates(n: int, c: int, heads: int, act_dtype, *,
                    device_kind: str = DEVICE_KIND, qkv_bias: bool = True,
                    compute_dtype=None) -> list[tuple[int, int]]:
    """All (block_q, block_kv) pairs legal for ``fused_trunk_attention`` at
    this geometry: tile-unit multiples (P001), padding waste ≤ 1.25 on both
    sequence paddings (P003), double-buffered VMEM within the device budget
    (P002)."""
    budget = flops_util.vmem_bytes(device_kind) or (16 << 20)
    cands = []
    for bq in _seq_block_candidates(n, act_dtype):
        for bkv in _seq_block_candidates(n, act_dtype):
            if attn_vmem_bytes(bq, bkv, c, heads, act_dtype,
                               qkv_bias=qkv_bias,
                               compute_dtype=compute_dtype) <= budget:
                cands.append((bq, bkv))
    return cands


def mlp_candidates(m: int, k: int, hidden: int, nout: int, act_dtype, *,
                   device_kind: str = DEVICE_KIND,
                   quant: bool = True) -> list[int]:
    """All legal ``block_m`` values for ``mlp_pallas`` at this geometry."""
    budget = flops_util.vmem_bytes(device_kind) or (16 << 20)
    return [bm for bm in _seq_block_candidates(m, act_dtype)
            if mlp_vmem_bytes(bm, k, hidden, nout, act_dtype,
                              quant=quant) <= budget]


def dequant_candidates(m: int, k: int, n: int, act_dtype, *,
                       device_kind: str = DEVICE_KIND,
                       steps=(128, 256, 512, 1024, 2048)
                       ) -> list[tuple[int, int, int]]:
    """Legal (block_m, block_n, block_k) triples for the dequant matmul —
    the K axis is the dual-dtype case: the activation's LANE dim and the
    int8 weight's SUBLANE dim must both divide the one block
    (tiling.legal_block min_unit=jnp.int8)."""
    import jax.numpy as jnp

    budget = flops_util.vmem_bytes(device_kind) or (16 << 20)
    cands = []
    bms = sorted({tiling.legal_block(s, m, act_dtype) for s in steps})
    bns = sorted({tiling.legal_block(s, n, jnp.float32, lane=True)
                  for s in steps})
    bks = sorted({tiling.legal_block(s, k, act_dtype, lane=True,
                                     min_unit=jnp.int8) for s in steps})
    for bm in bms:
        if not _waste_ok(m, bm):
            continue
        for bn in bns:
            for bk in bks:
                if dequant_vmem_bytes(bm, bn, bk, act_dtype) <= budget:
                    cands.append((bm, bn, bk))
    return cands


# ---------------------------------------------------------------------------
# static cost model + committed table
# ---------------------------------------------------------------------------

def pick_attn(n: int, c: int, heads: int, act_dtype, *,
              device_kind: str = DEVICE_KIND, qkv_bias: bool = True,
              compute_dtype=None) -> Optional[tuple[int, int]]:
    """Static pick: the in-kernel k/v reprojection costs one (bkv·C·2C) GEMM
    per (q-block, kv-chunk), so total reprojection work scales with the
    number of q blocks — maximize block_q first, then block_kv (fewer
    sequential chunks per q block), both inside the legal space."""
    cands = attn_candidates(n, c, heads, act_dtype,
                            device_kind=device_kind, qkv_bias=qkv_bias,
                            compute_dtype=compute_dtype)
    if not cands:
        return None
    n_q = lambda bq: tiling.round_up(n, bq) // bq  # noqa: E731
    n_kv = lambda bkv: tiling.round_up(n, bkv) // bkv  # noqa: E731
    return min(cands, key=lambda bqkv: (n_q(bqkv[0]), n_kv(bqkv[1]),
                                        -bqkv[0], -bqkv[1]))


def pick_mlp(m: int, k: int, hidden: int, nout: int, act_dtype, *,
             device_kind: str = DEVICE_KIND, quant: bool = True
             ) -> Optional[int]:
    """Static pick: largest legal M block — fewest weight-block revisits."""
    cands = mlp_candidates(m, k, hidden, nout, act_dtype,
                           device_kind=device_kind, quant=quant)
    return max(cands) if cands else None


#: committed tuned blocks, keyed (device kind, dtype name, geometry tag).
#: Values: attention (block_q, block_kv); mlp (block_m,); dequant
#: (block_m, block_n, block_k). Static-model picks over the P001-legal
#: space (regenerate: ``python -m ddim_cold_tpu.ops.tuning``); absent keys
#: fall back to NS_FLASH_BLOCKS / kernel defaults (see lookup_*). The int8
#: rows are the w8a8 activations (weights are int8 in every fused row).
TUNED_BLOCKS: dict[tuple[str, str, str], tuple[int, ...]] = {
    # 200px/p4 north-star trunk (N=2501, C=256, H=4) — f32, bf16, w8a8
    ("TPU v5 lite", "float32", "attn_n2501_c256_h4"): (1328, 1288),
    ("TPU v5 lite", "bfloat16", "attn_n2501_c256_h4"): (1552, 2512),
    ("TPU v5 lite", "int8", "attn_n2501_c256_h4"): (1536, 2528),
    # 200px/p8 trunk (N=626, C=384, H=12) — single-block on both axes
    ("TPU v5 lite", "float32", "attn_n626_c384_h12"): (632, 632),
    ("TPU v5 lite", "bfloat16", "attn_n626_c384_h12"): (640, 640),
    ("TPU v5 lite", "int8", "attn_n626_c384_h12"): (640, 640),
    # fused Mlp at the sampler's flattened row count (16 rows × 2501 tokens)
    ("TPU v5 lite", "float32", "mlp_c256_h256"): (3224,),
    ("TPU v5 lite", "bfloat16", "mlp_c256_h256"): (4016,),
    ("TPU v5 lite", "int8", "mlp_c256_h256"): (4576,),
    ("TPU v5 lite", "float32", "mlp_c384_h384"): (2104,),
    ("TPU v5 lite", "bfloat16", "mlp_c384_h384"): (2624,),
    ("TPU v5 lite", "int8", "mlp_c384_h384"): (3008,),
    # float-weight Mlp (quant=None): weight blocks are 4×/2× larger than the
    # int8 rows above, so the VMEM frontier sits at a smaller block_m
    ("TPU v5 lite", "float32", "mlpf_c256_h256"): (3064,),
    ("TPU v5 lite", "bfloat16", "mlpf_c256_h256"): (3952,),
    ("TPU v5 lite", "float32", "mlpf_c384_h384"): (1872,),
    ("TPU v5 lite", "bfloat16", "mlpf_c384_h384"): (2528,),
    # standalone dequant matmul at the 200px qkv/proj shapes (provenance for
    # the _dequant_matmul_pallas defaults; the dual-dtype K legality case)
    ("TPU v5 lite", "bfloat16", "dequant_m40016_k256_n768"): (2048, 512, 256),
    ("TPU v5 lite", "bfloat16", "dequant_m40016_k256_n256"): (2048, 256, 256),
}


def lookup(device_kind: str, dtype, geometry: str
           ) -> Optional[tuple[int, ...]]:
    """Tuned blocks for (device kind, dtype, geometry), or None. The device
    kind is prefix-matched like utils/flops peak tables (a 'TPU v5 lite'
    entry serves 'TPU v5 lite core …' kinds)."""
    name = str(np.dtype(dtype))
    best = None
    for (kind, dt, geom), blocks in TUNED_BLOCKS.items():
        if dt == name and geom == geometry and device_kind.startswith(kind):
            if best is None or len(kind) > best[0]:
                best = (len(kind), blocks)
    return best[1] if best else None


def _local_device_kind() -> str:
    try:
        import jax

        return jax.devices()[0].device_kind
    except Exception:  # noqa: BLE001 — no backend at all
        return "cpu"


def attn_blocks(n: int, c: int, heads: int, act_dtype, *,
                device_kind: Optional[str] = None) -> tuple[int, int]:
    """(block_q, block_kv) for a fused-attention problem: the tuned entry
    when the (device, dtype, geometry) key is present, else the
    ``NS_FLASH_BLOCKS`` fallback (which legal_block clamps to this N)."""
    from ddim_cold_tpu.ops.flash_attention import NS_FLASH_BLOCKS

    kind = device_kind if device_kind is not None else _local_device_kind()
    tuned = lookup(kind, act_dtype, attn_geometry(n, c, heads))
    if tuned is not None and len(tuned) == 2:
        return (int(tuned[0]), int(tuned[1]))
    return NS_FLASH_BLOCKS


def mlp_block_m(c: int, hidden: int, act_dtype, *,
                quant: bool = True, device_kind: Optional[str] = None,
                default: int = 256) -> int:
    """block_m for a fused-Mlp problem; kernel default when un-tuned.
    ``quant`` selects the weight-layout half of the geometry key (int8 vs
    float weights — see mlp_geometry)."""
    kind = device_kind if device_kind is not None else _local_device_kind()
    tuned = lookup(kind, act_dtype, mlp_geometry(c, hidden, quant=quant))
    if tuned is not None and len(tuned) == 1:
        return int(tuned[0])
    return default


# ---------------------------------------------------------------------------
# on-device timing sweeps (regenerate TUNED_BLOCKS in a hardware window)
# ---------------------------------------------------------------------------

def _time_fn(fn, *args, iters: int = 10) -> float:
    import time

    import jax

    out = fn(*args)
    jax.block_until_ready(out)  # compile + warm
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(*args)
    jax.block_until_ready(out)
    return (time.perf_counter() - t0) / iters


def autotune_attn(batch: int, n: int, c: int, heads: int, act_dtype, *,
                  mode: str = "pallas", iters: int = 10) -> list[dict]:
    """Time ``fused_trunk_attention`` over the legal candidate space on the
    LOCAL device; returns candidates sorted fastest-first. Meant for a TPU
    window — on CPU the interpreter timing is not meaningful (the static
    pick stands in; see TUNED_BLOCKS provenance)."""
    import jax
    import jax.numpy as jnp

    from ddim_cold_tpu.ops import flash_attention as fa

    kind = _local_device_kind()
    cdt = jnp.dtype(act_dtype) if mode != "w8a8" else jnp.float32
    xdt = jnp.int8 if mode == "w8a8" else cdt
    key = jax.random.PRNGKey(0)
    x = jax.random.normal(key, (batch, n, c), jnp.float32).astype(cdt)
    w_qkv = jax.random.randint(key, (c, 3 * c), -127, 128, jnp.int8)
    w_proj = jax.random.randint(key, (c, c), -127, 128, jnp.int8)
    s_qkv = jnp.full((3 * c,), 1e-2, jnp.float32)
    s_proj = jnp.full((c,), 1e-2, jnp.float32)
    b = jnp.zeros((3 * c,), jnp.float32)
    bp = jnp.zeros((c,), jnp.float32)
    results = []
    for bq, bkv in attn_candidates(n, c, heads, xdt, device_kind=kind,
                                   compute_dtype=cdt):
        fn = jax.jit(lambda xx, _bq=bq, _bkv=bkv: fa.fused_trunk_attention(
            xx, w_qkv, s_qkv, b, w_proj, s_proj, bp, num_heads=heads,
            scale=(c // heads) ** -0.5, block_q=_bq, block_kv=_bkv,
            mode=mode))
        results.append({"block_q": bq, "block_kv": bkv,
                        "seconds": _time_fn(fn, x, iters=iters)})
    return sorted(results, key=lambda r: r["seconds"])


def autotune_mlp(m: int, k: int, hidden: int, act_dtype, *,
                 mode: Optional[str] = "pallas", iters: int = 10
                 ) -> list[dict]:
    """Time ``mlp_pallas`` over the legal block_m space on the LOCAL device;
    fastest first. Same hardware-window caveat as autotune_attn."""
    import jax
    import jax.numpy as jnp

    from ddim_cold_tpu.ops import quant as q

    kind = _local_device_kind()
    cdt = jnp.dtype(act_dtype)
    xdt = jnp.int8 if mode == "w8a8" else cdt
    key = jax.random.PRNGKey(0)
    x = jax.random.normal(key, (m, k), jnp.float32).astype(cdt)
    if mode is None:
        w1 = jax.random.normal(key, (k, hidden), jnp.float32)
        w2 = jax.random.normal(key, (hidden, k), jnp.float32)
        s1 = s2 = None
    else:
        w1 = jax.random.randint(key, (k, hidden), -127, 128, jnp.int8)
        w2 = jax.random.randint(key, (hidden, k), -127, 128, jnp.int8)
        s1 = jnp.full((hidden,), 1e-2, jnp.float32)
        s2 = jnp.full((k,), 1e-2, jnp.float32)
    b1 = jnp.zeros((hidden,), jnp.float32)
    b2 = jnp.zeros((k,), jnp.float32)
    results = []
    for bm in mlp_candidates(m, k, hidden, k, xdt, device_kind=kind,
                             quant=mode is not None):
        fn = jax.jit(lambda xx, _bm=bm: q.mlp_pallas(
            xx, w1, b1, w2, b2, scale1=s1, scale2=s2, mode=mode,
            block_m=_bm))
        results.append({"block_m": bm,
                        "seconds": _time_fn(fn, x, iters=iters)})
    return sorted(results, key=lambda r: r["seconds"])


def _main() -> None:  # pragma: no cover — table-regeneration helper
    """Print the static picks for every committed geometry (the TUNED_BLOCKS
    provenance): ``python -m ddim_cold_tpu.ops.tuning``."""
    import jax.numpy as jnp

    rows = 16  # analysis/entries.NS_ROWS
    geoms = [(2501, 256, 4), (626, 384, 12)]
    for n, c, h in geoms:
        for dt in (jnp.float32, jnp.bfloat16, jnp.int8):
            cdt = jnp.float32 if dt == jnp.int8 else dt
            print(attn_geometry(n, c, h), np.dtype(dt),
                  pick_attn(n, c, h, dt, compute_dtype=cdt))
        for dt in (jnp.float32, jnp.bfloat16, jnp.int8):
            print(mlp_geometry(c, c), np.dtype(dt),
                  pick_mlp(rows * n, c, c, c, dt))
        for dt in (jnp.float32, jnp.bfloat16):  # float weights: no int8 act
            print(mlp_geometry(c, c, quant=False), np.dtype(dt),
                  pick_mlp(rows * n, c, c, c, dt, quant=False))
    for nout in (768, 256):
        cands = dequant_candidates(rows * 2501, 256, nout, jnp.bfloat16)
        print(dequant_geometry(rows * 2501, 256, nout),
              "bfloat16", max(cands) if cands else None)


if __name__ == "__main__":  # pragma: no cover
    _main()
