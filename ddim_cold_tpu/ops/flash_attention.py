"""Fused softmax-attention Pallas kernel for TPU (the long-sequence hot op).

The reference's attention is three separate cuDNN GEMMs with an O(N²) f32
attention matrix materialized in HBM (ViT.py:110-114). Here the whole
``softmax(q·kᵀ·scale)·v`` is one Pallas kernel: a grid over (batch·heads,
query blocks) where each program streams its K/V through VMEM, so the logits
never round-trip to HBM. For the in-repo configs (N ≤ 2501: the 200px/p4
model) K/V for one head fit VMEM whole, giving a single-pass masked softmax
per query block — the MXU sees two back-to-back GEMMs.

Autodiff: forward is the kernel; backward is a custom VJP that recomputes the
attention matrix with plain XLA einsums (flash-style recompute — O(N²) HBM
only under ``grad``, which the training path only hits with dropout disabled;
with attention dropout active the model falls back to the einsum path anyway).

On non-TPU backends the kernel runs in interpreter mode, so tests exercise the
identical code path on CPU.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

_NEG_INF = -1e30
_LANE = 128  # TPU lane width: last dim of VMEM tiles


def _attention_kernel(q_ref, k_ref, v_ref, o_ref, *, scale: float, n_valid: int):
    """One (head, query-block) program: out = softmax(mask(q·kᵀ))·v in f32."""
    q = q_ref[0].astype(jnp.float32)  # (bq, D)
    k = k_ref[0].astype(jnp.float32)  # (N, D)
    logits = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    ) * scale  # (bq, N)
    col = jax.lax.broadcasted_iota(jnp.int32, logits.shape, 1)
    logits = jnp.where(col < n_valid, logits, _NEG_INF)
    m = jnp.max(logits, axis=-1, keepdims=True)
    p = jnp.exp(logits - m)
    out = jnp.dot(p, v_ref[0].astype(jnp.float32), preferred_element_type=jnp.float32)
    o_ref[0] = (out / jnp.sum(p, axis=-1, keepdims=True)).astype(o_ref.dtype)


def _pad_to(x: jax.Array, axis: int, multiple: int) -> jax.Array:
    pad = (-x.shape[axis]) % multiple
    if pad == 0:
        return x
    widths = [(0, 0)] * x.ndim
    widths[axis] = (0, pad)
    return jnp.pad(x, widths)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    scale: float,
    block_q: int = 256,
) -> jax.Array:
    """Fused non-causal multi-head attention.

    q/k/v: ``(B, N, H, D)`` (the model's head layout, ViT.py:104-107);
    returns ``(B, N, H, D)`` in q's dtype. Softmax runs in float32 regardless
    of input dtype, matching the einsum path bit-for-bit up to GEMM precision.
    """
    return _flash_forward(q, k, v, scale, block_q)


def _flash_forward(q, k, v, scale, block_q):
    # Interpreter mode exists so CPU tests exercise the kernel path; on any
    # other non-TPU backend (e.g. GPU) interpreting would be a silent
    # orders-of-magnitude slowdown — use the dense einsum instead.
    backend = jax.default_backend()
    if backend not in ("tpu", "cpu"):
        return _dense_attention_f32(q, k, v, scale)[1].astype(q.dtype)

    B, N, H, D = q.shape
    # (B, N, H, D) → (B·H, N, D): each grid row owns one head's sequence.
    def to_heads(x):
        x = x.transpose(0, 2, 1, 3).reshape(B * H, N, D)
        # lane-align the head dim (zero columns are inert in q·kᵀ and produce
        # zero output columns, sliced off below) and sublane-align N.
        x = _pad_to(x, 2, _LANE)
        return _pad_to(x, 1, 8)

    qh, kh, vh = to_heads(q), to_heads(k), to_heads(v)
    BH, Np, Dp = qh.shape
    bq = min(block_q, Np)
    qh = _pad_to(qh, 1, bq)
    grid = (BH, qh.shape[1] // bq)

    kernel = functools.partial(_attention_kernel, scale=scale, n_valid=N)
    out = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, bq, Dp), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, Np, Dp), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, Np, Dp), lambda b, i: (b, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, bq, Dp), lambda b, i: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct(qh.shape, q.dtype),
        interpret=backend == "cpu",
    )(qh, kh, vh)

    out = out[:, :N, :D].reshape(B, H, N, D).transpose(0, 2, 1, 3)
    return out


def _dense_attention_f32(q, k, v, scale):
    """XLA-einsum oracle/backward path, f32 accumulation (ViT.py:110-114)."""
    logits = jnp.einsum(
        "bnhd,bmhd->bhnm", q.astype(jnp.float32), k.astype(jnp.float32)
    ) * scale
    p = jax.nn.softmax(logits, axis=-1)
    return p, jnp.einsum("bhnm,bmhd->bnhd", p, v.astype(jnp.float32))


def _flash_fwd(q, k, v, scale, block_q):
    return _flash_forward(q, k, v, scale, block_q), (q, k, v)


def _flash_bwd(scale, block_q, residuals, g):
    q, k, v = residuals
    p, _ = _dense_attention_f32(q, k, v, scale)  # recompute (flash-style)
    gf = g.astype(jnp.float32)
    dv = jnp.einsum("bhnm,bnhd->bmhd", p, gf)
    dp = jnp.einsum("bnhd,bmhd->bhnm", gf, v.astype(jnp.float32))
    ds = p * (dp - jnp.sum(dp * p, axis=-1, keepdims=True))
    dq = jnp.einsum("bhnm,bmhd->bnhd", ds, k.astype(jnp.float32)) * scale
    dk = jnp.einsum("bhnm,bnhd->bmhd", ds, q.astype(jnp.float32)) * scale
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


flash_attention.defvjp(_flash_fwd, _flash_bwd)
