"""Fused softmax-attention Pallas kernel for TPU (the long-sequence hot op).

The reference's attention is three separate cuDNN GEMMs with an O(N²) f32
attention matrix materialized in HBM (ViT.py:110-114). Here the whole
``softmax(q·kᵀ·scale)·v`` is one Pallas kernel: a grid over (batch·heads,
query blocks, K/V blocks) where each program streams one K/V chunk through
VMEM and folds it into a running (max, denominator, accumulator) triple —
the classic flash-attention online softmax. VMEM usage is bounded by the
block sizes, not the sequence length, so the kernel scales past the in-repo
worst case (N=2501, the 200px/p4 model) to genuinely long sequences; the
logits never round-trip to HBM and the MXU sees two GEMMs per chunk.

The K/V grid axis is innermost: TPU grids execute sequentially, so the VMEM
scratch accumulators carry across the chunks of one (head, q-block) and are
re-initialized when the chunk index wraps to 0.

Autodiff: forward is the kernel; backward is a custom VJP that recomputes the
attention matrix with plain XLA einsums (flash-style recompute). The
recompute bound: backward materializes the O(N²) probability matrix in HBM —
fine through N≈8k on a 16GB chip (N=8192, B·H=48 ⇒ ~12GB transient at f32,
XLA usually fuses it smaller); past that, shard the sequence instead (ring
attention, parallel/ring_attention.py, whose backward is blocked by
construction). The training path only hits this VJP with attention dropout
disabled — with dropout active the model falls back to the einsum path anyway.

On non-TPU backends the kernel runs in interpreter mode, so tests exercise the
identical code path on CPU.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_NEG_INF = -1e30
_LANE = 128  # TPU lane width: last dim of VMEM tiles


def _attention_kernel(q_ref, k_ref, v_ref, o_ref, acc_ref, m_ref, l_ref, *,
                      scale: float, n_valid: int, block_kv: int, n_kv: int):
    """One (head, q-block, kv-block) program: fold this K/V chunk into the
    running softmax state; emit o = acc/l on the last chunk."""
    kv_i = pl.program_id(2)

    @pl.when(kv_i == 0)
    def _init():
        m_ref[...] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)

    q = q_ref[0].astype(jnp.float32)  # (bq, D)
    k = k_ref[0].astype(jnp.float32)  # (bkv, D)
    logits = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    ) * scale  # (bq, bkv)
    col = kv_i * block_kv + jax.lax.broadcasted_iota(jnp.int32, logits.shape, 1)
    logits = jnp.where(col < n_valid, logits, _NEG_INF)

    # online softmax update (the same math the ring-attention steps use,
    # parallel/ring_attention.py:62-71, here per VMEM chunk)
    m_prev = jnp.max(m_ref[...], axis=-1, keepdims=True)  # (bq, 1) replicated
    l_prev = jnp.max(l_ref[...], axis=-1, keepdims=True)
    m_new = jnp.maximum(m_prev, jnp.max(logits, axis=-1, keepdims=True))
    alpha = jnp.exp(m_prev - m_new)
    p = jnp.exp(logits - m_new)  # (bq, bkv)
    l_new = alpha * l_prev + jnp.sum(p, axis=-1, keepdims=True)
    pv = jnp.dot(p, v_ref[0].astype(jnp.float32),
                 preferred_element_type=jnp.float32)
    acc_ref[...] = acc_ref[...] * alpha + pv
    m_ref[...] = jnp.broadcast_to(m_new, m_ref.shape)
    l_ref[...] = jnp.broadcast_to(l_new, l_ref.shape)

    @pl.when(kv_i == n_kv - 1)
    def _emit():
        l = jnp.max(l_ref[...], axis=-1, keepdims=True)
        o_ref[0] = (acc_ref[...] / l).astype(o_ref.dtype)


def _pad_to(x: jax.Array, axis: int, multiple: int) -> jax.Array:
    pad = (-x.shape[axis]) % multiple
    if pad == 0:
        return x
    widths = [(0, 0)] * x.ndim
    widths[axis] = (0, pad)
    return jnp.pad(x, widths)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    scale: float,
    block_q: int = 256,
    block_kv: int = 512,
) -> jax.Array:
    """Fused non-causal multi-head attention.

    q/k/v: ``(B, N, H, D)`` (the model's head layout, ViT.py:104-107);
    returns ``(B, N, H, D)`` in q's dtype. Softmax runs in float32 regardless
    of input dtype, matching the einsum path bit-for-bit up to GEMM precision.
    VMEM per program ≈ (block_q + 2·block_kv)·D_padded input tiles plus the
    f32 accumulator — independent of N.
    """
    return _flash_forward(q, k, v, scale, block_q, block_kv)


def _flash_forward(q, k, v, scale, block_q, block_kv):
    # Interpreter mode exists so CPU tests exercise the kernel path; on any
    # other non-TPU backend (e.g. GPU) interpreting would be a silent
    # orders-of-magnitude slowdown — use the dense einsum instead.
    backend = jax.default_backend()
    if backend not in ("tpu", "cpu"):
        return _dense_attention_f32(q, k, v, scale)[1].astype(q.dtype)

    B, N, H, D = q.shape
    # (B, N, H, D) → (B·H, N, D): each grid row owns one head's sequence.
    def to_heads(x):
        x = x.transpose(0, 2, 1, 3).reshape(B * H, N, D)
        # lane-align the head dim (zero columns are inert in q·kᵀ and produce
        # zero output columns, sliced off below) and sublane-align N.
        x = _pad_to(x, 2, _LANE)
        return _pad_to(x, 1, 8)

    qh, kh, vh = to_heads(q), to_heads(k), to_heads(v)
    BH, Np, Dp = qh.shape
    bq = min(block_q, Np)
    bkv = min(block_kv, Np)
    qh = _pad_to(qh, 1, bq)
    kh, vh = _pad_to(kh, 1, bkv), _pad_to(vh, 1, bkv)
    n_kv = kh.shape[1] // bkv
    grid = (BH, qh.shape[1] // bq, n_kv)

    kernel = functools.partial(_attention_kernel, scale=scale, n_valid=N,
                               block_kv=bkv, n_kv=n_kv)
    out = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, bq, Dp), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, bkv, Dp), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, bkv, Dp), lambda b, i, j: (b, j, 0)),
        ],
        out_specs=pl.BlockSpec((1, bq, Dp), lambda b, i, j: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct(qh.shape, q.dtype),
        scratch_shapes=[
            pltpu.VMEM((bq, Dp), jnp.float32),    # output accumulator
            pltpu.VMEM((bq, _LANE), jnp.float32),  # running max (lane-replicated)
            pltpu.VMEM((bq, _LANE), jnp.float32),  # running denominator
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        interpret=backend == "cpu",
    )(qh, kh, vh)

    out = out[:, :N, :D].reshape(B, H, N, D).transpose(0, 2, 1, 3)
    return out


def _dense_attention_f32(q, k, v, scale):
    """XLA-einsum oracle/backward path, f32 accumulation (ViT.py:110-114)."""
    logits = jnp.einsum(
        "bnhd,bmhd->bhnm", q.astype(jnp.float32), k.astype(jnp.float32)
    ) * scale
    p = jax.nn.softmax(logits, axis=-1)
    return p, jnp.einsum("bhnm,bmhd->bnhd", p, v.astype(jnp.float32))


def _flash_fwd(q, k, v, scale, block_q, block_kv):
    return _flash_forward(q, k, v, scale, block_q, block_kv), (q, k, v)


def _flash_bwd(scale, block_q, block_kv, residuals, g):
    q, k, v = residuals
    p, _ = _dense_attention_f32(q, k, v, scale)  # recompute (flash-style)
    gf = g.astype(jnp.float32)
    dv = jnp.einsum("bhnm,bnhd->bmhd", p, gf)
    dp = jnp.einsum("bnhd,bmhd->bhnm", gf, v.astype(jnp.float32))
    ds = p * (dp - jnp.sum(dp * p, axis=-1, keepdims=True))
    dq = jnp.einsum("bhnm,bmhd->bnhd", ds, k.astype(jnp.float32)) * scale
    dk = jnp.einsum("bhnm,bnhd->bmhd", ds, q.astype(jnp.float32)) * scale
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


flash_attention.defvjp(_flash_fwd, _flash_bwd)
