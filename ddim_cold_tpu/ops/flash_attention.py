"""Fused softmax-attention Pallas kernels for TPU (the long-sequence hot op).

The reference's attention is three separate cuDNN GEMMs with an O(N²) f32
attention matrix materialized in HBM (ViT.py:110-114). Here the whole
``softmax(q·kᵀ·scale)·v`` is one Pallas kernel: a grid over (batch·heads,
query blocks, K/V blocks) where each program streams one K/V chunk through
VMEM and folds it into a running (max, denominator, accumulator) triple —
the classic flash-attention online softmax. VMEM usage is bounded by the
block sizes, not the sequence length, so the kernel scales past the in-repo
worst case (N=2501, the 200px/p4 model) to genuinely long sequences; the
logits never round-trip to HBM and the MXU sees two GEMMs per chunk.

The K/V grid axis is innermost: TPU grids execute sequentially, so the VMEM
scratch accumulators carry across the chunks of one (head, q-block) and are
re-initialized when the chunk index wraps to 0.

Autodiff: the custom VJP is flash all the way through. The forward kernel
additionally emits the per-row log-sum-exp; the backward runs two more Pallas
kernels — dq (grid like the forward) and dk/dv (grid transposed: K/V blocks
outer, q chunks streamed innermost) — that rebuild probabilities from the
saved lse chunk by chunk, so the O(N²) matrix never exists in HBM in either
direction. Residuals are (q, k, v, o, lse): O(N·D) — the whole train-step
memory story for long sequences is bounded. (In-kernel, lse rides a
128-lane-replicated layout because TPU tiling rejects (1, bq) row blocks;
the replication is sliced off / re-broadcast outside the kernels so the
residual itself stays one lane. See _fwd_kernel._emit.)

On non-TPU backends the kernels run in interpreter mode, so tests exercise
the identical code paths on CPU (GPU falls back to the dense einsum).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ddim_cold_tpu.ops import tiling
from ddim_cold_tpu.utils import profiling

_NEG_INF = -1e30
_LANE = 128  # TPU lane width: last dim of VMEM tiles

#: Pallas-TPU compiler params across jax versions (renamed from
#: TPUCompilerParams to CompilerParams; same fields we use)
_CompilerParams = getattr(pltpu, "CompilerParams", None) or pltpu.TPUCompilerParams

#: kernel revision stamped into bench records (scripts/r05_stage_done.py keys
#: re-measurement off it): "bf16-gemm-v2" = GEMMs in input dtype with f32 MXU
#: accumulation (the r05 change); "fused-trunk-v3" adds the quant-aware fused
#: trunk attention (qkv dequant-GEMM as in-kernel producer, proj GEMM as
#: in-kernel consumer — see :func:`fused_trunk_attention`). The unfused
#: kernels are untouched by v3: their numerics are bit-identical to v2.
KERNEL_REV = "fused-trunk-v3"

#: tuned (block_q, block_kv) for the N=2501 north-star flash leg: the r05
#: on-chip sweep put full-sequence kv blocks ahead of streamed ones (512×4096:
#: 7.48 img/s vs 5.78 at the 256×512 default, old f32-GEMM kernel). The
#: kernel clamps block_kv to the padded sequence (2504 here) at runtime, so
#: any ≥N entry is the same single-chunk config. Lives here (not bench.py)
#: so the graftcheck kernels layer and the CPU tile-rule guard verify the
#: EXACT geometry the bench dispatches — bench re-exports both names.
NS_FLASH_BLOCKS = (512, 4096)

#: bench --flash-block-sweep configs for the 200px north-star kernel tuning;
#: tests/test_flash_attention.py and the graftcheck kernels layer pre-check
#: every entry against Mosaic's tile rules before it can burn a slot in the
#: one hardware window
FLASH_BLOCK_SWEEP = ((512, 512), (256, 1024), (256, 4096), (512, 4096))


# ---------------------------------------------------------------------------
# forward
# ---------------------------------------------------------------------------

def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, acc_ref, m_ref, l_ref, *,
                scale: float, n_valid: int, block_kv: int, n_kv: int):
    """One (head, q-block, kv-block) program: fold this K/V chunk into the
    running softmax state; emit o = acc/l and lse = m + log l on the last."""
    kv_i = pl.program_id(2)

    @pl.when(kv_i == 0)
    def _init():
        m_ref[...] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)

    # GEMMs run in the INPUT dtype with f32 MXU accumulation
    # (preferred_element_type): for bf16 models this is the native-speed MXU
    # path (an explicit f32 upcast here costs ~4× MXU throughput on v5e and
    # doubles VMEM traffic); for f32 inputs it is bit-identical to the old
    # explicit-upcast form. Softmax stays f32 either way.
    q = q_ref[0]  # (bq, D)
    k = k_ref[0]  # (bkv, D)
    logits = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    ) * scale  # (bq, bkv) f32
    col = kv_i * block_kv + jax.lax.broadcasted_iota(jnp.int32, logits.shape, 1)
    logits = jnp.where(col < n_valid, logits, _NEG_INF)

    # online softmax update (the same math the ring-attention steps use,
    # parallel/ring_attention.py:62-71, here per VMEM chunk)
    m_prev = jnp.max(m_ref[...], axis=-1, keepdims=True)  # (bq, 1) replicated
    l_prev = jnp.max(l_ref[...], axis=-1, keepdims=True)
    m_new = jnp.maximum(m_prev, jnp.max(logits, axis=-1, keepdims=True))
    alpha = jnp.exp(m_prev - m_new)
    p = jnp.exp(logits - m_new)  # (bq, bkv)
    l_new = alpha * l_prev + jnp.sum(p, axis=-1, keepdims=True)
    # p rounds to v's dtype for the MXU (f32 accumulate); exact for f32 v,
    # ≤1 bf16 ulp per product for bf16 v — inside the model's own precision
    pv = jnp.dot(p.astype(v_ref.dtype), v_ref[0],
                 preferred_element_type=jnp.float32)
    acc_ref[...] = acc_ref[...] * alpha + pv
    m_ref[...] = jnp.broadcast_to(m_new, m_ref.shape)
    l_ref[...] = jnp.broadcast_to(l_new, l_ref.shape)

    @pl.when(kv_i == n_kv - 1)
    def _emit():
        m = jnp.max(m_ref[...], axis=-1, keepdims=True)
        l = jnp.max(l_ref[...], axis=-1, keepdims=True)
        o_ref[0] = (acc_ref[...] / l).astype(o_ref.dtype)
        # lane-replicated (bq, LANE): a (1, bq) row block would violate the
        # TPU (8, 128) tile rule — Mosaic rejects sublane-dim-1 blocks unless
        # they equal the array dim (hit at N=2501 on real hardware)
        lse_ref[0] = jnp.broadcast_to(m + jnp.log(l), lse_ref.shape[1:])


def _sds(shape, dtype, like: jax.Array) -> jax.ShapeDtypeStruct:
    """ShapeDtypeStruct carrying ``like``'s varying-manual-axes type — needed
    when the kernel runs inside a ``shard_map`` (e.g. as Ulysses' local
    attention) where ``check_vma`` requires outputs to declare their vma."""
    # jax.typeof (and vma-typed avals) only exist on newer jax; without them
    # there is no vma checker to satisfy, so the plain struct is correct
    typeof = getattr(jax, "typeof", None)
    vma = getattr(typeof(like), "vma", None) if typeof is not None else None
    if vma:
        return jax.ShapeDtypeStruct(shape, dtype, vma=vma)
    return jax.ShapeDtypeStruct(shape, dtype)


def _pad_to(x: jax.Array, axis: int, multiple: int) -> jax.Array:
    pad = (-x.shape[axis]) % multiple
    if pad == 0:
        return x
    widths = [(0, 0)] * x.ndim
    widths[axis] = (0, pad)
    return jnp.pad(x, widths)


def _to_heads(x, B, N, H, D):
    """(B, N, H, D) → (B·H, N⁺, D⁺): one grid row per head's sequence,
    lane-aligned head dim (zero columns are inert in q·kᵀ and produce zero
    output columns, sliced off at the end), sublane-aligned N."""
    x = x.transpose(0, 2, 1, 3).reshape(B * H, N, D)
    x = _pad_to(x, 2, _LANE)
    return _pad_to(x, 1, 8)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    scale: float,
    block_q: int = 256,
    block_kv: int = 512,
) -> jax.Array:
    """Fused non-causal multi-head attention.

    q/k/v: ``(B, N, H, D)`` (the model's head layout, ViT.py:104-107);
    returns ``(B, N, H, D)`` in q's dtype. Softmax runs in float32 regardless
    of input dtype, matching the einsum path bit-for-bit up to GEMM precision.
    VMEM per program ≈ (block_q + 2·block_kv)·D_padded input tiles plus the
    f32 accumulator — independent of N, forward and backward alike.
    """
    return _flash_forward(q, k, v, scale, block_q, block_kv)[0]


def _use_kernel() -> bool:
    # Interpreter mode exists so CPU tests exercise the kernel path; on any
    # other non-TPU backend (e.g. GPU) interpreting would be a silent
    # orders-of-magnitude slowdown — use the dense einsum instead.
    return jax.default_backend() in ("tpu", "cpu")


def _flash_forward(q, k, v, scale, block_q, block_kv):
    if not _use_kernel():
        return _dense_attention_f32(q, k, v, scale)[1].astype(q.dtype), None

    B, N, H, D = q.shape
    qh, kh, vh = (_to_heads(x, B, N, H, D) for x in (q, k, v))
    BH, Np, Dp = qh.shape
    # pad-or-clamp the requested blocks to Mosaic-legal sizes for this
    # dtype/N — min() alone produced illegal tiles at odd requests or
    # sub-16 sublanes on bf16 (ops/tiling.py; N=2501 is the worst case)
    bq = tiling.legal_block(block_q, Np, qh.dtype)
    bkv = tiling.legal_block(block_kv, Np, qh.dtype)
    qh = _pad_to(qh, 1, bq)
    kh, vh = _pad_to(kh, 1, bkv), _pad_to(vh, 1, bkv)
    n_kv = kh.shape[1] // bkv
    grid = (BH, qh.shape[1] // bq, n_kv)

    kernel = functools.partial(_fwd_kernel, scale=scale, n_valid=N,
                               block_kv=bkv, n_kv=n_kv)
    with profiling.scope("flash_attention/fwd"):
        out, lse = pl.pallas_call(
            kernel,
            grid=grid,
            in_specs=[
                pl.BlockSpec((1, bq, Dp), lambda b, i, j: (b, i, 0)),
                pl.BlockSpec((1, bkv, Dp), lambda b, i, j: (b, j, 0)),
                pl.BlockSpec((1, bkv, Dp), lambda b, i, j: (b, j, 0)),
            ],
            out_specs=[
                pl.BlockSpec((1, bq, Dp), lambda b, i, j: (b, i, 0)),
                pl.BlockSpec((1, bq, _LANE), lambda b, i, j: (b, i, 0)),
            ],
            out_shape=[
                _sds(qh.shape, q.dtype, qh),
                _sds((*qh.shape[:2], _LANE), jnp.float32, qh),
            ],
            scratch_shapes=[
                pltpu.VMEM((bq, Dp), jnp.float32),    # output accumulator
                pltpu.VMEM((bq, _LANE), jnp.float32),  # running max
                pltpu.VMEM((bq, _LANE), jnp.float32),  # running denominator
            ],
            compiler_params=_CompilerParams(
                dimension_semantics=("parallel", "parallel", "arbitrary"),
            ),
            interpret=jax.default_backend() == "cpu",
        )(qh, kh, vh)

    out = out[:, :N, :D].reshape(B, H, N, D).transpose(0, 2, 1, 3)
    # drop the lane replication before the lse becomes a VJP residual —
    # carrying all 128 lanes would hold O(N·128) f32 across the backward
    return out, lse[:, :, 0]


# ---------------------------------------------------------------------------
# backward
# ---------------------------------------------------------------------------

def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref,
                   acc_ref, *, scale: float, n_valid: int, block_q: int,
                   block_kv: int, n_kv: int):
    """dq_i = Σ_j ds_ij·k_j·scale, K/V chunks streamed innermost."""
    kv_i = pl.program_id(2)

    @pl.when(kv_i == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)

    # input-dtype GEMMs, f32 accumulation — see _fwd_kernel
    q = q_ref[0]    # (bq, D)
    k = k_ref[0]    # (bkv, D)
    v = v_ref[0]
    do = do_ref[0]  # (bq, D)
    lse = lse_ref[0][:, :1]             # (bq, 1), lane-replicated block
    delta = delta_ref[0][:, :1]         # (bq, 1)

    logits = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    ) * scale  # (bq, bkv) f32
    # zero both padded kv columns (zero-filled k would contribute exp(−lse))
    # and padded q rows (their lse ≈ −inf would blow up exp)
    col = kv_i * block_kv + jax.lax.broadcasted_iota(jnp.int32, logits.shape, 1)
    row = pl.program_id(1) * block_q + jax.lax.broadcasted_iota(
        jnp.int32, logits.shape, 0)
    p = jnp.where((col < n_valid) & (row < n_valid),
                  jnp.exp(logits - lse), 0.0)
    dp = jnp.dot(do, v.T, preferred_element_type=jnp.float32)  # (bq, bkv) f32
    ds = p * (dp - delta)
    acc_ref[...] += jnp.dot(ds.astype(k.dtype), k,
                            preferred_element_type=jnp.float32) * scale

    @pl.when(kv_i == n_kv - 1)
    def _emit():
        dq_ref[0] = acc_ref[...].astype(dq_ref.dtype)


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                    dk_ref, dv_ref, dk_acc, dv_acc, *, scale: float,
                    n_valid: int, block_q: int, block_kv: int, n_q: int):
    """dv_j = Σ_i p_ijᵀ·do_i and dk_j = Σ_i ds_ijᵀ·q_i·scale — grid
    transposed: one K/V block per (outer) program, q chunks streamed
    innermost."""
    q_i = pl.program_id(2)

    @pl.when(q_i == 0)
    def _init():
        dk_acc[...] = jnp.zeros_like(dk_acc)
        dv_acc[...] = jnp.zeros_like(dv_acc)

    # input-dtype GEMMs, f32 accumulation — see _fwd_kernel
    q = q_ref[0]    # (bq, D)
    k = k_ref[0]    # (bkv, D)
    v = v_ref[0]
    do = do_ref[0]  # (bq, D)
    lse = lse_ref[0][:, :1]             # (bq, 1), lane-replicated block
    delta = delta_ref[0][:, :1]         # (bq, 1)

    logits = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    ) * scale  # (bq, bkv) f32
    # a padded q row's garbage lse would poison VALID kv columns through the
    # column-sum — masking rows here is correctness, not hygiene
    row = q_i * block_q + jax.lax.broadcasted_iota(jnp.int32, logits.shape, 0)
    col = pl.program_id(1) * block_kv + jax.lax.broadcasted_iota(
        jnp.int32, logits.shape, 1)
    p = jnp.where((row < n_valid) & (col < n_valid),
                  jnp.exp(logits - lse), 0.0)
    dv_acc[...] += jax.lax.dot_general(  # pᵀ·do: (bkv, D)
        p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    dp = jnp.dot(do, v.T, preferred_element_type=jnp.float32)  # (bq, bkv) f32
    ds = p * (dp - delta)
    dk_acc[...] += jax.lax.dot_general(  # dsᵀ·q: (bkv, D)
        ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32) * scale

    @pl.when(q_i == n_q - 1)
    def _emit():
        dk_ref[0] = dk_acc[...].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[...].astype(dv_ref.dtype)


def _flash_backward(q, k, v, o, lse, g, scale, block_q, block_kv):
    B, N, H, D = q.shape
    qh, kh, vh, oh, gh = (_to_heads(x, B, N, H, D) for x in (q, k, v, o, g))
    BH, Np, Dp = qh.shape
    bq = tiling.legal_block(block_q, Np, qh.dtype)
    bkv = tiling.legal_block(block_kv, Np, qh.dtype)
    qh, oh, gh = (_pad_to(x, 1, bq) for x in (qh, oh, gh))
    kh, vh = _pad_to(kh, 1, bkv), _pad_to(vh, 1, bkv)
    n_q, n_kv = qh.shape[1] // bq, kh.shape[1] // bkv
    # lse (BH, Nq⁺) and delta get lane-replicated to (…, LANE) blocks here —
    # sublane-dim-1 (1, bq) row blocks don't lower on TPU (the (8, 128) tile
    # rule); the broadcast is per-backward-call, so the residual stays O(N)
    lse = _pad_to(lse, 1, bq)
    lse = jnp.broadcast_to(lse[:, :, None], (*lse.shape, _LANE))
    delta = jnp.sum(oh.astype(jnp.float32) * gh.astype(jnp.float32), axis=-1)
    delta = jnp.broadcast_to(delta[:, :, None], (*delta.shape, _LANE))

    interpret = jax.default_backend() == "cpu"
    q_spec = pl.BlockSpec((1, bq, Dp), lambda b, i, j: (b, i, 0))
    kv_spec_dq = pl.BlockSpec((1, bkv, Dp), lambda b, i, j: (b, j, 0))
    row_spec = pl.BlockSpec((1, bq, _LANE), lambda b, i, j: (b, i, 0))

    with profiling.scope("flash_attention/dq"):
        dq = pl.pallas_call(
            functools.partial(_bwd_dq_kernel, scale=scale, n_valid=N,
                              block_q=bq, block_kv=bkv, n_kv=n_kv),
            grid=(BH, n_q, n_kv),
            in_specs=[q_spec, kv_spec_dq, kv_spec_dq, q_spec, row_spec,
                      row_spec],
            out_specs=q_spec,
            out_shape=_sds(qh.shape, q.dtype, qh),
            scratch_shapes=[pltpu.VMEM((bq, Dp), jnp.float32)],
            compiler_params=_CompilerParams(
                dimension_semantics=("parallel", "parallel", "arbitrary")),
            interpret=interpret,
        )(qh, kh, vh, gh, lse, delta)

    # transposed grid: (head, kv block, q chunk innermost)
    q_spec_t = pl.BlockSpec((1, bq, Dp), lambda b, j, i: (b, i, 0))
    kv_spec_t = pl.BlockSpec((1, bkv, Dp), lambda b, j, i: (b, j, 0))
    row_spec_t = pl.BlockSpec((1, bq, _LANE), lambda b, j, i: (b, i, 0))
    with profiling.scope("flash_attention/dkv"):
        dk, dv = pl.pallas_call(
            functools.partial(_bwd_dkv_kernel, scale=scale, n_valid=N,
                              block_q=bq, block_kv=bkv, n_q=n_q),
            grid=(BH, n_kv, n_q),
            in_specs=[q_spec_t, kv_spec_t, kv_spec_t, q_spec_t, row_spec_t,
                      row_spec_t],
            out_specs=[kv_spec_t, kv_spec_t],
            out_shape=[_sds(kh.shape, k.dtype, kh),
                       _sds(vh.shape, v.dtype, vh)],
            scratch_shapes=[pltpu.VMEM((bkv, Dp), jnp.float32),
                            pltpu.VMEM((bkv, Dp), jnp.float32)],
            compiler_params=_CompilerParams(
                dimension_semantics=("parallel", "parallel", "arbitrary")),
            interpret=interpret,
        )(qh, kh, vh, gh, lse, delta)

    def from_heads(x):
        return x[:, :N, :D].reshape(B, H, N, D).transpose(0, 2, 1, 3)

    return from_heads(dq), from_heads(dk), from_heads(dv)


def online_softmax_update(o, l, m, logits, v_blk):
    """One blockwise-softmax accumulation step — THE shared update used by
    the pure-XLA blockwise path below and the ring-attention rotation steps
    (parallel/ring_attention.py): fold a new logits block into the running
    (output-numerator, denominator, max) triple, all f32.

    Shapes: o ``(..., nq, D)``, l/m ``(..., nq)``, logits ``(..., nq, bkv)``,
    v_blk ``(..., bkv, D)`` — leading dims broadcast (B, H, ...).
    """
    m_new = jnp.maximum(m, logits.max(axis=-1))
    p = jnp.exp(logits - m_new[..., None])
    corr = jnp.exp(m - m_new)
    l = l * corr + p.sum(axis=-1)
    o = o * corr[..., None] + jnp.einsum("...qk,...kd->...qd", p, v_blk)
    return o, l, m_new


def blockwise_attention_xla(q, k, v, scale, block_kv: int = 512) -> jax.Array:
    """Pure-XLA blockwise softmax attention — the Mosaic-free middle path.

    Same online-softmax math as the Pallas kernel (and the ring steps,
    parallel/ring_attention.py:62-71), expressed as a ``lax.scan`` over K/V
    chunks: the N² logit matrix never exists as one array — only one
    (B, H, N, block_kv) block per step, which XLA keeps fused with its
    exp/max/accumulate tail. Compiles anywhere ``lax`` does, so it serves as
    the safety net for accelerators where the Pallas kernel fails to lower
    (Mosaic rejected the kernel once on real hardware at N=2501 — this path
    has no kernel to reject). Expected between dense and Pallas in speed;
    strictly better than dense in HBM traffic at long N.

    q/k/v ``(B, N, H, D)`` → ``(B, N, H, D)`` in q's dtype, f32 softmax.
    """
    B, N, H, D = q.shape
    qf = q.astype(jnp.float32).transpose(0, 2, 1, 3)  # (B, H, N, D)
    kf = k.astype(jnp.float32).transpose(0, 2, 1, 3)
    vf = v.astype(jnp.float32).transpose(0, 2, 1, 3)
    block_kv = min(block_kv, max(1, N))
    pad = (-N) % block_kv
    if pad:
        kf = jnp.pad(kf, ((0, 0), (0, 0), (0, pad), (0, 0)))
        vf = jnp.pad(vf, ((0, 0), (0, 0), (0, pad), (0, 0)))
    nb = kf.shape[2] // block_kv
    kb = kf.reshape(B, H, nb, block_kv, D).transpose(2, 0, 1, 3, 4)
    vb = vf.reshape(B, H, nb, block_kv, D).transpose(2, 0, 1, 3, 4)
    valid = (jnp.arange(nb * block_kv) < N).reshape(nb, block_kv)

    o = jnp.zeros((B, H, N, D), jnp.float32)
    l = jnp.zeros((B, H, N), jnp.float32)
    m = jnp.full((B, H, N), _NEG_INF, jnp.float32)

    def body(carry, blk):
        o, l, m = carry
        k_b, v_b, val = blk
        logits = jnp.einsum("bhqd,bhkd->bhqk", qf, k_b) * scale
        logits = jnp.where(val[None, None, None, :], logits, _NEG_INF)
        return online_softmax_update(o, l, m, logits, v_b), None

    (o, l, _), _ = jax.lax.scan(body, (o, l, m), (kb, vb, valid))
    return (o / l[..., None]).transpose(0, 2, 1, 3).astype(q.dtype)


def _dense_attention_f32(q, k, v, scale):
    """XLA-einsum oracle/fallback path, f32 accumulation (ViT.py:110-114)."""
    logits = jnp.einsum(
        "bnhd,bmhd->bhnm", q.astype(jnp.float32), k.astype(jnp.float32)
    ) * scale
    p = jax.nn.softmax(logits, axis=-1)
    return p, jnp.einsum("bhnm,bmhd->bnhd", p, v.astype(jnp.float32))


def _dense_backward(q, k, v, g, scale):
    p, _ = _dense_attention_f32(q, k, v, scale)
    gf = g.astype(jnp.float32)
    dv = jnp.einsum("bhnm,bnhd->bmhd", p, gf)
    dp = jnp.einsum("bnhd,bmhd->bhnm", gf, v.astype(jnp.float32))
    ds = p * (dp - jnp.sum(dp * p, axis=-1, keepdims=True))
    dq = jnp.einsum("bhnm,bmhd->bnhd", ds, k.astype(jnp.float32)) * scale
    dk = jnp.einsum("bhnm,bnhd->bmhd", ds, q.astype(jnp.float32)) * scale
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


def _flash_fwd(q, k, v, scale, block_q, block_kv):
    out, lse = _flash_forward(q, k, v, scale, block_q, block_kv)
    return out, (q, k, v, out, lse)


def _flash_bwd(scale, block_q, block_kv, residuals, g):
    q, k, v, o, lse = residuals
    if lse is None:  # dense fallback path (non-TPU/CPU backends)
        return _dense_backward(q, k, v, g, scale)
    dq, dk, dv = _flash_backward(q, k, v, o, lse, g, scale, block_q, block_kv)
    return dq, dk, dv


flash_attention.defvjp(_flash_fwd, _flash_bwd)


# ---------------------------------------------------------------------------
# fused quant-aware trunk attention (qkv producer → flash → proj consumer)
# ---------------------------------------------------------------------------

def _fused_trunk_kernel(*refs, heads: int, head_dim: int, scale: float,
                        n_valid: int, block_kv: int, n_kv: int,
                        qkv_bias: bool, proj_bias: bool, w8a8: bool):
    """One (batch, q-block, kv-block) program of the fused sampler-trunk
    attention: the w8a16 qkv dequant-matmul runs INSIDE the kernel as the
    producer (int8 weights + per-column scales staged in VMEM, dequantized at
    the MXU feed), the online softmax folds the kv chunk exactly like
    :func:`_fwd_kernel`, and on the last chunk the proj dequant-matmul
    consumes the attention output block in place — the (B, N, 3C) qkv and
    (B, N, C) context activations never round-trip through HBM.

    Numerics mirror the unfused ``QuantDense → flash_attention → QuantDense``
    composition term for term (same dot shapes over the same K reductions,
    same f32 scale/bias epilogues, same compute-dtype casts, same online-
    softmax update order), so the fused path is bitwise at f32 and within
    round-off at bf16 — tests/test_fusion.py pins both.

    ``w8a8=True`` switches the two trunk GEMMs to int8×int8 with int32 MXU
    accumulation: the x activations arrive pre-quantized (per-tensor dynamic
    scale folded into the qkv scales by the wrapper) and the attention output
    is requantized per q-block before the proj GEMM. Attention itself
    (softmax, p·v) stays in the compute dtype — only the trunk GEMM feeds are
    int8. Gated behind the paired-FID ``quantized_sampler_guard``.
    """
    bqkv_ref = bp_ref = None
    if qkv_bias and proj_bias:
        (xq_ref, xkv_ref, wqkv_ref, sqkv_ref, bqkv_ref, wp_ref, sp_ref,
         bp_ref, o_ref, q_s, acc_s, m_s, l_s) = refs
    elif qkv_bias:
        (xq_ref, xkv_ref, wqkv_ref, sqkv_ref, bqkv_ref, wp_ref, sp_ref,
         o_ref, q_s, acc_s, m_s, l_s) = refs
    elif proj_bias:
        (xq_ref, xkv_ref, wqkv_ref, sqkv_ref, wp_ref, sp_ref, bp_ref,
         o_ref, q_s, acc_s, m_s, l_s) = refs
    else:
        (xq_ref, xkv_ref, wqkv_ref, sqkv_ref, wp_ref, sp_ref,
         o_ref, q_s, acc_s, m_s, l_s) = refs
    kv_i = pl.program_id(2)
    C = heads * head_dim
    cdt = q_s.dtype
    w_all = wqkv_ref[...]   # (C, 3C) int8
    s_all = sqkv_ref[0]     # (3C,) f32 (w8a8: pre-folded with the act scale)
    b_all = bqkv_ref[0] if qkv_bias else None

    def project(x, w_cols, s_cols, b_cols):
        # one column range of the qkv dequant-matmul — per output element the
        # SAME K=C reduction the unfused kernel computes, so slicing the
        # weight columns (vs slicing the full qkv output) is value-identical
        if w8a8:
            y = jax.lax.dot_general(
                x, w_cols, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.int32).astype(jnp.float32) * s_cols
        else:
            y = jax.lax.dot_general(
                x, w_cols.astype(cdt), (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32) * s_cols
        if b_cols is not None:
            y = y + b_cols
        return y.astype(cdt)  # the QuantDense epilogue cast

    @pl.when(kv_i == 0)
    def _init():
        # q projection once per (batch, q-block); carried across kv chunks
        q_s[...] = project(xq_ref[0], w_all[:, :C], s_all[:C],
                           b_all[:C] if qkv_bias else None)
        m_s[...] = jnp.full_like(m_s, _NEG_INF)
        l_s[...] = jnp.zeros_like(l_s)
        acc_s[...] = jnp.zeros_like(acc_s)

    # k/v projection for THIS kv chunk — recomputed per chunk, the price of
    # never writing the (B, N, 2C) k/v activation to HBM (2·bkv·C·C MACs per
    # chunk vs a (B, N, 2C) HBM round-trip per layer)
    kv = project(xkv_ref[0], w_all[:, C:], s_all[C:],
                 b_all[C:] if qkv_bias else None)  # (bkv, 2C) cdt

    for h in range(heads):
        lo, hi = h * head_dim, (h + 1) * head_dim
        q_h = q_s[:, lo:hi]          # (bq, hd) cdt
        k_h = kv[:, lo:hi]           # (bkv, hd)
        v_h = kv[:, C + lo:C + hi]
        # identical update math to _fwd_kernel — the zero-padded head-dim
        # lanes of the unfused path contribute exact +0.0 partial products,
        # so the hd-width reduction here is bitwise the Dp-width one
        logits = jax.lax.dot_general(
            q_h, k_h, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale  # (bq, bkv) f32
        col = kv_i * block_kv + jax.lax.broadcasted_iota(
            jnp.int32, logits.shape, 1)
        logits = jnp.where(col < n_valid, logits, _NEG_INF)
        m_prev = jnp.max(m_s[h], axis=-1, keepdims=True)  # (bq, 1) replicated
        l_prev = jnp.max(l_s[h], axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, jnp.max(logits, axis=-1, keepdims=True))
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(logits - m_new)
        l_new = alpha * l_prev + jnp.sum(p, axis=-1, keepdims=True)
        pv = jnp.dot(p.astype(v_h.dtype), v_h,
                     preferred_element_type=jnp.float32)
        acc_s[:, lo:hi] = acc_s[:, lo:hi] * alpha + pv
        m_s[h] = jnp.broadcast_to(m_new, m_s.shape[1:])
        l_s[h] = jnp.broadcast_to(l_new, l_s.shape[1:])

    @pl.when(kv_i == n_kv - 1)
    def _emit():
        outs = []
        for h in range(heads):
            lo, hi = h * head_dim, (h + 1) * head_dim
            l = jnp.max(l_s[h], axis=-1, keepdims=True)
            outs.append((acc_s[:, lo:hi] / l).astype(cdt))
        attn = jnp.concatenate(outs, axis=-1)  # (bq, C) cdt, head-major cols
        if w8a8:
            amax = jnp.max(jnp.abs(attn.astype(jnp.float32)))
            qs = jnp.where(amax > 0, amax / 127.0, 1.0)
            ai = jnp.clip(jnp.round(attn.astype(jnp.float32) / qs),
                          -127.0, 127.0).astype(jnp.int8)
            y = jax.lax.dot_general(
                ai, wp_ref[...], (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.int32).astype(jnp.float32)
            y = y * (qs * sp_ref[0])
        else:
            y = jax.lax.dot_general(
                attn, wp_ref[...].astype(cdt), (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32) * sp_ref[0]
        if bp_ref is not None:
            # proj bias fused at the scale multiply — the same contraction
            # point as the unfused QuantDense epilogue (quant._mm_kernel)
            y = y + bp_ref[0]
        o_ref[0] = y  # f32; the wrapper casts to the compute dtype


def fused_trunk_attention(x, w_qkv, s_qkv, b_qkv, w_proj, s_proj, b_proj, *,
                          num_heads: int, scale: float, block_q: int = 512,
                          block_kv: int = 1024, mode: str = "pallas"):
    """Quant-aware fused trunk attention: ``x → qkv dequant-GEMM → flash
    attention → proj dequant-GEMM`` as ONE Pallas kernel (inference only —
    the sampler hot path; training keeps the unfused composition and its
    custom VJP).

    ``x``: (B, N, C) activations in the compute dtype; ``w_qkv``/``w_proj``:
    int8 (C, 3C)/(C, C) weights with f32 per-output-column scales (the
    ops/quant.py codec); biases f32 or None. Returns (B, N, C) in ``x``'s
    dtype — the full QuantDense epilogue (scale, bias, cast) included.
    ``mode="w8a8"`` additionally quantizes the activations (per-tensor
    dynamic scale, int8×int8 trunk GEMMs). Off TPU/CPU, falls back to the
    unfused XLA composition, same policy as :func:`flash_attention`.
    """
    from ddim_cold_tpu.ops import quant as _quant

    B, N, C = x.shape
    head_dim = C // num_heads
    if C % num_heads:
        raise ValueError(f"embed dim {C} must divide by heads {num_heads}")
    if mode not in ("pallas", "w8a8"):
        raise ValueError(f"fused attention mode must be 'pallas' or 'w8a8', "
                         f"got {mode!r}")
    w8a8 = mode == "w8a8"
    if not _use_kernel():
        # unfused XLA composition (GPU etc.) — the same epilogues
        xla_mode = "w8a8" if w8a8 else "xla"
        qkv = _quant.dequant_matmul(x, w_qkv, s_qkv, bias=b_qkv,
                                    mode=xla_mode)
        qkv = qkv.astype(x.dtype).reshape(B, N, 3, num_heads, head_dim)
        q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
        out = _dense_attention_f32(q, k, v, scale)[1].astype(x.dtype)
        y = _quant.dequant_matmul(out.reshape(B, N, C), w_proj, s_proj,
                                  bias=b_proj, mode=xla_mode)
        return y.astype(x.dtype)

    if w8a8:
        xi, xs = _quant.quantize_act(x)
        x_in = xi
        s_eff = s_qkv.astype(jnp.float32) * xs  # per-tensor act scale folded
    else:
        x_in, s_eff = x, s_qkv.astype(jnp.float32)
    bq = tiling.legal_block(block_q, N, x_in.dtype)
    bkv = tiling.legal_block(block_kv, N, x_in.dtype)
    xq = _pad_to(x_in, 1, bq)
    xkv = _pad_to(x_in, 1, bkv)
    n_q, n_kv = xq.shape[1] // bq, xkv.shape[1] // bkv

    C3 = 3 * C
    inputs = [xq, xkv, w_qkv, s_eff[None, :]]
    in_specs = [
        pl.BlockSpec((1, bq, C), lambda b, i, j: (b, i, 0)),
        pl.BlockSpec((1, bkv, C), lambda b, i, j: (b, j, 0)),
        pl.BlockSpec((C, C3), lambda b, i, j: (0, 0)),
        pl.BlockSpec((1, C3), lambda b, i, j: (0, 0)),
    ]
    if b_qkv is not None:
        inputs.append(b_qkv.astype(jnp.float32)[None, :])
        in_specs.append(pl.BlockSpec((1, C3), lambda b, i, j: (0, 0)))
    inputs += [w_proj, s_proj.astype(jnp.float32)[None, :]]
    in_specs += [
        pl.BlockSpec((C, C), lambda b, i, j: (0, 0)),
        pl.BlockSpec((1, C), lambda b, i, j: (0, 0)),
    ]
    if b_proj is not None:
        inputs.append(b_proj.astype(jnp.float32)[None, :])
        in_specs.append(pl.BlockSpec((1, C), lambda b, i, j: (0, 0)))
    kernel = functools.partial(
        _fused_trunk_kernel, heads=num_heads, head_dim=head_dim, scale=scale,
        n_valid=N, block_kv=bkv, n_kv=n_kv, qkv_bias=b_qkv is not None,
        proj_bias=b_proj is not None, w8a8=w8a8)
    with profiling.scope("flash_attention/fused_qkv"):
        out = pl.pallas_call(
            kernel,
            grid=(B, n_q, n_kv),
            in_specs=in_specs,
            out_specs=pl.BlockSpec((1, bq, C), lambda b, i, j: (b, i, 0)),
            out_shape=_sds((B, n_q * bq, C), jnp.float32, x),
            scratch_shapes=[
                pltpu.VMEM((bq, C), x.dtype),        # projected q block
                pltpu.VMEM((bq, C), jnp.float32),     # per-head output acc
                pltpu.VMEM((num_heads, bq, _LANE), jnp.float32),  # running max
                pltpu.VMEM((num_heads, bq, _LANE), jnp.float32),  # running den
            ],
            compiler_params=_CompilerParams(
                dimension_semantics=("parallel", "parallel", "arbitrary"),
            ),
            interpret=jax.default_backend() == "cpu",
        )(*inputs)
    with profiling.scope("flash_attention/fused_proj"):
        # scale + bias already applied in-kernel; only slice off the q-block
        # padding and cast back to the compute dtype
        return out[:, :N].astype(x.dtype)
