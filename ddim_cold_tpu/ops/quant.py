"""W8A16 post-training quantization for the ViT trunk (the param-traffic lever).

PERF.md's north-star analysis puts the 200px/k=20 sampler past the
attention-HBM wall (flash kernel); the next costs are trunk GEMM time and
parameter bytes over the link. Training-free weight-only quantization is the
standard diffusion-transformer answer (Efficient Diffusion Models survey,
arXiv:2502.06805): **symmetric per-output-channel int8 weights, bf16
activations** (w8a16) for the four trunk GEMMs per block — attention
``qkv``/``proj`` and Mlp ``fc1``/``fc2``. Embeddings, layernorms, the patch
projection and the output head stay in float (small, and the head sets pixel
accuracy).

Pieces:

* ``quantize_weight`` / ``dequantize_weight`` — the per-output-channel
  symmetric codec: ``scale = max|w|/127`` per output column, values clipped
  to [−127, 127] (the −128 code is unused, keeping the codec symmetric).
* ``quantize_params`` — one-shot transform of a DiffusionViT param tree:
  each trunk dense's ``kernel`` leaf becomes ``{w_int8, scale}`` IN PLACE
  (same module paths, bias untouched), so ``parallel/sharding.py``'s
  module-name keyed specs and the serving engine's pre-sharded param flow
  apply unchanged, and the tree ships ≈4× fewer trunk-param bytes.
* ``dequant_matmul`` — the w8a16 GEMM, two implementations behind one
  signature:

  - ``mode="xla"``: ``lax.dot_general`` on the int8 weights upcast to the
    activation dtype with ``preferred_element_type=f32`` accumulation; XLA
    fuses the int8→bf16 convert into the matmul read and the per-column
    scale multiply into the epilogue — no dequantized weight copy in HBM.
  - ``mode="pallas"``: a fused dequant-matmul kernel (grid over M/N tiles,
    K streamed innermost through a VMEM f32 accumulator, scale applied once
    at emit). Same capability gating as ops/flash_attention.py: TPU runs
    the kernel, CPU runs it in interpreter mode (tests exercise the real
    code path), any other backend falls back to the XLA form.

* ``QuantDense`` — the flax module models/vit.py swaps in for ``nn.Dense``
  when ``model.quant`` is set; declares exactly the ``{w_int8, scale[, bias]}``
  leaves ``quantize_params`` produces.
* ``calibrate`` — per-layer max-abs quantization error stats, so a bad layer
  in the paired Fréchet guard (eval/fid.quantized_sampler_guard) is
  attributable to its scale, not hunted by bisection.

Both matmul paths accumulate in f32 and apply scale/bias in f32, so
``mode="xla"`` and ``mode="pallas"`` agree to f32 round-off and either can
stand in for the other in tests.
"""

from __future__ import annotations

import functools
from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ddim_cold_tpu.ops import tiling
from ddim_cold_tpu.utils import profiling

#: Pallas-TPU compiler params across jax versions (same shim as
#: ops/flash_attention.py — renamed TPUCompilerParams → CompilerParams)
_CompilerParams = getattr(pltpu, "CompilerParams", None) or pltpu.TPUCompilerParams

#: quantization revision stamped into bench records (mirrors KERNEL_REV:
#: scripts/perf_tables.py renders it and stale-record protection keys
#: re-measurement off it). "w8a16-pcq-v1" = per-output-channel symmetric
#: int8 weights, [−127, 127] codes, f32-accumulated dequant matmul.
QUANT_REV = "w8a16-pcq-v1"

#: dequant_matmul modes a model/SamplerConfig may request
QUANT_MODES = ("xla", "pallas")

#: trunk modules whose ``kernel`` is quantized, keyed by parent module name —
#: the same (parent, leaf) addressing parallel/sharding.py's _spec_for uses.
#: NOTE ``proj`` alone is ambiguous (patch_embed's dense is also "proj");
#: the parent-name key is what keeps the patch projection in float.
_TRUNK_DENSE = {"attn": ("qkv", "proj"), "mlp": ("fc1", "fc2")}

_LANE = 128  # TPU lane width: last dim of VMEM tiles
_INT8_SUBLANE = 32  # int8 min tile is (32, 128): K blocks must be 32-aligned


# ---------------------------------------------------------------------------
# codec
# ---------------------------------------------------------------------------

def quantize_weight(kernel: jax.Array) -> tuple[jax.Array, jax.Array]:
    """Symmetric per-output-channel int8 quantization of a (in, out) kernel.

    ``scale[j] = max_i |kernel[i, j]| / 127`` (1.0 for all-zero columns so
    dequantization never divides by zero); codes are round-to-nearest-even
    and clipped to [−127, 127]. Round-trip error is ≤ scale/2 per channel by
    construction (asserted in tests/test_quant.py).
    """
    k32 = jnp.asarray(kernel, jnp.float32)
    amax = jnp.max(jnp.abs(k32), axis=tuple(range(k32.ndim - 1)))
    scale = jnp.where(amax > 0, amax / 127.0, 1.0).astype(jnp.float32)
    codes = jnp.clip(jnp.round(k32 / scale), -127.0, 127.0)
    return codes.astype(jnp.int8), scale


def dequantize_weight(w_int8: jax.Array, scale: jax.Array,
                      dtype: Any = jnp.float32) -> jax.Array:
    return (w_int8.astype(jnp.float32) * scale).astype(dtype)


# ---------------------------------------------------------------------------
# param-tree transform
# ---------------------------------------------------------------------------

def _is_trunk_dense(path: tuple[str, ...]) -> bool:
    return (len(path) >= 2 and path[-1] in _TRUNK_DENSE.get(path[-2], ()))


def _walk(tree, path=()):
    """Yield ``(path, module_dict)`` for every trunk dense holding a kernel."""
    if not isinstance(tree, dict) and not hasattr(tree, "items"):
        return
    for name, sub in tree.items():
        sub_path = path + (name,)
        if _is_trunk_dense(sub_path) and hasattr(sub, "items") and "kernel" in sub:
            yield sub_path, sub
        else:
            yield from _walk(sub, sub_path)


def quantize_params(params):
    """One-shot w8a16 transform of a DiffusionViT ``params`` tree.

    Every trunk dense (``attn/{qkv,proj}``, ``mlp/{fc1,fc2}``) has its
    ``kernel`` replaced by ``{w_int8, scale}``; biases and every non-trunk
    leaf pass through untouched. The tree topology (module paths) is
    preserved, so partition-spec derivation and the engine's param flow see
    the same structure. The result is what ``model.clone(quant=...)``'s
    forward consumes (models/vit.py routes the trunk through
    :class:`QuantDense`).
    """
    def rec(tree, path=()):
        if not hasattr(tree, "items"):
            return tree
        out = {}
        for name, sub in tree.items():
            sub_path = path + (name,)
            if (_is_trunk_dense(sub_path) and hasattr(sub, "items")
                    and "kernel" in sub):
                w_int8, scale = quantize_weight(sub["kernel"])
                mod = {k: v for k, v in sub.items() if k != "kernel"}
                mod["w_int8"], mod["scale"] = w_int8, scale
                out[name] = mod
            else:
                out[name] = rec(sub, sub_path)
        return out

    return rec(params)


def is_quantized(params) -> bool:
    """True when the tree carries at least one ``w_int8`` trunk leaf."""
    found = []

    def rec(tree):
        if hasattr(tree, "items"):
            for name, sub in tree.items():
                if name == "w_int8":
                    found.append(True)
                rec(sub)

    rec(params)
    return bool(found)


def param_bytes(params) -> int:
    """Total bytes of every array leaf — the H2D param-traffic number the
    serving engine reports (int8 trunks ship ≈4× fewer)."""
    return int(sum(leaf.size * jnp.dtype(leaf.dtype).itemsize
                   for leaf in jax.tree_util.tree_leaves(params)))


def calibrate(params) -> dict:
    """Per-layer quantization error stats: for every trunk dense, the
    worst-case absolute weight error, the worst error relative to the
    channel's own scale (≤ 0.5 by construction — a larger value means the
    codec is broken for that layer) and the scale range. Keys are
    '/'-joined module paths, so a bad layer in the paired Fréchet guard is
    attributable by name."""
    stats = {}
    for path, mod in _walk(params):
        w_int8, scale = quantize_weight(mod["kernel"])
        err = jnp.abs(jnp.asarray(mod["kernel"], jnp.float32)
                      - w_int8.astype(jnp.float32) * scale)
        stats["/".join(path)] = {
            "max_abs_err": float(jnp.max(err)),
            "max_err_over_scale": float(jnp.max(err / scale)),
            "scale_min": float(jnp.min(scale)),
            "scale_max": float(jnp.max(scale)),
            "shape": tuple(int(d) for d in mod["kernel"].shape),
        }
    return stats


# ---------------------------------------------------------------------------
# w8a16 matmul — XLA path
# ---------------------------------------------------------------------------

def _dequant_matmul_xla(x: jax.Array, w_int8: jax.Array,
                        scale: jax.Array) -> jax.Array:
    """``x @ (w_int8 * scale)`` without materializing the dequantized weight:
    the int8→activation-dtype convert fuses into the matmul operand read and
    the per-column scale into the f32 epilogue. Accumulation is f32
    (``preferred_element_type``), the w8a16 contract."""
    w = w_int8.astype(x.dtype)
    y = jax.lax.dot_general(
        x, w, (((x.ndim - 1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    return y * scale


# ---------------------------------------------------------------------------
# w8a16 matmul — Pallas fused kernel
# ---------------------------------------------------------------------------

def _use_kernel() -> bool:
    # same policy as ops/flash_attention.py: TPU compiles the kernel, CPU
    # interprets it (tests exercise the identical code path), any other
    # backend (GPU) takes the XLA form instead of a silent interpreter crawl
    return jax.default_backend() in ("tpu", "cpu")


def _mm_kernel(x_ref, w_ref, s_ref, o_ref, acc_ref, *, n_k: int):
    """One (m-tile, n-tile, k-chunk) program: dequantize this int8 weight
    chunk to the activation dtype in VMEM, fold its partial product into the
    f32 accumulator, and on the last chunk apply the per-column scale once
    and emit. K is the innermost (sequential) grid axis, so the scratch
    accumulator carries across chunks of one output tile."""
    k_i = pl.program_id(2)

    @pl.when(k_i == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)

    x = x_ref[...]                      # (bm, bk) activation dtype
    w = w_ref[...].astype(x.dtype)      # (bk, bn) int8 → activation dtype
    acc_ref[...] += jax.lax.dot_general(
        x, w, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32)

    @pl.when(k_i == n_k - 1)
    def _emit():
        o_ref[...] = acc_ref[...] * s_ref[0]


def _round_up(n: int, m: int) -> int:
    return -(-n // m) * m


def _pad_axis(x: jax.Array, axis: int, to: int) -> jax.Array:
    pad = to - x.shape[axis]
    if pad <= 0:
        return x
    widths = [(0, 0)] * x.ndim
    widths[axis] = (0, pad)
    return jnp.pad(x, widths)


@functools.partial(jax.jit, static_argnames=("block_m", "block_n", "block_k"))
def _dequant_matmul_pallas(x2d: jax.Array, w_int8: jax.Array, scale: jax.Array,
                           *, block_m: int = 256, block_n: int = 512,
                           block_k: int = 512) -> jax.Array:
    """Fused dequant-matmul on a 2-D ``(M, K) @ (K, N)`` problem.

    Tiling honors the TPU tile rules: K blocks are lane-width (128) aligned
    (covering the int8 (32, 128) min tile on the weight's sublane dim), N
    blocks lane-aligned, M blocks sublane (8) aligned. Zero-padding is
    inert — padded K rows of the weight contribute zero partial products,
    padded M/N rows/columns are sliced off the output.
    """
    M, K = x2d.shape
    _, N = w_int8.shape
    # pad-or-clamp to Mosaic-legal blocks (ops/tiling.py): M is the
    # activation's sublane dim (8 at f32, 16 at bf16); N is a lane dim; K is
    # the activation's LANE dim and the int8 weight's SUBLANE dim at once,
    # so it must also divide by int8's 32-sublane unit (128 % 32 == 0 —
    # folded in explicitly so the constraint survives a lane-width change)
    bm = tiling.legal_block(block_m, M, x2d.dtype)
    bn = tiling.legal_block(block_n, N, jnp.float32, lane=True)
    bk = tiling.legal_block(block_k, K, x2d.dtype, lane=True,
                            min_unit=tiling.sublane_unit(jnp.int8))
    xp = _pad_axis(_pad_axis(x2d, 0, _round_up(M, bm)), 1, _round_up(K, bk))
    wp = _pad_axis(_pad_axis(w_int8, 0, _round_up(K, bk)), 1, _round_up(N, bn))
    sp = _pad_axis(scale.astype(jnp.float32)[None, :], 1, _round_up(N, bn))
    n_k = xp.shape[1] // bk

    with profiling.scope("dequant_matmul/pallas"):
        out = pl.pallas_call(
            functools.partial(_mm_kernel, n_k=n_k),
            grid=(xp.shape[0] // bm, wp.shape[1] // bn, n_k),
            in_specs=[
                pl.BlockSpec((bm, bk), lambda i, j, kk: (i, kk)),
                pl.BlockSpec((bk, bn), lambda i, j, kk: (kk, j)),
                pl.BlockSpec((1, bn), lambda i, j, kk: (0, j)),
            ],
            out_specs=pl.BlockSpec((bm, bn), lambda i, j, kk: (i, j)),
            out_shape=jax.ShapeDtypeStruct((xp.shape[0], wp.shape[1]),
                                           jnp.float32),
            scratch_shapes=[pltpu.VMEM((bm, bn), jnp.float32)],
            compiler_params=_CompilerParams(
                dimension_semantics=("parallel", "parallel", "arbitrary")),
            interpret=jax.default_backend() == "cpu",
        )(xp, wp, sp)
    return out[:M, :N]


# ---------------------------------------------------------------------------
# public matmul entry
# ---------------------------------------------------------------------------

def dequant_matmul(x: jax.Array, w_int8: jax.Array, scale: jax.Array,
                   *, mode: str = "xla") -> jax.Array:
    """w8a16 matmul over the last axis of ``x``: ``x @ (w_int8·scale)`` with
    f32 accumulation; returns f32 (callers add bias in f32 and cast to the
    compute dtype — one epilogue for both modes). ``mode="pallas"`` runs the
    fused kernel where capability allows and silently takes the XLA form
    elsewhere, exactly the flash-attention fallback policy."""
    if mode not in QUANT_MODES:
        raise ValueError(f"quant mode must be one of {QUANT_MODES}, got {mode!r}")
    if w_int8.dtype != jnp.int8:
        raise ValueError(f"w_int8 must be int8, got {w_int8.dtype}")
    if mode == "pallas" and _use_kernel():
        lead = x.shape[:-1]
        y = _dequant_matmul_pallas(x.reshape(-1, x.shape[-1]), w_int8,
                                   scale)
        return y.reshape(*lead, w_int8.shape[-1])
    return _dequant_matmul_xla(x, w_int8, scale)


class QuantDense(nn.Module):
    """Drop-in for ``nn.Dense`` over a quantized kernel: declares the
    ``{w_int8, scale[, bias]}`` leaves ``quantize_params`` produces (same
    module path/name as the dense it replaces) and computes the w8a16 matmul.
    Zero-init params make ``model.init`` legal on a quant model, but the
    intended flow is quantizing a trained float tree."""

    features: int
    use_bias: bool = True
    dtype: Any = jnp.float32
    mode: str = "xla"

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        w_int8 = self.param("w_int8", nn.initializers.zeros_init(),
                            (x.shape[-1], self.features), jnp.int8)
        scale = self.param("scale", nn.initializers.ones_init(),
                           (self.features,), jnp.float32)
        y = dequant_matmul(x.astype(self.dtype), w_int8, scale, mode=self.mode)
        if self.use_bias:
            bias = self.param("bias", nn.initializers.zeros_init(),
                              (self.features,), jnp.float32)
            y = y + bias
        return y.astype(self.dtype)
