"""W8A16 post-training quantization for the ViT trunk (the param-traffic lever).

PERF.md's north-star analysis puts the 200px/k=20 sampler past the
attention-HBM wall (flash kernel); the next costs are trunk GEMM time and
parameter bytes over the link. Training-free weight-only quantization is the
standard diffusion-transformer answer (Efficient Diffusion Models survey,
arXiv:2502.06805): **symmetric per-output-channel int8 weights, bf16
activations** (w8a16) for the four trunk GEMMs per block — attention
``qkv``/``proj`` and Mlp ``fc1``/``fc2``. Embeddings, layernorms, the patch
projection and the output head stay in float (small, and the head sets pixel
accuracy).

Pieces:

* ``quantize_weight`` / ``dequantize_weight`` — the per-output-channel
  symmetric codec: ``scale = max|w|/127`` per output column, values clipped
  to [−127, 127] (the −128 code is unused, keeping the codec symmetric).
* ``quantize_params`` — one-shot transform of a DiffusionViT param tree:
  each trunk dense's ``kernel`` leaf becomes ``{w_int8, scale}`` IN PLACE
  (same module paths, bias untouched), so ``parallel/sharding.py``'s
  module-name keyed specs and the serving engine's pre-sharded param flow
  apply unchanged, and the tree ships ≈4× fewer trunk-param bytes.
* ``dequant_matmul`` — the w8a16 GEMM, two implementations behind one
  signature:

  - ``mode="xla"``: ``lax.dot_general`` on the int8 weights upcast to the
    activation dtype with ``preferred_element_type=f32`` accumulation; XLA
    fuses the int8→bf16 convert into the matmul read and the per-column
    scale multiply into the epilogue — no dequantized weight copy in HBM.
  - ``mode="pallas"``: a fused dequant-matmul kernel (grid over M/N tiles,
    K streamed innermost through a VMEM f32 accumulator, scale applied once
    at emit). Same capability gating as ops/flash_attention.py: TPU runs
    the kernel, CPU runs it in interpreter mode (tests exercise the real
    code path), any other backend falls back to the XLA form.

* ``QuantDense`` — the flax module models/vit.py swaps in for ``nn.Dense``
  when ``model.quant`` is set; declares exactly the ``{w_int8, scale[, bias]}``
  leaves ``quantize_params`` produces.
* ``calibrate`` — per-layer max-abs quantization error stats, so a bad layer
  in the paired Fréchet guard (eval/fid.quantized_sampler_guard) is
  attributable to its scale, not hunted by bisection.

Both matmul paths accumulate in f32 and apply scale/bias in f32, so
``mode="xla"`` and ``mode="pallas"`` agree to f32 round-off and either can
stand in for the other in tests.
"""

from __future__ import annotations

import functools
from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ddim_cold_tpu.ops import tiling
from ddim_cold_tpu.utils import profiling

#: Pallas-TPU compiler params across jax versions (same shim as
#: ops/flash_attention.py — renamed TPUCompilerParams → CompilerParams)
_CompilerParams = getattr(pltpu, "CompilerParams", None) or pltpu.TPUCompilerParams

#: quantization revision stamped into bench records (mirrors KERNEL_REV:
#: scripts/perf_tables.py renders it and stale-record protection keys
#: re-measurement off it). "w8a16-pcq-v1" = per-output-channel symmetric
#: int8 weights, [−127, 127] codes, f32-accumulated dequant matmul.
#: "w8a16-fused-v2" adds the fused trunk kernels (mlp_pallas here, the fused
#: attention in ops/flash_attention.py) and the optional "w8a8" activation
#: mode (per-tensor dynamic int8 activations, int32 MXU accumulation). The
#: weight codec is unchanged from v1 — int8 param trees need no re-quantize.
QUANT_REV = "w8a16-fused-v2"

#: dequant_matmul modes a model/SamplerConfig may request. "w8a8" = int8
#: weights AND int8 activations (per-tensor dynamic scale, round-to-nearest
#: [−127, 127] codes) — FID-guard gated (eval/fid.quantized_sampler_guard);
#: the weight tree is the same w8a16 tree, only the GEMM feed changes.
QUANT_MODES = ("xla", "pallas", "w8a8")

#: trunk modules whose ``kernel`` is quantized, keyed by parent module name —
#: the same (parent, leaf) addressing parallel/sharding.py's _spec_for uses.
#: NOTE ``proj`` alone is ambiguous (patch_embed's dense is also "proj");
#: the parent-name key is what keeps the patch projection in float.
_TRUNK_DENSE = {"attn": ("qkv", "proj"), "mlp": ("fc1", "fc2")}

_LANE = 128  # TPU lane width: last dim of VMEM tiles
_INT8_SUBLANE = 32  # int8 min tile is (32, 128): K blocks must be 32-aligned


# ---------------------------------------------------------------------------
# codec
# ---------------------------------------------------------------------------

def quantize_weight(kernel: jax.Array) -> tuple[jax.Array, jax.Array]:
    """Symmetric per-output-channel int8 quantization of a (in, out) kernel.

    ``scale[j] = max_i |kernel[i, j]| / 127`` (1.0 for all-zero columns so
    dequantization never divides by zero); codes are round-to-nearest-even
    and clipped to [−127, 127]. Round-trip error is ≤ scale/2 per channel by
    construction (asserted in tests/test_quant.py).
    """
    k32 = jnp.asarray(kernel, jnp.float32)
    amax = jnp.max(jnp.abs(k32), axis=tuple(range(k32.ndim - 1)))
    scale = jnp.where(amax > 0, amax / 127.0, 1.0).astype(jnp.float32)
    codes = jnp.clip(jnp.round(k32 / scale), -127.0, 127.0)
    return codes.astype(jnp.int8), scale


def dequantize_weight(w_int8: jax.Array, scale: jax.Array,
                      dtype: Any = jnp.float32) -> jax.Array:
    return (w_int8.astype(jnp.float32) * scale).astype(dtype)


# ---------------------------------------------------------------------------
# param-tree transform
# ---------------------------------------------------------------------------

def _is_trunk_dense(path: tuple[str, ...]) -> bool:
    return (len(path) >= 2 and path[-1] in _TRUNK_DENSE.get(path[-2], ()))


def _walk(tree, path=()):
    """Yield ``(path, module_dict)`` for every trunk dense holding a kernel."""
    if not isinstance(tree, dict) and not hasattr(tree, "items"):
        return
    for name, sub in tree.items():
        sub_path = path + (name,)
        if _is_trunk_dense(sub_path) and hasattr(sub, "items") and "kernel" in sub:
            yield sub_path, sub
        else:
            yield from _walk(sub, sub_path)


def quantize_params(params):
    """One-shot w8a16 transform of a DiffusionViT ``params`` tree.

    Every trunk dense (``attn/{qkv,proj}``, ``mlp/{fc1,fc2}``) has its
    ``kernel`` replaced by ``{w_int8, scale}``; biases and every non-trunk
    leaf pass through untouched. The tree topology (module paths) is
    preserved, so partition-spec derivation and the engine's param flow see
    the same structure. The result is what ``model.clone(quant=...)``'s
    forward consumes (models/vit.py routes the trunk through
    :class:`QuantDense`).
    """
    def rec(tree, path=()):
        if not hasattr(tree, "items"):
            return tree
        out = {}
        for name, sub in tree.items():
            sub_path = path + (name,)
            if (_is_trunk_dense(sub_path) and hasattr(sub, "items")
                    and "kernel" in sub):
                w_int8, scale = quantize_weight(sub["kernel"])
                mod = {k: v for k, v in sub.items() if k != "kernel"}
                mod["w_int8"], mod["scale"] = w_int8, scale
                out[name] = mod
            else:
                out[name] = rec(sub, sub_path)
        return out

    return rec(params)


def is_quantized(params) -> bool:
    """True when the tree carries at least one ``w_int8`` trunk leaf."""
    found = []

    def rec(tree):
        if hasattr(tree, "items"):
            for name, sub in tree.items():
                if name == "w_int8":
                    found.append(True)
                rec(sub)

    rec(params)
    return bool(found)


def param_bytes(params) -> int:
    """Total bytes of every array leaf — the H2D param-traffic number the
    serving engine reports (int8 trunks ship ≈4× fewer)."""
    return int(sum(leaf.size * jnp.dtype(leaf.dtype).itemsize
                   for leaf in jax.tree_util.tree_leaves(params)))


def calibrate(params) -> dict:
    """Per-layer quantization error stats: for every trunk dense, the
    worst-case absolute weight error, the worst error relative to the
    channel's own scale (≤ 0.5 by construction — a larger value means the
    codec is broken for that layer) and the scale range. Keys are
    '/'-joined module paths, so a bad layer in the paired Fréchet guard is
    attributable by name."""
    stats = {}
    for path, mod in _walk(params):
        w_int8, scale = quantize_weight(mod["kernel"])
        err = jnp.abs(jnp.asarray(mod["kernel"], jnp.float32)
                      - w_int8.astype(jnp.float32) * scale)
        stats["/".join(path)] = {
            "max_abs_err": float(jnp.max(err)),
            "max_err_over_scale": float(jnp.max(err / scale)),
            "scale_min": float(jnp.min(scale)),
            "scale_max": float(jnp.max(scale)),
            "shape": tuple(int(d) for d in mod["kernel"].shape),
        }
    return stats


# ---------------------------------------------------------------------------
# w8a16 matmul — XLA path
# ---------------------------------------------------------------------------

def _dequant_matmul_xla(x: jax.Array, w_int8: jax.Array, scale: jax.Array,
                        bias: Optional[jax.Array] = None) -> jax.Array:
    """``x @ (w_int8 * scale)`` without materializing the dequantized weight:
    the int8→activation-dtype convert fuses into the matmul operand read and
    the per-column scale (+ optional bias) into the f32 epilogue.
    Accumulation is f32 (``preferred_element_type``), the w8a16 contract."""
    w = w_int8.astype(x.dtype)
    y = jax.lax.dot_general(
        x, w, (((x.ndim - 1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    y = y * scale
    if bias is not None:
        y = y + bias
    return y


# ---------------------------------------------------------------------------
# w8a8 — dynamic activation quantization
# ---------------------------------------------------------------------------

def quantize_act(x: jax.Array) -> tuple[jax.Array, jax.Array]:
    """Per-TENSOR symmetric dynamic int8 quantization of an activation:
    ``scale = max|x|/127`` (1.0 for an all-zero tensor), round-to-nearest
    codes clipped to [−127, 127] — the activation half of the "w8a8" mode.
    Per-tensor (not per-channel): the scale is one scalar folded into the
    weight's per-column scales at the GEMM epilogue, so the int8×int8 MXU
    path needs no extra per-element work."""
    xf = x.astype(jnp.float32)
    amax = jnp.max(jnp.abs(xf))
    scale = jnp.where(amax > 0, amax / 127.0, 1.0).astype(jnp.float32)
    codes = jnp.clip(jnp.round(xf / scale), -127.0, 127.0)
    return codes.astype(jnp.int8), scale


def _dequant_matmul_w8a8(x: jax.Array, w_int8: jax.Array, scale: jax.Array,
                         bias: Optional[jax.Array] = None) -> jax.Array:
    """int8×int8 GEMM with int32 MXU accumulation: activations quantized
    on the fly (per-tensor dynamic scale), both scales (+ optional bias)
    applied once in the f32 epilogue. The unfused "w8a8" reference the
    fused kernels are guard-checked against."""
    xi, xs = quantize_act(x)
    y = jax.lax.dot_general(
        xi, w_int8, (((x.ndim - 1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32)
    y = y.astype(jnp.float32) * (xs * scale)
    if bias is not None:
        y = y + bias
    return y


# ---------------------------------------------------------------------------
# w8a16 matmul — Pallas fused kernel
# ---------------------------------------------------------------------------

def _use_kernel() -> bool:
    # same policy as ops/flash_attention.py: TPU compiles the kernel, CPU
    # interprets it (tests exercise the identical code path), any other
    # backend (GPU) takes the XLA form instead of a silent interpreter crawl
    return jax.default_backend() in ("tpu", "cpu")


def _mm_kernel(*refs, n_k: int, has_bias: bool):
    """One (m-tile, n-tile, k-chunk) program: dequantize this int8 weight
    chunk to the activation dtype in VMEM, fold its partial product into the
    f32 accumulator, and on the last chunk apply the per-column scale (and
    bias, when the caller fuses it) once and emit. K is the innermost
    (sequential) grid axis, so the scratch accumulator carries across chunks
    of one output tile.

    The bias rides INSIDE the kernel (not as a caller-side epilogue) so the
    ``acc·s + b`` contraction happens at the same point in every path: the
    fused trunk kernels keep their scale-multiply and bias-add adjacent, and
    XLA:CPU contracts adjacent multiply+add into a single-rounding fma —
    with the add on the other side of the kernel boundary the unfused path
    would round twice and the f32 bitwise-parity contract would break by one
    ulp (tests/test_fusion.py pins the contract)."""
    if has_bias:
        x_ref, w_ref, s_ref, b_ref, o_ref, acc_ref = refs
    else:
        x_ref, w_ref, s_ref, o_ref, acc_ref = refs
        b_ref = None
    k_i = pl.program_id(2)

    @pl.when(k_i == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)

    x = x_ref[...]                      # (bm, bk) activation dtype
    w = w_ref[...].astype(x.dtype)      # (bk, bn) int8 → activation dtype
    acc_ref[...] += jax.lax.dot_general(
        x, w, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32)

    @pl.when(k_i == n_k - 1)
    def _emit():
        y = acc_ref[...] * s_ref[0]
        if has_bias:
            y = y + b_ref[0]
        o_ref[...] = y


def _round_up(n: int, m: int) -> int:
    return -(-n // m) * m


def _pad_axis(x: jax.Array, axis: int, to: int) -> jax.Array:
    pad = to - x.shape[axis]
    if pad <= 0:
        return x
    widths = [(0, 0)] * x.ndim
    widths[axis] = (0, pad)
    return jnp.pad(x, widths)


@functools.partial(jax.jit, static_argnames=("block_m", "block_n", "block_k"))
def _dequant_matmul_pallas(x2d: jax.Array, w_int8: jax.Array, scale: jax.Array,
                           bias: Optional[jax.Array] = None,
                           *, block_m: int = 256, block_n: int = 512,
                           block_k: int = 512) -> jax.Array:
    """Fused dequant-matmul on a 2-D ``(M, K) @ (K, N)`` problem.

    Tiling honors the TPU tile rules: K blocks are lane-width (128) aligned
    (covering the int8 (32, 128) min tile on the weight's sublane dim), N
    blocks lane-aligned, M blocks sublane (8) aligned. Zero-padding is
    inert — padded K rows of the weight contribute zero partial products,
    padded M/N rows/columns are sliced off the output.
    """
    M, K = x2d.shape
    _, N = w_int8.shape
    # pad-or-clamp to Mosaic-legal blocks (ops/tiling.py): M is the
    # activation's sublane dim (8 at f32, 16 at bf16); N is a lane dim; K is
    # the activation's LANE dim and the int8 weight's SUBLANE dim at once,
    # so it must also divide by int8's 32-sublane unit (128 % 32 == 0 —
    # folded in explicitly so the constraint survives a lane-width change)
    bm = tiling.legal_block(block_m, M, x2d.dtype)
    bn = tiling.legal_block(block_n, N, jnp.float32, lane=True)
    bk = tiling.legal_block(block_k, K, x2d.dtype, lane=True,
                            min_unit=jnp.int8)
    xp = _pad_axis(_pad_axis(x2d, 0, _round_up(M, bm)), 1, _round_up(K, bk))
    wp = _pad_axis(_pad_axis(w_int8, 0, _round_up(K, bk)), 1, _round_up(N, bn))
    sp = _pad_axis(scale.astype(jnp.float32)[None, :], 1, _round_up(N, bn))
    n_k = xp.shape[1] // bk

    inputs = [xp, wp, sp]
    in_specs = [
        pl.BlockSpec((bm, bk), lambda i, j, kk: (i, kk)),
        pl.BlockSpec((bk, bn), lambda i, j, kk: (kk, j)),
        pl.BlockSpec((1, bn), lambda i, j, kk: (0, j)),
    ]
    if bias is not None:
        inputs.append(_pad_axis(bias.astype(jnp.float32)[None, :], 1,
                                _round_up(N, bn)))
        in_specs.append(pl.BlockSpec((1, bn), lambda i, j, kk: (0, j)))

    with profiling.scope("dequant_matmul/pallas"):
        out = pl.pallas_call(
            functools.partial(_mm_kernel, n_k=n_k,
                              has_bias=bias is not None),
            grid=(xp.shape[0] // bm, wp.shape[1] // bn, n_k),
            in_specs=in_specs,
            out_specs=pl.BlockSpec((bm, bn), lambda i, j, kk: (i, j)),
            out_shape=jax.ShapeDtypeStruct((xp.shape[0], wp.shape[1]),
                                           jnp.float32),
            scratch_shapes=[pltpu.VMEM((bm, bn), jnp.float32)],
            compiler_params=_CompilerParams(
                dimension_semantics=("parallel", "parallel", "arbitrary")),
            interpret=jax.default_backend() == "cpu",
        )(*inputs)
    return out[:M, :N]


# ---------------------------------------------------------------------------
# public matmul entry
# ---------------------------------------------------------------------------

def dequant_matmul(x: jax.Array, w_int8: jax.Array, scale: jax.Array,
                   *, bias: Optional[jax.Array] = None,
                   mode: str = "xla") -> jax.Array:
    """Quantized matmul over the last axis of ``x``: ``x @ (w_int8·scale)
    [+ bias]`` with f32 accumulation; returns f32 (callers cast to the
    compute dtype — one epilogue for every mode). The bias is fused into
    the kernel epilogue rather than added by the caller so the scale·acc+b
    contraction point is identical across the unfused and fused trunk paths
    (see ``_mm_kernel``). ``mode="pallas"`` runs the fused w8a16 kernel
    where capability allows and silently takes the XLA form elsewhere,
    exactly the flash-attention fallback policy. ``mode="w8a8"`` quantizes
    the activation too (per-tensor dynamic scale, int8×int8 GEMM) — the
    unfused reference for the fused w8a8 kernels."""
    if mode not in QUANT_MODES:
        raise ValueError(f"quant mode must be one of {QUANT_MODES}, got {mode!r}")
    if w_int8.dtype != jnp.int8:
        raise ValueError(f"w_int8 must be int8, got {w_int8.dtype}")
    if mode == "w8a8":
        return _dequant_matmul_w8a8(x, w_int8, scale, bias)
    if mode == "pallas" and _use_kernel():
        lead = x.shape[:-1]
        y = _dequant_matmul_pallas(x.reshape(-1, x.shape[-1]), w_int8,
                                   scale, bias)
        return y.reshape(*lead, w_int8.shape[-1])
    return _dequant_matmul_xla(x, w_int8, scale, bias)


# ---------------------------------------------------------------------------
# fused Mlp kernel (matmul → bias → exact GELU → matmul)
# ---------------------------------------------------------------------------

def _mlp_kernel(*refs, quant: bool, w8a8: bool, has_b2: bool, cdt):
    """One M-tile program of the fused Mlp: fc1 GEMM into the f32 scratch
    accumulator, bias + exact (erf) GELU in VMEM, fc2 GEMM straight out —
    the (M, hidden) activation never exists in HBM. Weights ride whole-array
    VMEM blocks (trunk Mlp weights are ≤ a few hundred KiB); ``quant``
    selects int8 weights dequantized at the MXU feed (w8a16), ``w8a8``
    additionally feeds int8 activations (int32 accumulation, per-tensor
    scale pre-folded by the wrapper; the hidden activation requantizes per
    M-tile). Numerics mirror the unfused ``Dense → gelu → Dense`` /
    ``QuantDense → gelu → QuantDense`` compositions term for term."""
    b2_ref = None
    if quant and has_b2:
        (x_ref, w1_ref, s1_ref, b1_ref, w2_ref, s2_ref, b2_ref,
         o_ref, acc_ref) = refs
    elif quant:
        x_ref, w1_ref, s1_ref, b1_ref, w2_ref, s2_ref, o_ref, acc_ref = refs
    elif has_b2:
        x_ref, w1_ref, b1_ref, w2_ref, b2_ref, o_ref, acc_ref = refs
        s1_ref = s2_ref = None
    else:
        x_ref, w1_ref, b1_ref, w2_ref, o_ref, acc_ref = refs
        s1_ref = s2_ref = None
    x = x_ref[...]  # (bm, K) compute dtype (w8a8: int8)
    if w8a8:
        y1 = jax.lax.dot_general(
            x, w1_ref[...], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.int32).astype(jnp.float32) * s1_ref[0]
    elif quant:
        y1 = jax.lax.dot_general(
            x, w1_ref[...].astype(cdt), (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32) * s1_ref[0]
    else:
        y1 = jax.lax.dot_general(
            x, w1_ref[...].astype(cdt), (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
    acc_ref[...] = y1 + b1_ref[0]  # f32 accumulator, f32 bias epilogue
    h = jax.nn.gelu(acc_ref[...].astype(cdt), approximate=False)
    if w8a8:
        amax = jnp.max(jnp.abs(h.astype(jnp.float32)))
        hs = jnp.where(amax > 0, amax / 127.0, 1.0)
        hi = jnp.clip(jnp.round(h.astype(jnp.float32) / hs),
                      -127.0, 127.0).astype(jnp.int8)
        y2 = jax.lax.dot_general(
            hi, w2_ref[...], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.int32).astype(jnp.float32)
        y2 = y2 * (hs * s2_ref[0])
    elif quant:
        y2 = jax.lax.dot_general(
            h, w2_ref[...].astype(cdt), (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32) * s2_ref[0]
    else:
        y2 = jax.lax.dot_general(
            h, w2_ref[...].astype(cdt), (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
    if has_b2:
        # fc2 bias fused at the scale-multiply (same contraction point as
        # the unfused QuantDense / Dense epilogue — see _mm_kernel)
        y2 = y2 + b2_ref[0]
    o_ref[...] = y2  # f32; the wrapper casts to the compute dtype


def mlp_pallas(x, w1, b1, w2, b2, *, scale1=None, scale2=None,
               mode: Optional[str] = None, block_m: int = 256) -> jax.Array:
    """Fused Mlp trunk ``x @ w1 + b1 → exact GELU → @ w2 + b2`` as ONE
    Pallas kernel — replaces the two ``nn.Dense`` + ``nn.gelu`` ops in
    ``Mlp.__call__`` behind the same capability gating as the flash kernel.

    ``mode=None``: float weights (``w1``/``w2`` are the dense kernels).
    ``mode="pallas"``: w8a16 — int8 weights with per-column f32 scales.
    ``mode="w8a8"``: int8 weights AND per-tensor dynamic int8 activations.
    Returns ``x.dtype``, full bias epilogues included; off TPU/CPU takes the
    unfused XLA composition (same fallback policy as flash/dequant)."""
    if mode not in (None, "pallas", "w8a8"):
        raise ValueError(f"mlp_pallas mode must be None, 'pallas' or "
                         f"'w8a8', got {mode!r}")
    quant = mode is not None
    if quant and (scale1 is None or scale2 is None):
        raise ValueError(f"mode={mode!r} needs scale1/scale2 (the w8a16 "
                         "per-column weight scales)")
    cdt = x.dtype
    lead, K = x.shape[:-1], x.shape[-1]
    Hf, Nout = w1.shape[-1], w2.shape[-1]
    if not _use_kernel():
        # unfused XLA composition (GPU etc.) — the same epilogues
        if mode is None:
            y1 = jax.lax.dot_general(
                x, w1.astype(cdt), (((x.ndim - 1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32) + b1
        else:
            mm = _dequant_matmul_w8a8 if mode == "w8a8" else _dequant_matmul_xla
            y1 = mm(x, w1, scale1, b1)
        h = jax.nn.gelu(y1.astype(cdt), approximate=False)
        if mode is None:
            y2 = jax.lax.dot_general(
                h, w2.astype(cdt), (((h.ndim - 1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
            if b2 is not None:
                y2 = y2 + b2
        else:
            y2 = mm(h, w2, scale2, b2)
        return y2.astype(cdt)

    if mode == "w8a8":
        xi, xs = quantize_act(x)
        x2d = xi.reshape(-1, K)
        s1_eff = scale1.astype(jnp.float32) * xs
    else:
        x2d = x.reshape(-1, K)
        s1_eff = None if scale1 is None else scale1.astype(jnp.float32)
    M = x2d.shape[0]
    bm = tiling.legal_block(block_m, M, x2d.dtype)
    xp = _pad_axis(x2d, 0, _round_up(M, bm))

    inputs = [xp, w1]
    in_specs = [pl.BlockSpec((bm, K), lambda i: (i, 0)),
                pl.BlockSpec((K, Hf), lambda i: (0, 0))]
    if quant:
        inputs.append(s1_eff[None, :])
        in_specs.append(pl.BlockSpec((1, Hf), lambda i: (0, 0)))
    inputs.append(b1.astype(jnp.float32)[None, :])
    in_specs.append(pl.BlockSpec((1, Hf), lambda i: (0, 0)))
    inputs.append(w2)
    in_specs.append(pl.BlockSpec((Hf, Nout), lambda i: (0, 0)))
    if quant:
        inputs.append(scale2.astype(jnp.float32)[None, :])
        in_specs.append(pl.BlockSpec((1, Nout), lambda i: (0, 0)))
    if b2 is not None:
        inputs.append(b2.astype(jnp.float32)[None, :])
        in_specs.append(pl.BlockSpec((1, Nout), lambda i: (0, 0)))

    with profiling.scope("mlp/pallas"):
        out = pl.pallas_call(
            functools.partial(_mlp_kernel, quant=quant,
                              w8a8=mode == "w8a8",
                              has_b2=b2 is not None, cdt=cdt),
            grid=(xp.shape[0] // bm,),
            in_specs=in_specs,
            out_specs=pl.BlockSpec((bm, Nout), lambda i: (i, 0)),
            out_shape=jax.ShapeDtypeStruct((xp.shape[0], Nout), jnp.float32),
            scratch_shapes=[pltpu.VMEM((bm, Hf), jnp.float32)],
            compiler_params=_CompilerParams(
                dimension_semantics=("parallel",)),
            interpret=jax.default_backend() == "cpu",
        )(*inputs)
    return out[:M].astype(cdt).reshape(*lead, Nout)


class QuantParams(nn.Module):
    """Declares the ``{w_int8, scale[, bias]}`` leaves of a :class:`QuantDense`
    WITHOUT computing the matmul — the fused trunk kernels consume the raw
    leaves. Same param names, shapes, dtypes and initializers as QuantDense
    (and the same module path when given the same ``name``), so a fused and
    an unfused model share one param tree interchangeably and
    ``quantize_params`` output loads into either."""

    features: int
    use_bias: bool = True

    @nn.compact
    def __call__(self, in_features: int):
        w_int8 = self.param("w_int8", nn.initializers.zeros_init(),
                            (in_features, self.features), jnp.int8)
        scale = self.param("scale", nn.initializers.ones_init(),
                           (self.features,), jnp.float32)
        bias = (self.param("bias", nn.initializers.zeros_init(),
                           (self.features,), jnp.float32)
                if self.use_bias else None)
        return w_int8, scale, bias


class QuantDense(nn.Module):
    """Drop-in for ``nn.Dense`` over a quantized kernel: declares the
    ``{w_int8, scale[, bias]}`` leaves ``quantize_params`` produces (same
    module path/name as the dense it replaces) and computes the w8a16 matmul.
    Zero-init params make ``model.init`` legal on a quant model, but the
    intended flow is quantizing a trained float tree."""

    features: int
    use_bias: bool = True
    dtype: Any = jnp.float32
    mode: str = "xla"

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        w_int8 = self.param("w_int8", nn.initializers.zeros_init(),
                            (x.shape[-1], self.features), jnp.int8)
        scale = self.param("scale", nn.initializers.ones_init(),
                           (self.features,), jnp.float32)
        bias = (self.param("bias", nn.initializers.zeros_init(),
                           (self.features,), jnp.float32)
                if self.use_bias else None)
        # bias fused into the matmul epilogue — the contraction point must
        # match the fused trunk kernels' (see _mm_kernel docstring)
        y = dequant_matmul(x.astype(self.dtype), w_int8, scale, bias=bias,
                           mode=self.mode)
        return y.astype(self.dtype)
