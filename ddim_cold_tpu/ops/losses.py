"""Losses. The reference trains and evaluates exclusively with mean
smooth-L1 (Huber, beta=1) — ``F.smooth_l1_loss`` at multi_gpu_trainer.py:43,124."""

from __future__ import annotations

import jax.numpy as jnp


def smooth_l1(pred: jnp.ndarray, target: jnp.ndarray, beta: float = 1.0) -> jnp.ndarray:
    """Mean smooth-L1: 0.5·d²/beta for |d| < beta, |d| − 0.5·beta otherwise."""
    d = jnp.abs(pred.astype(jnp.float32) - target.astype(jnp.float32))
    return jnp.mean(jnp.where(d < beta, 0.5 * d * d / beta, d - 0.5 * beta))
