"""TPU tile-geometry legality for the Pallas kernels (ops/flash_attention.py,
ops/quant.py).

Mosaic accepts a VMEM block only when each of its last two dims is either a
multiple of the dtype's minimum tile — sublane × lane: f32 (8, 128),
bf16/f16 (16, 128), int8 (32, 128) — or spans the whole array dim on that
axis. The kernels' old ``min(requested, dim)`` clamp could produce illegal
shapes: a hand-tuned odd block at a non-divisible token count (N = 2501, the
200px/p4 model, is the in-repo worst case) passes CPU interpret mode — which
does NOT enforce the rule and is what CI exercises — then Mosaic rejects it
in the one hardware window. A sub-16 sublane block on a bf16 model fails the
same way even at aligned Ns.

:func:`legal_block` is the single pad-or-clamp policy both kernels now
route every requested block size through. Pure host arithmetic on static
shapes — the regression tests assert legality at the exact 200px geometries
without a TPU attached.
"""

from __future__ import annotations

import numpy as np

#: TPU lane width — minimum last-dim tile unit for every dtype
LANE = 128


def round_up(n: int, m: int) -> int:
    return -(-n // m) * m


def sublane_unit(dtype) -> int:
    """Minimum second-minor (sublane) block unit for ``dtype``: 8 at 32-bit,
    16 at 16-bit, 32 at 8-bit — packing narrower dtypes keeps one
    (unit, 128) tile at the same 4 KiB of VMEM."""
    bits = np.dtype(dtype).itemsize * 8
    try:
        return {32: 8, 16: 16, 8: 32}[bits]
    except KeyError:
        raise ValueError(
            f"no TPU tile rule for {np.dtype(dtype)} ({bits}-bit)") from None


def legal_block(requested: int, dim: int, dtype, *, lane: bool = False,
                min_unit: int = 1) -> int:
    """Clamp a requested Pallas block size to a Mosaic-legal one for an
    array dim of ``dim`` elements of ``dtype``.

    ``lane=False`` legalizes a sublane (second-minor) block dim,
    ``lane=True`` a lane (minor) one. ``min_unit`` folds in an extra
    divisibility constraint when one block size tiles two arrays of
    different dtypes (e.g. the dequant matmul's K block is the activation's
    lane dim AND the int8 weight's sublane dim).

    Policy: round the request UP to the unit (never down — a shrunk block
    re-tiles the grid, a grown one only pads VMEM), then clamp to the
    unit-padded dim so a single block spans small arrays. The caller pads
    the array to a multiple of the returned block, which the unit-multiple
    guarantee keeps legal.
    """
    if requested < 1:
        raise ValueError(f"block size must be >= 1, got {requested}")
    if dim < 1:
        raise ValueError(f"array dim must be >= 1, got {dim}")
    unit = LANE if lane else sublane_unit(dtype)
    # int(): np.gcd promotes the lcm to np.int64, which would propagate into
    # every grid entry computed from the block — Pallas treats a non-Python-
    # int grid dim as DYNAMIC (DynamicGridDim), silently forfeiting the
    # static-grid scheduling the kernels are written for (graftcheck P001
    # proves all in-tree grids fully static)
    unit = int(unit * min_unit // np.gcd(unit, min_unit))  # lcm
    full = round_up(dim, unit)
    return min(round_up(requested, unit), full)
