"""TPU tile-geometry legality for the Pallas kernels (ops/flash_attention.py,
ops/quant.py).

Mosaic accepts a VMEM block only when each of its last two dims is either a
multiple of the dtype's minimum tile — sublane × lane: f32 (8, 128),
bf16/f16 (16, 128), int8 (32, 128) — or spans the whole array dim on that
axis. The kernels' old ``min(requested, dim)`` clamp could produce illegal
shapes: a hand-tuned odd block at a non-divisible token count (N = 2501, the
200px/p4 model, is the in-repo worst case) passes CPU interpret mode — which
does NOT enforce the rule and is what CI exercises — then Mosaic rejects it
in the one hardware window. A sub-16 sublane block on a bf16 model fails the
same way even at aligned Ns.

:func:`legal_block` is the single pad-or-clamp policy both kernels now
route every requested block size through. Pure host arithmetic on static
shapes — the regression tests assert legality at the exact 200px geometries
without a TPU attached.
"""

from __future__ import annotations

import numpy as np

#: TPU lane width — minimum last-dim tile unit for every dtype
LANE = 128


def round_up(n: int, m: int) -> int:
    return -(-n // m) * m


def sublane_unit(dtype) -> int:
    """Minimum second-minor (sublane) block unit for ``dtype``: 8 at 32-bit,
    16 at 16-bit, 32 at 8-bit — packing narrower dtypes keeps one
    (unit, 128) tile at the same 4 KiB of VMEM."""
    bits = np.dtype(dtype).itemsize * 8
    try:
        return {32: 8, 16: 16, 8: 32}[bits]
    except KeyError:
        raise ValueError(
            f"no TPU tile rule for {np.dtype(dtype)} ({bits}-bit)") from None


def _unit_of(spec) -> int:
    """One ``min_unit`` constraint → its divisibility unit: an int passes
    through; anything else is treated as a dtype whose SUBLANE unit the
    co-tiled operand imposes (the lane unit is dtype-independent — callers
    fold it via ``lane=True`` on the primary operand)."""
    if isinstance(spec, (int, np.integer)):
        u = int(spec)
        if u < 1:
            raise ValueError(f"min_unit must be >= 1, got {spec!r}")
        return u
    return sublane_unit(spec)


def legal_block(requested: int, dim: int, dtype, *, lane: bool = False,
                min_unit=1) -> int:
    """Clamp a requested Pallas block size to a Mosaic-legal one for an
    array dim of ``dim`` elements of ``dtype``.

    ``lane=False`` legalizes a sublane (second-minor) block dim,
    ``lane=True`` a lane (minor) one. ``min_unit`` folds in extra
    divisibility constraints when one block size tiles several arrays of
    different dtypes: an int (a raw unit), a dtype (that dtype's SUBLANE
    unit), or a sequence of either. The fused trunk kernels hit the
    dual-dtype case head-on — the dequant matmul's K block is the f32/bf16
    activation's lane dim AND the int8 weight's sublane dim, so BOTH the
    128-lane and the 32-sublane constraints must hold in the one block spec
    (previously each operand was legalized separately at the call site,
    which cannot express the conjunction).

    Policy: round the request UP to the unit (never down — a shrunk block
    re-tiles the grid, a grown one only pads VMEM), then clamp to the
    unit-padded dim so a single block spans small arrays. The caller pads
    the array to a multiple of the returned block, which the unit-multiple
    guarantee keeps legal.
    """
    if requested < 1:
        raise ValueError(f"block size must be >= 1, got {requested}")
    if dim < 1:
        raise ValueError(f"array dim must be >= 1, got {dim}")
    unit = LANE if lane else sublane_unit(dtype)
    specs = min_unit if isinstance(min_unit, (tuple, list)) else (min_unit,)
    for spec in specs:
        extra = _unit_of(spec)
        # int(): np.gcd promotes the lcm to np.int64, which would propagate
        # into every grid entry computed from the block — Pallas treats a
        # non-Python-int grid dim as DYNAMIC (DynamicGridDim), silently
        # forfeiting the static-grid scheduling the kernels are written for
        # (graftcheck P001 proves all in-tree grids fully static)
        unit = int(unit * extra // np.gcd(unit, extra))  # lcm
    full = round_up(dim, unit)
    return min(round_up(requested, unit), full)
