from ddim_cold_tpu.ops import schedule, step_cache

__all__ = ["schedule", "step_cache"]
