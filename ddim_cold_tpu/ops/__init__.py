from ddim_cold_tpu.ops import schedule

__all__ = ["schedule"]
