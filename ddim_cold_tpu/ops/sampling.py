"""Samplers — the inference "scheduler" layer, as jitted ``lax.scan`` loops.

Replaces the reference's Python-loop samplers (methods on the torch model):

* ``ddim_sample``      ← ``sampler``             (reference ViT.py:220-237)
* ``ddim_sample(..., return_sequence=True)``
                       ← ``diffusion_sequence``  (reference ViT.py:239-256)
* ``cold_sample``      ← ``cold_sampler``        (reference ViT_draft2drawing.py:259-288)
* ``cold_sample(..., return_sequence=True)``
                       ← ``cold_diffusion_sequence`` (reference ViT_draft2drawing.py:290-309)
* ``sample_from``      ← the draft2drawing inner loop (reference
                          ViT_draft2drawing.py:394-408) — DDIM from an
                          arbitrary start level, the guided-sampling primitive
                          that also expresses slerp interpolation (C25)
* ``forward_noise``    ← ``√(1−ᾱ)·ε + √ᾱ·x`` encoding (ViT_draft2drawing.py:395-396)

Design: each reverse step is affine in (x, x̂0) — the per-step coefficients are
precomputed host-side (ops/schedule.py) and fed to a single ``lax.scan`` whose
body is one model forward + clamp + two fused multiply-adds. There is no
host↔device traffic until the final gather; k, N, T are static so XLA compiles
one program per (model, stride) pair. The reference's per-step ``print`` timing
is replaced by ``jax.profiler`` tracing (utils/profiling.py).
"""

from __future__ import annotations

import math
from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp

from ddim_cold_tpu.obs.device import StepTelemetry
from ddim_cold_tpu.ops import schedule, step_cache
from ddim_cold_tpu.utils import profiling


def forward_noise(rng: jax.Array, img: jax.Array, t_start: int, total_steps: int = 2000):
    """Encode a clean image to noise level ``t_start``.

    ᾱ here is ``1 − √(t_start/T)`` — no +1, matching the draft2drawing app
    (reference ViT_draft2drawing.py:395), not the sampler's ``(t+1)/T``.
    """
    alpha = schedule.forward_noise_alpha(t_start, total_steps)
    eps = jax.random.normal(rng, img.shape, img.dtype)
    return math.sqrt(alpha) * img + math.sqrt(1.0 - alpha) * eps


def _ddim_step_update(x, x0, t, c1, c2, cz, noise_rng, eta: float):
    """One reverse-step update shared by both scan variants: the affine
    (cx, cx0) move plus, for stochastic DDIM (eta>0), fresh per-step noise
    keyed by folding t — one definition so the sequence and last-only paths
    can never sample from different stochastic processes."""
    x_next = c1 * x + c2 * x0
    if eta:
        z = jax.random.normal(jax.random.fold_in(noise_rng, t),
                              x.shape, x.dtype)
        x_next = x_next + cz * z
    return x_next


def _scan_inputs(coeffs):
    return (jnp.asarray(coeffs.t_seq), jnp.asarray(coeffs.cx),
            jnp.asarray(coeffs.cx0), jnp.asarray(coeffs.cz))


@partial(jax.jit, static_argnames=("model", "k", "t_start", "eta"))
def _ddim_scan_sequence(model, params, x_init, noise_rng, *, k: int,
                        t_start: Optional[int], eta: float = 0.0):
    coeffs = schedule.ddim_coefficients(model.total_steps, k, t_start, eta)
    n = x_init.shape[0]

    def step(x, inputs):
        t, c1, c2, cz = inputs
        with profiling.scope("sampler/model"):
            x0 = model.apply({"params": params}, x,
                             jnp.full((n,), t, jnp.int32))
        x0 = jnp.clip(x0, -1.0, 1.0)
        return _ddim_step_update(x, x0, t, c1, c2, cz, noise_rng, eta), x0

    _, x0_out = jax.lax.scan(step, x_init, _scan_inputs(coeffs))
    # frames: the initial noisy image, then every x̂0 prediction — matching the
    # reference's recorded trajectory (ViT.py:244,254).
    frames = jnp.concatenate([x_init[None], x0_out], axis=0)
    return (frames + 1.0) / 2.0


@partial(jax.jit, static_argnames=("model", "k", "t_start", "eta"),
         donate_argnames=("x_init",))
def _ddim_scan_last(model, params, x_init, noise_rng, *, k: int,
                    t_start: Optional[int], eta: float = 0.0):
    coeffs = schedule.ddim_coefficients(model.total_steps, k, t_start, eta)
    n = x_init.shape[0]

    def step(carry, inputs):
        x, _ = carry
        t, c1, c2, cz = inputs
        with profiling.scope("sampler/model"):
            x0 = model.apply({"params": params}, x,
                             jnp.full((n,), t, jnp.int32))
        x0 = jnp.clip(x0, -1.0, 1.0)
        return (_ddim_step_update(x, x0, t, c1, c2, cz, noise_rng, eta),
                x0), None

    (_, x0_last), _ = jax.lax.scan(
        step, (x_init, jnp.zeros_like(x_init)), _scan_inputs(coeffs))
    # the sample is the LAST x̂0 prediction, not the final noisy state
    # (reference ViT.py:236 returns denoised_img).
    return (x0_last + 1.0) / 2.0


def _fewstep_impl(model, params, x_init, noise_rng, *, steps: int,
                  t_start: Optional[int], eta: float, sequence: bool):
    """The few-step (distilled-student) scan family: ``steps`` model
    evaluations along the proportional ``fewstep_time_sequence``, with the
    FINAL evaluation hoisted OUT of the scan. The hoist is licensed by the
    schedule algebra (schedule.fewstep_coefficients): the last jump targets
    the clean image (ᾱ = 1), where the affine update degenerates to
    x' = x̂₀ exactly — so the program is scan(steps−1 updates) + one bare
    forward, and ``steps=1`` compiles to a scan-free single forward. That
    structure is also what keeps every k∈{1,2,4} program STRUCTURALLY
    distinct from the stride family's equal-trip-count scans under
    graftcheck's constant-blind J006 signature (a k-strided scan of equal
    length would hash identically once the baked coefficients are ignored).
    """
    coeffs = schedule.fewstep_coefficients(model.total_steps, steps, t_start,
                                           eta)
    n = x_init.shape[0]

    def forward(x, t):
        with profiling.scope("sampler/model"):
            x0 = model.apply({"params": params}, x,
                             jnp.full((n,), t, jnp.int32))
        return jnp.clip(x0, -1.0, 1.0)

    x, x0_out = x_init, None
    if steps > 1:
        def step(x, inputs):
            t, c1, c2, cz = inputs
            x0 = forward(x, t)
            return (_ddim_step_update(x, x0, t, c1, c2, cz, noise_rng, eta),
                    x0 if sequence else None)

        x, x0_out = jax.lax.scan(
            step, x_init, tuple(a[:-1] for a in _scan_inputs(coeffs)))
    x0_last = forward(x, int(coeffs.t_seq[-1]))
    if sequence:
        frames = [x_init[None]] + ([x0_out] if x0_out is not None else []) \
            + [x0_last[None]]
        return (jnp.concatenate(frames, axis=0) + 1.0) / 2.0
    return (x0_last + 1.0) / 2.0


_FEWSTEP_STATICS = ("model", "steps", "t_start", "eta", "sequence")
#: last-only entry donates x_init (image output aliases it), mirroring the
#: stride family; the sequence entry never donates.
_ddim_scan_fewstep = jax.jit(_fewstep_impl, static_argnames=_FEWSTEP_STATICS,
                             donate_argnames=("x_init",))
_ddim_scan_fewstep_seq = jax.jit(_fewstep_impl,
                                 static_argnames=_FEWSTEP_STATICS)


def _fewstep_cached_impl(model, params, x_init, noise_rng, cache0, *,
                         steps: int, t_start: Optional[int], eta: float,
                         cache_interval: int, cache_mode: str,
                         cache_threshold=None, cache_tokens=None,
                         sequence: bool):
    """Few-step scan composed with the step cache (ops/step_cache.py): the
    first steps−1 evaluations route through ``apply_step`` inside the scan,
    and the hoisted final evaluation takes the schedule's LAST branch id
    outside it — the same refresh/reuse pattern a ``steps``-long cached
    stride scan would run, so the composition semantics (and the τ→0 /
    k_tok→all bitwise degeneracies) carry over unchanged. Returns
    ``(images, final_cache)`` for the engine's cache recycling."""
    coeffs = schedule.fewstep_coefficients(model.total_steps, steps, t_start,
                                           eta)
    spec = _cached_spec(model, steps, cache_interval, cache_mode,
                        cache_threshold, cache_tokens)
    n = x_init.shape[0]
    branches = jnp.asarray(spec.branches, jnp.int32)

    def evaluate(x, t, br, cache):
        with profiling.scope("sampler/cached_step"):
            x0_raw, cache = step_cache.apply_step(
                model, params, x, jnp.full((n,), t, jnp.int32), br, cache,
                spec)
        return jnp.clip(x0_raw, -1.0, 1.0), cache

    x, cache, x0_out = x_init, cache0, None
    if steps > 1:
        def step(carry, inputs):
            x, cache = carry
            (t, c1, c2, cz), br = inputs
            x0, cache = evaluate(x, t, br, cache)
            x_next = _ddim_step_update(x, x0, t, c1, c2, cz, noise_rng, eta)
            return (x_next, cache), (x0 if sequence else None)

        (x, cache), x0_out = jax.lax.scan(
            step, (x_init, cache0),
            (tuple(a[:-1] for a in _scan_inputs(coeffs)), branches[:-1]))
    x0_last, cache_out = evaluate(x, int(coeffs.t_seq[-1]), branches[-1],
                                  cache)
    if sequence:
        frames = [x_init[None]] + ([x0_out] if x0_out is not None else []) \
            + [x0_last[None]]
        return (jnp.concatenate(frames, axis=0) + 1.0) / 2.0, cache_out
    return (x0_last + 1.0) / 2.0, cache_out


_FEWSTEP_CACHED_STATICS = ("model", "steps", "t_start", "eta",
                           "cache_interval", "cache_mode", "cache_threshold",
                           "cache_tokens", "sequence")
#: donation mirrors the cached stride scan: x_init and the cache carry alias
#: outputs on the last-only entry; the sequence entry never donates.
_ddim_scan_fewstep_cached = jax.jit(
    _fewstep_cached_impl, static_argnames=_FEWSTEP_CACHED_STATICS,
    donate_argnames=("x_init", "cache0"))
_ddim_scan_fewstep_cached_seq = jax.jit(
    _fewstep_cached_impl, static_argnames=_FEWSTEP_CACHED_STATICS)


def ddim_sample_fewstep(
    model,
    params,
    rng: Optional[jax.Array] = None,
    *,
    steps: int,
    n: int = 128,
    x_init: Optional[jax.Array] = None,
    t_start: Optional[int] = None,
    return_sequence: bool = False,
    mesh=None,
    eta: float = 0.0,
    cache_interval: int = 1,
    cache_mode: str = "delta",
    cache_threshold: Optional[float] = None,
    cache_tokens: Optional[int] = None,
) -> jax.Array:
    """Few-step DDIM sampling: exactly ``steps`` model evaluations (the
    distilled-student serving path, k∈{1,2,4}); returns images in [0, 1].

    Where :func:`ddim_sample` fixes a STRIDE k (the step count falls out of
    T), this fixes the step COUNT along the proportional
    ``schedule.fewstep_time_sequence`` — one compiled program per ``steps``
    regardless of T, which is what ``SamplerConfig(steps=...)`` serves.
    Running a k=20-trained teacher through ``steps`` ≤ 4 is a (poor-quality)
    valid program — the intended params are a ``train/distill.py`` student,
    but nothing here checks provenance; ``eval/fid.py
    distilled_sampler_guard`` is the quality gate.

    ``rng``/``x_init``/``t_start``/``return_sequence``/``mesh``/``eta`` and
    the ``cache_*`` statics behave exactly as in :func:`ddim_sample`
    (guided private copy, data-axis SPMD, stochastic eta, step-cache
    composition).
    """
    if eta and rng is None:
        raise ValueError("eta > 0 draws per-step noise — pass rng")
    if x_init is None:
        if rng is None:
            raise ValueError("ddim_sample_fewstep needs either rng or x_init")
        H, W = model.img_size
        x_init = jax.random.normal(rng, (n, H, W, model.in_chans), jnp.float32)
    elif mesh is None and not return_sequence:
        # last-only scans donate x_init — guided starts enter via a private
        # copy, exactly like ddim_sample's guided path
        x_init = jnp.array(x_init, copy=True)
    x_init = _shard_init(x_init, mesh)
    noise_rng = (jax.random.fold_in(rng, 0xD1F) if rng is not None
                 else jax.random.PRNGKey(0))
    if step_cache.enabled(cache_interval):
        fn = (_ddim_scan_fewstep_cached_seq if return_sequence
              else _ddim_scan_fewstep_cached)
        out, _ = fn(
            model, params, x_init, noise_rng,
            _make_cache(model, x_init, mesh, cache_mode),
            steps=steps, t_start=t_start, eta=eta,
            cache_interval=cache_interval, cache_mode=cache_mode,
            cache_threshold=cache_threshold, cache_tokens=cache_tokens,
            sequence=return_sequence)
        return out
    fn = _ddim_scan_fewstep_seq if return_sequence else _ddim_scan_fewstep
    return fn(model, params, x_init, noise_rng, steps=steps, t_start=t_start,
              eta=eta, sequence=return_sequence)


def _cached_spec(model, n_steps: int, cache_interval: int, cache_mode: str,
                 cache_threshold, cache_tokens) -> step_cache.CacheSpec:
    """One spec-construction site for every cached scan: supplies the
    model-derived token count for "token" mode and forwards the adaptive
    threshold / top-k statics so ops/step_cache.py's per-mode validation
    fires identically from samplers, engine, and graftcheck mirrors."""
    return step_cache.cache_spec(
        model.depth, n_steps, cache_interval, cache_mode,
        threshold=cache_threshold, token_k=cache_tokens,
        n_tokens=(model.num_patches + 1) if cache_mode == "token" else None)


def _ddim_cached_impl(model, params, x_init, noise_rng, cache0, *, k: int,
                      t_start: Optional[int], eta: float,
                      cache_interval: int, cache_mode: str,
                      cache_threshold=None, cache_tokens=None,
                      sequence: bool):
    """The feature-cached DDIM scan (ops/step_cache.py): same affine update
    as the plain scans, but the model evaluation routes through a
    ``lax.switch`` over the static refresh/reuse schedule and the block-delta
    cache rides the carry. One impl serves both the last-only and
    sequence-returning paths (``sequence`` is static) so the cached and exact
    samplers can never drift onto different update algebra.

    Returns ``(images, final_cache)``: the cache comes back out so the
    donated ``cache0`` buffers alias it (free at the XLA level — the carry is
    already materialized) and so a serving loop can recycle one cache
    allocation across dispatches (the schedule's step 0 always refreshes, so
    stale contents are never read; serve/engine.py does exactly this)."""
    coeffs = schedule.ddim_coefficients(model.total_steps, k, t_start, eta)
    spec = _cached_spec(model, len(coeffs.t_seq), cache_interval, cache_mode,
                        cache_threshold, cache_tokens)
    n = x_init.shape[0]

    def step(carry, inputs):
        x, x0_prev, cache = carry
        (t, c1, c2, cz), br = inputs
        with profiling.scope("sampler/cached_step"):
            x0_raw, cache = step_cache.apply_step(
                model, params, x, jnp.full((n,), t, jnp.int32), br, cache,
                spec)
        x0 = jnp.clip(x0_raw, -1.0, 1.0)
        x_next = _ddim_step_update(x, x0, t, c1, c2, cz, noise_rng, eta)
        return (x_next, x0, cache), (x0 if sequence else None)

    carry0 = (x_init, jnp.zeros_like(x_init), cache0)
    branches = jnp.asarray(spec.branches, jnp.int32)
    (_, x0_last, cache_out), x0_out = jax.lax.scan(
        step, carry0, (_scan_inputs(coeffs), branches))
    if sequence:
        frames = jnp.concatenate([x_init[None], x0_out], axis=0)
        return (frames + 1.0) / 2.0, cache_out
    return (x0_last + 1.0) / 2.0, cache_out


_CACHED_STATICS = ("model", "k", "t_start", "eta", "cache_interval",
                   "cache_mode", "cache_threshold", "cache_tokens",
                   "sequence")
#: last-only entry point — donates x_init and the cache carry (both alias
#: outputs: the image is x_init-shaped f32, the returned cache matches
#: cache0), so the sampler never double-buffers x or the deltas in HBM.
_ddim_scan_cached = jax.jit(_ddim_cached_impl, static_argnames=_CACHED_STATICS,
                            donate_argnames=("x_init", "cache0"))
#: sequence entry point — NO donation: the (steps+1, N, H, W, C) frames
#: output matches neither donated shape, so donation here would only raise
#: jax's unused-donation warning (the figure path keeps the plain behavior).
_ddim_scan_cached_seq = jax.jit(_ddim_cached_impl,
                                static_argnames=_CACHED_STATICS)


def _ddim_cached_tel_impl(model, params, x_init, noise_rng, cache0, *, k: int,
                          t_start: Optional[int], eta: float,
                          cache_interval: int, cache_mode: str,
                          cache_threshold=None, cache_tokens=None):
    """``_ddim_cached_impl`` with on-device step telemetry: the same cached
    scan, but each step also stacks the cache branch ACTUALLY taken (the
    adaptive gate's post-promotion index — ``ops/step_cache.apply_step_tel``)
    and the gate's drift value into a static-shaped ``(n_steps,)`` aux.
    Last-only (no ``sequence`` static — previews and telemetry are separate
    products; serve/batching.py rejects the combination), so the telemetry
    program keys on one fewer static than the plain cached scan. Returns
    ``(images, final_cache, (branch, drift))``; the host side decodes the
    aux via ``obs.device.summarize``."""
    coeffs = schedule.ddim_coefficients(model.total_steps, k, t_start, eta)
    spec = _cached_spec(model, len(coeffs.t_seq), cache_interval, cache_mode,
                        cache_threshold, cache_tokens)
    n = x_init.shape[0]

    def step(carry, inputs):
        x, x0_prev, cache = carry
        (t, c1, c2, cz), br = inputs
        with profiling.scope("sampler/cached_step"):
            x0_raw, cache, idx, drift = step_cache.apply_step_tel(
                model, params, x, jnp.full((n,), t, jnp.int32), br, cache,
                spec)
        x0 = jnp.clip(x0_raw, -1.0, 1.0)
        x_next = _ddim_step_update(x, x0, t, c1, c2, cz, noise_rng, eta)
        return (x_next, x0, cache), (idx, drift)

    carry0 = (x_init, jnp.zeros_like(x_init), cache0)
    branches = jnp.asarray(spec.branches, jnp.int32)
    (_, x0_last, cache_out), (br_seq, drift_seq) = jax.lax.scan(
        step, carry0, (_scan_inputs(coeffs), branches))
    return (x0_last + 1.0) / 2.0, cache_out, (br_seq, drift_seq)


_CACHED_TEL_STATICS = ("model", "k", "t_start", "eta", "cache_interval",
                       "cache_mode", "cache_threshold", "cache_tokens")
#: donation mirrors the last-only cached scan (x_init/cache alias outputs;
#: the tiny (n_steps,) aux allocates fresh — negligible).
_ddim_scan_cached_tel = jax.jit(_ddim_cached_tel_impl,
                                static_argnames=_CACHED_TEL_STATICS,
                                donate_argnames=("x_init", "cache0"))


def _ddim_inpaint_impl(model, params, x_init, known, mask, noise_rng, *,
                       k: int, t_start: Optional[int], eta: float,
                       sequence: bool):
    """The inpainting scan (ddim_cold_tpu/workloads): plain DDIM with a
    per-step constraint — after each x̂0 prediction, the KNOWN pixels are
    re-projected from the reference image (``x̂0 ← m·known + (1−m)·x̂0``)
    before the affine update, so the reverse process is pulled toward a
    sample whose masked region agrees with ``known`` exactly. ``mask`` is a
    static-shaped (N, H, W, 1) float batch input of {0, 1} (1 = known); the
    projection is per-row, so the engine's coalescing keeps the bitwise
    contract, and padding rows (mask 0) pass through untouched. The final
    output is the LAST projected x̂0, hence known pixels are preserved
    bit-exactly (mask idempotence — tests/test_workloads.py pins it).
    ``sequence=True`` returns the (steps+1, N, H, W, C) trajectory of
    projected x̂0 predictions (the preview path)."""
    coeffs = schedule.ddim_coefficients(model.total_steps, k, t_start, eta)
    n = x_init.shape[0]

    def step(carry, inputs):
        x, _ = carry
        t, c1, c2, cz = inputs
        with profiling.scope("sampler/model"):
            x0 = model.apply({"params": params}, x,
                             jnp.full((n,), t, jnp.int32))
        x0 = jnp.clip(x0, -1.0, 1.0)
        x0 = mask * known + (1.0 - mask) * x0
        return (_ddim_step_update(x, x0, t, c1, c2, cz, noise_rng, eta),
                x0), (x0 if sequence else None)

    (_, x0_last), x0_out = jax.lax.scan(
        step, (x_init, jnp.zeros_like(x_init)), _scan_inputs(coeffs))
    if sequence:
        frames = jnp.concatenate([x_init[None], x0_out], axis=0)
        return (frames + 1.0) / 2.0
    return (x0_last + 1.0) / 2.0


_INPAINT_STATICS = ("model", "k", "t_start", "eta", "sequence")
#: last-only entry donates x_init (fresh noise, image output aliases it);
#: ``known``/``mask`` are caller-owned conditioning inputs and never donate.
_ddim_scan_inpaint = jax.jit(_ddim_inpaint_impl,
                             static_argnames=_INPAINT_STATICS,
                             donate_argnames=("x_init",))
#: sequence entry — no donation (frames alias nothing), mirroring the other
#: sequence scans.
_ddim_scan_inpaint_seq = jax.jit(_ddim_inpaint_impl,
                                 static_argnames=_INPAINT_STATICS)


def _ddim_inpaint_cached_impl(model, params, x_init, known, mask, noise_rng,
                              cache0, *, k: int, t_start: Optional[int],
                              eta: float, cache_interval: int,
                              cache_mode: str, cache_threshold=None,
                              cache_tokens=None, sequence: bool):
    """Feature-cached inpainting scan: ``_ddim_inpaint_impl``'s per-step
    known-pixel projection composed with ``_ddim_cached_impl``'s step-cache
    routing. The projection runs on the CLIPPED x̂0 — after the cache branch,
    before the affine update — exactly where the plain inpaint scan applies
    it, so ``cache_interval=1``-adjacent degenerate settings (adaptive
    threshold 0, token k = n_tokens) stay bitwise against the plain scan.
    Returns ``(images, final_cache)`` for the engine's per-bucket cache
    recycling, like the other cached scans."""
    coeffs = schedule.ddim_coefficients(model.total_steps, k, t_start, eta)
    spec = _cached_spec(model, len(coeffs.t_seq), cache_interval, cache_mode,
                        cache_threshold, cache_tokens)
    n = x_init.shape[0]

    def step(carry, inputs):
        x, _, cache = carry
        (t, c1, c2, cz), br = inputs
        with profiling.scope("sampler/cached_step"):
            x0_raw, cache = step_cache.apply_step(
                model, params, x, jnp.full((n,), t, jnp.int32), br, cache,
                spec)
        x0 = jnp.clip(x0_raw, -1.0, 1.0)
        x0 = mask * known + (1.0 - mask) * x0
        x_next = _ddim_step_update(x, x0, t, c1, c2, cz, noise_rng, eta)
        return (x_next, x0, cache), (x0 if sequence else None)

    carry0 = (x_init, jnp.zeros_like(x_init), cache0)
    branches = jnp.asarray(spec.branches, jnp.int32)
    (_, x0_last, cache_out), x0_out = jax.lax.scan(
        step, carry0, (_scan_inputs(coeffs), branches))
    if sequence:
        frames = jnp.concatenate([x_init[None], x0_out], axis=0)
        return (frames + 1.0) / 2.0, cache_out
    return (x0_last + 1.0) / 2.0, cache_out


_INPAINT_CACHED_STATICS = ("model", "k", "t_start", "eta", "cache_interval",
                           "cache_mode", "cache_threshold", "cache_tokens",
                           "sequence")
#: donation mirrors the cached sampler: x_init (fresh noise) and the cache
#: carry alias outputs; known/mask are caller-owned conditioning, never
#: donated.
_ddim_scan_inpaint_cached = jax.jit(
    _ddim_inpaint_cached_impl, static_argnames=_INPAINT_CACHED_STATICS,
    donate_argnames=("x_init", "cache0"))
_ddim_scan_inpaint_cached_seq = jax.jit(
    _ddim_inpaint_cached_impl, static_argnames=_INPAINT_CACHED_STATICS)


def _make_cache(model, x_init: jax.Array, mesh,
                mode: str = "delta") -> step_cache.Cache:
    """Build the zero cache carry host-side and, under SPMD sampling, place
    it batch-sharded over the mesh's 'data' axis alongside the sample batch
    — explicit placement, so the scan's cache shards never gather.
    ``mode="adaptive"`` adds the drift-reference image leaf (x_init-shaped,
    f32); the other modes share the two-leaf (B, N+1, E) pair."""
    cache = step_cache.init_cache(x_init.shape[0], model.num_patches + 1,
                                  model.embed_dim, model.dtype, mode=mode,
                                  img_shape=x_init.shape[1:])
    return step_cache.shard_cache(cache, mesh)


def _shard_init(x_init: jax.Array, mesh) -> jax.Array:
    """Place the sample batch sharded over the mesh's 'data' axis: the whole
    scan then runs SPMD (params replicated, one psum-free forward per shard)
    — multi-chip sampling the reference's single-GPU sampler has no analogue
    for. The batch must divide over the data axis."""
    if mesh is None:
        return x_init
    from ddim_cold_tpu.parallel.mesh import batch_sharding

    return jax.device_put(x_init, batch_sharding(mesh))


def ddim_sample(
    model,
    params,
    rng: Optional[jax.Array] = None,
    *,
    k: int = 10,
    n: int = 128,
    x_init: Optional[jax.Array] = None,
    t_start: Optional[int] = None,
    return_sequence: bool = False,
    mesh=None,
    eta: float = 0.0,
    cache_interval: int = 1,
    cache_mode: str = "delta",
    cache_threshold: Optional[float] = None,
    cache_tokens: Optional[int] = None,
    telemetry: bool = False,
) -> jax.Array:
    """k-strided DDIM sampling; returns images in [0, 1], NHWC.

    Either pass ``rng`` (fresh N(0,1) start, reference ViT.py:224) or
    ``x_init`` (an already-encoded image — the guided path). Defaults mirror
    the reference API (k=10, N=128, ViT.py:221).

    ``return_sequence=True`` returns the (n_steps+1, N, H, W, C) trajectory of
    the initial noise plus every x̂0 prediction (the denoise-sequence figure).
    With a ``mesh``, the batch is sharded over its 'data' axis and the scan
    runs SPMD across the chips. A ``(data, seq)`` mesh additionally runs
    sequence-parallel attention when ``model`` was cloned onto it
    (``models.sp_clone`` — the serve engine's ``sp_mode``/``sp_degree``
    configs are exactly this pairing): the batch stays 'data'-sharded here
    while the patch tokens shard over 'seq' inside the attention shard_map,
    so ONE large request can use every chip instead of only scaling with
    batch. Put ``params`` on the same mesh (``parallel.shard_params``).

    ``eta`` interpolates toward stochastic (DDPM-like) sampling per the DDIM
    paper (schedule.ddim_coefficients; beyond-parity, default 0 = the
    reference's deterministic path, bit-exact). ``eta`` > 0 draws per-step
    noise from ``rng``, which is then required even with ``x_init``.

    ``cache_interval`` > 1 turns on training-free feature caching
    (ops/step_cache.py): every ``cache_interval``-th step runs the full model
    and refreshes a block-delta cache; the steps between skip the
    ``cache_mode``-selected trunk blocks ("delta" = the Δ-DiT front/rear
    phase split, "full" = the whole trunk) and apply the cached deltas
    instead. The schedule is static, so the scan stays one compiled program
    per (k, interval, mode). ``cache_interval=1`` (default) takes the plain
    scan — bit-for-bit the exact sampler. Requires ``scan_blocks=False``.

    Two further modes (ops/step_cache.py, this is the adaptive-caching
    surface):

    * ``cache_mode="adaptive"`` + ``cache_threshold=τ`` — error-gated delta
      reuse: the static schedule above becomes the worst-case bound, and a
      cheap on-device drift estimate (normalized ‖x − x_ref‖², max over the
      batch) overrides any reuse step back to a refresh whenever drift ≥ τ.
      Still one compiled program (data-dependent ``lax.switch`` index over
      the same static branch set), no host sync. τ=0.0 refreshes every step
      — bitwise the exact sampler. τ→∞ is bitwise the static "delta" mode.
    * ``cache_mode="token"`` + ``cache_tokens=k_tok`` — JiT spatial caching:
      non-refresh steps recompute only the ``k_tok`` most-changed tokens
      (CLS always live) through the trunk, scattering into the cached token
      stream. ``k_tok = num_patches + 1`` is bitwise the exact sampler.

    Both statics are part of the compiled-program key; they are rejected
    (by ops/step_cache.cache_spec) under any other ``cache_mode``.

    ``telemetry=True`` (requires the cached sampler, i.e.
    ``cache_interval`` > 1, and is last-only) additionally returns an
    ``obs.device.StepTelemetry`` aux — per scan step, the cache branch
    actually taken (post adaptive-gate promotion) and the gate's drift —
    as ``(images, telemetry)``. The aux is static-shaped and rides the
    same scan, so it costs no extra dispatches or compiles; images are
    bitwise identical with telemetry on or off.
    """
    if eta and rng is None:
        raise ValueError("eta > 0 draws per-step noise — pass rng")
    if x_init is None:
        if rng is None:
            raise ValueError("ddim_sample needs either rng or x_init")
        H, W = model.img_size
        x_init = jax.random.normal(rng, (n, H, W, model.in_chans), jnp.float32)
    elif mesh is None and not return_sequence:
        # the last-only scans DONATE x_init (no HBM double-buffer); a
        # caller-provided start must survive the call, so it enters through a
        # private copy. The mesh path already copies via device_put, and the
        # sequence scan does not donate.
        x_init = jnp.array(x_init, copy=True)
    x_init = _shard_init(x_init, mesh)
    # distinct fold: with a fresh start, rng already produced x_init — the
    # per-step noise must not be correlated with it
    noise_rng = (jax.random.fold_in(rng, 0xD1F) if rng is not None
                 else jax.random.PRNGKey(0))
    if telemetry:
        if return_sequence:
            raise ValueError("telemetry=True is last-only — previews and "
                             "telemetry are separate products")
        if not step_cache.enabled(cache_interval):
            raise ValueError("telemetry=True needs the cached sampler "
                             "(cache_interval > 1)")
        out, _, (br, drift) = _ddim_scan_cached_tel(
            model, params, x_init, noise_rng,
            _make_cache(model, x_init, mesh, cache_mode),
            k=k, t_start=t_start, eta=eta, cache_interval=cache_interval,
            cache_mode=cache_mode, cache_threshold=cache_threshold,
            cache_tokens=cache_tokens)
        return out, StepTelemetry(branch=br, drift=drift)
    if step_cache.enabled(cache_interval):
        fn = _ddim_scan_cached_seq if return_sequence else _ddim_scan_cached
        out, _ = fn(
            model, params, x_init, noise_rng,
            _make_cache(model, x_init, mesh, cache_mode),
            k=k, t_start=t_start, eta=eta, cache_interval=cache_interval,
            cache_mode=cache_mode, cache_threshold=cache_threshold,
            cache_tokens=cache_tokens, sequence=return_sequence)
        return out
    if return_sequence:
        return _ddim_scan_sequence(model, params, x_init, noise_rng,
                                   k=k, t_start=t_start, eta=eta)
    return _ddim_scan_last(model, params, x_init, noise_rng,
                           k=k, t_start=t_start, eta=eta)


def sample_from(model, params, x_init: jax.Array, t_start: int, k: int = 10,
                eta: float = 0.0,
                rng: Optional[jax.Array] = None,
                return_sequence: bool = False,
                mesh=None,
                cache_interval: int = 1,
                cache_mode: str = "delta",
                cache_threshold: Optional[float] = None,
                cache_tokens: Optional[int] = None) -> jax.Array:
    """Guided sampling: DDIM-denoise an encoded image from level ``t_start``.

    Strictly a prefix-truncated ``ddim_sample`` (SURVEY.md C24). The
    draft2drawing app composes this with ``forward_noise``; slerp interpolation
    (C25) composes it with a spherical mix of two encodings. ``eta`` > 0
    switches to stochastic DDIM (see ``ddim_sample``) and requires ``rng``.
    ``return_sequence``/``mesh``/``cache_interval``/``cache_mode`` thread
    through to ``ddim_sample`` (trajectory output, data-axis SPMD, and the
    feature-cached sampler), so every guided composition — the editing
    workloads in particular — reaches the same variants the plain sampler has.
    """
    return ddim_sample(model, params, rng, x_init=x_init, t_start=t_start,
                       k=k, eta=eta, return_sequence=return_sequence,
                       mesh=mesh, cache_interval=cache_interval,
                       cache_mode=cache_mode, cache_threshold=cache_threshold,
                       cache_tokens=cache_tokens)


def slerp(a: jax.Array, b: jax.Array, frac: jax.Array) -> jax.Array:
    """Spherical interpolation between two (batches of) latents.

    The primitive of the reference's dormant interpolation app
    (ViT_draft2drawing.py:422-476): mix two forward-noised encodings on the
    great circle, then DDIM-decode with ``sample_from``. ``frac`` broadcasts
    against the leading axes, so a (F, 1, 1, 1, 1) fraction vector against
    (N, H, W, C) endpoints yields all F interpolants in one shot.
    """
    flat_a = a.reshape(a.shape[0], -1) if a.ndim > 1 else a[None]
    flat_b = b.reshape(b.shape[0], -1) if b.ndim > 1 else b[None]
    cos = jnp.sum(flat_a * flat_b, -1) / (
        jnp.linalg.norm(flat_a, axis=-1) * jnp.linalg.norm(flat_b, axis=-1)
    )
    theta_shape = (a.shape[:1] + (1,) * (a.ndim - 1)) if a.ndim > 1 else ()
    theta = jnp.arccos(jnp.clip(cos, -1.0, 1.0)).reshape(theta_shape)
    sin = jnp.sin(theta)
    # guard the denominator so the untaken branch carries no NaN (0/0) —
    # keeps jax_debug_nans and grads clean near parallel endpoints.
    safe_sin = jnp.where(sin < 1e-6, 1.0, sin)
    wa = jnp.sin((1.0 - frac) * theta) / safe_sin
    wb = jnp.sin(frac * theta) / safe_sin
    # degenerate (parallel) endpoints: fall back to lerp
    lin = (1.0 - frac) * a + frac * b
    return jnp.where(sin < 1e-6, lin, wa * a + wb * b)


def interp_states(rng: jax.Array, img_a: jax.Array, img_b: jax.Array,
                  n_interp: int, t_start: int,
                  total_steps: int = 2000) -> jax.Array:
    """The slerp-mixed encodings :func:`slerp_interpolate` decodes: both
    endpoints forward-noised to ``t_start`` with ONE key (independent noise
    per endpoint — the batch draw covers both, matching the reference's two
    separate draws ViT_draft2drawing.py:442-443), then ``n_interp``
    great-circle fractions between the two encodings. Factored out so the
    serving engine's interp workload (ddim_cold_tpu/workloads) builds
    bit-identical init states to the direct call — row i depends only on
    (key, endpoints, n_interp), never on its batchmates."""
    batch = jnp.stack([img_a, img_b])
    noisy = forward_noise(rng, batch, t_start, total_steps)
    frac = jnp.linspace(0.0, 1.0, n_interp).reshape(-1, 1, 1, 1, 1)
    return slerp(noisy[0][None], noisy[1][None], frac)[:, 0]


def slerp_interpolate(
    model,
    params,
    rng: jax.Array,
    img_a: jax.Array,
    img_b: jax.Array,
    *,
    n_interp: int = 8,
    t_start: int = 1800,
    k: int = 10,
    eta: float = 0.0,
    return_sequence: bool = False,
) -> jax.Array:
    """End-to-end latent interpolation (C25): encode both images to ``t_start``
    (one rng key, independent noise per endpoint — matching the reference's two
    separate draws, ViT_draft2drawing.py:442-443), slerp ``n_interp`` fractions
    between the encodings, and DDIM-decode each — returns (n_interp, H, W, C)
    in [0, 1]. ``eta`` > 0 decodes stochastically (same semantics as
    :func:`sample_from`; the decode key is folded from ``rng`` so the
    encoding noise and the decode noise stay independent)."""
    mixed = interp_states(rng, img_a, img_b, n_interp, t_start,
                          model.total_steps)
    return sample_from(model, params, mixed, t_start=t_start, k=k, eta=eta,
                       return_sequence=return_sequence,
                       rng=jax.random.fold_in(rng, 1))


def _cold_impl(model, params, x_init, *, levels: int, return_sequence: bool):
    t_seq = jnp.asarray(schedule.cold_time_sequence(levels))
    n = x_init.shape[0]

    def step(x, t):
        with profiling.scope("sampler/model"):
            x0 = model.apply({"params": params}, x,
                             jnp.full((n,), t, jnp.int32))
        x0 = jnp.clip(x0, -1.0, 1.0)
        # naive Cold-Diffusion Algorithm 1: x ← clamp(f(x, t)); the reference's
        # DDIM-style correction is present upstream only as commented-out code
        # (ViT_draft2drawing.py:275-285).
        return x0, x0 if return_sequence else None

    x_last, frames = jax.lax.scan(step, x_init, t_seq)
    if return_sequence:
        return (jnp.concatenate([x_init[None], frames], axis=0) + 1.0) / 2.0
    return (x_last + 1.0) / 2.0


_COLD_STATICS = ("model", "levels", "return_sequence")
#: last-only / sequence split mirrors the DDIM scans: only the last-only
#: entry donates x_init (its image output aliases the buffer; the sequence
#: frames cannot).
_cold_scan = jax.jit(_cold_impl, static_argnames=_COLD_STATICS,
                     donate_argnames=("x_init",))
_cold_scan_seq = jax.jit(_cold_impl, static_argnames=_COLD_STATICS)


def _cold_cached_impl(model, params, x_init, cache0, *, levels: int,
                      return_sequence: bool, cache_interval: int,
                      cache_mode: str, cache_threshold=None,
                      cache_tokens=None):
    """Feature-cached cold-diffusion scan — same naive Algorithm-1 update as
    ``_cold_scan``, model evaluation routed through the step cache. Returns
    ``(images, final_cache)`` like ``_ddim_cached_impl`` (donation aliasing +
    serve-loop cache recycling)."""
    t_seq = jnp.asarray(schedule.cold_time_sequence(levels))
    spec = _cached_spec(model, levels, cache_interval, cache_mode,
                        cache_threshold, cache_tokens)
    n = x_init.shape[0]

    def step(carry, inputs):
        x, cache = carry
        t, br = inputs
        with profiling.scope("sampler/cached_step"):
            x0_raw, cache = step_cache.apply_step(
                model, params, x, jnp.full((n,), t, jnp.int32), br, cache,
                spec)
        x0 = jnp.clip(x0_raw, -1.0, 1.0)
        return (x0, cache), (x0 if return_sequence else None)

    branches = jnp.asarray(spec.branches, jnp.int32)
    (x_last, cache_out), frames = jax.lax.scan(step, (x_init, cache0),
                                               (t_seq, branches))
    if return_sequence:
        return ((jnp.concatenate([x_init[None], frames], axis=0) + 1.0) / 2.0,
                cache_out)
    return (x_last + 1.0) / 2.0, cache_out


_COLD_CACHED_STATICS = ("model", "levels", "return_sequence",
                        "cache_interval", "cache_mode", "cache_threshold",
                        "cache_tokens")
_cold_scan_cached = jax.jit(_cold_cached_impl,
                            static_argnames=_COLD_CACHED_STATICS,
                            donate_argnames=("x_init", "cache0"))
_cold_scan_cached_seq = jax.jit(_cold_cached_impl,
                                static_argnames=_COLD_CACHED_STATICS)


def cold_sample(
    model,
    params,
    rng: Optional[jax.Array] = None,
    *,
    n: int = 49,
    levels: int = 6,
    x_init: Optional[jax.Array] = None,
    return_sequence: bool = False,
    mesh=None,
    cache_interval: int = 1,
    cache_mode: str = "delta",
    cache_threshold: Optional[float] = None,
    cache_tokens: Optional[int] = None,
) -> jax.Array:
    """Cold-diffusion sampling from per-sample constant-color "noise".

    The default init is a single N(0,1) RGB color per sample broadcast over
    the image (reference ViT_draft2drawing.py:264 — the fully-downsampled
    degenerate state); ``levels`` defaults to 6 = log2(64). Passing
    ``x_init`` instead starts the cold scan from a caller-provided degraded
    state at degradation level ``levels`` — the guided cold path (the
    super-resolution workload feeds an upsampled low-res image here, with
    ``levels`` = its downsampling level). With a ``mesh``, the batch runs
    SPMD sharded over its 'data' axis (see ``ddim_sample``).
    ``cache_interval`` > 1 enables the feature-cached scan (see
    ``ddim_sample``); 1 is bit-for-bit the plain sampler.
    """
    H, W = model.img_size
    if x_init is None:
        if rng is None:
            raise ValueError("cold_sample needs either rng or x_init")
        color = jax.random.normal(rng, (n, 1, 1, model.in_chans), jnp.float32)
        x_init = jnp.broadcast_to(color, (n, H, W, model.in_chans))
    elif mesh is None and not return_sequence:
        # the last-only cold scans DONATE x_init — a caller-provided start
        # must survive the call (same private copy as ddim_sample's guided
        # path; the mesh path copies via device_put, sequence never donates).
        x_init = jnp.array(x_init, copy=True)
    x_init = _shard_init(x_init, mesh)
    if step_cache.enabled(cache_interval):
        fn = _cold_scan_cached_seq if return_sequence else _cold_scan_cached
        out, _ = fn(
            model, params, x_init, _make_cache(model, x_init, mesh, cache_mode),
            levels=levels, return_sequence=return_sequence,
            cache_interval=cache_interval, cache_mode=cache_mode,
            cache_threshold=cache_threshold, cache_tokens=cache_tokens)
        return out
    fn = _cold_scan_seq if return_sequence else _cold_scan
    return fn(model, params, x_init, levels=levels,
              return_sequence=return_sequence)
