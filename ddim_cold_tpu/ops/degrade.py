"""Device-side cold degradation operator D(x, t) — the jittable twin of
data/resize.py's host pipeline.

Index math is identical to the host path (torch interpolate-nearest
convention: src = floor(dst · in/out)), so host-prepared training targets and
on-device degradations agree bit-for-bit — the golden-test in
tests/test_degrade.py pins this.

Down-then-up nearest resize composes into a single gather per axis:
``idx[i] = down_idx[up_idx[i]]``; each level is a static gather and a traced
per-sample ``t`` selects between levels via ``lax.switch`` under ``vmap``
(compiler-friendly — no dynamic shapes).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from ddim_cold_tpu.data.resize import nearest_indices


def _level_indices(size: int, level: int) -> np.ndarray:
    """Composed gather indices for one degradation level (2^level)."""
    target = max(int(np.floor(size / (2**level))), 1)
    down = nearest_indices(target, size)  # small ← big
    up = nearest_indices(size, target)  # big ← small
    return down[up]


@partial(jax.jit, static_argnames=("size", "max_step"))
def cold_degrade(imgs: jax.Array, t: jax.Array, *, size: int, max_step: int = 6) -> jax.Array:
    """D(x, t) for a batch: (B, H, W, C) float, per-sample int t ∈ [0, max_step].

    t=0 is the identity (the reference's D(x, 2^0) — two identity resizes,
    diffusion_loader.py:94-95 with t−1=0).
    """
    tables = jnp.asarray(
        np.stack([_level_indices(size, lv) for lv in range(max_step + 1)])
    )  # (levels+1, size)

    def one(img, ti):
        idx = tables[ti]
        return img[idx][:, idx]

    return jax.vmap(one)(imgs, t.astype(jnp.int32))
