"""Device-side cold degradation operator D(x, t) — the jittable twin of
data/resize.py's host pipeline.

Index math is identical to the host path (torch interpolate-nearest
convention: src = floor(dst · in/out)), so host-prepared training targets and
on-device degradations agree bit-for-bit — the golden-test in
tests/test_degrade.py pins this.

Down-then-up nearest resize composes into a single gather per axis:
``idx[i] = down_idx[up_idx[i]]``; each level is a static gather and a traced
per-sample ``t`` selects between levels via ``lax.switch`` under ``vmap``
(compiler-friendly — no dynamic shapes).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from ddim_cold_tpu.data.resize import nearest_indices


def _level_indices(size: int, level: int) -> np.ndarray:
    """Composed gather indices for one degradation level (2^level)."""
    target = max(int(np.floor(size / (2**level))), 1)
    down = nearest_indices(target, size)  # small ← big
    up = nearest_indices(size, target)  # big ← small
    return down[up]


@partial(jax.jit, static_argnames=("size", "max_step"))
def cold_degrade(imgs: jax.Array, t: jax.Array, *, size: int, max_step: int = 6) -> jax.Array:
    """D(x, t) for a batch: (B, H, W, C) float, per-sample int t ∈ [0, max_step].

    t=0 is the identity (the reference's D(x, 2^0) — two identity resizes,
    diffusion_loader.py:94-95 with t−1=0).
    """
    tables = jnp.asarray(
        np.stack([_level_indices(size, lv) for lv in range(max_step + 1)])
    )  # (levels+1, size)

    def one(img, ti):
        idx = tables[ti]
        return img[idx][:, idx]

    return jax.vmap(one)(imgs, t.astype(jnp.int32))


def upsample_nearest(imgs: jax.Array, size: int) -> jax.Array:
    """Nearest-upsample (B, h, w, C) → (B, size, size, C), torch convention.

    The "up" half of the cold degradation on its own: for a low-res image
    ``lo = nearest-downsample(x, level)``, ``upsample_nearest(lo, size)`` IS
    ``cold_degrade(x, level)`` — the degraded full-size state the cold scan
    starts from. The super-resolution workload (ddim_cold_tpu/workloads)
    uses exactly this to lift a user's low-res input into the sampler's
    state space; the index math matches the host path bit-for-bit, so a
    constant-color 1×1 input reproduces ``cold_sample``'s broadcast init
    exactly (the equivalence test in tests/test_workloads.py).
    """
    imgs = jnp.asarray(imgs, jnp.float32)
    if imgs.ndim == 3:
        imgs = imgs[None]
    iy = jnp.asarray(nearest_indices(size, imgs.shape[1]))
    ix = jnp.asarray(nearest_indices(size, imgs.shape[2]))
    return imgs[:, iy][:, :, ix]


def normalize_base(base: jax.Array) -> jax.Array:
    """Raw base image → float32 in [−1, 1] with the host pipeline's exact op
    order (÷255 then ·2−1, datasets._load_base) so a uint8-shipped batch is
    bit-identical to the host-normalized float path. Float input passes
    through (already normalized host-side)."""
    if base.dtype == jnp.uint8:
        return base.astype(jnp.float32) / 255.0 * 2.0 - 1.0
    return base


def _batch_constrain(mesh, batch_axis):
    """Sharding hint pinning arrays batch-sharded, all other dims replicated.

    The degrade gathers are per-sample ops: partitioned over batch they need
    zero communication, but left to the partitioner's cost model under a
    dp×tp×sp mesh it can pick a W-sharded layout for the gather and then hit
    "Involuntary full rematerialization" resharding into the attention layout
    (replicate-the-tensor fallback — MULTICHIP_r02 tail). Identity when no
    mesh is given or the axis isn't in it (single-chip callers)."""
    if mesh is None or batch_axis not in getattr(mesh, "axis_names", ()):
        return lambda a: a
    from jax.sharding import NamedSharding, PartitionSpec

    def con(a):
        spec = PartitionSpec(batch_axis, *([None] * (a.ndim - 1)))
        return jax.lax.with_sharding_constraint(a, NamedSharding(mesh, spec))

    return con


def make_cold_prepare(size: int, max_step: int, chain: bool, *,
                      mesh=None, batch_axis: str = "data"):
    """In-jit batch corruption for the device-side cold data path.

    The host ships only ``(base, t)`` — one clean image per sample instead of
    the two degraded float copies (2× less host→device traffic, the dominant
    step cost on network-attached TPU hosts) — and this hook (train/step.py
    ``prepare``) rebuilds the exact host contract ``(D(x,t), D(x,t−1)|x₀, t)``
    on device. The degradation is a pure gather (cold_degrade), so the result
    is bit-identical to the host/C++ pipeline. ``normalize_base`` additionally
    accepts uint8 bases (a further 4× for identity-resize datasets) for
    callers that ship raw bytes.

    ``mesh``/``batch_axis`` keep the gathers batch-sharded under SPMD (see
    ``_batch_constrain``); pass the training mesh whenever the step is jitted
    over one.
    """
    con = _batch_constrain(mesh, batch_axis)

    def prepare(batch, rng):
        del rng  # cold corruption is deterministic given (base, t)
        base, t = batch
        x = con(normalize_base(base))
        t = con(t)
        noisy = con(cold_degrade(x, t, size=size, max_step=max_step))
        target = (con(cold_degrade(x, t - 1, size=size, max_step=max_step))
                  if chain else x)
        return noisy, target, t

    return prepare


def make_gaussian_prepare(total_steps: int, *, mesh=None,
                          batch_axis: str = "data"):
    """In-jit Gaussian forward-noising for the device-side data path (C13).

    The host ships ``(x₀, t)`` with t from the same Philox stream as the host
    pipeline (identical noising *schedule*); ε is drawn ON DEVICE from the
    step rng under ᾱ(t) = 1 − √((t+1)/T) (reference diffusion_loader.py:52-54,
    the ViT.py:231 schedule). The noise bit-stream therefore differs from the
    host path — statistically identical, not bit-identical, which is why the
    trainer keeps the val loader on the host path (deterministic val loss).
    """

    con = _batch_constrain(mesh, batch_axis)

    def prepare(batch, rng):
        base, t = batch
        x = con(normalize_base(base))
        t = con(t)
        alpha = 1.0 - jnp.sqrt((t.astype(jnp.float32) + 1.0) / total_steps)
        alpha = alpha[:, None, None, None]
        noise = jax.random.normal(rng, x.shape, jnp.float32)
        noisy = jnp.sqrt(alpha) * x + jnp.sqrt(1.0 - alpha) * noise
        return noisy, x, t

    return prepare
