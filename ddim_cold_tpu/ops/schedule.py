"""Diffusion schedules.

The reference uses a nonstandard signal-level schedule (reference ViT.py:231-232):

    alpha_bar(t)   = 1 - sqrt((t + 1) / T)            [+ 1e-5 on the *current* step only]

i.e. ``x_t = sqrt(alpha_bar) * x_0 + sqrt(1 - alpha_bar) * eps``. The +1e-5 is
applied asymmetrically — to ``alpha_t`` (the current noise level) but NOT to
``alpha_tk`` (the target level of the DDIM jump). This asymmetry affects sampler
outputs and is replicated exactly (SURVEY.md quirk #5).

All schedule values are computed host-side in float64 (matching Python-float
math in the reference) and handed to jitted loops as static per-step arrays, so
no schedule math runs on device.
"""

from __future__ import annotations

import math
from typing import NamedTuple

import numpy as np

#: epsilon added to the *current* alpha only (reference ViT.py:232)
ALPHA_EPS = 1e-5


def alpha_bar(t, total_steps: int, eps: float = 0.0):
    """Signal level ᾱ(t) = 1 − √((t+1)/T) + eps.

    Works on Python ints/floats and numpy arrays. ``eps`` is ``ALPHA_EPS`` when
    evaluating the current step in a sampler update, 0 for the jump target.
    """
    t = np.asarray(t, dtype=np.float64)
    return 1.0 - np.sqrt((t + 1.0) / float(total_steps)) + eps


def forward_noise_alpha(t_start: int, total_steps: int) -> float:
    """ᾱ used when forward-noising an image to level ``t_start``.

    The draft2drawing app uses ``1 - sqrt(t_start / T)`` — note: *no* +1
    (reference ViT_draft2drawing.py:395), unlike the sampler's ``(t+1)/T``.
    """
    return 1.0 - math.sqrt(t_start / float(total_steps))


def ddim_time_sequence(total_steps: int, k: int, t_start: int | None = None) -> np.ndarray:
    """The reverse-process visit order: t = t_start, t_start−k, …, > 0.

    Mirrors ``range(T-1, 0, -k)`` (reference ViT.py:226); ``t_start`` defaults
    to T−1 and is overridable for guided sampling (draft2drawing restarts).
    """
    if t_start is None:
        t_start = total_steps - 1
    return np.arange(t_start, 0, -k, dtype=np.int64)


class DDIMCoefficients(NamedTuple):
    """Per-step affine coefficients of the reference's DDIM update.

    The reference update (ViT.py:231-234), with x = noisy image, x0 = clamped
    model prediction:

        a_t  = ᾱ(t)   + 1e-5        (ALPHA_EPS asymmetry)
        a_tk = ᾱ(t−k)               (no eps; t−k clamped at −1 → ᾱ=1−√0=1... )
        noise = (x − √a_t·x0) / √(1−a_t)
        x'    = √a_tk · ( x/√a_t + (√((1−a_tk)/a_tk) − √((1−a_t)/a_t)) · noise )

    which is affine in (x, x0):  x' = cx·x + cx0·x0. We precompute (cx, cx0)
    host-side in float64; the on-device scan is then two fused multiplies.

    Fields are float32 numpy arrays of shape (n_steps,), plus the int32 time
    sequence fed to the model.
    """

    t_seq: np.ndarray  # (n,) int32 — model conditioning step at each iteration
    cx: np.ndarray  # (n,) float32 — coefficient on the current noisy image
    cx0: np.ndarray  # (n,) float32 — coefficient on the clamped x0 prediction
    cz: np.ndarray  # (n,) float32 — σ_t on fresh noise (all-zero when eta=0)


def ddim_coefficients(total_steps: int, k: int, t_start: int | None = None,
                      eta: float = 0.0) -> DDIMCoefficients:
    """Precompute the affine DDIM-update coefficients for a k-strided schedule.

    Deviation from the reference: when ``t+1−k < 0`` (possible for stride k not
    dividing T−1 nicely) the reference's ``math.sqrt`` would raise; we clamp the
    argument to 0 (ᾱ → 1, i.e. jump straight to the clean image). For every k
    used by the reference CLIs (1, 10, 20, 50, 100) the clamp never triggers.

    ``eta`` > 0 is the DDIM paper's stochastic interpolation (arXiv:2010.02502
    eq. 16, beyond-parity — the reference is deterministic-only): per-step
    noise scale ``σ_t = η·√((1−a_tk)/(1−a_t))·√(1−a_t/a_tk)`` with the
    ε-direction rescaled to ``√(1−a_tk−σ_t²)``. η=0 keeps the EXACT reference
    arithmetic (same operation order, bit-identical coefficients — the
    η-generalized expression is algebraically equal but rounds differently).
    """
    t_seq = ddim_time_sequence(total_steps, k, t_start)
    T = float(total_steps)
    cx = np.empty(len(t_seq), dtype=np.float64)
    cx0 = np.empty(len(t_seq), dtype=np.float64)
    cz = np.zeros(len(t_seq), dtype=np.float64)
    for i, t in enumerate(t_seq):
        a_t = 1.0 - math.sqrt((t + 1.0) / T) + ALPHA_EPS
        a_tk = 1.0 - math.sqrt(max(t + 1.0 - k, 0.0) / T)
        if eta == 0.0:
            # d = √((1−a_tk)/a_tk) − √((1−a_t)/a_t)
            d = math.sqrt((1.0 - a_tk) / a_tk) - math.sqrt((1.0 - a_t) / a_t)
            s = math.sqrt(a_tk)
            # x' = s·x/√a_t + s·d·noise ;  noise = x/√(1−a_t) − √a_t/√(1−a_t)·x0
            cx[i] = s / math.sqrt(a_t) + s * d / math.sqrt(1.0 - a_t)
            cx0[i] = -s * d * math.sqrt(a_t) / math.sqrt(1.0 - a_t)
        else:
            # x' = √a_tk·x0 + √(1−a_tk−σ²)·ε + σ·z,  ε = (x−√a_t·x0)/√(1−a_t)
            sigma = eta * math.sqrt((1.0 - a_tk) / (1.0 - a_t)) * math.sqrt(
                max(1.0 - a_t / a_tk, 0.0))
            ce = math.sqrt(max(1.0 - a_tk - sigma * sigma, 0.0)) / math.sqrt(
                1.0 - a_t)
            cx[i] = ce
            cx0[i] = math.sqrt(a_tk) - ce * math.sqrt(a_t)
            cz[i] = sigma
    return DDIMCoefficients(
        t_seq=t_seq.astype(np.int32),
        cx=cx.astype(np.float32),
        cx0=cx0.astype(np.float32),
        cz=cz.astype(np.float32),
    )


def fewstep_time_sequence(total_steps: int, steps: int,
                          t_start: int | None = None) -> np.ndarray:
    """Visit order for a ``steps``-evaluation few-step sampler: the
    evenly-spaced levels t_j = round(t_start · (steps − j) / steps),
    j = 0..steps−1 (t_start defaults to T−1, the full-noise start).

    Unlike :func:`ddim_time_sequence` (a fixed stride k whose step COUNT
    falls out of T), here the step COUNT is the knob — k∈{1,2,4} distilled
    students run exactly ``steps`` model evaluations. The proportional
    construction makes halving self-consistent: every other entry of the
    2s-step sequence IS the s-step sequence (round(t·(2s−2j)/(2s)) =
    round(t·(s−j)/s)), which is what lets progressive distillation
    (train/distill.py) target "two teacher steps = one student step"
    without schedule drift across halvings.
    """
    if steps < 1:
        raise ValueError(f"steps must be >= 1, got {steps}")
    if t_start is None:
        t_start = total_steps - 1
    if not 1 <= t_start < total_steps:
        raise ValueError(
            f"t_start must be in [1, {total_steps - 1}], got {t_start}")
    if t_start < steps:
        raise ValueError(
            f"t_start={t_start} < steps={steps}: the rounded levels would "
            "collide — fewer steps or a later start")
    t_seq = np.array([round(t_start * (steps - j) / steps)
                      for j in range(steps)], dtype=np.int64)
    return t_seq


def fewstep_coefficients(total_steps: int, steps: int,
                         t_start: int | None = None,
                         eta: float = 0.0) -> DDIMCoefficients:
    """Affine update coefficients along a :func:`fewstep_time_sequence`.

    Step j jumps t_j → t_{j+1} (the NEXT visited level, not t_j − k), with
    the reference's exact per-step arithmetic and ALPHA_EPS asymmetry; the
    FINAL step jumps to the clean image (ᾱ = 1), where the update
    degenerates to x' = x̂₀ identically — so its row is pinned to
    (cx, cx0, cz) = (0, 1, 0) exactly rather than computed through the
    affine form, whose algebraic cancellation (1/√a_t − 1/√a_t) is exact
    on paper but not in float. The scan family (ops/sampling.py
    ``ddim_sample_fewstep``) exploits exactly this: the last model
    evaluation runs OUTSIDE the scan as a bare forward.
    """
    t_seq = fewstep_time_sequence(total_steps, steps, t_start)
    T = float(total_steps)
    cx = np.zeros(steps, dtype=np.float64)
    cx0 = np.zeros(steps, dtype=np.float64)
    cz = np.zeros(steps, dtype=np.float64)
    for j, t in enumerate(t_seq):
        a_t = 1.0 - math.sqrt((t + 1.0) / T) + ALPHA_EPS
        if j == steps - 1:
            cx[j], cx0[j], cz[j] = 0.0, 1.0, 0.0  # jump-to-clean: x' = x̂₀
            continue
        a_tk = 1.0 - math.sqrt((t_seq[j + 1] + 1.0) / T)
        if eta == 0.0:
            d = math.sqrt((1.0 - a_tk) / a_tk) - math.sqrt((1.0 - a_t) / a_t)
            s = math.sqrt(a_tk)
            cx[j] = s / math.sqrt(a_t) + s * d / math.sqrt(1.0 - a_t)
            cx0[j] = -s * d * math.sqrt(a_t) / math.sqrt(1.0 - a_t)
        else:
            sigma = eta * math.sqrt((1.0 - a_tk) / (1.0 - a_t)) * math.sqrt(
                max(1.0 - a_t / a_tk, 0.0))
            ce = math.sqrt(max(1.0 - a_tk - sigma * sigma, 0.0)) / math.sqrt(
                1.0 - a_t)
            cx[j] = ce
            cx0[j] = math.sqrt(a_tk) - ce * math.sqrt(a_t)
            cz[j] = sigma
    return DDIMCoefficients(
        t_seq=t_seq.astype(np.int32),
        cx=cx.astype(np.float32),
        cx0=cx0.astype(np.float32),
        cz=cz.astype(np.float32),
    )


def cold_time_sequence(levels: int = 6) -> np.ndarray:
    """Cold-diffusion visit order t = levels..1 (reference ViT_draft2drawing.py:271)."""
    return np.arange(levels, 0, -1, dtype=np.int32)


#: step-cache branch ids (ops/step_cache.py): the scan feeds one of these per
#: reverse step, precomputed host-side like the DDIM coefficients above — the
#: refresh/reuse pattern is STATIC, so XLA compiles one program per
#: (k, interval, mode) with both branch bodies and no host sync.
CACHE_REFRESH = 0  # full forward, (re)populate the block-delta cache
CACHE_REUSE_REAR = 1  # skip the REAR trunk half, apply its cached delta
CACHE_REUSE_FRONT = 2  # skip the FRONT trunk half, apply its cached delta
CACHE_REUSE_ALL = 1  # ("full" mode) skip the whole trunk, apply both deltas
CACHE_REUSE_TOKEN = 1  # ("token" mode) recompute only the top-k changed tokens


def cache_branch_sequence(n_steps: int, cache_interval: int,
                          cache_mode: str = "delta") -> np.ndarray:
    """Per-step refresh/reuse branch ids for the feature-cached samplers.

    Uniform stride: step i refreshes iff ``i % cache_interval == 0`` (step 0
    always refreshes — the cache starts empty), every other step reuses.
    ``cache_mode`` picks what a reuse step skips:

    * ``"delta"`` — Δ-DiT-style front/rear split (arXiv:2406.01125): reverse
      diffusion lays down image structure in the EARLY (high-noise) steps and
      detail in the LATE steps, and the two live in different trunk halves —
      so early-phase reuse steps skip the rear half (CACHE_REUSE_REAR) and
      late-phase reuse steps skip the front half (CACHE_REUSE_FRONT). Skips
      half the block FLOPs per reuse step.
    * ``"full"`` — reuse steps skip the whole trunk (CACHE_REUSE_ALL): only
      the embed/head run against the fresh (x_t, t). Skips all block FLOPs
      per reuse step; the cheaper/looser end of the trade-off.
    * ``"adaptive"`` — SAME array as ``"delta"``: this is the static
      worst-case bound of the error-gated sampler (ops/step_cache.py). The
      branch-0 steps here are the guaranteed refreshes; the REAR/FRONT ids on
      the reuse steps are what the on-device drift gate may override back to
      CACHE_REFRESH (a data-dependent ``lax.switch`` index over the same
      static branch set — still one compiled program, still no host sync).
    * ``"token"`` — JiT-style spatial caching (arXiv:2603.10744): reuse
      steps take CACHE_REUSE_TOKEN, recomputing only a static top-k changed
      token subset through the trunk (models/vit.py ``token_cache``).

    ``cache_interval <= 1`` returns all-refresh (caching disabled; the
    samplers bypass the cache machinery entirely for bit-exactness with the
    plain scan).
    """
    if cache_mode not in ("delta", "full", "adaptive", "token"):
        raise ValueError(
            "cache_mode must be one of 'delta', 'full', 'adaptive', 'token', "
            f"got {cache_mode!r}")
    branch = np.zeros(n_steps, dtype=np.int32)
    if cache_interval <= 1:
        return branch
    idx = np.arange(n_steps)
    reuse = (idx % cache_interval) != 0
    if cache_mode == "full":
        branch[reuse] = CACHE_REUSE_ALL
    elif cache_mode == "token":
        branch[reuse] = CACHE_REUSE_TOKEN
    else:  # "delta" and its error-gated upgrade "adaptive" share the pattern
        early = idx < (n_steps + 1) // 2
        branch[reuse & early] = CACHE_REUSE_REAR
        branch[reuse & ~early] = CACHE_REUSE_FRONT
    return branch
