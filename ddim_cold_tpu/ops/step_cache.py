"""Training-free DDIM step caching — reuse transformer block deltas across
adjacent sampler steps (Δ-DiT, arXiv:2406.01125).

Adjacent reverse-diffusion steps feed the ViT nearly identical activations, so
the token-stream displacement a contiguous run of residual blocks contributes
(``tokens_out − tokens_in``, the *cumulative block delta*) barely moves between
steps. This module caches those deltas on periodic *refresh* steps and, on the
*reuse* steps in between, replaces the skipped blocks with one add of the
cached delta — no retraining, no extra parameters, and (empirically, Δ-DiT)
nearly FID-neutral at small intervals.

Design constraints inherited from ops/sampling.py:19-22 — the samplers are
single jitted ``lax.scan`` loops with no host↔device traffic until the final
gather. The refresh/reuse pattern is therefore a STATIC host-side schedule
(ops/schedule.py:cache_branch_sequence, generated next to the DDIM
coefficients): the scan body is one ``lax.switch`` over per-step branch ids
fed as a scanned input, XLA compiles every branch body into the one program,
and the cache pytree rides the scan carry. With a mesh, the cache is placed
batch-sharded over the 'data' axis exactly like the sample batch, so SPMD
sampling stays psum-free.

What a branch does (model hooks: models/vit.py ``capture_split`` /
``skip_blocks``):

* refresh   — full forward; emit ``(delta_front, delta_rear)``, the cumulative
              deltas of the trunk halves split at ``spec.split``.
* reuse     — "delta" mode: skip the phase-appropriate half (rear in the early
              sampling phase, front in the late phase) and add its cached
              delta; "full" mode: skip the whole trunk, add both.

Cost model: a reuse step skips ``(hi−lo)/depth`` of the block FLOPs (embed,
head, and the un-skipped blocks still run). See :func:`flops_saved_fraction`
and the PERF.md "Cached sampler" section for the measured numbers.
"""

from __future__ import annotations

from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp

from ddim_cold_tpu.ops import schedule

#: cache pytree: (delta_front, delta_rear), each (B, N+1, E) in the model's
#: compute dtype. Kept as a flat tuple so the scan carry stays a plain pytree.
Cache = tuple


class CacheSpec(NamedTuple):
    """Static description of one cached-sampling run — hashable, so jitted
    samplers can close over it keyed by their (k, interval, mode) statics."""

    depth: int  # model trunk depth
    split: int  # front half = blocks [0, split), rear = [split, depth)
    mode: str  # "delta" | "full"
    interval: int  # refresh stride (1 = caching disabled)
    branches: tuple  # per-step branch ids (static schedule)

    @property
    def n_steps(self) -> int:
        return len(self.branches)


def enabled(cache_interval: Optional[int]) -> bool:
    """True when the interval actually turns caching on. ``<= 1`` means every
    step refreshes, i.e. the exact sampler — callers bypass the cache
    machinery entirely so interval=1 stays bit-for-bit the plain scan."""
    return cache_interval is not None and cache_interval > 1


def cache_spec(depth: int, n_steps: int, cache_interval: int,
               cache_mode: str = "delta",
               split: Optional[int] = None) -> CacheSpec:
    """Build the static spec for a run of ``n_steps`` reverse steps.

    ``split`` defaults to ``depth // 2`` — the Δ-DiT front/rear halving. The
    model must have ≥ 2 blocks (a 1-block trunk has no half to skip).
    """
    if depth < 2:
        raise ValueError(f"step caching needs depth >= 2 blocks, got {depth}")
    if split is None:
        split = depth // 2
    if not (1 <= split < depth):
        raise ValueError(f"split {split} must lie in [1, {depth})")
    branches = schedule.cache_branch_sequence(n_steps, cache_interval, cache_mode)
    return CacheSpec(depth=depth, split=int(split), mode=cache_mode,
                     interval=int(cache_interval),
                     branches=tuple(int(b) for b in branches))


def init_cache(n: int, n_tokens: int, embed_dim: int, dtype) -> Cache:
    """Zero-filled cache carry. The schedule's step 0 is always a refresh, so
    the zeros are never consumed — they only fix the carry's shape/dtype.
    The two halves must be DISTINCT allocations: the cached samplers donate
    the carry, and donating one buffer under two arguments is invalid."""
    return (jnp.zeros((n, n_tokens, embed_dim), dtype),
            jnp.zeros((n, n_tokens, embed_dim), dtype))


def shard_cache(cache: Cache, mesh) -> Cache:
    """Place the cache batch-sharded over the mesh's 'data' axis — the same
    placement as the sample batch (sampling._shard_init), so the SPMD scan
    carries one cache shard per chip and never gathers activations."""
    if mesh is None:
        return cache
    from ddim_cold_tpu.parallel.mesh import batch_sharding

    sharding = batch_sharding(mesh)
    return jax.tree.map(lambda a: jax.device_put(a, sharding), cache)


def apply_step(model, params, x: jax.Array, t_vec: jax.Array,
               branch: jax.Array, cache: Cache, spec: CacheSpec):
    """One cache-aware model evaluation inside the sampler scan body.

    ``branch`` is the step's traced branch id (scanned input from
    ``spec.branches``); returns ``(x0_raw, new_cache)``. Every branch returns
    the same pytree structure, so ``lax.switch`` compiles all of them into
    the one scan program — the refresh/reuse decision costs no host sync.
    """
    depth, split = spec.depth, spec.split

    def refresh(x, cache):
        x0, deltas = model.apply({"params": params}, x, t_vec,
                                 capture_split=split)
        return x0, deltas

    def reuse_rear(x, cache):
        x0 = model.apply({"params": params}, x, t_vec,
                         skip_blocks=(split, depth), block_delta=cache[1])
        return x0, cache

    def reuse_front(x, cache):
        x0 = model.apply({"params": params}, x, t_vec,
                         skip_blocks=(0, split), block_delta=cache[0])
        return x0, cache

    def reuse_all(x, cache):
        x0 = model.apply({"params": params}, x, t_vec,
                         skip_blocks=(0, depth),
                         block_delta=cache[0] + cache[1])
        return x0, cache

    if spec.mode == "full":
        branches = (refresh, reuse_all)
    else:
        branches = (refresh, reuse_rear, reuse_front)
    return jax.lax.switch(branch, branches, x, cache)


def flops_saved_fraction(spec: CacheSpec) -> float:
    """Fraction of the run's BLOCK compute the schedule skips (embed/head and
    the schedule itself excluded) — the analytic ceiling on the speedup's
    compute term, quoted next to measured numbers in bench/PERF.md."""
    if not spec.branches:
        return 0.0
    saved = 0.0
    for b in spec.branches:
        if b == schedule.CACHE_REFRESH:
            continue
        if spec.mode == "full":
            saved += 1.0  # the whole trunk skipped
        elif b == schedule.CACHE_REUSE_REAR:
            saved += (spec.depth - spec.split) / spec.depth
        else:  # CACHE_REUSE_FRONT
            saved += spec.split / spec.depth
    return saved / len(spec.branches)
