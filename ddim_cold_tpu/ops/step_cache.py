"""Training-free DDIM step caching — reuse transformer block deltas across
adjacent sampler steps (Δ-DiT, arXiv:2406.01125) with error-gated and
token-level adaptive variants (JiT, arXiv:2603.10744).

Adjacent reverse-diffusion steps feed the ViT nearly identical activations, so
the token-stream displacement a contiguous run of residual blocks contributes
(``tokens_out − tokens_in``, the *cumulative block delta*) barely moves between
steps. This module caches those deltas on periodic *refresh* steps and, on the
*reuse* steps in between, replaces the skipped blocks with one add of the
cached delta — no retraining, no extra parameters, and (empirically, Δ-DiT)
nearly FID-neutral at small intervals.

Two adaptive modes extend the fixed-interval schedule, both keeping the
sampler ONE compiled ``lax.scan`` program:

* ``"adaptive"`` — error-gated refresh. The cache carry grows a third leaf,
  ``x_ref``: the scan state at the last refresh. Each step computes a cheap
  normalized drift ``max_rows ‖x − x_ref‖² / (‖x_ref‖² + ε)`` on device and
  overrides the static REAR/FRONT reuse id back to CACHE_REFRESH whenever
  drift ≥ ``spec.threshold``. The ``lax.switch`` index becomes data-dependent
  but ranges over the SAME static branch set, so there is no retrace and no
  host sync; the static ``"delta"``-pattern schedule is the worst-case bound
  (the gate can only add refreshes). The drift reduction is a batch ``max``
  on purpose: it makes the gate invariant to padding rows that replicate an
  existing row (serve/engine.py pads adaptive batches with row-0 replicas),
  preserving the engine's bitwise-vs-direct contract. ``threshold == 0``
  forces every step to refresh — bitwise the exact sampler; ``threshold =
  inf`` never fires — bitwise the static ``"delta"`` schedule.

* ``"token"`` — per-token spatial caching. The carry is ``(ref_in, delta)``,
  both (B, N+1, E): the post-embed token stream at the last refresh and the
  whole-trunk cumulative delta. A reuse step embeds the fresh input, ranks
  tokens by squared change against ``ref_in``, gathers the static top-k most
  changed (CLS always live), runs ONLY those through the trunk, and scatters
  the results back into ``embed + delta`` (models/vit.py ``token_cache``).
  Per-row top-k keeps rows independent of batchmates, so normal engine
  coalescing remains bitwise. ``token_k == n_tokens`` degenerates to the
  identity gather/scatter — bitwise the exact sampler.

Design constraints inherited from ops/sampling.py:19-22 — the samplers are
single jitted ``lax.scan`` loops with no host↔device traffic until the final
gather. The refresh/reuse pattern is therefore a STATIC host-side schedule
(ops/schedule.py:cache_branch_sequence, generated next to the DDIM
coefficients): the scan body is one ``lax.switch`` over per-step branch ids
fed as a scanned input, XLA compiles every branch body into the one program,
and the cache pytree rides the scan carry. With a mesh, the cache is placed
batch-sharded over the 'data' axis exactly like the sample batch, so SPMD
sampling stays psum-free.

What a branch does (model hooks: models/vit.py ``capture_split`` /
``skip_blocks``):

* refresh   — full forward; emit ``(delta_front, delta_rear)``, the cumulative
              deltas of the trunk halves split at ``spec.split``.
* reuse     — "delta" mode: skip the phase-appropriate half (rear in the early
              sampling phase, front in the late phase) and add its cached
              delta; "full" mode: skip the whole trunk, add both.

Cost model: a reuse step skips ``(hi−lo)/depth`` of the block FLOPs (embed,
head, and the un-skipped blocks still run). See :func:`flops_saved_fraction`
and the PERF.md "Cached sampler" section for the measured numbers.
"""

from __future__ import annotations

from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp

from ddim_cold_tpu.ops import schedule

#: cache pytree, by mode — kept as a flat tuple so the scan carry stays a
#: plain pytree:
#:   "delta"/"full": (delta_front, delta_rear), each (B, N+1, E) model dtype
#:   "adaptive":     (delta_front, delta_rear, x_ref), x_ref (B, H, W, C) f32
#:   "token":        (ref_in, trunk_delta), each (B, N+1, E) model dtype
Cache = tuple

#: denominator guard in the normalized drift estimate (f32; well below any
#: real ‖x_ref‖² for an image-shaped state, only there for the zero carry)
DRIFT_EPS = 1e-6


class CacheSpec(NamedTuple):
    """Static description of one cached-sampling run — hashable, so jitted
    samplers can close over it keyed by their (k, interval, mode, threshold,
    token_k) statics."""

    depth: int  # model trunk depth
    split: int  # front half = blocks [0, split), rear = [split, depth)
    mode: str  # "delta" | "full" | "adaptive" | "token"
    interval: int  # refresh stride (1 = caching disabled)
    branches: tuple  # per-step branch ids (static schedule)
    threshold: float = 0.0  # "adaptive": drift level that forces a refresh
    token_k: int = 0  # "token": tokens recomputed per reuse step (incl. CLS)
    n_tokens: int = 0  # "token": total tokens N+1 (for validation/accounting)

    @property
    def n_steps(self) -> int:
        return len(self.branches)


def enabled(cache_interval: Optional[int]) -> bool:
    """True when the interval actually turns caching on. ``<= 1`` means every
    step refreshes, i.e. the exact sampler — callers bypass the cache
    machinery entirely so interval=1 stays bit-for-bit the plain scan."""
    return cache_interval is not None and cache_interval > 1


def cache_spec(depth: int, n_steps: int, cache_interval: int,
               cache_mode: str = "delta",
               split: Optional[int] = None,
               threshold: Optional[float] = None,
               token_k: Optional[int] = None,
               n_tokens: Optional[int] = None) -> CacheSpec:
    """Build the static spec for a run of ``n_steps`` reverse steps.

    ``split`` defaults to ``depth // 2`` — the Δ-DiT front/rear halving. The
    model must have ≥ 2 blocks (a 1-block trunk has no half to skip).
    ``cache_mode="adaptive"`` requires ``threshold`` (≥ 0 — the drift level
    that forces a refresh; 0 refreshes every step). ``cache_mode="token"``
    requires ``token_k`` in [1, n_tokens] and ``n_tokens`` (the model's
    N+1). Each knob is rejected outside its mode so a silently ignored
    setting can't masquerade as an active one.
    """
    if depth < 2:
        raise ValueError(f"step caching needs depth >= 2 blocks, got {depth}")
    if split is None:
        split = depth // 2
    if not (1 <= split < depth):
        raise ValueError(f"split {split} must lie in [1, {depth})")
    if cache_mode == "adaptive":
        if threshold is None or not (float(threshold) >= 0.0):
            raise ValueError(
                "cache_mode='adaptive' needs a drift threshold >= 0, got "
                f"{threshold!r}")
    elif threshold is not None:
        raise ValueError(
            f"threshold only applies to cache_mode='adaptive' (got mode "
            f"{cache_mode!r} with threshold {threshold!r})")
    if cache_mode == "token":
        if n_tokens is None or n_tokens < 2:
            raise ValueError(
                f"cache_mode='token' needs the model's n_tokens (N+1) >= 2, "
                f"got {n_tokens!r}")
        if token_k is None or not (1 <= token_k <= n_tokens):
            raise ValueError(
                f"cache_mode='token' needs token_k in [1, {n_tokens}], got "
                f"{token_k!r}")
    elif token_k is not None or n_tokens is not None:
        raise ValueError(
            f"token_k/n_tokens only apply to cache_mode='token' (got mode "
            f"{cache_mode!r})")
    branches = schedule.cache_branch_sequence(n_steps, cache_interval, cache_mode)
    return CacheSpec(depth=depth, split=int(split), mode=cache_mode,
                     interval=int(cache_interval),
                     branches=tuple(int(b) for b in branches),
                     threshold=float(threshold or 0.0),
                     token_k=int(token_k or 0), n_tokens=int(n_tokens or 0))


def init_cache(n: int, n_tokens: int, embed_dim: int, dtype,
               mode: str = "delta",
               img_shape: Optional[tuple] = None) -> Cache:
    """Zero-filled cache carry. The schedule's step 0 is always a refresh
    (and in adaptive mode the gate is overridden to refresh there regardless
    of what drift the stale ``x_ref`` implies), so the zeros are never
    consumed — they only fix the carry's shape/dtype. Leaves must be
    DISTINCT allocations: the cached samplers donate the carry, and donating
    one buffer under two arguments is invalid.

    ``mode="adaptive"`` adds the f32 ``x_ref`` leaf and needs ``img_shape``
    = (H, W, C); ``mode="token"`` reuses the two-leaf (B, N+1, E) structure
    as (ref_in, trunk_delta)."""
    pair = (jnp.zeros((n, n_tokens, embed_dim), dtype),
            jnp.zeros((n, n_tokens, embed_dim), dtype))
    if mode != "adaptive":
        return pair
    if img_shape is None:
        raise ValueError("init_cache(mode='adaptive') needs img_shape=(H, W, C)")
    return pair + (jnp.zeros((n, *img_shape), jnp.float32),)


def shard_cache(cache: Cache, mesh) -> Cache:
    """Place the cache batch-sharded over the mesh's 'data' axis — the same
    placement as the sample batch (sampling._shard_init), so the SPMD scan
    carries one cache shard per chip and never gathers activations."""
    if mesh is None:
        return cache
    from ddim_cold_tpu.parallel.mesh import batch_sharding

    sharding = batch_sharding(mesh)
    return jax.tree.map(lambda a: jax.device_put(a, sharding), cache)


def adaptive_gate(x: jax.Array, cache: Cache, branch: jax.Array,
                  spec: CacheSpec):
    """The error gate of ``"adaptive"`` mode: fold the on-device drift into
    the step's branch index. Returns ``(idx, drift)``.

    Drift is computed per ROW and reduced with max: the gate is a
    batch-level scalar (``lax.switch`` takes one index) but the max keeps it
    invariant to padding rows that replicate a real row (serve/engine.py).
    ``>=`` makes threshold=0 an always-refresh gate — bitwise the exact
    sampler; a stale/zero ``x_ref`` is harmless because step 0's branch id
    is CACHE_REFRESH and the ``jnp.where`` pins idx to 0 there no matter
    what drift evaluates to."""
    x_ref = cache[2]
    axes = tuple(range(1, x_ref.ndim))
    xf = x.astype(jnp.float32)
    num = jnp.sum(jnp.square(xf - x_ref), axis=axes)
    den = jnp.sum(jnp.square(x_ref), axis=axes) + DRIFT_EPS
    drift = jnp.max(num / den)
    idx = jnp.where((branch == schedule.CACHE_REFRESH)
                    | (drift >= spec.threshold),
                    schedule.CACHE_REFRESH, branch)
    return idx, drift


def apply_step_tel(model, params, x: jax.Array, t_vec: jax.Array,
                   branch: jax.Array, cache: Cache, spec: CacheSpec):
    """:func:`apply_step` plus the step's telemetry aux — returns
    ``(x0_raw, new_cache, idx, drift)`` where ``idx`` is the branch
    ACTUALLY taken (post-gate in adaptive mode, the static branch
    otherwise) and ``drift`` the gate's value (0 for modes that never
    compute one). A separate entry point so telemetry-off programs trace
    exactly the pre-existing jaxpr (obs/device.py holds the host side)."""
    if spec.mode == "adaptive":
        idx, drift = adaptive_gate(x, cache, branch, spec)
    else:
        idx, drift = branch, jnp.float32(0.0)
    x0, new_cache = apply_step(model, params, x, t_vec, branch, cache, spec)
    return x0, new_cache, idx, drift


def apply_step(model, params, x: jax.Array, t_vec: jax.Array,
               branch: jax.Array, cache: Cache, spec: CacheSpec):
    """One cache-aware model evaluation inside the sampler scan body.

    ``branch`` is the step's traced branch id (scanned input from
    ``spec.branches``); returns ``(x0_raw, new_cache)``. Every branch returns
    the same pytree structure, so ``lax.switch`` compiles all of them into
    the one scan program — the refresh/reuse decision costs no host sync.
    In ``"adaptive"`` mode the switch index additionally folds in the
    on-device drift gate: still the same static branch set, so the program
    has a data-dependent branch INDEX but no data-dependent structure.
    """
    depth, split = spec.depth, spec.split

    if spec.mode == "token":
        def refresh_tokens(x, cache):
            x0, tok = model.apply({"params": params}, x, t_vec,
                                  capture_tokens=True)
            return x0, tok

        def reuse_token(x, cache):
            x0, new_cache = model.apply({"params": params}, x, t_vec,
                                        token_cache=cache,
                                        token_k=spec.token_k)
            return x0, new_cache

        return jax.lax.switch(branch, (refresh_tokens, reuse_token), x, cache)

    if spec.mode == "adaptive":
        def refresh(x, cache):
            x0, deltas = model.apply({"params": params}, x, t_vec,
                                     capture_split=split)
            return x0, deltas + (x.astype(jnp.float32),)

        def reuse_rear(x, cache):
            x0 = model.apply({"params": params}, x, t_vec,
                             skip_blocks=(split, depth), block_delta=cache[1])
            return x0, cache

        def reuse_front(x, cache):
            x0 = model.apply({"params": params}, x, t_vec,
                             skip_blocks=(0, split), block_delta=cache[0])
            return x0, cache

        idx, _ = adaptive_gate(x, cache, branch, spec)
        return jax.lax.switch(idx, (refresh, reuse_rear, reuse_front),
                              x, cache)

    def refresh(x, cache):
        x0, deltas = model.apply({"params": params}, x, t_vec,
                                 capture_split=split)
        return x0, deltas

    def reuse_rear(x, cache):
        x0 = model.apply({"params": params}, x, t_vec,
                         skip_blocks=(split, depth), block_delta=cache[1])
        return x0, cache

    def reuse_front(x, cache):
        x0 = model.apply({"params": params}, x, t_vec,
                         skip_blocks=(0, split), block_delta=cache[0])
        return x0, cache

    def reuse_all(x, cache):
        x0 = model.apply({"params": params}, x, t_vec,
                         skip_blocks=(0, depth),
                         block_delta=cache[0] + cache[1])
        return x0, cache

    if spec.mode == "full":
        branches = (refresh, reuse_all)
    else:
        branches = (refresh, reuse_rear, reuse_front)
    return jax.lax.switch(branch, branches, x, cache)


def flops_saved_fraction(spec: CacheSpec) -> float:
    """Fraction of the run's BLOCK compute the schedule skips (embed/head and
    the schedule itself excluded) — the analytic ceiling on the speedup's
    compute term, quoted next to measured numbers in bench/PERF.md.

    For ``"adaptive"`` this is the gate-never-fires ceiling (every forced
    refresh the gate adds eats into it); for ``"token"`` a reuse step still
    runs ``token_k`` of ``n_tokens`` tokens through the trunk, so it saves
    the complementary fraction of the linear-in-token block cost (attention's
    quadratic term makes this a slight underestimate of the true saving)."""
    if not spec.branches:
        return 0.0
    saved = 0.0
    for b in spec.branches:
        if b == schedule.CACHE_REFRESH:
            continue
        if spec.mode == "full":
            saved += 1.0  # the whole trunk skipped
        elif spec.mode == "token":
            saved += 1.0 - spec.token_k / spec.n_tokens
        elif b == schedule.CACHE_REUSE_REAR:
            saved += (spec.depth - spec.split) / spec.depth
        else:  # CACHE_REUSE_FRONT
            saved += spec.split / spec.depth
    return saved / len(spec.branches)
