from ddim_cold_tpu.eval.fid import (
    ActivationStats,
    compute_fid,
    fid_between,
    fid_from_stats,
    frechet_distance,
    make_feature_fn,
)
from ddim_cold_tpu.eval.inception import InceptionV3Features, load_torch_inception

__all__ = [
    "ActivationStats",
    "compute_fid",
    "fid_between",
    "fid_from_stats",
    "frechet_distance",
    "make_feature_fn",
    "InceptionV3Features",
    "load_torch_inception",
]
