"""FID — Fréchet Inception Distance (streaming statistics + distance).

The north-star acceptance metric (BASELINE.json: "FID within 0.5 of the CUDA
reference"); the reference codebase itself has NO quantitative image metric
(samples are compared by eye, reference README.md:24), so this subsystem is a
required new build per SURVEY.md §7.

Pieces:
* ``ActivationStats`` — streaming (count, Σx, Σxxᵀ) accumulator; batches can
  arrive from any loader/sampler, memory is O(d²) regardless of sample count.
* ``frechet_distance`` — ‖μ₁−μ₂‖² + tr(Σ₁+Σ₂−2(Σ₁Σ₂)^½), with the matrix
  square root via symmetric eigendecomposition (no scipy dependency in the
  hot path; scipy.linalg.sqrtm is cross-checked in tests).
* ``compute_fid`` / ``fid_between`` — end-to-end: images in [0,1] → 299×299
  bilinear resize → [−1,1] → InceptionV3 pool3 features (jitted, batched) →
  statistics → distance.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable, Iterable, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ddim_cold_tpu.eval import inception


@dataclass
class ActivationStats:
    """Streaming mean/covariance of feature activations."""

    dim: int
    count: int = 0
    _sum: np.ndarray = field(default=None, repr=False)  # type: ignore[assignment]
    _outer: np.ndarray = field(default=None, repr=False)  # type: ignore[assignment]

    def __post_init__(self):
        if self._sum is None:
            self._sum = np.zeros(self.dim, np.float64)
        if self._outer is None:
            self._outer = np.zeros((self.dim, self.dim), np.float64)

    def update(self, feats: np.ndarray) -> None:
        feats = np.asarray(feats, np.float64)
        if feats.ndim != 2 or feats.shape[1] != self.dim:
            raise ValueError(f"expected (N, {self.dim}) features, got {feats.shape}")
        self.count += feats.shape[0]
        self._sum += feats.sum(axis=0)
        self._outer += feats.T @ feats

    def merge(self, other: "ActivationStats") -> "ActivationStats":
        """Combine two accumulators (e.g. per-host shards)."""
        if other.dim != self.dim:
            raise ValueError("dimension mismatch")
        out = ActivationStats(self.dim)
        out.count = self.count + other.count
        out._sum = self._sum + other._sum
        out._outer = self._outer + other._outer
        return out

    @property
    def mean(self) -> np.ndarray:
        if self.count < 1:
            raise ValueError("no samples accumulated")
        return self._sum / self.count

    @property
    def cov(self) -> np.ndarray:
        """Unbiased (N−1) covariance — matches np.cov / pytorch-fid."""
        if self.count < 2:
            raise ValueError("need ≥2 samples for covariance")
        mu = self.mean
        return (self._outer - self.count * np.outer(mu, mu)) / (self.count - 1)


def _sqrtm_psd(mat: np.ndarray, eps: float = 0.0) -> np.ndarray:
    """Symmetric-PSD matrix square root via eigh (negative eigenvalues from
    round-off are clamped to 0)."""
    w, v = np.linalg.eigh(mat)
    return (v * np.sqrt(np.clip(w, eps, None))) @ v.T


def trace_sqrt_product(sigma1: np.ndarray, sigma2: np.ndarray) -> float:
    """tr((Σ₁Σ₂)^½) computed stably: tr((Σ₁^½ Σ₂ Σ₁^½)^½) — the inner matrix
    is symmetric PSD, so everything stays in real symmetric eigensolves
    (scipy.sqrtm on the non-symmetric product can go complex)."""
    s1h = _sqrtm_psd(np.asarray(sigma1, np.float64))
    inner = s1h @ np.asarray(sigma2, np.float64) @ s1h
    inner = (inner + inner.T) / 2.0
    w = np.linalg.eigvalsh(inner)
    return float(np.sqrt(np.clip(w, 0.0, None)).sum())


def frechet_distance(mu1, sigma1, mu2, sigma2) -> float:
    """d²((μ₁,Σ₁), (μ₂,Σ₂)) = ‖μ₁−μ₂‖² + tr(Σ₁) + tr(Σ₂) − 2·tr((Σ₁Σ₂)^½)."""
    mu1, mu2 = np.asarray(mu1, np.float64), np.asarray(mu2, np.float64)
    sigma1, sigma2 = np.asarray(sigma1, np.float64), np.asarray(sigma2, np.float64)
    diff = float(((mu1 - mu2) ** 2).sum())
    return diff + float(np.trace(sigma1) + np.trace(sigma2)) \
        - 2.0 * trace_sqrt_product(sigma1, sigma2)


def fid_from_stats(a: ActivationStats, b: ActivationStats) -> float:
    return frechet_distance(a.mean, a.cov, b.mean, b.cov)


# ---------------------------------------------------------------------------
# feature extraction pipeline
# ---------------------------------------------------------------------------

def make_feature_fn(model=None, variables=None) -> tuple[Callable, int]:
    """Returns ``(feature_fn, dim)`` where ``feature_fn(images_01)`` maps a
    [0,1] NHWC batch (any resolution) to pool3 features.

    With no arguments, uses a random-init InceptionV3 — a valid metric space
    for smoke tests/regression tracking but NOT comparable to published FID
    numbers; pass variables converted from torch weights
    (inception.load_torch_inception) for those. Passing only ``variables``
    pairs them with a default ``InceptionV3Features()``; a model without
    variables is an error (random init would silently corrupt the metric).
    """
    if variables is None:
        if model is not None:
            raise ValueError(
                "inception model given without variables — refusing to pair "
                "real weights' architecture with random init")
        model, variables = inception.init_variables(jax.random.PRNGKey(0))
    elif model is None:
        model = inception.InceptionV3Features()

    @jax.jit
    def feature_fn(images_01):
        x = jnp.clip(images_01, 0.0, 1.0)
        x = jax.image.resize(
            x, (x.shape[0], inception.INCEPTION_SIZE, inception.INCEPTION_SIZE,
                x.shape[3]), method="bilinear")
        x = x * 2.0 - 1.0  # the FID-inception normalization (mean=std=0.5)
        return model.apply(variables, x)

    return feature_fn, inception.FEATURE_DIM


def stats_for_batches(batches: Iterable[np.ndarray], feature_fn: Callable,
                      dim: int = inception.FEATURE_DIM) -> ActivationStats:
    """Accumulate activation statistics over an iterable of [0,1] NHWC batches."""
    stats = ActivationStats(dim)
    for batch in batches:
        stats.update(np.asarray(feature_fn(jnp.asarray(batch))))
    return stats


def fid_between(real_batches: Iterable[np.ndarray],
                fake_batches: Iterable[np.ndarray],
                model=None, variables=None) -> float:
    """End-to-end FID between two streams of [0,1] image batches."""
    feature_fn, dim = make_feature_fn(model, variables)
    real = stats_for_batches(real_batches, feature_fn, dim)
    fake = stats_for_batches(fake_batches, feature_fn, dim)
    return fid_from_stats(real, fake)


def compute_fid(
    model,
    params,
    real_batches: Iterable[np.ndarray],
    *,
    rng: jax.Array,
    n_samples: int = 1024,
    sample_batch: int = 64,
    k: int = 20,
    inception_model=None,
    inception_variables=None,
    sampler: Optional[Callable] = None,
    cache_interval: int = 1,
    cache_mode: str = "delta",
    cache_threshold: Optional[float] = None,
    cache_tokens: Optional[int] = None,
) -> float:
    """FID of a diffusion model's samples against a real-image stream.

    ``model/params`` are the DiffusionViT; samples are drawn with
    ``ops.sampling.ddim_sample`` at stride ``k`` (the north-star metric path:
    200px, k=20) unless a custom ``sampler(rng, n) → [0,1] images`` is given.
    ``cache_interval``/``cache_mode`` pass through to the sampler's step
    cache (ops/step_cache.py); the default interval=1 is the exact sampler.
    """
    from ddim_cold_tpu.ops import sampling

    feature_fn, dim = make_feature_fn(inception_model, inception_variables)
    real = stats_for_batches(real_batches, feature_fn, dim)
    fake = ActivationStats(dim)
    remaining = n_samples
    while remaining > 0:
        # always sample a full batch (static shape → one sampler/inception
        # compile); surplus features of the final batch are dropped before
        # they reach the statistics.
        keep = min(sample_batch, remaining)
        rng, sub = jax.random.split(rng)
        imgs = (sampler(sub, sample_batch) if sampler is not None
                else sampling.ddim_sample(model, params, sub, k=k, n=sample_batch,
                                          cache_interval=cache_interval,
                                          cache_mode=cache_mode,
                                          cache_threshold=cache_threshold,
                                          cache_tokens=cache_tokens))
        fake.update(np.asarray(feature_fn(imgs))[:keep])
        remaining -= keep
    return fid_from_stats(real, fake)


def cached_sampler_guard(
    model,
    params,
    *,
    rng: jax.Array,
    n_samples: int = 256,
    sample_batch: int = 64,
    k: int = 20,
    cache_interval: int = 2,
    cache_mode: str = "full",
    cache_threshold: Optional[float] = None,
    cache_tokens: Optional[int] = None,
    task: str = "sample",
    mask=None,
    inception_model=None,
    inception_variables=None,
) -> dict:
    """Quality guard for the step-cached sampler (ops/step_cache.py): the
    Fréchet distance between the EXACT and CACHED samplers' output streams
    drawn from the SAME rng sequence, under one extractor.

    This is deliberately not "FID vs the real set twice": a paired
    exact-vs-cached distance isolates the cache's own distributional shift
    (it is exactly 0 when the cache is harmless and needs no real images or
    canonical extractor weights), where two FID-vs-real numbers would bury a
    small shift under the shared real-set term. With no
    ``inception_variables`` the extractor is the seeded random-init proxy
    (see :func:`make_feature_fn`) — fine here, because both streams go
    through the SAME extractor and only their distance is reported.

    ``cache_threshold``/``cache_tokens`` pass through to the adaptive/token
    modes (see ``ddim_sample``). ``task`` selects the guarded workload:
    ``"sample"`` (plain generation) or ``"inpaint"``, which pairs the exact
    and step-cached inpainting scans over the same known images (a fresh
    uniform [−1,1] batch per step, drawn from the shared rng stream) and
    ``mask`` (default: top half known) — guarding the editing path's cache
    composition, where the per-step mask re-projection keeps feeding the
    drift gate pixels the cache never predicted.

    Returns a dict with ``fid_exact_vs_cached``, ``max_abs_pixel_delta``
    (worst per-pixel divergence across every paired batch) and the sampler
    configuration, ready to land in a bench record.
    """
    from ddim_cold_tpu.ops import sampling

    if task not in ("sample", "inpaint"):
        raise ValueError(f"cached_sampler_guard task must be 'sample' or "
                         f"'inpaint', got {task!r}")
    feature_fn, dim = make_feature_fn(inception_model, inception_variables)
    exact, cached = ActivationStats(dim), ActivationStats(dim)
    H, W = model.img_size
    if task == "inpaint" and mask is None:
        mask = np.zeros((H, W), np.float32)
        mask[: H // 2] = 1.0
    max_delta = 0.0
    remaining = n_samples
    while remaining > 0:
        keep = min(sample_batch, remaining)
        rng, sub = jax.random.split(rng)
        if task == "inpaint":
            from ddim_cold_tpu import workloads

            known = jax.random.uniform(
                jax.random.fold_in(sub, 0xFACE),
                (sample_batch, H, W, model.in_chans),
                jnp.float32, -1.0, 1.0)
            imgs_e = workloads.inpaint(model, params, sub, known, mask, k=k)
            imgs_c = workloads.inpaint(model, params, sub, known, mask, k=k,
                                       cache_interval=cache_interval,
                                       cache_mode=cache_mode,
                                       cache_threshold=cache_threshold,
                                       cache_tokens=cache_tokens)
        else:
            imgs_e = sampling.ddim_sample(model, params, sub, k=k,
                                          n=sample_batch)
            imgs_c = sampling.ddim_sample(model, params, sub, k=k,
                                          n=sample_batch,
                                          cache_interval=cache_interval,
                                          cache_mode=cache_mode,
                                          cache_threshold=cache_threshold,
                                          cache_tokens=cache_tokens)
        max_delta = max(max_delta, float(jnp.max(jnp.abs(imgs_e - imgs_c))))
        exact.update(np.asarray(feature_fn(imgs_e))[:keep])
        cached.update(np.asarray(feature_fn(imgs_c))[:keep])
        remaining -= keep
    return {
        "fid_exact_vs_cached": round(float(fid_from_stats(exact, cached)), 4),
        "max_abs_pixel_delta": round(max_delta, 6),
        "n_samples": n_samples,
        "k": k,
        "task": task,
        "cache_interval": cache_interval,
        "cache_mode": cache_mode,
        "cache_threshold": cache_threshold,
        "cache_tokens": cache_tokens,
        "extractor": ("canonical" if inception_variables is not None else
                      "seeded random-init proxy (paired streams, same "
                      "extractor — distance is meaningful, absolute FID "
                      "scale is not)"),
    }


def quantized_sampler_guard(
    model,
    params,
    *,
    rng: jax.Array,
    n_samples: int = 256,
    sample_batch: int = 64,
    k: int = 20,
    quant: str = "xla",
    cache_interval: int = 1,
    cache_mode: str = "full",
    quantized_params=None,
    inception_model=None,
    inception_variables=None,
) -> dict:
    """Quality guard for the w8a16 trunk (ops/quant.py), the exact shape of
    :func:`cached_sampler_guard`: the Fréchet distance between the EXACT
    float and the QUANTIZED samplers' output streams from the SAME rng
    sequence under one extractor — 0 when quantization is harmless, and the
    acceptance bound ("shift ≤ 0.5") reads directly off it.

    ``model/params`` are the float pair; the quantized side runs
    ``model.clone(quant=quant)`` over ``quant.quantize_params(params)``
    (pass ``quantized_params`` to reuse a tree built elsewhere, e.g. the
    serving engine's). ``cache_interval`` > 1 additionally routes the
    quantized stream through the step cache, measuring the COMPOSED shift
    (quantization × block reuse) the PERF.md composition table reports.
    Alongside the distance, ``quant.calibrate``'s per-layer max-abs-error
    stats ride the report so a bad distance is attributable to a layer.
    """
    from ddim_cold_tpu.ops import quant as quant_mod
    from ddim_cold_tpu.ops import sampling

    qmodel = model.clone(quant=quant)
    qparams = (quantized_params if quantized_params is not None
               else quant_mod.quantize_params(params))
    feature_fn, dim = make_feature_fn(inception_model, inception_variables)
    exact, quantized = ActivationStats(dim), ActivationStats(dim)
    max_delta = 0.0
    remaining = n_samples
    while remaining > 0:
        keep = min(sample_batch, remaining)
        rng, sub = jax.random.split(rng)
        imgs_e = sampling.ddim_sample(model, params, sub, k=k, n=sample_batch)
        imgs_q = sampling.ddim_sample(qmodel, qparams, sub, k=k,
                                      n=sample_batch,
                                      cache_interval=cache_interval,
                                      cache_mode=cache_mode)
        max_delta = max(max_delta, float(jnp.max(jnp.abs(imgs_e - imgs_q))))
        exact.update(np.asarray(feature_fn(imgs_e))[:keep])
        quantized.update(np.asarray(feature_fn(imgs_q))[:keep])
        remaining -= keep
    cal = quant_mod.calibrate(params)
    worst = (max(cal.items(), key=lambda kv: kv[1]["max_abs_err"])
             if cal else (None, None))
    return {
        "fid_exact_vs_quant": round(float(fid_from_stats(exact, quantized)), 4),
        "max_abs_pixel_delta": round(max_delta, 6),
        "n_samples": n_samples,
        "k": k,
        "quant": quant,
        "quant_rev": quant_mod.QUANT_REV,
        "cache_interval": cache_interval,
        "cache_mode": cache_mode,
        "calibration_worst_layer": worst[0],
        "calibration_max_abs_err": (None if worst[1] is None
                                    else round(worst[1]["max_abs_err"], 8)),
        "extractor": ("canonical" if inception_variables is not None else
                      "seeded random-init proxy (paired streams, same "
                      "extractor — distance is meaningful, absolute FID "
                      "scale is not)"),
    }


def distilled_sampler_guard(
    model,
    teacher_params,
    student_params,
    *,
    rng: jax.Array,
    steps: int,
    n_samples: int = 256,
    sample_batch: int = 64,
    k: int = 20,
    cache_interval: int = 1,
    cache_mode: str = "full",
    inception_model=None,
    inception_variables=None,
) -> dict:
    """Quality guard for few-step distilled serving (train/distill.py +
    ``SamplerConfig(steps=k)``), the exact shape of
    :func:`quantized_sampler_guard`: the Fréchet distance between the
    TEACHER's k-step baseline stream and the STUDENT's ``steps``-evaluation
    stream from the SAME rng sequence under one extractor — so a latency win
    bought by cutting k can never silently buy a quality loss. Run it once
    per served student (steps ∈ {1, 2, 4}) to fill PERF.md's k-vs-quality
    table.

    Both streams draw the SAME init per batch (same sub-key, same n), so
    the distance isolates the schedule compression: teacher refines that
    init over ``k`` strided steps (``ddim_sample``), the student jumps it
    through its ``steps``-level schedule (``ddim_sample_fewstep``).
    ``cache_interval`` > 1 routes the STUDENT stream through the step cache,
    measuring the composed shift (distillation × block reuse). Unlike the
    quant guard there is no ``max_abs_pixel_delta`` acceptance reading —
    teacher and student outputs differ by design; the Fréchet shift IS the
    metric.
    """
    from ddim_cold_tpu.ops import sampling

    feature_fn, dim = make_feature_fn(inception_model, inception_variables)
    teacher, student = ActivationStats(dim), ActivationStats(dim)
    max_delta = 0.0
    remaining = n_samples
    while remaining > 0:
        keep = min(sample_batch, remaining)
        rng, sub = jax.random.split(rng)
        imgs_t = sampling.ddim_sample(model, teacher_params, sub, k=k,
                                      n=sample_batch)
        imgs_s = sampling.ddim_sample_fewstep(model, student_params, sub,
                                              steps=steps, n=sample_batch,
                                              cache_interval=cache_interval,
                                              cache_mode=cache_mode)
        max_delta = max(max_delta, float(jnp.max(jnp.abs(imgs_t - imgs_s))))
        teacher.update(np.asarray(feature_fn(imgs_t))[:keep])
        student.update(np.asarray(feature_fn(imgs_s))[:keep])
        remaining -= keep
    return {
        "fid_teacher_vs_student": round(float(fid_from_stats(teacher,
                                                             student)), 4),
        "max_abs_pixel_delta": round(max_delta, 6),
        "n_samples": n_samples,
        "k": k,
        "steps": steps,
        "cache_interval": cache_interval,
        "cache_mode": cache_mode,
        "extractor": ("canonical" if inception_variables is not None else
                      "seeded random-init proxy (paired streams, same "
                      "extractor — distance is meaningful, absolute FID "
                      "scale is not)"),
    }


def superres_consistency_guard(outputs, low_res) -> dict:
    """Editing-quality guard for served super-resolution (ROADMAP open
    item): the delivered output must still CONTAIN its input — nearest-
    downsampling the output (ops/degrade's floor-index convention, i.e.
    sampling the static anchor pixels) must reproduce the low-res input
    bit-exactly, in the engine's [0, 1] delivery space against the task's
    [−1, 1] input space (``(low_res + 1) / 2``).

    The raw cold scan does not guarantee this (its naive Algorithm-1 update
    predicts the anchors rather than carrying them), so callers run
    ``workloads.superres_project`` — the host-side data-consistency
    projection — on the delivered batch first; the guard then proves the
    whole convention stack end to end: the nearest-index math, the value
    mapping, and (served) that every row was projected against ITS OWN
    request's input — a row swap, a bucket-padding leak, or a resampled
    index table all break bit-exactness. ``bench.py --edit`` rides this and
    raises when ``bit_exact`` is False.

    Returns ``{"bit_exact", "max_abs_delta", "anchor_pixels"}`` —
    ``max_abs_delta`` is also a useful RAW-output quality metric (how far
    the un-projected sampler drifts from its input), which is why the guard
    takes arrays instead of running the sampler itself.
    """
    from ddim_cold_tpu.data.resize import nearest_indices

    out = np.asarray(outputs, np.float32)
    low = np.asarray(low_res, np.float32)
    if out.ndim == 3:
        out = out[None]
    if low.ndim == 3:
        low = low[None]
    iy = nearest_indices(low.shape[1], out.shape[1])
    ix = nearest_indices(low.shape[2], out.shape[2])
    down = out[:, iy[:, None], ix[None, :], :]
    target = (low + 1.0) / 2.0
    return {
        "bit_exact": bool(np.array_equal(down, target)),
        "max_abs_delta": round(float(np.max(np.abs(down - target))), 6),
        "anchor_pixels": int(down[0, ..., 0].size),
    }
