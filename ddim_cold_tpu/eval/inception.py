"""InceptionV3 feature extractor (Flax) for FID.

The reference has no evaluation metric at all (its acceptance artifact is
sample PNGs compared by eye, reference README.md:24); the north-star target is
FID, so this subsystem is new-build per SURVEY.md §7 ("FID evaluation infra
... must be added (InceptionV3 in Flax + activation statistics)").

Architecture mirrors torchvision's ``inception_v3`` (aux head omitted — FID
reads the 2048-d pool3 features), with module names matching the torch
state_dict (``Conv2d_1a_3x3``, ``Mixed_5b.branch1x1`` …) so that
``flax_from_torch_inception`` is a purely mechanical layout transform. Feed it
a torchvision ``Inception_V3_Weights`` state_dict — or the pytorch-fid port of
the original TF weights for numbers comparable with published FID scores (the
two differ slightly; FID is only comparable under a fixed extractor either
way).

All convs run in NHWC (TPU-native layout); BatchNorm uses stored running
statistics (inference only).
"""

from __future__ import annotations

from functools import partial
from typing import Any
import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

#: canonical FID input resolution
INCEPTION_SIZE = 299
#: pool3 feature width
FEATURE_DIM = 2048


class BasicConv2d(nn.Module):
    """Conv(bias=False) → BatchNorm(eps=1e-3, running stats) → ReLU."""

    features: int
    kernel: tuple[int, int]
    strides: tuple[int, int] = (1, 1)
    padding: Any = (0, 0)
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        pad = self.padding
        if isinstance(pad, tuple) and isinstance(pad[0], int):
            pad = ((pad[0], pad[0]), (pad[1], pad[1]))
        x = nn.Conv(self.features, self.kernel, strides=self.strides, padding=pad,
                    use_bias=False, dtype=self.dtype, name="conv")(x)
        x = nn.BatchNorm(use_running_average=True, epsilon=1e-3, momentum=0.9,
                         dtype=self.dtype, name="bn")(x)
        return nn.relu(x)


def _avg_pool_3x3_same(x: jax.Array) -> jax.Array:
    """torch avg_pool2d(k=3, s=1, p=1, count_include_pad=True): zero-pad then
    divide by 9 everywhere — NOT the edge-renormalizing 'SAME' average."""
    s = jax.lax.reduce_window(x, 0.0, jax.lax.add, (1, 3, 3, 1), (1, 1, 1, 1),
                              [(0, 0), (1, 1), (1, 1), (0, 0)])
    return s / 9.0


def _max_pool_3x3_s2(x: jax.Array) -> jax.Array:
    return nn.max_pool(x, (3, 3), strides=(2, 2))


class InceptionA(nn.Module):
    pool_features: int
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x):
        c = partial(BasicConv2d, dtype=self.dtype)
        b1 = c(64, (1, 1), name="branch1x1")(x)
        b5 = c(48, (1, 1), name="branch5x5_1")(x)
        b5 = c(64, (5, 5), padding=(2, 2), name="branch5x5_2")(b5)
        b3 = c(64, (1, 1), name="branch3x3dbl_1")(x)
        b3 = c(96, (3, 3), padding=(1, 1), name="branch3x3dbl_2")(b3)
        b3 = c(96, (3, 3), padding=(1, 1), name="branch3x3dbl_3")(b3)
        bp = c(self.pool_features, (1, 1), name="branch_pool")(_avg_pool_3x3_same(x))
        return jnp.concatenate([b1, b5, b3, bp], axis=-1)


class InceptionB(nn.Module):
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x):
        c = partial(BasicConv2d, dtype=self.dtype)
        b3 = c(384, (3, 3), strides=(2, 2), name="branch3x3")(x)
        bd = c(64, (1, 1), name="branch3x3dbl_1")(x)
        bd = c(96, (3, 3), padding=(1, 1), name="branch3x3dbl_2")(bd)
        bd = c(96, (3, 3), strides=(2, 2), name="branch3x3dbl_3")(bd)
        return jnp.concatenate([b3, bd, _max_pool_3x3_s2(x)], axis=-1)


class InceptionC(nn.Module):
    channels_7x7: int
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x):
        c = partial(BasicConv2d, dtype=self.dtype)
        c7 = self.channels_7x7
        b1 = c(192, (1, 1), name="branch1x1")(x)
        b7 = c(c7, (1, 1), name="branch7x7_1")(x)
        b7 = c(c7, (1, 7), padding=(0, 3), name="branch7x7_2")(b7)
        b7 = c(192, (7, 1), padding=(3, 0), name="branch7x7_3")(b7)
        bd = c(c7, (1, 1), name="branch7x7dbl_1")(x)
        bd = c(c7, (7, 1), padding=(3, 0), name="branch7x7dbl_2")(bd)
        bd = c(c7, (1, 7), padding=(0, 3), name="branch7x7dbl_3")(bd)
        bd = c(c7, (7, 1), padding=(3, 0), name="branch7x7dbl_4")(bd)
        bd = c(192, (1, 7), padding=(0, 3), name="branch7x7dbl_5")(bd)
        bp = c(192, (1, 1), name="branch_pool")(_avg_pool_3x3_same(x))
        return jnp.concatenate([b1, b7, bd, bp], axis=-1)


class InceptionD(nn.Module):
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x):
        c = partial(BasicConv2d, dtype=self.dtype)
        b3 = c(192, (1, 1), name="branch3x3_1")(x)
        b3 = c(320, (3, 3), strides=(2, 2), name="branch3x3_2")(b3)
        b7 = c(192, (1, 1), name="branch7x7x3_1")(x)
        b7 = c(192, (1, 7), padding=(0, 3), name="branch7x7x3_2")(b7)
        b7 = c(192, (7, 1), padding=(3, 0), name="branch7x7x3_3")(b7)
        b7 = c(192, (3, 3), strides=(2, 2), name="branch7x7x3_4")(b7)
        return jnp.concatenate([b3, b7, _max_pool_3x3_s2(x)], axis=-1)


class InceptionE(nn.Module):
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x):
        c = partial(BasicConv2d, dtype=self.dtype)
        b1 = c(320, (1, 1), name="branch1x1")(x)
        b3 = c(384, (1, 1), name="branch3x3_1")(x)
        b3 = jnp.concatenate([
            c(384, (1, 3), padding=(0, 1), name="branch3x3_2a")(b3),
            c(384, (3, 1), padding=(1, 0), name="branch3x3_2b")(b3),
        ], axis=-1)
        bd = c(448, (1, 1), name="branch3x3dbl_1")(x)
        bd = c(384, (3, 3), padding=(1, 1), name="branch3x3dbl_2")(bd)
        bd = jnp.concatenate([
            c(384, (1, 3), padding=(0, 1), name="branch3x3dbl_3a")(bd),
            c(384, (3, 1), padding=(1, 0), name="branch3x3dbl_3b")(bd),
        ], axis=-1)
        bp = c(192, (1, 1), name="branch_pool")(_avg_pool_3x3_same(x))
        return jnp.concatenate([b1, b3, bd, bp], axis=-1)


class InceptionV3Features(nn.Module):
    """NHWC [−1, 1] images at 299×299 → (N, 2048) pool3 features."""

    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        c = partial(BasicConv2d, dtype=self.dtype)
        x = c(32, (3, 3), strides=(2, 2), name="Conv2d_1a_3x3")(x)
        x = c(32, (3, 3), name="Conv2d_2a_3x3")(x)
        x = c(64, (3, 3), padding=(1, 1), name="Conv2d_2b_3x3")(x)
        x = _max_pool_3x3_s2(x)
        x = c(80, (1, 1), name="Conv2d_3b_1x1")(x)
        x = c(192, (3, 3), name="Conv2d_4a_3x3")(x)
        x = _max_pool_3x3_s2(x)
        x = InceptionA(32, dtype=self.dtype, name="Mixed_5b")(x)
        x = InceptionA(64, dtype=self.dtype, name="Mixed_5c")(x)
        x = InceptionA(64, dtype=self.dtype, name="Mixed_5d")(x)
        x = InceptionB(dtype=self.dtype, name="Mixed_6a")(x)
        x = InceptionC(128, dtype=self.dtype, name="Mixed_6b")(x)
        x = InceptionC(160, dtype=self.dtype, name="Mixed_6c")(x)
        x = InceptionC(160, dtype=self.dtype, name="Mixed_6d")(x)
        x = InceptionC(192, dtype=self.dtype, name="Mixed_6e")(x)
        x = InceptionD(dtype=self.dtype, name="Mixed_7a")(x)
        x = InceptionE(dtype=self.dtype, name="Mixed_7b")(x)
        x = InceptionE(dtype=self.dtype, name="Mixed_7c")(x)
        return jnp.mean(x, axis=(1, 2))  # global average pool → (N, 2048)


def init_variables(rng: jax.Array, dtype=jnp.float32):
    """Seeded-random variables (params + batch_stats) forming a usable
    feature space: conv kernels are rescaled by √2 (He gain for ReLU) —
    without it the default lecun init loses ~9% signal std per layer and the
    94-conv stack collapses features to ≈1e-4 std, making every FID ≈ 0.
    Random features define a valid (if non-comparable-to-published) metric
    space under a FIXED seed; real FID needs converted torch weights
    (``load_torch_inception``)."""
    model = InceptionV3Features(dtype=dtype)
    tiny = jnp.zeros((1, INCEPTION_SIZE, INCEPTION_SIZE, 3), dtype)
    # jit: the 94-conv init traced eagerly costs ~20s on CPU; compiled (and
    # persistently cached) it is sub-second on reruns
    variables = jax.jit(model.init)(rng, tiny)

    def he(tree):
        return {
            k: (he(v) if isinstance(v, dict)
                else v * np.sqrt(2.0) if k == "kernel" else v)
            for k, v in tree.items()
        }

    return model, {"params": he(variables["params"]),
                   "batch_stats": variables["batch_stats"]}


def flax_from_torch_inception(state_dict: dict) -> dict:
    """torchvision ``inception_v3`` state_dict → {'params', 'batch_stats'}.

    Layout transforms only: conv ``(O, I, kh, kw)`` → ``(kh, kw, I, O)``;
    bn weight/bias → scale/bias, running_mean/var → batch_stats. The aux head
    (``AuxLogits.*``) and the classifier ``fc.*`` are ignored.
    """
    to_np = lambda v: np.asarray(
        v.detach().cpu().numpy() if hasattr(v, "detach") else v, np.float32)
    params: dict = {}
    stats: dict = {}

    def put(tree, path, leaf):
        node = tree
        for k in path[:-1]:
            node = node.setdefault(k, {})
        node[path[-1]] = leaf

    for key, value in state_dict.items():
        if key.startswith(("AuxLogits.", "fc.")):
            continue
        parts = key.split(".")
        mod_path, leaf_name = parts[:-1], parts[-1]
        v = to_np(value)
        if leaf_name == "weight" and mod_path[-1] == "conv":
            put(params, mod_path + ["kernel"], v.transpose(2, 3, 1, 0))
        elif mod_path[-1] == "bn":
            if leaf_name == "weight":
                put(params, mod_path + ["scale"], v)
            elif leaf_name == "bias":
                put(params, mod_path + ["bias"], v)
            elif leaf_name == "running_mean":
                put(stats, mod_path + ["mean"], v)
            elif leaf_name == "running_var":
                put(stats, mod_path + ["var"], v)
            # num_batches_tracked: irrelevant at inference
        elif leaf_name == "bias" and mod_path[-1] == "conv":
            put(params, mod_path + ["bias"], v)  # not present in torchvision
        else:
            raise ValueError(f"unexpected torch key {key!r}")
    return {"params": params, "batch_stats": stats}


def load_torch_inception(path: str):
    """Load a torchvision inception_v3 ``.pth`` checkpoint → (model, variables).
    torch is a conversion-time-only dependency (same policy as
    utils/checkpoint.py).

    The converted tree is VERIFIED against the model's own init template —
    every param/stat path must exist with the right shape, both directions —
    before it is returned: a truncated or wrong-architecture file (e.g. a
    classifier-only checkpoint) fails here with the offending path named,
    not deep inside the first FID batch. The numerics of the layout
    transform itself are pinned against a real torch forward in
    tests/test_inception_parity.py."""
    import jax
    import torch

    sd = torch.load(path, map_location="cpu", weights_only=False)
    if not isinstance(sd, dict):
        sd = sd.state_dict()
    variables = flax_from_torch_inception(sd)
    # shapes only — eval_shape traces the init abstractly (no compile, no
    # FLOPs), where a real init_variables() would pay the full 94-conv init
    template = jax.eval_shape(
        InceptionV3Features().init, jax.random.PRNGKey(0),
        jnp.zeros((1, INCEPTION_SIZE, INCEPTION_SIZE, 3)))
    want = {p: v.shape for p, v in
            jax.tree_util.tree_leaves_with_path(template)}
    got = {p: v.shape for p, v in
           jax.tree_util.tree_leaves_with_path(variables)}
    for p, shape in want.items():
        name = jax.tree_util.keystr(p)
        if p not in got:
            raise ValueError(
                f"{path}: converted checkpoint is missing {name} — not a "
                "full torchvision/pytorch-fid inception_v3 state_dict?")
        if tuple(got[p]) != tuple(shape):
            raise ValueError(
                f"{path}: {name} has shape {tuple(got[p])}, expected "
                f"{tuple(shape)}")
    extra = [jax.tree_util.keystr(p) for p in got if p not in want]
    if extra:
        raise ValueError(f"{path}: unexpected converted keys {extra[:5]}")
    return InceptionV3Features(), variables
