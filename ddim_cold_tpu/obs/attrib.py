"""Profiler-trace attribution: device time → named scopes → roofline/MFU.

PR 10 planted ``jax.named_scope`` markers (``sampler/model``,
``flash_attention/*``, ``dequant_matmul/pallas``, ``sp/*``) and the
``profiling.span_trace`` / ``bench --profile-northstar`` capture paths, but
nothing in-tree parsed the resulting dumps — the ROADMAP's MFU item ("the
hardware is >90% idle") had evidence with no reader. This module is the
reader:

* :func:`load_trace` — Chrome trace-event JSON as ``jax.profiler`` writes it
  (``<dir>/plugins/profile/<run>/<host>.trace.json.gz``), plain ``.json`` /
  ``.json.gz`` files, or an already-loaded dict.
* :func:`attribute` — picks each device's op lane, reconstructs the scope
  hierarchy from the op-name paths XLA stamps through ``named_scope``,
  splits device-busy vs idle-gap time per scope, joins the scopes against
  ``utils/flops.py`` flop/byte estimates (achieved TFLOP/s, per-scope MFU,
  compute-vs-HBM roofline class), and ranks fusion candidates — adjacent
  hot scopes separated by sub-``gap_us`` launch gaps, the shortlist the
  profile-driven Pallas pass consumes.
* :func:`synthetic_demo_trace` / :func:`demo_scope_costs` — the
  deterministic fixture ``scripts/attrib_report.py --demo`` and
  ``tests/test_attrib.py`` run against, and the loudly-labeled stand-in the
  bench ``--attrib`` leg asserts coverage on when a CPU capture carries no
  device lanes (jax CPU traces record host threads only).

Host-only module (graftcheck A004): no jax anywhere — traces are parsed
after the fact, often on a machine that never saw the device.
"""

from __future__ import annotations

import gzip
import json
import os
from typing import Optional

from ddim_cold_tpu.utils import flops as flops_util

#: every scope profiling.scope plants in the tree (ops/sampling.py,
#: ops/flash_attention.py, ops/quant.py, parallel/) — attribution's
#: registry: device time matching none of these is "unattributed", and the
#: bench leg's ≥90% coverage floor is measured against this list.
#: tests/test_attrib.py pins each entry to a literal call site.
REGISTERED_SCOPES = (
    "sampler/model",
    "sampler/cached_step",
    "flash_attention/fwd",
    "flash_attention/dq",
    "flash_attention/dkv",
    "flash_attention/fused_qkv",
    "flash_attention/fused_proj",
    "dequant_matmul/pallas",
    "mlp/pallas",
    "sp/ring_exchange",
    "sp/all_to_all_gather",
    "sp/all_to_all_scatter",
)

#: the bench --attrib acceptance floor: fraction of device-busy time that
#: must attribute to REGISTERED_SCOPES.
COVERAGE_FLOOR = 0.9

#: launch-gap ceiling (µs) for two adjacent scoped ops to count as a fusion
#: candidate pair.
DEFAULT_GAP_US = 50.0

DEMO_DEVICE_KIND = "TPU v5 lite"


class AttribError(ValueError):
    """A trace that cannot be parsed (missing file, bad JSON, no events)."""


_SCOPE = None


def _mscope():
    # lazy: scope ids are deterministic in construction order, so importing
    # this module must not consume one before the serving layers build theirs
    global _SCOPE
    if _SCOPE is None:
        from ddim_cold_tpu.obs import metrics
        _SCOPE = metrics.scope("attrib")
    return _SCOPE


# ---------------------------------------------------------------------------
# loading
# ---------------------------------------------------------------------------

def _read_json(path: str) -> dict:
    opener = gzip.open if path.endswith(".gz") else open
    try:
        with opener(path, "rt", errors="replace") as f:
            obj = json.load(f)
    except (OSError, ValueError) as e:
        raise AttribError(f"{path}: not a readable trace-event JSON ({e})")
    if not isinstance(obj, dict) or "traceEvents" not in obj:
        raise AttribError(f"{path}: no traceEvents key — not a Chrome "
                          "trace-event dump")
    return obj


def _trace_files(root: str) -> list:
    """Trace-event JSON files under a ``jax.profiler`` output dir: the
    newest ``plugins/profile/<run>/`` run, preferring the per-host
    ``*.trace.json(.gz)`` exports (they carry process/thread metadata for
    every plane) over ``perfetto_trace.json.gz``."""
    runs = sorted(
        d for d in (os.path.join(root, "plugins", "profile", n)
                    for n in (os.listdir(os.path.join(root, "plugins",
                                                      "profile"))
                              if os.path.isdir(os.path.join(
                                  root, "plugins", "profile")) else []))
        if os.path.isdir(d))
    search = [runs[-1]] if runs else [root]
    for d in search:
        names = sorted(os.listdir(d))
        hits = [os.path.join(d, n) for n in names
                if n.endswith((".trace.json", ".trace.json.gz"))]
        if not hits:
            hits = [os.path.join(d, n) for n in names
                    if n in ("perfetto_trace.json", "perfetto_trace.json.gz")]
        if hits:
            return hits
    return []


def load_trace(path) -> dict:
    """→ ``{"traceEvents": [...]}`` from a dict (passthrough), a ``.json`` /
    ``.json.gz`` file, or a profiler output directory (multiple hosts'
    dumps merge into one event list). Raises :exc:`AttribError` when
    nothing parseable is found."""
    if isinstance(path, dict):
        if "traceEvents" not in path:
            raise AttribError("trace dict has no traceEvents key")
        return path
    if os.path.isdir(path):
        files = _trace_files(path)
        if not files:
            raise AttribError(f"{path}: no trace-event JSON found (expected "
                              "plugins/profile/<run>/*.trace.json.gz — pass "
                              "perfetto=True to profiling.trace)")
        merged: list = []
        for f in files:
            merged.extend(_read_json(f).get("traceEvents") or [])
        return {"traceEvents": merged}
    return _read_json(path)


# ---------------------------------------------------------------------------
# lanes + scope matching
# ---------------------------------------------------------------------------

def _metadata_names(events) -> tuple:
    procs: dict = {}
    threads: dict = {}
    for ev in events:
        if ev.get("ph") != "M":
            continue
        args = ev.get("args") or {}
        if ev.get("name") == "process_name":
            procs[ev.get("pid")] = str(args.get("name", ""))
        elif ev.get("name") == "thread_name":
            threads[(ev.get("pid"), ev.get("tid"))] = str(args.get("name", ""))
    return procs, threads


def _is_device_process(name: str) -> bool:
    # xprof device planes are "/device:TPU:0 ..." (host planes "/host:CPU");
    # GPU exports sometimes drop the /device: prefix
    return ("/device:" in name and "/device:CPU" not in name) or \
        name.startswith(("TPU", "GPU"))


def scope_chain(event) -> tuple:
    """The ordered REGISTERED_SCOPES appearing in the event's op path —
    ``named_scope`` names land inside XLA op metadata (the event name for
    bare ops, ``args.long_name``/``args.tf_op``/``args.name`` for fusions),
    nested outer→inner, so positional order in the text IS the hierarchy.
    Empty tuple = unattributed."""
    texts = [str(event.get("name", ""))]
    args = event.get("args") or {}
    for v in args.values():
        if isinstance(v, str):
            texts.append(v)
    for text in texts:
        found = [(text.index(s), s) for s in REGISTERED_SCOPES if s in text]
        if found:
            return tuple(s for _, s in sorted(found))
    return ()


def _merged_busy(intervals) -> tuple:
    """(union-seconds, merged [(start, end)]) over µs intervals."""
    if not intervals:
        return 0.0, []
    ivs = sorted(intervals)
    merged = [list(ivs[0])]
    for s, e in ivs[1:]:
        if s <= merged[-1][1]:
            merged[-1][1] = max(merged[-1][1], e)
        else:
            merged.append([s, e])
    return sum(e - s for s, e in merged) * 1e-6, merged


def _device_op_lanes(events) -> dict:
    """{(pid, tid): [complete events]} — per device process, the ONE lane
    that looks like the op timeline: most scope-matching events, ties broken
    by event count. xprof emits several lanes per device (XLA Modules, Steps,
    framework ops); summing them would double-count busy time, and the
    module-level lane carries no scope names — coverage measured over it
    would be noise, not evidence."""
    procs, _ = _metadata_names(events)
    by_lane: dict = {}
    for ev in events:
        if ev.get("ph") != "X" or ev.get("dur") is None:
            continue
        if not _is_device_process(procs.get(ev.get("pid"), "")):
            continue
        by_lane.setdefault((ev.get("pid"), ev.get("tid")), []).append(ev)
    chosen: dict = {}
    best: dict = {}
    for (pid, tid), evs in by_lane.items():
        score = (sum(1 for e in evs if scope_chain(e)), len(evs))
        if pid not in best or score > best[pid]:
            best[pid] = score
            chosen[pid] = ((pid, tid), evs)
    return dict(chosen.values())


# ---------------------------------------------------------------------------
# attribution
# ---------------------------------------------------------------------------

def attribute(trace, *, device_kind: Optional[str] = None, scope_costs=None,
              gap_us: float = DEFAULT_GAP_US) -> dict:
    """Attribute a loaded trace (or path — see :func:`load_trace`) to the
    registered scope hierarchy.

    ``scope_costs`` maps scope → ``{"flops", "bytes"}`` for the WHOLE
    captured window (``flops_util.vit_scope_costs`` × images × model calls);
    with it and a recognized ``device_kind``, each scope gains achieved
    TFLOP/s, MFU and a roofline class. Per-scope time is reported both
    exclusive (``self_s``: the scope was the innermost match) and inclusive
    (``total_s``: the scope was anywhere on the chain) — MFU divides the
    inclusive time, matching the inclusive cost model.
    """
    trace = load_trace(trace)
    events = trace.get("traceEvents") or []
    lanes = _device_op_lanes(events)
    peak = flops_util.peak_tflops(device_kind) if device_kind else None
    ridge = (flops_util.ridge_flops_per_byte(device_kind)
             if device_kind else None)

    busy_s = idle_s = window_s = attributed_s = 0.0
    scopes: dict = {}
    children: dict = {}
    pair_gaps: dict = {}
    for _, evs in lanes.items():
        ivs = [(ev["ts"], ev["ts"] + ev["dur"]) for ev in evs]
        lane_busy, merged = _merged_busy(ivs)
        busy_s += lane_busy
        lo = min(s for s, _ in merged)
        hi = max(e for _, e in merged)
        window_s += (hi - lo) * 1e-6
        idle_s += (hi - lo) * 1e-6 - lane_busy
        scoped = []
        for ev in evs:
            chain = scope_chain(ev)
            if not chain:
                continue
            scoped.append((ev["ts"], ev["ts"] + ev["dur"], chain))
            dur = ev["dur"] * 1e-6
            leaf = chain[-1]
            node = scopes.setdefault(leaf, {"events": 0, "self_s": 0.0,
                                            "total_s": 0.0})
            node["events"] += 1
            node["self_s"] += dur
            for i, s in enumerate(chain):
                scopes.setdefault(s, {"events": 0, "self_s": 0.0,
                                      "total_s": 0.0})["total_s"] += dur
                if i:
                    children.setdefault(chain[i - 1], set()).add(s)
        attributed_s += _merged_busy([(s, e) for s, e, _ in scoped])[0]
        # fusion candidates: consecutive scoped ops on the lane separated by
        # a launch gap small enough that one fused kernel would absorb it
        scoped.sort()
        for (s0, e0, c0), (s1, e1, c1) in zip(scoped, scoped[1:]):
            gap = s1 - e0
            if 0 <= gap <= gap_us:
                key = (c0[-1], c1[-1])
                agg = pair_gaps.setdefault(key, {"count": 0, "gap_us": 0.0,
                                                 "busy_us": 0.0})
                agg["count"] += 1
                agg["gap_us"] += gap
                agg["busy_us"] += (e0 - s0) + (e1 - s1)

    coverage = attributed_s / busy_s if busy_s else None
    for name, node in scopes.items():
        node["share_of_busy"] = (round(node["self_s"] / busy_s, 4)
                                 if busy_s else None)
        cost = (scope_costs or {}).get(name)
        node.update(flops=None, bytes=None, achieved_tflops=None, mfu=None,
                    flops_per_byte=None, roofline=None)
        if cost and node["total_s"]:
            fl = float(cost.get("flops") or 0.0)
            by = float(cost.get("bytes") or 0.0)
            node["flops"] = fl
            node["bytes"] = by
            node["achieved_tflops"] = round(fl / node["total_s"] / 1e12, 4)
            if peak:
                node["mfu"] = round(fl / (node["total_s"] * peak * 1e12), 4)
            if by:
                node["flops_per_byte"] = round(fl / by, 2)
                if ridge is not None:
                    node["roofline"] = ("compute-bound" if fl / by >= ridge
                                        else "hbm-bound")
        node["self_s"] = round(node["self_s"], 6)
        node["total_s"] = round(node["total_s"], 6)

    fusion = sorted(
        ({"pair": list(pair), "count": agg["count"],
          "total_gap_us": round(agg["gap_us"], 1),
          "mean_gap_us": round(agg["gap_us"] / agg["count"], 2),
          "combined_busy_us": round(agg["busy_us"], 1)}
         for pair, agg in pair_gaps.items()),
        key=lambda c: (-c["total_gap_us"], -c["combined_busy_us"]))

    report = {
        "device_kind": device_kind,
        "device_lanes": len(lanes),
        "peak_bf16_tflops": peak,
        "hbm_gb_s": flops_util.hbm_gb_s(device_kind) if device_kind else None,
        "ridge_flops_per_byte": (round(ridge, 1) if ridge is not None
                                 else None),
        "window_s": round(window_s, 6),
        "device_busy_s": round(busy_s, 6),
        "idle_s": round(idle_s, 6),
        "busy_fraction": round(busy_s / window_s, 4) if window_s else None,
        "coverage": round(coverage, 4) if coverage is not None else None,
        "scopes": scopes,
        "tree": {p: sorted(kids) for p, kids in children.items()},
        "fusion_candidates": fusion,
    }
    m = _mscope()
    m.inc("attrib.traces")
    m.gauge("attrib.coverage_pct",
            round(100 * coverage, 2) if coverage is not None else None)
    m.gauge("attrib.device_busy_s", report["device_busy_s"])
    return report


def ranked_scopes(report: dict) -> list:
    """[(name, node)] slowest-first by exclusive time — the report table's
    row order (the top row is where the next optimization round digs)."""
    return sorted(report.get("scopes", {}).items(),
                  key=lambda kv: -kv[1]["self_s"])


# ---------------------------------------------------------------------------
# synthetic fixture (demo + CPU-CI stand-in)
# ---------------------------------------------------------------------------

#: one sampler step of the demo timeline: (µs duration, op name, scope path
#: as XLA stamps it — "" = deliberately unattributed overhead). Durations
#: are µs at a ~5%-MFU 200px flash step; per-step attributed share is
#: 935/990 ≈ 94.4%, safely over the floor but honest about residue.
_DEMO_STEP = (
    (30, "dynamic-update-slice.7", ""),
    (180, "fusion.11", "jit(ddim_sample)/sampler/model/Block_0/qkv/"
     "dot_general"),
    (260, "custom-call.3", "jit(ddim_sample)/sampler/model/"
     "flash_attention/fwd/flash_fwd"),
    (90, "custom-call.9", "jit(ddim_sample)/sampler/model/"
     "dequant_matmul/pallas/dequant_matmul"),
    (310, "fusion.12", "jit(ddim_sample)/sampler/model/Block_0/Mlp_0/"
     "dot_general"),
    (40, "select.2", "jit(ddim_sample)/sampler/cached_step/select_n"),
    (55, "all-to-all.1", "jit(ddim_sample)/sp/all_to_all_gather/all-to-all"),
    (25, "copy.4", ""),
)
_DEMO_STEPS = 4
_DEMO_GAP_US = 5


def synthetic_demo_trace() -> dict:
    """A deterministic Chrome trace-event dump with one TPU device lane:
    ``_DEMO_STEPS`` sampler steps of ``_DEMO_STEP`` ops at fixed 5 µs launch
    gaps. Checked in verbatim as ``tests/fixtures/attrib_trace.json`` (the
    test pins the file to this function — fixture drift is a failure)."""
    events = [
        {"ph": "M", "pid": 1, "name": "process_name",
         "args": {"name": "/device:TPU:0 (demo)"}},
        {"ph": "M", "pid": 1, "tid": 1, "name": "thread_name",
         "args": {"name": "XLA Ops"}},
        {"ph": "M", "pid": 9, "name": "process_name",
         "args": {"name": "/host:CPU"}},
        {"ph": "M", "pid": 9, "tid": 1, "name": "thread_name",
         "args": {"name": "main"}},
    ]
    ts = 1000
    for step in range(_DEMO_STEPS):
        for dur, name, path in _DEMO_STEP:
            ev = {"ph": "X", "pid": 1, "tid": 1, "ts": ts, "dur": dur,
                  "name": name}
            if path:
                ev["args"] = {"long_name": path}
            events.append(ev)
            # host-lane shadow event: proves lane selection ignores hosts
            events.append({"ph": "X", "pid": 9, "tid": 1, "ts": ts,
                           "dur": dur, "name": f"TfrtCpu step{step}"})
            ts += dur + _DEMO_GAP_US
        ts += 200  # inter-step idle gap (device waits on the host)
    return {"displayTimeUnit": "ns", "traceEvents": events}


def demo_scope_costs() -> dict:
    """Window costs paired with :func:`synthetic_demo_trace` (device kind
    ``DEMO_DEVICE_KIND``): chosen so the demo lands near the measured
    sampler MFU (~0.03–0.09, PERF.md) with one compute-bound scope
    (flash fwd), the rest HBM-bound — both roofline branches exercised."""
    return {
        # 3360 µs inclusive @ 197 TFLOP/s peak → MFU ≈ 0.05
        "sampler/model": {"flops": 3.3e10, "bytes": 2.2e8},
        "flash_attention/fwd": {"flops": 1.2e10, "bytes": 4.0e7},  # ≥ ridge
        "dequant_matmul/pallas": {"flops": 4.0e9, "bytes": 5.0e7},
        "sampler/cached_step": {"flops": 1.0e8, "bytes": 1.0e7},
        "sp/all_to_all_gather": {"flops": 0.0, "bytes": 2.0e7},
    }


def demo_report(gap_us: float = DEFAULT_GAP_US) -> dict:
    """The fixture attributed end-to-end — ``attrib_report --demo`` and the
    bench leg's CPU fallback both render exactly this."""
    return attribute(synthetic_demo_trace(), device_kind=DEMO_DEVICE_KIND,
                     scope_costs=demo_scope_costs(), gap_us=gap_us)
