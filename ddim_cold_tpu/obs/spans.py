"""Per-request trace spans through the serving stack.

A *trace* is one ticket's life: created at ``Router.submit`` /
``Engine.submit`` and closed at delivery or terminal failure. Everything
that happens to the ticket — planning, assembly, dispatch, fetch, preview,
hedged re-placements, failovers, replica replacement — lands as *spans*
under that one trace, so a hedged ticket's attempts share a trace_id and a
chaos run renders as one coherent tree per request.

Design constraints:

* **Disabled is free.** Tracing is off by default; every entry point checks
  one module bool and returns a falsy :data:`NULL` span, so the serving hot
  path pays a single attribute read. With tracing off, outputs are
  byte-identical to a build without this module.
* **Deterministic ids.** trace/span ids come from ``itertools.count`` — the
  same run produces the same ids (no ``random``, matching the repo's
  seeded-chaos ethos), and ids are unique per process.
* **Host-only** (graftcheck A004): no jax imports — spans ride the same
  host threads as the router/fleet layer.

Export: :func:`export_chrome` renders closed spans as Chrome trace-event
JSON (load in ``chrome://tracing`` / Perfetto; one row per trace), and
:func:`export_jsonl` as one JSON object per line. ``scripts/obs_report.py``
is the CLI over both.
"""

from __future__ import annotations

import itertools
import json
import threading
import time
from dataclasses import dataclass
from typing import Optional

__all__ = [
    "TraceContext", "Span", "NULL", "enable", "disable", "enabled",
    "tracing", "begin", "record", "now", "spans", "clear", "export_chrome",
    "export_jsonl",
]


@dataclass(frozen=True)
class TraceContext:
    """The propagatable part of a span — what rides a submit() call across
    the router→replica→engine boundary (and through hedges, which re-issue
    the same frozen call under the same trace)."""

    trace_id: int
    span_id: int


class Span:
    """One named, timed node of a trace. ``end()`` closes it (idempotent:
    first close wins, matching Ticket's first-resolution-wins rule)."""

    __slots__ = ("trace_id", "span_id", "parent_id", "name", "t0", "t1",
                 "attrs", "_rec")

    def __init__(self, rec, trace_id, span_id, parent_id, name, t0, attrs):
        self._rec = rec
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id
        self.name = name
        self.t0 = t0
        self.t1 = None
        self.attrs = attrs

    @property
    def ctx(self) -> TraceContext:
        return TraceContext(self.trace_id, self.span_id)

    @property
    def ended(self) -> bool:
        return self.t1 is not None

    def set(self, **attrs) -> "Span":
        self.attrs.update(attrs)
        return self

    def child(self, name: str, **attrs) -> "Span":
        return self._rec.begin(name, parent=self, **attrs)

    def end(self, **attrs) -> None:
        if self.t1 is None:
            self.attrs.update(attrs)
            self.t1 = self._rec.now()

    def __bool__(self) -> bool:
        return True

    def __repr__(self) -> str:
        state = "open" if self.t1 is None else f"{self.t1 - self.t0:.4f}s"
        return (f"Span({self.name!r}, trace={self.trace_id}, "
                f"id={self.span_id}, {state})")


class _NullSpan:
    """The disabled-tracing span: falsy, every operation a no-op, safe to
    thread anywhere a real span goes."""

    __slots__ = ()
    trace_id = span_id = parent_id = None
    name = ""
    t0 = t1 = None
    attrs: dict = {}
    ctx = None
    ended = True

    def set(self, **attrs):
        return self

    def child(self, name, **attrs):
        return self

    def end(self, **attrs):
        pass

    def __bool__(self):
        return False

    def __repr__(self):
        return "Span(<disabled>)"


NULL = _NullSpan()


class Recorder:
    """Process-local span store. Timing uses ``time.monotonic`` anchored to
    the recorder's first span, so exported timestamps start near zero."""

    def __init__(self):
        self._lock = threading.Lock()
        self._spans: list = []                          # guarded-by: _lock
        self._trace_ids = itertools.count(1)
        self._span_ids = itertools.count(1)
        self._t0: Optional[float] = None                # guarded-by: _lock

    def now(self) -> float:
        """Monotonic seconds since the recorder's first event. The epoch is
        lazily anchored with double-checked locking (the set and its
        re-check sit under ``_lock`` — graftcheck T005), and the anchored
        value is read back ONCE under the lock: the old code re-read
        ``self._t0`` unguarded after the check, so a concurrent ``clear()``
        could None it mid-call (TypeError) or swap in a newer epoch and
        skew the timestamp."""
        t = time.monotonic()
        t0 = self._t0
        if t0 is None:
            with self._lock:
                if self._t0 is None:
                    self._t0 = t
                t0 = self._t0
        return t - t0

    def begin(self, name: str, parent=None, **attrs) -> Span:
        if isinstance(parent, Span):
            trace_id, parent_id = parent.trace_id, parent.span_id
        elif isinstance(parent, TraceContext):
            trace_id, parent_id = parent.trace_id, parent.span_id
        else:
            trace_id, parent_id = next(self._trace_ids), None
        span = Span(self, trace_id, next(self._span_ids), parent_id, name,
                    self.now(), attrs)
        with self._lock:
            self._spans.append(span)
        return span

    def record(self, parent, name: str, t0: float, t1: float, **attrs) -> Span:
        """Retroactively add a CLOSED span — how per-batch stage timings
        (assemble/dispatch/fetch measured once per batch) become one span
        per participating request without re-running the stage."""
        span = self.begin(name, parent=parent, **attrs)
        span.t0, span.t1 = t0, t1
        return span

    def spans(self) -> list:
        with self._lock:
            return list(self._spans)

    def clear(self) -> None:
        with self._lock:
            self._spans.clear()
            self._t0 = None

    # -- export -----------------------------------------------------------
    def export_chrome(self, path: Optional[str] = None) -> dict:
        """Chrome trace-event JSON: complete ("X") events, one timeline row
        (tid) per trace so a request's whole tree reads left-to-right. Open
        spans export with dur=0 and ``"open": true`` — visible, not lost."""
        events = []
        for s in self.spans():
            t1 = s.t1 if s.t1 is not None else s.t0
            args = {"span_id": s.span_id, "parent_id": s.parent_id}
            args.update(s.attrs)
            if s.t1 is None:
                args["open"] = True
            events.append({
                "name": s.name, "cat": "serve", "ph": "X",
                "ts": round(s.t0 * 1e6, 3),
                "dur": round((t1 - s.t0) * 1e6, 3),
                "pid": 0, "tid": s.trace_id, "args": args,
            })
        doc = {"traceEvents": events, "displayTimeUnit": "ms"}
        if path is not None:
            with open(path, "w") as f:
                json.dump(doc, f)
        return doc

    def export_jsonl(self, path: Optional[str] = None) -> list:
        rows = [{
            "trace_id": s.trace_id, "span_id": s.span_id,
            "parent_id": s.parent_id, "name": s.name,
            "t0": round(s.t0, 6),
            "t1": None if s.t1 is None else round(s.t1, 6),
            "attrs": s.attrs,
        } for s in self.spans()]
        if path is not None:
            with open(path, "w") as f:
                for row in rows:
                    f.write(json.dumps(row) + "\n")
        return rows


_REC = Recorder()
_ENABLED = False


def recorder() -> Recorder:
    return _REC


def enable() -> None:
    global _ENABLED
    _ENABLED = True


def disable() -> None:
    global _ENABLED
    _ENABLED = False


def enabled() -> bool:
    return _ENABLED


class tracing:
    """``with obs.spans.tracing():`` — enable tracing for a scope, restore
    the previous state on exit (nesting-safe)."""

    def __enter__(self):
        self._prev = _ENABLED
        enable()
        return _REC

    def __exit__(self, *exc):
        if not self._prev:
            disable()
        return False


def begin(name: str, parent=None, **attrs):
    """Open a span (a new trace when ``parent`` is None). Returns
    :data:`NULL` when tracing is disabled — the one check every serving-path
    call site relies on for the zero-overhead contract."""
    if not _ENABLED:
        return NULL
    return _REC.begin(name, parent=parent, **attrs)


def record(parent, name: str, t0: float, t1: float, **attrs) -> None:
    if not _ENABLED or parent is None or parent is NULL:
        return
    _REC.record(parent, name, t0, t1, **attrs)


def now() -> float:
    """The recorder clock — the timebase ``record()``'s t0/t1 must be on."""
    return _REC.now()


def spans() -> list:
    return _REC.spans()


def clear() -> None:
    _REC.clear()


def export_chrome(path: Optional[str] = None) -> dict:
    return _REC.export_chrome(path)


def export_jsonl(path: Optional[str] = None) -> list:
    return _REC.export_jsonl(path)
