"""Process-local metrics registry — the single source of runtime counters.

Every serving-layer counter/gauge/histogram lives here instead of in
hand-maintained per-object dicts: engine, router, fleet, warmup, and the
fault-injection registry emit into named series, and ``Engine.health()`` /
``Router.health()`` snapshots are *rendered from* the registry (the legacy
``stats`` dict surfaces are read-only views over it).

Contracts (mirrors of ``utils/faults.py``'s site registry, enforced
statically by graftcheck GRAFT-A005):

* every emit site (``Scope.inc`` / ``Scope.gauge`` / ``Scope.observe``)
  passes a **literal** metric name,
* the name is **registered** in :data:`METRICS` below,
* each ``(name, key)`` pair appears at **one** emit site in the tree (a
  second site for the same name must carry a distinct literal ``key=``, the
  way a second ``faults.fire`` at one site carries a distinct tag).

Scopes separate instances sharing a process: each :class:`Engine` gets its
own scope (``engine#0``, ``engine#1``, …) so a 2-replica fleet's counters
never alias; a scope id is deterministic in construction order (no
wall-clock, no randomness — same run, same ids).

Host-only module (graftcheck A004): no jax / jax.numpy anywhere — the
registry must be importable (and near-free) from the router/fleet layer
that never touches a device.
"""

from __future__ import annotations

import itertools
import threading
from typing import Optional

#: The full metric registry: ``(name, kind, help)``. Emit sites may only
#: use names listed here (graftcheck GRAFT-A005, the A003 mirror); kinds are
#: checked at emit time too, so a gauge can never silently become a counter.
METRICS = (
    # -- engine (one scope per Engine instance) ---------------------------
    ("engine.compiles", "counter", "XLA programs built (ensure_program)"),
    ("engine.program_aliases", "counter",
     "program keys aliased to an equal-fingerprint executable (warmup dedup)"),
    ("engine.dispatches", "counter", "batches dispatched to the device"),
    ("engine.rows", "counter", "request rows served"),
    ("engine.padded_rows", "counter", "pad rows shipped for bucket alignment"),
    ("engine.max_queue_depth", "gauge", "high-water admission queue depth"),
    ("engine.preview_frames", "counter", "streamed x̂0 preview frames"),
    ("engine.latency_s", "hist", "per-ticket submit→deliver latency"),
    ("engine.param_bytes", "gauge", "resident float param bytes"),
    ("engine.param_bytes_quant", "gauge", "resident int8 param bytes"),
    ("engine.retries", "counter", "transient dispatch retries"),
    ("engine.failed_batches", "counter", "batches failed (key: dispatch|plan)"),
    ("engine.failed_tickets", "counter", "tickets resolved with an error"),
    ("engine.quarantined", "counter", "requests quarantined by bisection"),
    ("engine.deadline_expired", "counter",
     "deadlines expired (key: dispatch|plan)"),
    ("engine.rejected", "counter", "submissions rejected (queue full)"),
    ("engine.skipped_batches", "counter", "planned batches skipped"),
    ("engine.stalls", "counter", "soft-watchdog stall events"),
    ("engine.cache_refresh_steps", "counter",
     "device-telemetry: adaptive-gate refresh steps observed"),
    ("engine.cache_reuse_steps", "counter",
     "device-telemetry: adaptive-gate reuse steps observed"),
    # -- warmup (emitted under the warmed engine's scope) -----------------
    ("warmup.new_compiles", "counter", "programs compiled during warmup"),
    ("warmup.deduped", "counter",
     "warmup keys served by aliasing instead of compiling"),
    ("warmup.programs", "gauge", "resident programs after warmup"),
    # -- router -----------------------------------------------------------
    ("router.submitted", "counter", "fleet requests admitted"),
    ("router.completed", "counter", "fleet requests completed"),
    ("router.failed", "counter", "fleet requests failed terminally"),
    ("router.rejected", "counter", "fleet requests rejected at admission"),
    ("router.rejected_by_tenant", "counter",
     "admission rejections per tenant (key: tenant)"),
    ("router.placements", "counter", "ticket placements onto replicas"),
    ("router.hedges", "counter", "hedged re-placements"),
    ("router.failovers", "counter", "failovers off evicted replicas"),
    ("router.replicas_spawned", "counter", "replicas spawned"),
    ("router.replicas_retired", "counter", "replicas retired"),
    ("router.spawn_failures", "counter", "replica spawn failures"),
    ("router.loop_errors", "counter", "supervision-loop errors"),
    # -- fleet ------------------------------------------------------------
    ("fleet.replica_transitions", "counter",
     "replica lifecycle transitions (key: state)"),
    # -- remote replicas (serve/remote.py, one scope per handle) ----------
    ("remote.rpc_calls", "counter", "RPC round trips issued (key: method)"),
    ("remote.crashes", "counter",
     "replica process deaths detected (exit or heartbeat loss)"),
    ("remote.heartbeat_misses", "counter", "heartbeat pings that timed out"),
    ("remote.protocol_errors", "counter",
     "server-pushed protocol_error events (a frame the replica refused)"),
    # -- autoscaler (serve/autoscale.py) ----------------------------------
    ("autoscale.ticks", "counter", "control-loop decisions evaluated"),
    ("autoscale.scale_ups", "counter", "target increments issued"),
    ("autoscale.scale_downs", "counter", "target decrements issued"),
    ("autoscale.target", "gauge", "router replica target after last tick"),
    # -- fault injection --------------------------------------------------
    ("faults.injected", "counter", "realized fault injections (key: site)"),
    # -- attribution / trend (obs.attrib / obs.trend, host-side) ----------
    ("attrib.traces", "counter", "profiler traces attributed"),
    ("attrib.coverage_pct", "gauge",
     "device-busy % attributed to registered scopes (last trace)"),
    ("attrib.device_busy_s", "gauge",
     "device-busy seconds in the last attributed trace"),
    ("trend.points", "gauge", "series points loaded by the trend gate"),
    ("trend.checks", "counter", "trend-gate checks by outcome (key: status)"),
)

_KINDS = {name: kind for name, kind, _ in METRICS}


class _Series:
    """One (scope, name) series: a monotonic counter (optionally subdivided
    by a dynamic key), a last-value gauge, or a raw-sample histogram."""

    __slots__ = ("kind", "value", "by_key", "samples")

    def __init__(self, kind: str):
        self.kind = kind
        self.value = 0
        self.by_key: dict = {}
        self.samples: list = []

    @property
    def total(self):
        return self.value + sum(self.by_key.values())


class Scope:
    """A named emit handle: all series it touches are keyed by its id, so
    two engines in one process never share a counter."""

    def __init__(self, registry: "Registry", sid: str):
        self._reg = registry
        self.sid = sid

    # -- emit (the A005-linted surface: literal name first) ---------------
    def inc(self, name: str, value=1, key: Optional[str] = None) -> None:
        self._reg._emit(self.sid, name, "counter", value, key)

    def gauge(self, name: str, value) -> None:
        self._reg._emit(self.sid, name, "gauge", value, None)

    def observe(self, name: str, value) -> None:
        self._reg._emit(self.sid, name, "hist", value, None)

    # -- read -------------------------------------------------------------
    def value(self, name: str, default=0):
        s = self._reg._get(self.sid, name)
        if s is None:
            return default
        return s.total if s.kind == "counter" else s.value

    def raw(self, name: str):
        """Gauge value, or None when the gauge was never set (the legacy
        ``stats["param_bytes"] = None`` initial state)."""
        s = self._reg._get(self.sid, name)
        return None if s is None else s.value

    def by_key(self, name: str) -> dict:
        s = self._reg._get(self.sid, name)
        return dict(s.by_key) if s is not None else {}

    def samples(self, name: str) -> list:
        s = self._reg._get(self.sid, name)
        return list(s.samples) if s is not None else []

    def count(self, name: str) -> int:
        s = self._reg._get(self.sid, name)
        return len(s.samples) if s is not None else 0

    def snapshot(self) -> dict:
        return self._reg.snapshot().get(self.sid, {})


class Registry:
    def __init__(self):
        self._lock = threading.Lock()
        # (sid, name) -> _Series. The lock guards the dict AND the mutable
        # innards of every series in it (by_key / samples): emit mutates
        # them, so every read surface copies them out under the lock too —
        # a render racing an emit must never iterate a dict mid-resize.
        self._series: dict = {}                         # guarded-by: _lock
        self._scope_ids = itertools.count()

    def scope(self, name: str) -> Scope:
        with self._lock:
            sid = f"{name}#{next(self._scope_ids)}"
        return Scope(self, sid)

    def _emit(self, sid, name, kind, value, key):
        want = _KINDS.get(name)
        if want is None:
            raise ValueError(f"unregistered metric {name!r} — add it to "
                             "obs.metrics.METRICS (graftcheck GRAFT-A005)")
        if want != kind:
            raise ValueError(f"metric {name!r} is a {want}, emitted as {kind}")
        with self._lock:
            s = self._series.get((sid, name))
            if s is None:
                s = self._series[(sid, name)] = _Series(kind)
            if kind == "counter":
                if key is None:
                    s.value += value
                else:
                    s.by_key[key] = s.by_key.get(key, 0) + value
            elif kind == "gauge":
                s.value = value
            else:
                s.samples.append(value)

    def _get(self, sid, name) -> Optional[_Series]:
        """A point-in-time COPY of the series, taken under the lock. The
        live object's by_key/samples are mutated by concurrent emits; the
        old code handed the live series out and let Scope readers copy its
        innards OUTSIDE the lock — a snapshot racing an emit could iterate
        a dict mid-resize (emit-vs-render consistency, graftcheck T-rules
        audit)."""
        with self._lock:
            s = self._series.get((sid, name))
            if s is None:
                return None
            c = _Series(s.kind)
            c.value = s.value
            c.by_key = dict(s.by_key)
            c.samples = list(s.samples)
            return c

    def snapshot(self) -> dict:
        """{scope_id: {name: value | {key: value} | [samples]}} — counters
        render their total (keyed subdivisions under ``name + "/by_key"``),
        gauges their last value, histograms their raw sample list. Rendered
        entirely under the lock: the per-series containers it reads are
        emit-mutable, so the copy and the render must be one atomic view
        (a snapshot taken mid-request never shows a counter without its
        by_key breakdown)."""
        out: dict = {}
        with self._lock:
            for (sid, name), s in self._series.items():
                dst = out.setdefault(sid, {})
                if s.kind == "counter":
                    dst[name] = s.total
                    if s.by_key:
                        dst[name + "/by_key"] = dict(s.by_key)
                elif s.kind == "gauge":
                    dst[name] = s.value
                else:
                    dst[name] = list(s.samples)
        return out

    def reset(self) -> None:
        """Drop every series (tests). Scope ids keep counting up, so scopes
        created before a reset never alias ones created after."""
        with self._lock:
            self._series.clear()


_REG = Registry()


def registry() -> Registry:
    return _REG


def scope(name: str) -> Scope:
    """A fresh uniquely-identified emit scope on the process registry."""
    return _REG.scope(name)


def snapshot() -> dict:
    return _REG.snapshot()


def reset() -> None:
    _REG.reset()
