"""Bench-trajectory series loading + the out-of-band regression gate.

The repo commits one ``BENCH_r{N}.json`` / ``MULTICHIP_r{N}.json`` per
round, but until now nothing READ the series — a throughput regression
would only surface when a human diffed two rounds by hand. This module is
the one loader and one noise-band policy for every trajectory consumer:

* :func:`load_series` — unwraps the driver's ``{"cmd", "rc", "tail",
  "parsed"}`` wrapper (the bench record is ``parsed`` or the last parseable
  JSON line of ``tail``; a wrapper whose tail is truncated beyond recovery
  becomes a skipped, annotated point, not a crash), reads raw record files
  through ``utils.record.last_json_record``, and raises :exc:`TrendError`
  on files that are not JSON at all.
* :func:`check` — one dotted-path metric over an ordered series: the newest
  value against the median of its predecessors, with a noise band derived
  from the spread of successive relative deltas (the bench's best-of-N
  windows damp within-run noise; the band absorbs what remains
  between runs). First-run and missing-metric pass; drift beyond the band
  in the bad direction is a regression.
* :func:`gate` / ``python -m ddim_cold_tpu.obs.trend`` — the CI entry:
  exit 0 on the committed series, nonzero on any out-of-band regression.
* :func:`thin` / :func:`annotate_deltas` — the series-shaping helpers
  ``scripts/fid_trend.py`` rides (one thinning rule, one band policy).

Ordering honors the ``run_meta`` stamp bench records now carry (git sha,
device kind, jax versions, externally-supplied timestamp) and falls back to
the ``r{N}`` filename round only for pre-stamp records.

Host-only module (graftcheck A004): no jax — the gate runs in CI jobs and
on machines that never touch a device.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import re
import sys
from typing import Optional

from ddim_cold_tpu.utils.record import is_tpu_record, last_json_record

#: default relative noise floor: between-round spread on a healthy chip
#: (BENCH_r04 vs the r05 chain record differ ~6% on the headline) — drift
#: inside it is never a regression even on a 2-point series.
REL_FLOOR = 0.1
#: band = max(REL_FLOOR, BAND_K · median |successive relative delta|)
BAND_K = 3.0

#: the committed-series checks the gate runs by default: dotted metric path,
#: direction ("higher" is better / "lower" / "zero" = must equal 0 /
#: "true" = must be truthy). BENCH checks compare TPU records only — the
#: r02/r03 tunnel-outage CPU fallbacks are not a trajectory.
BENCH_CHECKS = (
    ("value", "higher"),
    ("mfu", "higher"),
    ("submetrics.sampler_throughput_200px_k20.value", "higher"),
    ("submetrics.sampler_throughput_200px_k20_flash.value", "higher"),
    ("submetrics.serving.img_per_sec", "higher"),
    ("submetrics.e2e_train_throughput_warm.value", "higher"),
    # static memory-budget rollups (bench's memory_budget section, computed
    # by analysis/memory_checks.budget_report) — residency creep is a
    # regression even when throughput holds
    ("submetrics.memory.peak_hbm_gb", "lower"),
    ("submetrics.memory.max_kernel_vmem_mb", "lower"),
    # fused-trunk leg (bench --fusion): the fused program's throughput and
    # its advantage over the unfused w8a16 composition must not decay
    ("submetrics.fusion.fused.img_per_sec", "higher"),
    ("submetrics.fusion.speedup", "higher"),
    # few-step distilled-sampling leg (bench --fewstep): the served per-k
    # throughput at both ends of the {1, 2, 4} family must not decay (the
    # latency contract itself is enforced in-leg — the bench raises)
    ("submetrics.fewstep.per_k.1.img_per_sec", "higher"),
    ("submetrics.fewstep.per_k.4.img_per_sec", "higher"),
    # out-of-process fleet leg (bench --fleet-proc): pre-warmed spawn must
    # stay fast — the replacement's spawn+warm wall rides the persistent
    # compile cache, and creep here means the cache stopped engaging (the
    # bitwise/zero-compile contracts are enforced in-leg — the bench raises)
    ("submetrics.fleet_proc.spawn_warm_s", "lower"),
)
MULTICHIP_CHECKS = (
    ("rc", "zero"),
    ("ok", "true"),
)

_MISSING = object()


class TrendError(ValueError):
    """A series file that is not parseable JSON at all (corrupt commit)."""


_SCOPE = None


def _mscope():
    global _SCOPE
    if _SCOPE is None:
        from ddim_cold_tpu.obs import metrics
        _SCOPE = metrics.scope("trend")
    return _SCOPE


# ---------------------------------------------------------------------------
# loading
# ---------------------------------------------------------------------------

class Point:
    """One series point: ``record`` is None when the file held a valid
    wrapper whose inner record is unrecoverable (``note`` says why)."""

    __slots__ = ("path", "round", "record", "note")

    def __init__(self, path, rnd, record, note=None):
        self.path = path
        self.round = rnd
        self.record = record
        self.note = note

    def meta(self) -> dict:
        return (self.record or {}).get("run_meta") or {}


def unwrap(obj):
    """Driver wrapper → (inner record | None, note | None); non-wrapper
    dicts pass through untouched."""
    if isinstance(obj, dict) and "tail" in obj and (
            "parsed" in obj or "cmd" in obj):
        if isinstance(obj.get("parsed"), dict):
            return obj["parsed"], None
        for ln in reversed(str(obj.get("tail") or "").splitlines()):
            try:
                rec = json.loads(ln)
            except ValueError:
                continue
            if isinstance(rec, dict):
                return rec, None
        return None, ("wrapper tail holds no parseable record "
                      "(truncated capture)")
    return obj, None


def load_record(path: str):
    """→ (record | None, note | None). :exc:`TrendError` when the file has
    no parseable JSON at all — a corrupt commit is an error, a truncated
    wrapper tail is a skipped point."""
    try:
        with open(path) as f:
            obj = json.load(f)
    except OSError as e:
        raise TrendError(f"{path}: unreadable ({e})")
    except ValueError:
        obj = last_json_record(path)  # JSONL-style record files
        if obj is None:
            raise TrendError(f"{path}: no parseable JSON record")
    return unwrap(obj)


def _round_of(path: str) -> Optional[int]:
    m = re.search(r"_r(\d+)\.json$", os.path.basename(path))
    return int(m.group(1)) if m else None


def load_series(paths) -> list:
    """Ordered [Point] for a glob pattern or explicit path list. Order: the
    ``run_meta.timestamp`` stamp when every loadable record carries one,
    else the filename round (timestamp as tie-break)."""
    if isinstance(paths, str):
        paths = sorted(glob.glob(paths))
    points = []
    for p in paths:
        rec, note = load_record(p)
        points.append(Point(p, _round_of(p), rec, note))
    stamps = [pt.meta().get("timestamp") for pt in points
              if pt.record is not None]
    if stamps and all(isinstance(t, (int, float)) for t in stamps):
        points.sort(key=lambda pt: (pt.meta().get("timestamp", 0),
                                    pt.round or 0))
    else:
        points.sort(key=lambda pt: (pt.round or 0, pt.path))
    return points


def metric_value(record, dotted: str):
    """``"submetrics.serving.img_per_sec"`` → value, or ``_MISSING``."""
    node = record
    for part in dotted.split("."):
        if not isinstance(node, dict) or part not in node:
            return _MISSING
        node = node[part]
    return node


# ---------------------------------------------------------------------------
# noise bands + the gate
# ---------------------------------------------------------------------------

def noise_band(prior_values, rel_floor: float = REL_FLOOR,
               k: float = BAND_K) -> float:
    """Relative band for "is the newest delta noise": k × the median
    absolute successive relative delta over the prior series, floored at
    ``rel_floor`` (a 1–2 point history has no measurable spread)."""
    deltas = [abs((b - a) / a) for a, b in zip(prior_values,
                                               prior_values[1:]) if a]
    if not deltas:
        return rel_floor
    deltas.sort()
    mid = len(deltas) // 2
    spread = (deltas[mid] if len(deltas) % 2
              else 0.5 * (deltas[mid - 1] + deltas[mid]))
    return max(rel_floor, k * spread)


def check(points, metric: str, direction: str = "higher",
          rel_floor: float = REL_FLOOR, k: float = BAND_K,
          tpu_only: bool = True) -> dict:
    """One metric over one ordered series → a verdict dict with ``status``
    in {"ok", "regression", "first_run", "missing", "no_points"}; only
    "regression" gates."""
    usable = [pt for pt in points if isinstance(pt.record, dict)
              and not pt.record.get("skipped")]
    if tpu_only:
        usable = [pt for pt in usable if is_tpu_record(pt.record)]
    out = {"metric": metric, "direction": direction,
           "points": len(usable), "status": "no_points",
           "last": None, "ref": None, "delta_rel": None, "band": None}
    if not usable:
        return out
    last_pt = usable[-1]
    last = metric_value(last_pt.record, metric)
    if direction in ("zero", "true"):
        ok = ((last == 0) if direction == "zero" else bool(last))
        out.update(status="missing" if last is _MISSING
                   else ("ok" if ok else "regression"), last=None
                   if last is _MISSING else last, path=last_pt.path)
        return out
    series = [(pt, metric_value(pt.record, metric)) for pt in usable]
    vals = [float(v) for _, v in series
            if v is not _MISSING and isinstance(v, (int, float))]
    if last is _MISSING or not isinstance(last, (int, float)):
        out.update(status="missing")
        return out
    if len(vals) < 2:
        out.update(status="first_run", last=last)
        return out
    prior = vals[:-1]
    prior_sorted = sorted(prior)
    mid = len(prior_sorted) // 2
    ref = (prior_sorted[mid] if len(prior_sorted) % 2
           else 0.5 * (prior_sorted[mid - 1] + prior_sorted[mid]))
    band = noise_band(prior, rel_floor, k)
    delta = (float(last) - ref) / abs(ref) if ref else 0.0
    bad = delta < -band if direction == "higher" else delta > band
    out.update(status="regression" if bad else "ok", last=float(last),
               ref=round(ref, 4), delta_rel=round(delta, 4),
               band=round(band, 4), path=last_pt.path)
    return out


def gate(root: str, rel_floor: float = REL_FLOOR, k: float = BAND_K,
         bench_checks=BENCH_CHECKS,
         multichip_checks=MULTICHIP_CHECKS) -> dict:
    """The committed-series gate over ``<root>/BENCH_r*.json`` +
    ``<root>/MULTICHIP_r*.json`` → {"exit_code", "checks", "statuses"}."""
    results = []
    bench = load_series(os.path.join(root, "BENCH_r*.json"))
    multi = load_series(os.path.join(root, "MULTICHIP_r*.json"))
    for pts, checks, tpu_only in ((bench, bench_checks, True),
                                  (multi, multichip_checks, False)):
        for metric, direction in checks:
            results.append(check(pts, metric, direction, rel_floor, k,
                                 tpu_only=tpu_only))
    skipped = [{"path": pt.path, "note": pt.note}
               for pt in bench + multi if pt.note]
    statuses: dict = {}
    for r in results:
        statuses[r["status"]] = statuses.get(r["status"], 0) + 1
    m = _mscope()
    m.gauge("trend.points", len(bench) + len(multi))
    for r in results:
        m.inc("trend.checks", key=r["status"])
    return {"exit_code": 1 if statuses.get("regression") else 0,
            "bench_points": len(bench), "multichip_points": len(multi),
            "skipped_points": skipped, "statuses": statuses,
            "checks": results}


# ---------------------------------------------------------------------------
# series shaping shared with scripts/fid_trend.py
# ---------------------------------------------------------------------------

def thin(seq, max_points: int) -> list:
    """Evenly thin to ≤ ``max_points``, always keeping first and last —
    the one thinning rule for trend artifacts (checkpoint snapshots here,
    any future long series)."""
    seq = list(seq)
    if max_points <= 0 or len(seq) <= max_points:
        return seq
    if max_points == 1:
        return [seq[0]]
    step = (len(seq) - 1) / (max_points - 1)
    idx = sorted({round(i * step) for i in range(max_points)})
    return [seq[i] for i in idx]


def annotate_deltas(rows, value_key: str, lower_is_better: bool = False,
                    rel_floor: float = REL_FLOOR, k: float = BAND_K) -> list:
    """Copy ``rows`` (dicts carrying ``value_key``) with per-point
    ``delta_rel`` / ``band`` / ``in_band`` annotations under the SAME
    noise-band policy as the regression gate — fid_trend's output speaks
    the gate's language instead of shipping raw values."""
    out = []
    vals: list = []
    for row in rows:
        row = dict(row)
        v = row.get(value_key)
        if isinstance(v, (int, float)) and vals:
            band = noise_band(vals, rel_floor, k)
            prev = vals[-1]
            delta = (float(v) - prev) / abs(prev) if prev else 0.0
            worse = delta > band if lower_is_better else delta < -band
            row.update(delta_rel=round(delta, 4), band=round(band, 4),
                       in_band=not worse)
        if isinstance(v, (int, float)):
            vals.append(float(v))
        out.append(row)
    return out


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------

def _render(report: dict) -> str:
    lines = [f"trend gate over {report['bench_points']} BENCH + "
             f"{report['multichip_points']} MULTICHIP points "
             f"— statuses {report['statuses']}"]
    for r in report["checks"]:
        extra = ""
        if r["status"] in ("ok", "regression") and r.get("delta_rel") is not None:
            extra = (f" last={r['last']} ref={r['ref']} "
                     f"Δ={100 * r['delta_rel']:+.1f}% "
                     f"band=±{100 * r['band']:.1f}%")
        elif r.get("last") is not None:
            extra = f" last={r['last']}"
        lines.append(f"  [{r['status']:>10}] {r['metric']} "
                     f"({r['direction']}){extra}")
    for s in report["skipped_points"]:
        lines.append(f"  [   skipped] {os.path.basename(s['path'])}: "
                     f"{s['note']}")
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="bench-trajectory regression gate (exit 1 on any "
                    "out-of-band regression)")
    ap.add_argument("--root", default=os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__)))),
        help="repo root holding BENCH_r*.json / MULTICHIP_r*.json")
    ap.add_argument("--rel-floor", type=float, default=REL_FLOOR)
    ap.add_argument("--band-k", type=float, default=BAND_K)
    ap.add_argument("--json", default=None,
                    help="also write the full report to this path")
    args = ap.parse_args(argv)
    report = gate(args.root, rel_floor=args.rel_floor, k=args.band_k)
    print(_render(report))
    if args.json:
        with open(args.json, "w") as f:
            json.dump(report, f, indent=1)
    if report["exit_code"]:
        print("trend gate: REGRESSION detected", file=sys.stderr)
    return report["exit_code"]


if __name__ == "__main__":
    raise SystemExit(main())
